"""Record transformer pipeline: ingestion-time row transforms.

Re-design of ``pinot-segment-local/.../recordtransformer/*`` —
``CompositeTransformer.java`` chains (in the reference's order):
ExpressionTransformer (derived columns), FilterTransformer (row drops),
DataTypeTransformer (schema coercion), NullValueTransformer (defaults +
null tracking), SanitizationTransformer (string cleanup), and
ComplexTypeTransformer (nested-object flattening/unnesting) — over
``GenericRow``-style dicts before they reach the segment writer.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional

from pinot_tpu.query.functions import EvalError, eval_row_filter, eval_scalar
from pinot_tpu.query.parser import parse_expression, parse_filter_expression
from pinot_tpu.spi.data import FieldSpec, Schema
from pinot_tpu.spi.table import TableConfig

Row = Dict[str, Any]

# sentinel: transformer dropped the row (ref: GenericRow skip-record flag)
SKIP = None


class RecordTransformer:
    """transform(row) -> row | None (None = drop; ref: RecordTransformer.java)."""

    def transform(self, row: Row) -> Optional[Row]:
        raise NotImplementedError


class ExpressionTransformer(RecordTransformer):
    """Derived columns from SQL expressions over source fields
    (ref: ExpressionTransformer.java; expressions come from
    ingestionConfig.transformConfigs and schema transformFunction)."""

    def __init__(self, expressions: Dict[str, str]):
        self._exprs = {col: parse_expression(e) for col, e in expressions.items()}

    def transform(self, row: Row) -> Optional[Row]:
        for col, expr in self._exprs.items():
            # reference semantics: don't overwrite an existing non-null value
            if row.get(col) is None:
                try:
                    row[col] = eval_scalar(expr, row)
                except EvalError:
                    row[col] = None
        return row


class FilterTransformer(RecordTransformer):
    """Drops rows matching filterConfig.filterFunction
    (ref: FilterTransformer.java)."""

    def __init__(self, filter_function: str):
        self._filter = parse_filter_expression(filter_function)

    def transform(self, row: Row) -> Optional[Row]:
        try:
            if eval_row_filter(self._filter, row):
                return SKIP
        except EvalError:
            pass
        return row


class DataTypeTransformer(RecordTransformer):
    """Coerces values to the schema's declared types; drops columns not in
    the schema (ref: DataTypeTransformer.java)."""

    def __init__(self, schema: Schema):
        self._specs: Dict[str, FieldSpec] = {fs.name: fs
                                             for fs in schema.field_specs}

    def transform(self, row: Row) -> Optional[Row]:
        out: Row = {}
        for name, fs in self._specs.items():
            v = row.get(name)
            if v is None:
                out[name] = None
                continue
            try:
                if fs.single_value:
                    if isinstance(v, (list, tuple)):
                        v = v[0] if v else None
                    out[name] = None if v is None else fs.data_type.convert(v)
                else:
                    vals = v if isinstance(v, (list, tuple)) else [v]
                    out[name] = [fs.data_type.convert(x) for x in vals
                                 if x is not None]
            except (ValueError, TypeError):
                out[name] = None
        return out


class NullValueTransformer(RecordTransformer):
    """Replaces nulls with the field's default null value and records which
    fields were null (ref: NullValueTransformer.java; the segment writer
    uses ``__nulls__`` for the null vector when nullHandlingEnabled)."""

    NULL_FIELDS_KEY = "__nulls__"

    def __init__(self, schema: Schema):
        self._specs = list(schema.field_specs)

    def transform(self, row: Row) -> Optional[Row]:
        nulls: List[str] = []
        for fs in self._specs:
            v = row.get(fs.name)
            if v is None or (not fs.single_value and v == []):
                nulls.append(fs.name)
                row[fs.name] = (fs.default_null_value if fs.single_value
                                else [fs.default_null_value])
        if nulls:
            row[self.NULL_FIELDS_KEY] = nulls
        return row


class SanitizationTransformer(RecordTransformer):
    """Strips NUL characters and over-length strings
    (ref: SanitizationTransformer.java)."""

    def __init__(self, schema: Schema):
        self._string_cols = {fs.name: fs.max_length
                             for fs in schema.field_specs
                             if not fs.data_type.is_numeric}

    def transform(self, row: Row) -> Optional[Row]:
        for name, max_len in self._string_cols.items():
            v = row.get(name)
            if isinstance(v, str):
                row[name] = self._clean(v, max_len)
            elif isinstance(v, list):
                row[name] = [self._clean(x, max_len) if isinstance(x, str)
                             else x for x in v]
        return row

    def _clean(self, s: str, max_len: int) -> str:
        if "\x00" in s:
            s = s.replace("\x00", "")
        return s[:max_len]


class ComplexTypeTransformer(RecordTransformer):
    """Flattens nested dicts into dotted columns, optionally unnesting is
    left to the caller (ref: ComplexTypeTransformer.java flatten mode)."""

    def __init__(self, delimiter: str = "."):
        self._delim = delimiter

    def transform(self, row: Row) -> Optional[Row]:
        out: Row = {}
        for k, v in row.items():
            if isinstance(v, dict):
                self._flatten(k, v, out)
            else:
                out[k] = v
        return out

    def _flatten(self, prefix: str, obj: Dict[str, Any], out: Row) -> None:
        for k, v in obj.items():
            key = f"{prefix}{self._delim}{k}"
            if isinstance(v, dict):
                self._flatten(key, v, out)
            else:
                out[key] = v


class CompositeTransformer(RecordTransformer):
    """Ref: CompositeTransformer.java — fixed default order."""

    def __init__(self, transformers: List[RecordTransformer]):
        self._transformers = transformers

    def transform(self, row: Row) -> Optional[Row]:
        for t in self._transformers:
            row = t.transform(row)
            if row is None:
                return SKIP
        return row

    @classmethod
    def for_table(cls, table_config: Optional[TableConfig],
                  schema: Schema) -> "CompositeTransformer":
        """Default pipeline (ref: CompositeTransformer.getDefaultTransformer):
        complex-type -> expression -> filter -> data-type -> null -> sanitize."""
        chain: List[RecordTransformer] = [ComplexTypeTransformer()]

        expressions: Dict[str, str] = {}
        for fs in schema.field_specs:
            if fs.transform_function:
                expressions[fs.name] = fs.transform_function
        ic = table_config.ingestion_config if table_config else None
        if ic:
            for tc in ic.transform_configs:
                expressions[tc.column] = tc.transform_function
        if expressions:
            chain.append(ExpressionTransformer(expressions))
        if ic and ic.filter_function:
            chain.append(FilterTransformer(ic.filter_function))
        chain.append(DataTypeTransformer(schema))
        chain.append(NullValueTransformer(schema))
        chain.append(SanitizationTransformer(schema))
        return cls(chain)


def transform_rows(transformer: RecordTransformer,
                   rows: Iterable[Row]) -> List[Row]:
    out = []
    for r in rows:
        t = transformer.transform(dict(r))
        if t is not None:
            out.append(t)
    return out
