"""Kafka WIRE-protocol stream plugin: the fetch-API subset over real TCP.

Re-design of the reference's Kafka consumer plugin
(``pinot-plugins/pinot-stream-ingestion/pinot-kafka-2.0/.../KafkaPartitionLevelConsumer.java``
+ ``KafkaStreamMetadataProvider`` + ``KafkaConsumerFactory``) WITHOUT the
Kafka client library: this module speaks the actual Kafka binary protocol —
the same subset the reference's consumer exercises through kafka-clients:

- ApiVersions (key 18, v0) — handshake sanity
- Metadata    (key  3, v1) — partition discovery
- ListOffsets (key  2, v1) — earliest (-2) / latest (-1) offsets
- Fetch       (key  1, v4) — record batches (magic v2, crc32c-verified,
  zigzag-varint record fields)

``KafkaBrokerSim`` is the scriptable in-test broker (the embedded-Kafka
analogue of the reference's ``KafkaStarterUtils``): it serves the SAME wire
bytes a real broker would for this subset, so the consumer's parser is
exercised against genuine protocol framing, not a convenience shim.
"""

from __future__ import annotations

import json
import socket
import socketserver
import struct
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from pinot_tpu.ingestion.stream import (
    MessageBatch,
    PartitionLevelConsumer,
    StreamConsumerFactory,
    StreamIngestionConfig,
    StreamMessage,
    StreamMetadataProvider,
    StreamOffset,
    register_stream_type,
)

API_FETCH = 1
API_LIST_OFFSETS = 2
API_METADATA = 3
API_VERSIONS = 18

EARLIEST_TS = -2
LATEST_TS = -1


# --------------------------------------------------------------------------
# primitives
# --------------------------------------------------------------------------

class _Reader:
    def __init__(self, buf: bytes):
        self.buf = buf
        self.pos = 0

    def take(self, n: int) -> bytes:
        b = self.buf[self.pos:self.pos + n]
        if len(b) != n:
            raise ValueError("short kafka buffer")
        self.pos += n
        return b

    def i8(self) -> int:
        return struct.unpack(">b", self.take(1))[0]

    def i16(self) -> int:
        return struct.unpack(">h", self.take(2))[0]

    def i32(self) -> int:
        return struct.unpack(">i", self.take(4))[0]

    def i64(self) -> int:
        return struct.unpack(">q", self.take(8))[0]

    def u32(self) -> int:
        return struct.unpack(">I", self.take(4))[0]

    def string(self) -> Optional[str]:
        n = self.i16()
        return None if n < 0 else self.take(n).decode("utf-8")

    def varint(self) -> int:
        """Zigzag varint (kafka record fields)."""
        shift, out = 0, 0
        while True:
            b = self.take(1)[0]
            out |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
        return (out >> 1) ^ -(out & 1)

    def remaining(self) -> int:
        return len(self.buf) - self.pos


def _s(v: Optional[str]) -> bytes:
    if v is None:
        return struct.pack(">h", -1)
    raw = v.encode("utf-8")
    return struct.pack(">h", len(raw)) + raw


def _varint(v: int) -> bytes:
    z = (v << 1) ^ (v >> 63) if v < 0 else v << 1
    out = bytearray()
    while True:
        b = z & 0x7F
        z >>= 7
        if z:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _build_crc32c_table() -> tuple:
    table = []
    for i in range(256):
        c = i
        for _ in range(8):
            c = (c >> 1) ^ 0x82F63B78 if c & 1 else c >> 1
        table.append(c)
    return tuple(table)


# built eagerly at import: the handlers run under ThreadingTCPServer, and a
# lazily-appended list is readable half-built by a concurrent request
_CRC32C_TABLE = _build_crc32c_table()


def _crc32c(data: bytes) -> int:
    """CRC-32C (Castagnoli), the record-batch checksum kafka uses."""
    crc = 0xFFFFFFFF
    for b in data:
        crc = (crc >> 8) ^ _CRC32C_TABLE[(crc ^ b) & 0xFF]
    return crc ^ 0xFFFFFFFF


# --------------------------------------------------------------------------
# record batches (magic v2)
# --------------------------------------------------------------------------

def encode_record_batch(base_offset: int,
                        records: List[Tuple[Optional[bytes], bytes, int]]
                        ) -> bytes:
    """[(key, value, timestamp_ms)] -> one magic-v2 batch."""
    first_ts = records[0][2] if records else 0
    max_ts = max((r[2] for r in records), default=0)
    body = bytearray()
    for i, (key, value, ts) in enumerate(records):
        rec = bytearray()
        rec += b"\x00"                       # attributes
        rec += _varint(ts - first_ts)        # timestamp delta
        rec += _varint(i)                    # offset delta
        if key is None:
            rec += _varint(-1)
        else:
            rec += _varint(len(key)) + key
        rec += _varint(len(value)) + value
        rec += _varint(0)                    # headers
        body += _varint(len(rec)) + rec

    after_crc = (
        struct.pack(">hiqqqhii", 0, len(records) - 1, first_ts, max_ts,
                    -1, -1, -1, len(records))
        + bytes(body))
    crc = _crc32c(after_crc)
    inner = struct.pack(">ibI", 0, 2, crc) + after_crc  # epoch, magic, crc
    return struct.pack(">qi", base_offset, len(inner)) + inner


def decode_record_batches(buf: bytes, verify_crc: bool = True
                          ) -> List[Tuple[int, Optional[bytes], bytes, int]]:
    """Record set bytes -> [(abs_offset, key, value, timestamp_ms)]."""
    out = []
    r = _Reader(buf)
    while r.remaining() >= 12:
        base_offset = r.i64()
        batch_len = r.i32()
        if r.remaining() < batch_len:
            break  # truncated trailing batch (kafka allows it) — drop
        br = _Reader(r.take(batch_len))
        br.i32()                     # partition leader epoch
        magic = br.i8()
        if magic != 2:
            raise ValueError(f"unsupported record batch magic {magic}")
        crc = br.u32()
        rest = br.buf[br.pos:]
        if verify_crc and _crc32c(rest) != crc:
            raise ValueError("record batch crc32c mismatch")
        attrs = br.i16()
        if attrs & 0x07:
            raise ValueError("compressed batches not supported")
        br.i32()                     # last offset delta
        first_ts = br.i64()
        br.i64()                     # max timestamp
        br.i64()                     # producer id
        br.i16()                     # producer epoch
        br.i32()                     # base sequence
        n = br.i32()
        for _ in range(n):
            size = br.varint()
            rr = _Reader(br.take(size))
            rr.i8()                  # attributes
            ts_delta = rr.varint()
            off_delta = rr.varint()
            klen = rr.varint()
            key = rr.take(klen) if klen >= 0 else None
            vlen = rr.varint()
            value = rr.take(vlen) if vlen >= 0 else b""
            rr.varint()              # headers (0)
            out.append((base_offset + off_delta, key, value,
                        first_ts + ts_delta))
    return out


# --------------------------------------------------------------------------
# in-test broker (KafkaStarterUtils analogue, wire-faithful)
# --------------------------------------------------------------------------

class KafkaBrokerSim:
    """Single-node broker speaking the consumer's protocol subset."""

    def __init__(self, port: int = 0):
        self._topics: Dict[str, List[List[Tuple[Optional[bytes], bytes, int]]]] = {}
        self._lock = threading.Lock()
        sim = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                try:
                    while True:
                        hdr = self._recv_exact(4)
                        if hdr is None:
                            return
                        size = struct.unpack(">i", hdr)[0]
                        req = self._recv_exact(size)
                        if req is None:
                            return
                        resp = sim._handle(req)
                        self.request.sendall(
                            struct.pack(">i", len(resp)) + resp)
                except (ConnectionError, OSError):
                    pass

            def _recv_exact(self, n):
                buf = b""
                while len(buf) < n:
                    chunk = self.request.recv(n - len(buf))
                    if not chunk:
                        return None
                    buf += chunk
                return buf

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._srv = Server(("127.0.0.1", port), Handler)
        self.port = self._srv.server_address[1]
        self.host = "127.0.0.1"
        self._thread: Optional[threading.Thread] = None

    # -- scripting surface ---------------------------------------------------
    def create_topic(self, topic: str, num_partitions: int = 1) -> None:
        with self._lock:
            t = self._topics.get(topic)
            if t is None:
                self._topics[topic] = [[] for _ in range(num_partitions)]
            else:
                while len(t) < num_partitions:
                    t.append([])

    def produce(self, topic: str, records: List[Any],
                partition: int = 0) -> int:
        now = int(time.time() * 1000)
        with self._lock:
            log = self._topics[topic][partition]
            for rec in records:
                value = (rec if isinstance(rec, bytes)
                         else json.dumps(rec).encode("utf-8"))
                log.append((None, value, now))
            return len(log)

    def start(self) -> "KafkaBrokerSim":
        self._thread = threading.Thread(
            target=self._srv.serve_forever, daemon=True, name="kafka-sim")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()

    # -- protocol ------------------------------------------------------------
    def _handle(self, req: bytes) -> bytes:
        r = _Reader(req)
        api_key, api_version, corr = r.i16(), r.i16(), r.i32()
        r.string()  # client id
        head = struct.pack(">i", corr)
        if api_key == API_VERSIONS:
            apis = [(API_FETCH, 0, 4), (API_LIST_OFFSETS, 0, 1),
                    (API_METADATA, 0, 1), (API_VERSIONS, 0, 0)]
            body = struct.pack(">hi", 0, len(apis)) + b"".join(
                struct.pack(">hhh", *a) for a in apis)
            return head + body
        if api_key == API_METADATA:
            return head + self._metadata(r)
        if api_key == API_LIST_OFFSETS:
            return head + self._list_offsets(r)
        if api_key == API_FETCH:
            return head + self._fetch(r)
        raise ValueError(f"unsupported api key {api_key}")

    def _metadata(self, r: _Reader) -> bytes:
        n = r.i32()
        names = ([r.string() for _ in range(n)] if n >= 0
                 else sorted(self._topics))
        out = bytearray()
        # brokers [node_id host port rack], controller_id
        out += struct.pack(">i", 1)
        out += struct.pack(">i", 0) + _s(self.host) \
            + struct.pack(">i", self.port) + _s(None)
        out += struct.pack(">i", 0)
        out += struct.pack(">i", len(names))
        with self._lock:
            for name in names:
                parts = self._topics.get(name)
                err = 0 if parts is not None else 3  # UNKNOWN_TOPIC
                out += struct.pack(">h", err) + _s(name) + b"\x00"
                out += struct.pack(">i", len(parts or []))
                for p in range(len(parts or [])):
                    # error, partition, leader, replicas [0], isr [0]
                    out += struct.pack(">hiiii", 0, p, 0, 1, 0)
                    out += struct.pack(">ii", 1, 0)
        return bytes(out)

    def _list_offsets(self, r: _Reader) -> bytes:
        r.i32()  # replica id
        n_topics = r.i32()
        out = bytearray(struct.pack(">i", n_topics))
        with self._lock:
            for _ in range(n_topics):
                name = r.string()
                n_parts = r.i32()
                out += _s(name) + struct.pack(">i", n_parts)
                for _ in range(n_parts):
                    part, ts = r.i32(), r.i64()
                    log = self._topics.get(name, [[]])[part] \
                        if name in self._topics else []
                    off = 0 if ts == EARLIEST_TS else len(log)
                    out += struct.pack(">ihqq", part, 0, -1, off)
        return bytes(out)

    def _fetch(self, r: _Reader) -> bytes:
        r.i32()  # replica
        r.i32()  # max wait
        r.i32()  # min bytes
        r.i32()  # max bytes
        r.i8()   # isolation level
        n_topics = r.i32()
        out = bytearray(struct.pack(">ii", 0, n_topics))  # throttle, topics
        with self._lock:
            for _ in range(n_topics):
                name = r.string()
                n_parts = r.i32()
                out += _s(name) + struct.pack(">i", n_parts)
                for _ in range(n_parts):
                    part, offset = r.i32(), r.i64()
                    r.i32()  # partition max bytes
                    log = self._topics.get(name, [])
                    plog = log[part] if part < len(log) else []
                    hw = len(plog)
                    chunk = plog[offset:offset + 500]
                    record_set = (encode_record_batch(offset, chunk)
                                  if chunk else b"")
                    out += struct.pack(">ihqqi", part, 0, hw, hw, 0)
                    out += struct.pack(">i", len(record_set)) + record_set
        return bytes(out)


# --------------------------------------------------------------------------
# client + plugin
# --------------------------------------------------------------------------

class KafkaWireClient:
    """One broker connection; blocking request/response with kafka framing."""

    def __init__(self, host: str, port: int, client_id: str = "pinot-tpu"):
        self.client_id = client_id
        self.host, self.port = host, port
        self._corr = 0
        self._sock: Optional[socket.socket] = None  # lazy: connect on use
        self._lock = threading.Lock()

    def request(self, api_key: int, api_version: int, body: bytes) -> _Reader:
        with self._lock:
            if self._sock is None:
                self._sock = socket.create_connection(
                    (self.host, self.port), timeout=30)
            self._corr += 1
            corr = self._corr
            req = (struct.pack(">hhi", api_key, api_version, corr)
                   + _s(self.client_id) + body)
            self._sock.sendall(struct.pack(">i", len(req)) + req)
            size = struct.unpack(">i", self._recv(4))[0]
            resp = _Reader(self._recv(size))
        got = resp.i32()
        if got != corr:
            raise ValueError(f"correlation mismatch {got} != {corr}")
        return resp

    def _recv(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self._sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("kafka broker closed the connection")
            buf += chunk
        return buf

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    # -- API calls the plugin uses ------------------------------------------
    def api_versions(self) -> Dict[int, Tuple[int, int]]:
        r = self.request(API_VERSIONS, 0, b"")
        err = r.i16()
        if err:
            raise ValueError(f"ApiVersions error {err}")
        return {k: (lo, hi) for k, lo, hi in
                (struct.unpack(">hhh", r.take(6))
                 for _ in range(r.i32()))}

    def partition_count(self, topic: str) -> int:
        body = struct.pack(">i", 1) + _s(topic)
        r = self.request(API_METADATA, 1, body)
        n_brokers = r.i32()
        for _ in range(n_brokers):
            r.i32()
            r.string()
            r.i32()
            r.string()
        r.i32()  # controller
        if r.i32() < 1:
            raise ValueError(f"no metadata for topic {topic!r}")
        err = r.i16()
        r.string()
        r.i8()
        if err:
            raise ValueError(f"metadata error {err} for topic {topic!r}")
        return r.i32()

    def list_offset(self, topic: str, partition: int, timestamp: int) -> int:
        body = (struct.pack(">ii", -1, 1) + _s(topic)
                + struct.pack(">iiq", 1, partition, timestamp))
        r = self.request(API_LIST_OFFSETS, 1, body)
        r.i32()  # topic count (1)
        r.string()
        r.i32()  # partition count (1)
        part, err, _ts, off = r.i32(), r.i16(), r.i64(), r.i64()
        if err:
            raise ValueError(f"ListOffsets error {err} on {topic}/{part}")
        return off

    def fetch(self, topic: str, partition: int, offset: int,
              max_bytes: int = 1 << 20, max_wait_ms: int = 100
              ) -> List[Tuple[int, Optional[bytes], bytes, int]]:
        body = (struct.pack(">iiiib", -1, max_wait_ms, 1, max_bytes, 0)
                + struct.pack(">i", 1) + _s(topic)
                + struct.pack(">iiqi", 1, partition, offset, max_bytes))
        r = self.request(API_FETCH, 4, body)
        r.i32()  # throttle
        r.i32()  # topic count (1)
        r.string()
        r.i32()  # partition count (1)
        part, err = r.i32(), r.i16()
        r.i64()  # high watermark
        r.i64()  # last stable offset
        n_aborted = r.i32()
        for _ in range(max(n_aborted, 0)):
            r.i64()
            r.i64()
        if err:
            raise ValueError(f"Fetch error {err} on {topic}/{part}")
        record_set = r.take(r.i32())
        return decode_record_batches(record_set)


class KafkaPartitionLevelConsumer(PartitionLevelConsumer):
    """Ref: KafkaPartitionLevelConsumer.java — poll records from one
    partition starting at an offset."""

    def __init__(self, host: str, port: int, topic: str, partition: int):
        self._client = KafkaWireClient(host, port)
        self.topic = topic
        self.partition = partition

    def fetch_messages(self, start: StreamOffset, max_messages: int = 5000,
                       timeout_ms: int = 5000) -> MessageBatch:
        records = self._client.fetch(self.topic, self.partition,
                                     start.value,
                                     max_wait_ms=min(timeout_ms, 500))
        msgs = []
        next_off = start.value
        for abs_off, _key, value, ts in records:
            if abs_off < start.value:
                continue  # batch started before the requested offset
            if len(msgs) >= max_messages:
                break
            try:
                payload = json.loads(value.decode("utf-8"))
            except (ValueError, UnicodeDecodeError):
                payload = value
            msgs.append(StreamMessage(payload=payload,
                                      offset=StreamOffset(abs_off),
                                      timestamp_ms=ts))
            next_off = abs_off + 1
        return MessageBatch(messages=msgs, next_offset=StreamOffset(next_off))

    def close(self) -> None:
        self._client.close()


class KafkaStreamMetadataProvider(StreamMetadataProvider):
    def __init__(self, host: str, port: int, topic: str):
        self._client = KafkaWireClient(host, port)
        self.topic = topic

    def partition_count(self) -> int:
        return self._client.partition_count(self.topic)

    def earliest_offset(self, partition: int) -> StreamOffset:
        return StreamOffset(
            self._client.list_offset(self.topic, partition, EARLIEST_TS))

    def latest_offset(self, partition: int) -> StreamOffset:
        return StreamOffset(
            self._client.list_offset(self.topic, partition, LATEST_TS))

    def close(self) -> None:
        self._client.close()


class KafkaWireConsumerFactory(StreamConsumerFactory):
    """Ref: KafkaConsumerFactory — stream.type=kafka; broker address from
    ``stream.kafka.broker.list`` ('host:port')."""

    def __init__(self, config: StreamIngestionConfig):
        super().__init__(config)
        addr = config.properties.get("stream.kafka.broker.list", "")
        if ":" not in addr:
            raise ValueError(
                "stream.kafka.broker.list must be 'host:port', got "
                f"{addr!r}")
        host, port = addr.rsplit(":", 1)
        self.host, self.port = host, int(port)
        self.topic = config.topic

    def create_partition_consumer(self, partition: int
                                  ) -> KafkaPartitionLevelConsumer:
        return KafkaPartitionLevelConsumer(self.host, self.port, self.topic,
                                           partition)

    def create_metadata_provider(self) -> KafkaStreamMetadataProvider:
        return KafkaStreamMetadataProvider(self.host, self.port, self.topic)


register_stream_type("kafka", KafkaWireConsumerFactory)
