"""Stream SPI: pluggable realtime stream consumption.

Re-design of ``pinot-spi/.../stream/*`` (27 files):
``StreamConsumerFactory`` -> ``PartitionLevelConsumer`` fetching
``MessageBatch``es by offset, ``StreamMetadataProvider`` for partition
counts/offsets, ``StreamMessageDecoder`` for payload decode. Includes an
in-process ``MemoryStream`` (the test/quickstart analogue of the reference's
embedded Kafka, ``KafkaStarterUtils`` / ``StreamDataServerStartable``).

Offsets are plain int64s (the reference's ``LongMsgOffset``); a factory
registry keyed by ``stream.type`` mirrors ``StreamConsumerFactoryProvider``.
"""

from __future__ import annotations

import json
import threading

from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

from pinot_tpu.spi.table import StreamIngestionConfig


# --------------------------------------------------------------------------
# offsets + message batch
# --------------------------------------------------------------------------

@dataclass(frozen=True, order=True)
class StreamOffset:
    """Ref: StreamPartitionMsgOffset / LongMsgOffset."""

    value: int

    def __str__(self) -> str:
        return str(self.value)

    @classmethod
    def parse(cls, s: str) -> "StreamOffset":
        return cls(int(s))


@dataclass
class StreamMessage:
    payload: Any
    offset: StreamOffset
    key: Optional[Any] = None
    timestamp_ms: int = 0


@dataclass
class MessageBatch:
    """Ref: MessageBatch.java — messages + the offset to resume from."""

    messages: List[StreamMessage]
    next_offset: StreamOffset

    @property
    def message_count(self) -> int:
        return len(self.messages)


# --------------------------------------------------------------------------
# SPI interfaces
# --------------------------------------------------------------------------

class PartitionLevelConsumer:
    """Ref: PartitionLevelConsumer.java — fetch [start, end) by offset."""

    def fetch_messages(self, start: StreamOffset,
                       max_messages: int = 5000,
                       timeout_ms: int = 5000) -> MessageBatch:
        raise NotImplementedError

    def close(self) -> None:
        pass


class StreamMetadataProvider:
    """Ref: StreamMetadataProvider.java."""

    def partition_count(self) -> int:
        raise NotImplementedError

    def earliest_offset(self, partition: int) -> StreamOffset:
        raise NotImplementedError

    def latest_offset(self, partition: int) -> StreamOffset:
        raise NotImplementedError

    def close(self) -> None:
        pass


class StreamConsumerFactory:
    """Ref: StreamConsumerFactory.java."""

    def __init__(self, config: StreamIngestionConfig):
        self.config = config

    def create_partition_consumer(self, partition: int) -> PartitionLevelConsumer:
        raise NotImplementedError

    def create_metadata_provider(self) -> StreamMetadataProvider:
        raise NotImplementedError


class StreamMessageDecoder:
    """Ref: StreamMessageDecoder.java — payload -> row dict or None."""

    def decode(self, message: StreamMessage) -> Optional[Dict[str, Any]]:
        raise NotImplementedError


class JsonMessageDecoder(StreamMessageDecoder):
    """Ref: org.apache.pinot.plugin.inputformat.json JSONMessageDecoder."""

    def decode(self, message: StreamMessage) -> Optional[Dict[str, Any]]:
        p = message.payload
        if isinstance(p, dict):
            return dict(p)
        if isinstance(p, bytes):
            p = p.decode("utf-8")
        try:
            v = json.loads(p)
        except (json.JSONDecodeError, TypeError):
            return None
        return v if isinstance(v, dict) else None


# --------------------------------------------------------------------------
# factory registry (ref: StreamConsumerFactoryProvider)
# --------------------------------------------------------------------------

_FACTORIES: Dict[str, Callable[[StreamIngestionConfig], StreamConsumerFactory]] = {}
_DECODERS: Dict[str, Callable[[], StreamMessageDecoder]] = {}


def register_stream_type(name: str,
                         factory: Callable[[StreamIngestionConfig], StreamConsumerFactory]) -> None:
    _FACTORIES[name.lower()] = factory


def register_decoder(name: str, ctor: Callable[[], StreamMessageDecoder]) -> None:
    _DECODERS[name.lower()] = ctor


def create_consumer_factory(config: StreamIngestionConfig) -> StreamConsumerFactory:
    f = _FACTORIES.get((config.stream_type or "").lower())
    if f is None:
        raise ValueError(f"unknown stream type {config.stream_type!r}; "
                         f"registered: {sorted(_FACTORIES)}")
    return f(config)


def create_decoder(name: Optional[str]) -> StreamMessageDecoder:
    if not name:
        return JsonMessageDecoder()
    d = _DECODERS.get(name.lower())
    if d is None:
        # accept reference class names, e.g. '...JSONMessageDecoder'
        if "json" in name.lower():
            return JsonMessageDecoder()
        raise ValueError(f"unknown decoder {name!r}")
    return d()


# --------------------------------------------------------------------------
# in-memory stream (embedded-Kafka analogue for tests/quickstarts)
# --------------------------------------------------------------------------

class MemoryStream:
    """In-process partitioned log. Producers append; consumers fetch by
    offset. One global registry by topic name so table configs can reference
    topics the way Kafka configs do."""

    _topics: Dict[str, "MemoryStream"] = {}
    _lock = threading.Lock()

    def __init__(self, topic: str, num_partitions: int = 1):
        self.topic = topic
        self.num_partitions = num_partitions
        self._partitions: List[List[StreamMessage]] = [
            [] for _ in range(num_partitions)]
        self._plock = threading.Lock()

    @classmethod
    def create(cls, topic: str, num_partitions: int = 1) -> "MemoryStream":
        with cls._lock:
            s = cls(topic, num_partitions)
            cls._topics[topic] = s
            return s

    @classmethod
    def get(cls, topic: str) -> "MemoryStream":
        with cls._lock:
            s = cls._topics.get(topic)
            if s is None:
                raise KeyError(f"no such topic {topic!r}")
            return s

    @classmethod
    def delete(cls, topic: str) -> None:
        with cls._lock:
            cls._topics.pop(topic, None)

    def produce(self, payload: Any, partition: Optional[int] = None,
                key: Optional[Any] = None, timestamp_ms: int = 0) -> StreamOffset:
        with self._plock:
            if partition is None:
                partition = (hash(key) if key is not None else 0) % self.num_partitions
            log = self._partitions[partition]
            off = StreamOffset(len(log))
            log.append(StreamMessage(payload, off, key, timestamp_ms))
            return off

    def fetch(self, partition: int, start: StreamOffset,
              max_messages: int) -> MessageBatch:
        with self._plock:
            log = self._partitions[partition]
            msgs = log[start.value: start.value + max_messages]
            next_off = StreamOffset(start.value + len(msgs))
            return MessageBatch(list(msgs), next_off)

    def latest_offset(self, partition: int) -> StreamOffset:
        with self._plock:
            return StreamOffset(len(self._partitions[partition]))


class MemoryStreamConsumer(PartitionLevelConsumer):
    def __init__(self, stream: MemoryStream, partition: int):
        self._stream = stream
        self._partition = partition

    def fetch_messages(self, start: StreamOffset, max_messages: int = 5000,
                       timeout_ms: int = 5000) -> MessageBatch:
        return self._stream.fetch(self._partition, start, max_messages)


class MemoryStreamMetadataProvider(StreamMetadataProvider):
    def __init__(self, stream: MemoryStream):
        self._stream = stream

    def partition_count(self) -> int:
        return self._stream.num_partitions

    def earliest_offset(self, partition: int) -> StreamOffset:
        return StreamOffset(0)

    def latest_offset(self, partition: int) -> StreamOffset:
        return self._stream.latest_offset(partition)


class MemoryStreamConsumerFactory(StreamConsumerFactory):
    """stream.type = 'memory'; topic from stream config."""

    def _stream(self) -> MemoryStream:
        return MemoryStream.get(self.config.topic)

    def create_partition_consumer(self, partition: int) -> MemoryStreamConsumer:
        return MemoryStreamConsumer(self._stream(), partition)

    def create_metadata_provider(self) -> MemoryStreamMetadataProvider:
        return MemoryStreamMetadataProvider(self._stream())


register_stream_type("memory", MemoryStreamConsumerFactory)
