"""Input-format readers: CSV / JSON / Parquet (+ gated Avro).

The pinot-input-format plugin family re-designed
(``pinot-plugins/pinot-input-format/pinot-csv/.../CSVRecordReader.java``,
``pinot-json/.../JSONRecordReader.java``, ``pinot-parquet/...``): each
format is a :class:`pinot_tpu.spi.readers.RecordReader`; a factory maps
file extension / declared format to the reader class, the reader-SPI
analogue of plugin discovery.

CSV conventions follow the reference's CSVRecordReaderConfig defaults:
header row, ',' delimiter, ';' multi-value delimiter, empty cell = null.
"""

from __future__ import annotations

import csv
import json
import os
from typing import Any, Dict, Iterator, List, Optional, Sequence, Type

from pinot_tpu.spi.readers import GenericRow, RecordReader, RecordReaderConfig


class CSVRecordReader(RecordReader):
    """Ref: pinot-csv CSVRecordReader + CSVRecordReaderConfig."""

    def init(self, data_file: str,
             fields_to_read: Optional[Sequence[str]] = None,
             config: Optional[RecordReaderConfig] = None) -> None:
        cfg = config or {}
        self._path = data_file
        self._fields = list(fields_to_read) if fields_to_read else None
        self._delimiter = str(cfg.get("delimiter", ","))
        self._mv_delimiter = str(cfg.get("multiValueDelimiter", ";"))
        # when the caller declares which columns are multi-value (the job
        # runner passes the schema's MV set), ONLY those cells split on the
        # MV delimiter — a ';' inside a single-value string survives intact.
        # With no declaration, any cell containing the delimiter splits
        # (the reference CSVRecordExtractor's schema-less behavior).
        mv = cfg.get("multiValueColumns")
        self._mv_columns = set(mv) if mv is not None else None
        with open(data_file, "r", newline="") as f:
            try:
                self._header = next(csv.reader(f, delimiter=self._delimiter))
            except StopIteration:
                raise ValueError(f"empty CSV file {data_file!r}") from None

    def _cell(self, name: str, v: str) -> Any:
        if v == "":
            return None
        if (self._mv_delimiter
                and (self._mv_columns is None or name in self._mv_columns)
                and self._mv_delimiter in v):
            return [x for x in v.split(self._mv_delimiter) if x != ""]
        return v

    def __iter__(self) -> Iterator[GenericRow]:
        fields = set(self._fields or self._header)
        with open(self._path, "r", newline="") as f:
            reader = csv.reader(f, delimiter=self._delimiter)
            next(reader)  # header
            for rec in reader:
                row = GenericRow()
                for name, val in zip(self._header, rec):
                    if name in fields:
                        row[name] = self._cell(name, val)
                yield row

    def rewind(self) -> None:  # iteration reopens the file
        pass

    def read_columnar(self) -> Optional[Dict[str, List[Any]]]:
        cols: Dict[str, List[Any]] = {}
        fields = self._fields or self._header
        idx = [(i, n) for i, n in enumerate(self._header) if n in fields]
        n_rows = 0
        for name in self._header:
            if name in fields:
                cols[name] = []
        with open(self._path, "r", newline="") as f:
            reader = csv.reader(f, delimiter=self._delimiter)
            next(reader)
            for rec in reader:
                n_rows += 1
                for i, name in idx:
                    cols[name].append(self._cell(name, rec[i])
                                      if i < len(rec) else None)
        # schema columns absent from the CSV null-fill (parity with the
        # row path, where row.get returns None)
        for name in fields:
            if name not in cols:
                cols[name] = [None] * n_rows
        return cols


class JSONRecordReader(RecordReader):
    """JSON lines or a top-level array of objects
    (ref: pinot-json JSONRecordReader)."""

    def init(self, data_file: str,
             fields_to_read: Optional[Sequence[str]] = None,
             config: Optional[RecordReaderConfig] = None) -> None:
        self._path = data_file
        self._fields = list(fields_to_read) if fields_to_read else None

    def _records(self) -> Iterator[Dict[str, Any]]:
        with open(self._path) as f:
            first = f.read(1)
            f.seek(0)
            if first == "[":
                yield from json.load(f)
            else:
                for line in f:
                    line = line.strip()
                    if line:
                        yield json.loads(line)

    def __iter__(self) -> Iterator[GenericRow]:
        for rec in self._records():
            row = GenericRow()
            for k, v in rec.items():
                if self._fields is None or k in self._fields:
                    row[k] = v
            yield row

    def rewind(self) -> None:
        pass


class ParquetRecordReader(RecordReader):
    """Parquet via pyarrow (ref: pinot-parquet ParquetRecordReader)."""

    def init(self, data_file: str,
             fields_to_read: Optional[Sequence[str]] = None,
             config: Optional[RecordReaderConfig] = None) -> None:
        import pyarrow.parquet as pq

        cols = None
        self._missing: List[str] = []
        if fields_to_read:
            # columns absent from the file null-fill (parity with the CSV
            # path; pyarrow raises on unknown column names)
            present = set(pq.read_schema(data_file).names)
            cols = [c for c in fields_to_read if c in present]
            self._missing = [c for c in fields_to_read if c not in present]
        self._table = pq.read_table(data_file, columns=cols)

    def __iter__(self) -> Iterator[GenericRow]:
        for rec in self._table.to_pylist():
            for c in self._missing:
                rec[c] = None
            yield GenericRow(rec)

    def rewind(self) -> None:
        pass

    def read_columnar(self) -> Dict[str, Any]:
        out = {name: col.to_numpy(zero_copy_only=False)
               for name, col in zip(self._table.column_names,
                                    self._table.columns)}
        for c in self._missing:
            out[c] = [None] * self._table.num_rows
        return out


class AvroRecordReader(RecordReader):
    """Avro container files via the from-scratch binary decoder
    (ingestion/avro.py; ref: pinot-avro AvroRecordReader over
    org.apache.avro DataFileStream)."""

    def init(self, data_file: str,
             fields_to_read: Optional[Sequence[str]] = None,
             config: Optional[RecordReaderConfig] = None) -> None:
        self._path = data_file
        self._fields = set(fields_to_read) if fields_to_read else None

    def __iter__(self) -> Iterator[GenericRow]:
        from pinot_tpu.ingestion.avro import read_container

        _, values = read_container(self._path)
        for rec in values:
            if not isinstance(rec, dict):
                rec = {"value": rec}
            row = GenericRow()
            for k, v in rec.items():
                if self._fields is None or k in self._fields:
                    row[k] = v
            yield row

    def rewind(self) -> None:  # iteration re-reads the file
        pass


from pinot_tpu.ingestion.protobuf import ProtoBufRecordReader  # noqa: E402
# (protobuf.py defers the google.protobuf import to first use)

_FORMATS: Dict[str, Type[RecordReader]] = {
    "csv": CSVRecordReader,
    "json": JSONRecordReader,
    "jsonl": JSONRecordReader,
    "parquet": ParquetRecordReader,
    "avro": AvroRecordReader,
    "proto": ProtoBufRecordReader,
    "pb": ProtoBufRecordReader,
}


def create_record_reader(data_file: str, data_format: Optional[str] = None,
                         fields_to_read: Optional[Sequence[str]] = None,
                         config: Optional[RecordReaderConfig] = None
                         ) -> RecordReader:
    """Factory by declared format or file extension (the RecordReader
    plugin registry, ref: RecordReaderFactory.java)."""
    fmt = (data_format or os.path.splitext(data_file)[1].lstrip(".")).lower()
    cls = _FORMATS.get(fmt)
    if cls is None:
        raise ValueError(f"unsupported input format {fmt!r} "
                         f"(supported: {sorted(_FORMATS)})")
    reader = cls()
    reader.init(data_file, fields_to_read, config)
    return reader
