"""Batch ingestion: job spec + standalone segment-generation job runner.

Re-design of the reference's batch-ingest stack:
- job spec model (``pinot-spi/.../ingestion/batch/spec/SegmentGenerationJobSpec.java``,
  loaded from the same YAML layout the reference ships —
  ``examples/batch/baseballStats/ingestionJobSpec.yaml``),
- standalone runner (``pinot-plugins/pinot-batch-ingestion/
  pinot-batch-ingestion-standalone/.../SegmentGenerationJobRunner.java``):
  glob input files, read each through the RecordReader SPI, run the
  record-transformer pipeline, build one segment per file, then push
  (``SegmentTarPushJobRunner`` equivalent = upload into the embedded
  cluster's controller, or leave segment dirs in outputDirURI).

Vectorized path: when a reader supplies ``read_columnar()`` AND the table
has no row transforms, columns go straight to the segment builder (numpy
fast path); otherwise rows stream through ``CompositeTransformer`` exactly
like the reference's mapper.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from pinot_tpu.ingestion.readers import create_record_reader
from pinot_tpu.ingestion.transformers import CompositeTransformer
from pinot_tpu.segment.creator import SegmentBuilder
from pinot_tpu.spi.data import Schema
from pinot_tpu.spi.readers import RecordReaderConfig
from pinot_tpu.spi.table import TableConfig


def _strip_uri(uri: str) -> str:
    return uri[7:] if uri.startswith("file://") else uri


def _load_json_uri(uri: str) -> Dict[str, Any]:
    """Schema/table-config URIs may be files OR controller endpoints (the
    reference's shipped job specs point at http://controller/...)."""
    import json

    if uri.startswith(("http://", "https://")):
        import urllib.request

        with urllib.request.urlopen(uri, timeout=30) as resp:
            return json.loads(resp.read().decode("utf-8"))
    with open(_strip_uri(uri)) as f:
        return json.load(f)


@dataclass
class SegmentGenerationJobSpec:
    """Ref: SegmentGenerationJobSpec.java + the shipped YAML layout."""

    job_type: str = "SegmentCreation"
    input_dir_uri: str = ""
    include_file_name_pattern: str = "glob:**/*"
    exclude_file_name_pattern: Optional[str] = None
    output_dir_uri: str = ""
    table_name: str = ""
    schema_uri: Optional[str] = None
    table_config_uri: Optional[str] = None
    data_format: Optional[str] = None
    reader_config: Dict[str, Any] = field(default_factory=dict)
    segment_name_prefix: Optional[str] = None
    # ref: segmentCreationJobParallelism — <=1 = sequential (the reference
    # default); >1 opts into a spawn-based process pool
    parallelism: int = 1

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "SegmentGenerationJobSpec":
        table = d.get("tableSpec") or {}
        reader = d.get("recordReaderSpec") or {}
        namegen = d.get("segmentNameGeneratorSpec") or {}
        return cls(
            job_type=d.get("jobType", "SegmentCreation"),
            input_dir_uri=_strip_uri(d.get("inputDirURI", "")),
            include_file_name_pattern=d.get("includeFileNamePattern",
                                            "glob:**/*"),
            exclude_file_name_pattern=d.get("excludeFileNamePattern"),
            output_dir_uri=_strip_uri(d.get("outputDirURI", "")),
            table_name=table.get("tableName", ""),
            schema_uri=table.get("schemaURI"),
            table_config_uri=table.get("tableConfigURI"),
            data_format=(reader.get("dataFormat") or "").lower() or None,
            reader_config=reader.get("configs") or {},
            segment_name_prefix=(namegen.get("configs") or {}).get(
                "segment.name.prefix"),
            parallelism=int(d.get("segmentCreationJobParallelism", 1) or 1),
        )

    @classmethod
    def from_yaml(cls, path: str) -> "SegmentGenerationJobSpec":
        import yaml

        with open(path) as f:
            return cls.from_dict(yaml.safe_load(f) or {})

    def resolve_relative(self, base_dir: str) -> None:
        """The reference resolves spec URIs against the working dir; resolve
        against the job file's directory for hermetic specs."""
        for attr in ("input_dir_uri", "output_dir_uri"):
            v = getattr(self, attr)
            if v and not os.path.isabs(v):
                setattr(self, attr, os.path.join(base_dir, v))
        for attr in ("schema_uri", "table_config_uri"):
            v = getattr(self, attr)
            if v and not v.startswith(("http://", "https://")):
                v = _strip_uri(v)
                if not os.path.isabs(v):
                    setattr(self, attr, os.path.join(base_dir, v))


def _glob_regex(pattern: str):
    """Java-glob semantics ('glob:' prefix, ref: FileSystems.getPathMatcher
    as used by SegmentGenerationUtils): '**' crosses directory separators,
    '*' and '?' do NOT — unlike fnmatch, whose '*' spans '/'."""
    import re

    pat = pattern[5:] if pattern.startswith("glob:") else pattern
    return re.compile(_glob_translate(pat) + r"\Z")


def _glob_translate(pat: str) -> str:
    import re

    out = []
    i = 0
    while i < len(pat):
        c = pat[i]
        if c == "*":
            if pat[i:i + 3] == "**/":
                out.append(r"(?:[^/]+/)*")
                i += 3
                continue
            if pat[i:i + 2] == "**":
                out.append(r".*")
                i += 2
                continue
            out.append(r"[^/]*")
        elif c == "?":
            out.append(r"[^/]")
        elif c == "{":
            # '{a,b}' alternation (non-nested, like java's glob); the
            # alternatives are themselves glob sub-patterns ('{*.csv,*.json}')
            end = pat.find("}", i)
            if end < 0:
                raise ValueError(f"unterminated '{{' in glob {pat!r}")
            alts = pat[i + 1:end].split(",")
            out.append("(?:" + "|".join(_glob_translate(a) for a in alts)
                       + ")")
            i = end + 1
            continue
        elif c == "[":
            end = pat.find("]", i + 1)
            if end < 0:
                raise ValueError(f"unterminated '[' in glob {pat!r}")
            out.append(pat[i:end + 1].replace("[!", "[^"))
            i = end + 1
            continue
        else:
            out.append(re.escape(c))
        i += 1
    return "".join(out)


def _match_glob(root: str, pattern: str,
                exclude: Optional[str] = None) -> List[str]:
    """'glob:**/*.csv'-style matching over files under root (ref:
    SegmentGenerationUtils.listMatchedFilesWithRecursiveOption)."""
    inc = _glob_regex(pattern)
    exc = _glob_regex(exclude) if exclude else None
    out = []
    for dirpath, _, files in os.walk(root):
        for fname in files:
            full = os.path.join(dirpath, fname)
            rel = os.path.relpath(full, root)
            if not inc.match(rel):
                continue
            if exc and exc.match(rel):
                continue
            out.append(full)
    return sorted(out)


def _build_one_process(spec, schema, table_config, input_file: str,
                       segment_name: str) -> None:
    """Process-pool entry: rebuild the runner in the worker (spawn-started,
    so nothing is inherited; specs/schemas are small plain dataclasses that
    pickle across the spawn boundary)."""
    SegmentGenerationJobRunner(spec, schema, table_config)._build_one(
        input_file, segment_name)


class SegmentGenerationJobRunner:
    """Ref: standalone SegmentGenerationJobRunner.java — one segment per
    matched input file, sequence-numbered names."""

    def __init__(self, spec: SegmentGenerationJobSpec,
                 schema: Optional[Schema] = None,
                 table_config: Optional[TableConfig] = None):
        self.spec = spec
        if schema is None:
            if not spec.schema_uri:
                raise ValueError(
                    "job spec has no tableSpec.schemaURI and no schema was "
                    "passed in")
            schema = Schema.from_dict(_load_json_uri(spec.schema_uri))
        self.schema = schema
        self.table_config = table_config
        if table_config is None and spec.table_config_uri:
            self.table_config = TableConfig.from_dict(
                _load_json_uri(spec.table_config_uri))

    def run(self) -> List[str]:
        """Build all glob-matched segments; returns the segment dirs."""
        spec = self.spec
        files = _match_glob(spec.input_dir_uri,
                            spec.include_file_name_pattern,
                            spec.exclude_file_name_pattern)
        if not files:
            raise FileNotFoundError(
                f"no input files match {spec.include_file_name_pattern!r} "
                f"under {spec.input_dir_uri!r}")
        return self.run_files(files)

    def run_files(self, files: List[str]) -> List[str]:
        """Build segments from an EXPLICIT file list (no glob round-trip —
        callers with exact paths, like the minion task, must not lose
        files to glob metacharacters in their names)."""
        spec = self.spec
        os.makedirs(spec.output_dir_uri, exist_ok=True)
        table = (spec.table_name
                 or (self.table_config.table_name if self.table_config
                     else self.schema.schema_name))
        prefix = spec.segment_name_prefix or f"{table}_batch"
        jobs = [(path, f"{prefix}_{seq}") for seq, path in enumerate(files)]
        workers = min(max(spec.parallelism, 1), len(jobs))
        if workers > 1:
            # per-file builds are independent (ref: the runner submits one
            # SegmentGenerationTaskRunner per file to an ExecutorService,
            # segmentCreationJobParallelism wide). SPAWN, not fork: callers
            # usually have a live JAX runtime whose threads/locks a forked
            # child would inherit mid-flight
            import multiprocessing as mp

            args = [(self.spec, self.schema, self.table_config, p, n)
                    for p, n in jobs]
            with mp.get_context("spawn").Pool(workers) as pool:
                pool.starmap(_build_one_process, args)
        else:
            for path, name in jobs:
                self._build_one(path, name)
        return [os.path.join(spec.output_dir_uri, name) for _, name in jobs]

    def _build_one(self, input_file: str, segment_name: str) -> None:
        spec = self.spec
        cfg = RecordReaderConfig(spec.reader_config)
        # only schema-declared MV columns split on the CSV MV delimiter
        cfg.setdefault("multiValueColumns",
                       [fs.name for fs in self.schema.field_specs
                        if not fs.single_value])
        reader = create_record_reader(
            input_file, spec.data_format,
            fields_to_read=self.schema.column_names, config=cfg)
        transformer = CompositeTransformer.for_table(self.table_config,
                                                     self.schema)
        columns = None
        if self._no_row_transforms():
            columns = reader.read_columnar()
            if columns is not None:
                self._sanitize_columnar(columns)
        if columns is None:
            from pinot_tpu.ingestion.transformers import (
                NullValueTransformer,
                transform_rows,
            )

            rows = transform_rows(transformer, iter(reader))
            # restore None for recorded nulls: the builder owns default
            # substitution AND the null bitmap, so defaults substituted by
            # NullValueTransformer must not masquerade as real values
            for row in rows:
                for col in row.pop(NullValueTransformer.NULL_FIELDS_KEY, ()):
                    row[col] = None
            columns = rows  # builder consumes row iterables directly
        reader.close()
        builder = SegmentBuilder(
            self.schema, segment_name,
            table_config=self.table_config)
        builder.build(columns, spec.output_dir_uri)

    def _sanitize_columnar(self, columns: Dict[str, Any]) -> None:
        """SanitizationTransformer semantics on the columnar path (NUL
        stripping + maxLength truncation) so both ingest paths build the
        same segment. Cells are only rewritten when they offend — the
        common all-clean case stays a read-only scan."""
        for fs in self.schema.field_specs:
            if fs.data_type.is_numeric or fs.name not in columns:
                continue
            max_len = fs.max_length
            vals = columns[fs.name]

            def clean(v):
                if isinstance(v, str):
                    if "\x00" in v:
                        v = v.replace("\x00", "")
                    return v[:max_len] if len(v) > max_len else v
                if isinstance(v, list):
                    return [clean(x) for x in v]
                return v

            import numpy as np

            def offends(v) -> bool:
                if isinstance(v, str):
                    return "\x00" in v or len(v) > max_len
                if isinstance(v, list):
                    return any(offends(x) for x in v)
                return False

            if isinstance(vals, np.ndarray) and vals.dtype.kind == "U":
                dirty = ((np.char.str_len(vals) > max_len)
                         | (np.char.find(vals, "\x00") >= 0))
                if dirty.any():
                    fixed = vals.astype(object)
                    for i in np.nonzero(dirty)[0]:
                        fixed[i] = clean(str(vals[i]))
                    columns[fs.name] = fixed.astype(str)
            elif any(offends(v) for v in vals):
                # scan-first: the all-clean common case stays read-only
                columns[fs.name] = [clean(v) for v in vals]

    def _no_row_transforms(self) -> bool:
        """Columnar fast path is sound only without row-level transforms
        (the builder does its own type coercion + null substitution)."""
        if any(fs.transform_function for fs in self.schema.field_specs):
            return False
        ic = (self.table_config.ingestion_config
              if self.table_config else None)
        return not (ic and (ic.transform_configs or ic.filter_function))


def run_ingestion_job(job_spec_file: str, cluster=None,
                      schema: Optional[Schema] = None,
                      table_config: Optional[TableConfig] = None) -> List[str]:
    """LaunchDataIngestionJob equivalent (ref: IngestionJobLauncher.java):
    run the generation job; when ``cluster`` (EmbeddedCluster) is given and
    the jobType includes a push, upload each built segment."""
    spec = SegmentGenerationJobSpec.from_yaml(job_spec_file)
    spec.resolve_relative(os.path.dirname(os.path.abspath(job_spec_file)))
    runner = SegmentGenerationJobRunner(spec, schema=schema,
                                        table_config=table_config)
    seg_dirs = runner.run()
    if cluster is not None and "Push" in spec.job_type:
        if runner.table_config is not None:
            table = runner.table_config.table_name_with_type
        else:
            # same fallback chain run() uses for segment names
            raw = spec.table_name or runner.schema.schema_name
            table = f"{raw}_OFFLINE"
        for seg_dir in seg_dirs:
            cluster.upload_segment_dir(table, seg_dir)
    return seg_dirs
