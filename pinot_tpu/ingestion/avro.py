"""Avro Object Container File decoder (pure python).

Re-design of the reference's avro input plugin
(``pinot-plugins/pinot-input-format/pinot-avro/.../AvroRecordReader.java``
over org.apache.avro): a from-scratch implementation of the Avro 1.x binary
spec — container framing (magic, metadata map, sync-delimited blocks,
null/deflate codecs) and the binary encoding (zigzag varints, length-
prefixed bytes/strings, block-encoded arrays/maps, index-prefixed unions,
in-order record fields). No avro library ships in this environment, and the
format is small enough that a direct decoder beats a dependency.

Covers the types the ingestion pipeline consumes: primitives, record, enum,
array, map, union, fixed, named-type references. Logical types decode as
their underlying primitive (the transformer pipeline owns time conversion,
matching the reference's treatment).
"""

from __future__ import annotations

import json
import struct
import zlib
from typing import Any, BinaryIO, Dict, Iterator, List, Tuple, Union

MAGIC = b"Obj\x01"

SchemaT = Union[str, dict, list]


class AvroError(ValueError):
    pass


class _Cursor:
    __slots__ = ("buf", "pos")

    def __init__(self, buf: bytes):
        self.buf = buf
        self.pos = 0

    def read(self, n: int) -> bytes:
        b = self.buf[self.pos:self.pos + n]
        if len(b) != n:
            raise AvroError("truncated avro data")
        self.pos += n
        return b

    def read_long(self) -> int:
        shift = 0
        acc = 0
        while True:
            if self.pos >= len(self.buf):
                raise AvroError("truncated avro data (mid-varint)")
            b = self.buf[self.pos]
            self.pos += 1
            acc |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
        return (acc >> 1) ^ -(acc & 1)  # zigzag

    def read_bytes(self) -> bytes:
        return self.read(self.read_long())


class _Decoder:
    """Schema-driven value decoder with a named-type registry."""

    def __init__(self, schema: SchemaT):
        self.named: Dict[str, dict] = {}
        self.schema = self._register(schema)

    def _register(self, s: SchemaT) -> SchemaT:
        if isinstance(s, dict):
            t = s.get("type")
            if t in ("record", "enum", "fixed"):
                name = s.get("name", "")
                ns = s.get("namespace", "")
                full = f"{ns}.{name}" if ns and "." not in name else name
                self.named[full] = s
                self.named[name] = s
                if t == "record":
                    for f in s.get("fields", []):
                        f["type"] = self._register(f["type"])
            elif t == "array":
                s["items"] = self._register(s["items"])
            elif t == "map":
                s["values"] = self._register(s["values"])
        elif isinstance(s, list):
            return [self._register(x) for x in s]
        return s

    def decode(self, c: _Cursor, s: SchemaT) -> Any:
        if isinstance(s, list):  # union: long index + value
            idx = c.read_long()
            if not 0 <= idx < len(s):
                raise AvroError(f"union index {idx} out of range")
            return self.decode(c, s[idx])
        if isinstance(s, str):
            if s in self.named:
                return self.decode(c, self.named[s])
            return self._primitive(c, s)
        t = s["type"]
        if isinstance(t, (dict, list)):
            return self.decode(c, t)
        if t == "record":
            return {f["name"]: self.decode(c, f["type"])
                    for f in s["fields"]}
        if t == "enum":
            symbols = s["symbols"]
            i = c.read_long()
            if not 0 <= i < len(symbols):
                raise AvroError(f"enum index {i} out of range")
            return symbols[i]
        if t == "array":
            out: List[Any] = []
            while True:
                n = c.read_long()
                if n == 0:
                    break
                if n < 0:  # block size follows (skippable form)
                    c.read_long()
                    n = -n
                for _ in range(n):
                    out.append(self.decode(c, s["items"]))
            return out
        if t == "map":
            m: Dict[str, Any] = {}
            while True:
                n = c.read_long()
                if n == 0:
                    break
                if n < 0:
                    c.read_long()
                    n = -n
                for _ in range(n):
                    k = c.read_bytes().decode("utf-8")
                    m[k] = self.decode(c, s["values"])
            return m
        if t == "fixed":
            return c.read(int(s["size"]))
        if t in self.named and t not in ("record", "enum", "fixed"):
            return self.decode(c, self.named[t])
        return self._primitive(c, t)

    @staticmethod
    def _primitive(c: _Cursor, t: str) -> Any:
        if t == "null":
            return None
        if t == "boolean":
            return c.read(1)[0] != 0
        if t in ("int", "long"):
            return c.read_long()
        if t == "float":
            return struct.unpack("<f", c.read(4))[0]
        if t == "double":
            return struct.unpack("<d", c.read(8))[0]
        if t == "bytes":
            return c.read_bytes()
        if t == "string":
            return c.read_bytes().decode("utf-8")
        raise AvroError(f"unknown avro type {t!r}")


def read_container(path: str) -> Tuple[SchemaT, Iterator[Any]]:
    """-> (writer schema, iterator of decoded values)."""
    with open(path, "rb") as f:
        blob = f.read()
    c = _Cursor(blob)
    if c.read(4) != MAGIC:
        raise AvroError(f"{path}: not an avro container file")
    meta: Dict[str, bytes] = {}
    while True:
        n = c.read_long()
        if n == 0:
            break
        if n < 0:
            c.read_long()
            n = -n
        for _ in range(n):
            k = c.read_bytes().decode("utf-8")
            meta[k] = c.read_bytes()
    sync = c.read(16)
    try:
        schema = json.loads(meta["avro.schema"].decode("utf-8"))
    except KeyError:
        raise AvroError(f"{path}: missing avro.schema metadata")
    codec = meta.get("avro.codec", b"null").decode("ascii")
    if codec not in ("null", "deflate"):
        raise AvroError(f"unsupported avro codec {codec!r}")
    dec = _Decoder(schema)

    def rows() -> Iterator[Any]:
        while c.pos < len(c.buf):
            count = c.read_long()
            size = c.read_long()
            data = c.read(size)
            if codec == "deflate":
                data = zlib.decompress(data, -15)
            if c.read(16) != sync:
                raise AvroError("sync marker mismatch")
            bc = _Cursor(data)
            for _ in range(count):
                yield dec.decode(bc, dec.schema)

    return schema, rows()


# -- writer (tests + tooling: produce container files without a library) ----

def _write_long(out: bytearray, v: int) -> None:
    """Unsigned varint (callers zigzag signed values first)."""
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _zigzag(v: int) -> int:
    return (v << 1) if v >= 0 else ((-v << 1) - 1)


def _encode(out: bytearray, s: SchemaT, v: Any, named: Dict[str, dict]) -> None:
    if isinstance(s, list):
        for i, branch in enumerate(s):
            if _matches(branch, v, named):
                _write_long(out, _zigzag(i))
                _encode(out, branch, v, named)
                return
        raise AvroError(f"value {v!r} matches no union branch")
    if isinstance(s, str) and s in named:
        s = named[s]
    if isinstance(s, str):
        _encode_primitive(out, s, v)
        return
    t = s["type"]
    if t == "record":
        for f in s["fields"]:
            _encode(out, f["type"], v[f["name"]], named)
    elif t == "enum":
        _write_long(out, _zigzag(s["symbols"].index(v)))
    elif t == "array":
        if v:
            _write_long(out, _zigzag(len(v)))
            for x in v:
                _encode(out, s["items"], x, named)
        _write_long(out, 0)
    elif t == "map":
        if v:
            _write_long(out, _zigzag(len(v)))
            for k, x in v.items():
                raw = k.encode("utf-8")
                _write_long(out, _zigzag(len(raw)))
                out.extend(raw)
                _encode(out, s["values"], x, named)
        _write_long(out, 0)
    elif t == "fixed":
        out.extend(v)
    else:
        _encode_primitive(out, t, v)


def _encode_primitive(out: bytearray, t: str, v: Any) -> None:
    if t == "null":
        return
    if t == "boolean":
        out.append(1 if v else 0)
    elif t in ("int", "long"):
        _write_long(out, _zigzag(int(v)))
    elif t == "float":
        out.extend(struct.pack("<f", float(v)))
    elif t == "double":
        out.extend(struct.pack("<d", float(v)))
    elif t == "bytes":
        _write_long(out, _zigzag(len(v)))
        out.extend(v)
    elif t == "string":
        raw = str(v).encode("utf-8")
        _write_long(out, _zigzag(len(raw)))
        out.extend(raw)
    else:
        raise AvroError(f"unknown avro type {t!r}")


def _matches(branch: SchemaT, v: Any, named: Dict[str, dict]) -> bool:
    if isinstance(branch, str) and branch in named:
        branch = named[branch]
    t = branch["type"] if isinstance(branch, dict) else branch
    if t == "null":
        return v is None
    if v is None:
        return False
    if t == "boolean":
        return isinstance(v, bool)
    if t in ("int", "long"):
        return isinstance(v, int) and not isinstance(v, bool)
    if t in ("float", "double"):
        return isinstance(v, (int, float)) and not isinstance(v, bool)
    if t in ("string", "enum"):
        return isinstance(v, str)
    if t in ("bytes", "fixed"):
        return isinstance(v, bytes)
    if t == "array":
        return isinstance(v, list)
    if t in ("map", "record"):
        return isinstance(v, dict)
    return False


def write_container(path: str, schema: dict, values: List[Any],
                    codec: str = "deflate") -> None:
    dec = _Decoder(schema)  # registers named types
    body = bytearray()
    for v in values:
        _encode(body, dec.schema, v, dec.named)
    data = bytes(body)
    if codec == "deflate":
        data = zlib.compress(data)[2:-4]  # raw deflate, no zlib wrapper
    elif codec != "null":
        raise AvroError(f"unsupported codec {codec!r}")
    out = bytearray(MAGIC)
    meta = {"avro.schema": json.dumps(schema).encode("utf-8"),
            "avro.codec": codec.encode("ascii")}
    _write_long(out, _zigzag(len(meta)))
    for k, v in meta.items():
        raw = k.encode("utf-8")
        _write_long(out, _zigzag(len(raw)))
        out.extend(raw)
        _write_long(out, _zigzag(len(v)))
        out.extend(v)
    _write_long(out, 0)
    sync = bytes(range(16))
    out.extend(sync)
    _write_long(out, _zigzag(len(values)))
    _write_long(out, _zigzag(len(data)))
    out.extend(data)
    out.extend(sync)
    with open(path, "wb") as f:
        f.write(bytes(out))
