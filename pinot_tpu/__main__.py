"""``python -m pinot_tpu`` -> admin CLI (ref: PinotAdministrator.java:86)."""

import sys

from pinot_tpu.tools.admin import main

sys.exit(main())
