"""pinot-tpu: a TPU-native realtime distributed OLAP datastore.

A ground-up re-design of the capabilities of Apache Pinot (reference:
/root/reference, v0.10.0-SNAPSHOT) for TPU hardware: columnar segments staged
into HBM, the per-segment Filter -> Projection -> Aggregation operator chain
executed as fused JAX/XLA (and Pallas) kernels, multi-segment combine via
`psum` over a `jax.sharding.Mesh`, and a host-side control plane (controller /
broker / server / minion roles) mirroring the reference's Helix-coordinated
cluster architecture.

Layer map (bottom-up, mirroring SURVEY.md section 1):

- ``pinot_tpu.spi``      -- contracts: schema, table config, configuration,
                            filesystem, stream + record-reader SPIs
                            (ref: pinot-spi)
- ``pinot_tpu.segment``  -- columnar segment storage engine: builders,
                            immutable + mutable segments, dictionaries,
                            forward/inverted/range indexes, star-tree
                            (ref: pinot-segment-spi + pinot-segment-local)
- ``pinot_tpu.query``    -- SQL parser, query context/request model, optimizer
                            (ref: pinot-common sql/ + request context)
- ``pinot_tpu.engine``   -- the TPU execution engine: plan maker, device
                            staging, filter/transform/aggregation kernels,
                            combine (ref: pinot-core query engine)
- ``pinot_tpu.parallel`` -- mesh construction, sharded multi-segment
                            execution, ICI collectives
- ``pinot_tpu.server``   -- server role: table data managers, query executor,
                            scheduler, transport (ref: pinot-server)
- ``pinot_tpu.broker``   -- broker role: routing, scatter/gather, reduce
                            (ref: pinot-broker)
- ``pinot_tpu.controller`` -- controller role: cluster state, table/segment
                            lifecycle, assignment, rebalance
                            (ref: pinot-controller)
- ``pinot_tpu.minion``   -- background task framework (ref: pinot-minion)
- ``pinot_tpu.ingestion`` -- batch + realtime ingestion: record readers,
                            transformers, stream consumers
"""

__version__ = "0.1.0"
