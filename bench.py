"""Benchmark: TPU query path vs the host (numpy) execution path.

Workloads (BASELINE.json configs):
- **SSB** (headline, config #5): flattened Star Schema Benchmark Q1.1-Q4.3
  (pinot_tpu/tools/ssb.py; ref: contrib/pinot-druid-benchmark/README.md) over
  a multi-segment table through the sharded device combine, parity-gated
  against the host engine. Scale via BENCH_SSB_ROWS (default 3,000,000 —
  SF 0.5; SF 1 = 6,000,000).
- **micro** (configs #1/#2): the round-2/3 7-query suite (filtered
  aggregations + dictionary group-bys, 8 x 131k rows) for cross-round
  continuity.
- **star-tree** (config #3): SUM/COUNT group-by served from StarTreeV2
  pre-aggregated records vs the same query forced to scan.
- **sketches** (config #4): DISTINCTCOUNTHLL + PERCENTILETDIGEST.

Prints ONE JSON line {"metric", "value", "unit", "vs_baseline", ...} where
value is the device p50 SSB latency and vs_baseline is host/device (>1 =>
the TPU path is faster). Sub-suite results ride in extra keys.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time
import traceback

import numpy as np

MICRO_SEGMENTS = 8
MICRO_DOCS = 131_072
SSB_ROWS = int(os.environ.get("BENCH_SSB_ROWS", 3_000_000))
WARMUP = 1
ITERS = 5
# wall-clock budget: past this, remaining sub-suites are skipped so the
# driver ALWAYS gets the headline JSON line even when first-compiles crawl
# through a degraded TPU tunnel (round-4 postmortem: a healthy bench run
# finishes in ~3 min on CPU; the tunnel added 20-40s per compile)
# generous default: 4 failed tunnel probes alone burn ~640s before the CPU
# fallback starts measuring, and the clock starts at import
TIME_BUDGET_S = float(os.environ.get("BENCH_TIME_BUDGET_S", 2100))
_T_START = time.time()


def _progress(msg: str) -> None:
    print(f"bench[{time.time() - _T_START:7.1f}s] {msg}", file=sys.stderr,
          flush=True)


def _over_budget() -> bool:
    return time.time() - _T_START > TIME_BUDGET_S

MICRO_QUERIES = [
    "SELECT count(*), sum(qty) FROM sales WHERE region = 'east'",
    "SELECT sum(price) FROM sales WHERE year BETWEEN 2017 AND 2021 AND kind != 'c'",
    "SELECT region, sum(qty), count(*) FROM sales GROUP BY region ORDER BY region",
    "SELECT region, kind, sum(price), avg(price), min(qty), max(qty) FROM sales "
    "GROUP BY region, kind ORDER BY region, kind",
    "SELECT year, min(price), max(price) FROM sales WHERE kind = 'a' "
    "GROUP BY year ORDER BY year",
    "SELECT distinctcount(region) FROM sales WHERE qty > 25",
    "SELECT sum(qty * price) FROM sales WHERE region IN ('west', 'south')",
]

STARTREE_QUERY = ("SELECT region, kind, sum(qty), count(*) FROM sales_st "
                  "GROUP BY region, kind ORDER BY region, kind")
SKETCH_QUERIES = [
    "SELECT distinctcounthll(user_id) FROM sales_st WHERE qty > 10",
    "SELECT percentiletdigest95(price) FROM sales_st",
]


def _micro_frame(n: int, seed: int, with_user: bool = False):
    rng = np.random.default_rng(seed)
    regions = np.array(["east", "west", "north", "south"])
    kinds = np.array(["a", "b", "c"])
    frame = {
        "region": regions[rng.integers(0, 4, n)],
        "kind": kinds[rng.integers(0, 3, n)],
        "year": rng.integers(2015, 2024, n).astype(np.int64),
        "qty": rng.integers(1, 50, n).astype(np.int64),
        "price": np.round(rng.normal(100.0, 25.0, n), 2),
    }
    if with_user:
        frame["user_id"] = rng.integers(0, 200_000, n).astype(np.int64)
    return frame


def _micro_schema(with_user: bool = False):
    from pinot_tpu.spi import DataType, FieldSpec, FieldType, Schema

    specs = [
        FieldSpec("region", DataType.STRING),
        FieldSpec("kind", DataType.STRING),
        FieldSpec("year", DataType.INT),
        FieldSpec("qty", DataType.LONG, FieldType.METRIC),
        FieldSpec("price", DataType.DOUBLE, FieldType.METRIC),
    ]
    if with_user:
        specs.insert(3, FieldSpec("user_id", DataType.LONG))
    name = "sales_st" if with_user else "sales"
    return Schema(name, specs)


def _build_micro(tmpdir: str):
    from pinot_tpu.segment import SegmentBuilder, load_segment

    schema = _micro_schema()
    segs = []
    for i in range(MICRO_SEGMENTS):
        b = SegmentBuilder(schema, f"sales_{i}")
        b.build(_micro_frame(MICRO_DOCS, seed=100 + i), tmpdir)
        segs.append(load_segment(f"{tmpdir}/sales_{i}"))
    return segs


def _build_startree(tmpdir: str):
    """sales_st: star-tree on (region, kind) + a high-card user_id column
    for the sketch queries (BASELINE configs #3/#4)."""
    from pinot_tpu.segment import SegmentBuilder, load_segment
    from pinot_tpu.spi.table import IndexingConfig, StarTreeIndexConfig

    cfg = IndexingConfig(star_tree_index_configs=[StarTreeIndexConfig(
        dimensions_split_order=["region", "kind"],
        function_column_pairs=["SUM__qty", "SUM__price", "COUNT__*"],
        max_leaf_records=1000)])
    schema = _micro_schema(with_user=True)
    segs = []
    for i in range(4):
        b = SegmentBuilder(schema, f"sales_st_{i}", indexing_config=cfg)
        b.build(_micro_frame(MICRO_DOCS, seed=300 + i, with_user=True),
                tmpdir)
        segs.append(load_segment(f"{tmpdir}/sales_st_{i}"))
    return segs


def _assert_parity(name, dev_rows, host_rows):
    assert len(dev_rows) == len(host_rows), \
        f"{name}: {len(dev_rows)} vs {len(host_rows)} rows"
    for dr, hr in zip(dev_rows, host_rows):
        for d, h in zip(dr, hr):
            if isinstance(h, float):
                # device float aggregation is f32/f64 mixed; host is f64
                assert abs(d - h) <= 1e-4 * max(1.0, abs(h)), (name, d, h)
            else:
                assert d == h, (name, d, h)


def _time_suite(run, ctxs, iters=ITERS, warmup=WARMUP):
    """(p50, p99) seconds over full-suite passes."""
    for _ in range(warmup):
        for ctx in ctxs:
            run(ctx)
    samples = []
    for _ in range(iters):
        t0 = time.perf_counter()
        for ctx in ctxs:
            run(ctx)
        samples.append(time.perf_counter() - t0)
    return (float(np.percentile(samples, 50)),
            float(np.percentile(samples, 99)))


def _init_backend() -> str:
    """Initialize a jax backend, surviving TPU-tunnel failures.

    Round-1 postmortem: the bench's single shot at real hardware died in
    ``jax.devices()`` and captured nothing — and backend init can either
    raise (UNAVAILABLE) or hang outright, so the probe must run in a
    subprocess with a hard timeout. If the preferred backend fails twice,
    fall back to the host platform so a number is always produced (the
    output records which backend ran)."""
    import subprocess

    # round-4 postmortem: tunnel health OSCILLATES — init sometimes hangs
    # for minutes then recovers, so be patient before giving up on the chip
    for attempt in range(4):
        try:
            probe = subprocess.run(
                [sys.executable, "-c",
                 "import jax; jax.devices(); print(jax.default_backend())"],
                capture_output=True, text=True, timeout=150)
            if probe.returncode == 0:
                break
            print(f"bench: backend probe {attempt + 1} failed:\n"
                  f"{probe.stderr[-500:]}", file=sys.stderr)
        except subprocess.TimeoutExpired:
            print(f"bench: backend probe {attempt + 1} timed out",
                  file=sys.stderr)
        time.sleep(10.0)
    else:
        print("bench: falling back to CPU host platform", file=sys.stderr)
        os.environ["JAX_PLATFORMS"] = "cpu"

        import jax

        jax.config.update("jax_platforms", "cpu")

    import jax

    jax.devices()
    return jax.default_backend()


def main() -> None:
    backend = _init_backend()

    from pinot_tpu.engine import ServerQueryExecutor
    from pinot_tpu.parallel import ShardedQueryExecutor
    from pinot_tpu.query import compile_query
    from pinot_tpu.tools import ssb

    tmpdir = tempfile.mkdtemp(prefix="bench_segs_")
    device_ex = ShardedQueryExecutor()
    host_ex = ServerQueryExecutor(use_device=False)

    result = {"metric": "ssb_suite_p50_latency", "unit": "ms/query",
              "backend": backend}

    # ---- SSB (headline) --------------------------------------------------
    _progress(f"building SSB segments ({SSB_ROWS} rows)")
    t0 = time.perf_counter()
    ssb_segs = ssb.build_segments(0, tmpdir, num_segments=8, rows=SSB_ROWS)
    build_s = time.perf_counter() - t0
    ssb_ctxs = {qid: compile_query(q) for qid, q in ssb.QUERIES.items()}

    host_times = {}
    for qid, ctx in ssb_ctxs.items():
        _progress(f"SSB {qid}: device compile+run / host / parity")
        dev_rt, _ = device_ex.execute(ctx, ssb_segs)
        host_rt, _ = host_ex.execute(ctx, ssb_segs)  # warmup (symmetric)
        _assert_parity(qid, dev_rt.rows, host_rt.rows)
        p50, _ = _time_suite(lambda c: host_ex.execute(c, ssb_segs),
                             [ctx], iters=1, warmup=0)
        host_times[qid] = p50

    per_query = {}
    for qid, ctx in ssb_ctxs.items():
        _progress(f"SSB {qid}: timing device path")
        p50, _ = _time_suite(lambda c: device_ex.execute(c, ssb_segs),
                             [ctx], iters=ITERS, warmup=WARMUP)
        per_query[qid] = p50
    dev_suite = sum(per_query.values())
    host_suite = sum(host_times.values())
    n = len(ssb_ctxs)
    result["value"] = round(dev_suite / n * 1e3, 3)
    result["vs_baseline"] = round(host_suite / dev_suite, 3)
    result["ssb"] = {
        "rows": SSB_ROWS,
        "sf": round(SSB_ROWS / ssb.ROWS_PER_SF, 3),
        "build_s": round(build_s, 1),
        "host_ms_per_query": round(host_suite / n * 1e3, 1),
        "per_query_ms": {q: round(v * 1e3, 1) for q, v in per_query.items()},
        "pallas_kernels": len(device_ex._pallas_sharded),
    }

    # ---- micro suite (configs #1/#2, cross-round continuity) -------------
    if _over_budget():
        _progress("time budget exhausted after SSB: emitting headline only")
        result["truncated"] = "time budget: micro/startree/sketches skipped"
        print(json.dumps(result))
        return
    _progress("micro suite")
    micro_segs = _build_micro(tmpdir)
    micro_ctxs = [compile_query(q) for q in MICRO_QUERIES]
    for ctx in micro_ctxs:
        dev_rt, _ = device_ex.execute(ctx, micro_segs)
        host_rt, _ = host_ex.execute(ctx, micro_segs)
        _assert_parity(ctx.sql, dev_rt.rows, host_rt.rows)
    # r2/r3 methodology (WARMUP=2/ITERS=7 BOTH sides) for cross-round
    # comparability of the micro number
    dev_p50, _ = _time_suite(lambda c: device_ex.execute(c, micro_segs),
                             micro_ctxs, iters=7, warmup=2)
    host_p50, _ = _time_suite(lambda c: host_ex.execute(c, micro_segs),
                              micro_ctxs, iters=7, warmup=2)
    result["micro"] = {
        "p50_ms_per_query": round(dev_p50 / len(micro_ctxs) * 1e3, 3),
        "vs_baseline": round(host_p50 / dev_p50, 3),
    }

    # ---- star-tree + sketches (configs #3/#4) ----------------------------
    if _over_budget():
        _progress("time budget exhausted after micro: emitting result")
        result["truncated"] = "time budget: startree/sketches skipped"
        print(json.dumps(result))
        return
    _progress("star-tree + sketches")
    st_segs = _build_startree(tmpdir)
    st_ctx = compile_query(STARTREE_QUERY)
    st_rt, st_stats = device_ex.execute(st_ctx, st_segs)
    scan_ctx = compile_query(STARTREE_QUERY + " OPTION(useStarTree=false)")
    scan_rt, _ = device_ex.execute(scan_ctx, st_segs)
    _assert_parity("startree", st_rt.rows, scan_rt.rows)
    st_p50, _ = _time_suite(lambda c: device_ex.execute(c, st_segs), [st_ctx])
    scan_p50, _ = _time_suite(lambda c: device_ex.execute(c, st_segs),
                              [scan_ctx])
    result["startree"] = {
        "ms": round(st_p50 * 1e3, 3),
        "scan_ms": round(scan_p50 * 1e3, 3),
        "docs_scanned": st_stats.num_docs_scanned,
    }

    sk_ctxs = [compile_query(q) for q in SKETCH_QUERIES]
    for ctx in sk_ctxs:
        device_ex.execute(ctx, st_segs)
    sk_p50, _ = _time_suite(lambda c: device_ex.execute(c, st_segs), sk_ctxs,
                            iters=3)
    result["sketches"] = {
        "p50_ms_per_query": round(sk_p50 / len(sk_ctxs) * 1e3, 3)}

    # ---- broker scatter-gather (BASELINE config #5's distributed half) ---
    if not _over_budget():
        _progress("broker scatter-gather")
        try:
            result["cluster"] = _bench_cluster(tmpdir)
        except Exception as exc:  # sub-suite must not sink the headline
            traceback.print_exc(file=sys.stderr)
            result["cluster"] = {"error": f"{type(exc).__name__}: {exc}"[:200]}

    print(json.dumps(result))


def _bench_cluster(tmpdir: str) -> dict:
    """SSB queries through the FULL distributed path: broker parse ->
    routing -> 2-server scatter -> per-server execution -> DataTable wire
    -> broker reduce (ref: BASELINE config #5 'multi-segment CombineOperator
    + broker scatter-gather reduce')."""
    from pinot_tpu.segment import SegmentBuilder  # noqa: F401 (env check)
    from pinot_tpu.spi.table import TableConfig
    from pinot_tpu.tools import ssb
    from pinot_tpu.tools.cluster import EmbeddedCluster

    cluster = EmbeddedCluster(num_servers=2,
                              data_dir=f"{tmpdir}/bench_cluster")
    try:
        schema = ssb.ssb_schema()
        cluster.create_table(TableConfig("ssb_lineorder"), schema)
        rows = min(SSB_ROWS, 500_000)
        seg_dir = f"{tmpdir}/bench_cluster_segs"
        ssb.build_segments(0, seg_dir, num_segments=4, rows=rows)
        for i in range(4):
            cluster.upload_segment_dir(
                "ssb_lineorder_OFFLINE", f"{seg_dir}/ssb_{i}")
        assert cluster.wait_for_ev_converged("ssb_lineorder_OFFLINE"), \
            "external view did not converge: refusing to bench partial data"
        queries = [ssb.QUERIES[q] for q in ("Q1.1", "Q2.1", "Q4.2")]
        for q in queries:  # warmup/compile
            cluster.query(q)
        t0 = time.perf_counter()
        iters = 5
        for _ in range(iters):
            for q in queries:
                resp = cluster.query(q)
                assert not resp.exceptions, resp.exceptions
        per_query = (time.perf_counter() - t0) / (iters * len(queries))
        return {"rows": rows, "servers": 2,
                "p50_ms_per_query": round(per_query * 1e3, 3)}
    finally:
        cluster.shutdown()


if __name__ == "__main__":
    try:
        main()
    except Exception as exc:  # never leave the round without a JSON line
        traceback.print_exc(file=sys.stderr)
        print(json.dumps({
            "metric": "ssb_suite_p50_latency",
            "value": None,
            "unit": "ms/query",
            "vs_baseline": None,
            "error": f"{type(exc).__name__}: {exc}"[:500],
        }))
        sys.exit(0)
