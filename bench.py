"""Benchmark: SSB on the TPU query path vs an external CPU baseline.

Architecture (round-6 redesign — probe-and-run in ONE process; round-5
postmortem: every recorded round shows ``tpu_attempts: 7`` worker
subprocesses each dying somewhere in init/build and dropping the WHOLE
suite record, so no partial ever survived the flapping tunnel):

- **probe-and-run** (default entry): a cheap subprocess PROBE (--probe:
  import jax, print the backend, exit) establishes chip liveness under a
  bounded timeout; failed probes retry on one unified exponential backoff
  that is clamped so it can NEVER burn into the CPU reserve (the old
  supervisor slept after rc 3/4 but retried rc -1 immediately, and its
  sleeps could eat the reserve). Once the probe sees a chip, the suites
  run DIRECTLY IN THIS PROCESS — no worker respawn, no re-build, no gap
  for the tunnel to flap into — streaming a partial JSON record per
  sub-suite AND per SSB query as each completes, so a mid-suite TPU loss
  still records everything that ran. A backend init that hangs AFTER a
  successful probe is caught by a watchdog that launches the CPU reserve
  pass itself before exiting.
- **CPU reserve** (kept as the fallback): when the chip never shows (or
  died mid-run), whatever sub-suites lack a record are filled in by a
  forced-CPU pass — in-process when jax was never initialized here, as a
  ``--worker`` subprocess otherwise (a process that touched the TPU
  runtime cannot re-init on CPU). Per-sub-suite ``backend`` tags make any
  fallback LOUD in the output.
- **worker** (``--worker``): builds/loads the SSB table (parallel segment
  builder, manifest-keyed reuse across attempts), runs the sub-suites, and
  appends one JSON line each to BENCH_RESULT_FILE.

Workloads (BASELINE.json configs):
- **SSB** (headline, config #5): Q1.1-Q4.3 over a multi-segment table via
  the sharded device combine; p50 AND p99 per query; parity-gated against
  the EXTERNAL pandas baseline (pinot_tpu/tools/ssb_baseline.py — the
  vs_baseline denominator; ref harness pair:
  contrib/pinot-druid-benchmark/README.md:1-60, pinot-perf BenchmarkQueryEngine).
- **QPS** (ref: pinot-tools/.../perf/QueryRunner.java): closed-loop
  multi-thread throughput + latency percentiles on three SSB flights.
- **micro** (configs #1/#2): the round-2/3 7-query suite vs the host engine
  (kept ONLY for cross-round continuity; not the headline baseline).
- **star-tree** (config #3) and **sketches** (config #4).
- **cluster**: broker scatter-gather over the full wire path, scaled
  2 -> 8 servers over partition-aligned segments; records per-query
  scatter fan-out + prune ratio and loud-fails if a partition-filtered
  query prunes <=50% of the 8 servers (BENCH_ALLOW_NO_PRUNE escapes).

Prints ONE JSON line {"metric", "value", "unit", "vs_baseline", ...}:
value = device p50 SSB ms/query, vs_baseline = pandas_baseline / device.
"""

from __future__ import annotations

import json
import os
import re
import subprocess
import sys
import tempfile
import threading
import time
import traceback

from typing import Optional

import numpy as np

_T_START = time.time()
TIME_BUDGET_S = float(os.environ.get("BENCH_TIME_BUDGET_S", 2100))
CPU_RESERVE_S = float(os.environ.get("BENCH_CPU_RESERVE_S", 700))
# SSB scale per backend: the chip takes SF >= 4; the CPU fallback keeps the
# round-4 scale so cross-round numbers stay comparable
TPU_SSB_ROWS = int(os.environ.get("BENCH_SSB_ROWS", 24_000_000))
CPU_SSB_ROWS = int(os.environ.get("BENCH_CPU_SSB_ROWS", 3_000_000))
NUM_SEGMENTS = int(os.environ.get("BENCH_SSB_SEGMENTS", 8))
INIT_TIMEOUT_S = 150
WARMUP = 1
ITERS = 5

SUITES = ("ssb", "qps", "micro", "startree", "sketches", "residency",
          "cluster", "reduce", "realtime", "userfacing")


def _log(msg: str) -> None:
    print(f"bench[{time.time() - _T_START:7.1f}s] {msg}", file=sys.stderr,
          flush=True)


# ==========================================================================
# probe-and-run (single process; CPU reserve as the fallback)
# ==========================================================================

def merge_results(result_file: str, results: dict) -> None:
    """Fold the JSONL partials into ``results`` (keyed by suite; per-SSB-
    query partials ride as ``"ssb:Q1.1"`` keys) and truncate the file."""
    try:
        with open(result_file) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                suite = rec.pop("suite", None)
                if suite is None:
                    continue
                # a SUCCESSFUL real-chip result is never overwritten by
                # a CPU one — but a chip ERROR record must not block the
                # CPU reserve from filling the suite in
                if (suite in results
                        and results[suite].get("backend") != "cpu"
                        and "error" not in results[suite]
                        and rec.get("backend") == "cpu"):
                    continue
                results[suite] = rec
        open(result_file, "w").close()
    except FileNotFoundError:
        pass


def _backoff_sleep(attempt: int, reserve_deadline: float) -> bool:
    """Unified retry backoff for EVERY failed chip probe — hung init,
    no-chip, and timeout alike (the old supervisor backed off on rc 3/4
    but retried a TimeoutExpired immediately, and its sleep could burn
    into the CPU reserve). Exponential 5s -> 60s, clamped so the sleep
    never crosses ``reserve_deadline`` minus the margin another attempt
    needs. False = no budget for another attempt."""
    room = reserve_deadline - time.time() - 120
    if room <= 0:
        return False
    delay = min(60.0, 5.0 * (2 ** max(0, attempt - 1)), room)
    _log(f"chip probe failed (attempt {attempt}); backing off "
         f"{delay:.0f}s")
    time.sleep(delay)
    return True


def probe_chip(timeout: float) -> Optional[str]:
    """Bounded subprocess probe: init jax in a throwaway process and
    report the default backend. None = no chip (timeout, hang, cpu-only,
    or init error) — the caller decides whether to retry."""
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--probe"],
            timeout=max(timeout, 10), capture_output=True, text=True)
    except subprocess.TimeoutExpired:
        return None
    if proc.returncode != 0:
        return None
    backend = (proc.stdout or "").strip().splitlines()
    return backend[-1] if backend else None


def probe_and_run() -> None:
    deadline = _T_START + TIME_BUDGET_S
    reserve_deadline = deadline - CPU_RESERVE_S
    result_file = os.environ.get("BENCH_RESULT_FILE") or os.path.join(
        tempfile.mkdtemp(prefix="bench_res_"), "results.jsonl")
    data_dir = os.environ.get("BENCH_DATA_DIR") or tempfile.mkdtemp(
        prefix="bench_data_")
    os.environ["BENCH_RESULT_FILE"] = result_file
    os.environ["BENCH_DATA_DIR"] = data_dir
    results: dict = {}
    tpu_attempts = 0

    def run_worker(backend: str, timeout: float, rows: int) -> int:
        """Forced-backend worker subprocess (the CPU reserve pass)."""
        env = dict(os.environ,
                   BENCH_RESULT_FILE=result_file,
                   BENCH_DATA_DIR=data_dir,
                   BENCH_WANT_BACKEND=backend,
                   BENCH_WORKER_ROWS=str(rows),
                   BENCH_WORKER_DEADLINE=str(deadline - 30),
                   BENCH_SKIP_SUITES=",".join(
                       s for s in SUITES
                       if s in results
                       and results[s].get("backend") != "cpu"
                       and "error" not in results[s]))
        _log(f"launching {backend} worker (timeout {timeout:.0f}s, "
             f"rows {rows}, skip [{env['BENCH_SKIP_SUITES']}])")
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--worker"],
                timeout=max(timeout, 60), env=env)
            return proc.returncode
        except subprocess.TimeoutExpired:
            _log(f"{backend} worker timed out")
            return -1

    def cpu_reserve(in_process: bool) -> None:
        missing = [s for s in SUITES if s not in results
                   or "error" in results[s]]
        if not missing:
            return
        _log(f"CPU reserve pass for {missing} "
             f"({'in-process' if in_process else 'subprocess'})")
        if in_process:
            os.environ["BENCH_WANT_BACKEND"] = "cpu"
            os.environ["BENCH_WORKER_ROWS"] = str(CPU_SSB_ROWS)
            os.environ["BENCH_WORKER_DEADLINE"] = str(deadline - 30)
            os.environ["BENCH_SKIP_SUITES"] = ",".join(
                s for s in SUITES if s not in missing)
            try:
                _Worker().run()
            except Exception:
                traceback.print_exc(file=sys.stderr)
        else:
            run_worker("cpu", deadline - time.time() - 30, CPU_SSB_ROWS)
        merge_results(result_file, results)

    # -- phase 1: fight for the chip (bounded probes, unified backoff) ----
    backend = None
    while time.time() + 120 < reserve_deadline:
        tpu_attempts += 1
        backend = probe_chip(min(INIT_TIMEOUT_S,
                                 reserve_deadline - time.time()))
        if backend and backend != "cpu":
            break
        backend = None
        if not _backoff_sleep(tpu_attempts, reserve_deadline):
            break

    # -- phase 2: run the suites IN THIS PROCESS on the probed chip ------
    if backend is not None:
        _log(f"chip probe ok ({backend}); running suites in-process")
        os.environ["BENCH_WANT_BACKEND"] = "tpu"
        os.environ["BENCH_WORKER_ROWS"] = str(TPU_SSB_ROWS)
        os.environ["BENCH_WORKER_DEADLINE"] = str(reserve_deadline)
        os.environ["BENCH_SKIP_SUITES"] = ""

        def on_hang() -> None:
            # the probe said chip but the in-process init wedged: this
            # thread runs the CPU reserve subprocess itself, emits, and
            # kills the process (the main thread is unrecoverable)
            _log("in-process backend init hung after successful probe; "
                 "watchdog running CPU reserve")
            merge_results(result_file, results)
            cpu_reserve(in_process=False)
            emit(results, tpu_attempts)
            os._exit(0)

        try:
            _Worker(on_hang=on_hang).run()
        except Exception:
            # mid-run chip loss: per-sub-suite and per-SSB-query partials
            # already on disk; the reserve pass fills the gaps
            traceback.print_exc(file=sys.stderr)
        merge_results(result_file, results)
        cpu_reserve(in_process=False)
    else:
        # the chip never showed: jax was never initialized here, so the
        # reserve pass runs in-process (no subprocess respawn gap)
        merge_results(result_file, results)
        cpu_reserve(in_process=True)

    emit(results, tpu_attempts)


def emit(results: dict, tpu_attempts: int) -> None:
    ssb = results.get("ssb", {})
    out = {
        "metric": "ssb_suite_p50_latency",
        "value": ssb.get("p50_ms_per_query"),
        "unit": "ms/query",
        "vs_baseline": ssb.get("vs_baseline"),
        "backend": ssb.get("backend", "none"),
        "baseline_engine": ssb.get("baseline_engine"),
        "tpu_attempts": tpu_attempts,
        "suite_backends": {s: results.get(s, {}).get("backend", "missing")
                           for s in SUITES},
        # mesh shape per suite record: >1 on a real multi-chip slice OR
        # the conftest-forced virtual CPU mesh; 1 means every sharded
        # combine psum in that suite was a single-device no-op
        "mesh_devices": {s: results.get(s, {}).get("mesh_devices",
                                                   "missing")
                         for s in SUITES},
    }
    for s in SUITES:
        if s in results:
            out[s] = results[s]
    # per-SSB-query partials: when the full SSB record is missing (chip
    # died mid-suite) the completed queries still ship, with their rungs
    # and pallas kernel counts — the record shows exactly which queries
    # fired pallas before the loss
    partial = {k.split(":", 1)[1]: v for k, v in results.items()
               if k.startswith("ssb:")}
    if partial and ("ssb" not in results or "error" in results.get(
            "ssb", {})):
        out["ssb_partial"] = {
            "queries_completed": sorted(partial),
            "per_query_ms": {q: v.get("p50_ms") for q, v in
                             sorted(partial.items())},
            "rungs": {q: v.get("rung") for q, v in sorted(partial.items())},
            "pallas_kernels": {q: v.get("pallas_kernels") for q, v in
                               sorted(partial.items())},
        }
    out["trajectory"] = trajectory_gate(results)
    print(json.dumps(out), flush=True)


# ==========================================================================
# trajectory gate: this round vs every prior BENCH_r*.json
# ==========================================================================

# suite -> (headline scalar key, higher_is_better): the per-suite number
# the cross-round trajectory is computed over
_TRAJECTORY_KEYS = {
    "ssb": ("p50_ms_per_query", False),
    "qps": ("qps", True),
    "micro": ("p50_ms_per_query", False),
    "startree": ("ms", False),
    "sketches": ("p50_ms_per_query", False),
    "residency": ("sliced_p50_ms_per_query", False),
    "cluster": ("p50_ms_per_query", False),
    # headline = vectorized group-by reduce wall time on the 180k-group
    # merge (the suite's own parity/speedup gates run inside bench_reduce)
    "reduce": ("p50_ms", False),
    # headline = consuming-segment write throughput; freshness/seal gates
    # run inside bench_realtime (finite p99, no unexplained host spills)
    "realtime": ("write_qps", True),
    # headline = 4-thread point-filter QPS; the index-rung SLO gates
    # (selective filters must not scan, declines must be registered)
    # run inside bench_userfacing
    "userfacing": ("qps", True),
}
REGRESSION_X = 1.3


def load_prior_rounds(root: str = None) -> dict:
    """round tag ('r05') -> that round's final bench JSON. Rounds are the
    checked-in ``BENCH_r*.json`` wrappers (the driver stores the worker's
    stdout in ``tail``); a bare result JSON parses too."""
    import glob
    import re as _re

    root = root or os.path.dirname(os.path.abspath(__file__))
    rounds = {}
    for path in sorted(glob.glob(os.path.join(root, "BENCH_r*.json"))):
        m = _re.search(r"BENCH_(r\d+)\.json$", path)
        if m is None:
            continue
        try:
            with open(path) as f:
                wrapper = json.load(f)
        except (OSError, ValueError):
            continue
        if isinstance(wrapper, dict) and "metric" in wrapper:
            rounds[m.group(1)] = wrapper
            continue
        for line in reversed(str(wrapper.get("tail", "")).splitlines()):
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict) and "metric" in rec:
                rounds[m.group(1)] = rec
                break
    return rounds


def _comparable(suite: str, cur: dict, prior: dict) -> bool:
    """Cross-round numbers only compare like-for-like: same backend, and
    — where the suite records a scale — the same row count (a 24M-row TPU
    round vs a 3M-row CPU round is not a regression signal)."""
    if cur.get("backend") != prior.get("backend"):
        return False
    if "rows" in cur or "rows" in prior:
        return cur.get("rows") == prior.get("rows")
    return True


def trajectory_gate(results: dict, rounds: dict = None) -> dict:
    """The cross-round delta table nobody was computing: per suite, this
    round's headline scalar vs the best comparable prior round, with a
    LOUD warning on a >1.3x p50 regression (or >1.3x QPS drop).
    ``BENCH_ALLOW_REGRESSION=1`` downgrades the warning to a note (capped
    budgets, tiny hosts). Never throws — a broken history must not cost
    the round its numbers."""
    try:
        rounds = load_prior_rounds() if rounds is None else rounds
    except Exception:
        return {"error": "prior-round load failed"}
    table: dict = {}
    regressions = []
    for suite, (key, higher_better) in _TRAJECTORY_KEYS.items():
        cur = results.get(suite) or {}
        value = cur.get(key)
        if not isinstance(value, (int, float)):
            continue
        best = None
        best_round = None
        for tag, rec in sorted(rounds.items()):
            prior = rec.get(suite) or {}
            pv = prior.get(key)
            if not isinstance(pv, (int, float)) or pv <= 0 \
                    or not _comparable(suite, cur, prior):
                continue
            if best is None or (pv > best if higher_better else pv < best):
                best, best_round = pv, tag
        row = {"current": value, "unit": key}
        if best is not None:
            ratio = (best / value) if higher_better else (value / best)
            row.update(best_prior=best, best_round=best_round,
                       ratio=round(ratio, 3),
                       regressed=bool(value and ratio > REGRESSION_X))
            if row["regressed"]:
                regressions.append(
                    f"{suite}: {key} {value} vs {best} in {best_round} "
                    f"({row['ratio']}x worse)")
        table[suite] = row
    out = {"vs_rounds": sorted(rounds), "suites": table}
    if regressions:
        allowed = bool(os.environ.get("BENCH_ALLOW_REGRESSION"))
        out["regressions"] = regressions
        out["allowed"] = allowed
        banner = ("TRAJECTORY REGRESSION (allowed by "
                  "BENCH_ALLOW_REGRESSION): " if allowed else
                  f"TRAJECTORY REGRESSION (> {REGRESSION_X}x vs best "
                  f"prior round): ")
        for r in regressions:
            _log(banner + r)
    return out


# ==========================================================================
# worker
# ==========================================================================

def _init_backend(want: str, on_hang=None) -> str:
    if want == "cpu":
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        # the axon plugin overrides the env var; config wins
        jax.config.update("jax_platforms", "cpu")
        jax.devices()
        return jax.default_backend()

    ok = threading.Event()

    def watchdog():
        if not ok.wait(INIT_TIMEOUT_S):
            if on_hang is not None:
                # probe-and-run mode: the watchdog OWNS recovery (CPU
                # reserve + emit) because the main thread is wedged in
                # backend init and nothing else will run
                on_hang()
            print("bench worker: backend init hung; self-terminating",
                  file=sys.stderr, flush=True)
            os._exit(3)

    threading.Thread(target=watchdog, daemon=True).start()
    import jax

    try:
        jax.devices()
    except Exception:
        traceback.print_exc(file=sys.stderr)
        os._exit(4)
    ok.set()
    backend = jax.default_backend()
    if backend == "cpu":
        os._exit(4)  # wanted the chip; the caller decides what's next
    return backend


def probe_main() -> None:
    """--probe entry: init the backend in this throwaway process and
    report it on stdout. rc 0 + a non-cpu name = chip available."""
    ok = threading.Event()

    def watchdog():
        if not ok.wait(INIT_TIMEOUT_S):
            os._exit(3)

    threading.Thread(target=watchdog, daemon=True).start()
    import jax

    try:
        jax.devices()
    except Exception:
        traceback.print_exc(file=sys.stderr)
        sys.exit(4)
    ok.set()
    backend = jax.default_backend()
    print(backend, flush=True)
    sys.exit(0 if backend != "cpu" else 4)


class _Worker:
    def __init__(self, on_hang=None):
        self.backend = _init_backend(os.environ["BENCH_WANT_BACKEND"],
                                     on_hang=on_hang)
        self.rows = int(os.environ["BENCH_WORKER_ROWS"])
        self.deadline = float(os.environ["BENCH_WORKER_DEADLINE"])
        self.result_file = os.environ["BENCH_RESULT_FILE"]
        self.data_dir = os.environ["BENCH_DATA_DIR"]
        self.skip = set(filter(None,
                               os.environ.get("BENCH_SKIP_SUITES", "")
                               .split(",")))
        from pinot_tpu.engine import ServerQueryExecutor
        from pinot_tpu.parallel import ShardedQueryExecutor

        self.dev = ShardedQueryExecutor()
        self.host = ServerQueryExecutor(use_device=False)
        self.ssb_segs = None
        self.build_s = 0.0

    def over(self, need: float = 30.0) -> bool:
        return time.time() + need > self.deadline

    # -- HBM residency accounting (engine/residency.py) ---------------------
    def _staging_mark(self) -> dict:
        return self.dev.residency.stats_snapshot()

    def _staging_delta(self, mark: dict) -> dict:
        """Per-suite staging counters: hit/miss/eviction/spill deltas since
        ``mark``, plus the current/peak staged bytes."""
        now = self.dev.residency.stats_snapshot()
        out = {k: now[k] - mark.get(k, 0)
               for k in ("hits", "misses", "evictions",
                         "pinBlockedEvictions", "spills", "demotions",
                         "promotions", "hostDrops", "slicedQueries")}
        out["stagedBytes"] = now["stagedBytes"]
        out["peakBytes"] = now["peakBytes"]
        out["hostBytes"] = now["hostBytes"]
        out["hostPeakBytes"] = now["hostPeakBytes"]
        return out

    # -- path-decision ledger (common/tracing.py) ---------------------------
    def _decision_mark(self) -> dict:
        from pinot_tpu.common.tracing import LEDGER

        return LEDGER.snapshot()

    def _decision_delta(self, mark: dict) -> dict:
        """Per-suite decline-reason histogram: every point where execution
        declined a faster rung during this suite, keyed
        "point:declined->chosen:reason"."""
        from pinot_tpu.common.tracing import LEDGER

        return LEDGER.delta(mark)

    @staticmethod
    def _validate_decisions(suite: str, decisions: dict) -> None:
        """Every reason in a suite's decision histogram must be registered
        in tracing.reason_registry() (per-tree ``treeN`` picks are the one
        dynamic namespace). The lint `decisions` family proves literal
        reasons statically; this is the runtime mirror that also catches
        reasons built from variables/f-strings. BENCH_ALLOW_UNREGISTERED_
        REASON=1 downgrades the failure to a log line for bring-up runs."""
        from pinot_tpu.common import tracing

        registered = tracing.registered_reason_codes()
        bad = []
        for key in decisions or {}:
            try:
                _point, _chosen, _declined, reason = \
                    tracing.parse_decision_key(key)
            except Exception:
                bad.append(key)
                continue
            if reason not in registered \
                    and not re.fullmatch(r"tree\d+", reason):
                bad.append(key)
        if not bad:
            return
        msg = (f"{suite}: unregistered decision reason(s) in the ledger: "
               f"{sorted(bad)[:8]} — register them in the matching "
               f"tracing reason namespace or fix the recording site")
        if os.environ.get("BENCH_ALLOW_UNREGISTERED_REASON"):
            _log(f"WARNING {msg}")
            return
        raise AssertionError(msg)

    @staticmethod
    def _mesh_devices():
        """Device count the sharded combine's mesh spans (conftest-forced
        virtual CPU devices count too) — recorded per suite so every round
        says what mesh shape produced its numbers."""
        try:
            import jax

            return len(jax.devices())
        except Exception:
            return None

    def record(self, suite: str, rec: dict) -> None:
        rec = dict(rec, suite=suite, backend=rec.get("backend", self.backend))
        rec.setdefault("mesh_devices", self._mesh_devices())
        with open(self.result_file, "a") as f:
            f.write(json.dumps(rec) + "\n")
            f.flush()
            os.fsync(f.fileno())
        # suites without a per-query p50 log their own headline scalar
        # (star-tree: ms; qps: queries/sec — the r05 log had an empty
        # "recorded qps:" line because neither key existed there;
        # residency: the sliced-combine p50)
        scalar = rec.get("p50_ms_per_query",
                         rec.get("ms", rec.get(
                             "qps", rec.get("sliced_p50_ms_per_query",
                                            rec.get("p50_ms", "")))))
        _log(f"recorded {suite}: {scalar}")

    def run(self) -> None:
        for suite, fn in (("ssb", self.bench_ssb),
                          ("qps", self.bench_qps),
                          ("micro", self.bench_micro),
                          ("startree", self.bench_startree),
                          ("sketches", self.bench_sketches),
                          ("residency", self.bench_residency),
                          ("cluster", self.bench_cluster),
                          ("reduce", self.bench_reduce),
                          ("realtime", self.bench_realtime),
                          ("userfacing", self.bench_userfacing)):
            if suite in self.skip:
                _log(f"{suite}: already chip-served, skipping")
                continue
            if self.over(60):
                _log(f"{suite}: budget exhausted, stopping worker")
                break
            try:
                mark = self._staging_mark()
                dmark = self._decision_mark()
                rec = fn()
                rec.setdefault("staging", self._staging_delta(mark))
                # every suite records its decline-reason histogram: the
                # BENCH JSON must EXPLAIN every non-device fallback, not
                # just count it (the "why is pallas_kernels 0" evidence)
                rec.setdefault("decisions", self._decision_delta(dmark))
                # ... and the histogram must parse against the reason
                # registry, whatever suite produced it (the userfacing
                # suite's loud-fail, promoted to all suites)
                self._validate_decisions(suite, rec.get("decisions"))
                self.record(suite, rec)
            except Exception as exc:
                traceback.print_exc(file=sys.stderr)
                self.record(suite, {
                    "error": f"{type(exc).__name__}: {exc}"[:300]})

    def _pallas_kernel_counts(self) -> dict:
        """Fused-kernel counters: compiled sharded-combine programs (incl.
        group-range probes) + the per-segment run_segment kernel cache."""
        return {"sharded": len(self.dev._pallas_sharded),
                "segment": len(self.dev.pallas_kernels),
                "total": (len(self.dev._pallas_sharded)
                          + len(self.dev.pallas_kernels))}

    # -- data ---------------------------------------------------------------
    def segments(self):
        from pinot_tpu.segment import load_segment
        from pinot_tpu.tools import ssb

        if self.ssb_segs is not None:
            return self.ssb_segs
        manifest = os.path.join(self.data_dir, "manifest.json")
        # treeConfig bumps when the default SSB tree set changes shape, so
        # prebuilt segments from an older round rebuild instead of serving
        # stale (fewer/smaller) trees: v2 = the 5-tree all-13-flights set
        want = {"rows": self.rows, "segments": NUM_SEGMENTS,
                "treeConfig": "v2-multitree"}
        have = None
        try:
            with open(manifest) as f:
                have = json.load(f)
        except (FileNotFoundError, ValueError):
            pass
        if have == want:
            _log(f"loading {NUM_SEGMENTS} prebuilt SSB segments")
            self.ssb_segs = [
                load_segment(os.path.join(self.data_dir, f"ssb_{i}"))
                for i in range(NUM_SEGMENTS)]
            self.build_s = 0.0
        else:
            _log(f"building SSB segments ({self.rows} rows, "
                 f"{NUM_SEGMENTS} segments, {os.cpu_count()} cpus)")
            t0 = time.perf_counter()
            self.ssb_segs = ssb.build_segments(
                0, self.data_dir, num_segments=NUM_SEGMENTS, rows=self.rows)
            self.build_s = time.perf_counter() - t0
            with open(manifest, "w") as f:
                json.dump(want, f)
            _log(f"built in {self.build_s:.1f}s")
        return self.ssb_segs

    def baseline_frame(self):
        from pinot_tpu.tools import ssb, ssb_baseline

        return ssb_baseline.make_frame(
            ssb.generate_table(NUM_SEGMENTS, self.rows))

    # -- sub-suites ---------------------------------------------------------
    def bench_ssb(self) -> dict:
        from pinot_tpu.common.tracing import parse_decision_key
        from pinot_tpu.query import compile_query
        from pinot_tpu.tools import ssb, ssb_baseline

        staging_mark = self._staging_mark()
        decision_mark = self._decision_mark()
        segs = self.segments()
        # explicit LIMIT: the engine applies the reference's default
        # group-by LIMIT 10 otherwise, and the baseline computes FULL
        # group sets (the SSB flights' intended result)
        ctxs = {qid: compile_query(q + " LIMIT 100000")
                for qid, q in ssb.QUERIES.items()}

        # plan-space kernel preflight BEFORE anything touches the chip:
        # every flight's extracted spec (+ the fuzz grid) through the
        # static lowering model; predicted-fail shapes pre-seed the
        # per-shape blocklist with their pallas_preflight_<rule> reason
        # so the engine declines them loudly instead of dying in Mosaic.
        # The verdict table rides the round JSON AND /debug/pallas.
        _log("ssb: kernel preflight (plan-space verdicts)")
        from pinot_tpu.tools import preflight as _preflight

        pf_table = _preflight.run_preflight(segs)
        pf_seeded = _preflight.attach_verdicts(self.dev, pf_table)
        pf = _preflight.serializable_table(pf_table)
        self.record("preflight", {
            "passed": pf["passed"], "failed": pf["failed"],
            "ssb_failed": pf["ssb_failed"],
            "seeded_blocklist": pf_seeded,
            "model": pf["model"], "shapes": pf["shapes"]})

        _log("ssb: pandas baseline (build frame)")
        df = self.baseline_frame()
        base_ms = {}
        parity_fail = []
        rungs = {}
        docs_scanned = {}
        tree_index = {}
        for qid, ctx in ctxs.items():
            _log(f"ssb {qid}: baseline + device compile + parity")
            want = ssb_baseline.run_query(df, qid)
            t0 = time.perf_counter()
            want = ssb_baseline.run_query(df, qid)
            base_ms[qid] = (time.perf_counter() - t0) * 1e3
            got, qstats = self.dev.execute(ctx, segs)   # compiles + warms
            rungs[qid] = _ssb_rung(qstats)
            docs_scanned[qid] = qstats.num_docs_scanned
            tree_index[qid] = qstats.startree_tree_index
            if not ssb_baseline.rows_match(got.rows, want, rel=1e-6):
                parity_fail.append(qid)
        if parity_fail:
            raise AssertionError(f"SSB parity vs pandas failed: "
                                 f"{parity_fail}")
        # the Q3.2/Q3.3 latency story depends on the hash rung (or the
        # narrowed dense rung): a silent regression back to the sort rung
        # must fail the suite LOUDLY, not ship a slow number
        regressed = [q for q in ("Q3.2", "Q3.3")
                     if rungs.get(q) in ("sort", "host")]
        if regressed:
            raise AssertionError(
                f"group-by rung regression: {regressed} fell back to "
                f"{[rungs[q] for q in regressed]} (rungs: {rungs})")
        # with the default multi-tree lineorder config, ALL 13 flights
        # must serve from pre-aggregated node slices on DEVICE — any
        # flight regressing to the scan (or the host walker) silently
        # re-pays the full-table scan this tree set removed. The ledger
        # must also carry ZERO of the two coverage-gap reasons the tree
        # set exists to close. BENCH_ALLOW_SCAN_RUNG=1 opts out (tree-less
        # experiments / capped-memory runs).
        if segs and segs[0].metadata.star_tree_count \
                and not os.environ.get("BENCH_ALLOW_SCAN_RUNG"):
            off_tree = [q for q in ctxs
                        if rungs.get(q) != "startree_device"]
            if off_tree:
                raise AssertionError(
                    f"star-tree rung regression: {off_tree} served by "
                    f"{[rungs[q] for q in off_tree]} instead of "
                    f"startree_device (rungs: {rungs})")
            # docs_scanned per query: the pre-agg rung must stay orders of
            # magnitude under the scan (a tree serving most of its records
            # means the split order no longer matches the flight)
            over = {q: n for q, n in docs_scanned.items()
                    if n >= max(1, self.rows // 10)}
            if over:
                raise AssertionError(
                    f"star-tree docs_scanned regression: {over} vs "
                    f"{self.rows} rows — the sub-scan rung is not sub-scan")
            closed = ("startree_expression_agg_no_pair",
                      "startree_group_off_split_order")
            reopened = [k for k in self._decision_delta(decision_mark)
                        if parse_decision_key(k)[0] == "startree"
                        and parse_decision_key(k)[3] in closed]
            if reopened:
                raise AssertionError(
                    f"star-tree coverage gap reopened: {reopened} — the "
                    "default tree set must fit every SSB flight")

        per_q50, per_q99 = {}, {}
        for qid, ctx in ctxs.items():
            _log(f"ssb {qid}: timing device path")
            samples = []
            for _ in range(WARMUP):
                self.dev.execute(ctx, segs)
            for _ in range(ITERS):
                t0 = time.perf_counter()
                self.dev.execute(ctx, segs)
                samples.append((time.perf_counter() - t0) * 1e3)
            per_q50[qid] = float(np.percentile(samples, 50))
            per_q99[qid] = float(np.percentile(samples, 99))
            # partial record PER QUERY: a mid-suite chip loss still ships
            # every completed query with its rung + pallas kernel counts
            # (exactly which queries fired pallas before the loss)
            self.record(f"ssb:{qid}", {
                "p50_ms": round(per_q50[qid], 3),
                "p99_ms": round(per_q99[qid], 3),
                "rung": rungs.get(qid),
                "docs_scanned": docs_scanned.get(qid),
                "tree_index": tree_index.get(qid),
                "pallas_kernels": self._pallas_kernel_counts(),
            })
        n = len(ctxs)
        dev50 = sum(per_q50.values()) / n
        base50 = sum(base_ms.values()) / n
        staging = self._staging_delta(staging_mark)
        # the SSB working set must be HBM-resident under the default
        # budget: a spill means the headline number silently timed the
        # HOST engine — fail loudly instead of shipping it
        # (BENCH_ALLOW_SPILL=1 opts out for capped-budget experiments)
        if staging["spills"] and not os.environ.get("BENCH_ALLOW_SPILL"):
            raise AssertionError(
                f"SSB spilled {staging['spills']} queries to the host "
                f"engine (budget "
                f"{self.dev.residency.budget_bytes}, peak "
                f"{staging['peakBytes']} B staged); the device number "
                f"would be a lie")
        # every pallas decline during the SSB suite must carry a
        # CLASSIFIED reason code: an "unknown" means a decline path the
        # ledger cannot explain, and the next TPU-fight PR would be
        # aiming blind — fail loudly instead of shipping it
        decisions = self._decision_delta(decision_mark)
        unknown = [k for k in decisions
                   if parse_decision_key(k)[0] == "pallas"
                   and parse_decision_key(k)[3] == "unknown"]
        if unknown:
            raise AssertionError(
                f"SSB pallas declines with unclassified reason codes: "
                f"{unknown} — every decline must be classified "
                f"(decisions: {decisions})")
        # preflight-miss gate: a shape the preflight PASSED must never
        # record pallas_exec_failed (predicted-fail shapes are seeded
        # into the blocklist, so they decline before the chip — any
        # exec failure left is a LOWERING-MODEL BUG and must be visible
        # in the trajectory, not silently absorbed by the jnp fallback)
        exec_failed = [k for k in decisions
                       if parse_decision_key(k)[0] == "pallas"
                       and parse_decision_key(k)[3] == "pallas_exec_failed"]
        if exec_failed and not os.environ.get("BENCH_ALLOW_PREFLIGHT_MISS"):
            raise AssertionError(
                f"pallas_exec_failed recorded for shapes the preflight "
                f"passed: {exec_failed} — the lowering model missed a "
                f"constraint; turn the Mosaic failure into a preflight "
                f"rule (BENCH_ALLOW_PREFLIGHT_MISS=1 records anyway)")
        return {
            "preflight": {"passed": pf["passed"], "failed": pf["failed"],
                          "ssb_failed": pf["ssb_failed"],
                          "seeded_blocklist": pf_seeded},
            "decisions": decisions,
            "staging": staging,
            "rows": self.rows,
            "sf": round(self.rows / ssb.ROWS_PER_SF, 3),
            "build_s": round(self.build_s, 1),
            "p50_ms_per_query": round(dev50, 3),
            "p99_ms_per_query": round(sum(per_q99.values()) / n, 3),
            "vs_baseline": round(base50 / dev50, 3),
            "baseline_engine": "pandas-vectorized-categorical",
            "baseline_ms_per_query": round(base50, 2),
            "per_query_ms": {q: round(v, 2) for q, v in per_q50.items()},
            "per_query_p99_ms": {q: round(v, 2) for q, v in per_q99.items()},
            "group_by_rung": rungs,
            "docs_scanned": docs_scanned,
            # which tree served each flight + what each tree cost to build
            # (wall seconds summed across segments; creator-measured)
            "startree_tree_index": tree_index,
            "startree_build_s": _tree_build_times(segs),
            # BOTH pallas counters: the sharded combine kernels (what the
            # serving path fires) AND the per-segment run_segment cache
            # (star-tree-free per-segment flights) — the old record
            # counted only the sharded dict, hiding per-segment firings
            "pallas_kernels": self._pallas_kernel_counts(),
            "parity": "ok",
        }

    def bench_qps(self) -> dict:
        """Closed-loop multi-thread throughput sweep (ref: QueryRunner.java
        multiThreadedQueryRunner: numThreads issuing back-to-back, report
        QPS + latency percentiles). Sweeps 1/2/4/8 client threads so the
        record carries the SCALING story, not one point: ``qps_scaling`` =
        4-thread QPS / 1-thread QPS and ``qps_scaling_8`` = 8-thread /
        1-thread, plus per-level launch-coalescing, adaptive-window,
        kernel single-flight, and admission deltas. Gates (escape:
        BENCH_ALLOW_FLAT_QPS=1 for 1-2 core hosts / capped experiments):
        4-thread scaling >= 1.5x on >=4 cores, 8-thread scaling > 1.5x on
        >=8 cores — the scheduler tier must keep scaling past the old
        gate level, not plateau at it. A final SATURATION level drives
        2x the admission capacity through a deliberately tight gate and
        records that overload degrades to bounded-latency REJECTION
        (p99 < 2x p50 with rejections > 0), not convoy collapse."""
        import concurrent.futures

        from pinot_tpu.engine.errors import QueryRejectedError
        from pinot_tpu.query import compile_query
        from pinot_tpu.tools import ssb

        segs = self.segments()
        qids = ("Q1.1", "Q2.1", "Q3.2")
        ctxs = [compile_query(ssb.QUERIES[q] + " LIMIT 100000")
                for q in qids]
        for ctx in ctxs:
            self.dev.execute(ctx, segs)   # compile/warm
        launcher = getattr(self.dev, "launcher", None)
        admission = getattr(self.dev, "admission", None)
        flight = getattr(self.dev, "_kernel_flight", None)
        qflight = getattr(self.dev, "_query_flight", None)
        seconds = 5.0
        levels = {}
        lock = threading.Lock()

        def run_level(threads: int) -> dict:
            lat: list = []
            rejected = [0]
            stop_at = time.perf_counter() + seconds

            def pump(i: int) -> int:
                done = 0
                while time.perf_counter() < stop_at:
                    ctx = ctxs[(i + done) % len(ctxs)]
                    t0 = time.perf_counter()
                    try:
                        self.dev.execute(ctx, segs)
                    except QueryRejectedError:
                        # typed retriable rejection: back off and retry —
                        # the client half of bounded-latency degradation
                        # (rejected attempts are counted, not folded into
                        # admitted-query latency; the backoff keeps the
                        # retry storm from stealing cpu from admitted
                        # queries)
                        with lock:
                            rejected[0] += 1
                        time.sleep(0.02)
                        continue
                    dt = (time.perf_counter() - t0) * 1e3
                    with lock:
                        lat.append(dt)
                    done += 1
                return done

            mark = launcher.stats_snapshot() if launcher else {}
            adm_mark = admission.stats_snapshot() if admission else {}
            t0 = time.perf_counter()
            with concurrent.futures.ThreadPoolExecutor(threads) as pool:
                total = sum(pool.map(pump, range(threads)))
            wall = time.perf_counter() - t0
            arr = np.asarray(lat) if lat else np.asarray([0.0])
            out = {
                "qps": round(total / wall, 2),
                "p50_ms": round(float(np.percentile(arr, 50)), 3),
                "p95_ms": round(float(np.percentile(arr, 95)), 3),
                "p99_ms": round(float(np.percentile(arr, 99)), 3),
                "rejected": rejected[0],
            }
            if launcher:
                now = launcher.stats_snapshot()
                out["launch"] = {
                    k: round(now[k] - mark.get(k, 0), 3)
                    for k in ("requests", "launches", "coalescedLaunches",
                              "launchesSaved", "dedupedRequests",
                              "windowWaits", "windowGathered")}
                out["launch"]["maxBatchSize"] = now["maxBatchSize"]
            if admission:
                now = admission.stats_snapshot()
                out["admission"] = {
                    k: round(now[k] - adm_mark.get(k, 0), 3)
                    for k in ("admitted", "rejected", "rejectedQueueFull",
                              "rejectedWaitExpired")}
            if flight:
                out["kernelFlight"] = flight.snapshot()
            if qflight:
                out["queryFlight"] = qflight.snapshot()
            return out

        for threads in (1, 2, 4, 8):
            _log(f"qps: sweeping {threads} thread(s)")
            levels[str(threads)] = run_level(threads)

        qps1 = levels["1"]["qps"]
        qps4 = levels["4"]["qps"]
        qps8 = levels["8"]["qps"]
        scaling = round(qps4 / qps1, 3) if qps1 else None
        scaling8 = round(qps8 / qps1, 3) if qps1 else None
        cpus = os.cpu_count() or 1
        allow_flat = os.environ.get("BENCH_ALLOW_FLAT_QPS")
        if cpus >= 4 and scaling is not None and scaling < 1.5 \
                and not allow_flat:
            raise AssertionError(
                f"QPS scaling regressed: 4-thread {qps4} vs 1-thread "
                f"{qps1} ({scaling}x < 1.5x on a {cpus}-core "
                f"host) — the launch scheduler is serializing instead of "
                f"coalescing (levels: {levels})")
        # 8-thread gate: the scheduler tier (single-flight + adaptive
        # window + SEWF + admission) must keep scaling PAST the 4-thread
        # gate level — an 8-thread result at/below 1.5x means queueing
        # above the fan-out still dominates
        if cpus >= 8 and scaling8 is not None and scaling8 <= 1.5 \
                and not allow_flat:
            raise AssertionError(
                f"8-thread QPS scaling stuck at the 4-thread gate: "
                f"{qps8} vs {qps1} ({scaling8}x <= 1.5x on a {cpus}-core "
                f"host) — the request tier is convoying (levels: "
                f"{levels})")

        saturation = self._qps_saturation(run_level, admission)

        four = levels["4"]
        return {
            "queries": list(qids),
            "threads": 4,
            "qps": four["qps"],
            "p50_ms": four["p50_ms"],
            "p95_ms": four["p95_ms"],
            "p99_ms": four["p99_ms"],
            "qps_scaling": scaling,
            "qps_scaling_8": scaling8,
            "qps_by_threads": levels,
            "saturation": saturation,
        }

    def _qps_saturation(self, run_level, admission) -> dict:
        """Overload-degradation probe: bound the admission gate to
        ``slots`` concurrent queries + an equal-depth queue, then drive
        4x slots closed-loop clients (>= 2x capacity including the
        queue). Healthy degradation = nonzero REJECTIONS with admitted
        p99 still bounded (< 2x p50) because no query ever waits behind
        more than ``slots`` others; convoy collapse would show p99
        stretching with zero rejections."""
        if admission is None:
            return {"skipped": "no admission gate"}
        snap = admission.snapshot()
        slots = min(8, max(2, (os.cpu_count() or 2) // 2))
        threads = 4 * slots
        _log(f"qps: saturation probe ({threads} threads vs {slots} slots)")
        admission.configure(max_concurrent=slots, max_queue=slots,
                            max_wait_ms=2000)
        try:
            out = run_level(threads)
        finally:
            admission.configure(max_concurrent=snap["maxConcurrent"],
                                max_queue=snap["maxQueue"],
                                max_wait_ms=snap["maxWaitMs"])
        out["threads"] = threads
        out["slots"] = slots
        p50, p99 = out["p50_ms"], out["p99_ms"]
        out["p99_over_p50"] = round(p99 / p50, 2) if p50 else None
        out["bounded"] = bool(p50 and p99 < 2 * p50
                              and out["rejected"] > 0)
        if out["rejected"] == 0 and not os.environ.get(
                "BENCH_ALLOW_FLAT_QPS"):
            # 4x-slots closed-loop clients vs a slots-deep queue MUST
            # produce rejections; zero means the admission gate is not
            # actually bounding — the overload story would be a lie
            raise AssertionError(
                f"saturation probe saw 0 rejections at {threads} threads "
                f"vs {slots} slots — admission gate not engaging ({out})")
        return out

    def bench_micro(self) -> dict:
        from pinot_tpu.query import compile_query

        tmp = tempfile.mkdtemp(prefix="bench_micro_", dir=self.data_dir)
        segs = _build_micro(tmp)
        ctxs = [compile_query(q) for q in MICRO_QUERIES]
        for ctx in ctxs:
            drt, _ = self.dev.execute(ctx, segs)
            hrt, _ = self.host.execute(ctx, segs)
            _assert_parity(ctx.sql, drt.rows, hrt.rows)
        # r2/r3 methodology (WARMUP=2/ITERS=7) for the DEVICE number's
        # cross-round comparability; the host engine is ~200x slower, so
        # its denominator gets 2 passes (r5: 9 host passes burned ~4 min
        # of the bench budget for a ratio that matched to 3 digits)
        dev_p50, _ = _time_suite(lambda c: self.dev.execute(c, segs),
                                 ctxs, iters=7, warmup=2)
        host_p50, _ = _time_suite(lambda c: self.host.execute(c, segs),
                                  ctxs, iters=2, warmup=0)
        return {"p50_ms_per_query": round(dev_p50 / len(ctxs) * 1e3, 3),
                "vs_host_engine": round(host_p50 / dev_p50, 3)}

    def bench_startree(self) -> dict:
        from pinot_tpu.query import compile_query

        tmp = tempfile.mkdtemp(prefix="bench_st_", dir=self.data_dir)
        segs = _build_startree(tmp)
        self._st_segs = segs
        st_ctx = compile_query(STARTREE_QUERY)
        st_rt, st_stats = self.dev.execute(st_ctx, segs)
        scan_ctx = compile_query(STARTREE_QUERY
                                 + " OPTION(useStarTree=false)")
        scan_rt, _ = self.dev.execute(scan_ctx, segs)
        _assert_parity("startree", st_rt.rows, scan_rt.rows)
        st_p50, _ = _time_suite(lambda c: self.dev.execute(c, segs),
                                [st_ctx])
        scan_p50, _ = _time_suite(lambda c: self.dev.execute(c, segs),
                                  [scan_ctx])
        out = {"ms": round(st_p50 * 1e3, 3),
               "scan_ms": round(scan_p50 * 1e3, 3),
               "group_by_rung": st_stats.group_by_rung,
               "docs_scanned": st_stats.num_docs_scanned}
        # tentpole (c) measurement: the default SSB tree set built by the
        # lexsort engine at scale (BENCH_TREEBUILD_ROWS, e.g. 24_000_000)
        # — per-tree wall seconds + record counts in the round JSON
        scale_rows = int(os.environ.get("BENCH_TREEBUILD_ROWS", "0") or 0)
        if scale_rows:
            out["build_at_scale"] = _tree_build_at_scale(scale_rows)
        return out

    def bench_sketches(self) -> dict:
        from pinot_tpu.query import compile_query

        segs = getattr(self, "_st_segs", None)
        if segs is None:
            tmp = tempfile.mkdtemp(prefix="bench_sk_", dir=self.data_dir)
            segs = _build_startree(tmp)
        ctxs = [compile_query(q) for q in SKETCH_QUERIES]
        for ctx in ctxs:
            self.dev.execute(ctx, segs)
        p50, _ = _time_suite(lambda c: self.dev.execute(c, segs), ctxs,
                             iters=3)
        return {"p50_ms_per_query": round(p50 / len(ctxs) * 1e3, 3)}

    def bench_residency(self) -> dict:
        """Tiered residency under memory pressure: pin the HBM budget to
        ~1/4 of the measured working set of three non-star-tree SSB
        flights and serve them via the budget-sliced sharded combine,
        against two baselines:

        - **host-spill**: the SAME budget with slicing + the host tier
          disabled (the pre-tier fit-or-fail behavior) — the over-budget
          queries fall to the host engine;
        - **restage vs rebuild**: one segment staged cold (full column
          build) vs re-staged from a host-tier image (plain H2D).

        Records sliced-vs-spill p50s, restage/rebuild stage latency, and
        the promoted/demoted/dropped byte counters. Fails LOUDLY if an
        over-budget query spilled to the host engine while the host tier
        + slicing could have served it (BENCH_ALLOW_TIER_SPILL=1 escape
        hatch for hosts whose segments individually exceed the budget)."""
        from pinot_tpu.parallel import ShardedQueryExecutor
        from pinot_tpu.query import compile_query
        from pinot_tpu.spi.config import (
            CommonConstants,
            PinotConfiguration,
        )
        from pinot_tpu.tools import ssb

        segs = self.segments()
        qids = ("Q1.1", "Q3.2", "Q4.2")
        # useStarTree=false: since the multi-tree default covers ALL 13
        # flights, the residency suite must opt out explicitly — it
        # exercises the budget-sliced sharded combine over forward
        # columns, not the per-segment node-slice path
        ctxs = [compile_query(ssb.QUERIES[q]
                              + " LIMIT 100000 OPTION(useStarTree=false)")
                for q in qids]

        # 1) working set of THIS query set, measured uncapped
        probe = ShardedQueryExecutor()
        oracle_rows = []
        for ctx in ctxs:
            rt, _ = probe.execute(ctx, segs)
            oracle_rows.append(rt.rows)
        ws = probe.residency.staged_bytes()
        probe.residency.clear()
        probe.close()
        budget = max(1, ws // 4)

        # 2) sliced-combine serving at budget = ws/4
        capped = ShardedQueryExecutor(hbm_budget_bytes=budget)
        parity_fail = []
        for qid, ctx, want in zip(qids, ctxs, oracle_rows):
            rt, _ = capped.execute(ctx, segs)
            if rt.rows != want:
                parity_fail.append(qid)
        if parity_fail:
            raise AssertionError(
                f"sliced combine diverged from the uncapped oracle: "
                f"{parity_fail}")
        sliced_p50, _ = _time_suite(
            lambda c: capped.execute(c, segs), ctxs, iters=3, warmup=0)
        snap = capped.residency.stats_snapshot()
        if snap["spills"] and not os.environ.get("BENCH_ALLOW_TIER_SPILL"):
            raise AssertionError(
                f"over-budget queries fell to the host engine "
                f"({snap['spills']} spills) while the host tier + sliced "
                f"combine could have served them (budget {budget} B, "
                f"working set {ws} B)")
        capped_counters = {
            k: snap[k] for k in
            ("demotions", "promotions", "hostDrops", "slicedQueries",
             "spills", "demotedBytes", "promotedBytes",
             "hostDroppedBytes", "hostPeakBytes", "estimateScale")}
        capped.residency.clear()
        capped.close()

        # 3) host-spill baseline: same budget, tier + slicing disabled
        cfg = PinotConfiguration(
            {CommonConstants.HBM_SLICING_ENABLED_KEY: "false",
             CommonConstants.HOSTRAM_ENABLED_KEY: "false"}, use_env=False)
        spill = ShardedQueryExecutor(hbm_budget_bytes=budget, config=cfg)
        spill_p50, _ = _time_suite(
            lambda c: spill.execute(c, segs), ctxs, iters=3, warmup=0)
        spill_snap = spill.residency.stats_snapshot()
        spill.residency.clear()
        spill.close()

        # 4) restage-from-host vs cold rebuild, one segment
        from pinot_tpu.engine.residency import ResidencyManager

        cols = [c for c in
                ("lo_orderdate", "lo_extendedprice", "lo_discount",
                 "lo_quantity")
                if c in segs[0].metadata.columns]
        rm = ResidencyManager(budget_bytes=0)
        t0 = time.perf_counter()
        st = rm.stage(segs[0])
        for c in cols:
            st.column(c)
        rebuild_ms = (time.perf_counter() - t0) * 1e3
        assert rm.demote(segs[0].segment_name)
        t0 = time.perf_counter()
        st = rm.stage(segs[0])
        for c in cols:
            st.column(c)
        restage_ms = (time.perf_counter() - t0) * 1e3
        promoted = rm.stats_snapshot()["promotions"]
        rm.clear()

        n = len(ctxs)
        return {
            "queries": list(qids),
            "working_set_bytes": ws,
            "budget_bytes": budget,
            "over_budget_x": round(ws / budget, 2),
            "sliced_p50_ms_per_query": round(sliced_p50 / n * 1e3, 3),
            "host_spill_p50_ms_per_query": round(spill_p50 / n * 1e3, 3),
            "sliced_vs_spill": round(spill_p50 / sliced_p50, 3)
            if sliced_p50 else None,
            "spill_baseline_spills": spill_snap["spills"],
            "restage_ms": round(restage_ms, 3),
            "rebuild_ms": round(rebuild_ms, 3),
            "restage_vs_rebuild": round(rebuild_ms / restage_ms, 3)
            if restage_ms else None,
            "restage_promotions": promoted,
            "tier_counters": capped_counters,
            "parity": "ok",
        }

    def bench_cluster(self) -> dict:
        """SSB through the FULL distributed path, scaled 2 -> 8 servers:
        broker parse -> partition-aware routing -> scatter -> DataTable
        wire -> broker reduce. Segments are partition-aligned (one d_year
        per segment, Modulo partition metadata recorded at build), the
        table config enables the broker partition pruner, and every query
        records its scatter fan-out (numServersQueried) + prune ratio.
        LOUD-FAIL: at 8 servers a partition-filtered SSB query must prune
        >50% of the scatter targets (BENCH_ALLOW_NO_PRUNE records anyway)."""
        from pinot_tpu.spi.table import (
            RoutingConfig,
            SegmentsValidationConfig,
            TableConfig,
        )
        from pinot_tpu.tools import ssb
        from pinot_tpu.tools.cluster import EmbeddedCluster

        rows = min(self.rows, 500_000)
        n_segs = 8
        seg_dir = os.path.join(self.data_dir, "cluster_segs_part")
        if not os.path.isdir(os.path.join(seg_dir,
                                          f"ssb_part_{n_segs - 1}")):
            ssb.build_segments(0, seg_dir, num_segments=n_segs, rows=rows,
                               partitioned=True)
        qids = ("Q1.1", "Q2.1", "Q4.2")
        # queries with a d_year eq/IN predicate the partition pruner eats
        partition_filtered = ("Q1.1", "Q4.2")
        iters = 5
        per_servers = {}
        for n_servers in (2, 8):
            # device_reduce: broker and servers share this process, so
            # group-by partials merge on device (PR-16); per-query
            # reduce_path below records which rung actually served
            cluster = EmbeddedCluster(
                num_servers=n_servers,
                data_dir=os.path.join(self.data_dir,
                                      f"cluster_{n_servers}"),
                device_reduce=True)
            try:
                cluster.create_table(
                    TableConfig(
                        "ssb_lineorder",
                        validation_config=SegmentsValidationConfig(
                            time_column_name="d_yearmonthnum"),
                        routing_config=RoutingConfig(
                            segment_pruner_types=["partition"])),
                    ssb.ssb_schema())
                for i in range(n_segs):
                    cluster.upload_segment_dir(
                        "ssb_lineorder_OFFLINE",
                        f"{seg_dir}/ssb_part_{i}")
                assert cluster.wait_for_ev_converged(
                    "ssb_lineorder_OFFLINE"), \
                    "external view did not converge: refusing a partial bench"
                hosting = cluster.hosting_servers("ssb_lineorder_OFFLINE")
                fanout, prune_ratio, p50 = {}, {}, {}
                reduce_p50, reduce_path, docs_scanned = {}, {}, {}
                for qid in qids:
                    sql = ssb.QUERIES[qid]
                    cluster.query(sql)  # warm: staging + kernel compile
                    samples = []
                    reduce_samples = []
                    queried = 0
                    for _ in range(iters):
                        t0 = time.perf_counter()
                        resp = cluster.query(sql)
                        samples.append(time.perf_counter() - t0)
                        assert not resp.exceptions, resp.exceptions
                        assert (resp.num_servers_responded
                                == resp.num_servers_queried), \
                            f"{qid}: partial gather in a healthy cluster"
                        queried = resp.num_servers_queried
                        # broker reduce phase (the PR-9 Reduce span's
                        # timer) — the array-native reduce's own cost,
                        # recorded per query so reduce-tier regressions
                        # show up independent of scatter/server time
                        reduce_samples.append(
                            resp.phase_times_ms.get("REDUCE", 0.0))
                        # which reduce rung served (device / vectorized
                        # / oracle) — trajectory rounds attribute reduce
                        # wins to the path, not just the timing
                        reduce_path[qid] = resp.stats.reduce_path
                        # per-query scan footprint (PR-18): with an index
                        # rung in the ladder, docs_scanned is the selectivity
                        # story — trajectory rounds can spot a query falling
                        # off the index back to a full scan
                        docs_scanned[qid] = resp.stats.num_docs_scanned
                    fanout[qid] = queried
                    prune_ratio[qid] = round(
                        1.0 - queried / max(len(hosting), 1), 3)
                    p50[qid] = round(
                        float(np.percentile(samples, 50)) * 1e3, 3)
                    reduce_p50[qid] = round(
                        float(np.percentile(reduce_samples, 50)), 3)
                per_servers[str(n_servers)] = {
                    "servers_hosting": len(hosting),
                    "scatter_fanout": fanout,
                    "prune_ratio": prune_ratio,
                    "p50_ms": p50,
                    "reduce_p50_ms": reduce_p50,
                    "reduce_path": reduce_path,
                    "docs_scanned": docs_scanned,
                }
            finally:
                cluster.shutdown()
        top = per_servers["8"]
        for qid in partition_filtered:
            if top["prune_ratio"][qid] <= 0.5 \
                    and not os.environ.get("BENCH_ALLOW_NO_PRUNE"):
                raise AssertionError(
                    f"cluster: partition-filtered {qid} pruned only "
                    f"{top['prune_ratio'][qid]:.0%} of 8 servers' scatter "
                    f"targets (want >50%) — routing regressed; set "
                    f"BENCH_ALLOW_NO_PRUNE=1 to record anyway")
        return {"rows": rows, "servers": 8, "servers_scaled": [2, 8],
                "p50_ms_per_query": round(
                    sum(top["p50_ms"].values()) / len(qids), 3),
                "partition_filtered": list(partition_filtered),
                "per_servers": per_servers}

    def bench_reduce(self) -> dict:
        """Broker reduce micro-suite: 8 synthesized servers' DataTables
        through the REAL binary wire into BrokerReduceService, vectorized
        vs the row-path oracle. Two shapes: a high-cardinality group-by
        merge (>=100k distinct groups after the merge) and a 100k-row
        ORDER BY LIMIT selection of pre-trimmed, pre-sorted server
        blocks. The group-by merge is ALSO pushed through the PR-16
        device rung (in-process constructor tables over the mesh) and
        must both serve (reduce_path == 'device') and match the oracle
        bit-wise. LOUD-FAIL: vectorized group-by < 5x the oracle,
        selection < 3x, device losing to the vectorized host on a
        multi-device mesh, or ANY row diverging bit-wise from the oracle
        (BENCH_ALLOW_SLOW_REDUCE records the numbers anyway; parity has
        no escape hatch)."""
        import random

        from pinot_tpu.broker.reduce import BrokerReduceService
        from pinot_tpu.common.datatable import DataTable
        from pinot_tpu.engine.results import DataSchema, QueryStats
        from pinot_tpu.query import compile_query

        rng = random.Random(20240814)
        n_servers = 8
        iters = 5
        vec = BrokerReduceService(vectorized=True)
        ora = BrokerReduceService(vectorized=False)
        dev = BrokerReduceService(vectorized=True, device_reduce=True)

        def timed(svc, ctx, raws):
            best = None
            rows = None
            for _ in range(iters):
                tables = [DataTable.from_bytes(r) for r in raws]
                t0 = time.perf_counter()
                table, _, _ = svc.reduce(ctx, tables)
                dt = time.perf_counter() - t0
                best = dt if best is None else min(best, dt)
                rows = table.rows
            return best * 1e3, rows

        # -- group-by: 8 servers x 40k groups -> ~150k-group merge ------
        gb_ctx = compile_query(
            "SELECT k1, k2, sum(v), count(*) FROM t GROUP BY k1, k2 "
            "ORDER BY sum(v) DESC LIMIT 1000")
        gb_tables = []
        for s in range(n_servers):
            groups = {}
            for _ in range(40_000):
                k = ("brand%04d" % rng.randint(0, 499),
                     rng.randint(0, 499))
                groups[k] = [float(rng.randint(0, 10**6)),
                             rng.randint(1, 100)]
            gb_tables.append(DataTable.for_group_by(
                groups, {"k1": "STRING", "k2": "INT"}, QueryStats()))
        gb_raws = [t.to_bytes() for t in gb_tables]
        merged_groups = len({k for r in gb_raws
                             for k in DataTable.from_bytes(r)
                             .group_by_groups()})
        vec_gb_ms, vec_gb_rows = timed(vec, gb_ctx, gb_raws)
        ora_gb_ms, ora_gb_rows = timed(ora, gb_ctx, gb_raws)
        assert vec_gb_rows == ora_gb_rows, \
            "reduce: vectorized group-by diverged from the row-path oracle"

        # -- device rung over the SAME merge (PR-16): the constructor
        # tables stand in for in-process server partials (the embedded
        # cluster topology — wire_decoded=False, the route's premise).
        # Columns pre-sniffed + one warm pass so the timing covers the
        # MERGE, not kernel compilation; parity vs the oracle has NO
        # escape hatch, and the path must actually be 'device'.
        for t in gb_tables:
            t.group_columns()
        dev.reduce(gb_ctx, gb_tables)  # warm: mesh + kernel cache
        dev_gb_ms, dev_gb_rows, dev_gb_path = None, None, None
        for _ in range(iters):
            t0 = time.perf_counter()
            table, dstats, _ = dev.reduce(gb_ctx, gb_tables)
            dms = (time.perf_counter() - t0) * 1e3
            dev_gb_ms = dms if dev_gb_ms is None else min(dev_gb_ms, dms)
            dev_gb_rows, dev_gb_path = table.rows, dstats.reduce_path
        assert dev_gb_rows == ora_gb_rows, \
            "reduce: device group-by diverged from the row-path oracle"
        assert dev_gb_rows == vec_gb_rows, \
            "reduce: device group-by diverged from the vectorized host path"
        assert dev_gb_path == "device", (
            f"reduce: device rung declined to '{dev_gb_path}' "
            f"({dstats.decisions}) — the bench merge shape must SERVE")
        import jax

        bench_devices = len(jax.devices())
        device_speedup = vec_gb_ms / max(dev_gb_ms, 1e-9)
        if bench_devices > 1 and dev_gb_ms > vec_gb_ms:
            print(f"reduce: WARN device merge {dev_gb_ms:.1f}ms LOSES to "
                  f"the vectorized host path {vec_gb_ms:.1f}ms on a "
                  f"{bench_devices}-device mesh",
                  file=sys.stderr)
            if not os.environ.get("BENCH_ALLOW_SLOW_REDUCE"):
                raise AssertionError(
                    f"reduce: device merge {dev_gb_ms:.1f}ms > vectorized "
                    f"host {vec_gb_ms:.1f}ms on a {bench_devices}-device "
                    f"mesh; set BENCH_ALLOW_SLOW_REDUCE=1 to record "
                    f"anyway (speed only — parity never waives)")

        # -- selection: 100k rows total, ORDER BY LIMIT, pre-sorted -----
        per_server = 100_000 // n_servers
        sel_ctx = compile_query(
            "SELECT a, b FROM t ORDER BY b, a LIMIT %d" % per_server)
        schema = DataSchema(["a", "b"], ["STRING", "LONG"])
        sel_raws = []
        for s in range(n_servers):
            rows = sorted(
                [["city%03d" % rng.randint(0, 299),
                  rng.randint(0, 10**6)] for _ in range(per_server)],
                key=lambda r: (r[1], r[0]))
            sel_raws.append(DataTable.for_selection(
                schema, rows, QueryStats(),
                sorted_rows=True).to_bytes())
        vec_sel_ms, vec_sel_rows = timed(vec, sel_ctx, sel_raws)
        ora_sel_ms, ora_sel_rows = timed(ora, sel_ctx, sel_raws)
        assert vec_sel_rows == ora_sel_rows, \
            "reduce: vectorized selection diverged from the row-path oracle"

        gb_speedup = ora_gb_ms / max(vec_gb_ms, 1e-9)
        sel_speedup = ora_sel_ms / max(vec_sel_ms, 1e-9)
        rec = {
            "servers": n_servers,
            "groupby": {"merged_groups": merged_groups,
                        "vectorized_ms": round(vec_gb_ms, 3),
                        "oracle_ms": round(ora_gb_ms, 3),
                        "speedup": round(gb_speedup, 2),
                        "device_ms": round(dev_gb_ms, 3),
                        "device_speedup": round(device_speedup, 2),
                        "device_path": dev_gb_path,
                        "mesh_devices": bench_devices},
            "selection": {"rows": per_server * n_servers,
                          "vectorized_ms": round(vec_sel_ms, 3),
                          "oracle_ms": round(ora_sel_ms, 3),
                          "speedup": round(sel_speedup, 2)},
            "p50_ms": round(vec_gb_ms, 3),
        }
        if not os.environ.get("BENCH_ALLOW_SLOW_REDUCE"):
            assert merged_groups >= 100_000, \
                f"reduce: merge shape shrank to {merged_groups} groups"
            assert gb_speedup >= 5.0, (
                f"reduce: vectorized group-by only {gb_speedup:.1f}x over "
                f"the row-path oracle (want >=5x) — the array-native "
                f"merge regressed; set BENCH_ALLOW_SLOW_REDUCE=1 to "
                f"record anyway")
            assert sel_speedup >= 3.0, (
                f"reduce: vectorized selection only {sel_speedup:.1f}x "
                f"over the row-path oracle (want >=3x); set "
                f"BENCH_ALLOW_SLOW_REDUCE=1 to record anyway")
        return rec

    def bench_realtime(self) -> dict:
        """Realtime serving tier (PR-17): consuming-segment write QPS,
        ingest-to-queryable freshness p50/p99 under a concurrent query
        cadence (the serve path's per-row freshness histogram), device
        group-by latency on the consuming segment, and the
        mutable->immutable seal wall-time through the real commit path
        (default star-tree stamped at seal). LOUD-FAIL: every
        device-eligible query on the consuming segment must serve from
        the mutable_device rung — a host spill means the staging tier
        regressed (BENCH_ALLOW_MUTABLE_HOST=1 records anyway), and the
        sealed segment must serve from startree_device."""
        import math

        from pinot_tpu.common.telemetry import TELEMETRY
        from pinot_tpu.engine import ServerQueryExecutor
        from pinot_tpu.ingestion import MemoryStream
        from pinot_tpu.ingestion.realtime import (
            ConsumerState,
            RealtimeSegmentDataManager,
        )
        from pinot_tpu.ingestion.stream import StreamOffset
        from pinot_tpu.query import compile_query
        from pinot_tpu.segment import load_segment
        from pinot_tpu.segment.mutable import MutableSegment
        from pinot_tpu.spi import DataType, FieldSpec, FieldType, Schema
        from pinot_tpu.spi.table import (
            SegmentsValidationConfig,
            StreamIngestionConfig,
            TableConfig,
            TableType,
        )

        schema = Schema("rtbench", [
            FieldSpec("city", DataType.STRING, FieldType.DIMENSION),
            FieldSpec("clicks", DataType.LONG, FieldType.METRIC),
            FieldSpec("price", DataType.DOUBLE, FieldType.METRIC),
            FieldSpec("ts", DataType.LONG, FieldType.DATE_TIME),
        ])
        cities = [f"city{i:03d}" for i in range(64)]
        rng = np.random.default_rng(7)
        n_rows = int(os.environ.get("BENCH_REALTIME_ROWS", 40_000))
        query_every = max(1, n_rows // 10)

        def make_row(i):
            return {"city": cities[int(rng.integers(64))],
                    "clicks": int(rng.integers(1000)),
                    "price": float(rng.integers(10_000)) / 4.0,
                    "ts": 1_600_000_000_000 + i}

        dev = ServerQueryExecutor(use_device=True)
        sql = ("SELECT city, count(*), sum(clicks) FROM rtbench "
               "GROUP BY city LIMIT 100")
        q = compile_query(sql)

        # -- write QPS + freshness under a query cadence ----------------
        seg = MutableSegment(schema, "rtbench__0__0__b",
                             capacity=max(n_rows, 1024))
        rungs, query_ms, index_s = [], [], 0.0
        for start in range(0, n_rows, query_every):
            t0 = time.perf_counter()
            for i in range(start, min(start + query_every, n_rows)):
                seg.index(make_row(i))
            index_s += time.perf_counter() - t0
            t0 = time.perf_counter()
            _, qstats = dev.execute(q, [seg])
            query_ms.append((time.perf_counter() - t0) * 1e3)
            rungs.append(qstats.group_by_rung)
        write_qps = n_rows / max(index_s, 1e-9)

        spills = [r for r in rungs if r != "mutable_device"]
        if spills and not os.environ.get("BENCH_ALLOW_MUTABLE_HOST"):
            from pinot_tpu.common.tracing import LEDGER

            declines = {k: v for k, v in LEDGER.reason_histogram().items()
                        if k.startswith("mutable_")}
            raise AssertionError(
                f"realtime: {len(spills)}/{len(rungs)} consuming-segment "
                f"queries spilled to {sorted(set(spills))} instead of "
                f"mutable_device (mutable declines: {declines}) — the "
                f"device staging tier regressed; set "
                f"BENCH_ALLOW_MUTABLE_HOST=1 to record anyway")

        fresh = TELEMETRY.histo("rtbench", "freshness").lifetime.snapshot()
        assert fresh["count"] > 0, \
            "realtime: serve path recorded no freshness observations"
        assert math.isfinite(fresh["p99"]), fresh

        # -- seal wall-time through the real commit path ----------------
        seal_rows = min(n_rows, 20_000)
        MemoryStream.create("bench_rt", 1)
        try:
            stream = MemoryStream.get("bench_rt")
            for i in range(seal_rows):
                stream.produce(make_row(i), partition=0)
            cfg = TableConfig(
                "rtbench", TableType.REALTIME,
                validation_config=SegmentsValidationConfig(
                    time_column_name="ts"),
                stream_config=StreamIngestionConfig(
                    stream_type="memory", topic="bench_rt",
                    segment_flush_threshold_rows=seal_rows))
            mgr = RealtimeSegmentDataManager(
                "rtbench__0__0__s", cfg, schema, partition=0,
                start_offset=StreamOffset(0),
                output_dir=os.path.join(self.data_dir, "bench_rt_seal"))
            res = mgr.consume_until_committed()
            assert res.state is ConsumerState.COMMITTED, res.state
            sealed = load_segment(res.segment_dir)
            _, sstats = dev.execute(q, [sealed])
            if sstats.group_by_rung != "startree_device" \
                    and not os.environ.get("BENCH_ALLOW_MUTABLE_HOST"):
                raise AssertionError(
                    f"realtime: sealed segment served from "
                    f"{sstats.group_by_rung!r}, not startree_device — the "
                    f"seal-time default star-tree stamp regressed")
            seal_ms = mgr.seal_wall_ms
        finally:
            MemoryStream.delete("bench_rt")

        return {
            "rows": n_rows,
            "write_qps": round(write_qps, 1),
            "freshness_p50_ms": fresh["p50"],
            "freshness_p99_ms": fresh["p99"],
            "freshness_rows": fresh["count"],
            "query_p50_ms": round(float(np.percentile(query_ms, 50)), 3),
            "consuming_rung": sorted(set(rungs)),
            "seal_rows": seal_rows,
            "seal_ms": round(seal_ms, 1),
            "sealed_rung": sstats.group_by_rung,
        }

    def bench_userfacing(self) -> dict:
        """User-facing analytics: Zipf point-filter group-bys over the wide
        user-event table at 1/2/4/8 closed-loop client threads (ref:
        Pinot's user-facing serving story — BitmapInvertedIndexReader /
        RangeIndexReader-served point lookups at strict latency SLOs).
        Every query in the mix is <1%-selective, so the PR-18 index rung
        must serve ALL of them; the suite records p50/p95/p99/QPS per
        level plus the per-query docs-scanned footprint and the rung
        histogram from the decision ledger. LOUD-FAIL (escapes noted):

        - a selective filter that leaves the index rung for a scan
          (``BENCH_ALLOW_SCAN_SELECTIVE=1`` records anyway) — the SLO
          story collapses if tail-user lookups pay full-scan latency;
        - any index decline reason in the ledger that is NOT in
          ``tracing.registered_reason_codes()`` — an unregistered decline
          is an unexplained fallback, and the BENCH JSON must explain
          every one."""
        import concurrent.futures

        from pinot_tpu.common import tracing
        from pinot_tpu.query import compile_query
        from pinot_tpu.tools import usertable

        rows = min(self.rows, 2_000_000)
        n_segs = 4
        seg_dir = os.path.join(self.data_dir, "user_segs")
        if not os.path.isdir(os.path.join(seg_dir, f"user_{n_segs - 1}")):
            _log(f"userfacing: building user table ({rows} rows)")
            segs = usertable.build_segments(seg_dir, num_segments=n_segs,
                                            rows=rows)
        else:
            from pinot_tpu.segment import load_segment
            segs = [load_segment(os.path.join(seg_dir, f"user_{i}"))
                    for i in range(n_segs)]
        users = usertable.tail_users(rows, num_segments=n_segs)
        assert users, "userfacing: no tail users sampled"
        ctxs = [compile_query(q) for q in usertable.point_queries(users)]

        # verification pass: every query is selective by construction, so
        # every one must ride the index rung on every segment — and every
        # decline the ledger recorded anywhere in the run must be a
        # registered reason code
        allow_scan = os.environ.get("BENCH_ALLOW_SCAN_SELECTIVE")
        docs_scanned = []
        scan_leaks = []
        for ctx in ctxs:
            _, st = self.dev.execute(ctx, segs)   # doubles as compile/warm
            docs_scanned.append(st.num_docs_scanned)
            served = sum(v for k, v in st.decisions.items()
                         if k.endswith(":index_served"))
            if served < len(segs):
                scan_leaks.append((ctx.sql, dict(st.decisions)))
        if scan_leaks and not allow_scan:
            raise AssertionError(
                f"userfacing: {len(scan_leaks)} selective (<1%) point "
                f"filter(s) left the index rung for a scan — first: "
                f"{scan_leaks[0]}; set BENCH_ALLOW_SCAN_SELECTIVE=1 to "
                f"record anyway")

        seconds = 4.0
        levels = {}
        lock = threading.Lock()

        def run_level(threads: int) -> dict:
            lat: list = []
            stop_at = time.perf_counter() + seconds

            def pump(i: int) -> int:
                done = 0
                while time.perf_counter() < stop_at:
                    ctx = ctxs[(i + done) % len(ctxs)]
                    t0 = time.perf_counter()
                    self.dev.execute(ctx, segs)
                    dt = (time.perf_counter() - t0) * 1e3
                    with lock:
                        lat.append(dt)
                    done += 1
                return done

            t0 = time.perf_counter()
            with concurrent.futures.ThreadPoolExecutor(threads) as pool:
                total = sum(pool.map(pump, range(threads)))
            wall = time.perf_counter() - t0
            arr = np.asarray(lat) if lat else np.asarray([0.0])
            return {
                "qps": round(total / wall, 2),
                "p50_ms": round(float(np.percentile(arr, 50)), 3),
                "p95_ms": round(float(np.percentile(arr, 95)), 3),
                "p99_ms": round(float(np.percentile(arr, 99)), 3),
                "queries": total,
            }

        dmark = self._decision_mark()
        for threads in (1, 2, 4, 8):
            _log(f"userfacing: sweeping {threads} thread(s)")
            levels[str(threads)] = run_level(threads)
        decisions = self._decision_delta(dmark)

        # rung histogram: where did the sweep's queries actually serve
        rungs = {}
        registered = tracing.registered_reason_codes()
        unregistered = []
        for key, count in decisions.items():
            point, chosen, _declined, reason = \
                tracing.parse_decision_key(key)
            if point != "index":
                continue
            rungs[chosen] = rungs.get(chosen, 0) + count
            if reason not in registered:
                unregistered.append(key)
        if unregistered:
            raise AssertionError(
                f"userfacing: unregistered index decline reason(s) in the "
                f"ledger: {unregistered} — register them in "
                f"tracing.INDEX_DECISION_REASONS or fix the recording site")

        four = levels["4"]
        return {
            "rows": rows,
            "num_queries": len(ctxs),
            "threads": 4,
            "qps": four["qps"],
            "p50_ms": four["p50_ms"],
            "p95_ms": four["p95_ms"],
            "p99_ms": four["p99_ms"],
            "qps_by_threads": levels,
            "docs_scanned_p50": int(np.percentile(docs_scanned, 50)),
            "docs_scanned_max": int(max(docs_scanned)),
            "selectivity_p50": round(
                float(np.percentile(docs_scanned, 50)) / max(rows, 1), 6),
            "rung_histogram": rungs,
            "scan_leaks": len(scan_leaks),
        }


# ==========================================================================
# micro/star-tree fixtures (configs #1-#4; unchanged from round 4)
# ==========================================================================

MICRO_SEGMENTS = 8
MICRO_DOCS = 131_072

MICRO_QUERIES = [
    "SELECT count(*), sum(qty) FROM sales WHERE region = 'east'",
    "SELECT sum(price) FROM sales WHERE year BETWEEN 2017 AND 2021 AND kind != 'c'",
    "SELECT region, sum(qty), count(*) FROM sales GROUP BY region ORDER BY region",
    "SELECT region, kind, sum(price), avg(price), min(qty), max(qty) FROM sales "
    "GROUP BY region, kind ORDER BY region, kind",
    "SELECT year, min(price), max(price) FROM sales WHERE kind = 'a' "
    "GROUP BY year ORDER BY year",
    "SELECT distinctcount(region) FROM sales WHERE qty > 25",
    "SELECT sum(qty * price) FROM sales WHERE region IN ('west', 'south')",
]

STARTREE_QUERY = ("SELECT region, kind, sum(qty), count(*) FROM sales_st "
                  "GROUP BY region, kind ORDER BY region, kind")
SKETCH_QUERIES = [
    "SELECT distinctcounthll(user_id) FROM sales_st WHERE qty > 10",
    "SELECT percentiletdigest95(price) FROM sales_st",
]


def _micro_frame(n: int, seed: int, with_user: bool = False):
    rng = np.random.default_rng(seed)
    regions = np.array(["east", "west", "north", "south"])
    kinds = np.array(["a", "b", "c"])
    frame = {
        "region": regions[rng.integers(0, 4, n)],
        "kind": kinds[rng.integers(0, 3, n)],
        "year": rng.integers(2015, 2024, n).astype(np.int64),
        "qty": rng.integers(1, 50, n).astype(np.int64),
        "price": np.round(rng.normal(100.0, 25.0, n), 2),
    }
    if with_user:
        frame["user_id"] = rng.integers(0, 200_000, n).astype(np.int64)
    return frame


def _micro_schema(with_user: bool = False):
    from pinot_tpu.spi import DataType, FieldSpec, FieldType, Schema

    specs = [
        FieldSpec("region", DataType.STRING),
        FieldSpec("kind", DataType.STRING),
        FieldSpec("year", DataType.INT),
        FieldSpec("qty", DataType.LONG, FieldType.METRIC),
        FieldSpec("price", DataType.DOUBLE, FieldType.METRIC),
    ]
    if with_user:
        specs.insert(3, FieldSpec("user_id", DataType.LONG))
    name = "sales_st" if with_user else "sales"
    return Schema(name, specs)


def _build_micro(tmpdir: str):
    from pinot_tpu.segment import SegmentBuilder, load_segment

    schema = _micro_schema()
    segs = []
    for i in range(MICRO_SEGMENTS):
        b = SegmentBuilder(schema, f"sales_{i}")
        b.build(_micro_frame(MICRO_DOCS, seed=100 + i), tmpdir)
        segs.append(load_segment(f"{tmpdir}/sales_{i}"))
    return segs


def _tree_build_at_scale(rows: int) -> dict:
    """Build the DEFAULT SSB tree set (all 5 trees) with the lexsort
    engine over ``rows`` rows in ONE shot — dictIds factorized the same
    way the segment creator does — and record per-tree build wall seconds
    + record counts. The 24M-row number the ROADMAP asks for: build cost
    must be measured where it scales, not inferred from 120k-row tests.
    Each tree gets fresh metric dicts so derived-pair evaluation is
    counted inside its own build time."""
    from pinot_tpu.segment.creator import _sorted_factorize
    from pinot_tpu.segment.startree import StarTreeConfig
    from pinot_tpu.segment.startree import StarTreeBuilder
    from pinot_tpu.tools import ssb

    _log(f"startree: generating {rows} rows for the at-scale tree build")
    t0 = time.perf_counter()
    cols = ssb.generate_table(NUM_SEGMENTS, rows)
    gen_s = time.perf_counter() - t0
    configs = [StarTreeConfig.from_spi(c) for c in
               ssb.ssb_indexing_config().star_tree_index_configs]
    dims_needed = sorted({d for c in configs
                          for d in c.dimensions_split_order})
    t0 = time.perf_counter()
    dict_ids = {d: _sorted_factorize(np.asarray(cols[d]))[1].astype(np.int32)
                for d in dims_needed}
    fact_s = time.perf_counter() - t0
    metric_cols = ("lo_revenue", "lo_supplycost", "lo_extendedprice",
                   "lo_discount")
    metrics = {m: np.asarray(cols[m]) for m in metric_cols}
    del cols  # the string columns are ~GBs at 24M rows; trees never read them
    per_tree = {}
    for i, cfg in enumerate(configs):
        t0 = time.perf_counter()
        tree = StarTreeBuilder(cfg).build(dict(dict_ids), dict(metrics),
                                          rows)
        per_tree[f"tree{i}"] = {
            "build_s": round(time.perf_counter() - t0, 2),
            "records": tree.num_records,
            "dims": len(cfg.dimensions_split_order)}
        _log(f"startree: tree{i} {per_tree[f'tree{i}']}")
        del tree
    return {"rows": rows, "engine": "lexsort",
            "generate_s": round(gen_s, 2), "factorize_s": round(fact_s, 2),
            "per_tree": per_tree}


def _ssb_rung(qstats) -> str:
    """The rung that served one SSB flight. Group-bys carry it directly;
    scalar flights (Q1.x) derive it from the ledger's chosen-tree record
    (startree:scan-><rung>:tree<i>) — a scalar query has no
    group_by_rung but absolutely has a rung."""
    if qstats.group_by_rung:
        return qstats.group_by_rung
    for k in qstats.decisions:
        if k.startswith("startree:scan->startree_device:"):
            return "startree_device"
    for k in qstats.decisions:
        if k.startswith("startree:scan->startree:"):
            return "startree"
    return "scalar"


def _tree_build_times(segs) -> dict:
    """Per-tree build wall seconds summed across segments (the creator
    stamps them into segment metadata at build time)."""
    out: dict = {}
    for s in segs:
        for i, b in enumerate(getattr(s.metadata, "star_tree_build_s", [])):
            out[f"tree{i}"] = round(out.get(f"tree{i}", 0.0) + float(b), 3)
    return out


def _build_startree(tmpdir: str):
    """sales_st: star-tree on (region, kind) + a high-card user_id column
    for the sketch queries (BASELINE configs #3/#4)."""
    from pinot_tpu.segment import SegmentBuilder, load_segment
    from pinot_tpu.spi.table import IndexingConfig, StarTreeIndexConfig

    cfg = IndexingConfig(star_tree_index_configs=[StarTreeIndexConfig(
        dimensions_split_order=["region", "kind"],
        function_column_pairs=["SUM__qty", "SUM__price", "COUNT__*"],
        max_leaf_records=1000)])
    schema = _micro_schema(with_user=True)
    segs = []
    for i in range(4):
        b = SegmentBuilder(schema, f"sales_st_{i}", indexing_config=cfg)
        b.build(_micro_frame(MICRO_DOCS, seed=300 + i, with_user=True),
                tmpdir)
        segs.append(load_segment(f"{tmpdir}/sales_st_{i}"))
    return segs


def _assert_parity(name, dev_rows, host_rows):
    assert len(dev_rows) == len(host_rows), \
        f"{name}: {len(dev_rows)} vs {len(host_rows)} rows"
    for dr, hr in zip(dev_rows, host_rows):
        for d, h in zip(dr, hr):
            if isinstance(h, float):
                assert abs(d - h) <= 1e-4 * max(1.0, abs(h)), (name, d, h)
            else:
                assert d == h, (name, d, h)


def _time_suite(run, ctxs, iters=ITERS, warmup=WARMUP):
    """(p50, p99) seconds over full-suite passes."""
    for _ in range(warmup):
        for ctx in ctxs:
            run(ctx)
    samples = []
    for _ in range(iters):
        t0 = time.perf_counter()
        for ctx in ctxs:
            run(ctx)
        samples.append(time.perf_counter() - t0)
    return (float(np.percentile(samples, 50)),
            float(np.percentile(samples, 99)))


# ==========================================================================

def main() -> None:
    if "--worker" in sys.argv:
        _Worker().run()
        return
    if "--probe" in sys.argv:
        probe_main()
        return
    try:
        probe_and_run()
    except Exception as exc:  # never leave the round without a JSON line
        traceback.print_exc(file=sys.stderr)
        print(json.dumps({
            "metric": "ssb_suite_p50_latency",
            "value": None,
            "unit": "ms/query",
            "vs_baseline": None,
            "error": f"{type(exc).__name__}: {exc}"[:500],
        }))
        sys.exit(0)


if __name__ == "__main__":
    main()
