"""Benchmark: fused TPU query kernels vs the host (CPU/numpy) execution path.

Workload: BASELINE.json configs #1/#2/#5 reduced to the current feature set —
filtered aggregations + dictionary group-bys over a multi-segment table, run
through the sharded device combine (parallel/executor.py) and through the
pure-host engine (engine/host_engine.py), same result tables asserted equal.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} where value
is the device p50 latency over the query suite and vs_baseline is the
host-path / device-path speedup (>1 means the TPU path is faster).
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time
import traceback

import numpy as np

NUM_SEGMENTS = 8
DOCS_PER_SEGMENT = 131_072
WARMUP = 2
ITERS = 7

QUERIES = [
    # config #1: filtered SUM/COUNT aggregation
    "SELECT count(*), sum(qty) FROM sales WHERE region = 'east'",
    "SELECT sum(price) FROM sales WHERE year BETWEEN 2017 AND 2021 AND kind != 'c'",
    # config #2: GROUP BY SUM/MIN/MAX/AVG on dictionary columns
    "SELECT region, sum(qty), count(*) FROM sales GROUP BY region ORDER BY region",
    "SELECT region, kind, sum(price), avg(price), min(qty), max(qty) FROM sales "
    "GROUP BY region, kind ORDER BY region, kind",
    "SELECT year, min(price), max(price) FROM sales WHERE kind = 'a' "
    "GROUP BY year ORDER BY year",
    # distinct-count + expression aggregation
    "SELECT distinctcount(region) FROM sales WHERE qty > 25",
    "SELECT sum(qty * price) FROM sales WHERE region IN ('west', 'south')",
]


def _frame(n: int, seed: int):
    rng = np.random.default_rng(seed)
    regions = ["east", "west", "north", "south"]
    kinds = ["a", "b", "c"]
    return {
        "region": [regions[i] for i in rng.integers(0, 4, n)],
        "kind": [kinds[i] for i in rng.integers(0, 3, n)],
        "year": [int(v) for v in rng.integers(2015, 2024, n)],
        "qty": [int(v) for v in rng.integers(1, 50, n)],
        "price": [float(v) for v in np.round(rng.normal(100.0, 25.0, n), 2)],
    }


def _build_segments(tmpdir: str):
    from pinot_tpu.segment import SegmentBuilder, load_segment
    from pinot_tpu.spi import DataType, FieldSpec, FieldType, Schema

    schema = Schema("sales", [
        FieldSpec("region", DataType.STRING),
        FieldSpec("kind", DataType.STRING),
        FieldSpec("year", DataType.INT),
        FieldSpec("qty", DataType.LONG, FieldType.METRIC),
        FieldSpec("price", DataType.DOUBLE, FieldType.METRIC),
    ])
    segs = []
    for i in range(NUM_SEGMENTS):
        b = SegmentBuilder(schema, f"sales_{i}")
        b.build(_frame(DOCS_PER_SEGMENT, seed=100 + i), tmpdir)
        segs.append(load_segment(f"{tmpdir}/sales_{i}"))
    return segs


def _time_suite(run, ctxs) -> float:
    """p50 over ITERS full-suite passes, seconds."""
    for _ in range(WARMUP):
        for ctx in ctxs:
            run(ctx)
    samples = []
    for _ in range(ITERS):
        t0 = time.perf_counter()
        for ctx in ctxs:
            run(ctx)
        samples.append(time.perf_counter() - t0)
    return float(np.percentile(samples, 50))


def _init_backend() -> str:
    """Initialize a jax backend, surviving TPU-tunnel failures.

    Round-1 postmortem: the bench's single shot at real hardware died in
    ``jax.devices()`` and captured nothing — and backend init can either
    raise (UNAVAILABLE) or hang outright, so the probe must run in a
    subprocess with a hard timeout. If the preferred backend fails twice,
    fall back to the host platform so a number is always produced (the
    output records which backend ran).
    """
    import subprocess

    for attempt in range(2):
        try:
            probe = subprocess.run(
                [sys.executable, "-c",
                 "import jax; jax.devices(); print(jax.default_backend())"],
                capture_output=True, text=True, timeout=150)
            if probe.returncode == 0:
                break
            print(f"bench: backend probe {attempt + 1} failed:\n"
                  f"{probe.stderr[-500:]}", file=sys.stderr)
        except subprocess.TimeoutExpired:
            print(f"bench: backend probe {attempt + 1} timed out",
                  file=sys.stderr)
        time.sleep(5.0)
    else:
        print("bench: falling back to CPU host platform", file=sys.stderr)
        os.environ["JAX_PLATFORMS"] = "cpu"

        import jax

        jax.config.update("jax_platforms", "cpu")

    import jax

    jax.devices()
    return jax.default_backend()


def main() -> None:
    backend = _init_backend()

    from pinot_tpu.engine import ServerQueryExecutor
    from pinot_tpu.parallel import ShardedQueryExecutor
    from pinot_tpu.query import compile_query

    tmpdir = tempfile.mkdtemp(prefix="bench_segs_")
    segs = _build_segments(tmpdir)
    ctxs = [compile_query(q) for q in QUERIES]

    device_ex = ShardedQueryExecutor()
    host_ex = ServerQueryExecutor(use_device=False)

    # parity gate: device suite must match host suite before timing
    for ctx in ctxs:
        dev, _ = device_ex.execute(ctx, segs)
        host, _ = host_ex.execute(ctx, segs)
        assert len(dev.rows) == len(host.rows), ctx.sql
        for dr, hr in zip(dev.rows, host.rows):
            for d, h in zip(dr, hr):
                if isinstance(h, float):
                    # device float aggregation is f32 (v5e-shaped); host is f64
                    assert abs(d - h) <= 1e-4 * max(1.0, abs(h)), (ctx.sql, d, h)
                else:
                    assert d == h, (ctx.sql, d, h)

    dev_s = _time_suite(lambda c: device_ex.execute(c, segs), ctxs)
    host_s = _time_suite(lambda c: host_ex.execute(c, segs), ctxs)

    per_query_ms = dev_s / len(QUERIES) * 1e3
    print(json.dumps({
        "metric": "multi_segment_query_suite_p50_latency",
        "value": round(per_query_ms, 3),
        "unit": "ms/query",
        "vs_baseline": round(host_s / dev_s, 3),
        "backend": backend,
    }))


if __name__ == "__main__":
    try:
        main()
    except Exception as exc:  # never leave the round without a JSON line
        traceback.print_exc(file=sys.stderr)
        print(json.dumps({
            "metric": "multi_segment_query_suite_p50_latency",
            "value": None,
            "unit": "ms/query",
            "vs_baseline": None,
            "error": f"{type(exc).__name__}: {exc}"[:500],
        }))
        sys.exit(0)
