"""Kafka wire-protocol plugin: binary fetch API over real TCP.

Ref: pinot-kafka-2.0 KafkaPartitionLevelConsumer / KafkaStreamMetadataProvider
/ KafkaConsumerFactory — here the consumer speaks the broker wire protocol
itself (ApiVersions/Metadata/ListOffsets/Fetch, magic-v2 record batches with
crc32c), exercised against a wire-faithful in-test broker.
"""

import numpy as np
import pandas as pd
import pytest

from pinot_tpu.ingestion.kafkawire import (
    KafkaBrokerSim,
    KafkaWireClient,
    decode_record_batches,
    encode_record_batch,
)
from pinot_tpu.ingestion.stream import StreamOffset, create_consumer_factory
from pinot_tpu.spi import DataType, FieldSpec, FieldType, Schema
from pinot_tpu.spi.table import (
    SegmentsValidationConfig,
    StreamIngestionConfig,
    TableConfig,
    TableType,
)
from pinot_tpu.tools.cluster import EmbeddedCluster


@pytest.fixture()
def broker():
    b = KafkaBrokerSim(port=0).start()
    yield b
    b.stop()


def _cfg(broker, topic, flush_rows=10_000):
    return StreamIngestionConfig(
        stream_type="kafka", topic=topic,
        segment_flush_threshold_rows=flush_rows,
        properties={"stream.kafka.broker.list":
                    f"{broker.host}:{broker.port}"})


class TestRecordBatchCodec:
    def test_roundtrip(self):
        recs = [(None, b'{"a":1}', 1000), (b"k", b'{"a":2}', 1005)]
        raw = encode_record_batch(37, recs)
        got = decode_record_batches(raw)
        assert got == [(37, None, b'{"a":1}', 1000),
                       (38, b"k", b'{"a":2}', 1005)]

    def test_crc_is_verified(self):
        raw = bytearray(encode_record_batch(0, [(None, b"v", 1)]))
        raw[-1] ^= 0xFF  # corrupt the payload
        with pytest.raises(ValueError, match="crc32c"):
            decode_record_batches(bytes(raw))


class TestWireApis:
    def test_handshake_metadata_offsets_fetch(self, broker):
        broker.create_topic("t", num_partitions=3)
        broker.produce("t", [{"i": i} for i in range(5)], partition=1)
        c = KafkaWireClient(broker.host, broker.port)
        versions = c.api_versions()
        assert 1 in versions and versions[1][1] >= 4
        assert c.partition_count("t") == 3
        assert c.list_offset("t", 1, -2) == 0   # earliest
        assert c.list_offset("t", 1, -1) == 5   # latest
        recs = c.fetch("t", 1, 2)
        assert [r[0] for r in recs] == [2, 3, 4]
        assert recs[0][2] == b'{"i": 2}'
        c.close()

    def test_spi_surface(self, broker):
        broker.create_topic("t2", num_partitions=2)
        broker.produce("t2", [{"x": 1}, {"x": 2}], partition=0)
        factory = create_consumer_factory(_cfg(broker, "t2"))
        meta = factory.create_metadata_provider()
        assert meta.partition_count() == 2
        assert meta.latest_offset(0).value == 2
        consumer = factory.create_partition_consumer(0)
        batch = consumer.fetch_messages(StreamOffset(0))
        assert [m.payload for m in batch.messages] == [{"x": 1}, {"x": 2}]
        assert batch.next_offset.value == 2


class TestRealtimeOverKafkaWire:
    def test_cluster_consumes_kafka_protocol(self, broker, tmp_path):
        """Full realtime path over the kafka WIRE: FSM consumption +
        commit + offset checkpoints, partition expansion included."""
        broker.create_topic("ksales", num_partitions=2)
        schema = Schema("ks", [
            FieldSpec("region", DataType.STRING),
            FieldSpec("qty", DataType.LONG, FieldType.METRIC),
            FieldSpec("ts", DataType.LONG, FieldType.DATE_TIME),
        ])
        cluster = EmbeddedCluster(num_servers=2,
                                  data_dir=str(tmp_path / "k"))
        cfg = TableConfig(
            "ks", TableType.REALTIME,
            validation_config=SegmentsValidationConfig(
                time_column_name="ts"),
            stream_config=_cfg(broker, "ksales", flush_rows=250))
        try:
            cluster.create_table(cfg, schema)
            rng = np.random.default_rng(9)
            df = pd.DataFrame({
                "region": np.array(["e", "w", "n"])[rng.integers(0, 3, 700)],
                "qty": rng.integers(1, 9, 700).astype(np.int64),
                "ts": np.arange(700).astype(np.int64),
            })
            recs = df.to_dict("records")
            for p in (0, 1):
                broker.produce("ksales", recs[p::2], partition=p)
            assert cluster.wait_for_docs("ks", 700), \
                cluster.query("SELECT count(*) FROM ks").to_dict()
            rows = cluster.query_rows(
                "SELECT region, sum(qty) FROM ks GROUP BY region "
                "ORDER BY region")
            want = df.groupby("region").qty.sum().sort_index()
            assert [(r[0], r[1]) for r in rows] == \
                [(k, float(v)) for k, v in want.items()]

            # sealed segments checkpoint kafka offsets
            sealed = [m for m in
                      cluster.store.segment_metadata_list("ks_REALTIME")
                      if m.status == "ONLINE"]
            assert sealed and all(m.end_offset is not None for m in sealed)

            # partition expansion over the wire protocol
            broker.create_topic("ksales", num_partitions=3)
            broker.produce("ksales", [{"region": "z", "qty": 5, "ts": 900}],
                           partition=2)
            fresh = cluster.controller.run_realtime_validation()
            assert any("__2__" in s for s in fresh), fresh
            assert cluster.wait_for_docs("ks", 701)
        finally:
            cluster.shutdown()
