"""Priority scheduler, plugin loader, ConvertToRaw + SegmentGenerationAndPush.

Ref: scheduler/priority/MultiLevelPriorityQueue.java, PluginManager.java:40,
ConvertToRawIndexTaskExecutor.java, SegmentGenerationAndPushTaskExecutor.java.
"""

import os
import threading
import time

import numpy as np
import pandas as pd
import pytest

from pinot_tpu.server.scheduler import (
    PriorityScheduler,
    SewfScheduler,
    make_scheduler,
)
from pinot_tpu.spi import DataType, FieldSpec, FieldType, Schema
from pinot_tpu.spi.plugin import PluginManager
from pinot_tpu.spi.table import TableConfig
from pinot_tpu.tools.cluster import EmbeddedCluster


class TestPriorityScheduler:
    def test_factory(self):
        s = make_scheduler("priority", num_workers=2)
        assert isinstance(s, PriorityScheduler)
        s.shutdown(timeout_s=2)

    def test_runs_and_drains(self):
        s = PriorityScheduler(num_workers=4)
        futs = [s.submit(lambda i=i: i * 2, table=f"t{i % 3}")
                for i in range(30)]
        assert sorted(f.result(timeout=10) for f in futs) == \
            sorted(i * 2 for i in range(30))
        s.shutdown(timeout_s=5)

    def test_fairness_under_flood(self):
        """A flood from one table cannot starve another: with one worker,
        the starved table's single query completes long before the flood
        drains (weighted-cost pick alternates tables)."""
        s = PriorityScheduler(num_workers=1)
        order = []
        lock = threading.Lock()

        def job(tag):
            with lock:
                order.append(tag)
            time.sleep(0.002)
            return tag

        flood = [s.submit(lambda i=i: job(("flood", i)), table="hot")
                 for i in range(40)]
        late = s.submit(lambda: job(("late", 0)), table="cold")
        late.result(timeout=10)
        done_floods = sum(1 for tag in order if tag[0] == "flood")
        assert done_floods < 40  # cold table jumped the hot queue
        for f in flood:
            f.result(timeout=10)
        s.shutdown(timeout_s=5)

    def test_priority_weights_prefer_high(self):
        s = PriorityScheduler(num_workers=1,
                              table_priorities={"vip": 100.0, "low": 1.0})
        order = []
        lock = threading.Lock()

        def job(tag):
            with lock:
                order.append(tag)
            time.sleep(0.001)

        # enqueue low first, then vip; vip should overtake under contention
        lows = [s.submit(lambda i=i: job(("low", i)), table="low")
                for i in range(20)]
        vips = [s.submit(lambda i=i: job(("vip", i)), table="vip")
                for i in range(20)]
        for f in lows + vips:
            f.result(timeout=10)
        first_20 = [t for t, _ in order[:20]]
        assert first_20.count("vip") > 10  # vip dominated the early slots
        s.shutdown(timeout_s=5)


class TestSewfScheduler:
    """Shortest-expected-work-first + the age anti-starvation boost."""

    def test_factory_and_snapshot(self):
        s = make_scheduler("sewf", num_workers=2)
        assert isinstance(s, SewfScheduler)
        snap = s.stats_snapshot()
        assert snap["policy"] == "SewfScheduler"
        assert snap["workers"] == 2 and snap["queued"] == 0
        s.shutdown(timeout_s=2)

    def _seed(self, s, shape, ms, n=3):
        """Establish a latency EWMA for ``shape`` by running real jobs."""
        for _ in range(n):
            s.submit(lambda: time.sleep(ms / 1e3), shape=shape).result(10)

    def test_short_shapes_overtake_long_under_contention(self):
        s = SewfScheduler(num_workers=1)
        self._seed(s, "slow", 30.0)
        self._seed(s, "fast", 1.0)
        assert s.expected_ms("slow") > s.expected_ms("fast")
        order = []
        lock = threading.Lock()

        def job(tag):
            with lock:
                order.append(tag)

        gate = threading.Event()
        blocker = s.submit(lambda: gate.wait(10), shape="blocker")
        # enqueue while the single worker is parked: two slow, then a fast
        futs = [s.submit(lambda: job("slow1"), shape="slow"),
                s.submit(lambda: job("slow2"), shape="slow"),
                s.submit(lambda: job("fast1"), shape="fast")]
        gate.set()
        for f in futs:
            f.result(10)
        blocker.result(10)
        assert order[0] == "fast1", \
            f"the cheap shape must jump the slow convoy (got {order})"
        s.shutdown(timeout_s=5)

    def test_age_boost_prevents_starvation(self):
        s = SewfScheduler(num_workers=1, aging_boost=2.0)
        self._seed(s, "slow", 30.0)
        self._seed(s, "fast", 1.0)
        order = []
        lock = threading.Lock()

        def job(tag):
            with lock:
                order.append(tag)

        gate = threading.Event()
        blocker = s.submit(lambda: gate.wait(10), shape="blocker")
        slow = s.submit(lambda: job("slow"), shape="slow")
        # let the slow entry AGE past its expected-work handicap
        # (30 ms EWMA / 2.0 boost = 15 ms of age cancels it out)
        time.sleep(0.05)
        fast = s.submit(lambda: job("fast"), shape="fast")
        gate.set()
        slow.result(10)
        fast.result(10)
        blocker.result(10)
        assert order[0] == "slow", \
            f"an aged expensive query must not starve (got {order})"
        s.shutdown(timeout_s=5)

    def test_runs_drains_and_propagates_errors(self):
        s = SewfScheduler(num_workers=4)
        futs = [s.submit(lambda i=i: i * 3, shape=f"s{i % 5}")
                for i in range(40)]
        assert sorted(f.result(10) for f in futs) == \
            sorted(i * 3 for i in range(40))

        def boom():
            raise ValueError("x")

        with pytest.raises(ValueError):
            s.submit(boom, shape="err").result(10)
        s.shutdown(timeout_s=5)
        with pytest.raises(RuntimeError):
            s.submit(lambda: 1)


class TestPluginLoader:
    def test_loads_and_registers(self, tmp_path):
        plugin = tmp_path / "my_stream.py"
        plugin.write_text(
            "from pinot_tpu.ingestion.stream import (\n"
            "    StreamConsumerFactory, register_stream_type)\n"
            "class MyFactory(StreamConsumerFactory):\n"
            "    pass\n"
            "register_stream_type('mytest', MyFactory)\n")
        (tmp_path / "_ignored.py").write_text("raise AssertionError\n")
        (tmp_path / "broken.py").write_text("import nonexistent_module\n")
        pm = PluginManager(str(tmp_path))
        loaded = pm.load_all()
        assert loaded == ["my_stream"]  # broken skipped, _ignored skipped
        from pinot_tpu.ingestion.stream import _FACTORIES

        assert "mytest" in _FACTORIES

    def test_env_dir(self, tmp_path, monkeypatch):
        monkeypatch.setenv("PINOT_PLUGINS_DIR", str(tmp_path))
        assert PluginManager().plugins_dir == str(tmp_path)

    def test_missing_dir_is_noop(self):
        assert PluginManager("/nonexistent/dir").load_all() == []


def _schema():
    return Schema("mnt", [
        FieldSpec("k", DataType.STRING),
        FieldSpec("v", DataType.LONG, FieldType.METRIC),
    ])


class TestConvertToRawTask:
    def test_convert_and_refresh(self, tmp_path):
        cluster = EmbeddedCluster(num_servers=1,
                                  data_dir=str(tmp_path / "c"))
        cfg = TableConfig("mnt", task_config={
            "ConvertToRawIndexTask": {"columnsToConvert": "v"}})
        try:
            cluster.create_table(cfg, _schema())
            cluster.ingest_rows("mnt_OFFLINE", _schema(), {
                "k": np.array(["a", "b"] * 200),
                "v": np.arange(400).astype(np.int64)}, segment_name="mnt_0")
            assert cluster.wait_for_ev_converged("mnt_OFFLINE")
            minion = cluster.add_minion(start=False)
            created = cluster.controller.task_manager.generate_tasks()
            assert len(created) == 1
            minion.run_one_task()
            md = cluster.store.get_segment_metadata("mnt_OFFLINE", "mnt_0")
            assert md.custom.get("convertToRawDone") == "v"
            # converted segment is RAW on v and still answers correctly
            from pinot_tpu.segment import load_segment

            seg = load_segment(md.download_url[len("file://"):])
            assert not seg.metadata.column("v").has_dictionary
            assert cluster.wait_for_ev_converged("mnt_OFFLINE")
            rows = cluster.query_rows("SELECT sum(v) FROM mnt")
            assert rows[0][0] == float(sum(range(400)))
            # generator stops regenerating
            assert cluster.controller.task_manager.generate_tasks() == []
        finally:
            cluster.shutdown()


class TestSegmentGenerationAndPushTask:
    def test_ingests_new_files(self, tmp_path):
        input_dir = tmp_path / "landing"
        input_dir.mkdir()
        pd.DataFrame({"k": ["a", "b", "a"], "v": [1, 2, 3]}).to_csv(
            input_dir / "d1.csv", index=False)
        cluster = EmbeddedCluster(num_servers=1,
                                  data_dir=str(tmp_path / "c"))
        cfg = TableConfig("mnt", task_config={
            "SegmentGenerationAndPushTask": {
                "inputDirURI": str(input_dir), "inputFormat": "csv"}})
        try:
            cluster.create_table(cfg, _schema())
            minion = cluster.add_minion(start=False)
            assert len(cluster.controller.task_manager.generate_tasks()) == 1
            minion.run_one_task()
            assert cluster.wait_for_ev_converged("mnt_OFFLINE")
            assert cluster.query_rows(
                "SELECT count(*), sum(v) FROM mnt")[0] == [3, 6.0]
            # nothing new -> no task; a new file -> another task
            assert cluster.controller.task_manager.generate_tasks() == []
            time.sleep(0.01)
            pd.DataFrame({"k": ["c"], "v": [10]}).to_csv(
                input_dir / "d2.csv", index=False)
            os.utime(input_dir / "d2.csv")
            assert len(cluster.controller.task_manager.generate_tasks()) == 1
            minion.run_one_task()
            assert cluster.wait_for_docs("mnt", 4)
        finally:
            cluster.shutdown()
