"""Continuous telemetry: histograms, SLO burn, flight recorder, endpoints.

Four layers:

- **Histogram correctness** (the quantitative foundation): log-bucket
  quantile estimates vs numpy references on known distributions, with the
  error bound the bucket ratio implies; window rotation/expiry on a fake
  clock; merge; and a multi-thread record hammer.
- **SLO burn tracking**: objectives from config RAW keys (table names
  with underscores survive), burn-rate math on both windows.
- **Flight recorder**: burst triggers, deferred freeze, bundle contents
  and persistence, debounce.
- **End-to-end**: the bench_qps-shaped overload run on a live cluster —
  sliding p99 visible on ``/debug/telemetry`` and distinct from the
  lifetime mean, a nonzero SLO burn for the loaded table, and a frozen
  ``rejection_burst`` bundle carrying span roots + decision deltas +
  residency/admission snapshots; plus the ``/debug/*`` endpoint
  inventory over every registered debug route.

``pytest -m telemetry`` runs this module in isolation (tier-1).
"""

import json
import threading
import urllib.request

import numpy as np
import pytest

from pinot_tpu.common.telemetry import (
    BUCKET_BOUNDS_MS,
    FlightRecorder,
    Histogram,
    Telemetry,
    TELEMETRY,
    WindowCounter,
    WindowedHistogram,
)

pytestmark = pytest.mark.telemetry

# the log-bucket growth ratio bounds the relative quantile error
_BUCKET_RATIO = BUCKET_BOUNDS_MS[1] / BUCKET_BOUNDS_MS[0]


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, s):
        self.t += s


# --------------------------------------------------------------------------
# histogram correctness
# --------------------------------------------------------------------------

class TestHistogram:
    @pytest.mark.parametrize("dist,args", [
        ("uniform", (1.0, 500.0)),
        ("lognormal", (3.0, 1.0)),
        ("exponential", (40.0,)),
    ])
    def test_quantile_accuracy_vs_numpy(self, dist, args):
        rng = np.random.default_rng(7)
        vals = getattr(rng, dist)(*args, size=20_000)
        vals = np.clip(vals, 1e-3, None)
        h = Histogram()
        for v in vals:
            h.record(float(v))
        for q in (0.5, 0.9, 0.95, 0.99):
            est = h.quantile(q)
            true = float(np.percentile(vals, q * 100))
            rel = abs(est - true) / true
            # one log bucket of slack (ratio ~1.19) is the design bound
            assert rel <= _BUCKET_RATIO - 1.0 + 0.02, \
                (dist, q, est, true, rel)

    def test_count_sum_max_exact(self):
        h = Histogram()
        vals = [0.5, 1.0, 2.5, 100.0, 100000.0]  # incl. overflow bucket
        for v in vals:
            h.record(v)
        snap = h.snapshot()
        assert snap["count"] == len(vals)
        assert snap["sumMs"] == pytest.approx(sum(vals), rel=1e-9)
        assert snap["maxMs"] == pytest.approx(max(vals))

    def test_overflow_bucket_quantile_is_max(self):
        h = Histogram()
        for v in (200_000.0, 300_000.0):  # beyond the top bound
            h.record(v)
        assert h.quantile(0.99) == pytest.approx(300_000.0)

    def test_count_over_threshold(self):
        h = Histogram()
        vals = np.linspace(1.0, 1000.0, 5000)
        for v in vals:
            h.record(float(v))
        true = int((vals > 250.0).sum())
        est = h.count_over(250.0)
        assert abs(est - true) / true <= 0.2, (est, true)

    def test_merge_equals_combined(self):
        rng = np.random.default_rng(11)
        a_vals = rng.lognormal(2, 1, 3000)
        b_vals = rng.uniform(1, 50, 3000)
        a, b, both = Histogram(), Histogram(), Histogram()
        for v in a_vals:
            a.record(float(v))
            both.record(float(v))
        for v in b_vals:
            b.record(float(v))
            both.record(float(v))
        a.merge(b)
        assert a.counts == both.counts
        assert a.count == both.count
        assert a.sum == pytest.approx(both.sum)
        assert a.quantile(0.95) == pytest.approx(both.quantile(0.95))

    def test_multithread_record_hammer(self):
        """8 threads x 5000 records: no lost updates under the record
        lock, bucket totals consistent with the scalar counters."""
        wh = WindowedHistogram(window_s=3600.0)
        rng = np.random.default_rng(3)
        per_thread = [rng.lognormal(2, 1, 5000) for _ in range(8)]

        def pump(vals):
            for v in vals:
                wh.record(float(v))

        threads = [threading.Thread(target=pump, args=(per_thread[i],))
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        total = 8 * 5000
        assert wh.lifetime.count == total
        assert sum(wh.lifetime.counts) == total
        assert wh.lifetime.sum == pytest.approx(
            sum(float(v) for vals in per_thread for v in vals), rel=1e-6)
        assert wh.sliding().count == total  # nothing rotated out


# --------------------------------------------------------------------------
# window rotation / expiry
# --------------------------------------------------------------------------

class TestWindowRotation:
    def test_sliding_expires_lifetime_keeps(self):
        clock = FakeClock()
        wh = WindowedHistogram(window_s=10.0, num_windows=3, clock=clock)
        for _ in range(50):
            wh.record(100.0)
        assert wh.sliding().count == 50
        clock.advance(15.0)  # one rotation: still inside the horizon
        wh.record(1.0)
        assert wh.sliding().count == 51
        clock.advance(35.0)  # past the whole 30 s horizon
        assert wh.sliding().count == 0
        assert wh.lifetime.count == 51  # lifetime never expires

    def test_partial_rotation_drops_oldest_window_only(self):
        clock = FakeClock()
        wh = WindowedHistogram(window_s=10.0, num_windows=3, clock=clock)
        wh.record(5.0)          # window 0
        clock.advance(10.0)
        wh.record(6.0)          # window 1
        clock.advance(10.0)
        wh.record(7.0)          # window 2
        assert wh.sliding().count == 3
        clock.advance(10.0)     # reuses window 0's slot: first value gone
        wh.record(8.0)
        assert wh.sliding().count == 3

    def test_sliding_differs_from_lifetime_after_shift(self):
        """The acceptance shape: a latency regime change shows in the
        sliding percentiles while the lifetime mean still averages the
        old regime in."""
        clock = FakeClock()
        wh = WindowedHistogram(window_s=10.0, num_windows=3, clock=clock)
        for _ in range(200):
            wh.record(2.0)       # fast regime
        clock.advance(40.0)      # fast regime rotates out entirely
        for _ in range(50):
            wh.record(400.0)     # slow regime
        sliding_p99 = wh.sliding().quantile(0.99)
        lifetime_mean = wh.lifetime.mean
        assert sliding_p99 > 300.0
        assert lifetime_mean < 150.0
        assert abs(sliding_p99 - lifetime_mean) > 100.0

    def test_window_counter(self):
        clock = FakeClock()
        wc = WindowCounter(window_s=10.0, num_windows=4, clock=clock)
        wc.add(5)
        clock.advance(10.0)
        wc.add(3)
        assert wc.in_window() == 8
        assert wc.in_window(1) == 3
        assert wc.total == 8
        clock.advance(45.0)
        assert wc.in_window() == 0
        assert wc.total == 8


# --------------------------------------------------------------------------
# SLO burn
# --------------------------------------------------------------------------

class TestSlo:
    def test_burn_rates_latency_and_error(self):
        clock = FakeClock()
        t = Telemetry(window_s=10.0, num_windows=4, clock=clock)
        t.slo.set_objective("tbl", p99_ms=50.0, error_pct=2.0)
        # 100 requests, 10 over the 50 ms objective (10% bad vs 1%
        # allowed -> burn 10), 4 errors (4% vs 2% -> burn 2)
        for i in range(100):
            t.note_broker_query("tbl", 500.0 if i < 10 else 5.0,
                                error=i < 4)
        snap = t.slo_snapshot()["tables"]["tbl"]
        assert snap["objectives"]["p99_ms"] == 50.0
        assert snap["latency"]["long"]["burnRate"] == pytest.approx(10.0,
                                                                    rel=0.15)
        assert snap["errors"]["long"]["burnRate"] == pytest.approx(2.0,
                                                                   rel=0.05)
        # burn gauges surface the same numbers for /metrics
        burns = t.burn_gauges()
        assert burns[("tbl", "p99", "long")] == \
            snap["latency"]["long"]["burnRate"]

    def test_short_window_reacts_long_window_smooths(self):
        clock = FakeClock()
        t = Telemetry(window_s=10.0, num_windows=6, clock=clock)
        t.slo.set_objective("tbl", p99_ms=50.0)
        for _ in range(300):
            t.note_broker_query("tbl", 1.0, error=False)  # healthy regime
        clock.advance(45.0)  # healthy data ages toward the horizon edge
        for _ in range(30):
            t.note_broker_query("tbl", 500.0, error=False)  # incident
        snap = t.slo_snapshot()["tables"]["tbl"]["latency"]
        assert snap["short"]["burnRate"] > snap["long"]["burnRate"]
        assert snap["short"]["burnRate"] > 50  # ~100% bad vs 1% allowed

    def test_objectives_parse_from_raw_config_keys(self):
        from pinot_tpu.spi.config import PinotConfiguration

        cfg = PinotConfiguration(
            {"pinot.broker.slo.ssb_lineorder_OFFLINE.p99.ms": "250",
             "pinot.broker.slo.ssb_lineorder_OFFLINE.error.pct": "0.5",
             "pinot.broker.slo.other_table.p99.ms": "100"},
            use_env=False)
        t = Telemetry()
        t.configure(cfg)
        obj = t.slo.objectives()
        # underscored table names survive relaxed-key normalization
        assert obj["ssb_lineorder_OFFLINE"] == {"p99_ms": 250.0,
                                                "error_pct": 0.5}
        assert obj["other_table"]["p99_ms"] == 100.0


# --------------------------------------------------------------------------
# prometheus exposition
# --------------------------------------------------------------------------

class TestExposition:
    def test_histogram_family_shape(self):
        from pinot_tpu.spi.metrics import MetricsRegistry

        t = Telemetry()
        for v in (1.0, 5.0, 50.0):
            t.observe("tbl", "broker", v)
        reg = MetricsRegistry(role="server")
        reg.bind_telemetry(t)
        text = reg.export_prometheus()
        fam = "pinot_server_query_phase_latency_ms"
        assert f"# TYPE {fam} histogram" in text
        assert f"# HELP {fam} " in text
        rows = [ln for ln in text.splitlines()
                if ln.startswith(f'{fam}_bucket{{table="tbl"')]
        # cumulative and monotonic, +Inf last and == _count
        counts = [int(ln.rsplit(" ", 1)[1]) for ln in rows]
        assert counts == sorted(counts)
        assert rows[-1].rsplit(" ", 1)[0].endswith('le="+Inf"}')
        assert counts[-1] == 3
        assert f'{fam}_count{{table="tbl",phase="broker"}} 3' in text
        assert f'{fam}_sum{{table="tbl",phase="broker"}} 56.0' in text

    def test_help_type_and_sanitized_names(self):
        from pinot_tpu.spi.metrics import MetricsRegistry

        reg = MetricsRegistry(role="server")
        reg.meter("weird name-with.bad:chars_total").mark(2)
        reg.gauge("g", 1.5)
        reg.timer("T").update_ms(2.0)
        text = reg.export_prometheus()
        # every family carries HELP + TYPE; names are sanitized
        assert "# TYPE pinot_server_weird_name_with_bad:chars_total " \
               "counter" in text
        assert "pinot_server_weird_name_with_bad:chars_total 2" in text
        for needle in ("# HELP pinot_server_g ", "# TYPE pinot_server_g "
                       "gauge", "# TYPE pinot_server_T_ms summary"):
            assert needle in text, text

    def test_slo_burn_gauge_family(self):
        from pinot_tpu.spi.metrics import MetricsRegistry

        t = Telemetry()
        t.slo.set_objective("tbl", p99_ms=1.0)
        for _ in range(10):
            t.note_broker_query("tbl", 100.0, error=False)
        reg = MetricsRegistry(role="broker")
        reg.bind_telemetry(t)
        text = reg.export_prometheus()
        assert "# TYPE pinot_broker_slo_burn_rate gauge" in text
        assert ('pinot_broker_slo_burn_rate{table="tbl",objective="p99",'
                'window="long"}') in text


# --------------------------------------------------------------------------
# flight recorder
# --------------------------------------------------------------------------

class TestFlightRecorder:
    def test_burst_trips_and_freeze_is_deferred(self, tmp_path):
        fr = FlightRecorder(out_dir=str(tmp_path))
        fr.bursts = {"rejection": (5, 5.0)}
        for _ in range(4):
            fr.note_event("rejection")
        assert fr.snapshot()["pendingTriggers"] == []  # under threshold
        fr.note_event("rejection")
        assert fr.snapshot()["pendingTriggers"] == ["rejection_burst"]
        assert fr.snapshot()["bundles"] == []  # note_event never freezes
        bundles = fr.process_pending()
        assert len(bundles) == 1
        assert bundles[0]["trigger"] == "rejection_burst"

    def test_bundle_contents_and_persistence(self, tmp_path):
        fr = FlightRecorder(out_dir=str(tmp_path))
        fr.note_query({"sql": "SELECT 1", "spans": [{"name": "ServerQuery",
                                                     "ms": 5.0}]})
        fr.note_ledger_mark({"pallas:a->b:x": 1}, ts=100.0)
        fr.note_ledger_mark({"pallas:a->b:x": 4}, ts=110.0)
        fr.register_provider("residency", lambda: {"stagedBytes": 123})
        fr.register_provider("broken", lambda: 1 / 0)
        b = fr.freeze("manual")
        assert b["spanRoots"][0]["spans"][0]["name"] == "ServerQuery"
        assert b["decisions"]["delta"] == {"pallas:a->b:x": 3}
        assert b["snapshots"]["residency"] == {"stagedBytes": 123}
        assert "error" in b["snapshots"]["broken"]  # provider crash isolated
        with open(b["path"]) as f:
            on_disk = json.load(f)
        assert on_disk["trigger"] == "manual"
        snap = fr.snapshot()
        assert snap["frozen"] == 1 and snap["last"]["trigger"] == "manual"

    def test_freeze_debounce(self, tmp_path):
        fr = FlightRecorder(out_dir=str(tmp_path),
                            min_freeze_interval_s=3600.0)
        fr.bursts = {"rejection": (1, 5.0)}
        fr.note_event("rejection")
        assert fr.process_pending()
        fr.note_event("rejection")  # inside the debounce interval
        assert fr.snapshot()["pendingTriggers"] == []
        assert not fr.process_pending()

    def test_p99_spike_trigger(self):
        clock = FakeClock()
        t = Telemetry(window_s=10.0, num_windows=3, clock=clock)
        t.recorder.min_freeze_interval_s = 0.0
        for _ in range(100):
            t.observe("tbl", "broker", 2.0)
        t.sample_now()  # seeds the p99 EWMA baseline on the fast regime
        clock.advance(40.0)
        for _ in range(100):
            t.observe("tbl", "broker", 2000.0)  # 1000x spike
        t.sample_now()
        snap = t.recorder.snapshot()
        triggers = [b["trigger"] for b in snap["bundles"]] \
            + snap["pendingTriggers"]
        assert any(tr.startswith("p99_spike:tbl:broker") for tr in triggers), \
            snap


# --------------------------------------------------------------------------
# end-to-end: overload run + endpoint inventory on a live cluster
# --------------------------------------------------------------------------

def _get_json(port, path):
    with urllib.request.urlopen(f"http://localhost:{port}{path}",
                                timeout=10) as r:
        assert r.status == 200, (path, r.status)
        return json.loads(r.read().decode())


@pytest.fixture()
def overload_cluster(tmp_path):
    """A 2-server cluster with a fresh process-wide telemetry center,
    bundles landing under tmp_path."""
    from pinot_tpu.spi import DataType, FieldSpec, FieldType, Schema
    from pinot_tpu.spi.table import TableConfig
    from pinot_tpu.tools.cluster import EmbeddedCluster

    TELEMETRY.reset()
    TELEMETRY.recorder.out_dir = str(tmp_path / "flight")
    c = EmbeddedCluster(num_servers=2, data_dir=str(tmp_path / "c"))
    schema = Schema("tel", [
        FieldSpec("city", DataType.STRING),
        FieldSpec("v", DataType.LONG, FieldType.METRIC)])
    c.create_table(TableConfig("tel"), schema)
    rng = np.random.default_rng(9)
    for i in range(2):
        c.ingest_rows("tel_OFFLINE", schema, {
            "city": np.array(["sf", "nyc", "oak"])[rng.integers(0, 3, 600)],
            "v": rng.integers(0, 50, 600).astype(np.int64)},
            segment_name=f"tel_{i}")
    assert c.wait_for_ev_converged("tel_OFFLINE")
    yield c
    c.shutdown()
    TELEMETRY.reset()


class TestOverloadEndToEnd:
    def test_saturated_cluster_produces_telemetry_slo_and_blackbox(
            self, overload_cluster):
        """The acceptance run: bench_qps's saturation shape against a
        live cluster. Must produce (a) sliding p99 on /debug/telemetry
        distinct from the lifetime mean, (b) nonzero SLO burn for the
        loaded table, (c) >= 1 flight-recorder bundle triggered by
        rejection_burst carrying span roots + decision deltas +
        residency/admission snapshots."""
        from pinot_tpu.transport.rest import BrokerApi, ServerAdminApi

        c = overload_cluster
        c.broker.coalesce = False  # distinct executions, not one flight
        # an unreachable p99 objective: every request burns budget
        TELEMETRY.slo.set_objective("tel", p99_ms=0.01, error_pct=1.0)
        # seed the span ring before overload: the burst can trip within
        # milliseconds, possibly before any overload-phase traced query
        # completes — a frozen bundle must still carry span roots
        c.query("SELECT city, sum(v) FROM tel GROUP BY city "
                "OPTION(trace=true)")
        for server in c.servers.values():
            server.executor.admission.configure(
                max_concurrent=1, max_queue=-1, max_wait_ms=50)
        TELEMETRY.sample_now()  # opening decision-ledger mark

        queries = [f"SELECT city, sum(v) FROM tel WHERE v > {i} "
                   f"GROUP BY city OPTION(trace=true)" for i in range(6)]

        def pump(i):
            for k in range(12):
                c.query(queries[(i + k) % len(queries)])

        threads = [threading.Thread(target=pump, args=(i,))
                   for i in range(12)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        # closing mark + freeze of the rejection burst the overload caused
        TELEMETRY.sample_now()

        apis = [BrokerApi(c.broker, port=0),
                ServerAdminApi(c.servers["server_0"], port=0)]
        for api in apis:
            api.start()
        try:
            broker_port, server_port = apis[0].port, apis[1].port
            # (a) sliding p99 != lifetime mean on /debug/telemetry
            tel = _get_json(server_port, "/debug/telemetry")
            h = tel["histograms"].get("tel:server_exec")
            assert h is not None, sorted(tel["histograms"])
            assert h["sliding"]["count"] > 0
            assert h["sliding"]["p99"] > 0
            assert h["sliding"]["p99"] != h["lifetime"]["meanMs"]
            # (b) nonzero SLO burn for the loaded table
            slo = _get_json(broker_port, "/debug/slo")["tables"]["tel"]
            assert slo["latency"]["long"]["burnRate"] > 0
            # rejections surfaced as exceptions -> error burn too
            assert slo["errors"]["long"]["requests"] > 0
            # (c) a rejection_burst bundle with the full black-box payload
            box = _get_json(server_port, "/debug/flightrecorder")
            triggers = [b["trigger"] for b in box["bundles"]]
            assert "rejection_burst" in triggers, box
            last = box["last"]
            if last["trigger"] != "rejection_burst":
                last = next(b for b in TELEMETRY.recorder.bundles
                            if b["trigger"] == "rejection_burst")
            assert last["spanRoots"], "no span roots in the bundle"
            assert any(e.get("spans") for e in last["spanRoots"])
            assert last["decisions"]["delta"], "no decision delta"
            assert "residency" in last["snapshots"]
            assert "admission" in last["snapshots"]
            assert last["snapshots"]["admission"].get("rejected", 0) > 0
            # the bundle persisted to disk as timestamped JSON
            assert last.get("path") and json.load(open(last["path"]))
        finally:
            for api in apis:
                api.stop()


class TestDebugEndpointInventory:
    @pytest.mark.parametrize("role", ["broker", "server"])
    def test_every_debug_route_serves_json(self, role, overload_cluster):
        """EVERY registered GET /debug/* route answers valid JSON on a
        live two-server cluster — route discovery is from the router
        itself, so a new debug endpoint joins the gate automatically."""
        from pinot_tpu.transport.rest import BrokerApi, ServerAdminApi

        c = overload_cluster
        c.query("SELECT count(*) FROM tel")  # warm every subsystem
        api = BrokerApi(c.broker, port=0) if role == "broker" else \
            ServerAdminApi(c.servers["server_0"], port=0)
        api.start()
        try:
            debug_routes = [
                (m, pat) for m, pat, _fn, _scope in api._routes
                if m == "GET" and pat.pattern.startswith(r"/debug/")]
            assert debug_routes, "no debug routes registered"
            hit = []
            for _m, pat in debug_routes:
                # substitute each capture group with the live table name
                path = pat.pattern.replace(r"([^/]+)", "tel")
                body = _get_json(api.port, path)
                assert isinstance(body, (dict, list)), path
                hit.append(path)
            expected = {"broker": ["/debug/scheduler", "/debug/telemetry",
                                   "/debug/slo", "/debug/flightrecorder",
                                   "/debug/routing/tel"],
                        "server": ["/debug/memory", "/debug/launches",
                                   "/debug/scheduler", "/debug/queries",
                                   "/debug/telemetry", "/debug/slo",
                                   "/debug/flightrecorder"]}[role]
            for path in expected:
                assert path in hit, (path, hit)
        finally:
            api.stop()
