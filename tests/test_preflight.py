"""Kernel preflight (tools/preflight.py): the tier-1 acceptance suite.

The contract under ``pytest -m pallas_preflight``:

- every SSB flight's extracted plan PASSES the lowering model at the
  default config (zero predicted failures), and every passing shape runs
  ``run_segment`` bit-parity in Pallas interpret mode — the model admits
  exactly what the engine can execute;
- every fuzz-grid FAIL shape fails with its intended
  ``pallas_preflight_<rule>`` code (no ``unknown``, no misattribution);
- a seeded predicted-fail shape declines through BOTH executors with its
  preflight reason on the decision ledger — and still serves the correct
  answer on the jnp path;
- the blocklist round-trips through disk
  (``pinot.server.query.pallas.blocklist.path``) and surfaces on
  ``GET /debug/pallas`` together with the verdict table.
"""

import numpy as np
import pytest

from pinot_tpu.engine import ensure_x64

ensure_x64()

from pinot_tpu.common.tracing import PALLAS_PREFLIGHT_REASONS
from pinot_tpu.engine.pallas_blocklist import PallasBlocklist
from pinot_tpu.engine.plan import plan_segment
from pinot_tpu.engine.staging import PALLAS_TILE, StagingCache
from pinot_tpu.query import compile_query
from pinot_tpu.tools import preflight, ssb

pytestmark = pytest.mark.pallas_preflight

# 2 segments x 3000 rows -> padded capacity not a multiple of
# PALLAS_TILE: every extracted spec carries a remainder tile
ROWS = 6_000


@pytest.fixture(scope="module")
def segs(tmp_path_factory):
    out = tmp_path_factory.mktemp("preflight_ssb")
    return ssb.build_segments(0, str(out), num_segments=2, rows=ROWS,
                              workers=1)


@pytest.fixture(scope="module")
def table(segs):
    return preflight.run_preflight(segs)


def _ssb_rows(table):
    return {r["shape"]: r for r in table["shapes"]
            if r["source"] == "ssb"}


# -- the acceptance gate: 13 flights, zero predicted failures ---------------

def test_all_13_ssb_flights_pass_at_default_config(table):
    rows = _ssb_rows(table)
    assert sorted(rows) == sorted(ssb.QUERIES)
    failed = {q: r for q, r in rows.items() if r["verdict"] != "pass"}
    assert not failed, failed
    assert table["ssb_failed"] == []


def test_verdicts_carry_budget_accounting(table):
    for r in _ssb_rows(table).values():
        assert r["vmem_bytes"] > 0
        assert r["smem_slots"] >= 3   # >= 1 interval-free params vector


# -- interpret-mode cross-check: every preflight PASS executes --------------

def test_every_pass_shape_runs_bit_parity_in_interpret_mode(segs, table):
    """A preflight PASS is a promise: the shape must actually run. Every
    passing SSB spec executes run_segment in interpret mode and matches
    the jnp kernel bit-for-bit (decoded-group equality for the
    probe-narrowed shapes, whose packed layout is the narrowed one)."""
    from pinot_tpu.engine.executor import decode_grouped_result
    from pinot_tpu.engine.kernels import build_kernel, unpack_outputs
    from pinot_tpu.engine.pallas_kernels import (
        PallasKernelCache,
        run_segment,
    )

    passing = [q for q, r in _ssb_rows(table).items()
               if r["verdict"] == "pass"]
    assert passing
    seg = segs[0]
    staged = StagingCache().stage(seg)
    cache = PallasKernelCache()
    for qid in passing:
        ctx = compile_query(ssb.QUERIES[qid] + " LIMIT 100000")
        plan = plan_segment(ctx, seg)
        served = run_segment(plan, staged, cache, interpret=True)
        assert served is not None, qid
        packed_pl, eff = served
        cols = {name: staged.column(name).tree() for name in plan.columns}
        packed_jnp = np.asarray(build_kernel(plan.spec)(
            cols, tuple(plan.params), np.int32(seg.num_docs)))
        if eff is plan:
            np.testing.assert_array_equal(np.asarray(packed_pl),
                                          packed_jnp, err_msg=qid)
        else:
            got = decode_grouped_result(
                eff, seg, unpack_outputs(np.asarray(packed_pl), eff.spec))
            want = decode_grouped_result(
                plan, seg, unpack_outputs(packed_jnp, plan.spec))
            assert got.groups == want.groups, qid


# -- fuzz grid: each FAIL shape fails with its intended rule ----------------

EXPECTED_FUZZ_FAILS = {
    "limbs8_over": "pallas_preflight_limb_planes",
    "limbs_on_float": "pallas_preflight_dtype_unsupported",
    "ivs512_over": "pallas_preflight_smem_budget",
    "groups16384_over": "pallas_preflight_groups_bound",
    "groups8100_unpadded": "pallas_preflight_groups_bound",
    "bits6_straddle": "pallas_preflight_tile_align",
    "grid_zero_tiles": "pallas_preflight_grid_bound",
    "wide96_vmem_over": "pallas_preflight_vmem_budget",
}


def test_fuzz_grid_rules_exact(table):
    fuzz = {r["shape"]: r for r in table["shapes"]
            if r["source"] == "fuzz"}
    fails = {s: r["rule"] for s, r in fuzz.items()
             if r["verdict"] == "fail"}
    assert fails == EXPECTED_FUZZ_FAILS
    # the pass side of the grid proves the model admits what the engine
    # emits: limb range, in-cap ivs pads, the dense group spectrum,
    # every word-aligned packed width, remainder tiles
    passing = {s for s, r in fuzz.items() if r["verdict"] == "pass"}
    for expected in ("limbs6", "ivs128", "groups8192", "bits16",
                     "tiles_remainder"):
        assert expected in passing
    # every rule in the registered namespace is exercised by the grid
    assert set(EXPECTED_FUZZ_FAILS.values()) == PALLAS_PREFLIGHT_REASONS


def test_fuzz_grid_covers_the_announced_axes():
    """The grid actually spans the axes it claims: limb counts, ivs run
    counts, group ranges, packed widths, remainder tiles."""
    labels = dict(preflight.fuzz_specs())
    assert labels["limbs6"].value_limbs == (6,)
    assert labels["ivs128"].n_slots == 128
    assert labels["groups8192"].num_groups_padded == 8192
    assert labels["bits16"].packed_bits == (16,)
    # a prime tile count models capacity % PALLAS_TILE != 0 segments
    assert labels["tiles_remainder"].tiles_per_seg == 5


# -- seeded FAIL shapes decline with their preflight reason -----------------

def test_seeded_fail_declines_per_segment_with_rule_reason(segs):
    """A predicted-fail shape seeded into the blocklist declines with
    its pallas_preflight_* reason (never ``unknown``, never the generic
    shape_blocked) AND the jnp path still serves the right answer."""
    from pinot_tpu.engine import ServerQueryExecutor

    ex = ServerQueryExecutor(use_device=True, use_pallas=True)
    host = ServerQueryExecutor(use_device=False)
    # useStarTree=false: the pre-agg rung would otherwise serve Q1.1
    # without ever consulting the pallas blocklist
    sql = ssb.QUERIES["Q1.1"] + " OPTION(useStarTree=false)"
    plan = plan_segment(compile_query(sql), segs[0])
    ex._pallas_blocked.add(plan.spec,
                           reason="pallas_preflight_vmem_budget")
    got, stats = ex.execute(compile_query(sql), segs)
    want, _ = host.execute(compile_query(sql), segs)
    assert got.rows == want.rows
    keys = [k for k in stats.decisions
            if k.endswith(":pallas_preflight_vmem_budget")]
    assert keys, stats.decisions
    assert not [k for k in stats.decisions if k.endswith(":unknown")]


def test_seeded_fail_declines_sharded_with_rule_reason(segs):
    from pinot_tpu.parallel import ShardedQueryExecutor

    ex = ShardedQueryExecutor(use_pallas=True)
    sql = ssb.QUERIES["Q2.1"] + " LIMIT 100000 OPTION(useStarTree=false)"
    # the sharded combine plans against the unified BATCH (its own
    # dictionaries/capacity), so the blocklist key must be the batch plan
    batch = ex.batch_for(segs)
    plan = plan_segment(compile_query(sql), batch)
    ex._pallas_blocked.add(plan.spec,
                           reason="pallas_preflight_smem_budget")
    _got, stats = ex.execute(compile_query(sql), segs)
    keys = [k for k in stats.decisions
            if k.endswith(":pallas_preflight_smem_budget")]
    assert keys, stats.decisions


def test_attach_verdicts_seeds_blocklist_under_pessimal_model(segs):
    """The whole loop: a pessimized model predicts every SSB shape
    fails -> attach_verdicts seeds all 13 into the executor blocklist
    with vmem reasons -> the engine declines them loudly."""
    from pinot_tpu.engine import ServerQueryExecutor

    tiny = preflight.LoweringModel(vmem_bytes=1 << 16)
    table = preflight.run_preflight(segs, model=tiny, fuzz=False)
    assert len(table["ssb_failed"]) == 13
    ex = ServerQueryExecutor(use_device=True, use_pallas=True)
    seeded = preflight.attach_verdicts(ex, table)
    assert seeded == 13
    assert len(ex._pallas_blocked) == 13
    assert ex.preflight_verdicts["failed"] >= 13
    # verdict table attached to the executor is the /debug/pallas body
    assert "_plan_specs" not in ex.preflight_verdicts
    sql = ssb.QUERIES["Q1.1"]
    plan = plan_segment(compile_query(sql), segs[0])
    assert ex._pallas_blocked.reason_for(plan.spec) \
        == "pallas_preflight_vmem_budget"


# -- blocklist persistence + /debug/pallas ----------------------------------

def test_blocklist_roundtrips_through_disk(tmp_path, segs):
    path = str(tmp_path / "blocklist.json")
    bl = PallasBlocklist(path=path)
    plan = plan_segment(compile_query(ssb.QUERIES["Q1.1"]), segs[0])
    bl.add(plan.spec, reason="pallas_preflight_tile_align")
    bl.add(("runtime", "shape"))   # runtime failure: default reason
    # a fresh instance (the restarted chip) remembers both
    bl2 = PallasBlocklist(path=path)
    assert plan.spec in bl2
    assert bl2.reason_for(plan.spec) == "pallas_preflight_tile_align"
    assert bl2.reason_for(("runtime", "shape")) == "pallas_shape_blocked"
    assert len(bl2) == 2


def test_executor_loads_blocklist_from_config(tmp_path, segs):
    from pinot_tpu.engine import ServerQueryExecutor
    from pinot_tpu.spi.config import CommonConstants, PinotConfiguration

    path = str(tmp_path / "bl.json")
    plan = plan_segment(compile_query(ssb.QUERIES["Q1.2"]), segs[0])
    PallasBlocklist(path=path).add(plan.spec,
                                   reason="pallas_preflight_smem_budget")
    ex = ServerQueryExecutor(
        use_device=True, use_pallas=True,
        config=PinotConfiguration(
            {CommonConstants.PALLAS_BLOCKLIST_PATH_KEY: path}))
    assert plan.spec in ex._pallas_blocked
    assert ex._pallas_blocked.reason_for(plan.spec) \
        == "pallas_preflight_smem_budget"
    # a runtime failure learned by THIS process persists for the next
    ex._pallas_blocked.add(("another", "shape"))
    assert ("another", "shape") in PallasBlocklist(path=path)


def test_debug_pallas_body(segs, table):
    """The ServerInstance /debug/pallas body: blocklist rows with
    reasons + the attached verdict table."""
    from types import SimpleNamespace

    from pinot_tpu.engine import ServerQueryExecutor
    from pinot_tpu.server.server import ServerInstance

    ex = ServerQueryExecutor(use_device=True, use_pallas=True)
    preflight.attach_verdicts(ex, table)
    ex._pallas_blocked.add(("bad", "shape"),
                           reason="pallas_preflight_groups_bound")
    body = ServerInstance.pallas_debug(SimpleNamespace(executor=ex))
    assert body["blockedShapes"] == 1
    [row] = body["blocklist"]
    assert row["reason"] == "pallas_preflight_groups_bound"
    assert body["preflight"]["passed"] == table["passed"]
    import json

    json.dumps(body)   # wire-safe


def test_not_extractable_plan_reports_reason(segs):
    """A plan the fused kernel cannot serve at all (distinct agg) gets a
    verdict row, not a crash."""
    staged = StagingCache().stage(segs[0])
    plan = plan_segment(compile_query(
        "SELECT distinctcount(c_city) FROM ssb_lineorder"), segs[0])
    spec, eff, reason = preflight.extract_query_spec(plan, staged)
    assert spec is None and eff is None
    assert reason == "pallas_distinct_agg"
