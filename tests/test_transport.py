"""Transport tests: REST + gRPC over REAL sockets.

The round-3 verdict's item 5: the only inter-process surfaces in the
system were untested. These spin the embedded cluster with its network
front doors bound to real ports — REST admin/query (ref: ClusterTest.java
driving controller/broker REST) and the gRPC query path (ref:
InstanceRequestHandler.java:90 — the broker talks to servers ONLY through
the wire here), including a server-kill partial-results case through the
real transport.
"""

import json
import urllib.error
import urllib.request

import numpy as np
import pytest

from pinot_tpu.spi import DataType, FieldSpec, FieldType, Schema
from pinot_tpu.spi.table import TableConfig
from pinot_tpu.tools.cluster import EmbeddedCluster
from pinot_tpu.transport.grpc_transport import GrpcQueryServer, GrpcServerStub
from pinot_tpu.transport.rest import BrokerApi, ControllerApi, ServerAdminApi

N = 4000


def _schema():
    return Schema("tx_sales", [
        FieldSpec("region", DataType.STRING),
        FieldSpec("qty", DataType.LONG, FieldType.METRIC),
    ])


def _frame(n, seed):
    rng = np.random.default_rng(seed)
    return {
        "region": np.array(["east", "west", "north"])[rng.integers(0, 3, n)],
        "qty": rng.integers(1, 100, n).astype(np.int64),
    }


def _http(method, url, body=None, timeout=30):
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method,
                                 headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read().decode())


@pytest.fixture()
def cluster(tmp_path):
    c = EmbeddedCluster(num_servers=2, data_dir=str(tmp_path / "cluster"))
    yield c
    c.shutdown()


@pytest.fixture()
def rest(cluster):
    """Controller + broker REST bound to ephemeral real ports."""
    ctrl = ControllerApi(cluster.controller, port=0)
    brk = BrokerApi(cluster.broker, port=0)
    ctrl.start()
    brk.start()
    yield cluster, f"http://localhost:{ctrl.port}", \
        f"http://localhost:{brk.port}"
    ctrl.stop()
    brk.stop()


def _create_and_load(cluster, tmp_path, num_segments=2):
    schema = _schema()
    cluster.create_table(TableConfig("tx_sales"), schema)
    total = 0
    frames = []
    for i in range(num_segments):
        f = _frame(N, seed=i)
        frames.append(f)
        cluster.ingest_rows("tx_sales_OFFLINE", schema, f,
                            segment_name=f"tx_{i}")
        total += N
    assert cluster.wait_for_ev_converged("tx_sales_OFFLINE")
    return frames, total


# --------------------------------------------------------------------------
# REST
# --------------------------------------------------------------------------

class TestRest:
    def test_controller_admin_roundtrip(self, rest, tmp_path):
        cluster, ctrl, _ = rest
        assert _http("GET", f"{ctrl}/health")["status"] == "OK"
        # create schema + table over the wire, reference JSON layouts
        _http("POST", f"{ctrl}/schemas", _schema().to_dict())
        assert "tx_sales" in _http("GET", f"{ctrl}/schemas")
        got = _http("GET", f"{ctrl}/schemas/tx_sales")
        assert got["schemaName"] == "tx_sales"
        _http("POST", f"{ctrl}/tables", TableConfig("tx_sales").to_dict())
        assert "tx_sales_OFFLINE" in _http("GET", f"{ctrl}/tables")["tables"]

    def test_segment_upload_and_state(self, rest, tmp_path):
        cluster, ctrl, _ = rest
        _http("POST", f"{ctrl}/schemas", _schema().to_dict())
        _http("POST", f"{ctrl}/tables", TableConfig("tx_sales").to_dict())
        # build a segment locally, upload by path (local-FS deep store)
        from pinot_tpu.segment import SegmentBuilder

        out = str(tmp_path / "built")
        b = SegmentBuilder(_schema(), "tx_up_0")
        b.build(_frame(N, seed=9), out)
        _http("POST", f"{ctrl}/segments",
              {"tableName": "tx_sales_OFFLINE",
               "segmentDir": f"{out}/tx_up_0"})
        assert cluster.wait_for_ev_converged("tx_sales_OFFLINE")
        segs = _http("GET", f"{ctrl}/segments/tx_sales_OFFLINE")
        assert "tx_up_0" in segs
        ideal = _http("GET", f"{ctrl}/tables/tx_sales_OFFLINE/idealstate")
        assert "tx_up_0" in ideal

    def test_broker_query_over_http(self, rest, tmp_path):
        cluster, _, broker = rest
        frames, total = _create_and_load(cluster, tmp_path)
        resp = _http("POST", f"{broker}/query/sql",
                     {"sql": "SELECT count(*) FROM tx_sales"})
        assert resp["resultTable"]["rows"][0][0] == total
        assert resp["numServersQueried"] >= 1
        resp = _http("POST", f"{broker}/query/sql",
                     {"sql": "SELECT region, sum(qty) FROM tx_sales "
                             "GROUP BY region ORDER BY region"})
        rows = resp["resultTable"]["rows"]
        exp = {}
        for f in frames:
            for r, q in zip(f["region"], f["qty"]):
                exp[r] = exp.get(r, 0) + int(q)
        assert {r[0]: r[1] for r in rows} == exp

    def test_broker_query_error_over_http(self, rest):
        _, _, broker = rest
        resp = _http("POST", f"{broker}/query/sql",
                     {"sql": "SELECT count(*) FROM no_such_table"})
        assert resp["exceptions"]

    def test_server_admin_api(self, cluster, tmp_path):
        _create_and_load(cluster, tmp_path)
        api = ServerAdminApi(cluster.servers["server_0"], port=0)
        api.start()
        try:
            base = f"http://localhost:{api.port}"
            assert _http("GET", f"{base}/health")["status"] == "OK"
            assert "tx_sales_OFFLINE" in _http("GET", f"{base}/tables")["tables"]
        finally:
            api.stop()

    def test_cli_post_query(self, rest, tmp_path, capsys):
        """PostQuery subcommand against the real broker port."""
        from pinot_tpu.tools.admin import main

        cluster, _, broker = rest
        _, total = _create_and_load(cluster, tmp_path)
        port = int(broker.rsplit(":", 1)[1])
        rc = main(["PostQuery", "-query", "SELECT count(*) FROM tx_sales",
                   "-brokerPort", str(port)])
        assert rc == 0
        out = json.loads(capsys.readouterr().out.strip())
        assert out["resultTable"]["rows"][0][0] == total


# --------------------------------------------------------------------------
# gRPC query path (broker -> server over the wire)
# --------------------------------------------------------------------------

@pytest.fixture()
def grpc_cluster(tmp_path):
    """Embedded cluster whose broker reaches servers ONLY via gRPC stubs
    over real sockets (the reference's Netty/gRPC data plane)."""
    c = EmbeddedCluster(num_servers=2, data_dir=str(tmp_path / "cluster"))
    fronts = {}
    for iid, server in c.servers.items():
        g = GrpcQueryServer(server, port=0)
        g.start()
        stub = GrpcServerStub(f"localhost:{g.port}", timeout_s=30.0)
        c.broker.register_server(iid, stub)  # replaces in-process handle
        fronts[iid] = (g, stub)
    yield c, fronts
    for g, stub in fronts.values():
        stub.close()
        g.stop(grace=0.5)
    c.shutdown()


class TestGrpc:
    def test_scatter_gather_over_grpc(self, grpc_cluster, tmp_path):
        cluster, _ = grpc_cluster
        frames, total = _create_and_load(cluster, tmp_path, num_segments=3)
        rows = cluster.query_rows("SELECT count(*), sum(qty) FROM tx_sales")
        exp_sum = sum(int(q) for f in frames for q in f["qty"])
        assert rows[0] == [3 * N, exp_sum]

        rows = cluster.query_rows(
            "SELECT region, count(*) FROM tx_sales "
            "GROUP BY region ORDER BY region")
        exp = {}
        for f in frames:
            for r in f["region"]:
                exp[r] = exp.get(r, 0) + 1
        assert {r[0]: r[1] for r in rows} == exp

    def test_grpc_matches_in_process(self, grpc_cluster, tmp_path):
        cluster, _ = grpc_cluster
        _create_and_load(cluster, tmp_path)
        sql = ("SELECT region, sum(qty), min(qty), max(qty) FROM tx_sales "
               "GROUP BY region ORDER BY region")
        wire_rows = cluster.query_rows(sql)
        # rewire in-process and compare
        for iid, server in cluster.servers.items():
            cluster.broker.register_server(iid, server)
        assert cluster.query_rows(sql) == wire_rows

    def test_server_kill_partial_results(self, grpc_cluster, tmp_path):
        """Ref: the reference tolerates server loss with partial results +
        exceptions (SingleConnectionBrokerRequestHandler.java:134-141)."""
        cluster, fronts = grpc_cluster
        _create_and_load(cluster, tmp_path, num_segments=4)
        resp = cluster.query("SELECT count(*) FROM tx_sales")
        assert not resp.has_exceptions
        full = resp.result_table.rows[0][0]

        # kill one server's network front mid-flight
        victim = "server_1"
        g, _stub = fronts[victim]
        g.stop(grace=0)
        resp = cluster.query("SELECT count(*) FROM tx_sales")
        assert resp.has_exceptions          # the caller SEES partiality
        if resp.result_table is not None:   # partial rows from live servers
            assert resp.result_table.rows[0][0] < full

    def test_grpc_bad_query_surfaces_exception(self, grpc_cluster, tmp_path):
        cluster, _ = grpc_cluster
        _create_and_load(cluster, tmp_path)
        resp = cluster.query("SELECT no_such_col FROM tx_sales")
        assert resp.has_exceptions

    def test_stub_connection_refused(self):
        """A stub pointed at a dead port degrades to an exception DataTable,
        not a crash."""
        from pinot_tpu.query import compile_query

        stub = GrpcServerStub("localhost:1", timeout_s=2.0)
        try:
            dt = stub.execute_query(
                compile_query("SELECT count(*) FROM t"), "t_OFFLINE", ["s0"])
            assert dt.exceptions
        finally:
            stub.close()


def test_recommender_and_ui_endpoints(rest, tmp_path):
    cluster, ctrl_url, _ = rest
    _create_and_load(cluster, tmp_path)
    out = _http("POST", f"{ctrl_url}/tables/tx_sales/recommender",
                {"queries": ["SELECT count(*) FROM tx_sales "
                             "WHERE region = 'east'"] * 5})
    assert out["recommendations"]["sortedColumn"] == ["region"]
    # the status page renders tables + instances
    req = urllib.request.Request(f"{ctrl_url}/ui")
    with urllib.request.urlopen(req, timeout=30) as resp:
        assert resp.headers["Content-Type"].startswith("text/html")
        html = resp.read().decode()
    assert "tx_sales_OFFLINE" in html


def test_lineage_endpoints(rest, tmp_path):
    cluster, ctrl_url, brk_url = rest
    _create_and_load(cluster, tmp_path)
    table = "tx_sales_OFFLINE"
    segs = _http("GET", f"{ctrl_url}/segments/{table}")
    out = _http("POST", f"{ctrl_url}/segments/{table}/startReplaceSegments",
                {"segmentsFrom": [segs[0]], "segmentsTo": ["merged_0"]})
    eid = out["segmentLineageEntryId"]
    _http("POST", f"{ctrl_url}/segments/{table}/endReplaceSegments/{eid}")
    # replaced input is now hidden from routing
    out = _http("GET", f"{brk_url}/debug/routing/{table}")
    routed = sorted(sum(out["routing"].values(), []))
    assert segs[0] not in routed
    assert out["segmentsRouted"] == len(routed)


def test_server_admin_size_and_memory(cluster, tmp_path):
    _create_and_load(cluster, tmp_path)
    server = next(iter(cluster.servers.values()))
    api = ServerAdminApi(server, port=0)
    api.start()
    try:
        base = f"http://localhost:{api.port}"
        size = _http("GET", f"{base}/tables/tx_sales_OFFLINE/size")
        assert size["totalBytes"] > 0
        # 2 segments are spread across the 2 servers; this one hosts >= 1
        assert len(size["segments"]) >= 1
        mem = _http("GET", f"{base}/debug/memory")
        assert "stagedSegments" in mem and "nativeMmapBuffers" in mem
        # bytes-accurate residency accounting + the ops eviction hook
        assert "stagedBytes" in mem and "budgetBytes" in mem
        for seg in mem["stagedSegments"].values():
            assert seg["bytes"] >= 0
        out = _http("POST", f"{base}/debug/memory/evict/not_staged")
        assert out["evicted"] == "not_staged"
        # tiered residency: both tiers reported + the ops demotion hook
        tier = mem["hostTier"]
        assert "hostBytes" in tier and "entries" in tier
        staged = [n for n in mem["stagedSegments"]]
        if staged:
            out = _http("POST", f"{base}/debug/memory/demote/{staged[0]}")
            assert out["demoted"] in (True, False)  # False iff pinned
            if out["demoted"]:
                mem2 = _http("GET", f"{base}/debug/memory")
                assert staged[0] in mem2["hostTier"]["entries"]
                assert mem2["hostTier"]["hostBytes"] > 0
        out = _http("POST", f"{base}/debug/memory/demote/not_staged")
        assert out["demoted"] is False
    finally:
        api.stop()
