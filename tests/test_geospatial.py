"""Geospatial: WKT types, ST_* functions, grid-cell geo index
(ref: pinot-core geospatial/, ImmutableH3IndexReader, H3IndexFilterOperator)."""

import numpy as np
import pytest

from pinot_tpu.engine import ServerQueryExecutor
from pinot_tpu.query import compile_query
from pinot_tpu.query.functions import lookup
from pinot_tpu.segment import SegmentBuilder, load_segment
from pinot_tpu.spi import DataType, FieldSpec, FieldType, Schema
from pinot_tpu.spi.table import FieldConfig, TableConfig
from pinot_tpu.utils import geo


class TestGeometry:
    def test_wkt_roundtrip(self):
        for wkt in ("POINT (1.5 -2.25)",
                    "POLYGON ((0 0, 10 0, 10 10, 0 10, 0 0))",
                    "MULTIPOINT (1 2, 3 4)"):
            assert geo.from_wkt(wkt).wkt() == wkt

    def test_haversine_known_distance(self):
        # SFO -> LAX ~ 543 km
        d = geo.haversine_m(-122.3790, 37.6213, -118.4085, 33.9416)
        assert abs(d - 543_000) < 8_000

    def test_euclidean_vs_geography(self):
        a, b = geo.point(0, 0), geo.point(3, 4)
        assert geo.distance(a, b) == 5.0
        ag = geo.point(0, 0, True)
        assert geo.distance(ag, b) > 500_000  # meters on the sphere

    def test_point_in_polygon(self):
        poly = ((0, 0), (10, 0), (10, 10), (0, 10))
        xs = np.array([5.0, 15.0, -1.0, 9.99])
        ys = np.array([5.0, 5.0, 5.0, 9.99])
        assert geo.points_in_polygon(xs, ys, poly).tolist() == \
            [True, False, False, True]

    def test_area(self):
        g = geo.from_wkt("POLYGON ((0 0, 4 0, 4 3, 0 3, 0 0))")
        assert geo.area(g) == 12.0

    def test_union_points(self):
        u = geo.union([geo.point(1, 2), geo.point(3, 4), geo.point(1, 2)])
        assert u.kind == "MULTIPOINT" and len(u.coords) == 2


class TestCells:
    def test_cell_stability(self):
        c1 = geo.cell_of(-122.4, 37.77, 9)
        c2 = geo.cells_of(np.array([-122.4]), np.array([37.77]), 9)[0]
        assert c1 == int(c2)

    def test_disk_covers_radius(self):
        # points within r of center must land in the disk's cells
        rng = np.random.default_rng(2)
        center = (-122.4, 37.77)
        disk = set(geo.cell_disk(*center, 5000, 10))
        for _ in range(200):
            ang = rng.uniform(0, 2 * np.pi)
            r = rng.uniform(0, 5000)
            dlat = r * np.cos(ang) / 111_320.0
            dlng = r * np.sin(ang) / (111_320.0 * np.cos(np.radians(37.77)))
            c = geo.cell_of(center[0] + dlng, center[1] + dlat, 10)
            assert c in disk


class TestStFunctions:
    def test_point_and_accessors(self):
        p = lookup("ST_Point")(-122.4, 37.77)
        assert lookup("ST_X")(p) == -122.4
        assert lookup("ST_Y")(p) == 37.77

    def test_within_contains(self):
        poly = "POLYGON ((0 0, 10 0, 10 10, 0 10, 0 0))"
        assert lookup("ST_Within")("POINT (3 3)", poly) == 1
        assert lookup("ST_Contains")(poly, "POINT (30 3)") == 0

    def test_geogfromtext_tags_geography(self):
        g = lookup("ST_GeogFromText")("POINT (0 0)")
        assert g.startswith("SRID=4326;")
        assert lookup("ST_AsText")(g) == "POINT (0 0)"


@pytest.fixture(scope="module")
def geo_segment(tmp_path_factory):
    out = str(tmp_path_factory.mktemp("geo"))
    rng = np.random.default_rng(31)
    n = 3000
    # cluster near SF + scatter across the US
    near = rng.integers(0, 2, n).astype(bool)
    lngs = np.where(near, -122.4 + rng.normal(0, 0.02, n),
                    rng.uniform(-120, -70, n))
    lats = np.where(near, 37.77 + rng.normal(0, 0.02, n),
                    rng.uniform(25, 48, n))
    points = [f"SRID=4326;POINT ({x:.6f} {y:.6f})" for x, y in zip(lngs, lats)]
    schema = Schema("places", [
        FieldSpec("loc", DataType.STRING),
        FieldSpec("v", DataType.LONG, FieldType.METRIC),
    ])
    tc = TableConfig(table_name="places", field_config_list=[
        FieldConfig("loc", index_type="H3", properties={"resolutions": "10"})])
    SegmentBuilder(schema, "p0", table_config=tc).build(
        {"loc": points, "v": list(range(n))}, out)
    return load_segment(f"{out}/p0"), lngs, lats


class TestGeoIndex:
    def test_index_built(self, geo_segment):
        seg, _, _ = geo_segment
        assert seg.metadata.column("loc").has_geo_index
        assert seg.data_source("loc").geo_index is not None

    def test_distance_query_parity(self, geo_segment):
        seg, lngs, lats = geo_segment
        ex = ServerQueryExecutor()
        center = "SRID=4326;POINT (-122.4 37.77)"
        t, _ = ex.execute(compile_query(
            f"SELECT count(*) FROM places "
            f"WHERE stdistance(loc, '{center}') < 3000"), [seg])
        d = geo.haversine_m(lngs, lats, -122.4, 37.77)
        # parity modulo float formatting: recompute from the stored strings
        stored = geo.haversine_m(np.round(lngs, 6), np.round(lats, 6),
                                 -122.4, 37.77)
        assert t.rows[0][0] == int((stored < 3000).sum())
        assert t.rows[0][0] > 0

    def test_index_path_matches_scan_path(self, geo_segment, tmp_path):
        """Same data WITHOUT the index must give identical results."""
        seg, lngs, lats = geo_segment
        n = len(lngs)
        points = [f"SRID=4326;POINT ({x:.6f} {y:.6f})"
                  for x, y in zip(lngs, lats)]
        schema = Schema("places", [
            FieldSpec("loc", DataType.STRING),
            FieldSpec("v", DataType.LONG, FieldType.METRIC),
        ])
        SegmentBuilder(schema, "noidx").build(
            {"loc": points, "v": list(range(n))}, str(tmp_path))
        plain = load_segment(str(tmp_path / "noidx"))
        ex = ServerQueryExecutor()
        center = "SRID=4326;POINT (-122.41 37.76)"
        sql = (f"SELECT sum(v), count(*) FROM places "
               f"WHERE stdistance(loc, '{center}') < 2500")
        with_idx, _ = ex.execute(compile_query(sql), [seg])
        without, _ = ex.execute(compile_query(sql), [plain])
        assert with_idx.rows == without.rows


def test_cell_disk_high_latitude():
    """The cap's longitude reach is widest poleward of the center; a point
    just inside the radius at lat 64 must be in the disk (regression)."""
    disk = set(geo.cell_disk(0.0, 60.0, 1_270_000, 12))
    d = geo.haversine_m(22.94, 64.05, 0.0, 60.0)
    assert d < 1_270_000
    assert geo.cell_of(22.94, 64.05, 12) in disk
