"""Driver-contract smoke tests: entry() compiles under jit; dryrun_multichip
executes the full sharded combine on the virtual 8-device CPU mesh."""

import jax
import numpy as np


def test_entry_jits():
    import __graft_entry__ as ge

    fn, args = ge.entry()
    out = jax.jit(fn)(*args)
    assert "presence" in out
    assert int(np.asarray(out["presence"]).sum()) > 0


def test_dryrun_multichip():
    import __graft_entry__ as ge

    ge.dryrun_multichip(8)
