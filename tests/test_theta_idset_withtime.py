"""Theta sketch, IDSET, LAST/FIRSTWITHTIME aggregations
(ref: DistinctCountThetaSketchAggregationFunction,
IdSetAggregationFunction + InIdSetTransformFunction,
LastWithTimeAggregationFunction / FirstWithTimeAggregationFunction)."""

import base64

import numpy as np
import pandas as pd
import pytest

from pinot_tpu.common import serde
from pinot_tpu.engine import ServerQueryExecutor
from pinot_tpu.query import compile_query
from pinot_tpu.segment import SegmentBuilder, load_segment
from pinot_tpu.spi import DataType, FieldSpec, FieldType, Schema
from pinot_tpu.utils.theta import ThetaSketch


class TestThetaSketch:
    def test_exact_below_k(self):
        s = ThetaSketch.of(list(range(1000)))
        assert s.estimate() == 1000.0

    @pytest.mark.parametrize("true_n", [10_000, 100_000])
    def test_estimate_within_error(self, true_n):
        vals = np.arange(true_n) * 7919
        est = ThetaSketch.of(vals).estimate()
        # RSE ~ 1/sqrt(k) = 1.6% at k=4096; allow 5 sigma
        assert abs(est - true_n) <= 0.08 * true_n, est

    def test_merge_equals_union(self):
        a_vals = np.arange(0, 60_000)
        b_vals = np.arange(30_000, 90_000)
        est = ThetaSketch.of(a_vals).merge(ThetaSketch.of(b_vals)).estimate()
        assert abs(est - 90_000) <= 0.08 * 90_000

    def test_intersect_and_anotb(self):
        a = ThetaSketch.of(np.arange(0, 50_000))
        b = ThetaSketch.of(np.arange(25_000, 75_000))
        inter = a.intersect(b).estimate()
        diff = a.a_not_b(b).estimate()
        assert abs(inter - 25_000) <= 0.15 * 25_000
        assert abs(diff - 25_000) <= 0.15 * 25_000

    def test_serde_round_trip(self):
        s = ThetaSketch.of(["x", "y", 3, 4.5, b"bytes"])
        s2 = ThetaSketch.deserialize(s.serialize())
        assert np.array_equal(s.hashes, s2.hashes)
        assert s2.theta == s.theta and s2.k == s.k


@pytest.fixture(scope="module")
def events(tmp_path_factory):
    out = str(tmp_path_factory.mktemp("theta"))
    rng = np.random.default_rng(23)
    n = 8000
    df = pd.DataFrame({
        "user": [f"u{i}" for i in rng.integers(0, 3000, n)],
        "grp": [f"g{i}" for i in rng.integers(0, 3, n)],
        "val": rng.integers(0, 1000, n).astype(np.int64),
        "ts": rng.permutation(n).astype(np.int64),  # unique times
    })
    schema = Schema("events", [
        FieldSpec("user", DataType.STRING),
        FieldSpec("grp", DataType.STRING),
        FieldSpec("val", DataType.LONG, FieldType.METRIC),
        FieldSpec("ts", DataType.LONG),
    ])
    SegmentBuilder(schema, "ev_0").build(
        {c: df[c].tolist() for c in df.columns}, out)
    SegmentBuilder(schema, "ev_1").build(
        {c: df[c].tolist()[:n // 2] for c in df.columns}, out)
    return [load_segment(f"{out}/ev_0"), load_segment(f"{out}/ev_1")], df


class TestThetaQueries:
    def test_scalar(self, events):
        segs, df = events
        ex = ServerQueryExecutor()
        t, _ = ex.execute(compile_query(
            "SELECT distinctcountthetasketch(user) FROM events"), segs)
        true_n = df.user.nunique()
        assert abs(t.rows[0][0] - true_n) <= max(0.05 * true_n, 2)

    def test_raw_returns_hex(self, events):
        segs, _ = events
        ex = ServerQueryExecutor()
        t, _ = ex.execute(compile_query(
            "SELECT distinctcountrawthetasketch(user) FROM events"), segs)
        raw = bytes.fromhex(t.rows[0][0])
        assert ThetaSketch.deserialize(raw).estimate() > 0

    def test_group_by(self, events):
        segs, df = events
        ex = ServerQueryExecutor()
        t, _ = ex.execute(compile_query(
            "SELECT grp, distinctcountthetasketch(user) FROM events "
            "GROUP BY grp ORDER BY grp"), segs)
        expect = df.groupby("grp").user.nunique()
        for grp, est in t.rows:
            true_n = int(expect[grp])
            assert abs(est - true_n) <= max(0.05 * true_n, 2), (grp, est)


class TestIdSet:
    def test_idset_roundtrips_through_inidset(self, events):
        segs, df = events
        ex = ServerQueryExecutor()
        t, _ = ex.execute(compile_query(
            "SELECT idset(val) FROM events WHERE grp = 'g1'"), segs)
        encoded = t.rows[0][0]
        ids = set(serde.loads(base64.b64decode(encoded)))
        assert ids == set(df[df.grp == "g1"].val.tolist())
        # the membership transform consumes the aggregation's output
        from pinot_tpu.query.functions import lookup
        in_id_set = lookup("inIdSet")
        member = next(iter(ids))
        assert in_id_set(member, encoded) == 1
        assert in_id_set(-999, encoded) == 0


class TestWithTime:
    def test_lastwithtime(self, events):
        segs, df = events
        ex = ServerQueryExecutor()
        t, _ = ex.execute(compile_query(
            "SELECT lastwithtime(val, ts, 'LONG') FROM events"), segs)
        expect = int(df.loc[df.ts.idxmax()].val)
        assert t.rows[0][0] == expect

    def test_firstwithtime_grouped(self, events):
        segs, df = events
        ex = ServerQueryExecutor()
        t, _ = ex.execute(compile_query(
            "SELECT grp, firstwithtime(user, ts, 'STRING') FROM events "
            "GROUP BY grp ORDER BY grp"), segs)
        expect = df.loc[df.groupby("grp").ts.idxmin()].set_index("grp").user
        for grp, got in t.rows:
            assert got == expect[grp], (grp, got)

    def test_withtime_empty_filter(self, events):
        segs, _ = events
        ex = ServerQueryExecutor()
        t, _ = ex.execute(compile_query(
            "SELECT lastwithtime(val, ts, 'LONG') FROM events "
            "WHERE grp = 'nope'"), segs)
        assert t.rows[0][0] == float("-inf")

    def test_bad_datatype_rejected(self, events):
        segs, _ = events
        from pinot_tpu.engine.errors import QueryError
        ex = ServerQueryExecutor()
        with pytest.raises(QueryError):
            ex.execute(compile_query(
                "SELECT lastwithtime(val, ts, 'BLOB') FROM events"), segs)


def test_lastwithtime_float_times(events, tmp_path):
    """DOUBLE time columns must not truncate (10.9 beats 10.2)."""
    import pandas as pd
    df = pd.DataFrame({"v": [1.0, 2.0], "t": [10.9, 10.2],
                       "g": ["a", "a"]})
    schema = Schema("ft", [
        FieldSpec("g", DataType.STRING),
        FieldSpec("v", DataType.DOUBLE, FieldType.METRIC),
        FieldSpec("t", DataType.DOUBLE),
    ])
    SegmentBuilder(schema, "ft0").build(
        {c: df[c].tolist() for c in df.columns}, str(tmp_path))
    seg = load_segment(str(tmp_path / "ft0"))
    ex = ServerQueryExecutor()
    t, _ = ex.execute(compile_query(
        "SELECT lastwithtime(v, t, 'DOUBLE') FROM ft"), [seg])
    assert t.rows[0][0] == 1.0


def test_sumprecision_exact(events, tmp_path):
    """SUMPRECISION: exact decimal sum where f64 would round
    (ref: SumPrecisionAggregationFunction over BigDecimal)."""
    import pandas as pd
    vals = [9007199254740993, 1, 9007199254740993]  # > 2^53: f64 rounds
    df = pd.DataFrame({"g": ["a"] * 3, "v": vals})
    schema = Schema("sp", [
        FieldSpec("g", DataType.STRING),
        FieldSpec("v", DataType.LONG, FieldType.METRIC)])
    SegmentBuilder(schema, "sp0").build(
        {c: df[c].tolist() for c in df.columns}, str(tmp_path))
    SegmentBuilder(schema, "sp1").build(
        {c: df[c].tolist() for c in df.columns}, str(tmp_path))
    segs = [load_segment(str(tmp_path / "sp0")),
            load_segment(str(tmp_path / "sp1"))]
    ex = ServerQueryExecutor()
    t, _ = ex.execute(compile_query("SELECT sumprecision(v) FROM sp"), segs)
    # integral sums finalize as exact ints; the values sit in the > 2^53
    # regime where f64 addition WOULD round (the guard below proves it)
    assert t.rows[0][0] == sum(vals) * 2
    assert int(float(sum(vals) * 2)) != sum(vals) * 2
