"""Approximate aggregations: HLL + t-digest sketch properties and their
end-to-end behavior (ref: DistinctCountHLLAggregationFunction /
PercentileTDigestAggregationFunction; BASELINE.json config #4)."""

import numpy as np
import pandas as pd
import pytest

from pinot_tpu.engine import ServerQueryExecutor
from pinot_tpu.query import compile_query
from pinot_tpu.segment import SegmentBuilder, load_segment
from pinot_tpu.spi import DataType, FieldSpec, FieldType, Schema
from pinot_tpu.utils.hll import HyperLogLog, hash_values
from pinot_tpu.utils.tdigest import TDigest


class TestHyperLogLog:
    @pytest.mark.parametrize("true_n", [10, 1000, 50_000])
    def test_estimate_within_error(self, true_n):
        rng = np.random.default_rng(42)
        values = rng.integers(0, 1 << 60, true_n * 3)[:true_n]
        h = HyperLogLog()
        h.add_values(values)
        est = h.cardinality()
        # standard error for log2m=8 is ~6.5%; allow 3 sigma
        assert abs(est - len(set(values.tolist()))) <= \
            max(0.2 * true_n, 5), (est, true_n)

    def test_merge_equals_union(self):
        rng = np.random.default_rng(7)
        a_vals = rng.integers(0, 10_000, 5000)
        b_vals = rng.integers(5_000, 15_000, 5000)
        a = HyperLogLog.of(a_vals)
        b = HyperLogLog.of(b_vals)
        both = HyperLogLog.of(np.concatenate([a_vals, b_vals]))
        assert a.merge(b).cardinality() == both.cardinality()

    def test_serde_round_trip(self):
        h = HyperLogLog.of(["a", "b", "c", b"\x01\x02", 42, 3.14])
        h2 = HyperLogLog.deserialize(h.serialize())
        assert np.array_equal(h.registers, h2.registers)
        assert h2.log2m == h.log2m

    def test_string_and_numeric_hashing_disjoint(self):
        hs = hash_values(["1", "2"])
        hn = hash_values(np.array([1, 2]))
        assert set(hs.tolist()).isdisjoint(set(hn.tolist()))


class TestTDigest:
    @pytest.mark.parametrize("q", [0.01, 0.25, 0.5, 0.9, 0.99])
    def test_quantile_accuracy(self, q):
        rng = np.random.default_rng(3)
        vals = rng.normal(100, 20, 100_000)
        d = TDigest.of(vals)
        true_q = float(np.quantile(vals, q))
        got = d.quantile(q)
        spread = float(np.quantile(vals, 0.999) - np.quantile(vals, 0.001))
        assert abs(got - true_q) <= 0.02 * spread, (q, got, true_q)

    def test_merge_matches_single_digest(self):
        rng = np.random.default_rng(5)
        a_vals = rng.exponential(10, 50_000)
        b_vals = rng.exponential(30, 50_000)
        merged = TDigest.of(a_vals).merge(TDigest.of(b_vals))
        combined = np.concatenate([a_vals, b_vals])
        for q in (0.1, 0.5, 0.95):
            true_q = float(np.quantile(combined, q))
            spread = float(np.quantile(combined, 0.999))
            assert abs(merged.quantile(q) - true_q) <= 0.03 * spread

    def test_compression_bounds_centroids(self):
        d = TDigest.of(np.random.default_rng(1).normal(0, 1, 200_000))
        assert d.means.shape[0] < 200  # ~compression centroids

    def test_serde_round_trip(self):
        d = TDigest.of([1.0, 2.0, 3.0, 100.0])
        d2 = TDigest.deserialize(d.serialize())
        assert d2.quantile(0.5) == d.quantile(0.5)


class TestSketchQueries:
    @pytest.fixture(scope="class")
    def seg(self, tmp_path_factory):
        out = str(tmp_path_factory.mktemp("sk"))
        rng = np.random.default_rng(17)
        n = 20_000
        self_df = pd.DataFrame({
            "user": [f"u{i}" for i in rng.integers(0, 5000, n)],
            "grp": [f"g{i}" for i in rng.integers(0, 4, n)],
            "lat": np.round(rng.gamma(3, 25, n), 3),
        })
        schema = Schema("events", [
            FieldSpec("user", DataType.STRING),
            FieldSpec("grp", DataType.STRING),
            FieldSpec("lat", DataType.DOUBLE, FieldType.METRIC),
        ])
        SegmentBuilder(schema, "ev_0").build(
            {c: self_df[c].tolist() for c in self_df.columns}, out)
        return load_segment(f"{out}/ev_0"), self_df

    def test_distinctcounthll_query(self, seg):
        segment, df = seg
        ex = ServerQueryExecutor()
        t, _ = ex.execute(compile_query(
            "SELECT distinctcounthll(user) FROM events"), [segment])
        true_n = df.user.nunique()
        assert abs(t.rows[0][0] - true_n) <= 0.2 * true_n

    def test_percentiletdigest_query(self, seg):
        segment, df = seg
        ex = ServerQueryExecutor()
        t, _ = ex.execute(compile_query(
            "SELECT percentiletdigest95(lat), percentiletdigest50(lat) "
            "FROM events"), [segment])
        for got, q in zip(t.rows[0], (0.95, 0.50)):
            true_q = float(df.lat.quantile(q))
            assert abs(got - true_q) <= 0.05 * float(df.lat.max())

    def test_group_by_sketches(self, seg):
        segment, df = seg
        ex = ServerQueryExecutor()
        t, _ = ex.execute(compile_query(
            "SELECT grp, distinctcounthll(user), percentiletdigest90(lat) "
            "FROM events GROUP BY grp ORDER BY grp LIMIT 10"), [segment])
        for row in t.rows:
            part = df[df.grp == row[0]]
            assert abs(row[1] - part.user.nunique()) <= 0.2 * part.user.nunique()
            assert abs(row[2] - part.lat.quantile(0.9)) <= \
                0.05 * float(df.lat.max())

    def test_multi_segment_merge(self, seg, tmp_path):
        """Sketch states must merge across segments (the wire/merge path)."""
        segment, df = seg
        out = str(tmp_path)
        schema = segment.metadata.schema
        half = len(df) // 2
        for i, sl in enumerate([slice(0, half), slice(half, None)]):
            part = df.iloc[sl]
            SegmentBuilder(schema, f"ev_s{i}").build(
                {c: part[c].tolist() for c in df.columns}, out)
        segs = [load_segment(f"{out}/ev_s{i}") for i in range(2)]
        ex = ServerQueryExecutor()
        t_split, _ = ex.execute(compile_query(
            "SELECT distinctcounthll(user) FROM events"), segs)
        t_single, _ = ex.execute(compile_query(
            "SELECT distinctcounthll(user) FROM events"), [segment])
        assert t_split.rows[0][0] == t_single.rows[0][0]

    def test_rawhll_returns_serialized(self, seg):
        segment, _ = seg
        ex = ServerQueryExecutor()
        t, _ = ex.execute(compile_query(
            "SELECT distinctcountrawhll(user) FROM events"), [segment])
        from pinot_tpu.utils.hll import HyperLogLog
        h = HyperLogLog.deserialize(bytes.fromhex(t.rows[0][0]))
        assert h.cardinality() > 0


class TestDeviceHLL:
    """Round-4: DISTINCTCOUNTHLL runs the TPU path (BASELINE config #4).
    Device and host hash identical values, so parity is EXACT."""

    @pytest.fixture(scope="class")
    def hll_segs(self, tmp_path_factory):
        out = str(tmp_path_factory.mktemp("dhll"))
        rng = np.random.default_rng(23)
        n = 30_000
        df = pd.DataFrame({
            "user": np.array([f"u{i}" for i in range(8000)])[
                rng.integers(0, 8000, n)],
            "grp": np.array(["a", "b", "c"])[rng.integers(0, 3, n)],
            "lat": np.round(rng.gamma(3, 25, n), 3),
        })
        schema = Schema("events", [
            FieldSpec("user", DataType.STRING),
            FieldSpec("grp", DataType.STRING),
            FieldSpec("lat", DataType.DOUBLE, FieldType.METRIC),
        ])
        segs = []
        for i, sl in enumerate([slice(0, n // 2), slice(n // 2, None)]):
            part = df.iloc[sl]
            SegmentBuilder(schema, f"dh_{i}").build(
                {c: part[c].to_numpy() for c in df.columns}, out)
            segs.append(load_segment(f"{out}/dh_{i}"))
        return segs

    def test_hll_plans_on_device(self, hll_segs):
        from pinot_tpu.engine.plan import plan_segment

        plan = plan_segment(compile_query(
            "SELECT distinctcounthll(user) FROM events"), hll_segs[0])
        assert plan.spec[1][0][0] == "distinctcounthll"
        plan = plan_segment(compile_query(
            "SELECT grp, distinctcounthll(user) FROM events GROUP BY grp"),
            hll_segs[0])
        assert plan.spec[1][0][0] == "distinctcounthll"

    def test_device_matches_host_exactly(self, hll_segs):
        dev = ServerQueryExecutor(use_device=True)
        host = ServerQueryExecutor(use_device=False)
        for sql in (
            "SELECT distinctcounthll(user) FROM events",
            "SELECT distinctcounthll(user) FROM events WHERE lat > 20",
            "SELECT grp, distinctcounthll(user) FROM events "
            "GROUP BY grp ORDER BY grp",
        ):
            got, _ = dev.execute(compile_query(sql), hll_segs[:1])
            want, _ = host.execute(compile_query(sql), hll_segs[:1])
            assert got.rows == want.rows, sql  # same hashes -> exact

    def test_sharded_hll_matches_host(self, hll_segs):
        from pinot_tpu.parallel import ShardedQueryExecutor

        dev = ShardedQueryExecutor()
        host = ServerQueryExecutor(use_device=False)
        for sql in (
            "SELECT distinctcounthll(user) FROM events",
            "SELECT grp, distinctcounthll(user), count(*) FROM events "
            "GROUP BY grp ORDER BY grp",
        ):
            got, _ = dev.execute(compile_query(sql), hll_segs)
            want, _ = host.execute(compile_query(sql), hll_segs)
            assert got.rows == want.rows, sql
