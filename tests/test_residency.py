"""HBM residency manager: budget / pins / LRU / spill / prefetch / wire.

The invariants the subsystem guarantees (engine/residency.py):
- concurrent stagers of one segment share ONE StagedSegment (the old
  get-then-set race built duplicate device arrays and leaked one set);
- budget enforcement evicts LRU-first and only UNPINNED residents;
- a query whose working set cannot fit spills to the host engine and
  returns host-identical results (graceful degradation, no device OOM);
- reload keeps the identity-based invalidation;
- ``QueryStats.staging`` merges across segments/shards and round-trips
  the DataTable wire;
- sharded batch eviction drops EVERY cache derived from a batch, for
  every batch containing an evicted segment.
"""

import threading

import numpy as np
import pytest

from pinot_tpu.common.datatable import DataTable
from pinot_tpu.engine import QueryStats, ServerQueryExecutor
from pinot_tpu.engine.residency import (
    QueryLease,
    ResidencyManager,
    estimate_segment_bytes,
)
from pinot_tpu.parallel import ShardedQueryExecutor
from pinot_tpu.query import compile_query
from pinot_tpu.segment import SegmentBuilder, load_segment
from pinot_tpu.spi import DataType, FieldSpec, FieldType, Schema

RNG = np.random.default_rng(7)
N = 1024
NUM_SEGMENTS = 4
COLUMNS = ("region", "qty")

GROUP_SQL = ("SELECT region, sum(qty), count(*) FROM sales "
             "GROUP BY region ORDER BY region")
AGG_SQL = "SELECT sum(qty), count(*) FROM sales WHERE region != 'west'"


def _schema():
    return Schema("sales", [
        FieldSpec("region", DataType.STRING),
        FieldSpec("qty", DataType.LONG, FieldType.METRIC),
    ])


@pytest.fixture(scope="module")
def segs(tmp_path_factory):
    out = tmp_path_factory.mktemp("residency_segs")
    regions = ["east", "west", "north", "south"]
    built = []
    for i in range(NUM_SEGMENTS):
        b = SegmentBuilder(_schema(), f"sales_{i}")
        b.build({
            "region": [regions[j] for j in RNG.integers(0, 4, N)],
            "qty": RNG.integers(1, 50, N).tolist(),
        }, str(out))
        built.append(load_segment(str(out / f"sales_{i}")))
    return built


def _stage_full(rm: ResidencyManager, seg, lease=None):
    st = rm.stage(seg, lease=lease)
    for c in COLUMNS:
        st.column(c)
    return st


def _host_rows(segs, sql):
    host = ServerQueryExecutor(use_device=False)
    rt, _ = host.execute(compile_query(sql), segs)
    return rt.rows


# --------------------------------------------------------------------------
# lock correctness (the stage() race satellite)
# --------------------------------------------------------------------------

def test_concurrent_stage_shares_one_resident(segs):
    rm = ResidencyManager(budget_bytes=0)  # uncapped
    barrier = threading.Barrier(8)
    got = []

    def worker():
        barrier.wait()
        got.append(rm.stage(segs[0]))

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len({id(s) for s in got}) == 1, \
        "concurrent stagers built duplicate StagedSegments (device leak)"
    assert rm.misses == 1 and rm.hits == 7


def test_stage_evict_thread_hammer(segs):
    """Stage + column-build + evict from many threads: no exceptions, and
    the manager ends in a consistent state."""
    rm = ResidencyManager(budget_bytes=0)
    stop = threading.Event()
    errors = []

    def stager(seg):
        while not stop.is_set():
            try:
                st = rm.stage(seg)
                st.column("region")
                st.column("qty")
            except Exception as e:  # pragma: no cover - failure mode
                errors.append(e)
                return

    def evictor():
        while not stop.is_set():
            for s in segs[:2]:
                try:
                    rm.evict(s.segment_name)
                except Exception as e:  # pragma: no cover - failure mode
                    errors.append(e)
                    return

    threads = [threading.Thread(target=stager, args=(s,))
               for s in segs[:2] for _ in range(3)]
    threads.append(threading.Thread(target=evictor))
    for t in threads:
        t.start()
    stop.wait(1.0)
    stop.set()
    for t in threads:
        t.join()
    assert not errors
    # post-hammer: staging still serves working residents
    st = rm.stage(segs[0])
    assert st.column("region").fwd is not None
    assert rm.staged_bytes() > 0


# --------------------------------------------------------------------------
# budget / LRU / pins
# --------------------------------------------------------------------------

def test_budget_evicts_lru_first(segs):
    rm = ResidencyManager(budget_bytes=0)
    for s in segs[:3]:
        _stage_full(rm, s)
    per_seg = rm.staged_bytes() // 3
    # touch segment 0: LRU order becomes [1, 2, 0]
    rm.stage(segs[0])
    rm.set_budget_bytes(int(per_seg * 2.5))
    names = rm.resident_names()
    assert segs[1].segment_name not in names, "LRU entry must evict first"
    assert segs[0].segment_name in names
    assert segs[2].segment_name in names
    snap = rm.stats_snapshot()
    assert snap["evictions"] == 1
    assert snap["stagedBytes"] <= int(per_seg * 2.5)


def test_register_accounts_and_enforces_on_insert():
    """Regression (graftlint conservation finding): ``register()`` used to
    insert a batch resident without re-running byte accounting or budget
    enforcement — stagedBytes drifted from reality until the next
    unrelated refresh, and over-budget batch inserts never evicted."""
    class _Resident:
        def __init__(self, n):
            self._n = n
            self.released = False

        def nbytes(self):
            return self._n

        def release(self):
            self.released = True

    rm = ResidencyManager(budget_bytes=1000)
    a = _Resident(600)
    rm.register("a", lambda: a)
    assert rm.staged_bytes() == 600, \
        "insert must be accounted on the register() call itself"
    b = _Resident(600)
    rm.register("b", lambda: b)
    # over budget: the unpinned LRU entry (a) must evict on the SAME call
    assert a.released and not b.released
    assert rm.resident_names() == ["b"]
    assert rm.staged_bytes() == 600


def test_pinned_segments_survive_eviction_pressure(segs):
    rm = ResidencyManager(budget_bytes=0)
    lease = QueryLease()
    _stage_full(rm, segs[0], lease=lease)
    _stage_full(rm, segs[1])  # unpinned
    assert rm.staged_bytes() > 0
    rm.set_budget_bytes(1)  # everything must go... except pins
    names = rm.resident_names()
    assert segs[0].segment_name in names, "pinned resident was evicted"
    assert segs[1].segment_name not in names
    assert rm.pin_blocked >= 1
    # lease closes -> the pin releases -> budget enforcement reclaims it
    stats = QueryStats()
    rm.end_query(lease, stats)
    assert segs[0].segment_name not in rm.resident_names()
    assert stats.staging["pinBlockedEvictions"] >= 0
    assert stats.staging["stagedBytes"] == 0


def test_reload_keeps_identity_invalidation(segs, tmp_path):
    rm = ResidencyManager(budget_bytes=0)
    st1 = _stage_full(rm, segs[0])
    reloaded = load_segment(segs[0].segment_dir)  # same name, new object
    st2 = rm.stage(reloaded)
    assert st2 is not st1
    assert st2.segment is reloaded
    assert rm.misses == 2  # both stagings were builds, not a stale hit
    assert len(rm.resident_names()) == 1


def test_estimate_tracks_actual_bytes(segs):
    rm = ResidencyManager(budget_bytes=0)
    st = _stage_full(rm, segs[0])
    est = estimate_segment_bytes(segs[0], COLUMNS)
    actual = st.nbytes()
    assert est > 0 and actual > 0
    # metadata estimate within 2x of truth either way (admission quality)
    assert actual / 2 <= est <= actual * 2


# --------------------------------------------------------------------------
# spill to host (admission control)
# --------------------------------------------------------------------------

def test_per_segment_spill_matches_host_oracle(segs):
    dev = ServerQueryExecutor(hbm_budget_bytes=64)
    for sql in (GROUP_SQL, AGG_SQL):
        rt, stats = dev.execute(compile_query(sql), segs)
        assert rt.rows == _host_rows(segs, sql)
        assert stats.staging["spills"] == 1
        assert stats.staging["stagedBytes"] == 0
    assert dev.residency.spills == 2


def test_sharded_spill_matches_host_oracle(segs):
    dev = ShardedQueryExecutor(hbm_budget_bytes=64)
    rt, stats = dev.execute(compile_query(GROUP_SQL), segs)
    assert rt.rows == _host_rows(segs, GROUP_SQL)
    assert stats.staging["spills"] == 1
    assert stats.group_by_rung == "host"


def test_sharded_capped_budget_churns_but_stays_correct(segs):
    """Budget fits ONE batch resident: alternating working sets (the full
    segment list vs a subset batch) evict each other — LRU churn — while
    every answer stays host-identical and nothing device-OOMs."""
    probe = ShardedQueryExecutor()
    ctx_all = compile_query(GROUP_SQL)
    probe.execute(ctx_all, segs)
    one_batch = probe.residency.staged_bytes()
    assert one_batch > 0

    dev = ShardedQueryExecutor(hbm_budget_bytes=int(one_batch * 1.5))
    ctx_sub = compile_query(AGG_SQL)
    want_all = _host_rows(segs, GROUP_SQL)
    want_sub = _host_rows(segs[:2], AGG_SQL)
    for _ in range(2):
        rt, stats = dev.execute(ctx_all, segs)
        assert rt.rows == want_all
        assert stats.staging["spills"] == 0
        rt, stats = dev.execute(ctx_sub, segs[:2])
        assert rt.rows == want_sub
    snap = dev.residency.stats_snapshot()
    assert snap["evictions"] >= 1, "capped budget never churned"
    assert snap["stagedBytes"] <= int(one_batch * 1.5)


def test_warm_hit_rate_is_total(segs):
    dev = ShardedQueryExecutor()
    ctx = compile_query(GROUP_SQL)
    dev.execute(ctx, segs)  # cold: miss + stage
    _, stats = dev.execute(ctx, segs)
    assert stats.staging["misses"] == 0
    assert stats.staging["hits"] >= 1
    assert stats.staging["spills"] == 0


# --------------------------------------------------------------------------
# sharded batch eviction (the _evict_batch satellite)
# --------------------------------------------------------------------------

def test_evict_segment_clears_every_containing_batch(segs):
    dev = ShardedQueryExecutor()
    ctx_all = compile_query(GROUP_SQL)
    ctx_sub = compile_query(AGG_SQL)
    want_all = _host_rows(segs, GROUP_SQL)
    dev.execute(ctx_all, segs)       # batch over all four segments
    dev.execute(ctx_sub, segs[:2])   # a second batch sharing segment 0
    assert len(dev._batches) == 2
    assert dev._device_cols and dev._param_cache and dev._launch_cache

    dev.evict_segment(segs[0].segment_name)
    assert not dev._batches, "a batch containing the segment survived"
    assert not dev._device_cols, "sharded device arrays leaked"
    assert not dev._launch_cache, \
        "compiled query closures (pinning old arrays) leaked"
    assert not dev._param_cache, "device param arrays leaked"
    assert not dev.residency.resident_names()

    # and the path rebuilds cleanly
    rt, _ = dev.execute(ctx_all, segs)
    assert rt.rows == want_all


def test_evict_batch_clears_query_cache_by_batch_name(segs):
    """Regression for the k[1]-vs-k[2] key bug: both cache tiers carry the
    batch name at slot [-2]; the old evictor compared the batch name
    against the FINGERPRINT slot and never evicted anything."""
    dev = ShardedQueryExecutor()
    dev.execute(compile_query(GROUP_SQL), segs)
    assert dev._param_cache and dev._launch_cache
    batch = dev.batch_for(segs)
    dev._evict_batch(batch)
    assert not dev._param_cache and not dev._launch_cache


# --------------------------------------------------------------------------
# stats plumbing: merge + wire
# --------------------------------------------------------------------------

def test_staging_stats_merge_counters_sum_bytes_max():
    a = QueryStats(staging={"hits": 1, "misses": 2, "spills": 0,
                            "stagedBytes": 100})
    b = QueryStats(staging={"hits": 3, "misses": 1, "spills": 1,
                            "stagedBytes": 40, "evictions": 2})
    a.merge(b)
    assert a.staging == {"hits": 4, "misses": 3, "spills": 1,
                         "stagedBytes": 100, "evictions": 2}


def test_staging_rides_the_datatable_wire():
    stats = QueryStats(num_docs_scanned=5,
                       staging={"hits": 2, "misses": 1, "evictions": 1,
                                "pinBlockedEvictions": 0, "spills": 0,
                                "stagedBytes": 4096})
    dt = DataTable.for_aggregation([7], stats)
    out = DataTable.from_bytes(dt.to_bytes())
    assert out.stats.staging == stats.staging
    # legacy JSON framing too (mixed-version interop)
    out2 = DataTable.from_bytes(dt.to_json_bytes())
    assert out2.stats.staging == stats.staging


# --------------------------------------------------------------------------
# prefetch + lifecycle hooks + debug snapshot
# --------------------------------------------------------------------------

def test_prefetch_stages_in_background(segs):
    rm = ResidencyManager(budget_bytes=0)
    try:
        rm.prefetch(segs[0])
        rm.drain_prefetch()
        assert segs[0].segment_name in rm.resident_names()
        assert rm.staged_bytes() > 0
        assert rm.stats_snapshot()["prefetched"] == 1
    finally:
        rm.close()


def test_prefetch_never_evicts_for_itself(segs):
    rm = ResidencyManager(budget_bytes=0)
    try:
        _stage_full(rm, segs[0])
        rm.set_budget_bytes(rm.staged_bytes())  # exactly full
        rm.stage(segs[0])  # pinless touch: seg 0 is MRU anyway
        rm.prefetch(segs[1])
        rm.drain_prefetch()
        assert segs[0].segment_name in rm.resident_names(), \
            "prefetch evicted a serving resident"
    finally:
        rm.close()


def test_prefetch_queued_before_remove_cannot_resurrect(segs):
    """The prefetch-vs-removeSegment race, made deterministic: a prefetch
    sits in the queue behind a stalled item while the segment is evicted.
    When the worker finally runs it, the retire-generation check must turn
    it into a no-op — staging anyway would resurrect a removed segment as
    an orphaned resident no removeSegment will ever clean up."""
    from types import SimpleNamespace

    release_worker = threading.Event()

    class _BlockingCols:
        def keys(self):
            release_worker.wait(10.0)
            return []

    blocker = SimpleNamespace(
        segment_name="__blocker__", is_mutable=False, num_docs=0,
        padded_capacity=0, metadata=SimpleNamespace(columns=_BlockingCols()))

    rm = ResidencyManager(budget_bytes=0)
    try:
        rm.prefetch(blocker)            # worker stalls inside this item
        rm.prefetch(segs[0])            # queued behind the stall
        rm.evict(segs[0].segment_name)  # removeSegment lands first
        release_worker.set()
        rm.drain_prefetch()
        assert segs[0].segment_name not in rm.resident_names(), \
            "queued prefetch resurrected a removed segment"
        # a re-add AFTER the remove is a fresh generation and must prefetch
        rm.prefetch(segs[0])
        rm.drain_prefetch()
        assert segs[0].segment_name in rm.resident_names()
    finally:
        release_worker.set()
        rm.close()


def test_prefetch_vs_remove_thread_hammer(segs):
    """Background lifecycle-listener staging racing removeSegment eviction:
    no exceptions, no orphaned resident after the final remove, and byte
    accounting stays exact (== sum of resident bytes, never negative)."""
    rm = ResidencyManager(budget_bytes=0)
    stop = threading.Event()
    errors = []

    def prefetcher(seg):
        while not stop.is_set():
            try:
                rm.prefetch(seg)
            except Exception as e:  # pragma: no cover - failure mode
                errors.append(e)
                return

    def remover():
        while not stop.is_set():
            for s in segs[:2]:
                try:
                    rm.evict(s.segment_name)
                except Exception as e:  # pragma: no cover - failure mode
                    errors.append(e)
                    return

    threads = [threading.Thread(target=prefetcher, args=(s,))
               for s in segs[:2] for _ in range(2)]
    threads += [threading.Thread(target=remover) for _ in range(2)]
    try:
        for t in threads:
            t.start()
        stop.wait(1.0)
    finally:
        stop.set()
        for t in threads:
            t.join()
    assert not errors
    rm.drain_prefetch()
    # the final word is remove: nothing may stay (or come back) resident
    for s in segs[:2]:
        rm.evict(s.segment_name)
    rm.drain_prefetch()
    for s in segs[:2]:
        assert s.segment_name not in rm.resident_names()
    snap = rm.snapshot()
    by_resident = sum(e["bytes"] for e in snap["stagedSegments"].values())
    assert snap["stagedBytes"] == by_resident >= 0
    rm.close()


def test_data_manager_lifecycle_hooks(segs, tmp_path):
    from pinot_tpu.server.data_manager import TableDataManager

    class Listener:
        def __init__(self):
            self.added, self.removed = [], []

        def segment_added(self, table, segment):
            self.added.append((table, segment.segment_name))

        def segment_removed(self, table, segment_name):
            self.removed.append((table, segment_name))

    lis = Listener()
    tdm = TableDataManager("sales_OFFLINE", listener=lis)
    tdm.add_segment(segs[0])
    assert lis.added == [("sales_OFFLINE", segs[0].segment_name)]
    tdm.remove_segment(segs[0].segment_name)
    assert lis.removed == [("sales_OFFLINE", segs[0].segment_name)]


def test_snapshot_is_bytes_accurate(segs):
    rm = ResidencyManager(budget_bytes=0)
    st = _stage_full(rm, segs[0])
    snap = rm.snapshot()
    ent = snap["stagedSegments"][segs[0].segment_name]
    assert ent["bytes"] == st.nbytes() > 0
    assert ent["columns"] == len(COLUMNS)
    assert snap["stagedBytes"] == ent["bytes"]
    assert snap["peakBytes"] >= snap["stagedBytes"]
    assert snap["budgetBytes"] is None
