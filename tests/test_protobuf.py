"""Protobuf input format: protoc descriptor -> delimited decode -> segment.

Ref: pinot-plugins/pinot-input-format/pinot-protobuf (ProtoBufRecordReader
+ ProtoBufRecordExtractor): data files hold varint-length-delimited
messages; the reader resolves the message type from a protoc-compiled
FileDescriptorSet.
"""

import subprocess

import pytest

from pinot_tpu.ingestion.readers import create_record_reader
from pinot_tpu.spi import DataType, FieldSpec, FieldType, Schema

PROTO_SRC = """
syntax = "proto3";
package bench;

message Order {
  string region = 1;
  int64 qty = 2;
  double price = 3;
  repeated string tags = 4;
  Status status = 5;
  enum Status { NEW = 0; SHIPPED = 1; }
}
"""


@pytest.fixture(scope="module")
def proto_env(tmp_path_factory):
    """Compile the .proto with the REAL protoc, build the dynamic message
    class, and write a delimited data file."""
    out = tmp_path_factory.mktemp("proto")
    (out / "order.proto").write_text(PROTO_SRC)
    desc = out / "order.desc"
    subprocess.run(
        ["protoc", f"--proto_path={out}",
         f"--descriptor_set_out={desc}", "order.proto"],
        check=True)

    from pinot_tpu.ingestion.protobuf import (
        load_message_class,
        write_delimited,
    )

    Order = load_message_class(str(desc), "bench.Order")
    msgs = []
    for i in range(50):
        m = Order()
        m.region = ["east", "west"][i % 2]
        m.qty = i
        m.price = i * 1.5
        m.tags.extend([f"t{i % 3}", "all"])
        m.status = i % 2
        msgs.append(m)
    data = out / "orders.pb"
    write_delimited(str(data), msgs)
    return str(desc), str(data)


def test_reader_roundtrip(proto_env):
    desc, data = proto_env
    reader = create_record_reader(
        data, "proto",
        config={"descriptorFile": desc, "protoClassName": "bench.Order"})
    rows = list(reader)
    assert len(rows) == 50
    assert rows[0].get("region") == "east"
    assert rows[3].get("qty") == 3
    assert rows[3].get("tags") == ["t0", "all"]
    assert rows[1].get("status") == "SHIPPED"  # enum -> name


def test_extension_dispatch(proto_env):
    desc, data = proto_env
    reader = create_record_reader(
        data,  # .pb extension resolves the format
        config={"descriptorFile": desc, "protoClassName": "bench.Order"})
    assert len(list(reader)) == 50


def test_segment_from_protobuf(proto_env, tmp_path):
    from pinot_tpu.engine import ServerQueryExecutor
    from pinot_tpu.query import compile_query
    from pinot_tpu.segment import SegmentBuilder, load_segment

    desc, data = proto_env
    schema = Schema("orders", [
        FieldSpec("region", DataType.STRING),
        FieldSpec("tags", DataType.STRING, single_value=False),
        FieldSpec("qty", DataType.LONG, FieldType.METRIC),
        FieldSpec("price", DataType.DOUBLE, FieldType.METRIC),
    ])
    rows = list(create_record_reader(
        data, "proto",
        config={"descriptorFile": desc, "protoClassName": "bench.Order"}))
    frame = {fs.name: [r.get(fs.name) for r in rows]
             for fs in schema.field_specs}
    SegmentBuilder(schema, "orders_0").build(frame, str(tmp_path))
    seg = load_segment(str(tmp_path / "orders_0"))
    ex = ServerQueryExecutor(use_device=False)
    rt, _ = ex.execute(compile_query(
        "SELECT region, sum(qty) FROM orders GROUP BY region "
        "ORDER BY region"), [seg])
    assert rt.rows == [["east", float(sum(range(0, 50, 2)))],
                       ["west", float(sum(range(1, 50, 2)))]]
