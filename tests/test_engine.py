"""Execution engine parity tests: device kernels vs pandas oracle, and
device path vs host path (mirrors the reference's *QueriesTest strategy,
pinot-core/src/test/java/org/apache/pinot/queries/)."""

import numpy as np
import pandas as pd
import pytest

from pinot_tpu.engine import QueryError, ServerQueryExecutor
from pinot_tpu.query import compile_query
from pinot_tpu.segment import SegmentBuilder, load_segment
from pinot_tpu.spi import (
    DataType,
    FieldSpec,
    FieldType,
    IndexingConfig,
    Schema,
)

RNG = np.random.default_rng(7)
N = 3000


def make_data():
    teams = ["ATL", "BOS", "CHC", "NYA", "SFO", "LAD", "HOU"]
    leagues = ["AL", "NL"]
    df = pd.DataFrame({
        "team": [teams[i] for i in RNG.integers(0, len(teams), N)],
        "league": [leagues[i] for i in RNG.integers(0, 2, N)],
        "year": RNG.integers(1990, 2021, N).astype(np.int64),
        "runs": RNG.integers(0, 150, N).astype(np.int64),
        "score": np.round(RNG.normal(50, 12, N), 3),
        "salary": RNG.integers(10_000, 5_000_000, N).astype(np.int64),  # raw
    })
    tags = [[f"t{j}" for j in RNG.choice(5, size=RNG.integers(0, 4), replace=False)]
            for _ in range(N)]
    mvnums = [RNG.integers(0, 30, RNG.integers(1, 5)).astype(np.int64).tolist()
              for _ in range(N)]
    return df, tags, mvnums


def make_schema():
    return Schema("stats", [
        FieldSpec("team", DataType.STRING),
        FieldSpec("league", DataType.STRING),
        FieldSpec("year", DataType.INT),
        FieldSpec("tags", DataType.STRING, single_value=False),
        FieldSpec("nums", DataType.INT, single_value=False),
        FieldSpec("runs", DataType.LONG, FieldType.METRIC),
        FieldSpec("score", DataType.DOUBLE, FieldType.METRIC),
        FieldSpec("salary", DataType.LONG, FieldType.METRIC),
    ])


@pytest.fixture(scope="module")
def setup(tmp_path_factory):
    out = tmp_path_factory.mktemp("engine_segs")
    df, tags, mvnums = make_data()
    cols = {c: df[c].tolist() for c in df.columns}
    cols["tags"] = [t or None for t in tags]
    cols["nums"] = mvnums
    # two segments over row halves (exercises the combine/merge path)
    half = N // 2
    segs = []
    for i, sl in enumerate([slice(0, half), slice(half, N)]):
        b = SegmentBuilder(
            make_schema(), f"stats_{i}",
            indexing_config=IndexingConfig(no_dictionary_columns=["salary"]))
        b.build({k: v[sl] for k, v in cols.items()}, str(out))
        segs.append(load_segment(str(out / f"stats_{i}")))
    df["tags"] = tags
    df["nums"] = mvnums
    return df, segs


@pytest.fixture(scope="module")
def device_exec():
    return ServerQueryExecutor(use_device=True)


@pytest.fixture(scope="module")
def host_exec():
    return ServerQueryExecutor(use_device=False)


def run(executor, segments, sql):
    ctx = compile_query(sql)
    rt, stats = executor.execute(ctx, segments)
    return rt


def rows(executor, segments, sql):
    return run(executor, segments, sql).rows


# device float aggregation is f32 (metadata-narrowed for v5e); the pandas /
# host oracle is f64 — float parity is to f32-accumulation precision
FLOAT_REL = 1e-5


def assert_rows_close(got, want, rel=FLOAT_REL):
    assert len(got) == len(want), (got, want)
    for gr, wr in zip(got, want):
        assert len(gr) == len(wr), (gr, wr)
        for g, w in zip(gr, wr):
            if isinstance(w, float):
                assert g == pytest.approx(w, rel=rel, abs=1e-9), (gr, wr)
            else:
                assert g == w, (gr, wr)


class TestAggregationParity:
    SQL = "SELECT count(*), sum(runs), min(score), max(score), avg(runs), minmaxrange(year) FROM stats WHERE team = 'BOS'"

    def _expected(self, df):
        d = df[df.team == "BOS"]
        return [len(d), float(d.runs.sum()), float(d.score.min()),
                float(d.score.max()), float(d.runs.mean()),
                float(d.year.max() - d.year.min())]

    def test_device_matches_pandas(self, setup, device_exec):
        df, segs = setup
        got = rows(device_exec, segs, self.SQL)[0]
        exp = self._expected(df)
        assert got[0] == exp[0]
        for g, e in zip(got[1:], exp[1:]):
            assert g == pytest.approx(e, rel=FLOAT_REL)

    def test_host_matches_device(self, setup, device_exec, host_exec):
        df, segs = setup
        assert_rows_close(rows(device_exec, segs, self.SQL),
                          rows(host_exec, segs, self.SQL))


class TestFilters:
    CASES = [
        ("year BETWEEN 2000 AND 2010", lambda d: (d.year >= 2000) & (d.year <= 2010)),
        ("team IN ('ATL','BOS','LAD')", lambda d: d.team.isin(["ATL", "BOS", "LAD"])),
        ("team NOT IN ('ATL')", lambda d: ~d.team.isin(["ATL"])),
        ("team != 'SFO'", lambda d: d.team != "SFO"),
        ("score > 60.5", lambda d: d.score > 60.5),
        ("score <= 40", lambda d: d.score <= 40),
        ("team LIKE 'B%'", lambda d: d.team.str.startswith("B")),
        ("regexp_like(team, '^[AB]')", lambda d: d.team.str.match("[AB]")),
        ("salary > 2500000", lambda d: d.salary > 2500000),
        ("salary BETWEEN 100000 AND 200000",
         lambda d: (d.salary >= 100000) & (d.salary <= 200000)),
        ("team = 'BOS' AND year > 2005 OR league = 'NL' AND runs < 10",
         lambda d: (d.team == "BOS") & (d.year > 2005) | (d.league == "NL") & (d.runs < 10)),
        ("NOT (team = 'BOS' OR team = 'ATL')",
         lambda d: ~((d.team == "BOS") | (d.team == "ATL"))),
        ("year = 2015", lambda d: d.year == 2015),
        ("team = 'NOPE'", lambda d: d.team == "NOPE"),
    ]

    @pytest.mark.parametrize("where,fn", CASES, ids=[c[0][:40] for c in CASES])
    def test_count_parity(self, setup, device_exec, where, fn):
        df, segs = setup
        got = rows(device_exec, segs, f"SELECT count(*) FROM stats WHERE {where}")
        assert got[0][0] == int(fn(df).sum())

    def test_mv_predicate(self, setup, device_exec):
        df, segs = setup
        got = rows(device_exec, segs,
                   "SELECT count(*) FROM stats WHERE tags = 't1'")
        exp = sum(1 for t in df.tags if "t1" in t)
        assert got[0][0] == exp

    def test_mv_in_predicate(self, setup, device_exec):
        df, segs = setup
        got = rows(device_exec, segs,
                   "SELECT count(*) FROM stats WHERE tags IN ('t1','t3')")
        exp = sum(1 for t in df.tags if set(t) & {"t1", "t3"})
        assert got[0][0] == exp

    @pytest.mark.parametrize("exec_name", ["device", "host"])
    def test_mv_exclusive_predicates_all_semantics(self, setup, device_exec,
                                                   host_exec, exec_name):
        # NOT_EQ / NOT_IN on MV: ALL values must satisfy (regression,
        # ref: BaseDictionaryBasedPredicateEvaluator.applyMV isExclusive)
        df, segs = setup
        ex = device_exec if exec_name == "device" else host_exec
        got = rows(ex, segs, "SELECT count(*) FROM stats WHERE tags != 't1'")
        exp = sum(1 for t in df.tags if "t1" not in t)  # doc must NOT contain t1
        assert got[0][0] == exp
        got2 = rows(ex, segs,
                    "SELECT count(*) FROM stats WHERE tags NOT IN ('t1','t3')")
        exp2 = sum(1 for t in df.tags if not (set(t) & {"t1", "t3"}))
        assert got2[0][0] == exp2


class TestGroupBy:
    SQL = ("SELECT team, sum(runs), count(*) FROM stats WHERE year >= 2000 "
           "GROUP BY team ORDER BY sum(runs) DESC LIMIT 5")

    def _expected(self, df):
        d = df[df.year >= 2000]
        g = d.groupby("team").agg(s=("runs", "sum"), c=("runs", "size"))
        g = g.sort_values("s", ascending=False).head(5)
        return [[t, float(r.s), int(r.c)] for t, r in g.iterrows()]

    def test_device_matches_pandas(self, setup, device_exec):
        df, segs = setup
        assert rows(device_exec, segs, self.SQL) == self._expected(df)

    def test_host_matches_device(self, setup, device_exec, host_exec):
        df, segs = setup
        assert_rows_close(rows(device_exec, segs, self.SQL),
                          rows(host_exec, segs, self.SQL))

    def test_multi_column_group(self, setup, device_exec):
        df, segs = setup
        got = rows(device_exec, segs,
                   "SELECT league, team, avg(score) FROM stats "
                   "GROUP BY league, team ORDER BY league, team LIMIT 100")
        g = df.groupby(["league", "team"]).score.mean().reset_index()
        g = g.sort_values(["league", "team"])
        exp = [[r.league, r.team, pytest.approx(r.score, rel=FLOAT_REL)]
               for r in g.itertuples()]
        assert got == exp

    def test_group_by_int_column(self, setup, device_exec):
        df, segs = setup
        got = rows(device_exec, segs,
                   "SELECT year, max(runs) FROM stats GROUP BY year "
                   "ORDER BY year LIMIT 50")
        g = df.groupby("year").runs.max().reset_index().sort_values("year")
        assert got == [[int(r.year), float(r.runs)] for r in g.itertuples()]

    def test_having(self, setup, device_exec):
        df, segs = setup
        got = rows(device_exec, segs,
                   "SELECT team, count(*) FROM stats GROUP BY team "
                   "HAVING count(*) > 400 ORDER BY count(*) DESC LIMIT 10")
        g = df.groupby("team").size()
        g = g[g > 400].sort_values(ascending=False)
        assert got == [[t, int(c)] for t, c in g.items()]

    def test_group_by_raw_int(self, setup, device_exec, host_exec):
        # salary is raw (no dictionary): host and device must agree
        sql = ("SELECT year, sum(salary) FROM stats GROUP BY year "
               "ORDER BY year LIMIT 40")
        assert_rows_close(rows(device_exec, setup[1], sql),
                          rows(host_exec, setup[1], sql))

    def test_post_aggregation(self, setup, device_exec):
        df, segs = setup
        got = rows(device_exec, segs,
                   "SELECT team, sum(runs) / count(*) FROM stats GROUP BY team "
                   "ORDER BY team LIMIT 10")
        g = df.groupby("team").agg(s=("runs", "sum"), c=("runs", "size"))
        exp = [[t, pytest.approx(r.s / r.c, rel=FLOAT_REL)] for t, r in
               g.sort_index().iterrows()]
        assert got == exp


class TestMVAggregations:
    def test_summv_countmv(self, setup, device_exec):
        df, segs = setup
        got = rows(device_exec, segs,
                   "SELECT summv(nums), countmv(nums), minmv(nums), maxmv(nums) "
                   "FROM stats WHERE team = 'ATL'")
        sel = df[df.team == "ATL"].nums
        flat = [x for row in sel for x in row]
        assert got[0][0] == pytest.approx(sum(flat))
        assert got[0][1] == len(flat)
        assert got[0][2] == min(flat)
        assert got[0][3] == max(flat)

    def test_host_matches_device(self, setup, device_exec, host_exec):
        sql = "SELECT summv(nums), avgmv(nums) FROM stats WHERE year < 2000"
        assert (rows(device_exec, setup[1], sql)
                == rows(host_exec, setup[1], sql))


class TestDistinctCount:
    def test_distinctcount(self, setup, device_exec):
        df, segs = setup
        got = rows(device_exec, segs,
                   "SELECT distinctcount(team), distinctcount(year) FROM stats "
                   "WHERE league = 'AL'")
        d = df[df.league == "AL"]
        assert got[0] == [d.team.nunique(), d.year.nunique()]

    def test_count_distinct_sql(self, setup, device_exec):
        df, segs = setup
        got = rows(device_exec, segs, "SELECT COUNT(DISTINCT team) FROM stats")
        assert got[0][0] == df.team.nunique()


class TestPercentile:
    def test_percentile_host_path(self, setup, device_exec):
        df, segs = setup
        got = rows(device_exec, segs,
                   "SELECT percentile95(score) FROM stats WHERE team='CHC'")
        vals = np.sort(df[df.team == "CHC"].score.values)
        exp = vals[min(int(len(vals) * 0.95), len(vals) - 1)]
        assert got[0][0] == pytest.approx(exp)


class TestSelection:
    def test_selection_limit(self, setup, device_exec):
        df, segs = setup
        got = rows(device_exec, segs,
                   "SELECT team, year, runs FROM stats WHERE team='HOU' LIMIT 7")
        d = df[df.team == "HOU"].head(7)
        assert got == [[r.team, int(r.year), int(r.runs)] for r in d.itertuples()]

    def test_selection_order_by(self, setup, device_exec):
        df, segs = setup
        got = rows(device_exec, segs,
                   "SELECT year, score FROM stats WHERE team='BOS' "
                   "ORDER BY score DESC LIMIT 5")
        d = df[df.team == "BOS"].sort_values("score", ascending=False).head(5)
        assert got == [[int(r.year), pytest.approx(r.score)] for r in d.itertuples()]

    def test_selection_offset(self, setup, device_exec):
        df, segs = setup
        got = rows(device_exec, segs,
                   "SELECT year FROM stats WHERE team='BOS' "
                   "ORDER BY year LIMIT 5 OFFSET 3")
        d = df[df.team == "BOS"].sort_values("year").year.iloc[3:8]
        assert [r[0] for r in got] == [int(y) for y in d]

    def test_select_star(self, setup, device_exec):
        df, segs = setup
        rt = run(device_exec, segs, "SELECT * FROM stats LIMIT 2")
        assert rt.schema.column_names == list(make_schema().column_names)
        assert len(rt.rows) == 2
        assert rt.rows[0][0] == df.team.iloc[0]


class TestDistinct:
    def test_distinct(self, setup, device_exec):
        df, segs = setup
        got = rows(device_exec, segs,
                   "SELECT DISTINCT league FROM stats ORDER BY league")
        assert got == [["AL"], ["NL"]]

    def test_group_by_without_aggregation_is_distinct(self, setup, device_exec):
        # regression: must not run as plain selection with duplicates
        df, segs = setup
        got = rows(device_exec, segs,
                   "SELECT league FROM stats GROUP BY league ORDER BY league")
        assert got == [["AL"], ["NL"]]

    def test_group_by_select_mismatch_rejected(self, setup, device_exec):
        from pinot_tpu.query import SqlParseError
        with pytest.raises(SqlParseError, match="must appear in"):
            compile_query("SELECT team FROM stats GROUP BY league")


class TestFastPaths:
    def test_metadata_count_star(self, setup, device_exec):
        df, segs = setup
        rt, stats = device_exec.execute(
            compile_query("SELECT count(*) FROM stats"), segs)
        assert rt.rows[0][0] == len(df)
        assert stats.num_docs_scanned == 0  # metadata path: no scan

    def test_metadata_min_max(self, setup, device_exec):
        df, segs = setup
        got = rows(device_exec, segs, "SELECT min(year), max(year) FROM stats")
        assert got[0] == [float(df.year.min()), float(df.year.max())]


class TestErrors:
    def test_unknown_column(self, setup, device_exec):
        with pytest.raises(QueryError, match="unknown column"):
            run(device_exec, setup[1], "SELECT nope FROM stats")

    def test_empty_result_aggregation(self, setup, device_exec):
        got = rows(device_exec, setup[1],
                   "SELECT count(*), sum(runs) FROM stats WHERE team='ZZZ'")
        assert got[0][0] == 0
        assert got[0][1] == 0.0

    def test_empty_group_by(self, setup, device_exec):
        got = rows(device_exec, setup[1],
                   "SELECT team, count(*) FROM stats WHERE team='ZZZ' GROUP BY team")
        assert got == []


class TestJitCaching:
    def test_literal_change_reuses_kernel(self, setup, device_exec):
        segs = setup[1]
        run(device_exec, segs, "SELECT sum(runs) FROM stats WHERE year > 2000")
        n = len(device_exec.kernels)
        run(device_exec, segs, "SELECT sum(runs) FROM stats WHERE year > 2010")
        run(device_exec, segs, "SELECT sum(runs) FROM stats WHERE year > 1995")
        assert len(device_exec.kernels) == n  # same structure -> same kernel


def test_batched_scatter_branch_parity(tmp_path, monkeypatch):
    """Force the TPU-only batched-scatter lowering on the CPU oracle: the
    stacked [N, k] segment reduces must match the split per-leaf path
    (regression guard for the branch CI otherwise never runs)."""
    import numpy as np

    from pinot_tpu.engine import kernels
    from pinot_tpu.engine.executor import ServerQueryExecutor
    from pinot_tpu.query import compile_query
    from pinot_tpu.segment import SegmentBuilder, load_segment
    from pinot_tpu.spi import DataType, FieldSpec, FieldType, Schema

    rng = np.random.default_rng(21)
    n = 5000
    frame = {"g": [f"g{i % 6}" for i in range(n)],
             "a": rng.integers(0, 100, n).tolist(),
             "b": np.round(rng.normal(10, 3, n), 2).tolist()}
    schema = Schema("bs", [
        FieldSpec("g", DataType.STRING),
        FieldSpec("a", DataType.LONG, FieldType.METRIC),
        FieldSpec("b", DataType.DOUBLE, FieldType.METRIC)])
    SegmentBuilder(schema, "bs0").build(frame, str(tmp_path))
    seg = load_segment(str(tmp_path / "bs0"))
    sql = ("SELECT g, count(*), sum(a), avg(a), min(b), max(b), "
           "minmaxrange(a), sum(b) FROM bs WHERE a > 10 "
           "GROUP BY g ORDER BY g")

    split = ServerQueryExecutor(use_device=True)
    rt_split, _ = split.execute(compile_query(sql), [seg])
    assert len(split.kernels) == 1  # the DEVICE path served, not host

    monkeypatch.setattr(kernels, "FORCE_BATCH_SCATTERS", True)
    batched = ServerQueryExecutor(use_device=True)  # fresh kernel cache
    rt_batched, _ = batched.execute(compile_query(sql), [seg])
    assert len(batched.kernels) == 1
    assert rt_batched.rows == rt_split.rows


# -- device time transforms: epoch arithmetic compiles to EXACT device
# integer ops (plan._device_transform_rewrite; ref: the reference's
# vectorized datetime transforms, operator/transform/function/) ----------

TIME_TRANSFORM_QUERIES = [
    "SELECT sum(toEpochDays(runs)) FROM stats WHERE year > 2005",
    "SELECT team, sum(toEpochHours(runs)), max(runs) FROM stats "
    "GROUP BY team ORDER BY team",
    "SELECT sum(dateTrunc('minute', runs)) FROM stats",
    "SELECT sum(timeConvert(runs, 'MILLISECONDS', 'SECONDS')) FROM stats",
    "SELECT min(fromEpochSeconds(year)) FROM stats",
]


def test_time_transforms_plan_on_device(setup):
    """The plan must NOT fall back to the host path (PlanError = fail)."""
    from pinot_tpu.engine.plan import plan_segment

    _, segs = setup
    for sql in TIME_TRANSFORM_QUERIES:
        plan_segment(compile_query(sql), segs[0])


@pytest.mark.parametrize("sql", TIME_TRANSFORM_QUERIES,
                         ids=[q[:55] for q in TIME_TRANSFORM_QUERIES])
def test_time_transform_device_matches_host(setup, device_exec, host_exec,
                                            sql):
    _, segs = setup
    got = rows(device_exec, segs, sql)
    want = rows(host_exec, segs, sql)
    # integer-exact: the device computes these in i32/i64, not f32
    assert_rows_close(got, want, rel=1e-12)


GEXPR_QUERIES = [
    "SELECT toEpochDays(runs), sum(score), count(*) FROM stats "
    "GROUP BY toEpochDays(runs) ORDER BY toEpochDays(runs) LIMIT 1000",
    "SELECT dateTrunc('minute', runs), team, sum(runs) FROM stats "
    "GROUP BY dateTrunc('minute', runs), team "
    "ORDER BY dateTrunc('minute', runs), team LIMIT 1000",
    "SELECT year - 2000, count(*) FROM stats WHERE year >= 2002 "
    "GROUP BY year - 2000 ORDER BY year - 2000 LIMIT 100",
]


def test_gexpr_group_by_plans_on_device(setup):
    """Bounded integral expressions group on DEVICE (the time-bucket
    query shape; strategy 'gexpr' in plan._group_strategy)."""
    from pinot_tpu.engine.plan import plan_segment

    _, segs = setup
    for sql in GEXPR_QUERIES:
        plan = plan_segment(compile_query(sql), segs[0])
        assert any(s == "gexpr" for s, _ in plan.group_defs), sql


@pytest.mark.parametrize("sql", GEXPR_QUERIES,
                         ids=[q[:55] for q in GEXPR_QUERIES])
def test_gexpr_group_by_matches_host(setup, device_exec, host_exec, sql):
    _, segs = setup
    assert_rows_close(rows(device_exec, segs, sql),
                      rows(host_exec, segs, sql))


@pytest.mark.parametrize("sql", GEXPR_QUERIES,
                         ids=[q[:55] for q in GEXPR_QUERIES])
def test_gexpr_group_by_sharded(setup, host_exec, sql):
    """The sharded combine handles gexpr keys (value-space keys share the
    batch-wide base, so partials psum exactly)."""
    from pinot_tpu.parallel import ShardedQueryExecutor

    _, segs = setup
    dev = ShardedQueryExecutor()
    assert_rows_close(rows(dev, segs, sql), rows(host_exec, segs, sql))


# --------------------------------------------------------------------------
# param-protocol runtime mirror (PR 5: lint `protocol` family's dynamic half)
# --------------------------------------------------------------------------

def test_param_cursor_finish_flags_unconsumed_tail():
    from pinot_tpu.engine.kernels import _ParamCursor

    pc = _ParamCursor([1, 2])
    pc.take()
    with pytest.raises(AssertionError, match="pack/unpack drift"):
        pc.finish()
    pc.take()
    pc.finish()  # fully consumed: clean


def test_plan_pack_matches_expected_param_count(setup):
    """Every planned query packs exactly the params its spec consumes —
    the pack-time half of the protocol mirror (plan_segment asserts this
    internally; re-check it here so a relaxed assert can't rot)."""
    from pinot_tpu.engine.plan import expected_param_count, plan_segment

    _, segs = setup
    queries = [
        "SELECT count(*) FROM stats",
        "SELECT sum(salary), max(runs) FROM stats WHERE year > 2000",
        "SELECT team, sum(runs * 2) FROM stats "
        "WHERE league != 'AL' GROUP BY team",
    ]
    for sql in queries:
        plan = plan_segment(compile_query(sql), segs[0])
        assert len(plan.params) == expected_param_count(plan.spec), sql


def test_pack_time_drift_check_fires(setup, monkeypatch):
    """Seed pack/unpack drift (an eq predicate that packs TWO params) and
    prove plan_segment's length check catches it at plan time instead of
    letting the kernel silently mis-key."""
    from pinot_tpu.engine import plan as plan_mod

    _, segs = setup
    real = plan_mod._compile_predicate

    def drifted(pred, segment, params, columns):
        spec = real(pred, segment, params, columns)
        if spec[0] == "eq":
            params.append(np.int32(0))  # stray param: cursor drift
        return spec

    monkeypatch.setattr(plan_mod, "_compile_predicate", drifted)
    with pytest.raises(AssertionError, match="pack/unpack drift"):
        plan_mod.plan_segment(
            compile_query("SELECT count(*) FROM stats WHERE team = 'BOS'"),
            segs[0])
