"""Batch ingestion e2e: RecordReader SPI + readers + job runner + CLI.

Ref parity targets: RecordReader.java (SPI), CSVRecordReader/JSONRecordReader
(pinot-input-format), standalone SegmentGenerationJobRunner.java,
LaunchDataIngestionJobCommand, Quickstart.java — proven against the
reference's own baseballStats example configs
(/root/reference/pinot-tools/src/main/resources/examples/batch/baseballStats).
"""

import json
import os

import numpy as np
import pandas as pd
import pytest

from pinot_tpu.ingestion.batchjob import (
    SegmentGenerationJobRunner,
    SegmentGenerationJobSpec,
    run_ingestion_job,
)
from pinot_tpu.ingestion.readers import create_record_reader
from pinot_tpu.spi import Schema
from pinot_tpu.spi.table import TableConfig
from pinot_tpu.tools.cluster import EmbeddedCluster

REF_EXAMPLE = ("/root/reference/pinot-tools/src/main/resources/examples/"
               "batch/baseballStats")
REF_TEAMS_CSV = ("/root/reference/pinot-core/src/test/resources/data/"
                 "dimBaseballTeams.csv")


def _synth_baseball_csv(path: str, n: int, seed: int) -> pd.DataFrame:
    """Synthesized rawdata for the reference's baseballStats schema (the
    checkout ships the schema/table-config/jobspec but not the CSV)."""
    schema = Schema.from_file(f"{REF_EXAMPLE}/baseballStats_schema.json")
    rng = np.random.default_rng(seed)
    cols = {}
    for fs in schema.field_specs:
        if fs.data_type.is_numeric:
            cols[fs.name] = rng.integers(0, 100, n)
        elif fs.name == "league":
            cols[fs.name] = np.array(["AL", "NL"])[rng.integers(0, 2, n)]
        elif fs.name == "teamID":
            cols[fs.name] = np.array(["BOS", "NYA", "SFN"])[
                rng.integers(0, 3, n)]
        else:
            cols[fs.name] = np.array([f"{fs.name}_{i % 17}"
                                      for i in range(n)])
    df = pd.DataFrame(cols)
    df.to_csv(path, index=False)
    return df


def test_reference_jobspec_parses():
    spec = SegmentGenerationJobSpec.from_yaml(
        f"{REF_EXAMPLE}/ingestionJobSpec.yaml")
    assert spec.job_type == "SegmentCreationAndTarPush"
    assert spec.include_file_name_pattern == "glob:**/*.csv"
    assert spec.input_dir_uri.endswith("baseballStats/rawdata")
    assert spec.data_format == "csv"


def test_baseball_quickstart_e2e(tmp_path):
    """The SURVEY.md minimum end-to-end slice: reference configs -> CSV ->
    job runner -> embedded cluster -> SQL answers match pandas."""
    raw = tmp_path / "rawdata"
    raw.mkdir()
    df1 = _synth_baseball_csv(str(raw / "part1.csv"), 700, seed=1)
    df2 = _synth_baseball_csv(str(raw / "part2.csv"), 500, seed=2)
    df = pd.concat([df1, df2], ignore_index=True)

    job = {
        "jobType": "SegmentCreationAndTarPush",
        "inputDirURI": "rawdata",
        "includeFileNamePattern": "glob:**/*.csv",
        "outputDirURI": "segments",
        "tableSpec": {
            "tableName": "baseballStats",
            "schemaURI": f"{REF_EXAMPLE}/baseballStats_schema.json",
            "tableConfigURI":
                f"{REF_EXAMPLE}/baseballStats_offline_table_config.json",
        },
        "recordReaderSpec": {"dataFormat": "csv"},
    }
    import yaml

    spec_file = tmp_path / "jobSpec.yaml"
    spec_file.write_text(yaml.safe_dump(job))

    schema = Schema.from_file(f"{REF_EXAMPLE}/baseballStats_schema.json")
    table_config = TableConfig.from_file(
        f"{REF_EXAMPLE}/baseballStats_offline_table_config.json")
    cluster = EmbeddedCluster(num_servers=2,
                              data_dir=str(tmp_path / "cluster"))
    try:
        cluster.create_table(table_config, schema)
        seg_dirs = run_ingestion_job(str(spec_file), cluster=cluster)
        assert len(seg_dirs) == 2
        assert cluster.wait_for_ev_converged("baseballStats_OFFLINE")

        rows = cluster.query_rows("SELECT count(*) FROM baseballStats")
        assert rows[0][0] == len(df)

        rows = cluster.query_rows(
            "SELECT league, sum(homeRuns), count(*) FROM baseballStats "
            "GROUP BY league ORDER BY league")
        exp = df.groupby("league").agg(hr=("homeRuns", "sum"),
                                       n=("homeRuns", "size")).sort_index()
        assert [r[0] for r in rows] == list(exp.index)
        assert [r[1] for r in rows] == pytest.approx(list(exp.hr))
        assert [r[2] for r in rows] == list(exp.n)

        rows = cluster.query_rows(
            "SELECT playerName, sum(runs) FROM baseballStats "
            "WHERE teamID = 'BOS' GROUP BY playerName "
            "ORDER BY sum(runs) DESC LIMIT 5")
        exp = (df[df.teamID == "BOS"].groupby("playerName").runs.sum()
               .sort_values(ascending=False).head(5))
        assert rows[0][1] == pytest.approx(exp.iloc[0])
    finally:
        cluster.shutdown()


def test_real_reference_csv(tmp_path):
    """Ingest an actual CSV shipped in the reference checkout."""
    schema = Schema.from_dict({
        "schemaName": "dimBaseballTeams",
        "dimensionFieldSpecs": [
            {"name": "teamID", "dataType": "STRING"},
            {"name": "teamName", "dataType": "STRING"},
        ]})
    spec = SegmentGenerationJobSpec(
        input_dir_uri=os.path.dirname(REF_TEAMS_CSV),
        include_file_name_pattern="glob:dimBaseballTeams.csv",
        output_dir_uri=str(tmp_path / "segments"),
        table_name="dimBaseballTeams", data_format="csv")
    seg_dirs = SegmentGenerationJobRunner(spec, schema=schema).run()
    assert len(seg_dirs) == 1

    from pinot_tpu.engine import ServerQueryExecutor
    from pinot_tpu.query import compile_query
    from pinot_tpu.segment import load_segment

    seg = load_segment(seg_dirs[0])
    df = pd.read_csv(REF_TEAMS_CSV)
    assert seg.num_docs == len(df)
    ex = ServerQueryExecutor(use_device=False)
    rt, _ = ex.execute(compile_query(
        "SELECT count(*), distinctcount(teamID) FROM dimBaseballTeams"), [seg])
    assert rt.rows[0] == [len(df), df.teamID.nunique()]
    rt, _ = ex.execute(compile_query(
        "SELECT teamName FROM dimBaseballTeams WHERE teamID = 'BOS'"), [seg])
    assert rt.rows[0][0] == df[df.teamID == "BOS"].teamName.iloc[0]


def test_json_and_mv_csv_readers(tmp_path):
    jl = tmp_path / "rows.jsonl"
    jl.write_text('{"a": "x", "n": 1}\n{"a": "y", "n": 2}\n')
    rows = list(create_record_reader(str(jl)))
    assert rows == [{"a": "x", "n": 1}, {"a": "y", "n": 2}]

    arr = tmp_path / "rows.json"
    arr.write_text('[{"a": "x"}, {"a": "z", "tags": ["t1", "t2"]}]')
    rows = list(create_record_reader(str(arr)))
    assert rows[1]["tags"] == ["t1", "t2"]

    mv = tmp_path / "mv.csv"
    mv.write_text("name,tags\nbob,red;blue\neve,green\n")
    rows = list(create_record_reader(str(mv)))
    assert rows[0]["tags"] == ["red", "blue"]
    assert rows[1]["tags"] == "green"
    cols = create_record_reader(str(mv)).read_columnar()
    assert cols["tags"] == [["red", "blue"], "green"]


def test_parquet_reader(tmp_path):
    pq_file = tmp_path / "rows.parquet"
    df = pd.DataFrame({"city": ["sf", "nyc"], "v": [1, 2]})
    df.to_parquet(pq_file)
    reader = create_record_reader(str(pq_file))
    assert list(reader) == [{"city": "sf", "v": 1}, {"city": "nyc", "v": 2}]
    cols = reader.read_columnar()
    assert list(cols["city"]) == ["sf", "nyc"]


def test_cli_quickstart(tmp_path, capsys):
    """Quickstart subcommand over a reference-layout example dir."""
    from pinot_tpu.tools.admin import main

    example = tmp_path / "example"
    raw = example / "rawdata"
    raw.mkdir(parents=True)
    df = _synth_baseball_csv(str(raw / "data.csv"), 300, seed=9)
    import shutil

    shutil.copy(f"{REF_EXAMPLE}/baseballStats_schema.json", example)
    shutil.copy(f"{REF_EXAMPLE}/baseballStats_offline_table_config.json",
                example)
    import yaml

    (example / "ingestionJobSpec.yaml").write_text(yaml.safe_dump({
        "jobType": "SegmentCreationAndTarPush",
        "inputDirURI": "rawdata",
        "includeFileNamePattern": "glob:**/*.csv",
        "outputDirURI": "segments",
        "tableSpec": {"tableName": "baseballStats"},
        "recordReaderSpec": {"dataFormat": "csv"},
    }))
    rc = main(["Quickstart", "-exampleDir", str(example),
               "-dataDir", str(tmp_path / "qs"),
               "-query", "SELECT count(*) FROM baseballStats"])
    assert rc == 0
    out = capsys.readouterr().out.strip().splitlines()
    resp = json.loads(out[-1])
    assert resp["resultTable"]["rows"][0][0] == len(df)


def test_cli_ingestion_job_command(tmp_path, capsys):
    """LaunchDataIngestionJob subcommand builds segments standalone."""
    from pinot_tpu.tools.admin import main

    raw = tmp_path / "rawdata"
    raw.mkdir()
    _synth_baseball_csv(str(raw / "d.csv"), 100, seed=4)
    import yaml

    spec_file = tmp_path / "job.yaml"
    spec_file.write_text(yaml.safe_dump({
        "jobType": "SegmentCreation",
        "inputDirURI": "rawdata",
        "includeFileNamePattern": "glob:**/*.csv",
        "outputDirURI": "out",
        "tableSpec": {
            "tableName": "baseballStats",
            "schemaURI": f"{REF_EXAMPLE}/baseballStats_schema.json"},
        "recordReaderSpec": {"dataFormat": "csv"},
    }))
    rc = main(["LaunchDataIngestionJob", "-jobSpecFile", str(spec_file)])
    assert rc == 0
    seg_dir = capsys.readouterr().out.strip().splitlines()[0]
    assert os.path.isdir(seg_dir)
    from pinot_tpu.segment import load_segment

    assert load_segment(seg_dir).num_docs == 100


def test_sv_string_with_semicolon_survives(tmp_path):
    """MV splitting is schema-aware: ';' inside an SV string is data, not a
    delimiter (regression: every cell used to split)."""
    csv_file = tmp_path / "d.csv"
    csv_file.write_text("name,tags\na;b,x;y\nplain,z\n")
    schema = Schema.from_dict({
        "schemaName": "t",
        "dimensionFieldSpecs": [
            {"name": "name", "dataType": "STRING"},
            {"name": "tags", "dataType": "STRING",
             "singleValueField": False},
        ]})
    spec = SegmentGenerationJobSpec(
        input_dir_uri=str(tmp_path), include_file_name_pattern="glob:*.csv",
        output_dir_uri=str(tmp_path / "out"), table_name="t",
        data_format="csv")
    seg_dirs = SegmentGenerationJobRunner(spec, schema=schema).run()
    from pinot_tpu.segment import load_segment

    seg = load_segment(seg_dirs[0])
    assert seg.get_value("name", 0) == "a;b"          # SV: intact
    assert list(seg.get_value("tags", 0)) == ["x", "y"]  # MV: split


def test_missing_csv_column_null_fills(tmp_path):
    """A schema column absent from the CSV header null-fills instead of
    crashing the columnar fast path."""
    csv_file = tmp_path / "d.csv"
    csv_file.write_text("a\nx\ny\n")
    schema = Schema.from_dict({
        "schemaName": "t",
        "dimensionFieldSpecs": [
            {"name": "a", "dataType": "STRING"},
            {"name": "missing", "dataType": "STRING"},
        ]})
    spec = SegmentGenerationJobSpec(
        input_dir_uri=str(tmp_path), include_file_name_pattern="glob:*.csv",
        output_dir_uri=str(tmp_path / "out"), table_name="t",
        data_format="csv")
    seg_dirs = SegmentGenerationJobRunner(spec, schema=schema).run()
    from pinot_tpu.segment import load_segment

    seg = load_segment(seg_dirs[0])
    assert seg.num_docs == 2
    assert seg.metadata.column("missing").has_nulls


def test_nulls_survive_transform_path(tmp_path):
    """JSON ingest (row path) must keep the null bitmap: defaults
    substituted by NullValueTransformer are not real values."""
    jl = tmp_path / "d.jsonl"
    jl.write_text('{"a": "x", "n": 5}\n{"a": "y"}\n')
    schema = Schema.from_dict({
        "schemaName": "t",
        "dimensionFieldSpecs": [{"name": "a", "dataType": "STRING"}],
        "metricFieldSpecs": [{"name": "n", "dataType": "LONG"}]})
    spec = SegmentGenerationJobSpec(
        input_dir_uri=str(tmp_path), include_file_name_pattern="glob:*.jsonl",
        output_dir_uri=str(tmp_path / "out"), table_name="t",
        data_format="jsonl")
    seg_dirs = SegmentGenerationJobRunner(spec, schema=schema).run()
    from pinot_tpu.engine import ServerQueryExecutor
    from pinot_tpu.query import compile_query
    from pinot_tpu.segment import load_segment

    seg = load_segment(seg_dirs[0])
    assert seg.metadata.column("n").has_nulls
    ex = ServerQueryExecutor(use_device=False)
    rt, _ = ex.execute(compile_query(
        "SELECT count(*) FROM t WHERE n IS NOT NULL"), [seg])
    assert rt.rows[0][0] == 1


def test_glob_star_does_not_cross_directories(tmp_path):
    """'glob:*.csv' is root-only (java glob semantics); '**/*.csv' recurses."""
    from pinot_tpu.ingestion.batchjob import _match_glob

    (tmp_path / "root.csv").write_text("a\n1\n")
    sub = tmp_path / "archive"
    sub.mkdir()
    (sub / "old.csv").write_text("a\n1\n")
    assert [os.path.basename(p)
            for p in _match_glob(str(tmp_path), "glob:*.csv")] == ["root.csv"]
    assert len(_match_glob(str(tmp_path), "glob:**/*.csv")) == 2
    assert [os.path.basename(p) for p in _match_glob(
        str(tmp_path), "glob:**/*.csv", exclude="glob:archive/*")] == \
        ["root.csv"]


def test_glob_braces_and_classes(tmp_path):
    from pinot_tpu.ingestion.batchjob import _match_glob

    for name in ("a.csv", "b.json", "c.txt", "d1.csv"):
        (tmp_path / name).write_text("x\n1\n")
    got = [os.path.basename(p)
           for p in _match_glob(str(tmp_path), "glob:*.{csv,json}")]
    assert got == ["a.csv", "b.json", "d1.csv"]
    got = [os.path.basename(p)
           for p in _match_glob(str(tmp_path), "glob:[ab].*")]
    assert got == ["a.csv", "b.json"]


def test_columnar_path_sanitizes(tmp_path):
    """NUL stripping + maxLength truncation apply on the columnar fast
    path too (regression: only the row path sanitized)."""
    csv_file = tmp_path / "d.csv"
    long = "x" * 600
    csv_file.write_text(f"a\nhas\x00nul\n{long}\n")
    schema = Schema.from_dict({
        "schemaName": "t",
        "dimensionFieldSpecs": [
            {"name": "a", "dataType": "STRING", "maxLength": 512}]})
    spec = SegmentGenerationJobSpec(
        input_dir_uri=str(tmp_path), include_file_name_pattern="glob:*.csv",
        output_dir_uri=str(tmp_path / "out"), table_name="t",
        data_format="csv")
    seg_dirs = SegmentGenerationJobRunner(spec, schema=schema).run()
    from pinot_tpu.segment import load_segment

    seg = load_segment(seg_dirs[0])
    assert seg.get_value("a", 0) == "hasnul"
    assert len(seg.get_value("a", 1)) == 512


def test_parquet_missing_column_null_fills(tmp_path):
    pq_file = tmp_path / "d.parquet"
    pd.DataFrame({"a": ["x", "y"]}).to_parquet(pq_file)
    reader = create_record_reader(str(pq_file),
                                  fields_to_read=["a", "missing"])
    assert list(reader) == [{"a": "x", "missing": None},
                            {"a": "y", "missing": None}]
    cols = reader.read_columnar()
    assert cols["missing"] == [None, None]


def test_empty_csv_raises_meaningfully(tmp_path):
    (tmp_path / "empty.csv").write_text("")
    with pytest.raises(ValueError, match="empty CSV"):
        create_record_reader(str(tmp_path / "empty.csv"))


def test_glob_braces_with_wildcards(tmp_path):
    from pinot_tpu.ingestion.batchjob import _match_glob

    for name in ("a.csv", "b.json", "c.txt"):
        (tmp_path / name).write_text("x\n1\n")
    got = [os.path.basename(p)
           for p in _match_glob(str(tmp_path), "glob:{*.csv,*.json}")]
    assert got == ["a.csv", "b.json"]


def test_job_parallelism_builds_all_segments(tmp_path):
    """segmentCreationJobParallelism > 1: per-file builds run in a process
    pool; every matched file still becomes exactly one segment (ref: the
    runner's ExecutorService fan-out)."""
    import numpy as np

    from pinot_tpu.ingestion.batchjob import (
        SegmentGenerationJobRunner,
        SegmentGenerationJobSpec,
    )
    from pinot_tpu.segment import load_segment
    from pinot_tpu.spi import DataType, FieldSpec, FieldType, Schema

    inp = tmp_path / "in"
    inp.mkdir()
    rng = np.random.default_rng(2)
    for i in range(4):
        lines = ["k,v"] + [f"k{j % 3},{int(rng.integers(0, 9))}"
                           for j in range(200)]
        (inp / f"part{i}.csv").write_text("\n".join(lines))
    schema = Schema("pj", [
        FieldSpec("k", DataType.STRING),
        FieldSpec("v", DataType.LONG, FieldType.METRIC)])
    spec = SegmentGenerationJobSpec(
        input_dir_uri=str(inp), include_file_name_pattern="glob:**/*.csv",
        output_dir_uri=str(tmp_path / "out"), table_name="pj",
        data_format="csv", parallelism=4)
    dirs = SegmentGenerationJobRunner(spec, schema=schema).run()
    assert len(dirs) == 4
    total = sum(load_segment(d).num_docs for d in dirs)
    assert total == 800
