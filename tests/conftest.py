"""Test bootstrap: run the whole suite on a virtual 8-device CPU mesh.

Multi-chip TPU hardware is unavailable in CI; sharding correctness is
validated on XLA's host platform with 8 virtual devices (the same mechanism
the driver's ``dryrun_multichip`` uses).
"""

import os

FORCED_HOST_DEVICES = 8


def _force_host_devices(n: int = FORCED_HOST_DEVICES) -> None:
    """Force ``n`` virtual CPU devices BEFORE jax initializes a backend.

    Subprocess-safe: the flag is appended to ``os.environ['XLA_FLAGS']``
    (inherited by every child process — spawn-pool segment builders, bench
    workers), idempotent (a flag already present, ours or the caller's, is
    left alone), and pinned to CPU via BOTH the env var and
    ``jax.config`` — the environment presets JAX_PLATFORMS=axon (the
    real-TPU tunnel) and the axon plugin overrides the env var, so
    jax.config.update is the only reliable way to force CPU here.
    """
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}").strip()
    os.environ["JAX_PLATFORMS"] = "cpu"


_force_host_devices()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "lint: graftlint static-analysis gate (pytest -m lint runs just "
        "the invariant checkers)")
    config.addinivalue_line(
        "markers",
        "startree: star-tree pre-aggregation rung (pytest -m startree "
        "exercises build/plan/device-exec in isolation; part of tier-1)")
    config.addinivalue_line(
        "markers",
        "residency_tier: tiered residency (host-RAM spill tier, "
        "restage-cost-aware eviction, budget-sliced sharded combine; "
        "pytest -m residency_tier runs it in isolation; part of tier-1)")
    config.addinivalue_line(
        "markers",
        "trace: query lifecycle tracing (span trees, decision ledger, "
        "slow-query log; pytest -m trace runs it in isolation; part of "
        "tier-1)")
    config.addinivalue_line(
        "markers",
        "telemetry: continuous telemetry (windowed histograms, SLO burn "
        "tracking, flight recorder; pytest -m telemetry runs it in "
        "isolation; part of tier-1)")
    config.addinivalue_line(
        "markers",
        "pallas: fused Pallas scan kernel (interpret-mode parity, SSB-13 "
        "eligibility, group-range probe narrowing; pytest -m pallas runs "
        "it in isolation; part of tier-1)")
    config.addinivalue_line(
        "markers",
        "cluster_routing: partition-aware scatter routing + replica "
        "groups + partial-result gather + the sharded combine on the "
        "forced multi-device mesh (pytest -m cluster_routing runs it in "
        "isolation; part of tier-1)")
    config.addinivalue_line(
        "markers",
        "reduce: array-native broker reduce (columnar DataTables, "
        "vectorized merge parity vs the row-path oracle, "
        "reduce-as-arrivals; pytest -m reduce runs it in isolation; "
        "part of tier-1)")
    config.addinivalue_line(
        "markers",
        "pallas_preflight: kernel preflight (static lowering model over "
        "the SSB plan space + fuzz grid, interpret-mode cross-check, "
        "blocklist seeding/persistence; pytest -m pallas_preflight runs "
        "it in isolation; part of tier-1)")
    config.addinivalue_line(
        "markers",
        "reduce_device: device-resident broker reduce (group-by merge "
        "over the forced 8-virtual-device mesh, SSB parity vs the "
        "vectorized host path and the row oracle, decline-shape "
        "fixtures; pytest -m reduce_device runs it in isolation; part "
        "of tier-1)")
    config.addinivalue_line(
        "markers",
        "realtime_tier: realtime serving tier (device-queryable "
        "consuming segments, watermark-snapshot parity, seal-under-query "
        "hammer, hybrid time-boundary routing, freshness SLO; pytest "
        "-m realtime_tier runs it in isolation; part of tier-1)")
    config.addinivalue_line(
        "markers",
        "index_rung: index-accelerated selective filters (host docId "
        "resolution over inverted/sorted/range indexes, device gather "
        "kernel parity vs scan and host oracle, residency pinning, "
        "decision-ledger exactness; pytest -m index_rung runs it in "
        "isolation; part of tier-1)")


@pytest.fixture(scope="session")
def eight_devices():
    import jax

    devs = jax.devices()
    assert len(devs) >= 8, f"expected >=8 virtual devices, got {devs}"
    return devs


@pytest.fixture(scope="session")
def forced_mesh_devices(eight_devices):
    """The conftest-forced virtual device set the multi-device mesh tests
    build their ``Mesh`` from (see ``_force_host_devices``: env-flag based,
    so spawn subprocesses — segment builders, bench workers — inherit the
    same device count)."""
    return eight_devices
