"""Test bootstrap: run the whole suite on a virtual 8-device CPU mesh.

Multi-chip TPU hardware is unavailable in CI; sharding correctness is
validated on XLA's host platform with 8 virtual devices (the same mechanism
the driver's ``dryrun_multichip`` uses).
"""

import os

# Must be set before jax is imported anywhere.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def eight_devices():
    import jax

    devs = jax.devices()
    assert len(devs) >= 8, f"expected >=8 virtual devices, got {devs}"
    return devs
