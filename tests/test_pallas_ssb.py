"""SSB-13 fused-kernel eligibility + interpret-mode parity (tier-1).

The acceptance suite for the zero-decline pallas SSB goal: every one of
the 13 SSB flights must extract an eligible pallas plan (Q3.2/Q4.3 via the
group-range probe narrowing), run the fused kernel in interpret mode on
CPU, and match the jnp kernel bit-for-bit — packed f64 vector equality
where the layouts coincide, exact decoded-group equality for the
probe-narrowed shapes whose packed layout is the narrowed dense one.
Fixtures deliberately use a REMAINDER-TILE capacity (padded_capacity not a
multiple of PALLAS_TILE) and an i64-staged value column, the two shapes the
widened eligibility must cover.
"""

import numpy as np
import pytest

from pinot_tpu.engine import ensure_x64

ensure_x64()

from pinot_tpu.common.tracing import LEDGER, parse_decision_key
from pinot_tpu.engine import ServerQueryExecutor
from pinot_tpu.engine.kernels import build_kernel, unpack_outputs
from pinot_tpu.engine.pallas_kernels import (
    MAX_PALLAS_GROUPS,
    extract_plan,
    run_segment,
)
from pinot_tpu.engine.plan import plan_segment
from pinot_tpu.engine.staging import PALLAS_TILE, StagingCache
from pinot_tpu.query import compile_query
from pinot_tpu.segment import SegmentBuilder, load_segment
from pinot_tpu.spi import DataType, FieldSpec, FieldType, Schema
from pinot_tpu.tools import ssb

pytestmark = pytest.mark.pallas

# 2 segments x 9000 rows -> padded_capacity 9216 (x1024), which is NOT a
# multiple of PALLAS_TILE (4096): every kernel in this suite carries a
# masked remainder tile
ROWS = 18_000

# the two flights whose composed key space exceeds MAX_PALLAS_GROUPS until
# the group-range probe narrows it
NARROWED = ("Q3.2", "Q4.3")


@pytest.fixture(scope="module")
def ssb_segs(tmp_path_factory):
    out = tmp_path_factory.mktemp("pallas_ssb")
    return ssb.build_segments(0, str(out), num_segments=2, rows=ROWS)


@pytest.fixture(scope="module")
def ctxs():
    # explicit LIMIT: full group sets, same as bench.py
    return {qid: compile_query(q + " LIMIT 100000")
            for qid, q in ssb.QUERIES.items()}


@pytest.fixture(scope="module")
def pallas_cache():
    from pinot_tpu.engine.pallas_kernels import PallasKernelCache

    return PallasKernelCache()


def test_fixture_has_remainder_tiles(ssb_segs):
    assert ssb_segs[0].padded_capacity % PALLAS_TILE != 0


def test_all_13_extract_eligible(ssb_segs, ctxs):
    """Every SSB flight extracts an eligible plan at the extract level —
    directly for 11, and for Q3.2/Q4.3 the ONLY obstacle is the group
    bound the probe removes."""
    for qid, ctx in ctxs.items():
        reasons = []
        plan = plan_segment(ctx, ssb_segs[0])
        pp = extract_plan(plan, ssb_segs[0], on_decline=reasons.append)
        if qid in NARROWED:
            assert pp is None and reasons == ["pallas_too_many_groups"], \
                (qid, reasons)
            # the probe path's precondition: the unchecked extraction
            # (filter/values/aggs) is fully eligible
            assert extract_plan(plan, ssb_segs[0],
                                unchecked_groups=True) is not None, qid
        else:
            assert pp is not None, (qid, reasons)


def test_all_13_run_segment_zero_declines(ssb_segs, ctxs, pallas_cache):
    """run_segment serves every flight (probe narrowing included) without
    a single decline."""
    staged = StagingCache().stage(ssb_segs[0])
    for qid, ctx in ctxs.items():
        reasons = []
        plan = plan_segment(ctx, ssb_segs[0])
        served = run_segment(plan, staged, pallas_cache, interpret=True,
                             on_decline=reasons.append)
        assert served is not None and not reasons, (qid, reasons)
        packed, eff = served
        if qid in NARROWED:
            assert eff is not plan
            assert eff.num_groups <= MAX_PALLAS_GROUPS
            assert getattr(eff, "_narrowed_from") == plan.spec
        else:
            assert eff is plan


@pytest.mark.parametrize("qid", sorted(ssb.QUERIES))
def test_ssb13_bit_parity_vs_jnp(ssb_segs, ctxs, pallas_cache, qid):
    """Per segment: the fused kernel's PACKED output is bit-identical to
    the jnp kernel's (same f64 vector where the spec coincides; exact
    decoded-group equality for the probe-narrowed shapes, whose packed
    layout is the narrowed dense one while jnp's is the sparse compact)."""
    from pinot_tpu.engine.executor import decode_grouped_result

    ctx = ctxs[qid]
    for seg in ssb_segs:
        plan = plan_segment(ctx, seg)
        staged = StagingCache().stage(seg)
        served = run_segment(plan, staged, pallas_cache, interpret=True)
        assert served is not None, qid
        packed_pl, eff = served

        cols = {name: staged.column(name).tree() for name in plan.columns}
        packed_jnp = np.asarray(build_kernel(plan.spec)(
            cols, tuple(plan.params), np.int32(seg.num_docs)))

        if eff is plan:
            np.testing.assert_array_equal(np.asarray(packed_pl),
                                          packed_jnp, err_msg=qid)
        else:
            got = decode_grouped_result(
                eff, seg, unpack_outputs(np.asarray(packed_pl), eff.spec))
            want = decode_grouped_result(
                plan, seg, unpack_outputs(packed_jnp, plan.spec))
            assert got.groups == want.groups, qid


def test_sharded_all_13_parity_and_zero_declines(ssb_segs, ctxs):
    """The serving path: every flight through the sharded executor with
    pallas on matches the host engine exactly, the decline histogram
    records ZERO pallas entries, and the fused kernels actually fired."""
    from pinot_tpu.parallel import ShardedQueryExecutor

    dev = ShardedQueryExecutor(use_pallas=True)
    host = ServerQueryExecutor(use_device=False)
    mark = LEDGER.snapshot()
    for qid in sorted(ssb.QUERIES):
        # useStarTree=false: Q2.x must exercise the pallas scan here, not
        # the pre-agg rung (the star-tree suite covers that path)
        sql = ssb.QUERIES[qid] + " LIMIT 100000 OPTION(useStarTree=false)"
        got, stats = dev.execute(compile_query(sql), ssb_segs)
        want, _ = host.execute(compile_query(sql), ssb_segs)
        assert sorted(map(tuple, got.rows)) == sorted(map(tuple, want.rows)), qid
    delta = LEDGER.delta(mark)
    pallas = {k: v for k, v in delta.items()
              if parse_decision_key(k)[0] == "pallas"}
    assert not pallas, pallas
    assert len(dev._pallas_sharded) > 0


def test_narrow_declines_when_probe_cannot_shrink(tmp_path):
    """Adversarial shape: unfiltered high-card group columns keep their
    full ranges under the probe, so the narrowed product still exceeds
    the bound — a CLASSIFIED decline, never a wrong result."""
    rng = np.random.default_rng(5)
    n = 6000
    vals = [f"v{i:04d}" for i in range(600)]
    schema = Schema("wide", [FieldSpec("a", DataType.STRING),
                             FieldSpec("b", DataType.STRING),
                             FieldSpec("qty", DataType.INT,
                                       FieldType.METRIC)])
    frame = {"a": np.array(vals)[rng.integers(0, 600, n)],
             "b": np.array(vals)[rng.integers(0, 600, n)],
             "qty": rng.integers(1, 50, n).astype(np.int64)}
    b = SegmentBuilder(schema, "wide_0")
    b.build(frame, str(tmp_path))
    seg = load_segment(str(tmp_path / "wide_0"))

    from pinot_tpu.engine.pallas_kernels import PallasKernelCache

    plan = plan_segment(compile_query(
        "SELECT a, b, sum(qty) FROM wide GROUP BY a, b LIMIT 400000"), seg)
    reasons = []
    served = run_segment(plan, StagingCache().stage(seg),
                         PallasKernelCache(), interpret=True,
                         on_decline=reasons.append)
    assert served is None
    assert reasons == ["pallas_too_many_groups"]


# -- i64-staged value columns (limb planes at the value-load layer) --------

@pytest.fixture(scope="module")
def i64_segs(tmp_path_factory):
    out = tmp_path_factory.mktemp("pallas_i64")
    rng = np.random.default_rng(9)
    n = 9_000   # 4500/segment -> remainder tile again
    schema = Schema("big64", [
        FieldSpec("k", DataType.STRING),
        FieldSpec("big", DataType.LONG, FieldType.METRIC),
        FieldSpec("qty", DataType.INT, FieldType.METRIC),
    ])
    frame = {
        "k": np.array(["a", "b", "c"])[rng.integers(0, 3, n)],
        # values far beyond i32 -> staged_int_dtype int64 -> limb planes
        "big": (rng.integers(0, 1 << 40, n) - (1 << 39)).astype(np.int64),
        "qty": rng.integers(1, 50, n).astype(np.int64),
    }
    segs = []
    for i, sl in enumerate([slice(0, n // 2), slice(n // 2, n)]):
        b = SegmentBuilder(schema, f"big64_{i}")
        b.build({c: v[sl] for c, v in frame.items()}, str(out))
        segs.append(load_segment(str(out / f"big64_{i}")))
    return frame, segs


I64_QUERIES = [
    "SELECT sum(big) FROM big64",
    "SELECT k, sum(big), count(*) FROM big64 GROUP BY k ORDER BY k",
    "SELECT sum(big), avg(big) FROM big64 WHERE qty > 25",
]


def test_i64_value_eligible_with_limb_planes(i64_segs):
    _, segs = i64_segs
    for sql in I64_QUERIES:
        plan = plan_segment(compile_query(sql), segs[0])
        reasons = []
        pp = extract_plan(plan, segs[0], on_decline=reasons.append)
        assert pp is not None, (sql, reasons)
        assert any(l > 0 for l in pp.value_limbs), sql


@pytest.mark.parametrize("sql", I64_QUERIES, ids=[q[:50] for q in I64_QUERIES])
def test_i64_value_sums_exact(i64_segs, sql):
    """Limb-plane accumulation is EXACT (integer equality vs the host
    engine's int64 math), per-segment and sharded."""
    from pinot_tpu.parallel import ShardedQueryExecutor

    _, segs = i64_segs
    dev = ServerQueryExecutor(use_device=True, use_pallas=True)
    sh = ShardedQueryExecutor(use_pallas=True)
    host = ServerQueryExecutor(use_device=False)
    want, _ = host.execute(compile_query(sql), segs)
    got, _ = dev.execute(compile_query(sql), segs)
    shg, _ = sh.execute(compile_query(sql), segs)
    assert got.rows == want.rows, sql
    assert shg.rows == want.rows, sql


def test_i64_sum_matches_numpy_exactly(i64_segs):
    frame, segs = i64_segs
    dev = ServerQueryExecutor(use_device=True, use_pallas=True)
    got, _ = dev.execute(compile_query("SELECT sum(big) FROM big64"), segs)
    assert float(got.rows[0][0]) == float(int(frame["big"].sum()))


# -- many-run LUT predicates (the interval-set fallback) -------------------

def test_lut_interval_set_fallback(ssb_segs):
    """An IN over many scattered cities exceeds the static leaf budget but
    rides the padded interval-set node — eligible, exact, and the
    over-cap decline stays classified."""
    cities = sorted({c for c in np.asarray(
        ssb_segs[0].data_source("c_city").dictionary.get_values(
            range(ssb_segs[0].metadata.column("c_city").cardinality)))})
    picks = cities[::7][:24]   # scattered -> ~24 runs
    vals = ", ".join(f"'{c}'" for c in picks)
    sql = (f"SELECT sum(lo_revenue), count(*) FROM ssb_lineorder "
           f"WHERE c_city IN ({vals})")
    plan = plan_segment(compile_query(sql), ssb_segs[0])
    reasons = []
    pp = extract_plan(plan, ssb_segs[0], on_decline=reasons.append)
    assert pp is not None and not reasons
    assert any(node == "ivs" for node in _flatten_ops(pp.filter_tree))

    dev = ServerQueryExecutor(use_device=True, use_pallas=True)
    host = ServerQueryExecutor(use_device=False)
    got, _ = dev.execute(compile_query(sql), ssb_segs)
    want, _ = host.execute(compile_query(sql), ssb_segs)
    assert got.rows == want.rows

    # over the configured cap: a CLASSIFIED decline
    reasons = []
    pp = extract_plan(plan, ssb_segs[0], on_decline=reasons.append,
                      lut_run_cap=4)
    assert pp is None and reasons == ["pallas_lut_too_many_runs"]


def _flatten_ops(tree):
    out = [tree[0]]
    if tree[0] in ("and", "or", "not"):
        for c in tree[1]:
            out.extend(_flatten_ops(c))
    return out
