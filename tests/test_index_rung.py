"""Index-accelerated selective filters (PR 18): the docId-gather rung.

Parity contract: for any filter the rung accepts, the result must be
BIT-IDENTICAL to the scan rungs (``OPTION(useIndexRung=false)``) and the
host oracle — the gather feeds the very same ``build_kernel_body`` the
scan kernels run, minus the filter. ``num_docs_scanned`` must equal the
matched row count (the selectivity story user-facing SLOs are built on),
every decline must land in the ledger with a registered reason code, and
the pinned idx arrays must obey residency accounting/eviction.

Ref: BitmapBasedFilterOperator / SortedIndexBasedFilterOperator /
RangeIndexBasedFilterOperator — the reference's index-served filter
operators this rung re-shapes for the device.
"""

import numpy as np
import pytest

from pinot_tpu.common import tracing
from pinot_tpu.engine import ServerQueryExecutor
from pinot_tpu.query import compile_query
from pinot_tpu.spi import DataType, FieldSpec, FieldType, Schema

pytestmark = pytest.mark.index_rung

ROWS = 60_000
N_SEGS = 2

SERVED = "index:scan->index_gather:index_served"
DECLINED = "index:index_gather->scan:{}"
MUT_SERVED = "index:mutable_device->index_gather:mutable_index_served"
MUT_DECLINED = "index:index_gather->mutable_device:{}"


@pytest.fixture(scope="module")
def setup(tmp_path_factory):
    from pinot_tpu.tools import usertable

    out = tmp_path_factory.mktemp("index_rung_segs")
    segs = usertable.build_segments(str(out), num_segments=N_SEGS,
                                    rows=ROWS, workers=1)
    frame = {}
    per = ROWS // N_SEGS
    for i in range(N_SEGS):
        f = usertable.generate_frame(i, N_SEGS, per)
        for k, v in f.items():
            if k == "tags":
                frame.setdefault(k, []).extend(v)
            else:
                frame[k] = (v if k not in frame
                            else np.concatenate([frame[k], v]))
    dev = ServerQueryExecutor(use_device=True)
    host = ServerQueryExecutor(use_device=False)
    return segs, frame, dev, host


def _rows(result):
    return sorted(tuple(r) for r in result.rows)


def _run3(dev, host, segs, sql):
    """(index-run rows+stats, scan-rung rows, host-oracle rows)."""
    r_i, s_i = dev.execute(compile_query(sql), segs)
    r_s, _ = dev.execute(
        compile_query(sql + " OPTION(useIndexRung=false)"), segs)
    r_h, _ = host.execute(compile_query(sql), segs)
    return (r_i, s_i), _rows(r_s), _rows(r_h)


def _tail_user(frame, lo=3, hi=50):
    uniq, cnt = np.unique(frame["user_id"], return_counts=True)
    for u, c in zip(uniq.tolist(), cnt.tolist()):
        if lo <= c <= hi:
            return int(u), int(c)
    raise AssertionError("no tail user in range")


# -- parity across filter shapes --------------------------------------------

def test_eq_point_group_by_parity(setup):
    segs, frame, dev, host = setup
    u, c = _tail_user(frame)
    (r_i, s_i), scan, oracle = _run3(
        dev, host, segs,
        f"SELECT event_type, count(*), sum(revenue) FROM user_events "
        f"WHERE user_id = {u} GROUP BY event_type")
    assert _rows(r_i) == scan == oracle
    assert s_i.group_by_rung == "index"
    assert s_i.num_docs_scanned == c
    assert s_i.decisions.get(SERVED) == N_SEGS


def test_string_in_and_range_parity(setup):
    segs, frame, dev, host = setup
    u, _ = _tail_user(frame)
    for sql in (
        f"SELECT country, count(*), sum(num_items) FROM user_events "
        f"WHERE user_id IN ({u}, 987654321) GROUP BY country",
        f"SELECT count(*), sum(revenue) FROM user_events "
        f"WHERE user_id = {u} AND latency_ms BETWEEN 10 AND 200",
        f"SELECT count(*) FROM user_events WHERE user_id = {u} "
        f"AND event_type IN ('click', 'purchase')",
        f"SELECT device, count(*) FROM user_events WHERE user_id = {u} "
        f"AND country = 'US' GROUP BY device",
    ):
        (r_i, s_i), scan, oracle = _run3(dev, host, segs, sql)
        assert _rows(r_i) == scan == oracle, sql
        assert s_i.decisions.get(SERVED) == N_SEGS, (sql, s_i.decisions)


def test_mv_postings_union_parity(setup):
    """MV predicate: a tag's postings are the union over per-value lists —
    still index-served when selective enough (tags here are broad, so
    conjoin with the point filter; the MV route contributes its postings
    to the intersection)."""
    segs, frame, dev, host = setup
    u, _ = _tail_user(frame)
    (r_i, s_i), scan, oracle = _run3(
        dev, host, segs,
        f"SELECT count(*) FROM user_events WHERE user_id = {u} "
        f"AND tags = 'tag3'")
    assert _rows(r_i) == scan == oracle
    assert s_i.decisions.get(SERVED) == N_SEGS


def test_dict_encoded_sum_parity(setup):
    """SUM over a DICTIONARY-ENCODED numeric: the gather kernel must pass
    the dictId->value LUT through UNGATHERED (gathering dictvals by docId
    would corrupt every dict-encoded aggregation — the one column class
    the scan kernels index by dictId, not docId)."""
    segs, frame, dev, host = setup
    u, c = _tail_user(frame)
    (r_i, s_i), scan, oracle = _run3(
        dev, host, segs,
        f"SELECT sum(revenue), sum(num_items), min(revenue), max(revenue) "
        f"FROM user_events WHERE user_id = {u}")
    assert _rows(r_i) == scan == oracle
    m = frame["user_id"] == u
    assert _rows(r_i)[0][0] == float(frame["revenue"][m].sum())
    assert s_i.num_docs_scanned == c


def test_empty_match_is_index_served(setup):
    """An absent literal resolves to ZERO docIds — still index-served
    (scanned 0), identical to the scan rungs' empty result."""
    segs, _, dev, host = setup
    (r_i, s_i), scan, oracle = _run3(
        dev, host, segs,
        "SELECT count(*), sum(revenue) FROM user_events "
        "WHERE user_id = 987654321")
    assert _rows(r_i) == scan == oracle
    assert s_i.num_docs_scanned == 0
    # min/max pruning may eat segments before the rung sees them; every
    # unpruned segment must be index-served
    served = s_i.decisions.get(SERVED, 0)
    assert served >= 1
    assert served + s_i.num_segments_pruned == N_SEGS


def test_parity_fuzz_random_conjunctions(setup):
    """Randomized eq/IN/range conjunctions over indexed columns: every
    index-served query is bit-identical to scan and host, and
    docs_scanned equals the numpy-oracle match count."""
    segs, frame, dev, host = setup
    rng = np.random.default_rng(42)
    uniq = np.unique(frame["user_id"])
    served = 0
    for _ in range(12):
        u = int(uniq[rng.integers(0, uniq.size)])
        lo = int(rng.integers(1, 150))
        hi = lo + int(rng.integers(10, 300))
        preds = [f"user_id = {u}"]
        m = frame["user_id"] == u
        if rng.random() < 0.5:
            preds.append(f"latency_ms BETWEEN {lo} AND {hi}")
            m = m & (frame["latency_ms"] >= lo) & (frame["latency_ms"] <= hi)
        if rng.random() < 0.5:
            preds.append("event_type IN ('view', 'cart')")
            m = m & np.isin(frame["event_type"], ["view", "cart"])
        sql = (f"SELECT count(*), sum(revenue) FROM user_events "
               f"WHERE {' AND '.join(preds)}")
        (r_i, s_i), scan, oracle = _run3(dev, host, segs, sql)
        assert _rows(r_i) == scan == oracle, sql
        if s_i.decisions.get(SERVED) == N_SEGS:
            served += 1
            assert s_i.num_docs_scanned == int(m.sum()), sql
    assert served >= 8  # the mix is dominated by selective shapes


# -- declines: every one ledgered with the exact registered reason ----------

def test_over_threshold_declines_to_scan(setup):
    """A ~100%-selectivity filter must NOT ride the index rung: the cost
    gate declines (exact ledger reason) and the scan rungs serve with
    identical results."""
    segs, _, dev, host = setup
    sql = ("SELECT country, count(*) FROM user_events "
           "WHERE latency_ms >= 1 GROUP BY country")
    (r_i, s_i), scan, oracle = _run3(dev, host, segs, sql)
    assert _rows(r_i) == scan == oracle
    assert s_i.group_by_rung != "index"
    assert s_i.decisions.get(
        DECLINED.format("index_selectivity_over_threshold")) == N_SEGS
    assert SERVED not in s_i.decisions


def test_missing_index_declines(setup):
    """`device` carries a dictionary but no inverted index and is not
    sorted — the rung declines with the missing-index reason."""
    segs, _, dev, host = setup
    (r_i, s_i), scan, oracle = _run3(
        dev, host, segs,
        "SELECT count(*) FROM user_events WHERE device = 'ios'")
    assert _rows(r_i) == scan == oracle
    assert s_i.decisions.get(
        DECLINED.format("index_missing_index")) == N_SEGS


def test_or_shape_declines(setup):
    """Cross-column OR: indexes don't compose here (same-column OR
    normalizes to IN upstream and stays index-served — covered above)."""
    segs, frame, dev, host = setup
    u, _ = _tail_user(frame)
    (r_i, s_i), scan, oracle = _run3(
        dev, host, segs,
        f"SELECT count(*) FROM user_events WHERE user_id = {u} "
        f"OR device = 'ios'")
    assert _rows(r_i) == scan == oracle
    assert s_i.decisions.get(
        DECLINED.format("index_filter_shape")) == N_SEGS


def test_every_reason_code_is_registered(setup):
    """Ledger exactness: every index-point decision recorded by this
    module's workload uses a reason registered in
    tracing.INDEX_DECISION_REASONS (+ the mutable codes) — an
    unregistered reason is an unexplained fallback."""
    registered = tracing.registered_reason_codes()
    assert tracing.INDEX_DECISION_REASONS <= registered
    mark = tracing.LEDGER.snapshot()
    segs, frame, dev, host = setup
    u, _ = _tail_user(frame)
    for sql in (
        f"SELECT count(*) FROM user_events WHERE user_id = {u}",
        "SELECT count(*) FROM user_events WHERE latency_ms >= 1",
        "SELECT count(*) FROM user_events WHERE device = 'web'",
    ):
        dev.execute(compile_query(sql), segs)
    delta = tracing.LEDGER.delta(mark)
    index_keys = [k for k in delta if k.startswith("index:")]
    assert index_keys, delta
    for key in index_keys:
        _, _, _, reason = tracing.parse_decision_key(key)
        assert reason in registered, key


def test_operator_opt_out_is_silent(setup):
    """OPTION(useIndexRung=false) routes to the scan rungs with NO index
    decision recorded — an operator choice is not a decline."""
    segs, frame, dev, _ = setup
    u, _ = _tail_user(frame)
    _, s = dev.execute(compile_query(
        f"SELECT count(*) FROM user_events WHERE user_id = {u} "
        f"OPTION(useIndexRung=false)"), segs)
    assert not any(k.startswith("index:") for k in s.decisions)


# -- sorted-column route ----------------------------------------------------

def test_sorted_column_route(tmp_path):
    """A dict column whose values arrive sorted gets is_sorted metadata;
    EQ/range predicates resolve to contiguous docId runs by binary search
    (SortedIndexBasedFilterOperator's shape) — no inverted index needed."""
    from pinot_tpu.segment import SegmentBuilder, load_segment

    n = 20_000
    rng = np.random.default_rng(3)
    schema = Schema("sorted_t", [
        FieldSpec("k", DataType.INT, FieldType.DIMENSION),
        FieldSpec("v", DataType.INT, FieldType.METRIC),
    ])
    frame = {"k": np.sort(rng.integers(0, 2000, n)).astype(np.int64),
             "v": rng.integers(1, 100, n).astype(np.int64)}
    SegmentBuilder(schema, "sorted_0").build(frame, str(tmp_path))
    seg = load_segment(str(tmp_path / "sorted_0"))
    assert seg.metadata.column("k").is_sorted

    dev = ServerQueryExecutor(use_device=True)
    host = ServerQueryExecutor(use_device=False)
    k = int(frame["k"][n // 2])
    for sql in (
        f"SELECT count(*), sum(v) FROM sorted_t WHERE k = {k}",
        f"SELECT count(*) FROM sorted_t WHERE k IN ({k}, {k + 1})",
    ):
        r_i, s_i = dev.execute(compile_query(sql), [seg])
        r_h, _ = host.execute(compile_query(sql), [seg])
        assert _rows(r_i) == _rows(r_h), sql
        if s_i.decisions.get("index:scan->index_gather:index_served"):
            m = int((frame["k"] == k).sum()) if "=" in sql.split("WHERE")[1] \
                else 0
            assert s_i.num_docs_scanned > 0 or m == 0


# -- residency: pinned idx arrays under churn -------------------------------

def test_idx_slices_accounted_and_capped(setup):
    """Pinned idx arrays count into the resident's nbytes, survive repeat
    queries (cache hit), stay bounded under filter churn (LRU cap), and
    release() drops them."""
    segs, frame, dev, _ = setup
    seg = segs[0]
    uniq = np.unique(frame["user_id"])[:80]
    for u in uniq.tolist():
        dev.execute(compile_query(
            f"SELECT count(*) FROM user_events WHERE user_id = {int(u)}"),
            [seg])
    staged = dev.residency.stage(seg, lease=None)
    assert staged.index_nbytes() > 0
    assert len(staged._index_slices) <= 64  # _INDEX_SLICE_CAP
    total = staged.nbytes()
    assert total >= staged.index_nbytes()
    freed = staged.release_index_slices()
    assert freed > 0
    assert staged.index_nbytes() == 0
    # post-release queries still serve correctly (slices rebuild)
    u, c = _tail_user(frame)
    r, s = dev.execute(compile_query(
        f"SELECT count(*) FROM user_events WHERE user_id = {u}"), segs)
    assert s.decisions.get(SERVED) == N_SEGS
    assert r.rows[0][0] == c


def test_eviction_churn_keeps_parity(setup):
    """Evicting the resident between index-served queries forces restage +
    idx rebuild — results stay identical."""
    segs, frame, dev, host = setup
    u, c = _tail_user(frame)
    sql = (f"SELECT event_type, count(*) FROM user_events "
           f"WHERE user_id = {u} GROUP BY event_type")
    before, _ = dev.execute(compile_query(sql), segs)
    for seg in segs:
        dev.residency.evict(seg.segment_name)
    after, s = dev.execute(compile_query(sql), segs)
    oracle, _ = host.execute(compile_query(sql), segs)
    assert _rows(before) == _rows(after) == _rows(oracle)
    assert s.decisions.get(SERVED) == N_SEGS


# -- mutable (consuming) segments -------------------------------------------

def _mutable_segment():
    from pinot_tpu.segment.mutable import MutableSegment

    schema = Schema("events", [
        FieldSpec("user", DataType.INT, FieldType.DIMENSION),
        FieldSpec("kind", DataType.STRING, FieldType.DIMENSION),
        FieldSpec("tags", DataType.STRING, FieldType.DIMENSION,
                  single_value=False),
        FieldSpec("value", DataType.INT, FieldType.METRIC),
    ])
    rng = np.random.default_rng(11)
    seg = MutableSegment(schema, "events__0")
    users = rng.zipf(1.4, 12_000).clip(1, 400).astype(np.int64)
    kinds = rng.choice(["a", "b", "c"], 12_000)
    vals = rng.integers(1, 50, 12_000)
    for i in range(12_000):
        seg.index({"user": int(users[i]), "kind": str(kinds[i]),
                   "tags": [f"t{int(users[i]) % 5}"],
                   "value": int(vals[i])})
    return seg, users, vals


def test_mutable_index_gather_parity():
    """Consuming segment: the growing dictId->docIds map serves selective
    point filters through the same gather kernel, rung stays
    mutable_device, ledger says the index gather served."""
    seg, users, vals = _mutable_segment()
    dev = ServerQueryExecutor(use_device=True)
    host = ServerQueryExecutor(use_device=False)
    uniq, cnt = np.unique(users, return_counts=True)
    u = int(next(u for u, c in zip(uniq.tolist(), cnt.tolist())
                 if 5 <= c <= 60))
    c = int(cnt[uniq == u][0])
    sql = (f"SELECT kind, count(*), sum(value) FROM events "
           f"WHERE user = {u} GROUP BY kind")
    r, s = dev.execute(compile_query(sql), [seg])
    rh, _ = host.execute(compile_query(sql), [seg])
    assert _rows(r) == _rows(rh)
    assert s.group_by_rung == "mutable_device"
    assert s.num_docs_scanned == c
    assert s.decisions.get(MUT_SERVED) == 1

    # append rows AFTER the postings map was built: incremental growth
    for _ in range(40):
        seg.index({"user": u, "kind": "a", "tags": ["t0"], "value": 1})
    r2, s2 = dev.execute(compile_query(sql), [seg])
    rh2, _ = host.execute(compile_query(sql), [seg])
    assert _rows(r2) == _rows(rh2)
    assert s2.num_docs_scanned == c + 40


def test_mutable_unsupported_shape_declines():
    """MV-column predicate on a consuming segment: the growing map only
    covers SV dict columns — the rung declines with the registered
    unsupported-shape reason and the chunk scan serves correctly."""
    seg, _, _ = _mutable_segment()
    dev = ServerQueryExecutor(use_device=True)
    host = ServerQueryExecutor(use_device=False)
    sql = ("SELECT kind, count(*) FROM events WHERE tags = 't1' "
           "GROUP BY kind")
    r, s = dev.execute(compile_query(sql), [seg])
    rh, _ = host.execute(compile_query(sql), [seg])
    assert _rows(r) == _rows(rh)
    assert s.decisions.get(
        MUT_DECLINED.format("mutable_index_unsupported_shape")) == 1
    assert s.group_by_rung == "mutable_device"
