"""Device-resident broker reduce (PR 16): group-by merge on the forced
8-virtual-device mesh, bit-identical to the vectorized host path AND the
row-path oracle — plus every decline shape proving the fallback ladder
(device -> vectorized host -> row oracle) fires with its registered
``reduce:device->host:<reason>`` ledger record.

The device service receives IN-PROCESS tables (constructor-built /
executor-built, never wire-decoded) — the embedded-cluster topology the
route exists for; the host paths get wire round-tripped copies, exactly
what a cross-process broker would hold.
"""

import math
import random

import numpy as np
import pytest

from pinot_tpu.broker.reduce import BrokerReduceService
from pinot_tpu.common import tracing
from pinot_tpu.common.datatable import DataTable
from pinot_tpu.engine.results import QueryStats
from pinot_tpu.parallel import reduce_device
from pinot_tpu.query import compile_query

pytestmark = pytest.mark.reduce_device

DEV = BrokerReduceService(vectorized=True, device_reduce=True)
VEC = BrokerReduceService(vectorized=True)
ORA = BrokerReduceService(vectorized=False)


def _wire(t: DataTable) -> DataTable:
    return DataTable.from_bytes(t.to_bytes())


def _assert_bit_identical(a, b, label=""):
    assert a.schema.to_dict() == b.schema.to_dict(), label
    assert len(a.rows) == len(b.rows), (label, len(a.rows), len(b.rows))
    for ra, rb in zip(a.rows, b.rows):
        assert len(ra) == len(rb), label
        for x, y in zip(ra, rb):
            if isinstance(y, float) and math.isnan(y):
                assert isinstance(x, float) and math.isnan(x), label
            else:
                assert x == y and type(x) is type(y), (label, ra, rb)


def _device_declines(stats):
    return {k: v for k, v in stats.decisions.items()
            if k.startswith("reduce:device->host:")}


def _gb_tables(rng, n_servers, per_server, aggs_fn, key_fn,
               schema_types=None):
    tables = []
    for _ in range(n_servers):
        groups = {}
        for _ in range(per_server):
            groups.setdefault(key_fn(rng), aggs_fn(rng))
        tables.append(DataTable.for_group_by(
            groups, schema_types or {"k1": "STRING", "k2": "INT"},
            QueryStats()))
    return tables


# --------------------------------------------------------------------------
# three-path parity on randomized merges: dense rung and sort rung
# --------------------------------------------------------------------------

@pytest.mark.parametrize("sql", [
    "SELECT k1, k2, sum(v), count(*) FROM t GROUP BY k1, k2 LIMIT 100000",
    "SELECT k1, k2, sum(v), count(*), min(v), max(v) FROM t "
    "GROUP BY k1, k2 ORDER BY sum(v) DESC, k1 LIMIT 97",
    "SELECT k2, count(*) FROM t GROUP BY k2, k1 "
    "ORDER BY count(*) DESC, k2 LIMIT 13, 29",
    "SELECT k1, sum(v) FROM t GROUP BY k1, k2 "
    "HAVING sum(v) > 300 ORDER BY k1, sum(v) LIMIT 50",
])
def test_device_group_by_parity(sql, eight_devices):
    """Device merge == vectorized host merge == row oracle, bit for bit,
    across ORDER BY / OFFSET / HAVING / value ties — and the device path
    actually served (no silent host fallback)."""
    rng = random.Random(hash(sql) & 0xFFFF)
    ctx = compile_query(sql)

    def aggs_fn(r):
        states = {
            "sum(v)": float(r.randint(0, 1000)),
            "count(*)": r.randint(1, 50),
            "min(v)": float(r.randint(-100, 100)),
            "max(v)": float(r.randint(-100, 100)),
        }
        return [states[str(f)] for f in ctx.aggregations]

    def key_fn(r):
        return ("b%02d" % r.randint(0, 25), r.randint(0, 40))

    tables = _gb_tables(rng, 5, 400, aggs_fn, key_fn)
    rd, sd, _ = DEV.reduce(ctx, tables)
    rv, _, _ = VEC.reduce(ctx, [_wire(t) for t in tables])
    ro, _, _ = ORA.reduce(ctx, [_wire(t) for t in tables])
    _assert_bit_identical(rd, rv, sql)
    _assert_bit_identical(rd, ro, sql)
    assert sd.reduce_path == "device", (sql, sd.decisions)
    assert not _device_declines(sd)


def test_device_sort_rung_parity(monkeypatch, eight_devices):
    """Composite spaces past the dense slot budget ride the sort rung
    (all_gather + global argsort + rank scatter) — same bit parity."""
    monkeypatch.setattr(reduce_device, "DENSE_SLOTS", 1)
    ctx = compile_query(
        "SELECT k, sum(v), count(*) FROM t GROUP BY k "
        "ORDER BY sum(v) DESC, k LIMIT 500")
    tables = _gb_tables(
        random.Random(3), 6, 500,
        lambda r: [float(r.randint(0, 9999)), r.randint(1, 5)],
        lambda r: (r.randint(-(1 << 40), 1 << 40),),
        schema_types={"k": "LONG"})
    rd, sd, _ = DEV.reduce(ctx, tables)
    ro, _, _ = ORA.reduce(ctx, [_wire(t) for t in tables])
    _assert_bit_identical(rd, ro)
    assert sd.reduce_path == "device", sd.decisions
    assert not _device_declines(sd)


def test_device_dense_a2a_flavor_parity(monkeypatch, eight_devices):
    """Dense slot spaces past ``_PSUM_SLOTS`` combine with the
    all_to_all slice exchange instead of psum (each device folds one
    slot-space slice; sharded outputs reassemble on the host) — same
    bit parity, same live-slot compaction."""
    monkeypatch.setattr(reduce_device, "_PSUM_SLOTS", 1)
    ctx = compile_query(
        "SELECT k, sum(v), min(v), max(v), count(*) FROM t GROUP BY k "
        "ORDER BY sum(v) DESC, k LIMIT 500")
    tables = _gb_tables(
        random.Random(7), 6, 500,
        lambda r: [float(r.randint(0, 9999)), float(r.randint(0, 99)),
                   float(r.randint(100, 199)), r.randint(1, 5)],
        lambda r: (r.randint(0, 800),), schema_types={"k": "INT"})
    rd, sd, _ = DEV.reduce(ctx, tables)
    ro, _, _ = ORA.reduce(ctx, [_wire(t) for t in tables])
    _assert_bit_identical(rd, ro)
    assert sd.reduce_path == "device", sd.decisions
    assert not _device_declines(sd)


def test_device_num_groups_limit_trim_parity(eight_devices):
    svc_d = BrokerReduceService(num_groups_limit=50, vectorized=True,
                                device_reduce=True)
    svc_o = BrokerReduceService(num_groups_limit=50, vectorized=False)
    ctx = compile_query("SELECT k, count(*) FROM t GROUP BY k LIMIT 100000")

    def build():
        return _gb_tables(
            random.Random(11), 4, 60, lambda r: [r.randint(1, 5)],
            lambda r: (r.randint(0, 500),), schema_types={"k": "INT"})

    rd, sd, _ = svc_d.reduce(ctx, build())
    ro, so, _ = svc_o.reduce(ctx, [_wire(t) for t in build()])
    _assert_bit_identical(rd, ro)
    assert sd.reduce_path == "device"
    assert sd.num_groups_limit_reached and so.num_groups_limit_reached


def test_device_route_query_option_override(eight_devices):
    """OPTION(deviceReduce=...) flips the route per query, both ways."""
    ctx = compile_query("SELECT k, sum(v) FROM t GROUP BY k LIMIT 1000")
    tables = _gb_tables(random.Random(5), 3, 100,
                        lambda r: [float(r.randint(0, 100))],
                        lambda r: ("g%02d" % r.randint(0, 30),),
                        schema_types={"k": "STRING"})
    ctx.options["deviceReduce"] = "true"
    _, s_on, _ = VEC.reduce(ctx, tables)     # default-off service
    assert s_on.reduce_path == "device"
    ctx.options["deviceReduce"] = "false"
    _, s_off, _ = DEV.reduce(ctx, tables)    # default-on service
    assert s_off.reduce_path == "vectorized"
    assert not _device_declines(s_off)       # opted out, not declined


# --------------------------------------------------------------------------
# SSB: all 13 flights, three paths bit-identical on the 8-device mesh
# --------------------------------------------------------------------------

@pytest.fixture(scope="module")
def ssb_server_tables(tmp_path_factory, eight_devices):
    """Two 'servers' (host executors over disjoint segment halves) share
    the process with the device reduce — the embedded-cluster topology.
    Their tables are handed to the device service AS BUILT (in-process,
    wire_decoded=False); host paths get wire round-tripped copies."""
    from pinot_tpu.engine import ServerQueryExecutor
    from pinot_tpu.tools import ssb

    out = tmp_path_factory.mktemp("ssb_reduce_dev_segs")
    segs = ssb.build_segments(0, str(out), num_segments=4, rows=40_000)
    servers = [ServerQueryExecutor(use_device=False),
               ServerQueryExecutor(use_device=False)]
    halves = [segs[:2], segs[2:]]

    def run(sql: str):
        ctx = compile_query(sql)
        return ctx, [srv.execute_instance(ctx, half)
                     for srv, half in zip(servers, halves)]

    return run


from pinot_tpu.tools import ssb as _ssb_queries  # noqa: E402


@pytest.mark.parametrize("qid", sorted(_ssb_queries.QUERIES))
def test_ssb_flight_device_parity(ssb_server_tables, qid):
    from pinot_tpu.tools import ssb

    ctx, tables = ssb_server_tables(ssb.QUERIES[qid] + " LIMIT 100000")
    rd, sd, _ = DEV.reduce(ctx, tables)
    rv, _, _ = VEC.reduce(ctx, [_wire(t) for t in tables])
    ro, _, _ = ORA.reduce(ctx, [_wire(t) for t in tables])
    _assert_bit_identical(rd, rv, qid)
    _assert_bit_identical(rd, ro, qid)
    if ctx.group_by and rd.rows:
        # every SSB group-by flight that merges groups must SERVE from
        # the device path — a decline here is a regression
        assert sd.reduce_path == "device", (qid, sd.decisions)
        assert not _device_declines(sd), (qid, sd.decisions)
    elif ctx.group_by:
        # empty group set (Q3.4's filter matches no rows in the small
        # fixture): nothing reaches the device merge, but nothing may
        # DECLINE either
        assert not _device_declines(sd), (qid, sd.decisions)
    else:
        # Q1.x are scalar aggregations: no group-by block to merge
        assert sd.reduce_path == "vectorized", (qid, sd.reduce_path)


# --------------------------------------------------------------------------
# decline shapes: each fallback fires loudly with its registered reason
# --------------------------------------------------------------------------

def _expect_decline(ctx, tables, reason, oracle_parity=False):
    """DEV declines to the vectorized host path with ``reason`` on the
    ledger; rows stay bit-identical to the next rung down."""
    rd, sd, _ = DEV.reduce(ctx, tables)
    key = f"reduce:device->host:{reason}"
    assert key in sd.decisions, (reason, sd.decisions)
    assert reason in tracing.REDUCE_DEVICE_REASONS
    ref_svc = ORA if oracle_parity else VEC
    rr, _, _ = ref_svc.reduce(ctx, [_wire(t) for t in tables])
    _assert_bit_identical(rd, rr, reason)
    return sd


def test_decline_obj_state(eight_devices):
    """avg ships (sum, count) tuple states — obj kind, host fold only."""
    ctx = compile_query(
        "SELECT k, avg(v) FROM t GROUP BY k ORDER BY k LIMIT 100")
    tables = _gb_tables(
        random.Random(2), 3, 50,
        lambda r: [(float(r.randint(0, 500)), r.randint(1, 9))],
        lambda r: ("a%02d" % r.randint(0, 20),),
        schema_types={"k": "STRING"})
    sd = _expect_decline(ctx, tables, "reduce_device_obj_state")
    assert sd.reduce_path == "vectorized"


def test_decline_nan_key(eight_devices):
    """NaN group keys: NaN != NaN breaks composite-key group identity,
    so the device route declines (the host vectorized path gives every
    NaN row its own run — both host paths agree)."""
    ctx = compile_query("SELECT k, count(*) FROM t GROUP BY k LIMIT 100")
    t1 = DataTable.for_group_by(
        {(1.5,): [3], (float("nan"),): [5]}, {"k": "DOUBLE"}, QueryStats())
    t2 = DataTable.for_group_by(
        {(1.5,): [2], (2.5,): [1]}, {"k": "DOUBLE"}, QueryStats())
    _expect_decline(ctx, [t1, t2], "reduce_device_nan_key")


def test_decline_i64_sum_bound(eight_devices):
    """i64 sums near 2^62: BOTH rungs decline — the device record first,
    then the vectorized path's own bound record — and the oracle's
    python-int arithmetic is the contract."""
    ctx = compile_query("SELECT k, sum(v) FROM t GROUP BY k LIMIT 10")
    t1 = DataTable.for_group_by({("a",): [1 << 61]}, {}, QueryStats())
    t2 = DataTable.for_group_by({("a",): [1 << 61]}, {}, QueryStats())
    sd = _expect_decline(ctx, [t1, t2], "reduce_device_i64_sum_bound",
                         oracle_parity=True)
    assert "reduce:vectorized->row_path:reduce_i64_sum_bound" \
        in sd.decisions
    assert sd.reduce_path == "oracle"


def test_decline_cross_process(eight_devices):
    """Wire-decoded tables already paid D2H + serialization: the device
    premise is gone, the host lexsort is the frame."""
    ctx = compile_query("SELECT k, sum(v) FROM t GROUP BY k LIMIT 1000")
    tables = [_wire(t) for t in _gb_tables(
        random.Random(9), 3, 80, lambda r: [float(r.randint(0, 100))],
        lambda r: (r.randint(0, 40),), schema_types={"k": "INT"})]
    rd, sd, _ = DEV.reduce(ctx, tables)
    assert "reduce:device->host:reduce_device_cross_process" \
        in sd.decisions, sd.decisions
    assert sd.reduce_path == "vectorized"
    rv, _, _ = VEC.reduce(ctx, tables)
    _assert_bit_identical(rd, rv)


def test_decline_mesh_unavailable(monkeypatch, eight_devices):
    monkeypatch.setattr(reduce_device, "broker_mesh", lambda: None)
    ctx = compile_query("SELECT k, count(*) FROM t GROUP BY k LIMIT 100")
    tables = _gb_tables(random.Random(4), 2, 30,
                        lambda r: [r.randint(1, 9)],
                        lambda r: (r.randint(0, 20),),
                        schema_types={"k": "INT"})
    _expect_decline(ctx, tables, "reduce_device_mesh_unavailable")


def test_decline_rows_over_capacity(monkeypatch, eight_devices):
    monkeypatch.setattr(reduce_device, "MAX_MERGE_ROWS", 16)
    ctx = compile_query("SELECT k, count(*) FROM t GROUP BY k LIMIT 1000")
    tables = _gb_tables(random.Random(6), 4, 50,
                        lambda r: [r.randint(1, 9)],
                        lambda r: (r.randint(0, 999),),
                        schema_types={"k": "INT"})
    _expect_decline(ctx, tables, "reduce_device_rows_over_capacity")


def test_decline_key_space_overflow(eight_devices):
    """Two wide-range i64 key columns whose composite space cannot fit
    the i64 budget decline loudly instead of wrapping."""
    ctx = compile_query(
        "SELECT k1, k2, count(*) FROM t GROUP BY k1, k2 LIMIT 100")
    big = 1 << 40
    t1 = DataTable.for_group_by(
        {(0, 0): [1], (big, big): [2]},
        {"k1": "LONG", "k2": "LONG"}, QueryStats())
    t2 = DataTable.for_group_by(
        {(0, 0): [3], (big, 0): [4]},
        {"k1": "LONG", "k2": "LONG"}, QueryStats())
    _expect_decline(ctx, [t1, t2], "reduce_device_key_space_overflow")


def test_decline_f64_sum_order(eight_devices):
    """Fractional f64 sums are order-dependent; only the host reduceat
    order is the contract, so the device path refuses them."""
    ctx = compile_query("SELECT k, sum(v) FROM t GROUP BY k LIMIT 100")
    t1 = DataTable.for_group_by({("a",): [1.5]}, {}, QueryStats())
    t2 = DataTable.for_group_by({("a",): [2.25]}, {}, QueryStats())
    _expect_decline(ctx, [t1, t2], "reduce_device_f64_sum_order")


def test_decline_kernel_error(monkeypatch, eight_devices):
    """A kernel-build/run failure falls back, never crashes the query."""
    def boom(*a, **k):
        raise RuntimeError("synthetic kernel failure")

    monkeypatch.setattr(reduce_device, "device_group_merge", boom)
    ctx = compile_query("SELECT k, count(*) FROM t GROUP BY k LIMIT 100")
    tables = _gb_tables(random.Random(8), 2, 30,
                        lambda r: [r.randint(1, 9)],
                        lambda r: (r.randint(0, 20),),
                        schema_types={"k": "INT"})
    _expect_decline(ctx, tables, "reduce_device_kernel_error")


# --------------------------------------------------------------------------
# registry + stats plumbing
# --------------------------------------------------------------------------

def test_reduce_device_reasons_registered():
    """The namespace is in the unified registry, exact (every code has a
    live ``_decline_device`` record site in broker/reduce.py), and
    disjoint from the vectorized->oracle reason set."""
    ns = tracing.reason_registry("reduce_device")
    assert ns.codes == tracing.REDUCE_DEVICE_REASONS
    assert ns.exact
    found, unregistered = ns.conformance()
    assert found == tracing.REDUCE_DEVICE_REASONS
    assert not unregistered
    assert not (tracing.REDUCE_DEVICE_REASONS
                & tracing.REDUCE_DECISION_REASONS)


def test_reduce_path_survives_the_wire(eight_devices):
    """``reducePath`` round-trips DataTable stats framing (the bench's
    cluster suite reads it off BrokerResponse.stats)."""
    st = QueryStats()
    st.reduce_path = "device"
    t = _wire(DataTable.for_group_by({("a",): [1]}, {}, st))
    assert t.stats.reduce_path == "device"
    assert t.wire_decoded
    merged = QueryStats()
    merged.merge(t.stats)
    assert merged.reduce_path == "device"
    assert QueryStats().to_dict().get("reducePath") is None
