"""FST index: trie build, prefix narrowing, REGEXP parity
(ref: LuceneFSTIndexReader, FSTBasedRegexpPredicateEvaluator)."""

import re

import numpy as np
import pytest

from pinot_tpu.engine import ServerQueryExecutor
from pinot_tpu.query import compile_query
from pinot_tpu.segment import SegmentBuilder, load_segment
from pinot_tpu.segment.fstindex import (
    FstIndexBuilder,
    FstIndexReader,
    literal_prefix,
)
from pinot_tpu.spi import DataType, FieldSpec, FieldType, Schema
from pinot_tpu.spi.table import IndexingConfig


class _Dict:
    def __init__(self, terms):
        self.terms = terms

    def get_value(self, i):
        return self.terms[i]


def _reader(terms):
    terms = sorted(terms)
    return FstIndexReader(*FstIndexBuilder(terms).build(), _Dict(terms)), terms


class TestLiteralPrefix:
    @pytest.mark.parametrize("pattern,expect", [
        ("^abc.*", "abc"),
        ("^abc", "abc"),
        ("abc", ""),            # unanchored: search semantics
        ("^a[bc]d", "a"),
        ("^ab?c", "a"),         # quantified literal excluded
        ("^", ""),
        (r"^a\.b", "a.b"),      # escaped metachar is literal
        (r"^a\d+", "a"),
        ("^(ab|cd)", ""),
    ])
    def test_extraction(self, pattern, expect):
        assert literal_prefix(pattern) == expect


class TestTrie:
    def test_prefix_range_exact(self):
        r, terms = _reader(["apple", "apricot", "banana", "band", "bandit",
                            "cherry"])
        lo, hi = r.prefix_range("ban")
        assert terms[lo:hi] == ["banana", "band", "bandit"]
        lo, hi = r.prefix_range("band")
        assert terms[lo:hi] == ["band", "bandit"]
        assert r.prefix_range("zz") == (0, 0)
        lo, hi = r.prefix_range("")
        assert (lo, hi) == (0, len(terms))

    def test_prefix_beyond_max_depth(self):
        base = "x" * 20
        r, terms = _reader([base + "a", base + "b", "other"])
        lo, hi = r.prefix_range(base + "b")
        assert terms[lo:hi] == [base + "b"]

    def test_matching_ids_parity_random(self):
        rng = np.random.default_rng(5)
        terms = sorted({f"{p}{i}" for p in ("foo", "bar", "bazz", "qux")
                        for i in rng.integers(0, 500, 80)})
        r, terms = _reader(terms)
        for pattern in ("^foo", "^bar1.*", "^bazz4[0-9]$", "qux", "9$"):
            rx = re.compile(pattern)
            expect = [i for i, t in enumerate(terms) if rx.search(t)]
            got = r.matching_ids(pattern).tolist()
            assert got == expect, pattern

    def test_single_term(self):
        r, terms = _reader(["only"])
        assert r.matching_ids("^on").tolist() == [0]
        assert r.matching_ids("^x").tolist() == []


class TestEndToEnd:
    @pytest.fixture(scope="class")
    def seg(self, tmp_path_factory):
        out = str(tmp_path_factory.mktemp("fst"))
        rng = np.random.default_rng(9)
        n = 4000
        urls = [f"/api/v{rng.integers(1, 4)}/users/{i % 100}" if i % 3
                else f"/static/img/{i % 50}.png" for i in range(n)]
        schema = Schema("logs", [
            FieldSpec("url", DataType.STRING),
            FieldSpec("n", DataType.LONG, FieldType.METRIC),
        ])
        cfg = IndexingConfig(fst_index_columns=["url"])
        SegmentBuilder(schema, "l0", indexing_config=cfg).build(
            {"url": urls, "n": list(range(n))}, out)
        return load_segment(f"{out}/l0"), urls

    def test_has_index(self, seg):
        segment, _ = seg
        assert segment.metadata.column("url").has_fst_index
        assert segment.data_source("url").fst_index is not None

    def test_regexp_query_parity(self, seg):
        segment, urls = seg
        ex = ServerQueryExecutor()
        t, _ = ex.execute(compile_query(
            "SELECT count(*) FROM logs WHERE regexp_like(url, '^/static/')"),
            [segment])
        expect = sum(1 for u in urls if u.startswith("/static/"))
        assert t.rows[0][0] == expect

    def test_regexp_unanchored_parity(self, seg):
        segment, urls = seg
        ex = ServerQueryExecutor()
        t, _ = ex.execute(compile_query(
            "SELECT count(*) FROM logs WHERE regexp_like(url, 'users/7$')"),
            [segment])
        expect = sum(1 for u in urls if re.search("users/7$", u))
        assert t.rows[0][0] == expect


def test_alternation_voids_prefix():
    """'^abc|xyz': the anchor binds only to the first alternative, so
    prefix narrowing must be disabled."""
    assert literal_prefix("^abc|xyz") == ""
    assert literal_prefix("^a(b|c)d") == "a"  # grouped alternation is fine
    r, terms = _reader(["abcx", "hello xyz", "zzz"])
    assert r.matching_ids("^abc|xyz").tolist() == [0, 1]
