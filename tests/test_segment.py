"""Segment storage round-trip tests.

Mirrors the reference's creator/reader round-trip strategy
(pinot-segment-local/src/test/java/.../segment/index/creator/).
"""

import numpy as np
import pytest

from pinot_tpu.segment import (
    DOC_TILE,
    Encoding,
    SegmentBuilder,
    load_segment,
    pad_capacity,
    verify_crc,
)
from pinot_tpu.spi import (
    DataType,
    FieldSpec,
    FieldType,
    IndexingConfig,
    Schema,
    SegmentPartitionConfig,
)

RNG = np.random.default_rng(42)


def make_schema():
    return Schema("stats", [
        FieldSpec("team", DataType.STRING),
        FieldSpec("year", DataType.INT),
        FieldSpec("tags", DataType.STRING, single_value=False),
        FieldSpec("score", DataType.DOUBLE, FieldType.METRIC),
        FieldSpec("hits", DataType.LONG, FieldType.METRIC),
        FieldSpec("payload", DataType.BYTES),
    ])


def make_rows(n=500):
    teams = ["ATL", "BOS", "CHC", "NYA", "SFO"]
    rows = []
    for i in range(n):
        rows.append({
            "team": teams[int(RNG.integers(len(teams)))],
            "year": int(RNG.integers(1990, 2021)),
            "tags": [f"t{j}" for j in range(int(RNG.integers(0, 4)))] or None,
            "score": float(np.round(RNG.normal(50, 10), 3)),
            "hits": int(RNG.integers(0, 10_000)),
            "payload": bytes([i % 256, (i * 7) % 256]),
        })
    return rows


@pytest.fixture(scope="module")
def built_segment(tmp_path_factory):
    out = tmp_path_factory.mktemp("segs")
    rows = make_rows()
    builder = SegmentBuilder(
        make_schema(), "stats_0",
        indexing_config=IndexingConfig(
            inverted_index_columns=["team", "tags"],
            no_dictionary_columns=["hits"],
        ))
    md = builder.build(rows, str(out))
    return rows, str(out / "stats_0"), md


class TestSegmentBuild:
    def test_metadata(self, built_segment):
        rows, seg_dir, md = built_segment
        assert md.num_docs == 500
        assert md.padded_capacity == pad_capacity(500) == DOC_TILE
        assert md.columns["team"].encoding is Encoding.DICT
        assert md.columns["hits"].encoding is Encoding.RAW
        assert md.columns["team"].cardinality == 5
        # 5 distinct values -> 3-bit fixed-bit packing (native format)
        assert md.columns["team"].stored_dtype == "packed:3"
        assert md.columns["team"].has_inverted_index
        assert md.crc != 0

    def test_sv_roundtrip(self, built_segment):
        rows, seg_dir, md = built_segment
        seg = load_segment(seg_dir)
        for i in (0, 1, 123, 499):
            assert seg.get_value("team", i) == rows[i]["team"]
            assert seg.get_value("year", i) == rows[i]["year"]
            assert seg.get_value("score", i) == pytest.approx(rows[i]["score"])
            assert seg.get_value("hits", i) == rows[i]["hits"]
            assert seg.get_value("payload", i) == rows[i]["payload"]

    def test_mv_roundtrip(self, built_segment):
        rows, seg_dir, md = built_segment
        seg = load_segment(seg_dir)
        for i in (0, 7, 250, 499):
            expected = rows[i]["tags"] or ["null"]  # null -> [default]
            assert seg.get_value("tags", i) == expected

    def test_dictionary_sorted_and_searchable(self, built_segment):
        rows, seg_dir, md = built_segment
        seg = load_segment(seg_dir)
        d = seg.data_source("team").dictionary
        values = [d.get_value(i) for i in range(len(d))]
        assert values == sorted(values)
        for i, v in enumerate(values):
            assert d.index_of(v) == i
        assert d.index_of("ZZZ") == -1
        # range -> dictId interval (the device filter fast path)
        a, b = d.range_to_dict_id_interval("B", "N", True, True)
        assert [values[i] for i in range(a, b + 1)] == ["BOS", "CHC"]

    def test_inverted_index(self, built_segment):
        rows, seg_dir, md = built_segment
        seg = load_segment(seg_dir)
        ds = seg.data_source("team")
        d = ds.dictionary
        for team in ("ATL", "SFO"):
            did = d.index_of(team)
            docs = ds.doc_ids_for_dict_id(did)
            expected = [i for i, r in enumerate(rows) if r["team"] == team]
            assert docs.tolist() == expected
        # MV inverted index
        ds_mv = seg.data_source("tags")
        did = ds_mv.dictionary.index_of("t1")
        docs = ds_mv.doc_ids_for_dict_id(did)
        expected = [i for i, r in enumerate(rows) if r["tags"] and "t1" in r["tags"]]
        assert docs.tolist() == expected

    def test_padding_and_dtypes(self, built_segment):
        rows, seg_dir, md = built_segment
        seg = load_segment(seg_dir)
        fwd = seg.data_source("team").forward_index
        assert fwd.shape[0] == md.padded_capacity
        assert fwd.dtype == np.int32  # packed on disk, int32 staging buffer
        assert np.all(np.asarray(fwd[500:]) == 0)  # pad rows are dictId 0

    def test_min_max_metadata(self, built_segment):
        rows, seg_dir, md = built_segment
        assert md.columns["year"].min_value == min(r["year"] for r in rows)
        assert md.columns["year"].max_value == max(r["year"] for r in rows)
        assert md.columns["team"].min_value == "ATL"
        assert md.columns["team"].max_value == "SFO"

    def test_null_bitmap(self, built_segment):
        rows, seg_dir, md = built_segment
        seg = load_segment(seg_dir)
        nb = seg.data_source("tags").null_bitmap
        assert nb is not None
        expected = [r["tags"] is None for r in rows]
        assert nb[:500].tolist() == expected

    def test_crc_verification(self, built_segment):
        rows, seg_dir, md = built_segment
        assert verify_crc(seg_dir)

    def test_dense_mv(self, built_segment):
        rows, seg_dir, md = built_segment
        seg = load_segment(seg_dir)
        ds = seg.data_source("tags")
        dense, counts = ds.dense_mv()
        assert dense.shape == (md.padded_capacity, md.columns["tags"].max_num_multi_values)
        d = ds.dictionary
        for i in (3, 77, 410):
            expected = rows[i]["tags"] or ["null"]
            got = [d.get_value(int(x)) for x in dense[i, :counts[i]]]
            assert got == expected


class TestEdgeCases:
    def test_columnar_input(self, tmp_path):
        schema = Schema("t", [FieldSpec("a", DataType.INT),
                              FieldSpec("m", DataType.DOUBLE, FieldType.METRIC)])
        cols = {"a": list(range(10)), "m": [float(i) * 1.5 for i in range(10)]}
        md = SegmentBuilder(schema, "t_0").build(cols, str(tmp_path))
        seg = load_segment(str(tmp_path / "t_0"))
        assert seg.get_value("a", 9) == 9
        assert seg.get_value("m", 3) == 4.5
        assert md.columns["a"].is_sorted

    def test_ragged_columns_rejected(self, tmp_path):
        schema = Schema("t", [FieldSpec("a", DataType.INT), FieldSpec("b", DataType.INT)])
        with pytest.raises(ValueError, match="ragged"):
            SegmentBuilder(schema, "t_0").build({"a": [1, 2], "b": [1]}, str(tmp_path))

    def test_missing_column_gets_defaults(self, tmp_path):
        schema = Schema("t", [FieldSpec("a", DataType.INT),
                              FieldSpec("missing", DataType.STRING)])
        md = SegmentBuilder(schema, "t_0").build({"a": [1, 2, 3]}, str(tmp_path))
        seg = load_segment(str(tmp_path / "t_0"))
        assert seg.get_value("missing", 1) == "null"
        assert md.columns["missing"].has_nulls

    def test_time_column_range(self, tmp_path):
        schema = Schema("t", [
            FieldSpec("d", DataType.INT, FieldType.DATE_TIME),
            FieldSpec("m", DataType.INT, FieldType.METRIC)])
        md = SegmentBuilder(schema, "t_0").build(
            {"d": [100, 50, 200], "m": [1, 2, 3]}, str(tmp_path))
        assert md.time_column == "d"
        assert md.min_time == 50 and md.max_time == 200

    def test_partition_metadata(self, tmp_path):
        schema = Schema("t", [FieldSpec("k", DataType.INT),
                              FieldSpec("m", DataType.INT, FieldType.METRIC)])
        idx = IndexingConfig(segment_partition_config=SegmentPartitionConfig(
            {"k": {"functionName": "Modulo", "numPartitions": 4}}))
        md = SegmentBuilder(schema, "t_0", indexing_config=idx).build(
            {"k": [0, 4, 8, 1], "m": [1, 1, 1, 1]}, str(tmp_path))
        assert md.columns["k"].partition_function == "Modulo"
        assert md.columns["k"].partitions == [0, 1]

    def test_large_cardinality_dtype(self, tmp_path):
        schema = Schema("t", [FieldSpec("k", DataType.INT)])
        n = 40_000  # > 2^15 distinct -> 16-bit packed dictIds
        md = SegmentBuilder(schema, "t_0").build({"k": list(range(n))}, str(tmp_path))
        assert md.columns["k"].stored_dtype == "packed:16"
        assert md.padded_capacity % DOC_TILE == 0
        seg = load_segment(str(tmp_path / "t_0"))
        assert seg.get_value("k", n - 1) == n - 1

    def test_string_time_column(self, tmp_path):
        # non-integral time columns must not crash the build (regression)
        schema = Schema("t", [FieldSpec("day", DataType.STRING, FieldType.DATE_TIME),
                              FieldSpec("m", DataType.INT, FieldType.METRIC)])
        md = SegmentBuilder(schema, "t_0").build(
            {"day": ["2021-01-02", "2021-01-01"], "m": [1, 2]}, str(tmp_path))
        assert md.min_time == "2021-01-01" and md.max_time == "2021-01-02"

    def test_empty_ndarray_mv_row_is_null(self, tmp_path):
        # np.array([]) must behave exactly like [] (regression)
        schema = Schema("t", [FieldSpec("tags", DataType.STRING, single_value=False)])
        md = SegmentBuilder(schema, "t_0").build(
            {"tags": [np.array([]), ["a"]]}, str(tmp_path))
        seg = load_segment(str(tmp_path / "t_0"))
        assert seg.get_value("tags", 0) == ["null"]
        assert md.columns["tags"].has_nulls

    def test_boolean_and_timestamp(self, tmp_path):
        schema = Schema("t", [FieldSpec("b", DataType.BOOLEAN),
                              FieldSpec("ts", DataType.TIMESTAMP)])
        md = SegmentBuilder(schema, "t_0").build(
            {"b": [True, False, True], "ts": [1000, 2000, 3000]}, str(tmp_path))
        seg = load_segment(str(tmp_path / "t_0"))
        assert seg.get_value("b", 0) == 1
        assert seg.get_value("ts", 2) == 3000
