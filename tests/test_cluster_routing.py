"""Partition-aware scatter routing + replica groups + partial-result gather
(``pytest -m cluster_routing``, part of tier-1).

Covers ISSUE 12's cluster half: the cached RoutingTable snapshot (segment
partition/time metadata pushed in by store watches — zero state-store reads
on the warmed hot path), eq/IN/range partition pruning, the routing
decision ledger, scatter fan-out accounting (numServersQueried /
numServersResponded on QueryStats and the wire), and per-server failure
handling in gather (a down or timed-out server yields a PARTIAL result
with loud accounting and no pin/lease leak on the surviving servers).
"""

import time

import numpy as np
import pytest

from pinot_tpu.broker.routing import RoutingManager
from pinot_tpu.controller.state import (
    ONLINE,
    ClusterStateStore,
    InstanceInfo,
    SegmentZKMetadata,
)
from pinot_tpu.engine.results import QueryStats
from pinot_tpu.query import compile_query
from pinot_tpu.spi.table import (
    RoutingConfig,
    SegmentsValidationConfig,
    TableConfig,
)
from pinot_tpu.tools import ssb
from pinot_tpu.tools.cluster import EmbeddedCluster

pytestmark = pytest.mark.cluster_routing

TABLE = "part_OFFLINE"


def _store_with_partitioned_segments(num_segments=4, num_partitions=4,
                                     fn_name="Modulo", pruner=True,
                                     time_ranges=None):
    """A store holding ``num_segments`` segments, segment i owning
    partition i (mod num_partitions) of column 'k', each served by its
    own server — routing-only tests need no real segment files."""
    store = ClusterStateStore()
    from pinot_tpu.spi.data import DataType, FieldSpec, Schema

    store.add_schema(Schema("part", [FieldSpec("k", DataType.INT)]))
    store.add_table_config(TableConfig(
        "part",
        validation_config=SegmentsValidationConfig(
            time_column_name="ts" if time_ranges else None),
        routing_config=RoutingConfig(
            segment_pruner_types=["partition"] if pruner else [])))
    for i in range(num_segments):
        store.register_instance(InstanceInfo(f"s{i}", "SERVER"))
        md = SegmentZKMetadata(
            segment_name=f"seg_{i}", table_name=TABLE,
            partition_metadata={"k": {
                "functionName": fn_name,
                "numPartitions": num_partitions,
                "partitions": [i % num_partitions]}})
        if time_ranges:
            md.start_time, md.end_time = time_ranges[i]
        store.set_segment_metadata(md)
        store.report_instance_state(TABLE, f"seg_{i}", f"s{i}", ONLINE)
    return store


def _routed_segments(rm, ctx=None, stats=None):
    res = rm.route(TABLE, ctx, stats=stats)
    return sorted(sum(res.routing.values(), [])), res


class TestRoutingTableSnapshot:
    def test_metadata_pushed_no_store_reads_on_hot_path(self):
        """The warmed per-query path must not touch the state store: the
        snapshot carries replicas + partition fns + time ranges (ref:
        buildRouting caching per RoutingEntry)."""
        store = _store_with_partitioned_segments()
        rm = RoutingManager(store)
        ctx = compile_query("SELECT count(*) FROM part WHERE k = 2")
        routed, res = _routed_segments(rm, ctx)
        assert routed == ["seg_2"]

        def boom(*a, **k):
            raise AssertionError("state store read on the routing hot path")

        for name in ("get_segment_metadata", "segment_metadata_list",
                     "get_external_view", "get_table_config",
                     "get_instance_partitions", "instances"):
            setattr(store, name, boom)
        routed, res = _routed_segments(rm, ctx)
        assert routed == ["seg_2"]
        assert res.servers_routed == 1

    def test_watch_invalidation_on_new_segment(self):
        store = _store_with_partitioned_segments()
        rm = RoutingManager(store)
        assert _routed_segments(rm)[0] == [f"seg_{i}" for i in range(4)]
        # a segment push + EV report must invalidate the cached snapshot
        store.set_segment_metadata(SegmentZKMetadata(
            segment_name="seg_4", table_name=TABLE,
            partition_metadata={"k": {"functionName": "Modulo",
                                      "numPartitions": 4,
                                      "partitions": [0]}}))
        store.report_instance_state(TABLE, "seg_4", "s0", ONLINE)
        assert "seg_4" in _routed_segments(rm)[0]
        ctx = compile_query("SELECT count(*) FROM part WHERE k = 4")
        assert _routed_segments(rm, ctx)[0] == ["seg_0", "seg_4"]

    def test_liveness_watch_refreshes_dead_set(self):
        store = _store_with_partitioned_segments()
        rm = RoutingManager(store)
        _routed_segments(rm)  # warm the dead-instance cache
        store.set_instance_alive("s1", False)
        routed, res = _routed_segments(rm)
        # seg_1's only replica is dead -> unavailable, not silently routed
        assert "seg_1" not in routed
        assert res.unavailable == ["seg_1"]
        store.set_instance_alive("s1", True)
        assert "seg_1" in _routed_segments(rm)[0]


class TestPartitionPruning:
    def test_eq_in_and_range_predicates(self):
        store = _store_with_partitioned_segments()
        rm = RoutingManager(store)
        eq = compile_query("SELECT count(*) FROM part WHERE k = 6")
        assert _routed_segments(rm, eq)[0] == ["seg_2"]
        isin = compile_query("SELECT count(*) FROM part WHERE k IN (1, 2)")
        assert _routed_segments(rm, isin)[0] == ["seg_1", "seg_2"]
        # narrow closed int range enumerates its values (4..5 -> {0, 1})
        rng = compile_query(
            "SELECT count(*) FROM part WHERE k BETWEEN 4 AND 5")
        assert _routed_segments(rm, rng)[0] == ["seg_0", "seg_1"]

    def test_wide_and_open_ranges_do_not_prune(self):
        store = _store_with_partitioned_segments()
        rm = RoutingManager(store)
        wide = compile_query(
            "SELECT count(*) FROM part WHERE k BETWEEN 0 AND 100000")
        assert len(_routed_segments(rm, wide)[0]) == 4
        open_ = compile_query("SELECT count(*) FROM part WHERE k > 7")
        assert len(_routed_segments(rm, open_)[0]) == 4

    def test_or_filters_do_not_prune(self):
        # a top-level OR is not conjunctive: pruning on either branch is
        # wrong (same-column OR-of-eq may legally collapse to IN upstream,
        # so the shape here mixes eq with an open range)
        store = _store_with_partitioned_segments()
        rm = RoutingManager(store)
        ctx = compile_query(
            "SELECT count(*) FROM part WHERE k = 2 OR k > 100000")
        assert len(_routed_segments(rm, ctx)[0]) == 4

    def test_murmur_partition_function(self):
        store = _store_with_partitioned_segments(fn_name="Murmur")
        rm = RoutingManager(store)
        from pinot_tpu.utils.partition import get_partition_function

        fn = get_partition_function("Murmur", 4)
        v = 37
        ctx = compile_query(f"SELECT count(*) FROM part WHERE k = {v}")
        assert _routed_segments(rm, ctx)[0] == [f"seg_{fn.partition(v)}"]

    def test_ledger_records_prune_and_declines(self):
        store = _store_with_partitioned_segments()
        rm = RoutingManager(store)
        stats = QueryStats()
        ctx = compile_query("SELECT count(*) FROM part WHERE k = 2")
        _routed_segments(rm, ctx, stats=stats)
        assert stats.decisions.get(
            "routing:all_servers->pruned:partition_prune") == 1
        # no usable predicate -> the decline says WHY nothing was pruned
        stats = QueryStats()
        _routed_segments(rm, compile_query("SELECT count(*) FROM part"),
                         stats=stats)
        assert stats.decisions.get(
            "routing:pruned->all_servers:no_filter") == 1
        stats = QueryStats()
        _routed_segments(
            rm, compile_query("SELECT count(*) FROM part WHERE k > 3"),
            stats=stats)
        assert stats.decisions.get(
            "routing:pruned->all_servers:no_partition_predicate") == 1

    def test_no_metadata_declines(self):
        store = ClusterStateStore()
        from pinot_tpu.spi.data import DataType, FieldSpec, Schema

        store.add_schema(Schema("part", [FieldSpec("k", DataType.INT)]))
        store.add_table_config(TableConfig(
            "part", routing_config=RoutingConfig(
                segment_pruner_types=["partition"])))
        store.register_instance(InstanceInfo("s0", "SERVER"))
        store.set_segment_metadata(SegmentZKMetadata(
            segment_name="seg_0", table_name=TABLE))
        store.report_instance_state(TABLE, "seg_0", "s0", ONLINE)
        rm = RoutingManager(store)
        stats = QueryStats()
        ctx = compile_query("SELECT count(*) FROM part WHERE k = 2")
        routed, _ = _routed_segments(rm, ctx, stats=stats)
        assert routed == ["seg_0"]  # nothing prunable, nothing lost
        assert stats.decisions.get(
            "routing:pruned->all_servers:no_partition_metadata") == 1


# (The routing/gather reason-registry conformance test moved to
# tests/test_reasons.py: ONE generic harness parameterized over
# tracing.reason_registry() replaced the per-module scans.)


class TestTimePruning:
    def test_time_prune_with_ledger(self):
        store = _store_with_partitioned_segments(
            time_ranges=[(0, 9), (10, 19), (20, 29), (30, 39)])
        rm = RoutingManager(store)
        stats = QueryStats()
        ctx = compile_query(
            "SELECT count(*) FROM part WHERE ts BETWEEN 12 AND 25")
        routed, res = _routed_segments(rm, ctx, stats=stats)
        assert routed == ["seg_1", "seg_2"]
        assert res.time_pruned == 2
        assert stats.decisions.get(
            "routing:all_servers->pruned:time_prune") == 1


@pytest.fixture(scope="module")
def partitioned_cluster(tmp_path_factory):
    """4 servers x 8 partition-aligned SSB segments (one d_year each,
    Modulo(8) metadata recorded at build), partition pruner enabled."""
    data_dir = str(tmp_path_factory.mktemp("part_cluster"))
    seg_dir = f"{data_dir}/segs"
    segs = ssb.build_segments(0, seg_dir, num_segments=8, rows=4000,
                              partitioned=True, star_tree=False, workers=1)
    cluster = EmbeddedCluster(num_servers=4, data_dir=data_dir)
    cluster.create_table(
        TableConfig("ssb_lineorder",
                    validation_config=SegmentsValidationConfig(
                        time_column_name="d_yearmonthnum"),
                    routing_config=RoutingConfig(
                        segment_pruner_types=["partition"])),
        ssb.ssb_schema())
    for i in range(8):
        cluster.upload_segment_dir("ssb_lineorder_OFFLINE",
                                   f"{seg_dir}/ssb_part_{i}")
    assert cluster.wait_for_ev_converged("ssb_lineorder_OFFLINE")
    yield cluster, segs
    cluster.shutdown()


class TestClusterScatterAccounting:
    def test_partition_filtered_query_prunes_servers(self,
                                                     partitioned_cluster):
        cluster, _ = partitioned_cluster
        resp = cluster.query(ssb.QUERIES["Q1.1"])
        assert not resp.exceptions, resp.exceptions
        # 1993 lives in exactly one segment -> one server of four
        assert resp.num_servers_queried == 1
        assert resp.num_servers_responded == 1
        # the accounting ALSO rides QueryStats (and thus the wire)
        assert resp.stats.num_servers_queried == 1
        assert resp.stats.num_servers_responded == 1
        assert resp.stats.decisions.get(
            "routing:all_servers->pruned:partition_prune") == 1

    def test_unfiltered_query_fans_out_to_all(self, partitioned_cluster):
        cluster, segs = partitioned_cluster
        resp = cluster.query("SELECT count(*) FROM ssb_lineorder")
        assert not resp.exceptions
        assert resp.result_table.rows[0][0] == sum(
            s.metadata.num_docs for s in segs)
        assert resp.num_servers_queried == 4
        assert resp.num_servers_responded == 4
        assert resp.to_dict()["partialResult"] is False
        assert cluster.hosting_servers("ssb_lineorder_OFFLINE") \
            == sorted(cluster.servers)

    def test_pruned_answer_matches_oracle(self, partitioned_cluster):
        """Pruning must be sound: the partition-filtered answer equals the
        pandas oracle over the SAME generated frames."""
        cluster, _ = partitioned_cluster
        frames = [ssb.generate_partitioned_frame(i, 8, 500) for i in
                  range(8)]
        cols = {k: np.concatenate([f[k] for f in frames])
                for k in frames[0]}
        want = ssb.pandas_answer(cols, "Q1.1")
        rows = cluster.query_rows(ssb.QUERIES["Q1.1"])
        assert int(rows[0][0]) == want

    def test_stats_wire_roundtrip_carries_server_counts(self):
        from pinot_tpu.common.datatable import DataTable, ResponseType

        stats = QueryStats(num_servers_queried=7, num_servers_responded=5)
        dt = DataTable(ResponseType.AGGREGATION, {"states": []}, stats, [])
        back = DataTable.from_bytes(dt.to_bytes())
        assert back.stats.num_servers_queried == 7
        assert back.stats.num_servers_responded == 5


@pytest.fixture()
def small_cluster(tmp_path):
    """3 servers, replication 1 — every server owns exclusive segments, so
    losing one MUST yield a partial result (nobody else holds its data)."""
    cluster = EmbeddedCluster(num_servers=3, data_dir=str(tmp_path))
    from pinot_tpu.spi.data import DataType, FieldSpec, FieldType, Schema

    schema = Schema("sales", [
        FieldSpec("region", DataType.STRING),
        FieldSpec("qty", DataType.LONG, FieldType.METRIC)])
    cluster.create_table(TableConfig("sales"), schema)
    rng = np.random.default_rng(7)
    for i in range(3):
        cluster.ingest_rows(
            "sales_OFFLINE", schema,
            {"region": ["east", "west"] * 50,
             "qty": rng.integers(1, 9, 100).tolist()},
            segment_name=f"sales_{i}")
    assert cluster.wait_for_ev_converged("sales_OFFLINE")
    yield cluster
    cluster.shutdown()


def _assert_no_pins(cluster, skip=()):
    for sid, server in cluster.servers.items():
        if sid in skip:
            continue
        snap = server.executor.residency.snapshot()
        pinned = {n: d["pins"] for n, d in snap["stagedSegments"].items()
                  if d["pins"]}
        assert not pinned, f"{sid} leaked pins after partial gather: {pinned}"


class TestPartialGather:
    def test_timed_out_server_yields_partial_with_accounting(
            self, small_cluster, monkeypatch):
        cluster = small_cluster
        victim_id = sorted(cluster.servers)[0]
        victim = cluster.servers[victim_id]
        real = victim.execute_query
        release = [0.6]

        def slow(ctx, table, segment_names=None):
            time.sleep(release[0])
            return real(ctx, table, segment_names)

        monkeypatch.setattr(victim, "execute_query", slow)
        monkeypatch.setattr(cluster.broker, "query_timeout_s", 0.15)
        resp = cluster.query("SELECT sum(qty) FROM sales")
        # partial result: the surviving servers' table stands, the broker
        # flags the loss loudly instead of hanging or silently lying
        assert resp.result_table is not None
        assert resp.num_servers_queried == 3
        assert resp.num_servers_responded < resp.num_servers_queried
        assert resp.stats.num_servers_responded \
            < resp.stats.num_servers_queried
        assert any("timed out" in e["message"] for e in resp.exceptions)
        assert resp.to_dict()["partialResult"] is True
        assert resp.stats.decisions.get(
            "gather:full_result->partial_result:server_timeout") == 1
        # no pin/lease leak anywhere: the survivors released at end_query,
        # and the straggler releases when its execution finally finishes
        time.sleep(release[0] + 0.3)
        _assert_no_pins(cluster)

    def test_downed_server_yields_partial_not_wrong(self, small_cluster):
        cluster = small_cluster
        full = cluster.query("SELECT count(*) FROM sales")
        assert full.result_table.rows[0][0] == 300
        victim_id = sorted(cluster.servers)[1]
        victim = cluster.servers[victim_id]
        victim._queries_enabled = False  # kill mid-scatter: typed refusal
        try:
            resp = cluster.query("SELECT count(*) FROM sales")
            assert resp.result_table is not None
            # partial, and SAYS so: fewer rows counted, responded < queried
            assert resp.result_table.rows[0][0] < 300
            assert resp.num_servers_responded < resp.num_servers_queried
            assert resp.stats.decisions.get(
                "gather:full_result->partial_result:server_error") == 1
            assert resp.exceptions
            _assert_no_pins(cluster)
        finally:
            victim._queries_enabled = True
        assert cluster.query(
            "SELECT count(*) FROM sales").result_table.rows[0][0] == 300


class TestReplicaGroupFanOut:
    def test_one_group_of_eight_serves_each_query(self, tmp_path):
        """8 servers in 2 replica groups of 4: every query scatters to at
        most one group — the reference's QPS-scaling story at the ISSUE's
        target server count."""
        cluster = EmbeddedCluster(num_servers=8, data_dir=str(tmp_path))
        try:
            from pinot_tpu.spi.data import (
                DataType,
                FieldSpec,
                FieldType,
                Schema,
            )

            schema = Schema("rg8", [
                FieldSpec("region", DataType.STRING),
                FieldSpec("qty", DataType.LONG, FieldType.METRIC)])
            cluster.create_table(
                TableConfig("rg8",
                            validation_config=SegmentsValidationConfig(
                                replication=2),
                            routing_config=RoutingConfig(
                                instance_selector_type="replicaGroup")),
                schema)
            groups = cluster.store.get_instance_partitions("rg8_OFFLINE")
            assert len(groups) == 2 and all(len(g) == 4 for g in groups)
            for i in range(8):
                cluster.ingest_rows(
                    "rg8_OFFLINE", schema,
                    {"region": ["east"] * 40, "qty": list(range(40))},
                    segment_name=f"rg8_{i}")
            assert cluster.wait_for_ev_converged("rg8_OFFLINE")
            for _ in range(4):
                resp = cluster.query("SELECT count(*) FROM rg8")
                assert not resp.exceptions, resp.exceptions
                assert resp.result_table.rows[0][0] == 320
                # fan-out bounded by one replica group
                assert resp.num_servers_queried <= 4
        finally:
            cluster.shutdown()
