"""Scheduler-tier tests: single-flight coalescing + admission control.

The millions-of-users tier contracts:

- ``SingleFlight``: N concurrent identical calls -> 1 execution, every
  caller gets the SAME result object (bit-identical by construction);
  failures propagate to all; the flight table never caches results.
- Broker coalescing: concurrent identical SQL shares one execution;
  a cluster-state mutation (table generation bump) prevents later
  arrivals from joining a stale in-flight answer.
- ``AdmissionGate``: past the bounded queue -> immediate typed
  rejection carrying queue depth; queued waiters are rejected at the
  wait bound (bounded latency); quota trips are the same typed error.
- Reject-path hygiene: a rejected query holds NO residency lease — the
  manager's byte/pin accounting is untouched (the lease opens strictly
  after admission; graftlint's pairing family guards the pairing).
"""

import threading
import time

import numpy as np
import pytest

from pinot_tpu.broker.broker import (
    TOO_MANY_REQUESTS_ERROR,
    BrokerRequestHandler,
)
from pinot_tpu.common.singleflight import SingleFlight
from pinot_tpu.controller.state import ClusterStateStore
from pinot_tpu.engine import ServerQueryExecutor
from pinot_tpu.engine.errors import QueryError, QueryRejectedError
from pinot_tpu.query import compile_query
from pinot_tpu.segment import SegmentBuilder, load_segment
from pinot_tpu.server.admission import AdmissionGate
from pinot_tpu.spi import DataType, FieldSpec, FieldType, Schema
from pinot_tpu.spi.table import QuotaConfig, TableConfig

RNG = np.random.default_rng(7)


# --------------------------------------------------------------------------
# SingleFlight
# --------------------------------------------------------------------------

class TestSingleFlight:
    def test_concurrent_identical_calls_share_one_execution(self):
        sf = SingleFlight()
        calls = []
        entered = threading.Event()
        go = threading.Event()

        def work():
            calls.append(1)
            entered.set()
            go.wait(10)
            return {"rows": [1, 2, 3]}

        results = []
        lock = threading.Lock()

        def run():
            out, coalesced = sf.do("k", work)
            with lock:
                results.append((out, coalesced))

        leader = threading.Thread(target=run)
        leader.start()
        assert entered.wait(10)
        followers = [threading.Thread(target=run) for _ in range(4)]
        for t in followers:
            t.start()
        # every follower must be REGISTERED on the flight before release
        deadline = time.monotonic() + 10
        while sf.snapshot()["hits"] < 4 and time.monotonic() < deadline:
            time.sleep(0.001)
        assert sf.snapshot()["hits"] == 4
        go.set()
        leader.join(10)
        for t in followers:
            t.join(10)
        assert len(calls) == 1, "exactly one execution"
        outs = [r for r, _ in results]
        assert all(o is outs[0] for o in outs), \
            "all callers share the SAME result object (bit-identical)"
        assert sorted(c for _, c in results) == [False] + [True] * 4
        assert sf.inflight() == 0

    def test_exception_propagates_to_followers_and_flight_clears(self):
        sf = SingleFlight()
        entered = threading.Event()
        go = threading.Event()

        def boom():
            entered.set()
            go.wait(10)
            raise QueryError("inner failure")

        errs = []

        def run():
            try:
                sf.do("k", boom)
            except QueryError as e:
                errs.append(str(e))

        leader = threading.Thread(target=run)
        leader.start()
        assert entered.wait(10)
        follower = threading.Thread(target=run)
        follower.start()
        deadline = time.monotonic() + 10
        while sf.snapshot()["hits"] < 1 and time.monotonic() < deadline:
            time.sleep(0.001)
        go.set()
        leader.join(10)
        follower.join(10)
        assert errs == ["inner failure"] * 2
        assert sf.inflight() == 0
        # a later call starts a FRESH flight (failures are not cached)
        out, coalesced = sf.do("k", lambda: "ok")
        assert (out, coalesced) == ("ok", False)

    def test_none_key_never_coalesces(self):
        sf = SingleFlight()
        assert sf.do(None, lambda: 1) == (1, False)
        assert sf.snapshot() == {"leaders": 0, "hits": 0, "inflight": 0}


# --------------------------------------------------------------------------
# broker single-flight
# --------------------------------------------------------------------------

def _broker():
    return BrokerRequestHandler(ClusterStateStore())


class TestBrokerCoalescing:
    def test_identical_concurrent_queries_share_one_execution(self):
        broker = _broker()
        calls = []
        entered = threading.Event()
        go = threading.Event()

        def fake_handle(sql, principal=None, access_control=None):
            calls.append(sql)
            entered.set()
            go.wait(10)
            return {"sql": sql, "rows": [[42]]}

        broker._handle_sql = fake_handle
        results = []
        lock = threading.Lock()

        def run():
            r = broker.handle_sql("SELECT 1  FROM t")
            with lock:
                results.append(r)

        leader = threading.Thread(target=run)
        leader.start()
        assert entered.wait(10)
        # whitespace-normalized duplicates join the same flight
        followers = [threading.Thread(target=lambda: results.append(
            broker.handle_sql("SELECT 1 FROM t"))) for _ in range(3)]
        for t in followers:
            t.start()
        deadline = time.monotonic() + 10
        while broker._flights.snapshot()["hits"] < 3 \
                and time.monotonic() < deadline:
            time.sleep(0.001)
        go.set()
        leader.join(10)
        for t in followers:
            t.join(10)
        assert len(calls) == 1, "one execution served all four callers"
        assert all(r is results[0] for r in results), "fanned-out result"
        snap = broker.scheduler_snapshot()["singleFlight"]
        assert snap["hits"] == 3 and snap["leaders"] == 1

    def test_generation_bump_prevents_joining_stale_flight(self):
        broker = _broker()
        calls = []
        first_gate = threading.Event()
        entered = threading.Event()

        def fake_handle(sql, principal=None, access_control=None):
            calls.append(sql)
            if len(calls) == 1:
                entered.set()
                first_gate.wait(10)
            return {"n": len(calls)}

        broker._handle_sql = fake_handle
        leader = threading.Thread(
            target=lambda: broker.handle_sql("SELECT 1 FROM t"))
        leader.start()
        assert entered.wait(10)
        # a table-config push bumps the cluster-state version: the SAME
        # SQL arriving now must NOT join the in-flight stale answer
        broker.store.set("tables/t_OFFLINE", {"changed": True})
        second = broker.handle_sql("SELECT 1 FROM t")
        assert second == {"n": 2}, "post-mutation arrival ran fresh"
        first_gate.set()
        leader.join(10)
        assert len(calls) == 2

    def test_principal_and_now_queries_do_not_coalesce(self):
        broker = _broker()
        assert broker._flight_key("SELECT now() FROM t", None, None) is None
        k_a = broker._flight_key("SELECT 1 FROM t",
                                 type("P", (), {"name": "alice"})(), None)
        k_b = broker._flight_key("SELECT 1 FROM t",
                                 type("P", (), {"name": "bob"})(), None)
        assert k_a != k_b, "different principals never share a flight"

    def test_quota_rejection_is_429_with_queue_depth(self):
        store = ClusterStateStore()
        store.add_table_config(TableConfig(
            "t", quota_config=QuotaConfig(max_queries_per_second=1)))
        broker = BrokerRequestHandler(store)
        broker._scatter_reduce = lambda *a, **k: a[3]  # response passthru
        ok = broker.handle_sql("SELECT count(*) FROM t_OFFLINE")
        assert not any(e["errorCode"] == TOO_MANY_REQUESTS_ERROR
                       for e in ok.exceptions)
        throttled = broker.handle_sql("SELECT count(*) FROM t_OFFLINE "
                                      "OPTION(x=1)")
        codes = [e["errorCode"] for e in throttled.exceptions]
        assert codes == [TOO_MANY_REQUESTS_ERROR]
        assert "retriable" in throttled.exceptions[0]["message"]
        assert broker.admission.stats_snapshot()["rejectedQuota"] == 1


# --------------------------------------------------------------------------
# AdmissionGate
# --------------------------------------------------------------------------

class TestAdmissionGate:
    def test_queue_full_rejects_immediately_with_typed_error(self):
        gate = AdmissionGate(max_concurrent=1, max_queue=1,
                             max_wait_ms=5000)
        held = gate.admit("t")
        waiter_err = []

        def waiter():
            try:
                t = gate.admit("t")
                gate.release(t)
            except QueryRejectedError as e:
                waiter_err.append(e)

        w = threading.Thread(target=waiter)
        w.start()
        deadline = time.monotonic() + 10
        while gate.snapshot()["queued"] < 1 and time.monotonic() < deadline:
            time.sleep(0.001)
        t0 = time.monotonic()
        with pytest.raises(QueryRejectedError) as ei:
            gate.admit("t")
        assert (time.monotonic() - t0) < 1.0, "queue-full reject is instant"
        assert isinstance(ei.value, QueryError)
        assert ei.value.retriable is True
        assert ei.value.reason == "queue_full"
        assert ei.value.queue_depth == 1
        assert ei.value.code == 429
        gate.release(held)
        w.join(10)
        assert not waiter_err, "the queued waiter got the freed slot"
        snap = gate.stats_snapshot()
        assert snap["rejectedQueueFull"] == 1
        assert snap["admitted"] == 2

    def test_wait_bound_rejects_queued_waiter(self):
        gate = AdmissionGate(max_concurrent=1, max_queue=4, max_wait_ms=100)
        held = gate.admit("t")
        t0 = time.monotonic()
        with pytest.raises(QueryRejectedError) as ei:
            gate.admit("t")
        waited = time.monotonic() - t0
        assert 0.05 < waited < 2.0, f"bounded wait, not forever ({waited})"
        assert ei.value.reason == "wait_expired"
        gate.release(held)
        # slot freed: admission works again
        t = gate.admit("t")
        gate.release(t)
        assert gate.stats_snapshot()["rejectedWaitExpired"] == 1

    def test_release_is_idempotent_and_reconfigure_wakes_waiters(self):
        gate = AdmissionGate(max_concurrent=1, max_queue=4,
                             max_wait_ms=5000)
        held = gate.admit("t")
        gate.release(held)
        gate.release(held)  # double release must not free a phantom slot
        a = gate.admit("t")
        got = []

        def waiter():
            t = gate.admit("t")
            got.append(t)
            gate.release(t)

        w = threading.Thread(target=waiter)
        w.start()
        deadline = time.monotonic() + 10
        while gate.snapshot()["queued"] < 1 and time.monotonic() < deadline:
            time.sleep(0.001)
        gate.configure(max_concurrent=2)  # widened: waiter admits now
        w.join(10)
        assert got, "configure() wakes and admits the queued waiter"
        gate.release(a)

    def test_disabled_gate_admits_everything(self):
        gate = AdmissionGate(max_concurrent=-1, max_queue=0, max_wait_ms=1)
        tickets = [gate.admit("t") for _ in range(64)]
        assert gate.stats_snapshot()["admitted"] == 64
        for t in tickets:
            gate.release(t)


# --------------------------------------------------------------------------
# executor admission: reject path leaks nothing
# --------------------------------------------------------------------------

def _schema():
    return Schema("s", [
        FieldSpec("k", DataType.STRING),
        FieldSpec("v", DataType.LONG, FieldType.METRIC),
    ])


@pytest.fixture(scope="module")
def seg(tmp_path_factory):
    out = tmp_path_factory.mktemp("adm_segs")
    b = SegmentBuilder(_schema(), "s_0")
    b.build({"k": [["a", "b"][i % 2] for i in range(512)],
             "v": list(range(512))}, str(out))
    return load_segment(str(out / "s_0"))


class TestExecutorAdmission:
    def test_reject_path_leaks_no_lease_or_bytes(self, seg):
        ex = ServerQueryExecutor()
        ctx = compile_query("SELECT sum(v) FROM s")
        table, stats = ex.execute(ctx, [seg])
        assert table.rows[0][0] == float(sum(range(512)))
        before = ex.residency.snapshot()
        assert all(r["pins"] == 0
                   for r in before["stagedSegments"].values())

        ex.admission.configure(max_concurrent=1, max_queue=-1,
                               max_wait_ms=50)
        blocker = ex.admission.admit("hold")
        try:
            with pytest.raises(QueryRejectedError) as ei:
                ex.execute(ctx, [seg])
            assert ei.value.retriable
        finally:
            ex.admission.release(blocker)
        after = ex.residency.snapshot()
        # the reject fired BEFORE any lease: pins untouched, bytes stable
        assert all(r["pins"] == 0
                   for r in after["stagedSegments"].values())
        assert after["stagedBytes"] == before["stagedBytes"]
        # and the path recovers: same query, same answer
        table2, _ = ex.execute(ctx, [seg])
        assert table2.rows == table.rows
        assert ex.admission.stats_snapshot()["rejectedQueueFull"] >= 1

    def test_query_singleflight_shares_whole_execution(self, seg):
        """N concurrent identical queries (same compiled ctx object, same
        segment objects) -> ONE execution, shared result object."""
        ex = ServerQueryExecutor()
        ctx = compile_query("SELECT sum(v) FROM s")
        calls = []
        entered = threading.Event()
        go = threading.Event()
        real = ex._execute_admitted

        def counted(c, segs, **kw):
            calls.append(1)
            entered.set()
            go.wait(10)
            return real(c, segs, **kw)

        ex._execute_admitted = counted
        results = []
        lock = threading.Lock()

        def run():
            out = ex.execute(ctx, [seg])
            with lock:
                results.append(out)

        leader = threading.Thread(target=run)
        leader.start()
        assert entered.wait(10)
        followers = [threading.Thread(target=run) for _ in range(3)]
        for t in followers:
            t.start()
        deadline = time.monotonic() + 10
        while ex._query_flight.snapshot()["hits"] < 3 \
                and time.monotonic() < deadline:
            time.sleep(0.001)
        go.set()
        leader.join(10)
        for t in followers:
            t.join(10)
        assert len(calls) == 1, "one whole-query execution for all four"
        assert all(r is results[0] for r in results)
        assert results[0][0].rows[0][0] == float(sum(range(512)))
        # mutable/upsert segments must never share a flight
        class FakeMutable:
            is_mutable = True
        assert ex._query_flight_key(ctx, [FakeMutable()]) is None

    def test_debug_scheduler_snapshot(self, seg):
        """/debug/scheduler body: policy + queue depth, admission bounds,
        launch-window state, kernel single-flight counters."""
        from pinot_tpu.server.server import ServerInstance

        store = ClusterStateStore()
        inst = ServerInstance("Server_adm_0", store)
        d = inst.scheduler_debug()
        assert d["scheduler"]["policy"] == "SewfScheduler"
        assert d["scheduler"]["queued"] == 0
        assert d["admission"]["enabled"] is True
        assert {"maxConcurrent", "maxQueue", "rejected",
                "queued"} <= set(d["admission"])
        assert {"leaders", "hits", "inflight"} == set(d["kernelFlight"])
        # the REST route serves the same body
        from pinot_tpu.transport.rest import ServerAdminApi

        api = ServerAdminApi(inst)
        handler = next(h for m, rx, h, _scope in api._routes
                       if m == "GET" and rx.pattern == r"/debug/scheduler")
        status, body = handler(None, None)
        assert status == 200 and body["scheduler"]["policy"] == \
            "SewfScheduler"

    def test_concurrent_identical_queries_bit_identical(self, seg):
        """Kernel single-flight hammer: concurrent identical queries (the
        dashboard case) must agree bit-for-bit with the serial answer."""
        ex = ServerQueryExecutor()
        ctx = compile_query("SELECT k, sum(v), count(*) FROM s "
                            "GROUP BY k ORDER BY k")
        want, _ = ex.execute(ctx, [seg])
        outs = []
        lock = threading.Lock()

        def run():
            t, _ = ex.execute(ctx, [seg])
            with lock:
                outs.append(t.rows)

        threads = [threading.Thread(target=run) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30)
        assert len(outs) == 8
        assert all(rows == want.rows for rows in outs)
