"""Realtime ingestion core: mutable segment queryability, transformer
pipeline semantics, stream SPI, and the consume->commit state machine
(ref: MutableSegmentImpl / CompositeTransformer / LLRealtimeSegmentDataManager)."""

import numpy as np
import pytest

from pinot_tpu.engine import ServerQueryExecutor
from pinot_tpu.ingestion import (
    CompositeTransformer,
    ConsumerState,
    MemoryStream,
    RealtimeSegmentDataManager,
    StreamOffset,
    transform_rows,
)
from pinot_tpu.query import compile_query
from pinot_tpu.segment import MutableSegment, load_segment
from pinot_tpu.spi import DataType, FieldSpec, FieldType, Schema
from pinot_tpu.spi.table import (
    IngestionConfig,
    StreamIngestionConfig,
    TableConfig,
    TableType,
    TransformConfig,
)

RNG = np.random.default_rng(5)


def make_schema():
    return Schema("events", [
        FieldSpec("user", DataType.STRING),
        FieldSpec("kind", DataType.STRING),
        FieldSpec("tags", DataType.STRING, single_value=False),
        FieldSpec("value", DataType.LONG, FieldType.METRIC),
        FieldSpec("ts", DataType.LONG, FieldType.DATE_TIME),
    ])


def make_rows(n, seed=5):
    rng = np.random.default_rng(seed)
    users = ["u1", "u2", "u3"]
    kinds = ["click", "view", "buy"]
    return [{
        "user": users[int(rng.integers(0, 3))],
        "kind": kinds[int(rng.integers(0, 3))],
        "tags": [f"t{int(x)}" for x in rng.integers(0, 4, int(rng.integers(1, 4)))],
        "value": int(rng.integers(1, 100)),
        "ts": 1_600_000_000_000 + int(rng.integers(0, 10_000_000)),
    } for _ in range(n)]


# --------------------------------------------------------------------------
# mutable segment
# --------------------------------------------------------------------------

class TestMutableSegment:
    def test_index_and_read(self):
        seg = MutableSegment(make_schema(), "events__0")
        rows = make_rows(100)
        for r in rows:
            assert seg.index(dict(r))
        assert seg.num_docs == 100
        assert seg.get_value("user", 0) == rows[0]["user"]
        assert seg.get_value("tags", 3) == rows[3]["tags"]
        assert seg.get_value("value", 99) == rows[99]["value"]

    def test_capacity_limit(self):
        seg = MutableSegment(make_schema(), "events__0", capacity=10)
        rows = make_rows(20)
        accepted = sum(1 for r in rows if seg.index(r))
        assert accepted == 10

    def test_queryable_via_host_engine(self):
        seg = MutableSegment(make_schema(), "events__0")
        rows = make_rows(500)
        for r in rows:
            seg.index(dict(r))
        ex = ServerQueryExecutor()

        t, _ = ex.execute(compile_query(
            "SELECT count(*), sum(value) FROM events WHERE kind = 'click'"), [seg])
        want = [r for r in rows if r["kind"] == "click"]
        assert t.rows[0][0] == len(want)
        assert t.rows[0][1] == pytest.approx(sum(r["value"] for r in want))

        # range over unsorted mutable dictionary
        t2, _ = ex.execute(compile_query(
            "SELECT count(*) FROM events WHERE value BETWEEN 20 AND 50"), [seg])
        assert t2.rows[0][0] == sum(1 for r in rows if 20 <= r["value"] <= 50)

        # group-by + MV predicate
        t3, _ = ex.execute(compile_query(
            "SELECT user, count(*) FROM events WHERE tags = 't1' "
            "GROUP BY user ORDER BY user"), [seg])
        want_g = {}
        for r in rows:
            if "t1" in r["tags"]:
                want_g[r["user"]] = want_g.get(r["user"], 0) + 1
        assert [(r[0], r[1]) for r in t3.rows] == sorted(want_g.items())

    def test_min_max_time_tracked(self):
        seg = MutableSegment(make_schema(), "events__0")
        rows = make_rows(50)
        for r in rows:
            seg.index(dict(r))
        assert seg.min_time == min(r["ts"] for r in rows)
        assert seg.max_time == max(r["ts"] for r in rows)

    def test_build_immutable_round_trip(self, tmp_path):
        seg = MutableSegment(make_schema(), "events__0")
        rows = make_rows(200)
        for r in rows:
            seg.index(dict(r))
        md = seg.build_immutable(str(tmp_path))
        imm = load_segment(str(tmp_path / "events__0"))
        assert imm.num_docs == 200

        ex = ServerQueryExecutor()
        q = compile_query("SELECT kind, sum(value) FROM events GROUP BY kind ORDER BY kind")
        mut_res, _ = ex.execute(q, [seg])
        imm_res, _ = ex.execute(compile_query(
            "SELECT kind, sum(value) FROM events GROUP BY kind ORDER BY kind"), [imm])
        assert mut_res.rows == imm_res.rows


# --------------------------------------------------------------------------
# transformers
# --------------------------------------------------------------------------

class TestTransformers:
    def test_expression_and_filter(self):
        schema = Schema("t", [
            FieldSpec("name", DataType.STRING),
            FieldSpec("ms", DataType.LONG),
            FieldSpec("days", DataType.LONG,
                      transform_function="toEpochDays(ms)"),
        ])
        tc = TableConfig(
            "t", ingestion_config=IngestionConfig(
                filter_function="name = 'drop_me'",
                transform_configs=[TransformConfig("name", "upper(name)")]))
        tr = CompositeTransformer.for_table(tc, schema)
        rows = transform_rows(tr, [
            {"name": "drop_me", "ms": 86_400_000},
            {"name": None, "ms": 86_400_000 * 3},
        ])
        assert len(rows) == 1  # filter dropped the first
        assert rows[0]["days"] == 3
        # expression fills only null destination; name was null -> upper(None) fails -> default
        assert rows[0]["name"] == "null"

    def test_type_coercion_and_nulls(self):
        schema = Schema("t", [
            FieldSpec("a", DataType.INT),
            FieldSpec("b", DataType.DOUBLE, FieldType.METRIC),
        ])
        tr = CompositeTransformer.for_table(None, schema)
        rows = transform_rows(tr, [
            {"a": "42", "b": "3.5", "junk": 1},
            {"a": None, "b": None},
        ])
        assert rows[0]["a"] == 42 and rows[0]["b"] == 3.5
        assert "junk" not in rows[0]
        assert rows[1]["a"] == -2147483648 or rows[1]["a"] is not None  # default null value
        assert rows[1]["__nulls__"] == ["a", "b"]

    def test_null_tracking_survives_pipeline(self):
        """__nulls__ produced by NullValueTransformer must reach the mutable
        segment's null vector (IS NULL parity with directly built segments)."""
        schema = Schema("t", [
            FieldSpec("a", DataType.STRING),
            FieldSpec("b", DataType.LONG, FieldType.METRIC),
        ])
        tr = CompositeTransformer.for_table(None, schema)
        seg = MutableSegment(schema, "t__0")
        for raw in [{"a": "x", "b": 1}, {"a": None, "b": 2}, {"a": "y", "b": None}]:
            seg.index(tr.transform(dict(raw)))
        ex = ServerQueryExecutor()
        t, _ = ex.execute(compile_query("SELECT count(*) FROM t WHERE a IS NULL"), [seg])
        assert t.rows[0][0] == 1
        t2, _ = ex.execute(compile_query("SELECT count(*) FROM t WHERE b IS NOT NULL"), [seg])
        assert t2.rows[0][0] == 2

    def test_complex_flatten(self):
        schema = Schema("t", [
            FieldSpec("user.name", DataType.STRING),
            FieldSpec("user.age", DataType.INT),
        ])
        tr = CompositeTransformer.for_table(None, schema)
        rows = transform_rows(tr, [{"user": {"name": "bob", "age": 7}}])
        assert rows[0]["user.name"] == "bob"
        assert rows[0]["user.age"] == 7


# --------------------------------------------------------------------------
# stream + realtime consumption
# --------------------------------------------------------------------------

def realtime_table(topic, threshold=200):
    return TableConfig(
        "events", table_type=TableType.REALTIME,
        stream_config=StreamIngestionConfig(
            stream_type="memory", topic=topic, decoder="json",
            segment_flush_threshold_rows=threshold))


class TestRealtimeConsumption:
    def test_consume_and_commit(self, tmp_path):
        MemoryStream.create("topic_a", 1)
        rows = make_rows(500, seed=9)
        for r in rows:
            MemoryStream.get("topic_a").produce(r, partition=0)

        mgr = RealtimeSegmentDataManager(
            "events__0__0__20260729T0000Z", realtime_table("topic_a", 200),
            make_schema(), partition=0, start_offset=StreamOffset(0),
            output_dir=str(tmp_path))
        result = mgr.consume_until_committed()
        assert result.state is ConsumerState.COMMITTED
        assert result.rows_indexed == 200
        assert result.final_offset == StreamOffset(200)
        assert result.metadata.custom["segment.realtime.endOffset"] == "200"

        seg = load_segment(result.segment_dir)
        assert seg.num_docs == 200
        MemoryStream.delete("topic_a")

    def test_next_segment_resumes_from_offset(self, tmp_path):
        MemoryStream.create("topic_b", 1)
        for r in make_rows(450, seed=11):
            MemoryStream.get("topic_b").produce(r, partition=0)
        tc = realtime_table("topic_b", 200)

        committed = []
        start = StreamOffset(0)
        for seq in range(2):
            mgr = RealtimeSegmentDataManager(
                f"events__0__{seq}__x", tc, make_schema(), partition=0,
                start_offset=start, output_dir=str(tmp_path))
            res = mgr.consume_until_committed()
            assert res.state is ConsumerState.COMMITTED
            committed.append(res)
            start = res.final_offset
        assert committed[0].final_offset == StreamOffset(200)
        assert committed[1].final_offset == StreamOffset(400)

        # the two sealed segments + the remaining tail are queryable together
        segs = [load_segment(r.segment_dir) for r in committed]
        tail = RealtimeSegmentDataManager(
            "events__0__2__x", tc, make_schema(), partition=0,
            start_offset=start, output_dir=str(tmp_path))
        tail._index_batch()
        assert tail.segment.num_docs == 50
        ex = ServerQueryExecutor()
        t, _ = ex.execute(compile_query("SELECT count(*) FROM events"),
                          segs + [tail.segment])
        assert t.rows[0][0] == 450
        MemoryStream.delete("topic_b")

    def test_background_thread_consumption(self, tmp_path):
        import time

        MemoryStream.create("topic_c", 1)
        tc = realtime_table("topic_c", 100)
        mgr = RealtimeSegmentDataManager(
            "events__0__0__bg", tc, make_schema(), partition=0,
            start_offset=StreamOffset(0), output_dir=str(tmp_path))
        mgr.start(tick_seconds=0.01)
        for r in make_rows(100, seed=13):
            MemoryStream.get("topic_c").produce(r, partition=0)
        deadline = time.time() + 20
        while mgr.state is not ConsumerState.COMMITTED and time.time() < deadline:
            time.sleep(0.05)
        mgr.stop()
        assert mgr.state is ConsumerState.COMMITTED
        assert mgr.rows_indexed == 100
        MemoryStream.delete("topic_c")
