"""Ordered-selection device path: filter + top-k on the accelerator.

Ref: SelectionOrderByOperator.java — the hot realtime shape
(SELECT ... WHERE ... ORDER BY ts DESC LIMIT k) scans and sorts on device;
parity must be EXACT against the numpy host path, including stable-sort
tie semantics (docId order within equal keys).
"""

import numpy as np
import pytest

from pinot_tpu.engine import ServerQueryExecutor
from pinot_tpu.query import compile_query
from pinot_tpu.segment import SegmentBuilder, load_segment
from pinot_tpu.spi import DataType, FieldSpec, FieldType, Schema

N = 9000


@pytest.fixture(scope="module")
def setup(tmp_path_factory):
    out = tmp_path_factory.mktemp("seldev")
    rng = np.random.default_rng(31)
    frame = {
        "host": np.array(["h1", "h2", "h3"])[rng.integers(0, 3, N)],
        "code": rng.integers(200, 600, N).astype(np.int64),
        # heavy ties: only 40 distinct ts values across 9000 rows
        "ts": rng.integers(1000, 1040, N).astype(np.int64),
        "lat": np.round(rng.uniform(0.1, 9.9, N), 3),
    }
    schema = Schema("ev", [
        FieldSpec("host", DataType.STRING),
        FieldSpec("code", DataType.INT),
        FieldSpec("ts", DataType.LONG, FieldType.DATE_TIME),
        FieldSpec("lat", DataType.DOUBLE, FieldType.METRIC),
    ])
    segs = []
    for i, sl in enumerate([slice(0, N // 2), slice(N // 2, N)]):
        SegmentBuilder(schema, f"ev_{i}").build(
            {k: v[sl] for k, v in frame.items()}, str(out))
        segs.append(load_segment(str(out / f"ev_{i}")))
    return segs


@pytest.fixture(scope="module")
def dev():
    return ServerQueryExecutor(use_device=True)


@pytest.fixture(scope="module")
def host():
    return ServerQueryExecutor(use_device=False)


ORDERED = [
    "SELECT host, ts, code FROM ev ORDER BY ts DESC LIMIT 25",
    "SELECT host, ts FROM ev WHERE code >= 500 ORDER BY ts DESC LIMIT 10",
    "SELECT * FROM ev WHERE host = 'h2' ORDER BY ts, code DESC LIMIT 40",
    "SELECT host, lat FROM ev ORDER BY lat LIMIT 17",
    "SELECT ts FROM ev WHERE code BETWEEN 300 AND 400 "
    "ORDER BY code DESC, ts LIMIT 30 OFFSET 5",
    "SELECT host, code FROM ev WHERE host IN ('h1', 'h3') "
    "ORDER BY code LIMIT 1000",
]


def test_device_path_engages(setup, dev):
    rt, _ = dev.execute(compile_query(ORDERED[0]), setup)
    assert rt.rows
    assert len(dev._selection_kernels) >= 1


@pytest.mark.parametrize("sql", ORDERED, ids=[q[:55] for q in ORDERED])
def test_ordered_selection_exact_parity(setup, dev, host, sql):
    """EXACT row-for-row equality — the tie-heavy ts column means any
    deviation from the host's stable-sort semantics fails here."""
    got, _ = dev.execute(compile_query(sql), setup)
    want, _ = host.execute(compile_query(sql), setup)
    assert got.schema.column_names == want.schema.column_names
    assert got.rows == want.rows


def test_string_dict_order_serves_on_device(setup, dev, host):
    """ORDER BY a STRING dictionary column rides the device too: the
    dictionary is sorted, so dictId order IS lexicographic value order."""
    sql = "SELECT host, code FROM ev ORDER BY host, code LIMIT 20"
    before = len(dev._selection_kernels)
    got, _ = dev.execute(compile_query(sql), setup)
    want, _ = host.execute(compile_query(sql), setup)
    assert got.rows == want.rows
    assert len(dev._selection_kernels) > before


def test_expression_order_falls_back(setup, dev, host):
    """ORDER BY an expression is host-served (same results, no kernel)."""
    sql = "SELECT host, code FROM ev ORDER BY code + 1 LIMIT 20"
    before = len(dev._selection_kernels)
    got, _ = dev.execute(compile_query(sql), setup)
    want, _ = host.execute(compile_query(sql), setup)
    assert got.rows == want.rows
    assert len(dev._selection_kernels) == before


def test_through_instance_datatable_path(setup, dev, host):
    """The server DataTable path (hidden order-by columns) serves device
    selections too."""
    from pinot_tpu.broker.reduce import BrokerReduceService

    ctx = compile_query(
        "SELECT host FROM ev WHERE code < 250 ORDER BY ts DESC LIMIT 12")
    t_dev = dev.execute_instance(ctx, setup)
    table, _, _ = BrokerReduceService().reduce(ctx, [t_dev])
    want, _ = host.execute(ctx, setup)
    assert table.rows == want.rows


@pytest.mark.parametrize("qi", range(25))
def test_ordered_selection_fuzz(setup, dev, host, qi):
    """Seeded random ordered selections: exact device/host parity."""
    rng = np.random.default_rng(777 + qi)
    cols = ["host", "code", "ts", "lat"]
    sel = list(rng.choice(cols, size=int(rng.integers(1, 4)),
                          replace=False))
    nord = int(rng.integers(1, 3))
    order = []
    for c in rng.choice(["code", "ts", "lat", "host"], size=nord,
                        replace=False):
        order.append(f"{c} {'DESC' if rng.integers(0, 2) else 'ASC'}")
    where = ""
    if rng.integers(0, 2):
        where = f" WHERE code >= {int(rng.integers(200, 550))}"
    limit = int(rng.integers(1, 60))
    offset = int(rng.integers(0, 10)) if rng.integers(0, 2) else 0
    sql = (f"SELECT {', '.join(sel)} FROM ev{where} "
           f"ORDER BY {', '.join(order)} LIMIT {limit}"
           + (f" OFFSET {offset}" if offset else ""))
    got, _ = dev.execute(compile_query(sql), setup)
    want, _ = host.execute(compile_query(sql), setup)
    assert got.rows == want.rows, sql
