"""JSON flattening index + range index (VERDICT r3 item 7).

Ref: ImmutableJsonIndexReader / segment/creator/impl/inv/json/ (JSON),
BitSlicedRangeIndexReader / RangeIndexBasedFilterOperator (range).
"""

import json

import numpy as np
import pytest

from pinot_tpu.engine import ServerQueryExecutor
from pinot_tpu.engine.plan import plan_segment
from pinot_tpu.query import compile_query
from pinot_tpu.segment import SegmentBuilder, load_segment
from pinot_tpu.segment.jsonindex import (
    flatten_json,
    match_json_value,
    parse_match_filter,
)
from pinot_tpu.spi import DataType, FieldSpec, FieldType, Schema
from pinot_tpu.spi.table import IndexingConfig

N = 4000


class TestFlatten:
    def test_nested_and_arrays(self):
        obj = {"a": {"b": 1}, "tags": ["x", "y"],
               "items": [{"k": "v1"}, {"k": "v2"}], "f": 2.0, "t": True}
        pairs = set(flatten_json(obj))
        assert ("a.b", "1") in pairs
        assert ("tags[*]", "x") in pairs and ("tags[*]", "y") in pairs
        assert ("items[*].k", "v1") in pairs
        assert ("f", "2") in pairs          # 2.0 canonicalizes to "2"
        assert ("t", "true") in pairs

    def test_filter_parser(self):
        ast = parse_match_filter("\"$.a.b\"='x' AND \"$.c\" IS NOT NULL")
        assert ast == ("and", [("eq", "a.b", "x"), ("exists", "c")])
        ast = parse_match_filter("(\"$.a\"=1 OR \"$.a\"=2) AND \"$.b\"!='z'")
        assert ast[0] == "and"
        with pytest.raises(ValueError):
            parse_match_filter("\"$.arr[0]\"='x'")  # exact index unsupported

    def test_match_json_value(self):
        ast = parse_match_filter("\"$.a.b\"='x'")
        assert match_json_value('{"a": {"b": "x"}}', ast)
        assert not match_json_value('{"a": {"b": "y"}}', ast)


def _json_docs(n, seed):
    rng = np.random.default_rng(seed)
    docs = []
    for i in range(n):
        doc = {"user": {"name": f"u{int(rng.integers(0, 50))}",
                        "tier": ["gold", "silver", "bronze"][
                            int(rng.integers(0, 3))]},
               "tags": [f"t{int(x)}" for x in rng.integers(0, 8,
                                                           rng.integers(0, 3))]}
        if i % 5 == 0:
            doc["promo"] = True
        docs.append(json.dumps(doc))
    return docs


@pytest.fixture(scope="module", params=["indexed", "unindexed"])
def seg(request, tmp_path_factory):
    out = str(tmp_path_factory.mktemp(f"js_{request.param}"))
    docs = _json_docs(N, seed=9)
    rng = np.random.default_rng(9)
    schema = Schema("js", [
        FieldSpec("payload", DataType.JSON),
        FieldSpec("v", DataType.LONG, FieldType.METRIC),
        FieldSpec("amt", DataType.LONG, FieldType.METRIC),
    ])
    cfg = IndexingConfig(
        json_index_columns=["payload"] if request.param == "indexed" else [],
        range_index_columns=["amt"] if request.param == "indexed" else [],
        no_dictionary_columns=["amt"])
    b = SegmentBuilder(schema, "js_0", indexing_config=cfg)
    b.build({"payload": docs,
             "v": np.ones(N, dtype=np.int64),
             "amt": rng.integers(0, 100_000, N).astype(np.int64)}, out)
    return load_segment(f"{out}/js_0"), docs


MATCH_QUERIES = [
    ("\"$.user.tier\"='gold'",
     lambda d: d["user"]["tier"] == "gold"),
    ("\"$.tags[*]\"='t3'",
     lambda d: "t3" in d["tags"]),
    ("\"$.user.tier\"='gold' AND \"$.tags[*]\"='t1'",
     lambda d: d["user"]["tier"] == "gold" and "t1" in d["tags"]),
    ("\"$.promo\" IS NOT NULL",
     lambda d: "promo" in d),
    ("\"$.user.tier\"!='gold'",
     lambda d: d["user"]["tier"] != "gold"),
    ("\"$.user.tier\"='gold' OR \"$.user.tier\"='silver'",
     lambda d: d["user"]["tier"] in ("gold", "silver")),
]


class TestJsonMatchQueries:
    @pytest.mark.parametrize("flt,oracle", MATCH_QUERIES,
                             ids=[q[0][:40] for q in MATCH_QUERIES])
    def test_json_match_counts(self, seg, flt, oracle):
        segment, docs = seg
        expected = sum(1 for raw in docs if oracle(json.loads(raw)))
        sql_flt = flt.replace("'", "''")  # SQL single-quote escaping
        for use_device in (True, False):
            ex = ServerQueryExecutor(use_device=use_device)
            rt, _ = ex.execute(compile_query(
                f"SELECT count(*) FROM js "
                f"WHERE json_match(payload, '{sql_flt}')"), [segment])
            assert rt.rows[0][0] == expected, (flt, use_device)

    def test_device_plan_uses_lut(self, seg):
        segment, _ = seg
        plan = plan_segment(compile_query(
            "SELECT count(*) FROM js WHERE "
            "json_match(payload, '\"$.promo\" IS NOT NULL')"), segment)
        assert plan.spec[0][0] == "lut"  # JSON_MATCH rides the device scan


class TestRangeIndex:
    def test_range_index_built_and_matches(self, seg):
        segment, _ = seg
        cm = segment.metadata.column("amt")
        ds = segment.data_source("amt")
        host = ServerQueryExecutor(use_device=False)
        rt, _ = host.execute(compile_query(
            "SELECT count(*), sum(v) FROM js "
            "WHERE amt BETWEEN 20000 AND 30000"), [segment])
        fwd = np.asarray(ds.forward_index[:segment.num_docs])
        expected = int(((fwd >= 20000) & (fwd <= 30000)).sum())
        assert rt.rows[0][0] == expected
        if cm.has_range_index:
            assert ds.range_order is not None
            # permutation sorts the column
            sv = fwd[np.asarray(ds.range_order)]
            assert bool(np.all(sv[:-1] <= sv[1:]))

    def test_exclusive_bounds(self, seg):
        segment, _ = seg
        ds = segment.data_source("amt")
        fwd = np.asarray(ds.forward_index[:segment.num_docs])
        pivot = int(fwd[17])
        host = ServerQueryExecutor(use_device=False)
        rt, _ = host.execute(compile_query(
            f"SELECT count(*) FROM js WHERE amt > {pivot}"), [segment])
        assert rt.rows[0][0] == int((fwd > pivot).sum())
        rt, _ = host.execute(compile_query(
            f"SELECT count(*) FROM js WHERE amt < {pivot}"), [segment])
        assert rt.rows[0][0] == int((fwd < pivot).sum())


class TestReviewRegressions:
    def test_astral_plane_values_in_path_range(self, tmp_path):
        """Keys with values above U+FFFF stay inside the path's prefix
        range (regression: the upper bound used a BMP sentinel)."""
        docs = [json.dumps({"a": "\U0001F600"}), json.dumps({"b": 1})]
        schema = Schema("ap", [FieldSpec("d", DataType.JSON),
                               FieldSpec("v", DataType.LONG,
                                         FieldType.METRIC)])
        cfg = IndexingConfig(json_index_columns=["d"])
        b = SegmentBuilder(schema, "ap_0", indexing_config=cfg)
        b.build({"d": docs, "v": np.ones(2, dtype=np.int64)}, str(tmp_path))
        seg2 = load_segment(f"{tmp_path}/ap_0")
        host = ServerQueryExecutor(use_device=False)
        rt, _ = host.execute(compile_query(
            "SELECT count(*) FROM ap WHERE "
            "json_match(d, '\"$.a\" IS NOT NULL')"), [seg2])
        assert rt.rows[0][0] == 1

    def test_unparseable_doc_consistent_missing(self, tmp_path):
        """Unparseable docs count as 'missing' on BOTH index and fallback
        paths (regression: fallback returned False for IS NULL)."""
        docs = ["{bad json", json.dumps({"a": "x"})]
        schema = Schema("bp", [FieldSpec("d", DataType.JSON),
                               FieldSpec("v", DataType.LONG,
                                         FieldType.METRIC)])
        for use_idx in (True, False):
            cfg = IndexingConfig(json_index_columns=["d"] if use_idx else [])
            name = f"bp_{int(use_idx)}"
            b = SegmentBuilder(schema, name, indexing_config=cfg)
            b.build({"d": docs, "v": np.ones(2, dtype=np.int64)},
                    str(tmp_path))
            seg2 = load_segment(f"{tmp_path}/{name}")
            host = ServerQueryExecutor(use_device=False)
            rt, _ = host.execute(compile_query(
                "SELECT count(*) FROM bp WHERE "
                "json_match(d, '\"$.a\" IS NULL')"), [seg2])
            assert rt.rows[0][0] == 1, use_idx

    def test_bad_filter_is_query_error(self, seg):
        from pinot_tpu.engine.errors import QueryError

        segment, _ = seg
        host = ServerQueryExecutor(use_device=False)
        with pytest.raises(QueryError):
            host.execute(compile_query(
                "SELECT count(*) FROM js WHERE "
                "json_match(payload, '\"$.a\" >')"), [segment])


def test_and_binds_tighter_than_or():
    """SQL precedence in JSON_MATCH filters (regression: flat left-assoc)."""
    ast = parse_match_filter("\"$.a\"=1 OR \"$.b\"=2 AND \"$.c\"=3")
    assert ast == ("or", [("eq", "a", "1"),
                          ("and", [("eq", "b", "2"), ("eq", "c", "3")])])
    assert match_json_value('{"a": 1}', ast)          # a=1 alone satisfies
    assert not match_json_value('{"b": 2}', ast)      # b=2 needs c=3
    assert match_json_value('{"b": 2, "c": 3}', ast)
    # trailing whitespace tolerated
    assert parse_match_filter("\"$.a\"=1 ") == ("eq", "a", "1")
