"""PR 17 realtime serving tier: device-queryable consuming segments
(watermark-snapshot parity at every doc count), the seal-to-star-tree
handoff (seal-under-query hammer, no partial-result window, no pin
leaks), hybrid time-boundary routing vs the merged-table oracle, and
the ingest-to-queryable freshness SLO.

Ref: MutableSegmentImpl serving queries while consuming,
LLRealtimeSegmentDataManager CONSUMING->ONLINE, TimeBoundaryManager,
HybridClusterIntegrationTest.
"""

import threading
import time

import numpy as np
import pandas as pd
import pytest

from pinot_tpu.common.tracing import LEDGER
from pinot_tpu.engine.executor import ServerQueryExecutor
from pinot_tpu.ingestion import MemoryStream
from pinot_tpu.ingestion.realtime import (
    CompletionReply,
    CompletionResponse,
    ConsumerState,
    LocalCompletionProtocol,
    RealtimeSegmentDataManager,
)
from pinot_tpu.ingestion.stream import StreamOffset
from pinot_tpu.query import compile_query
from pinot_tpu.segment.mutable import MutableSegment
from pinot_tpu.server.data_manager import RealtimeTableDataManager
from pinot_tpu.spi import DataType, FieldSpec, FieldType, Schema
from pinot_tpu.spi.table import (
    SegmentsValidationConfig,
    StreamIngestionConfig,
    TableConfig,
    TableType,
)

pytestmark = pytest.mark.realtime_tier

CITIES = ["nyc", "sf", "la", "chi", "sea"]


def make_schema(name="rt"):
    return Schema(name, [
        FieldSpec("city", DataType.STRING, FieldType.DIMENSION),
        FieldSpec("clicks", DataType.LONG, FieldType.METRIC),
        FieldSpec("price", DataType.DOUBLE, FieldType.METRIC),
        FieldSpec("ts", DataType.LONG, FieldType.DATE_TIME),
    ])


def make_row(i, rng):
    return {"city": CITIES[int(rng.integers(len(CITIES)))],
            "clicks": int(rng.integers(100)),
            "price": float(rng.integers(1000)) / 4.0,
            "ts": 1_600_000_000_000 + i}


def rows_key(rows):
    """Group-by row order without ORDER BY is path-dependent (the mutable
    dictionary is arrival-ordered, the immutable one sorted) — parity is
    on the row SET."""
    return sorted(map(repr, rows))


# --------------------------------------------------------------------------
# Consuming segment on the device kernel
# --------------------------------------------------------------------------

class TestConsumingDeviceParity:
    QUERIES = (
        "SELECT city, count(*), sum(clicks), max(price) FROM rt "
        "WHERE clicks > 10 GROUP BY city LIMIT 100",
        "SELECT city, avg(price) FROM rt WHERE city IN ('nyc', 'sf') "
        "GROUP BY city LIMIT 100",
        "SELECT count(*), sum(clicks) FROM rt",
        "SELECT count(*) FROM rt WHERE price > 100.0 AND price <= 200.0",
        "SELECT min(clicks), max(clicks) FROM rt WHERE city <> 'la'",
    )

    def test_parity_at_every_watermark(self):
        """The consuming segment answers through the fused device kernel
        bit-identically to the host engine at every watermark, including
        one below the chunk floor, one mid-chunk, and one that forces
        pow2 capacity regrowth."""
        seg = MutableSegment(make_schema(), "rt__0__0__x", capacity=100_000)
        rng = np.random.default_rng(0)
        dev = ServerQueryExecutor(use_device=True)
        host = ServerQueryExecutor(use_device=False)
        n = 0
        for step in (7, 100, 1500):
            for _ in range(step):
                seg.index(make_row(n, rng))
                n += 1
            for sql in self.QUERIES:
                drt, dstats = dev.execute(compile_query(sql), [seg])
                hrt, _ = host.execute(compile_query(sql), [seg])
                assert rows_key(drt.rows) == rows_key(hrt.rows), \
                    (sql, n, drt.rows, hrt.rows)
                if "GROUP BY" in sql:
                    # parity must come from the DEVICE path, not a silent
                    # host fallback
                    assert dstats.group_by_rung == "mutable_device", \
                        (sql, n, dstats.group_by_rung)

    def test_watermark_snapshot_is_stable_under_writes(self):
        """A snapshot taken at watermark W answers for exactly W rows even
        while the writer keeps appending: two executions bracketing a
        burst of writes see monotonically consistent counts, never a torn
        read of half-published rows."""
        seg = MutableSegment(make_schema(), "rt__0__1__x", capacity=65536)
        rng = np.random.default_rng(1)
        dev = ServerQueryExecutor(use_device=True)
        q = compile_query("SELECT count(*) FROM rt")
        stop = threading.Event()
        errs = []

        def writer():
            i = 0
            while not stop.is_set() and i < 20_000:
                seg.index(make_row(i, rng))
                i += 1

        t = threading.Thread(target=writer, daemon=True)
        t.start()
        try:
            prev = 0
            for _ in range(30):
                cnt = dev.execute(q, [seg])[0].rows[0][0]
                if cnt < prev:
                    errs.append((prev, cnt))
                prev = cnt
        finally:
            stop.set()
            t.join(timeout=10)
        assert not errs, f"count went backwards across snapshots: {errs}"
        # quiesced: the final snapshot sees every published row
        assert dev.execute(q, [seg])[0].rows[0][0] == seg.num_docs

    def test_unsupported_shapes_decline_onto_the_ledger(self):
        """HLL aggregations pre-decline (memoized register LUTs are not
        dictId-stable on a growing dictionary) — served by host, with the
        decline on the decision ledger, never silently."""
        seg = MutableSegment(make_schema(), "rt__0__2__x", capacity=4096)
        rng = np.random.default_rng(2)
        for i in range(50):
            seg.index(make_row(i, rng))
        dev = ServerQueryExecutor(use_device=True)
        host = ServerQueryExecutor(use_device=False)
        mark = LEDGER.snapshot()
        sql = ("SELECT city, distinctcounthll(clicks) FROM rt "
               "GROUP BY city LIMIT 100")
        drt, dstats = dev.execute(compile_query(sql), [seg])
        hrt, _ = host.execute(compile_query(sql), [seg])
        assert rows_key(drt.rows) == rows_key(hrt.rows)
        assert dstats.group_by_rung == "host"
        delta = LEDGER.delta(mark)
        assert any("mutable_hll_lut_unstable" in k for k in delta), delta


# --------------------------------------------------------------------------
# Seal-to-star-tree handoff under concurrent queries
# --------------------------------------------------------------------------

class _GatedProtocol(LocalCompletionProtocol):
    """HOLDs the completion protocol until the test opens the gate, so the
    hammer threads get a long stable consuming phase before the seal."""

    def __init__(self):
        self.gate = threading.Event()

    def segment_consumed(self, segment_name, instance, offset):
        if not self.gate.is_set():
            return CompletionReply(CompletionResponse.HOLD)
        return CompletionReply(CompletionResponse.COMMIT)


class _ResidencyListener:
    """The server's segment-lifecycle -> HBM residency wiring
    (ServerInstance.segment_added/segment_removed), minus the server."""

    def __init__(self, executor):
        self.executor = executor

    def segment_added(self, table, segment):
        residency = self.executor.residency
        if residency is None:
            return
        if not getattr(segment, "is_mutable", False):
            from pinot_tpu.engine.mutable_staging import resident_name

            residency.evict(resident_name(segment.segment_name))
        residency.prefetch(segment)

    def segment_removed(self, table, segment_name):
        evict = getattr(self.executor, "evict_segment", None)
        if evict is not None:
            evict(segment_name)


N_HAMMER_ROWS = 400
HAMMER_SQL = ("SELECT city, count(*), sum(clicks), max(price) FROM rt "
              "GROUP BY city LIMIT 100")


class TestSealUnderQuery:
    def _consuming_table(self, tmp_path, topic, executor):
        MemoryStream.create(topic, 1)
        schema = make_schema()
        cfg = TableConfig(
            "rt", TableType.REALTIME,
            validation_config=SegmentsValidationConfig(
                time_column_name="ts"),
            stream_config=StreamIngestionConfig(
                stream_type="memory", topic=topic,
                segment_flush_threshold_rows=N_HAMMER_ROWS))
        stream = MemoryStream.get(topic)
        rng = np.random.default_rng(5)
        for i in range(N_HAMMER_ROWS):
            stream.produce(make_row(i, rng), partition=0)
        tdm = RealtimeTableDataManager(
            "rt_REALTIME", listener=_ResidencyListener(executor))
        protocol = _GatedProtocol()
        mgr = RealtimeSegmentDataManager(
            "rt__0__0__h", cfg, schema, partition=0,
            start_offset=StreamOffset(0), protocol=protocol,
            output_dir=str(tmp_path),
            on_committed=lambda m, md, d: tdm.on_sealed(m.segment_name, d))
        tdm.add_consuming(mgr)
        return tdm, mgr, protocol

    def test_seal_under_query_hammer(self, tmp_path):
        """4 query threads hammer the table through the seal: every result
        is bit-identical to the full-watermark oracle (the consuming and
        sealed views contain the same 400 rows), every acquire sees
        exactly one registered segment (no partial-result window), and
        after the swap no residency pins leak and the mutable resident's
        chunks are evicted."""
        dev = ServerQueryExecutor(use_device=True)
        host = ServerQueryExecutor(use_device=False)
        tdm, mgr, protocol = self._consuming_table(tmp_path, "rt_hammer",
                                                   dev)
        try:
            mgr.start(tick_seconds=0.002)
            deadline = time.time() + 20
            while mgr.segment.num_docs < N_HAMMER_ROWS:
                assert time.time() < deadline, mgr.segment.num_docs
                time.sleep(0.01)

            sdms = tdm.acquire_segments()
            oracle = rows_key(host.execute(
                compile_query(HAMMER_SQL),
                [s.segment for s in sdms])[0].rows)
            tdm.release_segments(sdms)

            q = compile_query(HAMMER_SQL)
            stop = threading.Event()
            failures = []
            kinds_seen = set()

            def hammer():
                while not stop.is_set():
                    acquired = tdm.acquire_segments()
                    try:
                        if len(acquired) != 1:
                            failures.append(
                                ("partial_window",
                                 [s.segment_name for s in acquired]))
                            continue
                        seg = acquired[0].segment
                        kinds_seen.add(bool(getattr(seg, "is_mutable",
                                                    False)))
                        got = rows_key(dev.execute(q, [seg])[0].rows)
                        if got != oracle:
                            failures.append(("mismatch", got))
                    except Exception as e:  # pragma: no cover - fail loud
                        failures.append(("exception", repr(e)))
                    finally:
                        tdm.release_segments(acquired)

            threads = [threading.Thread(target=hammer, daemon=True)
                       for _ in range(4)]
            for t in threads:
                t.start()
            time.sleep(0.3)          # hammer the consuming segment
            protocol.gate.set()      # trigger build -> commit -> swap
            deadline = time.time() + 30
            while mgr.state is not ConsumerState.COMMITTED:
                assert time.time() < deadline, mgr.state
                time.sleep(0.01)
            time.sleep(0.3)          # hammer the sealed segment
            stop.set()
            for t in threads:
                t.join(timeout=10)

            assert not failures, failures[:5]
            assert kinds_seen == {True, False}, \
                f"hammer never saw both sides of the swap: {kinds_seen}"

            # the registry holds exactly the sealed immutable build
            sdms = tdm.acquire_segments()
            try:
                assert [s.segment_name for s in sdms] == ["rt__0__0__h"]
                sealed = sdms[0].segment
                assert not getattr(sealed, "is_mutable", False)
                # seal stamped the default star-tree set (COUNT + SUM per
                # numeric metric): an eligible group-by serves from the
                # startree_device rung from its first query
                _, st = dev.execute(compile_query(
                    "SELECT city, count(*), sum(clicks) FROM rt "
                    "GROUP BY city LIMIT 100"), [sealed])
                assert st.group_by_rung == "startree_device", \
                    st.group_by_rung
                assert rows_key(dev.execute(q, [sealed])[0].rows) == oracle
            finally:
                tdm.release_segments(sdms)

            staged = dev.residency.snapshot()["stagedSegments"]
            assert all(d["pins"] == 0 for d in staged.values()), staged
            from pinot_tpu.engine.mutable_staging import resident_name

            assert resident_name("rt__0__0__h") not in staged
            # seal wall-time was measured (the bench realtime suite input)
            assert mgr.seal_wall_ms is not None and mgr.seal_wall_ms > 0
        finally:
            tdm.shutdown()
            MemoryStream.delete("rt_hammer")

    def test_seal_swap_lands_on_the_ledger(self, tmp_path):
        dev = ServerQueryExecutor(use_device=True)
        tdm, mgr, protocol = self._consuming_table(tmp_path, "rt_ledger",
                                                   dev)
        try:
            mark = LEDGER.snapshot()
            protocol.gate.set()
            res = mgr.consume_until_committed()
            assert res.state is ConsumerState.COMMITTED
            delta = LEDGER.delta(mark)
            assert any("seal_swap" in k for k in delta), delta
        finally:
            tdm.shutdown()
            MemoryStream.delete("rt_ledger")


# --------------------------------------------------------------------------
# Hybrid time-boundary routing
# --------------------------------------------------------------------------

class TestHybridRouting:
    def test_hybrid_bit_identical_to_merged_oracle(self, tmp_path):
        """Offline + realtime halves of a hybrid table answer exactly like
        one merged table: the time-boundary split must neither double
        count the overlap nor drop rows at the boundary, for both scalar
        aggregations and group-bys; the split outcome lands on the
        decision ledger."""
        from pinot_tpu.tools import EmbeddedCluster

        MemoryStream.create("hy_rt_topic", 1)
        cluster = EmbeddedCluster(num_servers=2, data_dir=str(tmp_path))
        try:
            schema = make_schema("hy")
            off_cfg = TableConfig(
                "hy", TableType.OFFLINE,
                validation_config=SegmentsValidationConfig(
                    time_column_name="ts"))
            rt_cfg = TableConfig(
                "hy", TableType.REALTIME,
                validation_config=SegmentsValidationConfig(
                    time_column_name="ts"),
                stream_config=StreamIngestionConfig(
                    stream_type="memory", topic="hy_rt_topic",
                    segment_flush_threshold_rows=10_000))
            cluster.create_table(off_cfg, schema)
            cluster.controller.add_table(rt_cfg)

            rng = np.random.default_rng(11)
            n = 2000
            df = pd.DataFrame(
                [make_row(i, rng) for i in range(n)]).sort_values(
                    "ts").reset_index(drop=True)
            offline_part = df.iloc[:1200]
            overlap_and_new = df.iloc[1000:]  # overlaps + extends past

            cluster.ingest_rows(
                "hy_OFFLINE", schema,
                {c: offline_part[c].tolist() for c in df.columns},
                segment_name="hy_off_0")
            stream = MemoryStream.get("hy_rt_topic")
            for r in overlap_and_new.to_dict("records"):
                stream.produce(r, partition=0)
            assert cluster.wait_for_ev_converged("hy_OFFLINE")

            boundary = cluster.broker.routing.time_boundary.get_boundary(
                "hy_OFFLINE")
            assert boundary is not None
            # the merged-table oracle: offline rows up to the boundary +
            # realtime rows strictly after it, each row exactly once
            oracle = pd.concat([
                offline_part[offline_part.ts <= boundary],
                overlap_and_new[overlap_and_new.ts > boundary]])

            mark = LEDGER.snapshot()
            deadline = time.time() + 15
            while True:
                rows = cluster.query_rows("SELECT count(*) FROM hy")
                if rows[0][0] == len(oracle) or time.time() > deadline:
                    break
                time.sleep(0.05)
            assert rows[0][0] == len(oracle), (rows, len(oracle))
            assert any("hybrid_time_split" in k
                       for k in LEDGER.delta(mark)), LEDGER.delta(mark)

            rows = cluster.query_rows(
                "SELECT city, count(*), sum(clicks) FROM hy "
                "GROUP BY city ORDER BY city LIMIT 50")
            want = oracle.groupby("city").agg(
                n=("city", "size"), s=("clicks", "sum")).sort_index()
            assert [(r[0], r[1], r[2]) for r in rows] == \
                [(k, int(v.n), float(v.s)) for k, v in want.iterrows()]

            rows = cluster.query_rows(
                "SELECT sum(price), min(ts), max(ts) FROM hy")
            assert rows[0][0] == pytest.approx(float(oracle.price.sum()))
            assert rows[0][1] == int(oracle.ts.min())
            assert rows[0][2] == int(oracle.ts.max())
        finally:
            cluster.shutdown()
            MemoryStream.delete("hy_rt_topic")

    def test_single_table_and_no_boundary_outcomes_ledgered(self, tmp_path):
        """The non-split outcomes are decisions too: a single physical
        table routes direct, a hybrid with no offline boundary routes
        everything to realtime — both on the ledger."""
        from pinot_tpu.tools import EmbeddedCluster

        MeteredTopic = "hy_nb_topic"
        MemoryStream.create(MeteredTopic, 1)
        cluster = EmbeddedCluster(num_servers=1, data_dir=str(tmp_path))
        try:
            schema = make_schema("hynb")
            rt_cfg = TableConfig(
                "hynb", TableType.REALTIME,
                validation_config=SegmentsValidationConfig(
                    time_column_name="ts"),
                stream_config=StreamIngestionConfig(
                    stream_type="memory", topic=MeteredTopic,
                    segment_flush_threshold_rows=10_000))
            cluster.create_table(rt_cfg, schema)
            stream = MemoryStream.get(MeteredTopic)
            rng = np.random.default_rng(17)
            for i in range(20):
                stream.produce(make_row(i, rng), partition=0)
            assert cluster.wait_for_docs("hynb", 20)

            mark = LEDGER.snapshot()
            cluster.query_rows("SELECT count(*) FROM hynb")
            delta = LEDGER.delta(mark)
            assert any("hybrid_single_table" in k for k in delta), delta

            # add the offline half with NO segments: boundary undefined,
            # realtime serves everything
            off_cfg = TableConfig(
                "hynb", TableType.OFFLINE,
                validation_config=SegmentsValidationConfig(
                    time_column_name="ts"))
            cluster.controller.add_table(off_cfg)
            mark = LEDGER.snapshot()
            rows = cluster.query_rows("SELECT count(*) FROM hynb")
            assert rows[0][0] == 20
            delta = LEDGER.delta(mark)
            assert any("hybrid_no_boundary" in k for k in delta), delta
        finally:
            cluster.shutdown()
            MemoryStream.delete(MeteredTopic)


# --------------------------------------------------------------------------
# Freshness SLO
# --------------------------------------------------------------------------

class TestFreshnessSlo:
    def test_serve_path_records_ingest_to_queryable(self):
        """Serving a consuming segment flushes per-row ingest-to-queryable
        latencies into the (table, 'freshness') windowed histogram — each
        row counted once, at the first snapshot that made it queryable."""
        from pinot_tpu.common.telemetry import TELEMETRY

        TELEMETRY.reset()
        seg = MutableSegment(make_schema("fresh"), "fr__0__0__x",
                             capacity=4096)
        rng = np.random.default_rng(23)
        dev = ServerQueryExecutor(use_device=True)
        q = compile_query("SELECT city, count(*) FROM fresh "
                          "GROUP BY city LIMIT 100")
        for i in range(100):
            seg.index(make_row(i, rng))
        dev.execute(q, [seg])
        h = TELEMETRY.histo("fresh", "freshness")
        assert h.lifetime.count == 100
        # repeat query at the same watermark: no double counting
        dev.execute(q, [seg])
        assert h.lifetime.count == 100
        for i in range(40):
            seg.index(make_row(100 + i, rng))
        dev.execute(q, [seg])
        assert h.lifetime.count == 140
        p99 = h.sliding().quantile(0.99)
        assert np.isfinite(p99) and p99 >= 0.0

    def test_freshness_objective_burns_and_surfaces(self):
        """`pinot.broker.slo.<table>.freshness.ms` configures the
        objective; rows staler than it burn the SLO budget, and the
        /debug/freshness snapshot carries histogram + burn state."""
        from pinot_tpu.common.telemetry import Telemetry
        from pinot_tpu.spi.config import PinotConfiguration

        t = Telemetry(window_s=10.0, num_windows=4)
        t.configure(PinotConfiguration(
            {"pinot.broker.slo.fresh.freshness.ms": "100"}, use_env=False))
        assert t.slo.objectives()["fresh"]["freshness_ms"] == 100.0
        # 50 fast rows, 50 stale rows: 50% bad vs 1% allowed -> burn ~50
        for i in range(100):
            t.observe("fresh", "freshness", 500.0 if i % 2 else 5.0)
        snap = t.slo_snapshot()["tables"]["fresh"]
        assert snap["objectives"]["freshness_ms"] == 100.0
        assert snap["freshness"]["long"]["burnRate"] == pytest.approx(
            50.0, rel=0.1)
        burns = t.burn_gauges()
        assert burns[("fresh", "freshness", "long")] == \
            snap["freshness"]["long"]["burnRate"]
        # the debug surface: histogram + objective + burn per table
        dbg = t.freshness_snapshot()
        assert "fresh" in dbg["tables"]
        assert dbg["tables"]["fresh"]["objectiveMs"] == 100.0
        assert dbg["tables"]["fresh"]["histogram"]["lifetime"]["count"] \
            == 100

    def test_debug_freshness_routes_exist(self):
        """Both the broker and server admin APIs expose /debug/freshness
        (wired beside /debug/slo in transport/rest.py)."""
        import inspect

        from pinot_tpu.transport import rest

        src = inspect.getsource(rest)
        assert src.count("/debug/freshness") >= 2
