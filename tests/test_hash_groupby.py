"""Hash-aggregation rung + filter-aware dictId narrowing.

The device group-by ladder (engine/kernels.py) gained a rung between the
dense segment_sum scatter and the sort-based sparse compaction: an
open-addressing hash table over the LIVE docs, with in-kernel fallback to
the sort rung on overflow (probe failure, live-doc window overflow, or
more live groups than the compact cap). plan.py narrows each group
column's dictId range from conjunctive filter predicates so selective
queries drop below the sparse threshold entirely. Every path must stay
bit-exact with the sort rung and the host engine.
"""

import numpy as np
import pytest

from pinot_tpu.engine import ServerQueryExecutor
from pinot_tpu.engine.kernels import SPARSE_MIN_GROUPS, sparse_mode
from pinot_tpu.engine.plan import plan_segment
from pinot_tpu.parallel import ShardedQueryExecutor
from pinot_tpu.query import compile_query
from pinot_tpu.segment import SegmentBuilder, load_segment
from pinot_tpu.spi import DataType, FieldSpec, FieldType, Schema


def _build(tmp, name, frame):
    schema = Schema(name, [
        FieldSpec("a", DataType.STRING),
        FieldSpec("b", DataType.STRING),
        FieldSpec("year", DataType.INT),
        FieldSpec("v", DataType.LONG, FieldType.METRIC),
    ])
    segs = []
    for i in range(2):
        SegmentBuilder(schema, f"{name}_{i}").build(frame, tmp)
        segs.append(load_segment(f"{tmp}/{name}_{i}"))
    return segs


@pytest.fixture(scope="module")
def wide_segs(tmp_path_factory):
    """150 x 150 x 4 key space (~2^17 padded): past SPARSE_MIN_GROUPS."""
    out = str(tmp_path_factory.mktemp("hashwide"))
    rng = np.random.default_rng(11)
    n = 20_000
    frame = {
        "a": [f"a{i:03d}" for i in rng.integers(0, 150, n)],
        "b": [f"b{i:03d}" for i in rng.integers(0, 150, n)],
        "year": rng.integers(2000, 2004, n).tolist(),
        "v": rng.integers(0, 100, n).tolist(),
    }
    return _build(out, "hw", frame)


@pytest.fixture(scope="module")
def tied_segs(tmp_path_factory):
    """Same 150x150 dictionaries but only 150 LIVE (a, b) pairs — every
    group carries ~130 tied docs and every doc is live (no filter)."""
    out = str(tmp_path_factory.mktemp("hashtied"))
    rng = np.random.default_rng(12)
    n = 20_000
    ai = rng.integers(0, 150, n)
    frame = {
        "a": [f"a{i:03d}" for i in ai],
        "b": [f"b{i:03d}" for i in ai],          # b correlates with a
        "year": rng.integers(2000, 2004, n).tolist(),
        "v": rng.integers(0, 100, n).tolist(),
    }
    return _build(out, "ht", frame)


def _parity(sql, segs):
    dev = ShardedQueryExecutor()
    host = ServerQueryExecutor(use_device=False)
    drt, dstats = dev.execute(compile_query(sql), segs)
    hrt, _ = host.execute(compile_query(sql), segs)
    assert drt.rows == hrt.rows, sql
    assert len(drt.rows) > 0
    return dstats


SELECTIVE_SQL = ("SELECT a, b, year, sum(v), count(*), min(v), max(v), "
                 "avg(v) FROM hw WHERE v < 2 "
                 "GROUP BY a, b, year ORDER BY a, b, year LIMIT 15000")


def test_hash_rung_serves_selective_query(wide_segs):
    """Few live rows against a huge key space: the hash table must place
    every key (no sort fallback) and match the host engine exactly."""
    spec = plan_segment(compile_query(SELECTIVE_SQL), wide_segs[0]).spec
    assert sparse_mode(spec) > 0
    stats = _parity(SELECTIVE_SQL, wide_segs)
    assert stats.group_by_rung == "hash"


def test_hash_rung_per_segment_executor(wide_segs):
    """The per-segment executor's in-kernel lax.cond path (the sharded
    combine conds at the device level instead)."""
    dev = ServerQueryExecutor()
    host = ServerQueryExecutor(use_device=False)
    drt, dstats = dev.execute(compile_query(SELECTIVE_SQL), wide_segs)
    hrt, _ = host.execute(compile_query(SELECTIVE_SQL), wide_segs)
    assert drt.rows == hrt.rows
    assert dstats.group_by_rung == "hash"


def test_tie_heavy_full_capacity_live(tied_segs):
    """No filter: every doc is live and groups are heavily tied — the
    live-doc window equals the capacity and accumulation order must stay
    doc order (bit-exact sums vs the host)."""
    sql = ("SELECT a, b, year, sum(v), count(*), avg(v) FROM ht "
           "GROUP BY a, b, year ORDER BY a, b, year LIMIT 15000")
    spec = plan_segment(compile_query(sql), tied_segs[0]).spec
    assert sparse_mode(spec) > 0
    stats = _parity(sql, tied_segs)
    assert stats.group_by_rung == "hash"


def test_probe_overflow_falls_back_to_sort(wide_segs, monkeypatch):
    """Zero probe passes place nothing: the overflow flag must route the
    kernel through the sort rung with identical results."""
    from pinot_tpu.engine import kernels

    monkeypatch.setattr(kernels, "HASH_PROBES", 0)
    stats = _parity(SELECTIVE_SQL, wide_segs)
    assert stats.group_by_rung == "sort"


def test_live_window_overflow_falls_back_to_sort(wide_segs, monkeypatch):
    """More matched docs than the live-doc window: sort rung serves."""
    from pinot_tpu.engine import kernels

    monkeypatch.setattr(kernels, "HASH_LIVE_DOCS", 64)
    stats = _parity(SELECTIVE_SQL, wide_segs)
    assert stats.group_by_rung == "sort"


def test_narrowing_takes_dense_rung(wide_segs):
    """An IN predicate on a group column narrows its dictId range: the
    composed key space drops below SPARSE_MIN_GROUPS and the dense rung
    serves outright (the SSB Q3.3/Q3.4 shape)."""
    sql = ("SELECT a, b, year, sum(v), count(*) FROM hw "
           "WHERE a IN ('a001', 'a002', 'a003') "
           "GROUP BY a, b, year ORDER BY a, b, year LIMIT 15000")
    plan = plan_segment(compile_query(sql), wide_segs[0])
    assert plan.spec[3] < SPARSE_MIN_GROUPS
    assert sparse_mode(plan.spec) == 0
    assert plan.group_bases[0] > 0          # 'a001' is not dictId 0
    stats = _parity(sql, wide_segs)
    assert stats.group_by_rung == "dense"


def test_narrowing_eq_and_range(wide_segs):
    """EQ + RANGE predicates narrow their columns; decode must add the
    bases back so group VALUES stay exact."""
    sql = ("SELECT a, b, year, sum(v) FROM hw "
           "WHERE a = 'a077' AND b BETWEEN 'b100' AND 'b120' "
           "GROUP BY a, b, year ORDER BY a, b, year LIMIT 15000")
    plan = plan_segment(compile_query(sql), wide_segs[0])
    assert plan.group_cards[0] == 1          # a narrowed to the single id
    assert plan.group_cards[1] <= 21         # b narrowed to the range
    stats = _parity(sql, wide_segs)
    assert stats.group_by_rung == "dense"


def test_narrowing_ignores_or_branches(wide_segs):
    """Predicates under OR prove nothing about live docs: no narrowing,
    and results still match."""
    sql = ("SELECT a, b, year, sum(v) FROM hw "
           "WHERE a = 'a001' OR b = 'b140' "
           "GROUP BY a, b, year ORDER BY a, b, year LIMIT 15000")
    plan = plan_segment(compile_query(sql), wide_segs[0])
    assert plan.group_cards[0] == 150        # NOT narrowed
    assert plan.group_cards[1] == 150
    _parity(sql, wide_segs)


def test_group_overflow_still_serves_full_results(wide_segs):
    """More live groups than the compact cap: hash overflows, sort
    overflows too, and the host path serves the complete result."""
    sql = ("SELECT a, b, year, sum(v) FROM hw "
           "GROUP BY a, b, year ORDER BY a, b, year LIMIT 100000")
    stats = _parity(sql, wide_segs)
    assert stats.group_by_rung == "host"
    assert stats.num_docs_scanned > 0
