"""Property test of the segment-completion FSM (SURVEY §7 'port semantics
exactly, property-test it'; ref: SegmentCompletionManager.java:59).

Random replica schedules (arrival order, offsets, crashes) must always
preserve the protocol invariants:

  P1  exactly ONE replica ever receives COMMIT-at-consume and completes
  P2  the committed offset is the max offset reported before election
  P3  after commit, same-offset replicas get KEEP, others DISCARD
  P4  CATCHUP targets are exactly the winner offset
  P5  a crashed committer never wedges the segment (re-election)
"""

import time

import numpy as np
import pytest

from pinot_tpu.controller.completion import SegmentCompletionManager
from pinot_tpu.ingestion.realtime import CompletionResponse
from pinot_tpu.ingestion.stream import StreamOffset


def _drive(mgr, seg, replicas, offsets, rng, crash=None):
    """Replicas report in random order until one commits; returns
    (committer, committed_offset, replies log)."""
    log = []
    committed = None
    committer = None
    alive = {r for r in replicas if r != crash}
    for _ in range(200):
        time.sleep(0.002)  # let hold windows / commit timeouts elapse
        order = list(alive)
        rng.shuffle(order)
        for r in order:
            reply = mgr.segment_consumed(seg, r, offsets[r])
            log.append((r, reply))
            if reply.response is CompletionResponse.CATCHUP:
                # the replica catches up to the target and re-reports
                offsets[r] = reply.target_offset
            elif reply.response is CompletionResponse.COMMIT:
                if r == crash:
                    continue  # crashes before committing
                start = mgr.segment_commit_start(seg, r, offsets[r])
                assert start.response is CompletionResponse.COMMIT
                loc = mgr.segment_commit_upload(seg, r, f"/tmp/{seg}")
                end = mgr.segment_commit_end(seg, r, offsets[r], loc, None)
                assert end.response is CompletionResponse.COMMIT
                committer = r
                committed = offsets[r]
                return committer, committed, log
    raise AssertionError("no replica ever committed")


@pytest.mark.parametrize("seed", range(20))
def test_fsm_invariants_random_schedules(seed):
    rng = np.random.default_rng(seed)
    n = int(rng.integers(1, 5))
    replicas = [f"srv{i}" for i in range(n)]
    # a LONG hold window: the election must wait for all replicas, so the
    # max-initial-offset invariant (P2) is well defined
    mgr = SegmentCompletionManager(
        num_replicas_provider=lambda seg: n, hold_window_s=30.0)
    offsets = {r: StreamOffset(int(rng.integers(50, 100)))
               for r in replicas}
    max_initial = max(o.value for o in offsets.values())

    committer, committed, log = _drive(
        mgr, f"seg_{seed}", replicas, dict(offsets), rng)

    # P2: committed offset is the max reported before election
    assert committed.value == max_initial
    # P4: every CATCHUP pointed at the winner offset
    for r, reply in log:
        if reply.response is CompletionResponse.CATCHUP:
            assert reply.target_offset.value == max_initial
    # P1: only the elected committer got commit_start acceptance
    for r in replicas:
        if r != committer:
            s = mgr.segment_commit_start(f"seg_{seed}", r,
                                         StreamOffset(max_initial))
            assert s.response is not CompletionResponse.COMMIT
    # P3: post-commit reports: same offset -> KEEP, stale -> DISCARD
    same = mgr.segment_consumed(f"seg_{seed}", "late_same",
                                StreamOffset(max_initial))
    assert same.response is CompletionResponse.KEEP
    stale = mgr.segment_consumed(f"seg_{seed}", "late_stale",
                                 StreamOffset(1))
    assert stale.response is CompletionResponse.DISCARD


@pytest.mark.parametrize("seed", range(8))
def test_crashed_committer_reelection(seed):
    """P5: the elected committer crashes (never calls commit_start); after
    the commit timeout another replica is elected and completes."""
    rng = np.random.default_rng(100 + seed)
    replicas = ["srv0", "srv1", "srv2"]
    # SHORT window: re-election after the crash relies on window expiry
    # (only 2 of 3 survivors can ever report)
    mgr = SegmentCompletionManager(
        num_replicas_provider=lambda seg: 3, hold_window_s=0.05,
        max_commit_time_s=0.0)  # immediate re-election on next report
    offsets = {r: StreamOffset(int(rng.integers(50, 100)))
               for r in replicas}
    # find who WOULD win; that replica crashes
    winner = max(offsets.items(), key=lambda kv: (kv[1].value, kv[0]))[0]

    committer, committed, _ = _drive(
        mgr, f"cseg_{seed}", replicas, dict(offsets), rng, crash=winner)
    assert committer != winner
    survivors = {r: o for r, o in offsets.items() if r != winner}
    # the re-election winner had (or caught up to) the surviving max —
    # and the crashed winner's earlier report may legitimately have raised
    # the target before it died
    assert committed.value >= max(o.value for o in survivors.values())
