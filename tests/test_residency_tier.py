"""Tiered residency: host-RAM spill tier + restage-cost-aware eviction +
budget-sliced sharded combine (engine/residency.py, engine/staging.py,
parallel/executor.py).

The invariants the tier guarantees:

- eviction DEMOTES to host numpy copies instead of dropping; a re-stage
  promotes with a plain H2D (no decode/dictionary/pack) and the restored
  arrays are bit-identical to a cold rebuild;
- a working set over the HBM budget is served ON THE DEVICE PATH in
  budget-sized slices (sharded combine slices + the per-segment serial
  fallback), bit-identical to the uncapped oracle — host-engine spill only
  when a single segment alone cannot fit;
- host-tier entries are themselves LRU-dropped under their own budget;
- lease pins survive demotion pressure (a pinned resident never demotes
  mid-query);
- the eviction ranking prefers evicting cheap-to-restage residents
  (host-tier-backed) over expensive ones (star-tree-bearing) at equal
  bytes/recency;
- admission estimates are validated against measured bytes and the
  correction factor feeds back into admission + slice sizing;
- the new ``QueryStats.staging`` keys (promotions/demotions/hostBytes/
  slices) merge and ride the DataTable wire incl. legacy JSON.
"""

import threading

import numpy as np
import pytest

from pinot_tpu.common.datatable import DataTable
from pinot_tpu.engine import QueryStats, ServerQueryExecutor
from pinot_tpu.engine import residency as residency_mod
from pinot_tpu.engine.residency import (
    COST_HOST_RESTAGE,
    COST_STARTREE_BUILD,
    QueryLease,
    ResidencyManager,
)
from pinot_tpu.parallel import ShardedQueryExecutor
from pinot_tpu.parallel.combine import make_combine_mesh
from pinot_tpu.query import compile_query
from pinot_tpu.segment import SegmentBuilder, load_segment
from pinot_tpu.spi import DataType, FieldSpec, FieldType, Schema

pytestmark = pytest.mark.residency_tier

RNG = np.random.default_rng(11)
N = 512
NUM_SEGMENTS = 16
COLUMNS = ("region", "qty")

GROUP_SQL = ("SELECT region, sum(qty), count(*) FROM sales "
             "GROUP BY region ORDER BY region")
AGG_SQL = "SELECT sum(qty), count(*) FROM sales WHERE region != 'west'"


def _schema():
    return Schema("sales", [
        FieldSpec("region", DataType.STRING),
        FieldSpec("qty", DataType.LONG, FieldType.METRIC),
    ])


@pytest.fixture(scope="module")
def segs(tmp_path_factory):
    out = tmp_path_factory.mktemp("tier_segs")
    regions = ["east", "west", "north", "south"]
    built = []
    for i in range(NUM_SEGMENTS):
        b = SegmentBuilder(_schema(), f"sales_{i}")
        b.build({
            "region": [regions[j] for j in RNG.integers(0, 4, N)],
            "qty": RNG.integers(1, 50, N).tolist(),
        }, str(out))
        built.append(load_segment(str(out / f"sales_{i}")))
    return built


def _one_device_mesh():
    """Single-device mesh: batch stacking pads S to the seg-axis width, so
    slice bytes only scale with k on a width-1 mesh — the shape the
    sliced-combine math is exercised on (the 8-virtual-device default mesh
    pads every slice to 8 segments)."""
    import jax

    return make_combine_mesh(jax.devices()[:1])


def _stage_full(rm: ResidencyManager, seg, lease=None):
    st = rm.stage(seg, lease=lease)
    for c in COLUMNS:
        st.column(c)
    return st


@pytest.fixture(scope="module")
def oracle(segs):
    """Uncapped sharded executor: the bit-identical reference for every
    capped/sliced configuration, plus the measured working set."""
    dev = ShardedQueryExecutor(mesh=_one_device_mesh())
    rows = {}
    for name, sql in (("group", GROUP_SQL), ("agg", AGG_SQL)):
        rt, _ = dev.execute(compile_query(sql), segs)
        rows[name] = rt.rows
    ws = dev.residency.staged_bytes()
    assert ws > 0
    return {"rows": rows, "ws": ws}


# --------------------------------------------------------------------------
# demote/promote parity
# --------------------------------------------------------------------------

def test_demote_then_promote_restores_identical_arrays(segs):
    rm = ResidencyManager(budget_bytes=0)
    st = _stage_full(rm, segs[0])
    cold = {c: np.asarray(st.column(c).fwd) for c in COLUMNS}
    cold_vals = np.asarray(st.value_column("qty"))
    assert rm.demote(segs[0].segment_name)
    assert segs[0].segment_name not in rm.resident_names()
    assert segs[0].segment_name in rm.host_entry_names()
    assert rm.host_bytes() > 0

    st2 = _stage_full(rm, segs[0])
    assert st2 is not st
    snap = rm.stats_snapshot()
    assert snap["demotions"] == 1
    assert snap["promotions"] == 1
    # promotion consumed the host entry; bytes moved back to the device
    assert segs[0].segment_name not in rm.host_entry_names()
    assert rm.host_bytes() == 0
    for c in COLUMNS:
        assert np.array_equal(np.asarray(st2.column(c).fwd), cold[c])
    assert np.array_equal(np.asarray(st2.value_column("qty")), cold_vals)


def test_promote_validates_segment_identity(segs):
    """A reloaded segment (same name, new object) must never promote from
    a stale host image — the image is dropped and a cold build serves."""
    rm = ResidencyManager(budget_bytes=0)
    _stage_full(rm, segs[0])
    assert rm.demote(segs[0].segment_name)
    reloaded = load_segment(segs[0].segment_dir)
    st = _stage_full(rm, reloaded)
    assert st.segment is reloaded
    snap = rm.stats_snapshot()
    assert snap["promotions"] == 0
    assert snap["hostDrops"] == 1
    assert rm.host_bytes() == 0


def test_eviction_demotes_instead_of_dropping(segs):
    """The budget evictor's doomed residents land in the host tier (the
    old behavior dropped their bytes outright)."""
    rm = ResidencyManager(budget_bytes=0)
    for s in segs[:3]:
        _stage_full(rm, s)
    per_seg = rm.staged_bytes() // 3
    rm.set_budget_bytes(int(per_seg * 1.5))
    assert rm.stats_snapshot()["demotions"] == 2
    assert rm.host_entry_count() == 2
    assert rm.host_bytes() > 0
    # the demoted residents promote back when budget allows again
    rm.set_budget_bytes(0)
    for s in segs[:3]:
        _stage_full(rm, s)
    assert rm.stats_snapshot()["promotions"] == 2


def test_query_parity_under_demote_promote_churn_vs_uncapped(segs, oracle):
    """Per-segment executor with a budget of ~2 segments: repeated queries
    churn every segment through demote -> promote cycles and every answer
    stays bit-identical to the uncapped oracle."""
    est = residency_mod.estimate_segment_bytes(segs[0], COLUMNS)
    dev = ServerQueryExecutor(hbm_budget_bytes=int(est * 2.5))
    for _ in range(2):
        for name, sql in (("group", GROUP_SQL), ("agg", AGG_SQL)):
            rt, stats = dev.execute(compile_query(sql), segs)
            assert rt.rows == oracle["rows"][name]
            assert stats.staging["spills"] == 0
    snap = dev.residency.stats_snapshot()
    assert snap["demotions"] > 0
    assert snap["promotions"] > 0, \
        "repeat passes must promote from the host tier, not rebuild"
    assert snap["spills"] == 0


# --------------------------------------------------------------------------
# budget-sliced sharded combine
# --------------------------------------------------------------------------

@pytest.mark.parametrize("frac", [4, 10])
def test_sliced_combine_parity_at_fraction_of_working_set(segs, oracle,
                                                          frac):
    ws = oracle["ws"]
    dev = ShardedQueryExecutor(mesh=_one_device_mesh(),
                               hbm_budget_bytes=ws // frac)
    for name, sql in (("group", GROUP_SQL), ("agg", AGG_SQL)):
        rt, stats = dev.execute(compile_query(sql), segs)
        assert rt.rows == oracle["rows"][name], \
            f"sliced combine at ws/{frac} diverged from the oracle"
        assert stats.staging["spills"] == 0, \
            "over-budget query fell to the host engine instead of slicing"
        assert stats.staging["slices"] >= 2
        assert stats.staging["demotions"] >= 1
    # repeat pass: slices promote from the host tier instead of rebuilding
    rt, stats = dev.execute(compile_query(GROUP_SQL), segs)
    assert rt.rows == oracle["rows"]["group"]
    assert stats.staging["promotions"] >= 1
    snap = dev.residency.stats_snapshot()
    assert snap["slicedQueries"] >= 3
    assert snap["spills"] == 0
    assert snap["stagedBytes"] <= ws // frac


def test_sliced_combine_on_padded_mesh_degrades_to_per_segment(segs,
                                                               oracle):
    """On the default (8-virtual-device) mesh every batch pads to 8
    segments, so a small budget can fit no multi-segment slice —
    plan_slices returns None and the per-segment sliced path serves,
    still on device, still exact."""
    est = residency_mod.estimate_segment_bytes(segs[0], COLUMNS)
    dev = ShardedQueryExecutor(hbm_budget_bytes=int(est * 2.5))
    rt, stats = dev.execute(compile_query(GROUP_SQL), segs)
    assert rt.rows == oracle["rows"]["group"]
    assert stats.staging["spills"] == 0
    assert stats.staging["slices"] >= 2


def test_single_segment_over_budget_still_spills(segs):
    """Slicing has a floor: when one segment alone exceeds the budget the
    host engine still serves (host-identical, no device OOM) — the old
    admission contract."""
    host = ServerQueryExecutor(use_device=False)
    want, _ = host.execute(compile_query(GROUP_SQL), segs)
    dev = ShardedQueryExecutor(mesh=_one_device_mesh(), hbm_budget_bytes=64)
    rt, stats = dev.execute(compile_query(GROUP_SQL), segs)
    assert rt.rows == want.rows
    assert stats.staging["spills"] == 1
    assert stats.staging["slices"] == 0


def test_selection_is_not_sliceable(segs):
    """Selection/distinct shapes keep fit-or-spill admission (their
    execution cannot release pins mid-query)."""
    sql = "SELECT region, qty FROM sales ORDER BY qty LIMIT 5"
    host = ServerQueryExecutor(use_device=False)
    want, _ = host.execute(compile_query(sql), segs)
    est = residency_mod.estimate_segment_bytes(segs[0],
                                               ("region", "qty"))
    dev = ShardedQueryExecutor(mesh=_one_device_mesh(),
                               hbm_budget_bytes=int(est * 2.5))
    rt, stats = dev.execute(compile_query(sql), segs)
    assert rt.rows == want.rows
    assert stats.staging["spills"] == 1
    assert stats.staging["slices"] == 0


def test_slicing_disabled_by_config_restores_spill(segs):
    from pinot_tpu.spi.config import CommonConstants, PinotConfiguration

    cfg = PinotConfiguration(
        {CommonConstants.HBM_SLICING_ENABLED_KEY: "false"}, use_env=False)
    host = ServerQueryExecutor(use_device=False)
    want, _ = host.execute(compile_query(GROUP_SQL), segs)
    est = residency_mod.estimate_segment_bytes(segs[0], COLUMNS)
    dev = ShardedQueryExecutor(mesh=_one_device_mesh(),
                               hbm_budget_bytes=int(est * 3), config=cfg)
    rt, stats = dev.execute(compile_query(GROUP_SQL), segs)
    assert rt.rows == want.rows
    assert stats.staging["spills"] == 1


# --------------------------------------------------------------------------
# host-tier budget / LRU
# --------------------------------------------------------------------------

def test_host_tier_lru_drop_under_its_own_budget(segs):
    rm = ResidencyManager(budget_bytes=0)
    for s in segs[:3]:
        _stage_full(rm, s)
    per_seg = rm.staged_bytes() // 3
    # host tier fits roughly one segment image
    rm.set_host_budget_bytes(int(per_seg * 1.5))
    rm.set_budget_bytes(1)  # demote everything
    snap = rm.stats_snapshot()
    assert snap["demotions"] == 3
    assert snap["hostDrops"] >= 2, "host tier never LRU-dropped"
    assert rm.host_bytes() <= int(per_seg * 1.5)
    assert rm.host_entry_count() <= 1
    # the survivor is the most recently demoted (LRU order)
    assert rm.host_entry_names() == [segs[2].segment_name]


def test_host_tier_disabled_drops_on_eviction(segs):
    rm = ResidencyManager(budget_bytes=0)
    rm.set_host_tier_enabled(False)
    _stage_full(rm, segs[0])
    rm.set_budget_bytes(1)
    snap = rm.stats_snapshot()
    assert snap["evictions"] == 1
    assert snap["demotions"] == 0
    assert rm.host_entry_count() == 0


def test_evict_drops_both_tiers(segs):
    rm = ResidencyManager(budget_bytes=0)
    _stage_full(rm, segs[0])
    assert rm.demote(segs[0].segment_name)
    assert rm.host_entry_count() == 1
    rm.evict(segs[0].segment_name)
    assert rm.host_entry_count() == 0
    assert rm.host_bytes() == 0
    assert rm.stats_snapshot()["hostDrops"] == 1


def test_snapshot_reports_both_tiers(segs):
    rm = ResidencyManager(budget_bytes=0)
    _stage_full(rm, segs[0])
    _stage_full(rm, segs[1])
    assert rm.demote(segs[0].segment_name)
    snap = rm.snapshot()
    assert segs[1].segment_name in snap["stagedSegments"]
    tier = snap["hostTier"]
    assert tier["enabled"] is True
    assert segs[0].segment_name in tier["entries"]
    assert tier["entries"][segs[0].segment_name]["bytes"] > 0
    assert tier["hostBytes"] == sum(e["bytes"]
                                    for e in tier["entries"].values())
    assert tier["peakBytes"] >= tier["hostBytes"]


# --------------------------------------------------------------------------
# pins + eviction ranking
# --------------------------------------------------------------------------

def test_lease_pins_survive_demotion_pressure(segs):
    """A pinned resident is never demoted mid-query; once the lease
    closes it demotes normally and the next stage promotes it."""
    rm = ResidencyManager(budget_bytes=0)
    lease = QueryLease()
    st = _stage_full(rm, segs[0], lease=lease)
    rm.set_budget_bytes(1)
    assert segs[0].segment_name in rm.resident_names(), \
        "pinned resident was demoted/evicted under pressure"
    assert rm.host_entry_count() == 0
    # the pinned resident's arrays stayed live on device
    assert st.column("region").fwd is not None
    stats = QueryStats()
    rm.end_query(lease, stats)
    assert segs[0].segment_name not in rm.resident_names()
    assert segs[0].segment_name in rm.host_entry_names()
    assert stats.staging["demotions"] == 1
    assert stats.staging["hostBytes"] > 0
    # promotion after the lease closed
    st2 = _stage_full(rm, segs[0])
    assert rm.stats_snapshot()["promotions"] == 1
    assert st2.segment is segs[0]


def test_eviction_prefers_cheap_to_restage_over_pure_lru(segs):
    """Restage-cost ranking (bytes * staleness / rebuild_cost): at equal
    bytes, a host-tier-backed resident (cost 1) evicts BEFORE an older
    cold resident (cost 4) — pure LRU would pick the older one."""
    rm = ResidencyManager(budget_bytes=0)
    _stage_full(rm, segs[0])  # cold build, OLDER
    _stage_full(rm, segs[1])  # newer, about to gain host backing
    from pinot_tpu.engine.staging import SegmentHostImage

    with rm._lock:
        # white-box: a host image for seg1, as a prior demotion leaves it
        rm._host_entries[segs[1].segment_name] = residency_mod._Entry(
            SegmentHostImage(segs[1]))
        c0 = rm._rebuild_cost_locked(segs[0].segment_name,
                                     rm._entries[segs[0].segment_name])
        c1 = rm._rebuild_cost_locked(segs[1].segment_name,
                                     rm._entries[segs[1].segment_name])
    assert c0 == residency_mod.COST_COLUMN_BUILD
    assert c1 == COST_HOST_RESTAGE
    per = rm.staged_bytes() // 2
    rm.set_budget_bytes(int(per * 1.5))
    names = rm.resident_names()
    assert segs[0].segment_name in names, \
        "cost-aware ranking should keep the expensive-to-rebuild resident"
    assert segs[1].segment_name not in names, \
        "the host-backed (cheap-restage) resident must evict first"


def test_startree_residents_rank_expensive(segs):
    """Star-tree-bearing residents carry the highest rebuild cost — the
    budget preferentially keeps node arrays (tree walk + H2D to rebuild)
    over plain column sets."""
    import jax.numpy as jnp

    from pinot_tpu.engine.staging import StagedSegment

    rm = ResidencyManager(budget_bytes=0)
    st = StagedSegment(segs[0])
    st._startree[0] = {"stdim:a": jnp.zeros(4, dtype=jnp.int32)}
    e = residency_mod._Entry(st)
    with rm._lock:
        assert rm._rebuild_cost_locked("x", e) == COST_STARTREE_BUILD


# --------------------------------------------------------------------------
# admission-estimate drift
# --------------------------------------------------------------------------

def test_estimate_drift_correction_feeds_admission(segs, monkeypatch):
    """A deliberately 4x-under-estimating metadata path: after one staged
    query the EWMA correction rises toward measured/estimated, and the
    corrected estimates change the admission outcome for the same
    budget."""
    real = residency_mod.estimate_segment_bytes
    monkeypatch.setattr(residency_mod, "estimate_segment_bytes",
                        lambda s, c: max(1, real(s, c) // 4))
    rm = ResidencyManager(budget_bytes=0)
    est = residency_mod.estimate_segment_bytes(segs[0], COLUMNS)
    # budget fits the raw (4x-under) 2-segment estimate comfortably, but
    # NOT the corrected one (8x est); one corrected segment (4x) does fit
    rm.set_budget_bytes(int(est * 5))
    lease = rm.begin_query(segs[:2], COLUMNS, sliceable=True)
    assert lease.device_allowed and not lease.sliced, \
        "raw mis-estimate should admit un-sliced"
    for s in segs[:2]:
        _stage_full(rm, s, lease=lease)
    rm.end_query(lease, QueryStats())
    assert rm.est_observations >= 2
    assert rm.estimate_scale() > 1.3, \
        f"EWMA barely moved: {rm.estimate_scale()}"
    # same budget, same query: corrected estimates now exceed it -> the
    # admission outcome flips to sliced
    for _ in range(8):  # converge the EWMA
        rm.observe_estimate(est, est * 4)
    lease2 = rm.begin_query(segs[:2], COLUMNS, sliceable=True)
    assert lease2.sliced, "corrected estimates did not reach admission"
    # and slice sizing shrinks: k segments per slice from real bytes
    chunks = rm.plan_slices(segs[:4], COLUMNS, lease2)
    assert chunks is not None
    assert max(len(c) for c in chunks) <= 2


def test_observe_estimate_clamps():
    rm = ResidencyManager(budget_bytes=0)
    for _ in range(100):
        rm.observe_estimate(1, 1000)  # 1000x drift
    assert rm.estimate_scale() <= 4.0
    for _ in range(100):
        rm.observe_estimate(1000, 1)
    assert rm.estimate_scale() >= 0.25


# --------------------------------------------------------------------------
# wire + merge
# --------------------------------------------------------------------------

def test_tier_stats_merge_counters_sum_bytes_max():
    a = QueryStats(staging={"promotions": 1, "demotions": 2, "slices": 3,
                            "hostBytes": 100, "stagedBytes": 10})
    b = QueryStats(staging={"promotions": 2, "demotions": 1, "slices": 1,
                            "hostBytes": 40, "stagedBytes": 20})
    a.merge(b)
    assert a.staging == {"promotions": 3, "demotions": 3, "slices": 4,
                         "hostBytes": 100, "stagedBytes": 20}


def test_tier_stats_ride_the_datatable_wire():
    stats = QueryStats(num_docs_scanned=5,
                       staging={"hits": 2, "misses": 1, "evictions": 1,
                                "pinBlockedEvictions": 0, "spills": 0,
                                "promotions": 3, "demotions": 2,
                                "slices": 4, "stagedBytes": 4096,
                                "hostBytes": 8192})
    dt = DataTable.for_aggregation([7], stats)
    out = DataTable.from_bytes(dt.to_bytes())
    assert out.stats.staging == stats.staging
    out2 = DataTable.from_bytes(dt.to_json_bytes())
    assert out2.stats.staging == stats.staging


# --------------------------------------------------------------------------
# churn-while-querying hammer
# --------------------------------------------------------------------------

def test_churn_while_querying_hammer(segs, oracle):
    """Multi-thread: capped sliced executors answering queries while a
    churner forces demotions/evictions — no exceptions, every result
    bit-identical to the uncapped oracle, byte accounting consistent."""
    ws = oracle["ws"]
    dev = ShardedQueryExecutor(mesh=_one_device_mesh(),
                               hbm_budget_bytes=ws // 4)
    ctxs = {"group": compile_query(GROUP_SQL),
            "agg": compile_query(AGG_SQL)}
    stop = threading.Event()
    errors = []

    def querier(name):
        while not stop.is_set():
            try:
                rt, _ = dev.execute(ctxs[name], segs)
                if rt.rows != oracle["rows"][name]:
                    errors.append(AssertionError(
                        f"{name}: parity lost under churn"))
                    return
            except Exception as e:  # pragma: no cover - failure mode
                errors.append(e)
                return

    def churner():
        while not stop.is_set():
            for s in segs[::3]:
                try:
                    dev.residency.demote(s.segment_name)
                except Exception as e:  # pragma: no cover - failure mode
                    errors.append(e)
                    return

    threads = [threading.Thread(target=querier, args=(n,))
               for n in ("group", "agg") for _ in range(2)]
    threads.append(threading.Thread(target=churner))
    for t in threads:
        t.start()
    stop.wait(2.0)
    stop.set()
    for t in threads:
        t.join()
    assert not errors, errors[:3]
    # accounting stayed exact across the churn
    snap = dev.residency.snapshot()
    by_resident = sum(e["bytes"] for e in snap["stagedSegments"].values())
    assert snap["stagedBytes"] == by_resident >= 0
    tier = snap["hostTier"]
    assert tier["hostBytes"] == sum(e["bytes"]
                                    for e in tier["entries"].values()) >= 0
