"""PinotFS deep-store SPI + HTTP fetcher + query quota.

Ref: PinotFS.java / LocalPinotFS.java / PinotFSFactory (filesystem),
HttpSegmentFetcher + FileUploadDownloadClient (fetch),
HelixExternalViewBasedQueryQuotaManager.java:55 + HitCounter (quota).
"""

import functools
import http.server
import json
import os
import threading

import numpy as np
import pytest

from pinot_tpu.broker.quota import HitCounter, QueryQuotaManager
from pinot_tpu.spi import DataType, FieldSpec, FieldType, Schema
from pinot_tpu.spi.filesystem import (
    LocalPinotFS,
    fetch_segment,
    get_fs,
    register_fs,
)
from pinot_tpu.spi.table import QuotaConfig, TableConfig
from pinot_tpu.tools.cluster import EmbeddedCluster


def _schema():
    return Schema("fsq", [
        FieldSpec("k", DataType.STRING),
        FieldSpec("v", DataType.LONG, FieldType.METRIC)])


def _build_segment(tmp_path, name="fsq_0"):
    from pinot_tpu.segment import SegmentBuilder

    b = SegmentBuilder(_schema(), name)
    b.build({"k": np.array(["a", "b"] * 100),
             "v": np.arange(200).astype(np.int64)}, str(tmp_path))
    return os.path.join(str(tmp_path), name)


class TestPinotFS:
    def test_scheme_registry(self):
        assert isinstance(get_fs("file:///tmp/x"), LocalPinotFS)
        assert isinstance(get_fs("/tmp/x"), LocalPinotFS)
        assert get_fs("http://h/x").scheme == "http"
        # s3 is a first-class scheme now (spi/s3fs.py, lazily registered)
        assert get_fs("s3://bucket/x").scheme == "s3"
        with pytest.raises(ValueError):
            get_fs("adls://container/x")

        class FakeAdls(LocalPinotFS):
            scheme = "adls"

        register_fs("adls", FakeAdls)
        assert get_fs("adls://container/x").scheme == "adls"

    def test_local_roundtrip(self, tmp_path):
        seg_dir = _build_segment(tmp_path / "src")
        fs = LocalPinotFS()
        dst = str(tmp_path / "store" / "fsq_0")
        fs.copy_from_local_dir(seg_dir, f"file://{dst}")
        assert fs.exists(f"file://{dst}")
        assert any(f.endswith("metadata.json") or "columns" in f
                   for f in fs.list_files(dst))
        # local fetch serves in place (no copy)
        assert fetch_segment(f"file://{dst}", str(tmp_path / "cache")) == dst
        fs.delete(f"file://{dst}")
        assert not fs.exists(dst)

    def test_http_fetch_segment(self, tmp_path):
        """Segment served over HTTP downloads + loads (ref:
        HttpSegmentFetcher; __files__ manifest lists the layout)."""
        seg_dir = _build_segment(tmp_path / "deep")
        manifest = []
        for root, _, files in os.walk(seg_dir):
            for f in files:
                manifest.append(os.path.relpath(os.path.join(root, f),
                                                seg_dir))
        with open(os.path.join(seg_dir, "__files__"), "w") as f:
            json.dump(manifest, f)
        handler = functools.partial(
            http.server.SimpleHTTPRequestHandler,
            directory=str(tmp_path / "deep"))
        httpd = http.server.ThreadingHTTPServer(("localhost", 0), handler)
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        try:
            url = f"http://localhost:{httpd.server_port}/fsq_0"
            local = fetch_segment(url, str(tmp_path / "cache"))
            from pinot_tpu.segment import load_segment

            seg = load_segment(local)
            assert seg.num_docs == 200
        finally:
            httpd.shutdown()


class TestHitCounter:
    def test_sliding_window(self):
        c = HitCounter()
        t0 = 1_000_000
        for i in range(5):
            c.hit(t0 + i * 10)
        assert c.count(t0 + 50) == 5
        assert c.count(t0 + 2000) == 0  # window slid past

    def test_bucket_reuse(self):
        c = HitCounter()
        t0 = 1_000_000
        c.hit(t0)
        c.hit(t0 + 1000)  # same ring slot, newer stamp -> reset
        assert c.count(t0 + 1000) == 1


class TestQueryQuota:
    def test_quota_admission(self, tmp_path):
        cluster = EmbeddedCluster(num_servers=1,
                                  data_dir=str(tmp_path / "c"))
        cfg = TableConfig("fsq", quota_config=QuotaConfig(
            max_queries_per_second=3))
        try:
            cluster.create_table(cfg, _schema())
            cluster.ingest_rows("fsq_OFFLINE", _schema(), {
                "k": np.array(["a", "b"] * 50),
                "v": np.arange(100).astype(np.int64)})
            assert cluster.wait_for_ev_converged("fsq_OFFLINE")
            results = [cluster.query("SELECT count(*) FROM fsq")
                       for _ in range(8)]
            ok = [r for r in results if not r.has_exceptions]
            rejected = [r for r in results if r.has_exceptions]
            assert len(ok) == 3              # admitted within the window
            assert len(rejected) == 5
            assert all("quota" in r.exceptions[0]["message"]
                       for r in rejected)
        finally:
            cluster.shutdown()

    def test_no_quota_unlimited(self, tmp_path):
        cluster = EmbeddedCluster(num_servers=1,
                                  data_dir=str(tmp_path / "c"))
        try:
            cluster.create_table(TableConfig("fsq"), _schema())
            cluster.ingest_rows("fsq_OFFLINE", _schema(), {
                "k": np.array(["a"]), "v": np.array([1], dtype=np.int64)})
            assert cluster.wait_for_ev_converged("fsq_OFFLINE")
            for _ in range(10):
                assert not cluster.query(
                    "SELECT count(*) FROM fsq").has_exceptions
        finally:
            cluster.shutdown()

    def test_quota_config_json_roundtrip(self):
        d = {"tableName": "t", "tableType": "OFFLINE",
             "quota": {"maxQueriesPerSecond": "7.5", "storage": "10G"}}
        cfg = TableConfig.from_dict(d)
        assert cfg.quota_config.max_queries_per_second == 7.5
        assert cfg.to_dict()["quota"]["storage"] == "10G"


def test_http_fetch_rejects_escaping_names(tmp_path):
    """Deep-store manifests cannot write outside the segment dir."""
    from pinot_tpu.spi.filesystem import HttpSegmentFetcher

    class EvilFetcher(HttpSegmentFetcher):
        def list_files(self, uri):
            return ["../../evil.txt"]

        def exists(self, uri):
            return True

    with pytest.raises(ValueError, match="escaping"):
        EvilFetcher().copy_to_local_dir("http://h/seg", str(tmp_path))
