"""Regression tests for the races the graftlint ``threads`` family found
at landing (PR 20) — each was fixed in-code, never baselined.

The lint family proves the static side (every shared-field access is
lock-consistent, role-confined, published-before-spawn, or waived with a
registered reason — see ``tools/lint/threads.py``); these tests pin the
RUNTIME contract of each fix deterministically: lock probes that record
what happened while the lock was held, and a mid-drain watcher
registration that exercises the snapshot semantics directly. No sleeps,
no thread interleaving lotteries.
"""

import threading

from pinot_tpu.common.telemetry import Telemetry
from pinot_tpu.controller.controller import Controller
from pinot_tpu.controller.state import ClusterStateStore


class _LockProbe:
    """Context-manager lock wrapper that records entry count and lets a
    callback observe state while the lock is held (at exit, before
    release) — a deterministic 'did this happen under the lock' probe."""

    def __init__(self, on_exit=None):
        self._lock = threading.Lock()
        self.entries = 0
        self.exit_snapshots = []
        self._on_exit = on_exit

    def __enter__(self):
        self._lock.acquire()
        self.entries += 1
        return self

    def __exit__(self, *exc):
        if self._on_exit is not None:
            self.exit_snapshots.append(self._on_exit())
        self._lock.release()
        return False


def test_telemetry_configure_writes_under_lock():
    """configure() used to assign resolution_s / p99_spike_factor /
    recorder bounds lock-free while the sampler thread read them each
    tick; the writes now serialize through _lock (the fields are
    ``guarded-by-writes``, so lock-guard keeps it that way)."""
    t = Telemetry()
    probe = _LockProbe(on_exit=lambda: (t.resolution_s,
                                        t.p99_spike_factor))
    t._lock = probe
    t.configure()
    assert probe.entries >= 1
    # the locked region saw the post-write values: the assignment
    # happened inside it, not after release
    assert probe.exit_snapshots[-1] == (t.resolution_s,
                                        t.p99_spike_factor)


def test_telemetry_reset_swaps_recorder_under_lock():
    """reset() used to publish ``self.recorder = FlightRecorder(...)``
    AFTER its ``with self._lock`` block closed — a sampler mid-tick
    could see the half-reset object graph. The swap (and the SloTracker
    swap) now happen inside the same locked region that clears the
    histograms."""
    t = Telemetry()
    old_recorder = t.recorder
    old_slo = t.slo
    probe = _LockProbe(on_exit=lambda: (t.recorder, t.slo))
    t._lock = probe
    t.reset()
    assert t.recorder is not old_recorder and t.slo is not old_slo
    # some locked region ended with BOTH replacements already visible
    assert (t.recorder, t.slo) in probe.exit_snapshots


def test_telemetry_reset_preserves_flight_dir():
    t = Telemetry()
    t.recorder.out_dir = "/tmp/flight-xyz"
    t.reset()
    assert t.recorder.out_dir == "/tmp/flight-xyz"


def test_store_watcher_registered_mid_drain_misses_the_batch():
    """_drain_notifications() used to re-read ``list(self._watchers)``
    per batch item with no lock — a watcher registered mid-drain saw an
    arbitrary suffix of the in-flight batch (and the copy itself raced
    the append). The watcher set is now snapshotted once per batch under
    the same lock watch() appends under: a registration during delivery
    sees either the whole NEXT batch or nothing, never a torn suffix."""
    store = ClusterStateStore()
    late_seen = []

    def late(path, value):
        late_seen.append(path)

    registered = []

    def early(path, value):
        if not registered:
            registered.append(True)
            store.watch("k", late)  # no deadlock: delivery is unlocked

    store.watch("k", early)
    # stage a two-event batch directly, then drain once — the only
    # deterministic way to get a multi-item batch single-threaded
    with store._lock:
        store._pending.extend([("k/1", 1), ("k/2", 2)])
    store._drain_notifications()
    assert registered and late_seen == []  # mid-batch: sees none of it
    store.set("k/3", 3)
    assert late_seen == ["k/3"]  # next batch: sees all of it


def test_controller_segment_table_map_is_locked():
    """The segment->table FSM map is written from the REST path and the
    controller-periodic repair loop; every access now takes the
    controller lock (``guarded-by: _lock`` — lock-guard enforces the
    discipline; this pins that the runtime path really acquires it)."""
    c = Controller()
    probe = _LockProbe()
    c._lock = probe
    assert c._table_of("not-an-llc-name") is None
    assert probe.entries >= 1
