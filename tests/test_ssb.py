"""SSB suite parity at test scale (bench.py runs the timed version).

Ref: contrib/pinot-druid-benchmark (the reference's macro benchmark
harness); pandas is the oracle here, mirroring the reference's H2-parity
strategy (SURVEY.md §4.3).
"""

import numpy as np
import pandas as pd
import pytest

from pinot_tpu.engine import ServerQueryExecutor
from pinot_tpu.parallel import ShardedQueryExecutor
from pinot_tpu.query import compile_query
from pinot_tpu.tools import ssb

ROWS = 120_000


@pytest.fixture(scope="module")
def setup(tmp_path_factory):
    out = tmp_path_factory.mktemp("ssb_segs")
    segs = ssb.build_segments(0, str(out), num_segments=4, rows=ROWS)
    cols = ssb.generate_table(4, ROWS)
    return cols, segs


@pytest.fixture(scope="module")
def dev_exec():
    return ShardedQueryExecutor()


@pytest.fixture(scope="module")
def host_exec():
    return ServerQueryExecutor(use_device=False)


@pytest.mark.parametrize("qid", ["Q1.1", "Q1.2", "Q1.3"])
def test_q1_vs_pandas_oracle(setup, dev_exec, qid):
    cols, segs = setup
    rt, _ = dev_exec.execute(compile_query(ssb.QUERIES[qid]), segs)
    exp = ssb.pandas_answer(cols, qid)
    assert rt.rows[0][0] == pytest.approx(exp, rel=1e-4)


def _assert_rows_match(qid, got, want):
    assert len(got.rows) == len(want.rows), qid
    for gr, wr in zip(got.rows, want.rows):
        for g, w in zip(gr, wr):
            if isinstance(w, float):
                assert g == pytest.approx(w, rel=1e-4), (qid, gr, wr)
            else:
                assert g == w, (qid, gr, wr)


@pytest.mark.parametrize("qid", sorted(ssb.QUERIES))
def test_device_matches_host(setup, dev_exec, host_exec, qid):
    cols, segs = setup
    ctx = compile_query(ssb.QUERIES[qid])
    got, _ = dev_exec.execute(ctx, segs)
    want, _ = host_exec.execute(ctx, segs)
    _assert_rows_match(qid, got, want)


def test_capped_hbm_budget_matches_host(setup, host_exec):
    """The residency acceptance bar: with the HBM budget capped below the
    SSB working set, every flight still returns host-engine-identical
    results — wide queries spill to host, narrow ones churn the LRU — and
    nothing device-OOMs. Under the DEFAULT (uncapped) budget the suite
    must not spill at all and must serve warm queries 100% from cache."""
    cols, segs = setup
    probe = ShardedQueryExecutor()
    probe.execute(compile_query(ssb.QUERIES["Q1.1"]), segs)
    one_flight = probe.residency.staged_bytes()
    assert one_flight > 0

    capped = ShardedQueryExecutor(hbm_budget_bytes=int(one_flight * 1.5))
    for qid in sorted(ssb.QUERIES):
        ctx = compile_query(ssb.QUERIES[qid])
        got, stats = capped.execute(ctx, segs)
        want, _ = host_exec.execute(ctx, segs)
        _assert_rows_match(qid, got, want)
        assert "spills" in stats.staging, qid
    snap = capped.residency.stats_snapshot()
    assert snap["spills"] + snap["evictions"] >= 1, \
        "cap below the working set exercised neither churn nor spill"
    budget = capped.residency.budget_bytes
    assert snap["stagedBytes"] <= budget

    # default budget: warm reruns are all hits, never spills
    warm = ShardedQueryExecutor()
    qids = sorted(ssb.QUERIES)[:4]
    for qid in qids:
        warm.execute(compile_query(ssb.QUERIES[qid]), segs)
    for qid in qids:
        _, stats = warm.execute(compile_query(ssb.QUERIES[qid]), segs)
        assert stats.staging["misses"] == 0, qid
        assert stats.staging["spills"] == 0, qid
        assert stats.staging["hits"] >= 1, qid


def test_q2_groupby_vs_pandas(setup, dev_exec):
    cols, segs = setup
    df = pd.DataFrame(cols)
    rt, _ = dev_exec.execute(compile_query(ssb.QUERIES["Q2.1"]), segs)
    m = (df.p_category == "MFGR#12") & (df.s_region == "AMERICA")
    exp = (df[m].groupby(["d_year", "p_brand1"]).lo_revenue.sum()
           .reset_index().sort_values(["d_year", "p_brand1"]).head(10))
    assert len(rt.rows) == min(10, len(exp))
    for row, (_, erow) in zip(rt.rows, exp.iterrows()):
        assert row[0] == erow.d_year and row[1] == erow.p_brand1
        assert row[2] == pytest.approx(erow.lo_revenue, rel=1e-6)


def test_generator_distributions(setup):
    cols, _ = setup
    assert set(np.unique(cols["c_region"])) == set(ssb.REGIONS)
    assert len(np.unique(cols["p_brand1"])) == 1000
    assert len(np.unique(cols["c_city"])) == 250
    assert cols["lo_discount"].min() >= 0 and cols["lo_discount"].max() <= 10
    # revenue derivation holds
    np.testing.assert_array_equal(
        cols["lo_revenue"],
        cols["lo_extendedprice"] * (100 - cols["lo_discount"]) // 100)


def test_all_13_flights_on_sub_scan_rung(setup, dev_exec):
    """PR-13 acceptance: with the default multi-tree config every SSB
    flight serves from the star-tree DEVICE rung — zero
    expression-pair/group-off coverage-gap declines, docs_scanned orders
    of magnitude under the scan, chosen tree recorded."""
    cols, segs = setup
    assert all(s.metadata.star_tree_count == 5 for s in segs)
    for qid in sorted(ssb.QUERIES):
        ctx = compile_query(ssb.QUERIES[qid] + " LIMIT 100000")
        _, stats = dev_exec.execute(ctx, segs)
        served = [k for k in stats.decisions
                  if k.startswith("startree:scan->startree_device:tree")]
        assert served, (qid, stats.decisions)
        assert stats.startree_tree_index is not None, qid
        if stats.group_by_rung:
            assert stats.group_by_rung == "startree_device", \
                (qid, stats.group_by_rung)
        assert stats.num_docs_scanned < ROWS // 10, \
            (qid, stats.num_docs_scanned)
        gap = [k for k in stats.decisions
               if "startree_expression_agg_no_pair" in k
               or "startree_group_off_split_order" in k]
        assert not gap, (qid, gap)


def test_tree_build_times_recorded(setup):
    """The creator stamps per-tree build wall time into segment metadata
    (what the bench sums into the round JSON)."""
    _, segs = setup
    for s in segs:
        bs = s.metadata.star_tree_build_s
        assert len(bs) == s.metadata.star_tree_count
        assert all(b >= 0 for b in bs)
