"""SSB suite parity at test scale (bench.py runs the timed version).

Ref: contrib/pinot-druid-benchmark (the reference's macro benchmark
harness); pandas is the oracle here, mirroring the reference's H2-parity
strategy (SURVEY.md §4.3).
"""

import numpy as np
import pandas as pd
import pytest

from pinot_tpu.engine import ServerQueryExecutor
from pinot_tpu.parallel import ShardedQueryExecutor
from pinot_tpu.query import compile_query
from pinot_tpu.tools import ssb

ROWS = 120_000


@pytest.fixture(scope="module")
def setup(tmp_path_factory):
    out = tmp_path_factory.mktemp("ssb_segs")
    segs = ssb.build_segments(0, str(out), num_segments=4, rows=ROWS)
    cols = ssb.generate_table(4, ROWS)
    return cols, segs


@pytest.fixture(scope="module")
def dev_exec():
    return ShardedQueryExecutor()


@pytest.fixture(scope="module")
def host_exec():
    return ServerQueryExecutor(use_device=False)


@pytest.mark.parametrize("qid", ["Q1.1", "Q1.2", "Q1.3"])
def test_q1_vs_pandas_oracle(setup, dev_exec, qid):
    cols, segs = setup
    rt, _ = dev_exec.execute(compile_query(ssb.QUERIES[qid]), segs)
    exp = ssb.pandas_answer(cols, qid)
    assert rt.rows[0][0] == pytest.approx(exp, rel=1e-4)


@pytest.mark.parametrize("qid", sorted(ssb.QUERIES))
def test_device_matches_host(setup, dev_exec, host_exec, qid):
    cols, segs = setup
    ctx = compile_query(ssb.QUERIES[qid])
    got, _ = dev_exec.execute(ctx, segs)
    want, _ = host_exec.execute(ctx, segs)
    assert len(got.rows) == len(want.rows), qid
    for gr, wr in zip(got.rows, want.rows):
        for g, w in zip(gr, wr):
            if isinstance(w, float):
                assert g == pytest.approx(w, rel=1e-4), (qid, gr, wr)
            else:
                assert g == w, (qid, gr, wr)


def test_q2_groupby_vs_pandas(setup, dev_exec):
    cols, segs = setup
    df = pd.DataFrame(cols)
    rt, _ = dev_exec.execute(compile_query(ssb.QUERIES["Q2.1"]), segs)
    m = (df.p_category == "MFGR#12") & (df.s_region == "AMERICA")
    exp = (df[m].groupby(["d_year", "p_brand1"]).lo_revenue.sum()
           .reset_index().sort_values(["d_year", "p_brand1"]).head(10))
    assert len(rt.rows) == min(10, len(exp))
    for row, (_, erow) in zip(rt.rows, exp.iterrows()):
        assert row[0] == erow.d_year and row[1] == erow.p_brand1
        assert row[2] == pytest.approx(erow.lo_revenue, rel=1e-6)


def test_generator_distributions(setup):
    cols, _ = setup
    assert set(np.unique(cols["c_region"])) == set(ssb.REGIONS)
    assert len(np.unique(cols["p_brand1"])) == 1000
    assert len(np.unique(cols["c_city"])) == 250
    assert cols["lo_discount"].min() >= 0 and cols["lo_discount"].max() <= 10
    # revenue derivation holds
    np.testing.assert_array_equal(
        cols["lo_revenue"],
        cols["lo_extendedprice"] * (100 - cols["lo_discount"]) // 100)
