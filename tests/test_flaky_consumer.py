"""Fault injection: a stream consumer that randomly throws must not lose
data or kill consumption (ref: FlakyConsumerRealtimeClusterIntegrationTest
— a consumer plugin that randomly throws; ChaosMonkey tier of SURVEY §4)."""

import numpy as np
import pytest

from pinot_tpu.ingestion import MemoryStream
from pinot_tpu.ingestion.realtime import (
    ConsumerState,
    RealtimeSegmentDataManager,
)
from pinot_tpu.ingestion.stream import (
    MemoryStreamConsumer,
    MemoryStreamConsumerFactory,
    StreamOffset,
)
from pinot_tpu.spi import DataType, FieldSpec, FieldType, Schema
from pinot_tpu.spi.table import (
    StreamIngestionConfig,
    TableConfig,
    TableType,
)


class FlakyConsumer(MemoryStreamConsumer):
    """Throws on a deterministic schedule: every 3rd fetch fails."""

    def __init__(self, stream, partition):
        super().__init__(stream, partition)
        self.calls = 0
        self.failures = 0

    def fetch_messages(self, start, max_messages=5000, timeout_ms=5000):
        self.calls += 1
        if self.calls % 3 == 1:  # the FIRST fetch fails, then every 3rd
            self.failures += 1
            raise ConnectionError("injected transient stream failure")
        return super().fetch_messages(start, max_messages, timeout_ms)


class FlakyFactory(MemoryStreamConsumerFactory):
    def __init__(self, config):
        super().__init__(config)
        self.consumers = []

    def create_partition_consumer(self, partition):
        c = FlakyConsumer(self._stream(), partition)
        self.consumers.append(c)
        return c


def _schema():
    return Schema("fl", [
        FieldSpec("k", DataType.STRING),
        FieldSpec("v", DataType.LONG, FieldType.METRIC),
        FieldSpec("ts", DataType.LONG, FieldType.DATE_TIME)])


def _table(threshold=500):
    return TableConfig(
        "fl", table_type=TableType.REALTIME,
        stream_config=StreamIngestionConfig(
            stream_type="memory", topic="flaky_events", decoder="json",
            segment_flush_threshold_rows=threshold))


@pytest.fixture
def topic():
    s = MemoryStream.create("flaky_events", 1)
    rng = np.random.default_rng(3)
    for i in range(500):
        s.produce({"k": f"k{i % 5}", "v": int(rng.integers(0, 100)),
                   "ts": i}, partition=0)
    yield s
    MemoryStream.delete("flaky_events")


def test_flaky_consumer_loses_nothing(topic, tmp_path):
    """Every injected failure retries from the same offset: all 500 rows
    land exactly once and the segment commits."""
    cfg = _table()
    factory = FlakyFactory(cfg.stream_config)
    mgr = RealtimeSegmentDataManager(
        "fl__0__0__t0", cfg, _schema(), partition=0,
        start_offset=StreamOffset(0), output_dir=str(tmp_path),
        consumer_factory=factory)
    result = mgr.consume_until_committed()
    assert result.state is ConsumerState.COMMITTED
    assert result.rows_indexed == 500
    assert result.final_offset == StreamOffset(500)
    assert factory.consumers[0].failures > 0  # the fault actually fired


def test_persistent_failure_marks_error(topic, tmp_path):
    """A consumer that ALWAYS throws ends in ERROR (bounded retries), not
    an infinite loop or a dead thread."""

    class DeadConsumer(MemoryStreamConsumer):
        def fetch_messages(self, *a, **k):
            raise ConnectionError("permanently down")

    class DeadFactory(MemoryStreamConsumerFactory):
        def create_partition_consumer(self, partition):
            return DeadConsumer(self._stream(), partition)

    cfg = _table()
    mgr = RealtimeSegmentDataManager(
        "fl__0__1__t0", cfg, _schema(), partition=0,
        start_offset=StreamOffset(0), output_dir=str(tmp_path),
        consumer_factory=DeadFactory(cfg.stream_config))
    result = mgr.consume_until_committed(max_iters=300)
    assert result.state is ConsumerState.ERROR
    assert result.rows_indexed == 0
