"""Sharded combine on a REAL >1-device mesh (``pytest -m cluster_routing``,
part of tier-1).

ISSUE 12's device half: ``make_combine_mesh`` builds from ALL local devices
(the conftest forces 8 virtual CPU devices through
``--xla_force_host_platform_device_count``, subprocess-safe via XLA_FLAGS),
every psum/pmin/pmax in the combine actually crosses device boundaries, and
the results are BIT-identical to the 1-device mesh for all 13 SSB flights.
The PR-8 slice planner pads to the segment axis of the actual mesh, and
launch/coalescing stats stay correct across mesh shapes.

Bit parity is exact (==, not approx): the SSB aggregates are integer-valued
sums accumulated in f64 far below 2^53, so the cross-device reduction order
cannot change a single bit.
"""

import numpy as np
import pytest

from pinot_tpu.parallel import ShardedQueryExecutor, make_combine_mesh
from pinot_tpu.parallel.combine import DOC_AXIS, SEG_AXIS
from pinot_tpu.query import compile_query
from pinot_tpu.tools import ssb

pytestmark = pytest.mark.cluster_routing

NUM_SEGMENTS = 4
ROWS = 10_000  # per-segment capacity pads to 4096 (remainder-tile shape)

QIDS = sorted(ssb.QUERIES)


@pytest.fixture(scope="module")
def ssb_segs(tmp_path_factory):
    # star_tree=False: every flight must ride the sharded combine (a tree
    # would reroute Q2.x onto the per-segment star-tree rung)
    out = tmp_path_factory.mktemp("mesh_ssb")
    return ssb.build_segments(0, str(out), num_segments=NUM_SEGMENTS,
                              rows=ROWS, star_tree=False, workers=1)


@pytest.fixture(scope="module")
def exec_1dev(forced_mesh_devices):
    mesh = make_combine_mesh(devices=forced_mesh_devices[:1])
    return ShardedQueryExecutor(mesh=mesh)


@pytest.fixture(scope="module")
def exec_8dev(forced_mesh_devices):
    mesh = make_combine_mesh(devices=forced_mesh_devices)
    assert mesh.shape[SEG_AXIS] == 8 and mesh.shape[DOC_AXIS] == 1
    return ShardedQueryExecutor(mesh=mesh)


def test_default_mesh_spans_all_local_devices(forced_mesh_devices):
    """make_combine_mesh() with no argument must take EVERY local device —
    the 1-device mesh every pre-ISSUE-12 measurement ran on is now only
    reachable by explicit request."""
    mesh = make_combine_mesh()
    assert mesh.devices.size == len(forced_mesh_devices) == 8


@pytest.mark.parametrize("qid", QIDS)
def test_ssb_bit_parity_8dev_vs_1dev(ssb_segs, exec_1dev, exec_8dev, qid):
    sql = ssb.QUERIES[qid] + " LIMIT 100000"
    rt1, st1 = exec_1dev.execute(compile_query(sql), ssb_segs)
    rt8, st8 = exec_8dev.execute(compile_query(sql), ssb_segs)
    assert len(rt8.rows) == len(rt1.rows)
    for r8, r1 in zip(rt8.rows, rt1.rows):
        assert r8 == r1  # BIT parity, incl. the float aggregate cells
    # stats parity across mesh shapes: same docs matched, same server-side
    # min/max pruning, same rung story (prune + process covers the table)
    assert st8.num_docs_scanned == st1.num_docs_scanned
    assert st8.num_segments_processed == st1.num_segments_processed
    assert st8.num_segments_pruned == st1.num_segments_pruned
    assert st8.num_segments_processed + st8.num_segments_pruned \
        == NUM_SEGMENTS
    assert st8.group_by_rung == st1.group_by_rung


def test_doc_axis_sharding_bit_parity(ssb_segs, exec_1dev,
                                      forced_mesh_devices):
    """4x2 mesh: the doc dimension ALSO crosses devices (context
    parallelism) — same bits out."""
    ex = ShardedQueryExecutor(
        mesh=make_combine_mesh(devices=forced_mesh_devices, doc_shards=2))
    for qid in ("Q1.1", "Q3.2", "Q4.3"):
        sql = ssb.QUERIES[qid] + " LIMIT 100000"
        rt, _ = ex.execute(compile_query(sql), ssb_segs)
        want, _ = exec_1dev.execute(compile_query(sql), ssb_segs)
        assert rt.rows == want.rows


def test_launch_stats_correct_across_mesh_shapes(ssb_segs, exec_1dev,
                                                 exec_8dev):
    """The coalescing counters describe LAUNCHES, not devices: one query =
    one launch on any mesh shape, and repeats stay launch-cache hits."""
    sql = ssb.QUERIES["Q1.1"] + " LIMIT 100000"
    for ex in (exec_1dev, exec_8dev):
        _, stats = ex.execute(compile_query(sql), ssb_segs)
        assert stats.launch["launches"] == 1
        assert stats.launch["batchSize"] >= 1
        assert stats.launch["queueWaitMs"] >= 0


def test_slice_planner_pads_to_actual_mesh(ssb_segs):
    """plan_slices costs each slice at ceil(k / seg_axis) * seg_axis
    segments: a budget that fits a couple of raw segments fits NO 8-padded
    slice (-> None, per-segment fallback), while the 1-wide mesh slices
    happily — the PR-8 planner keyed on the REAL mesh shape, not a
    hardcoded 1."""
    from pinot_tpu.engine.residency import (
        ResidencyManager,
        estimate_segment_bytes,
    )

    cols = ["lo_extendedprice", "lo_discount", "d_year", "lo_quantity"]
    est = estimate_segment_bytes(ssb_segs[0], cols)
    rm = ResidencyManager(budget_bytes=int(3 * est))
    assert rm.plan_slices(ssb_segs, cols, pad_to=8) is None
    slices = rm.plan_slices(ssb_segs, cols, pad_to=1)
    assert slices is not None and len(slices) >= 2
    assert sorted(s.segment_name for sl in slices for s in sl) == \
        sorted(s.segment_name for s in ssb_segs)
    # a budget that fits the 8-pad slices on the 8-wide mesh too
    rm_big = ResidencyManager(budget_bytes=int(20 * est))
    slices8 = rm_big.plan_slices(ssb_segs, cols, pad_to=8)
    assert slices8 is not None


def test_sliced_combine_on_8dev_mesh_matches_uncapped(ssb_segs,
                                                      forced_mesh_devices):
    """Budget-sliced execution over the 8-device mesh stays bit-identical
    to the uncapped oracle (PR-8's guarantee, now on a real mesh)."""
    sql = ssb.QUERIES["Q4.1"] + " LIMIT 100000"
    oracle = ShardedQueryExecutor(
        mesh=make_combine_mesh(devices=forced_mesh_devices))
    want, _ = oracle.execute(compile_query(sql), ssb_segs)
    from pinot_tpu.engine.residency import estimate_segment_bytes

    cols = compile_query(sql).referenced_columns()
    ws = sum(estimate_segment_bytes(s, cols) for s in ssb_segs)
    capped = ShardedQueryExecutor(
        mesh=make_combine_mesh(devices=forced_mesh_devices),
        hbm_budget_bytes=max(int(ws * 0.6), 1))
    got, stats = capped.execute(compile_query(sql), ssb_segs)
    assert got.rows == want.rows
    assert stats.staging.get("spills", 0) == 0, \
        "capped run spilled to host instead of slicing on the mesh"
