"""Minion task pipeline: generation, execution, watermarks, retry caps.

Covers the round-2 advisor findings: MergeRollup must not lose rows of
segments straddling a bucket boundary (ref: MergeRollupTaskGenerator's
PARTITION_BUCKET_TIME_PERIOD behavior), watermarks advance on completion
rather than at scheduling time, and failing tasks stop regenerating after
a retry cap (with terminal-record pruning bounding state growth).
"""

import pytest

from pinot_tpu.controller.tasks import (
    COMPLETED,
    ERROR,
    MAX_TASK_ATTEMPTS,
    MERGE_ROLLUP_TASK,
    PURGE_TASK,
    TERMINAL_TASK_TTL_MS,
    WAITING,
)
from pinot_tpu.segment.processing import (
    MergeType,
    SegmentProcessorConfig,
    SegmentProcessorFramework,
)
from pinot_tpu.spi import DataType, FieldSpec, FieldType, Schema
from pinot_tpu.spi.table import (
    SegmentsValidationConfig,
    TableConfig,
    TableType,
)
from pinot_tpu.tools import EmbeddedCluster

DAY_MS = 86_400_000
D0 = 18_519 * DAY_MS          # an exact day boundary
D1 = D0 + DAY_MS
D2 = D0 + 2 * DAY_MS


def make_schema(name="events"):
    return Schema(name, [
        FieldSpec("k", DataType.STRING),
        FieldSpec("qty", DataType.LONG, FieldType.METRIC),
        FieldSpec("ts", DataType.LONG, FieldType.DATE_TIME),
    ])


def merge_table_cfg(name="events", bucket="1d"):
    return TableConfig(
        name, TableType.OFFLINE,
        validation_config=SegmentsValidationConfig(
            time_column_name="ts", replication=1),
        task_config={MERGE_ROLLUP_TASK: {
            "bucketTimePeriod": bucket, "bufferTimePeriod": "0d",
            "mergeType": "CONCAT",
        }})


def rows(ts_list, k="x"):
    return {"k": [k] * len(ts_list),
            "qty": [1] * len(ts_list),
            "ts": list(ts_list)}


@pytest.fixture()
def cluster(tmp_path):
    c = EmbeddedCluster(num_servers=1, data_dir=str(tmp_path))
    yield c
    c.shutdown()


def run_all_tasks(cluster, minion):
    while minion.run_one_task():
        pass


class TestMergeRollup:
    def test_straddling_segment_loses_no_rows(self, cluster):
        """A segment overlapping the bucket boundary is merged via bucket
        partitioning: its day-1 rows land in a day-1 output segment, and
        total counts are preserved after the inputs are deleted."""
        schema = make_schema()
        cluster.create_table(merge_table_cfg(), schema)
        cluster.ingest_rows("events_OFFLINE", schema,
                            rows(range(D0, D0 + 100)), segment_name="seg_a")
        cluster.ingest_rows("events_OFFLINE", schema,
                            rows(range(D0 + 2000, D0 + 2100)),
                            segment_name="seg_b")
        # 50 rows in day 0, 50 rows in day 1 — the straddler
        cluster.ingest_rows("events_OFFLINE", schema,
                            rows(range(D1 - 50, D1 + 50)),
                            segment_name="seg_c")
        assert cluster.wait_for_ev_converged("events_OFFLINE")
        assert cluster.query_rows("SELECT count(*) FROM events")[0][0] == 300

        tm = cluster.controller.task_manager
        created = tm.generate_tasks(now_ms=D2 + DAY_MS)
        assert len(created) == 1
        task = tm.get(created[0])
        assert set(task.input_segments) == {"seg_a", "seg_b", "seg_c"}

        minion = cluster.add_minion(start=False)
        run_all_tasks(cluster, minion)
        assert minion.tasks_failed == 0
        assert tm.get(created[0]).status == COMPLETED

        assert cluster.wait_for_ev_converged("events_OFFLINE")
        # no rows lost, inputs replaced by merged outputs
        assert cluster.query_rows("SELECT count(*) FROM events")[0][0] == 300
        assert cluster.query_rows(
            "SELECT sum(qty) FROM events")[0][0] == 300
        names = {md.segment_name for md in
                 cluster.store.segment_metadata_list("events_OFFLINE")}
        assert names.isdisjoint({"seg_a", "seg_b", "seg_c"})
        assert all(n.startswith("merged_") for n in names)
        # the day-1 spill rows are queryable on their own
        assert cluster.query_rows(
            f"SELECT count(*) FROM events WHERE ts >= {D1}")[0][0] == 50

    def test_watermark_advances_on_completion_not_scheduling(self, cluster):
        schema = make_schema()
        cluster.create_table(merge_table_cfg(), schema)
        cluster.ingest_rows("events_OFFLINE", schema,
                            rows(range(D0, D0 + 10)), segment_name="d0_a")
        cluster.ingest_rows("events_OFFLINE", schema,
                            rows(range(D0 + 20, D0 + 30)),
                            segment_name="d0_b")
        cluster.ingest_rows("events_OFFLINE", schema,
                            rows(range(D2, D2 + 10)), segment_name="d2_a")
        cluster.ingest_rows("events_OFFLINE", schema,
                            rows(range(D2 + 20, D2 + 30)),
                            segment_name="d2_b")
        assert cluster.wait_for_ev_converged("events_OFFLINE")

        tm = cluster.controller.task_manager
        now = D2 + 2 * DAY_MS
        created = tm.generate_tasks(now_ms=now)
        assert len(created) == 1
        assert tm.get(created[0]).configs["windowStartMs"] == str(D0)
        # pending task: watermark must NOT have advanced past day 0
        wm = tm.get_watermark_ms("events_OFFLINE", MERGE_ROLLUP_TASK)
        assert wm is None or wm <= D0
        # while the task is in flight no duplicate is generated
        assert tm.generate_tasks(now_ms=now) == []

        minion = cluster.add_minion(start=False)
        run_all_tasks(cluster, minion)
        assert cluster.wait_for_ev_converged("events_OFFLINE")

        # day 0 drained -> watermark rolls forward, day 2 gets its task
        created2 = tm.generate_tasks(now_ms=now)
        assert len(created2) == 1
        assert tm.get(created2[0]).configs["windowStartMs"] == str(D2)


class TestRetryCapAndPruning:
    def _purge_table(self, cluster):
        schema = make_schema("purgeme")
        cfg = TableConfig(
            "purgeme", TableType.OFFLINE,
            validation_config=SegmentsValidationConfig(
                time_column_name="ts", replication=1),
            task_config={PURGE_TASK: {}})
        cluster.create_table(cfg, schema)
        cluster.ingest_rows("purgeme_OFFLINE", schema,
                            rows(range(D0, D0 + 10)), segment_name="p0")
        assert cluster.wait_for_ev_converged("purgeme_OFFLINE")
        return schema

    def test_failing_task_stops_regenerating_after_cap(self, cluster):
        self._purge_table(cluster)  # no purger registered -> executor errors
        tm = cluster.controller.task_manager
        minion = cluster.add_minion(start=False)
        for _ in range(MAX_TASK_ATTEMPTS + 3):
            tm.generate_tasks(now_ms=D2)
            run_all_tasks(cluster, minion)
        errors = tm.list_tasks(table="purgeme_OFFLINE",
                               task_type=PURGE_TASK, status=ERROR)
        assert len(errors) == MAX_TASK_ATTEMPTS
        assert minion.tasks_failed == MAX_TASK_ATTEMPTS
        # and nothing is left waiting
        assert not tm.list_tasks(status=WAITING)

    def test_terminal_records_pruned_after_ttl(self, cluster):
        self._purge_table(cluster)
        tm = cluster.controller.task_manager
        minion = cluster.add_minion(start=False)
        tm.generate_tasks(now_ms=D2)
        run_all_tasks(cluster, minion)
        assert tm.list_tasks(status=ERROR)
        import time as _time
        far_future = int(_time.time() * 1000) + TERMINAL_TASK_TTL_MS + 1000
        tm.prune_terminal_tasks(far_future)
        assert tm.list_tasks() == []


class TestRollupPrecision:
    def test_long_sum_exact_past_float53(self):
        """LONG metric sums beyond 2**53 must not round through float64."""
        schema = make_schema()
        cfg = merge_table_cfg()
        fw = SegmentProcessorFramework([], SegmentProcessorConfig(
            schema=schema, table_config=cfg, merge_type=MergeType.ROLLUP))
        cols = {"k": ["x", "x", "x"],
                "qty": [2 ** 53, 1, 2],
                "ts": [D0, D0, D0]}
        out = fw._rollup(cols)
        assert out["qty"] == [2 ** 53 + 3]
