"""Array-native broker reduce: columnar DataTables end-to-end, vectorized
merge parity vs the row-path oracle, server-side trim, reduce-as-arrivals.

Every parity test feeds BOTH reduce paths tables decoded from the real
binary wire (`to_bytes`/`from_bytes`), so the vectorized path runs over
the zero-copy column buffers it would see in production — and asserts
BIT-identical rows (values AND python types), never approx.
"""

import math
import random

import numpy as np
import pytest

from pinot_tpu.broker.reduce import (
    BrokerReduceService,
    MixedResponseTypeError,
)
from pinot_tpu.common import datatable as dt_mod
from pinot_tpu.common.datatable import Column, DataTable, ResponseType
from pinot_tpu.engine.errors import QueryError
from pinot_tpu.engine.results import DataSchema, QueryStats
from pinot_tpu.query import compile_query

pytestmark = pytest.mark.reduce

VEC = BrokerReduceService(vectorized=True)
ORA = BrokerReduceService(vectorized=False)


def _wire(dt: DataTable) -> DataTable:
    return DataTable.from_bytes(dt.to_bytes())


def _assert_bit_identical(a, b, label=""):
    assert a.schema.to_dict() == b.schema.to_dict(), label
    assert len(a.rows) == len(b.rows), (label, len(a.rows), len(b.rows))
    for ra, rb in zip(a.rows, b.rows):
        assert len(ra) == len(rb), label
        for x, y in zip(ra, rb):
            if isinstance(y, float) and math.isnan(y):
                assert isinstance(x, float) and math.isnan(x), label
            else:
                assert x == y and type(x) is type(y), (label, ra, rb)


def _both(ctx, tables):
    rv, sv, ev = VEC.reduce(ctx, [_wire(t) for t in tables])
    ro, so, eo = ORA.reduce(ctx, [_wire(t) for t in tables])
    assert ev == eo
    return (rv, sv), (ro, so)


# --------------------------------------------------------------------------
# group-by parity
# --------------------------------------------------------------------------

def _gb_tables(rng, n_servers, per_server, aggs_fn, key_fn,
               schema_types=None, empties=()):
    tables = []
    for s in range(n_servers):
        groups = {}
        if s not in empties:
            for _ in range(per_server):
                groups.setdefault(key_fn(rng), aggs_fn(rng))
        tables.append(DataTable.for_group_by(
            groups, schema_types or {"k1": "STRING", "k2": "INT"},
            QueryStats()))
    return tables


@pytest.mark.parametrize("sql", [
    "SELECT k1, k2, sum(v), count(*) FROM t GROUP BY k1, k2 LIMIT 100000",
    "SELECT k1, k2, sum(v), count(*), min(v), max(v), avg(v) FROM t "
    "GROUP BY k1, k2 ORDER BY sum(v) DESC, k1 LIMIT 97",
    "SELECT k2, count(*) FROM t GROUP BY k2, k1 "
    "ORDER BY count(*) DESC, k2 LIMIT 13, 29",
    "SELECT k1, sum(v) FROM t GROUP BY k1, k2 "
    "HAVING sum(v) > 300 ORDER BY k1, sum(v) LIMIT 50",
    "SELECT k1, k2, avg(v), distinctcount(v) FROM t GROUP BY k1, k2 "
    "ORDER BY k1 LIMIT 40",
])
def test_group_by_parity(sql):
    """Vectorized group-by merge (lexsort + reduceat fold) is
    bit-identical to the per-key oracle across ORDER BY, OFFSET, HAVING,
    object-state aggs (avg tuples, distinctcount frozensets), ties, and
    an empty server."""
    rng = random.Random(hash(sql) & 0xFFFF)
    ctx = compile_query(sql)

    def aggs_fn(r):
        states = {
            "sum(v)": float(r.randint(0, 1000)),
            "count(*)": r.randint(1, 50),
            "min(v)": float(r.randint(-100, 100)),
            "max(v)": float(r.randint(-100, 100)),
            "avg(v)": (float(r.randint(0, 500)), r.randint(1, 9)),
            "distinctcount(v)": frozenset(
                r.randint(0, 9) for _ in range(r.randint(0, 4))),
        }
        return [states[str(f)] for f in ctx.aggregations]

    def key_fn(r):
        return ("b%02d" % r.randint(0, 25), r.randint(0, 40))

    tables = _gb_tables(rng, 5, 400, aggs_fn, key_fn, empties=(3,))
    (rv, sv), (ro, so) = _both(ctx, tables)
    _assert_bit_identical(rv, ro, sql)
    assert not sv.decisions, sv.decisions  # fully vectorized, no fallback


def test_group_by_numeric_keys_and_tie_heavy():
    ctx = compile_query(
        "SELECT k, sum(v) FROM t GROUP BY k ORDER BY sum(v), k LIMIT 1000")
    rng = random.Random(7)
    tables = _gb_tables(
        rng, 8, 300,
        lambda r: [float(r.randint(0, 3))],   # heavy value ties
        lambda r: (r.randint(0, 60),),        # i64 single key
        schema_types={"k": "INT"})
    (rv, sv), (ro, _) = _both(ctx, tables)
    _assert_bit_identical(rv, ro)
    assert not sv.decisions


def test_group_by_object_key_falls_back_with_ledger_reason():
    """A None in a group key -> obj column -> row-path fallback, recorded
    on the decision ledger — and still bit-identical."""
    ctx = compile_query(
        "SELECT k, count(*) FROM t GROUP BY k ORDER BY count(*) DESC LIMIT 10")
    t1 = DataTable.for_group_by({("a",): [3], (None,): [5]}, {}, QueryStats())
    t2 = DataTable.for_group_by({("a",): [2], ("b",): [1]}, {}, QueryStats())
    (rv, sv), (ro, _) = _both(ctx, [t1, t2])
    _assert_bit_identical(rv, ro)
    assert sv.decisions == {
        "reduce:vectorized->row_path:reduce_group_key_not_sortable": 1}


def test_group_by_mixed_state_kind_falls_back():
    """Server A ships int sums, server B floats: exact-int-then-float
    oracle arithmetic is the contract, so the merge declines."""
    ctx = compile_query("SELECT k, sum(v) FROM t GROUP BY k LIMIT 10")
    t1 = DataTable.for_group_by({("a",): [3]}, {}, QueryStats())
    t2 = DataTable.for_group_by({("a",): [2.5]}, {}, QueryStats())
    (rv, sv), (ro, _) = _both(ctx, [t1, t2])
    _assert_bit_identical(rv, ro)
    assert "reduce:vectorized->row_path:reduce_column_kind_mismatch" \
        in sv.decisions


def test_group_by_num_groups_limit_trim_parity():
    svc_v = BrokerReduceService(num_groups_limit=50, vectorized=True)
    svc_o = BrokerReduceService(num_groups_limit=50, vectorized=False)
    ctx = compile_query("SELECT k, count(*) FROM t GROUP BY k LIMIT 100000")

    def build():
        return [_wire(t) for t in _gb_tables(
            random.Random(11), 4, 60, lambda r: [r.randint(1, 5)],
            lambda r: (r.randint(0, 500),), schema_types={"k": "INT"})]

    rv, sv, _ = svc_v.reduce(ctx, build())
    ro, so, _ = svc_o.reduce(ctx, build())
    _assert_bit_identical(rv, ro)
    assert sv.num_groups_limit_reached and so.num_groups_limit_reached


# --------------------------------------------------------------------------
# selection parity (server-side trim + pre-sorted block merge)
# --------------------------------------------------------------------------

def _sel_tables(rng, n_servers, rows_per, ncols=3, sort_key=None,
                trim=None, hidden=0, empties=()):
    tables = []
    for s in range(n_servers):
        rows = [] if s in empties else [
            ["s%02d" % rng.randint(0, 30), rng.randint(-500, 500),
             float(rng.randint(0, 99))][:ncols]
            for _ in range(rows_per)]
        if sort_key is not None:
            rows.sort(key=sort_key)
        if trim is not None:
            rows = rows[:trim]
        tables.append(DataTable.for_selection(
            DataSchema(["a", "b", "c"][:ncols],
                       ["STRING", "LONG", "DOUBLE"][:ncols]),
            rows, QueryStats(), num_hidden=hidden,
            sorted_rows=sort_key is not None))
    return tables


@pytest.mark.parametrize("sql,sort_key", [
    ("SELECT a, b, c FROM t LIMIT 200", None),
    ("SELECT a, b, c FROM t ORDER BY b, a LIMIT 150",
     lambda r: (r[1], r[0])),
    ("SELECT a, b, c FROM t ORDER BY c DESC, b LIMIT 30, 77",
     None),  # unsorted blocks: broker must still produce the oracle order
])
def test_selection_parity(sql, sort_key):
    """Ordered + unordered selection reduce over pre-trimmed blocks:
    identical rows/types incl. ties, offsets, and trim boundaries."""
    rng = random.Random(hash(sql) & 0xFFFF)
    ctx = compile_query(sql)
    trim = ctx.offset + ctx.limit
    tables = _sel_tables(rng, 6, 200, sort_key=sort_key, trim=trim,
                         empties=(2,))
    (rv, sv), (ro, _) = _both(ctx, tables)
    _assert_bit_identical(rv, ro, sql)
    assert not sv.decisions


def test_selection_hidden_order_columns_parity():
    """ORDER BY over a hidden trailing column (the executor's order-key
    carry) trims to the visible schema on both paths."""
    ctx = compile_query("SELECT a FROM t ORDER BY b DESC LIMIT 11, 23")
    rng = random.Random(3)
    tables = _sel_tables(rng, 4, 60, ncols=2,
                         sort_key=lambda r: (-r[1], ), trim=34, hidden=1)
    (rv, sv), (ro, _) = _both(ctx, tables)
    _assert_bit_identical(rv, ro)
    assert rv.schema.column_names == ["a"]


def test_selection_single_presorted_block_skips_resort():
    """One server, block flagged sorted: the trim window IS the answer
    (no broker sort at all) — and matches the oracle's stable re-sort."""
    ctx = compile_query("SELECT a, b FROM t ORDER BY b LIMIT 5, 10")
    rng = random.Random(5)
    [t] = _sel_tables(rng, 1, 50, ncols=2, sort_key=lambda r: (r[1],),
                      trim=15)
    assert _wire(t).selection_sorted
    (rv, _), (ro, _) = _both(ctx, [t])
    _assert_bit_identical(rv, ro)


def test_selection_non_finite_floats_parity():
    ctx = compile_query("SELECT a, b, c FROM t ORDER BY b LIMIT 40")
    rows1 = [["x", i, float("inf") if i % 3 == 0 else float(i)]
             for i in range(20)]
    rows2 = [["y", i, float("-inf") if i % 4 == 0 else -float(i)]
             for i in range(20)]
    schema = DataSchema(["a", "b", "c"], ["STRING", "LONG", "DOUBLE"])
    tables = [DataTable.for_selection(schema, r, QueryStats())
              for r in (rows1, rows2)]
    (rv, _), (ro, _) = _both(ctx, tables)
    _assert_bit_identical(rv, ro)


# --------------------------------------------------------------------------
# distinct parity
# --------------------------------------------------------------------------

@pytest.mark.parametrize("sql", [
    "SELECT DISTINCT a, b FROM t LIMIT 500",
    "SELECT DISTINCT a, b FROM t ORDER BY b DESC, a LIMIT 7, 31",
])
def test_distinct_parity(sql):
    """Vectorized unique over the concatenated key columns: first-seen
    order, cross-server dedup, ORDER BY + OFFSET — all oracle-identical."""
    rng = random.Random(hash(sql) & 0xFFFF)
    ctx = compile_query(sql)
    schema = DataSchema(["a", "b"], ["STRING", "LONG"])
    tables = []
    for s in range(5):
        seen = {}
        for _ in range(120):
            r = ["d%d" % rng.randint(0, 12), rng.randint(0, 9)]
            seen.setdefault(tuple(r), r)
        tables.append(DataTable.for_distinct(schema, list(seen.values()),
                                             QueryStats()))
    tables.append(DataTable.for_distinct(schema, [], QueryStats()))
    (rv, sv), (ro, _) = _both(ctx, tables)
    _assert_bit_identical(rv, ro, sql)
    assert not sv.decisions


# --------------------------------------------------------------------------
# aggregation + mixed-type guard + arrivals
# --------------------------------------------------------------------------

def test_aggregation_parity():
    ctx = compile_query("SELECT sum(v), count(*), avg(v) FROM t")
    tables = [DataTable.for_aggregation(
        [float(i * 10), i, (float(i), i)], QueryStats())
        for i in range(1, 7)]
    (rv, _), (ro, _) = _both(ctx, tables)
    _assert_bit_identical(rv, ro)


def test_mixed_response_types_raise_typed_error():
    """reduce.py:59 satellite: servers disagreeing on response type is a
    typed QueryError, never a silent wrong-shaped merge."""
    ctx = compile_query("SELECT count(*) FROM t")
    t1 = DataTable.for_aggregation([3], QueryStats())
    t2 = DataTable.for_group_by({("a",): [1]}, {}, QueryStats())
    for svc in (VEC, ORA):
        with pytest.raises(MixedResponseTypeError, match="disagree"):
            svc.reduce(ctx, [_wire(t1), _wire(t2)])
    # plain QueryError surface for callers that catch broadly
    assert issubclass(MixedResponseTypeError, QueryError)


def test_reduce_as_arrivals_accumulator():
    """Folding tables one arrival at a time == batch reduce; fold spans
    record one per-table split with instance tags."""
    ctx = compile_query(
        "SELECT k1, k2, sum(v), count(*) FROM t GROUP BY k1, k2 "
        "ORDER BY sum(v) DESC LIMIT 50")
    rng = random.Random(19)
    tables = [_wire(t) for t in _gb_tables(
        rng, 6, 200, lambda r: [float(r.randint(0, 99)), r.randint(1, 5)],
        lambda r: ("g%d" % r.randint(0, 40), r.randint(0, 9)))]
    batch, _, _ = VEC.reduce(ctx, [_wire_copy(t) for t in tables])

    acc = VEC.accumulator(ctx)
    for i, t in enumerate(tables):
        acc.add(t, instance=f"server_{i}")
    streamed, stats, _ = acc.finish()
    _assert_bit_identical(streamed, batch)
    assert len(acc.fold_spans) == 6
    assert all(s["name"] == "Fold" and "ms" in s and "rows" in s
               for s in acc.fold_spans)
    assert acc.fold_spans[0]["instance"] == "server_0"


def _wire_copy(t: DataTable) -> DataTable:
    return DataTable.from_bytes(t.to_bytes())


def test_exception_tables_still_partial_reduce():
    ctx = compile_query("SELECT k, count(*) FROM t GROUP BY k LIMIT 10")
    ok = DataTable.for_group_by({("a",): [4]}, {}, QueryStats())
    bad = DataTable.for_exception("server s2 timed out")
    table, _, errors = VEC.reduce(ctx, [_wire(ok), _wire(bad)])
    assert table.rows == [["a", 4]]
    assert errors == ["server s2 timed out"]
    with pytest.raises(QueryError, match="timed out"):
        VEC.reduce(ctx, [_wire(bad)])


# --------------------------------------------------------------------------
# zero-boxing acceptance
# --------------------------------------------------------------------------

def test_numeric_columns_never_box_through_vectorized_reduce(monkeypatch):
    """The acceptance bar: numeric columns reach the reducer with ZERO
    per-cell python boxing — Column.tolist on a numeric column and
    decode_value both trap, and the lazy payload never materializes."""
    calls = {"decode": 0}
    real_decode = dt_mod.decode_value

    def counting_decode(v):
        calls["decode"] += 1
        return real_decode(v)

    real_tolist = Column.tolist

    def guarded_tolist(self):
        if self.is_numeric:
            raise AssertionError("numeric column boxed via tolist()")
        return real_tolist(self)

    monkeypatch.setattr(dt_mod, "decode_value", counting_decode)
    monkeypatch.setattr(Column, "tolist", guarded_tolist)

    ctx = compile_query(
        "SELECT k, sum(v), count(*) FROM t GROUP BY k "
        "ORDER BY sum(v) DESC LIMIT 100")
    tables = []
    for s in range(4):
        groups = {(i + s * 1000,): [float(i), i % 7 + 1]
                  for i in range(500)}
        tables.append(_wire_copy(DataTable.for_group_by(
            groups, {"k": "INT"}, QueryStats())))
    calls["decode"] = 0
    result, stats, _ = VEC.reduce(ctx, tables)
    assert len(result.rows) == 100 and not stats.decisions
    assert calls["decode"] == 0
    for t in tables:
        assert "groups" not in t._payload  # lazy payload stayed columnar

    # ordered selection: numeric key + output columns stay array-native
    ctx2 = compile_query("SELECT b, c FROM t ORDER BY b LIMIT 50")
    schema = DataSchema(["b", "c"], ["LONG", "DOUBLE"])
    sel = []
    for s in range(4):
        rows = sorted([[random.Random(s * 97 + i).randint(0, 10_000),
                        float(i)] for i in range(100)])
        sel.append(_wire_copy(DataTable.for_selection(
            schema, rows, QueryStats(), sorted_rows=True)))
    calls["decode"] = 0
    result2, stats2, _ = VEC.reduce(ctx2, sel)
    assert len(result2.rows) == 50 and not stats2.decisions
    assert calls["decode"] == 0
    for t in sel:
        assert "rows" not in t._payload


# --------------------------------------------------------------------------
# wire columns: empty tables + ledger-reason registry conformance
# --------------------------------------------------------------------------

def test_empty_tables_roundtrip_and_reduce():
    ctx = compile_query("SELECT a, b FROM t ORDER BY b LIMIT 10")
    schema = DataSchema(["a", "b"], ["STRING", "LONG"])
    empty = _wire_copy(DataTable.for_selection(schema, [], QueryStats()))
    assert empty.num_rows() == 0 and empty.rows() == []
    assert [c.n for c in empty.columns()] == [0, 0]
    table, _, _ = VEC.reduce(ctx, [empty, _wire_copy(
        DataTable.for_selection(schema, [["x", 1]], QueryStats()))])
    assert table.rows == [["x", 1]]


# --------------------------------------------------------------------------
# SSB: all 13 flights bit-identical between reduce paths
# --------------------------------------------------------------------------

@pytest.fixture(scope="module")
def ssb_server_tables(tmp_path_factory):
    """Two 'servers' (host executors over disjoint segment halves)
    answer every SSB flight; the DataTables round-trip the binary wire —
    exactly what the broker's reduce receives in a 2-server cluster."""
    from pinot_tpu.engine import ServerQueryExecutor
    from pinot_tpu.tools import ssb

    out = tmp_path_factory.mktemp("ssb_reduce_segs")
    segs = ssb.build_segments(0, str(out), num_segments=4, rows=40_000)
    servers = [ServerQueryExecutor(use_device=False),
               ServerQueryExecutor(use_device=False)]
    halves = [segs[:2], segs[2:]]

    def run(sql: str):
        ctx = compile_query(sql)
        return ctx, [DataTable.from_bytes(
            srv.execute_instance(ctx, half).to_bytes())
            for srv, half in zip(servers, halves)]

    return run


from pinot_tpu.tools import ssb as _ssb_queries  # noqa: E402


@pytest.mark.parametrize("qid", sorted(_ssb_queries.QUERIES))
def test_ssb_flight_reduce_parity(ssb_server_tables, qid):
    from pinot_tpu.tools import ssb

    # explicit LIMIT: full group sets, past the default group-by LIMIT 10
    ctx, tables = ssb_server_tables(ssb.QUERIES[qid] + " LIMIT 100000")
    rv, sv, _ = VEC.reduce(ctx, tables)
    ro, _, _ = ORA.reduce(ctx, [_wire_copy(t) for t in tables])
    _assert_bit_identical(rv, ro, qid)
    # no reduce-point fallback: every flight stays on the vectorized path
    # (server-side ledger entries ride the merged stats — ignore them)
    assert not [k for k in sv.decisions if k.startswith("reduce:")], \
        (qid, sv.decisions)


# (The reduce reason-registry conformance test moved to
# tests/test_reasons.py: ONE generic harness parameterized over
# tracing.reason_registry() replaced the per-module scans.)
