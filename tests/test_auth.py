"""Access control: basic-auth REST guard + table scoping
(ref: AccessControlFactory, BasicAuthAccessControlFactory)."""

import base64
import json
import urllib.error
import urllib.request

import pytest

from pinot_tpu.spi.auth import (
    AllowAllAccessControl,
    BasicAuthAccessControl,
    Principal,
    access_control_from_config,
)


def _basic(user, pw):
    return "Basic " + base64.b64encode(
        f"{user}:{pw}".encode()).decode("ascii")


class TestSpi:
    def test_allow_all(self):
        ac = AllowAllAccessControl()
        assert ac.authenticate({}) is not None
        assert ac.has_access(None, "t", "WRITE")

    def test_basic_auth_rejects_bad_credentials(self):
        ac = BasicAuthAccessControl([Principal("admin", "secret")])
        assert ac.authenticate({}) is None
        assert ac.authenticate(
            {"Authorization": _basic("admin", "wrong")}) is None
        p = ac.authenticate({"Authorization": _basic("admin", "secret")})
        assert p.name == "admin"
        assert ac.has_access(p, "anything", "WRITE")

    def test_table_and_permission_scoping(self):
        p = Principal("ro", "x", tables=["sales"], permissions=["READ"])
        assert p.allows("sales_OFFLINE", "READ")
        assert p.allows("sales", "read")
        assert not p.allows("sales", "WRITE")
        assert not p.allows("other", "READ")
        # unscoped principal allows everything
        assert Principal("admin").allows("any", "WRITE")

    def test_factory(self):
        assert isinstance(access_control_from_config(None),
                          AllowAllAccessControl)
        ac = access_control_from_config({"type": "basic", "principals": [
            {"username": "u", "password": "p", "tables": ["t"]}]})
        assert isinstance(ac, BasicAuthAccessControl)
        with pytest.raises(ValueError):
            access_control_from_config({"type": "kerberos"})


class TestRestGuard:
    @pytest.fixture(scope="class")
    def cluster(self, tmp_path_factory):
        import numpy as np

        from pinot_tpu.segment import SegmentBuilder
        from pinot_tpu.spi import DataType, FieldSpec, FieldType, Schema
        from pinot_tpu.tools.cluster import EmbeddedCluster
        from pinot_tpu.transport.rest import BrokerApi

        out = str(tmp_path_factory.mktemp("auth"))
        schema = Schema("sales", [
            FieldSpec("region", DataType.STRING),
            FieldSpec("qty", DataType.LONG, FieldType.METRIC),
        ])
        rng = np.random.default_rng(3)
        frame = {"region": ["east", "west"] * 500,
                 "qty": rng.integers(0, 10, 1000).tolist()}
        from pinot_tpu.spi.table import TableConfig

        cluster = EmbeddedCluster(data_dir=out)
        cluster.create_table(TableConfig(table_name="sales"), schema)
        seg_dir = str(tmp_path_factory.mktemp("authseg"))
        SegmentBuilder(schema, "sales_0").build(frame, seg_dir)
        cluster.upload_segment_dir("sales_OFFLINE", f"{seg_dir}/sales_0")
        cluster.wait_for_ev_converged("sales_OFFLINE")
        # a second real table so subquery-laundering tests can run an
        # ALLOWED outer query probing a DENIED inner table
        schema2 = Schema("sales2", schema.field_specs)
        cluster.create_table(TableConfig(table_name="sales2"), schema2)
        SegmentBuilder(schema2, "sales2_0").build(frame, seg_dir)
        cluster.upload_segment_dir("sales2_OFFLINE", f"{seg_dir}/sales2_0")
        cluster.wait_for_ev_converged("sales2_OFFLINE")
        ac = access_control_from_config({"type": "basic", "principals": [
            {"username": "admin", "password": "s3cret"},
            {"username": "scoped", "password": "pw", "tables": ["other"]},
            {"username": "scoped2", "password": "pw", "tables": ["sales2"]},
        ]})
        api = BrokerApi(cluster.broker, access_control=ac)
        api.start()
        yield api
        api.stop()
        cluster.shutdown()

    def _query(self, api, auth=None):
        req = urllib.request.Request(
            f"http://localhost:{api.port}/query/sql",
            data=json.dumps({"sql": "SELECT count(*) FROM sales"}).encode(),
            headers={"Content-Type": "application/json",
                     **({"Authorization": auth} if auth else {})})
        with urllib.request.urlopen(req, timeout=10) as r:
            return r.status, json.loads(r.read().decode())

    def test_unauthenticated_401(self, cluster):
        with pytest.raises(urllib.error.HTTPError) as e:
            self._query(cluster)
        assert e.value.code == 401

    def test_health_stays_open(self, cluster):
        with urllib.request.urlopen(
                f"http://localhost:{cluster.port}/health", timeout=10) as r:
            assert r.status == 200

    def test_authenticated_query(self, cluster):
        status, payload = self._query(cluster, _basic("admin", "s3cret"))
        assert status == 200
        assert payload["resultTable"]["rows"][0][0] == 1000

    def test_scoped_principal_403(self, cluster):
        with pytest.raises(urllib.error.HTTPError) as e:
            self._query(cluster, _basic("scoped", "pw"))
        assert e.value.code == 403

    def test_subquery_access_denied_403(self, cluster):
        """A table-scoped principal must not probe another table through
        the IN_SUBQUERY rewrite — the inner query is authorized with the
        OUTER principal and the denial keeps its 403 identity."""
        import urllib.error
        import urllib.request

        sql = ("SELECT count(*) FROM sales2 WHERE "
               "inSubquery(region, 'SELECT idset(region) FROM sales') = 1")
        req = urllib.request.Request(
            f"http://localhost:{cluster.port}/query/sql",
            data=json.dumps({"sql": sql}).encode(),
            headers={"Content-Type": "application/json",
                     "Authorization": _basic("scoped2", "pw")})
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(req, timeout=10)
        assert e.value.code == 403

    def test_string_literal_from_cannot_spoof_table(self, cluster):
        """ADVICE r4 high: 'SELECT ... FROM secret' hidden inside a string
        literal must not authorize against the literal's table — the PARSED
        table is what gets checked."""
        import urllib.error
        import urllib.request

        sql = "SELECT 'x FROM other' FROM sales LIMIT 1"
        req = urllib.request.Request(
            f"http://localhost:{cluster.port}/query/sql",
            data=json.dumps({"sql": sql}).encode(),
            headers={"Content-Type": "application/json",
                     "Authorization": _basic("scoped", "pw")})
        # principal is scoped to 'other'; real table is 'sales' -> 403
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(req, timeout=10)
        assert e.value.code == 403
