"""Sharded combine parity: ShardedQueryExecutor over the virtual 8-device
mesh must return exactly what the per-segment executor returns (the
reference's combine-vs-sequential invariant, BaseCombineOperator.java:55)."""

import numpy as np
import pandas as pd
import pytest

from pinot_tpu.engine import ServerQueryExecutor
from pinot_tpu.parallel import (
    SegmentBatch,
    ShardedQueryExecutor,
    make_combine_mesh,
)
from pinot_tpu.query import compile_query
from pinot_tpu.segment import SegmentBuilder, load_segment
from pinot_tpu.spi import DataType, FieldSpec, FieldType, IndexingConfig, Schema

RNG = np.random.default_rng(11)
N = 4000
NUM_SEGMENTS = 5   # deliberately not a divisor of the mesh (pad path)


def make_schema():
    return Schema("sales", [
        FieldSpec("region", DataType.STRING),
        FieldSpec("kind", DataType.STRING),
        FieldSpec("year", DataType.INT),
        FieldSpec("qty", DataType.LONG, FieldType.METRIC),
        FieldSpec("price", DataType.DOUBLE, FieldType.METRIC),
        FieldSpec("raw_amt", DataType.LONG, FieldType.METRIC),
    ])


@pytest.fixture(scope="module")
def setup(tmp_path_factory):
    out = tmp_path_factory.mktemp("parallel_segs")
    regions = ["east", "west", "north", "south"]
    kinds = ["a", "b", "c"]
    df = pd.DataFrame({
        "region": [regions[i] for i in RNG.integers(0, 4, N)],
        "kind": [kinds[i] for i in RNG.integers(0, 3, N)],
        "year": RNG.integers(2015, 2024, N).astype(np.int64),
        "qty": RNG.integers(1, 50, N).astype(np.int64),
        "price": np.round(RNG.normal(100, 25, N), 2),
        "raw_amt": RNG.integers(0, 10_000, N).astype(np.int64),
    })
    segs = []
    # uneven split -> segments with different sizes, capacities, dictionaries
    bounds = [0, 500, 1400, 2000, 3100, N]
    for i in range(NUM_SEGMENTS):
        sl = slice(bounds[i], bounds[i + 1])
        b = SegmentBuilder(
            make_schema(), f"sales_{i}",
            indexing_config=IndexingConfig(no_dictionary_columns=["raw_amt"]))
        b.build({c: df[c].tolist()[sl] for c in df.columns}, str(out))
        segs.append(load_segment(str(out / f"sales_{i}")))
    return df, segs


@pytest.fixture(scope="module", params=[1, 2], ids=["doc1", "doc2"])
def sharded_exec(request):
    mesh = make_combine_mesh(doc_shards=request.param)
    return ShardedQueryExecutor(mesh=mesh)


@pytest.fixture(scope="module")
def base_exec():
    return ServerQueryExecutor(use_device=True)


QUERIES = [
    "SELECT count(*) FROM sales WHERE region = 'east'",
    "SELECT sum(qty), min(price), max(price), avg(qty) FROM sales",
    "SELECT sum(price) FROM sales WHERE year BETWEEN 2017 AND 2021 AND kind != 'c'",
    "SELECT minmaxrange(year), count(*) FROM sales WHERE region IN ('west','north')",
    "SELECT distinctcount(region) FROM sales WHERE qty > 25",
    "SELECT sum(raw_amt) FROM sales WHERE raw_amt > 5000",
    "SELECT region, sum(qty), count(*) FROM sales GROUP BY region ORDER BY region",
    "SELECT region, kind, sum(price), avg(price) FROM sales "
    "GROUP BY region, kind ORDER BY region, kind LIMIT 20",
    "SELECT year, min(price), max(qty) FROM sales WHERE kind = 'a' "
    "GROUP BY year ORDER BY year",
    "SELECT sum(qty * price) FROM sales WHERE region = 'south'",
]


@pytest.mark.parametrize("sql", QUERIES)
def test_sharded_matches_per_segment(setup, sharded_exec, base_exec, sql):
    _, segs = setup
    ctx = compile_query(sql)
    got, _ = sharded_exec.execute(ctx, segs)
    want, _ = base_exec.execute(compile_query(sql), segs)
    assert len(got.rows) == len(want.rows)
    for gr, wr in zip(got.rows, want.rows):
        for g, w in zip(gr, wr):
            if isinstance(w, float):
                assert g == pytest.approx(w, rel=1e-5)  # f32 device accumulation
            else:
                assert g == w


def test_sharded_matches_pandas(setup, sharded_exec):
    df, segs = setup
    ctx = compile_query(
        "SELECT region, sum(qty) FROM sales WHERE year >= 2018 "
        "GROUP BY region ORDER BY region")
    rt, stats = sharded_exec.execute(ctx, segs)
    exp = (df[df.year >= 2018].groupby("region").qty.sum()
           .sort_index())
    assert [r[0] for r in rt.rows] == list(exp.index)
    assert [r[1] for r in rt.rows] == pytest.approx(list(exp.values))
    assert stats.num_segments_processed == NUM_SEGMENTS


def test_batch_unified_dictionary(setup):
    df, segs = setup
    batch = SegmentBatch(segs)
    d = batch.unified_dictionary("region")
    assert [d.get_value(i) for i in range(d.cardinality)] == \
        sorted(df.region.unique())
    # remapped stacked fwd decodes back to the original values
    st = batch.stacked_column("region")
    seg0 = segs[0]
    vals = [d.get_value(int(st["fwd"][0, i])) for i in range(5)]
    assert vals == [seg0.get_value("region", i) for i in range(5)]


def test_selection_falls_back(setup, sharded_exec):
    _, segs = setup
    ctx = compile_query("SELECT region, qty FROM sales "
                        "ORDER BY qty DESC LIMIT 5")
    rt, _ = sharded_exec.execute(ctx, segs)
    assert len(rt.rows) == 5
    qtys = [r[1] for r in rt.rows]
    assert qtys == sorted(qtys, reverse=True)


def test_groupby_no_agg_having(setup, base_exec):
    """GROUP BY without aggregations converts to DISTINCT; HAVING on the
    group expressions must still filter (regression: HAVING was dropped)."""
    _, segs = setup
    ctx = compile_query("SELECT region FROM sales GROUP BY region "
                        "HAVING region != 'east' ORDER BY region")
    rt, _ = base_exec.execute(ctx, segs)
    assert [r[0] for r in rt.rows] == ["north", "south", "west"]


def test_distinctcount_string_plans_on_device(setup):
    """Regression: DISTINCTCOUNT(string_col) used to hit _compile_value
    (which rejects non-numeric columns) before its own plan branch and fell
    to the 1000x-slower host path."""
    from pinot_tpu.engine.plan import plan_segment

    _, segs = setup
    ctx = compile_query(
        "SELECT distinctcount(region) FROM sales WHERE qty > 25")
    plan = plan_segment(ctx, segs[0])
    assert plan.spec[1][0][0] == "distinctcount"


def test_packed_output_roundtrip():
    """pack_outputs/unpack_outputs are inverse over the output tree (the
    single-fetch decode contract of the serving path)."""
    import jax.numpy as jnp
    import numpy as np

    from pinot_tpu.engine.kernels import (
        output_layout,
        pack_outputs,
        unpack_outputs,
    )

    # grouped spec: presence + sum + avg(2 leaves) + seg_matched
    spec = (("true",),
            (("sum", False, ("col", "x", True), "f32"),
             ("avg", False, ("col", "x", True), "f32")),
            (("gdict", "g"),), 4, 1024)
    out = {
        "presence": jnp.array([1, 0, 2, 0]),
        "agg0": jnp.array([1.5, 0.0, 2.5, 0.0]),
        "agg1": (jnp.array([3.0, 0.0, 5.0, 0.0]), jnp.array([2, 0, 1, 0])),
        "seg_matched": jnp.array([3, 0, 1]),
    }
    packed = np.asarray(pack_outputs(out, spec))
    total = sum(size for _, size in output_layout(spec, num_seg=3))
    assert packed.shape == (total,)
    back = unpack_outputs(packed, spec, num_seg=3)
    np.testing.assert_array_equal(back["presence"], [1, 0, 2, 0])
    np.testing.assert_array_equal(back["agg0"], [1.5, 0.0, 2.5, 0.0])
    np.testing.assert_array_equal(back["agg1"][0], [3.0, 0.0, 5.0, 0.0])
    np.testing.assert_array_equal(back["agg1"][1], [2, 0, 1, 0])
    np.testing.assert_array_equal(back["seg_matched"], [3, 0, 1])

    # scalar spec: num_matched + count + distinctcount presence
    spec_s = (("true",),
              (("count", False, None, "i32"),
               ("distinctcount", "region", 5)),
              (), 0, 1024)
    out_s = {
        "num_matched": jnp.asarray(7),
        "agg0": jnp.asarray(7),
        "agg1": jnp.array([1, 0, 1, 1, 0]),
    }
    back_s = unpack_outputs(np.asarray(pack_outputs(out_s, spec_s)), spec_s)
    assert int(back_s["num_matched"]) == 7
    assert int(back_s["agg0"]) == 7
    np.testing.assert_array_equal(back_s["agg1"], [1, 0, 1, 1, 0])


class TestCompactGroupBy:
    """Sparse output compaction for huge padded key spaces
    (kernels.compact_mode; SSB Q3.2/Q4.3 shape)."""

    @pytest.fixture(scope="class")
    def wide_segs(self, tmp_path_factory):
        out = str(tmp_path_factory.mktemp("wide"))
        rng = np.random.default_rng(77)
        n = 20_000
        # two ~150-card dims + year: padded key space ~2^18 >> live groups
        schema = Schema("wide", [
            FieldSpec("a", DataType.STRING),
            FieldSpec("b", DataType.STRING),
            FieldSpec("year", DataType.INT),
            FieldSpec("v", DataType.LONG, FieldType.METRIC),
        ])
        frame = {
            "a": [f"a{i:03d}" for i in rng.integers(0, 150, n)],
            "b": [f"b{i:03d}" for i in rng.integers(0, 150, n)],
            "year": rng.integers(2000, 2004, n).tolist(),
            "v": rng.integers(0, 100, n).tolist(),
        }
        segs = []
        for i in range(2):
            SegmentBuilder(schema, f"w{i}").build(frame, out)
            segs.append(load_segment(f"{out}/w{i}"))
        return segs

    def test_compact_parity(self, wide_segs):
        from pinot_tpu.engine.kernels import compact_mode, sparse_mode
        from pinot_tpu.engine.plan import plan_segment

        # the filter rides a NON-group column so dictId narrowing can't
        # shrink the 2^17 composed key space (an `a IN (...)` filter now
        # takes the dense rung outright — covered by test_hash_groupby)
        sql = ("SELECT a, b, year, sum(v), count(*) FROM wide "
               "WHERE v < 30 "
               "GROUP BY a, b, year ORDER BY a, b, year LIMIT 15000")
        ctx = compile_query(sql)
        spec = plan_segment(ctx, wide_segs[0]).spec
        assert compact_mode(spec) > 0
        # a ~2^17 key space must ride the sparse-grouping rungs of the
        # cardinality ladder (hash with sort fallback), not a dense scatter
        assert sparse_mode(spec) > 0
        dev = ShardedQueryExecutor()
        host = ServerQueryExecutor(use_device=False)
        drt, stats = dev.execute(ctx, wide_segs)
        hrt, _ = host.execute(ctx, wide_segs)
        assert drt.rows == hrt.rows
        assert len(drt.rows) > 100
        assert stats.group_by_rung in ("hash", "sort")

    def test_sparse_doc_sharded_parity(self, wide_segs):
        """Sparse compacts carry DIFFERENT key sets per doc shard; the
        cross-shard merge must re-group them exactly (combine.py
        _sparse_cross_combine)."""
        from pinot_tpu.parallel import make_combine_mesh

        sql = ("SELECT a, b, year, sum(v), count(*), min(v), max(v), "
               "avg(v) FROM wide WHERE v < 30 "
               "GROUP BY a, b, year ORDER BY a, b, year LIMIT 15000")
        ctx = compile_query(sql)
        dev = ShardedQueryExecutor(mesh=make_combine_mesh(doc_shards=2))
        host = ServerQueryExecutor(use_device=False)
        drt, _ = dev.execute(ctx, wide_segs)
        hrt, _ = host.execute(ctx, wide_segs)
        assert drt.rows == hrt.rows
        assert len(drt.rows) > 100

    def test_overflow_falls_back_to_full_results(self, wide_segs):
        """More live groups than the compact cap: the host path must serve
        the complete result (never truncation)."""
        sql = ("SELECT a, b, year, sum(v) FROM wide "
               "GROUP BY a, b, year ORDER BY a, b, year LIMIT 100000")
        ctx = compile_query(sql)
        dev = ShardedQueryExecutor()
        host = ServerQueryExecutor(use_device=False)
        drt, _ = dev.execute(ctx, wide_segs)
        hrt, _ = host.execute(ctx, wide_segs)
        assert drt.rows == hrt.rows
        assert len(drt.rows) > 8192


def test_sharded_executor_concurrent_queries(tmp_path):
    """16 threads through ONE ShardedQueryExecutor: the query/device-col
    caches are shared mutable state on the serving path (locks added in
    round 4) — results must stay correct under the race."""
    import concurrent.futures

    rng = np.random.default_rng(3)
    schema = Schema("cc", [
        FieldSpec("k", DataType.STRING),
        FieldSpec("v", DataType.LONG, FieldType.METRIC)])
    segs = []
    expect = {}
    frame = {"k": [f"k{i % 4}" for i in range(6000)],
             "v": rng.integers(0, 100, 6000).tolist()}
    for i in range(4):
        SegmentBuilder(schema, f"cc_{i}").build(frame, str(tmp_path))
        segs.append(load_segment(str(tmp_path / f"cc_{i}")))
    for key in ("k0", "k1", "k2", "k3"):
        expect[key] = 4 * sum(v for k, v in zip(frame["k"], frame["v"])
                              if k == key)
    ex = ShardedQueryExecutor()
    queries = [f"SELECT sum(v) FROM cc WHERE k = '{k}'" for k in expect] * 8

    def run(sql):
        t, _ = ex.execute(compile_query(sql), segs)
        return sql, t.rows[0][0]

    with concurrent.futures.ThreadPoolExecutor(16) as pool:
        for sql, got in pool.map(run, queries):
            key = sql.split("'")[1]
            assert got == expect[key], (sql, got)
