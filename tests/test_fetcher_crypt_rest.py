"""Retrying segment fetcher + encryption SPI + controller REST breadth.

Refs: pinot-common/.../utils/fetcher/SegmentFetcherFactory.java (retry
policies + fetchAndDecryptSegmentToLocal), pinot-common/.../crypt/
(PinotCrypter SPI), PinotTenantRestletResource / PinotTaskRestletResource /
ZookeeperResource (controller API resources).
"""

import json
import urllib.error
import urllib.request

import numpy as np
import pytest

from pinot_tpu.spi import DataType, FieldSpec, FieldType, Schema
from pinot_tpu.spi.crypt import (
    KeyedStreamCrypter,
    NoOpPinotCrypter,
    get_crypter,
    register_crypter,
)
from pinot_tpu.spi.filesystem import fetch_segment, get_fs, register_fs


class TestCrypt:
    def test_keyed_roundtrip(self, tmp_path):
        p = tmp_path / "seg.bin"
        payload = bytes(range(256)) * 100
        p.write_bytes(payload)
        c = KeyedStreamCrypter(b"s3cret-key")
        c.encrypt(str(p))
        assert p.read_bytes() != payload  # actually transformed
        c.decrypt(str(p))
        assert p.read_bytes() == payload

    def test_wrong_key_differs(self, tmp_path):
        p = tmp_path / "seg.bin"
        p.write_bytes(b"columnar bytes" * 50)
        KeyedStreamCrypter(b"key-a").encrypt(str(p))
        KeyedStreamCrypter(b"key-b").decrypt(str(p))
        assert p.read_bytes() != b"columnar bytes" * 50

    def test_decrypt_rejects_plain_file(self, tmp_path):
        p = tmp_path / "plain.bin"
        p.write_bytes(b"not encrypted")
        with pytest.raises(ValueError):
            KeyedStreamCrypter(b"k").decrypt(str(p))

    def test_registry(self):
        assert isinstance(get_crypter("noop"), NoOpPinotCrypter)
        register_crypter("test-keyed", lambda: KeyedStreamCrypter(b"k"))
        assert isinstance(get_crypter("TEST-KEYED"), KeyedStreamCrypter)
        with pytest.raises(ValueError):
            get_crypter("aes-gcm-unregistered")


class TestRetryingFetcher:
    def test_retries_transient_failures(self, tmp_path, monkeypatch):
        """First two attempts fail, third succeeds — the fetch must survive
        (SegmentFetcherFactory wraps fetchers in RetryPolicies)."""
        src = tmp_path / "seg_src"
        src.mkdir()
        (src / "col.npy").write_bytes(b"data")
        attempts = {"n": 0}

        class FlakyFS:
            def copy_to_local_dir(self, uri, local_dir):
                attempts["n"] += 1
                if attempts["n"] < 3:
                    raise OSError("transient deep-store fault")
                import shutil

                dst = str(tmp_path / "seg_dst")
                shutil.copytree(str(src), dst, dirs_exist_ok=True)
                return dst

        register_fs("flaky", FlakyFS)
        out = fetch_segment("flaky://deep/seg_src", str(tmp_path),
                            retries=3, backoff_s=0.01)
        assert attempts["n"] == 3
        assert (tmp_path / "seg_dst" / "col.npy").read_bytes() == b"data"

    def test_exhausted_retries_raise(self, tmp_path):
        class DeadFS:
            def copy_to_local_dir(self, uri, local_dir):
                raise OSError("down")

        from pinot_tpu.spi.retry import AttemptsExceededError

        register_fs("dead", DeadFS)
        with pytest.raises(AttemptsExceededError) as e:
            fetch_segment("dead://x/y", str(tmp_path), retries=2,
                          backoff_s=0.01)
        assert isinstance(e.value.last, OSError)

    def test_unknown_scheme_fails_fast(self, tmp_path):
        """A permanent error (no FS for the scheme) must not burn the
        retry/backoff budget."""
        import time

        t0 = time.perf_counter()
        with pytest.raises(ValueError):
            fetch_segment("s4://bucket/seg", str(tmp_path), retries=5,
                          backoff_s=5.0)
        assert time.perf_counter() - t0 < 1.0

    def test_decrypt_never_mutates_file_deep_store(self, tmp_path):
        """file:// stores serve segments in place; decrypt must act on a
        LOCAL copy or the first fetch silently de-encrypts the shared
        store and every later fetch fails."""
        deep = tmp_path / "deepstore" / "segX"
        deep.mkdir(parents=True)
        f = deep / "col.npy"
        f.write_bytes(b"columnar payload")
        register_crypter("deeptest", lambda: KeyedStreamCrypter(b"dk"))
        get_crypter("deeptest").encrypt(str(f))
        encrypted = f.read_bytes()

        local = tmp_path / "local"
        local.mkdir()
        for _ in range(2):  # a second replica fetch must also succeed
            out = fetch_segment(f"file://{deep}", str(local),
                                crypter="deeptest")
            assert (tmp_path / "local" / "segX" / "col.npy").read_bytes() \
                == b"columnar payload"
        assert f.read_bytes() == encrypted  # deep store untouched

    def test_fetch_and_decrypt(self, tmp_path):
        """Encrypted files in the deep store come back readable
        (fetchAndDecryptSegmentToLocal)."""
        src = tmp_path / "enc_src"
        src.mkdir()
        f = src / "part.npy"
        f.write_bytes(b"\x93NUMPY fake payload")
        register_crypter("fetchtest", lambda: KeyedStreamCrypter(b"fk"))
        get_crypter("fetchtest").encrypt(str(f))

        import shutil

        class EncFS:
            def copy_to_local_dir(self, uri, local_dir):
                dst = str(tmp_path / "enc_dst")
                shutil.copytree(str(src), dst, dirs_exist_ok=True)
                return dst

        register_fs("encfs", EncFS)
        out = fetch_segment("encfs://deep/enc_src", str(tmp_path),
                            crypter="fetchtest")
        assert (tmp_path / "enc_dst" / "part.npy").read_bytes() == \
            b"\x93NUMPY fake payload"


@pytest.fixture(scope="module")
def rest_cluster(tmp_path_factory):
    from pinot_tpu.spi.table import TableConfig
    from pinot_tpu.tools.cluster import EmbeddedCluster
    from pinot_tpu.transport.rest import ControllerApi

    out = str(tmp_path_factory.mktemp("restb"))
    schema = Schema("rb", [
        FieldSpec("k", DataType.STRING),
        FieldSpec("v", DataType.LONG, FieldType.METRIC),
    ])
    cluster = EmbeddedCluster(num_servers=2, data_dir=out)
    cluster.create_table(TableConfig(table_name="rb"), schema)
    cluster.ingest_rows("rb_OFFLINE", schema,
                        {"k": ["a", "b"] * 10,
                         "v": list(np.arange(20))},
                        segment_name="rb_seg0")
    cluster.wait_for_ev_converged("rb_OFFLINE")
    api = ControllerApi(cluster.controller)
    api.start()
    yield cluster, api
    api.stop()
    cluster.shutdown()


def _get(api, path):
    with urllib.request.urlopen(
            f"http://localhost:{api.port}{path}", timeout=10) as r:
        return json.loads(r.read().decode())


class TestControllerRestBreadth:
    def test_tenants(self, rest_cluster):
        cluster, api = rest_cluster
        tenants = _get(api, "/tenants")
        assert "DefaultTenant" in tenants["SERVER_TENANTS"]
        members = _get(api, "/tenants/DefaultTenant")
        assert len(members["instances"]) >= 2

    def test_update_instance_tags(self, rest_cluster):
        cluster, api = rest_cluster
        inst = _get(api, "/instances")["instances"]
        server = next(i["instanceId"] for i in inst
                      if i["type"].upper().startswith("SERVER"))
        req = urllib.request.Request(
            f"http://localhost:{api.port}/instances/{server}/updateTags",
            data=json.dumps({"tags": ["DefaultTenant", "hotTier"]}).encode(),
            method="PUT", headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=10) as r:
            assert r.status == 200
        assert server in _get(api, "/tenants/hotTier")["instances"]

    def test_task_endpoints(self, rest_cluster):
        cluster, api = rest_cluster
        req = urllib.request.Request(
            f"http://localhost:{api.port}/tasks/schedule", data=b"{}",
            method="POST", headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=10) as r:
            assert r.status == 200
        assert isinstance(_get(api, "/tasks/tasktypes"), list)

    def test_zk_browse(self, rest_cluster):
        cluster, api = rest_cluster
        keys = _get(api, "/zk/ls")
        assert keys, "state store browse returned nothing"
        node = _get(api, f"/zk/get/{keys[0]}")
        assert node["path"] == keys[0]
        with pytest.raises(urllib.error.HTTPError) as e:
            _get(api, "/zk/get/NO/SUCH/NODE")
        assert e.value.code == 404


class TestEnvironmentProvider:
    """Fault-domain discovery SPI + domain-aware replica spread
    (ref: pinot-plugins/pinot-environment, AzureEnvironmentProvider)."""

    def test_env_var_provider(self, monkeypatch):
        from pinot_tpu.spi.environment import (
            EnvVarEnvironmentProvider,
            get_environment_provider,
        )

        monkeypatch.setenv("PINOT_FAILURE_DOMAIN", "zone-b")
        assert EnvVarEnvironmentProvider().failure_domain() == "zone-b"
        assert get_environment_provider("env").failure_domain() == "zone-b"
        monkeypatch.delenv("PINOT_FAILURE_DOMAIN")
        assert get_environment_provider("env").failure_domain() is None
        assert get_environment_provider("noop").get_environment() == {}

    def test_replicas_spread_across_domains(self):
        from pinot_tpu.controller.assignment import (
            BalancedSegmentAssignment,
        )

        # 4 servers in 2 domains; replication 2 must land on BOTH domains
        # even when one domain's servers are the least loaded
        domains = {"s1": "zoneA", "s2": "zoneA", "s3": "zoneB",
                   "s4": "zoneB"}
        strat = BalancedSegmentAssignment(domains=domains)
        current = {"seg0": {"s3": "ONLINE"}, "seg1": {"s4": "ONLINE"}}
        chosen = strat.assign("seg2", current, ["s1", "s2", "s3", "s4"], 2)
        assert {domains[c] for c in chosen} == {"zoneA", "zoneB"}, chosen

    def test_restart_preserves_operator_tags(self, tmp_path):
        """PUT updateTags must survive a server restart (re-registration
        carries stored tags forward)."""
        from pinot_tpu.spi.table import TableConfig
        from pinot_tpu.tools.cluster import EmbeddedCluster

        cluster = EmbeddedCluster(num_servers=1, data_dir=str(tmp_path))
        try:
            sid = cluster.store.instances("SERVER")[0].instance_id
            cluster.controller.update_instance_tags(
                sid, ["DefaultTenant", "hotTier"])
            # restart = re-run registration (ServerInstance.start path)
            cluster.servers[sid].start()
            info = cluster.store.get_instance(sid)
            assert "hotTier" in info.tags
        finally:
            cluster.shutdown()

    def test_rebalance_keeps_domain_spread(self, tmp_path):
        from pinot_tpu.controller.assignment import (
            compute_target_assignment,
        )

        domains = {"s1": "fd1", "s2": "fd1", "s3": "fd2"}
        current = {"seg0": {"s1": "ONLINE", "s2": "ONLINE"}}
        target = compute_target_assignment(
            current, ["s1", "s2", "s3"], 2, domains=domains)
        assert {domains[i] for i in target["seg0"]} == {"fd1", "fd2"}

    def test_registration_carries_domain(self, tmp_path, monkeypatch):
        from pinot_tpu.spi.table import TableConfig
        from pinot_tpu.tools.cluster import EmbeddedCluster

        monkeypatch.setenv("PINOT_FAILURE_DOMAIN", "rack-7")
        cluster = EmbeddedCluster(num_servers=1, data_dir=str(tmp_path))
        try:
            infos = cluster.store.instances("SERVER")
            assert infos and all(i.failure_domain == "rack-7"
                                 for i in infos)
        finally:
            cluster.shutdown()
