"""Regression tests for the PR-19 conformance fixes.

Each true positive the v4 graftlint families (``decisions`` /
``exactness`` / ``configkeys``) surfaced at landing time was fixed
in-code, never baselined; these tests pin the fixed behavior so a
revert re-fails loudly:

- ``common/bounds.py`` — the hoisted wide-bound constants keep their
  derivations (a typo'd bit width is exactly the bug the hoist
  prevents), and the reduce-tier guards still cut over at them;
- ``engine/executor.py`` — the host star-tree walker refusing a tree
  the pick accepted now lands in the decision ledger
  (``startree_walker_declined``) instead of silently falling to scan;
- ``broker/broker.py`` — ``device_reduce=None`` resolves through
  ``PinotConfiguration`` (``pinot.broker.reduce.device.enabled``), an
  explicit constructor argument still wins;
- ``common/telemetry.py`` — the SLO key parse is built from the
  declared ``SLO_KEY_PREFIX`` constant, so a key composed from the
  constant always parses.
"""

import numpy as np
import pandas as pd
import pytest

from pinot_tpu.common import bounds, tracing
from pinot_tpu.spi.config import CommonConstants, PinotConfiguration

pytestmark = pytest.mark.trace


class TestBounds:
    def test_values_and_derivations(self):
        assert bounds.I64_FOLD_BOUND == 2 ** 62
        assert bounds.I64_KEY_SPACE_BOUND == 2 ** 62
        assert bounds.F64_EXACT_INT_BOUND == float(2 ** 53)
        assert isinstance(bounds.F64_EXACT_INT_BOUND, float)
        assert bounds.I64_PAD_SENTINEL == 2 ** 63 - 1
        # the derivation relations the comments promise
        assert bounds.I64_FOLD_BOUND * 2 - 1 == bounds.I64_PAD_SENTINEL
        assert bounds.I64_KEY_SPACE_BOUND < bounds.I64_PAD_SENTINEL
        assert float(2 ** 53) + 1.0 == float(2 ** 53)  # why 53 is the edge
        assert float(2 ** 53 - 1) + 1.0 != float(2 ** 53 - 1)

    def test_f64_sum_exact_cuts_over_at_named_bound(self):
        from pinot_tpu.parallel.reduce_device import f64_sum_exact

        under = np.array([bounds.F64_EXACT_INT_BOUND / 2], dtype=np.float64)
        over = np.array([bounds.F64_EXACT_INT_BOUND], dtype=np.float64)
        assert f64_sum_exact(under)
        assert not f64_sum_exact(over)

    def test_composite_key_space_declines_past_named_bound(self):
        from pinot_tpu.parallel.reduce_device import encode_composite_keys

        # two i64 dims each spanning ~2^32 values: the radix product
        # exceeds I64_KEY_SPACE_BOUND, so the encoder must decline
        wide = np.array([0, 1 << 32], dtype=np.int64)
        keys, space = encode_composite_keys([wide, wide])
        assert keys is None and space == 0
        # ...while one such dim still fits
        keys, space = encode_composite_keys([wide])
        assert keys is not None and space == (1 << 32) + 1


class TestWalkerDeclineLedger:
    def test_walker_refusal_is_recorded_not_silent(self, monkeypatch,
                                                   tmp_path):
        """The pick accepts a tree, the host walker refuses it at
        execution time: the scan serves AND the ledger explains the
        fallback (the v4 `decisions` family's flagship true positive)."""
        from pinot_tpu.engine import ServerQueryExecutor, startree_exec
        from pinot_tpu.query import compile_query
        from pinot_tpu.segment import SegmentBuilder, load_segment
        from pinot_tpu.spi import DataType, FieldSpec, FieldType, Schema
        from pinot_tpu.spi.table import IndexingConfig, StarTreeIndexConfig

        rng = np.random.default_rng(7)
        n = 400
        df = pd.DataFrame({
            "country": [f"c{i}" for i in rng.integers(0, 5, n)],
            "revenue": np.round(rng.gamma(2.0, 50.0, n), 2),
        })
        schema = Schema("orders", [
            FieldSpec("country", DataType.STRING),
            FieldSpec("revenue", DataType.DOUBLE, FieldType.METRIC),
        ])
        cfg = IndexingConfig(star_tree_index_configs=[StarTreeIndexConfig(
            dimensions_split_order=["country"],
            function_column_pairs=["COUNT__*", "SUM__revenue"])])
        out = str(tmp_path)
        b = SegmentBuilder(schema, "orders_0", indexing_config=cfg)
        b.build({c: df[c].tolist() for c in df.columns}, out)
        seg = load_segment(f"{out}/orders_0")
        assert seg.metadata.star_tree_count == 1

        monkeypatch.setattr(startree_exec, "execute_with_matches",
                            lambda *a, **kw: None)
        mark = tracing.LEDGER.snapshot()
        ex = ServerQueryExecutor(use_device=False)
        table, stats = ex.execute(
            compile_query("SELECT sum(revenue) FROM orders"), [seg])
        assert table.rows[0][0] == pytest.approx(float(df["revenue"].sum()))
        delta = tracing.LEDGER.delta(mark)
        hits = [k for k in delta if "startree_walker_declined" in k]
        assert hits, f"walker refusal not in the ledger: {sorted(delta)}"
        assert "startree_walker_declined" in \
            tracing.registered_reason_codes()


class TestBrokerDeviceReduceConfig:
    def _handler(self, **kw):
        from pinot_tpu.broker.broker import BrokerRequestHandler
        from pinot_tpu.controller.state import ClusterStateStore

        return BrokerRequestHandler(ClusterStateStore(), **kw)

    def test_env_key_enables_device_reduce(self, monkeypatch):
        monkeypatch.setenv("PINOT_BROKER_REDUCE_DEVICE_ENABLED", "true")
        h = self._handler()
        try:
            assert h.reduce_service.device_reduce is True
        finally:
            h.shutdown()

    def test_default_is_declared_constant(self, monkeypatch):
        monkeypatch.delenv("PINOT_BROKER_REDUCE_DEVICE_ENABLED",
                           raising=False)
        h = self._handler()
        try:
            assert h.reduce_service.device_reduce \
                is CommonConstants.DEFAULT_BROKER_DEVICE_REDUCE
        finally:
            h.shutdown()

    def test_explicit_argument_wins_over_env(self, monkeypatch):
        monkeypatch.setenv("PINOT_BROKER_REDUCE_DEVICE_ENABLED", "true")
        h = self._handler(device_reduce=False)
        try:
            assert h.reduce_service.device_reduce is False
        finally:
            h.shutdown()


class TestBenchDecisionValidation:
    """bench.py's runtime mirror of the lint `decisions` family: every
    suite's decision histogram must parse against the reason registry."""

    def test_registered_and_dynamic_reasons_pass(self):
        import bench

        ok = {tracing.decision_key("startree", "scan", "startree",
                                   "tree3"): 2,
              tracing.decision_key("routing", "pruned", "all_servers",
                                   "time_prune"): 1}
        bench._Worker._validate_decisions("ssb", ok)

    def test_unregistered_reason_fails_loud(self, monkeypatch):
        import bench

        bad = {tracing.decision_key("startree", "scan", "startree",
                                    "bogus_reason_zzz"): 1}
        monkeypatch.delenv("BENCH_ALLOW_UNREGISTERED_REASON",
                           raising=False)
        with pytest.raises(AssertionError, match="bogus_reason_zzz"):
            bench._Worker._validate_decisions("qps", bad)
        # the bring-up escape downgrades to a log line
        monkeypatch.setenv("BENCH_ALLOW_UNREGISTERED_REASON", "1")
        bench._Worker._validate_decisions("qps", bad)


class TestSloPrefixIsDeclared:
    def test_key_built_from_constant_parses(self):
        from pinot_tpu.common.telemetry import Telemetry

        key = CommonConstants.SLO_KEY_PREFIX + "my_table_REALTIME.p99.ms"
        t = Telemetry()
        t.configure(PinotConfiguration({key: "150"}, use_env=False))
        assert t.slo.objectives()["my_table_REALTIME"]["p99_ms"] == 150.0
