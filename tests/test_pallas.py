"""Pallas fused scan kernel parity: bit-unpack + predicate + one-hot
group-by matmul vs the host engine (interpret mode on the CPU backend;
the same kernel compiles for real TPUs).

Ref parity targets: SVScanDocIdIterator.java:36 (predicate scan),
PinotDataBitSet.java:25 (bit extraction), DefaultGroupByExecutor (grouping).
"""

import numpy as np
import pandas as pd
import pytest

from pinot_tpu.engine import ServerQueryExecutor

pytestmark = pytest.mark.pallas
from pinot_tpu.engine.plan import plan_segment
from pinot_tpu.engine.staging import PALLAS_TILE, StagingCache, pack_bits
from pinot_tpu.query import compile_query
from pinot_tpu.segment import SegmentBuilder, load_segment
from pinot_tpu.spi import DataType, FieldSpec, FieldType, Schema

N = 2 * PALLAS_TILE - 700   # 2 tiles with a padded tail


def make_schema():
    return Schema("pl_sales", [
        FieldSpec("region", DataType.STRING),
        FieldSpec("city", DataType.STRING),
        FieldSpec("year", DataType.INT),
        FieldSpec("qty", DataType.LONG, FieldType.METRIC),
        FieldSpec("price", DataType.DOUBLE, FieldType.METRIC),
    ])


@pytest.fixture(scope="module")
def setup(tmp_path_factory):
    out = tmp_path_factory.mktemp("pallas_segs")
    rng = np.random.default_rng(11)
    regions = ["east", "west", "north", "south"]
    cities = [f"c{i:03d}" for i in range(137)]   # 8-bit dictIds
    df = pd.DataFrame({
        "region": [regions[i] for i in rng.integers(0, 4, N)],
        "city": [cities[i] for i in rng.integers(0, len(cities), N)],
        "year": rng.integers(2000, 2024, N).astype(np.int64),
        "qty": rng.integers(1, 100, N).astype(np.int64),
        "price": np.round(rng.normal(80.0, 30.0, N), 2),
    })
    segs = []
    for i, sl in enumerate([slice(0, N // 2), slice(N // 2, N)]):
        b = SegmentBuilder(make_schema(), f"pl_sales_{i}")
        b.build({c: df[c].tolist()[sl] for c in df.columns}, str(out))
        segs.append(load_segment(str(out / f"pl_sales_{i}")))
    return df, segs


@pytest.fixture(scope="module")
def pallas_exec():
    return ServerQueryExecutor(use_device=True, use_pallas=True)


@pytest.fixture(scope="module")
def host_exec():
    return ServerQueryExecutor(use_device=False)


QUERIES = [
    "SELECT region, count(*) FROM pl_sales GROUP BY region ORDER BY region",
    "SELECT region, sum(qty), count(*) FROM pl_sales "
    "WHERE year BETWEEN 2005 AND 2015 GROUP BY region ORDER BY region",
    "SELECT region, sum(price), avg(price) FROM pl_sales "
    "WHERE region != 'west' GROUP BY region ORDER BY region",
    "SELECT city, sum(qty), avg(qty) FROM pl_sales WHERE year = 2010 "
    "GROUP BY city ORDER BY city LIMIT 200",
    "SELECT region, city, sum(price), count(*) FROM pl_sales "
    "WHERE year >= 2012 AND region = 'east' "
    "GROUP BY region, city ORDER BY region, city LIMIT 200",
    "SELECT year, sum(qty), sum(price) FROM pl_sales "
    "GROUP BY year ORDER BY year LIMIT 30",
]


def test_plans_are_pallas_eligible(setup, pallas_exec):
    """The suite must actually exercise the pallas path, not fall back."""
    from pinot_tpu.engine.pallas_kernels import extract_plan

    _, segs = setup
    for sql in QUERIES:
        plan = plan_segment(compile_query(sql), segs[0])
        assert extract_plan(plan, segs[0]) is not None, sql


@pytest.mark.parametrize("sql", QUERIES, ids=[q[:60] for q in QUERIES])
def test_pallas_matches_host(setup, pallas_exec, host_exec, sql):
    _, segs = setup
    got, _ = pallas_exec.execute(compile_query(sql), segs)
    want, _ = host_exec.execute(compile_query(sql), segs)
    assert len(got.rows) == len(want.rows)
    for gr, wr in zip(got.rows, want.rows):
        for g, w in zip(gr, wr):
            if isinstance(w, float):
                assert g == pytest.approx(w, rel=1e-5, abs=1e-6), (sql, gr, wr)
            else:
                assert g == w, (sql, gr, wr)
    assert len(pallas_exec.pallas_kernels) >= 1


def test_pallas_kernels_cached(setup, pallas_exec):
    _, segs = setup
    before = len(pallas_exec.pallas_kernels)
    sql = QUERIES[1]
    pallas_exec.execute(compile_query(sql), segs)
    pallas_exec.execute(compile_query(sql), segs)
    assert len(pallas_exec.pallas_kernels) == before


def test_packed_layout_roundtrip(setup):
    """Planar packing: unpacking word j%W slot (j//W)*B recovers dictIds."""
    _, segs = setup
    staged = StagingCache().stage(segs[0])
    for col in ("region", "city", "year"):
        pc = staged.packed_column(col)
        assert pc is not None
        bits, K = pc.bits, pc.vals_per_word
        assert bits == pack_bits(
            max(1, (segs[0].metadata.column(col).cardinality - 1).bit_length()))
        words = np.asarray(pc.words)               # [tiles, W]
        W = PALLAS_TILE // K
        got = np.zeros((words.shape[0], K, W), dtype=np.uint32)
        for k in range(K):
            got[:, k, :] = (words >> np.uint32(k * bits)) & ((1 << bits) - 1)
        fwd = np.asarray(segs[0].data_source(col).forward_index)
        flat = got.reshape(-1)[:fwd.shape[0]]
        np.testing.assert_array_equal(flat, fwd.astype(np.uint32))


# -- widened eligibility (round-4): scalar aggs, min/max, OR filters --------

WIDE_QUERIES = [
    "SELECT count(*), sum(qty) FROM pl_sales WHERE region = 'east'",
    "SELECT sum(price), avg(qty) FROM pl_sales "
    "WHERE year BETWEEN 2005 AND 2015",
    "SELECT min(price), max(price), minmaxrange(qty) FROM pl_sales "
    "WHERE region != 'west'",
    "SELECT region, min(qty), max(price) FROM pl_sales "
    "GROUP BY region ORDER BY region",
    "SELECT region, sum(qty) FROM pl_sales "
    "WHERE year = 2010 OR region = 'east' GROUP BY region ORDER BY region",
    "SELECT count(*) FROM pl_sales "
    "WHERE (region = 'east' OR region = 'west') AND year >= 2012",
]


def test_wide_plans_are_pallas_eligible(setup):
    from pinot_tpu.engine.pallas_kernels import extract_plan

    _, segs = setup
    for sql in WIDE_QUERIES:
        plan = plan_segment(compile_query(sql), segs[0])
        assert extract_plan(plan, segs[0]) is not None, sql


@pytest.mark.parametrize("sql", WIDE_QUERIES, ids=[q[:60] for q in WIDE_QUERIES])
def test_wide_pallas_matches_host(setup, pallas_exec, host_exec, sql):
    _, segs = setup
    got, _ = pallas_exec.execute(compile_query(sql), segs)
    want, _ = host_exec.execute(compile_query(sql), segs)
    assert len(got.rows) == len(want.rows)
    for gr, wr in zip(got.rows, want.rows):
        for g, w in zip(gr, wr):
            if isinstance(w, float):
                assert g == pytest.approx(w, rel=1e-5, abs=1e-6), (sql, gr, wr)
            else:
                assert g == w, (sql, gr, wr)


# -- round-5 eligibility: expression agg values + limb-exact big-int sums ---

@pytest.fixture(scope="module")
def big_setup(tmp_path_factory):
    """SSB-shaped values: products and sums far beyond the old kernel's
    f32-per-tile and provider-wide-i32 exactness bounds."""
    out = tmp_path_factory.mktemp("pallas_big")
    rng = np.random.default_rng(23)
    n = N
    schema = Schema("pl_big", [
        FieldSpec("k", DataType.STRING),
        FieldSpec("price", DataType.INT, FieldType.METRIC),
        FieldSpec("disc", DataType.INT, FieldType.METRIC),
        FieldSpec("rev", DataType.LONG, FieldType.METRIC),
    ])
    frame = {
        "k": np.array(["a", "b", "c"])[rng.integers(0, 3, n)],
        "price": rng.integers(905, 5_550_000, n).astype(np.int64),
        "disc": rng.integers(0, 11, n).astype(np.int64),
        "rev": rng.integers(0, 5_500_000, n).astype(np.int64),
    }
    segs = []
    for i, sl in enumerate([slice(0, n // 2), slice(n // 2, n)]):
        b = SegmentBuilder(schema, f"pl_big_{i}")
        b.build({c: v[sl] for c, v in frame.items()}, str(out))
        segs.append(load_segment(str(out / f"pl_big_{i}")))
    return frame, segs


BIG_QUERIES = [
    # all three SSB Q1 flights are sum(extendedprice * discount) shapes
    "SELECT sum(price * disc) FROM pl_big WHERE disc BETWEEN 1 AND 3",
    # literal operands bake into the kernel spec as constants
    "SELECT sum(disc * 1000), max(rev) FROM pl_big WHERE disc > 2",
    "SELECT k, sum(fromEpochSeconds(disc)) FROM pl_big GROUP BY k "
    "ORDER BY k",
    "SELECT sum(rev) FROM pl_big",                       # > i32 total
    "SELECT k, sum(rev), count(*) FROM pl_big GROUP BY k ORDER BY k",
    "SELECT k, sum(price * disc), avg(rev) FROM pl_big "
    "GROUP BY k ORDER BY k",
    "SELECT sum(rev - price) FROM pl_big WHERE disc > 5",  # Q4 shape
]


def test_big_value_plans_are_pallas_eligible(big_setup):
    from pinot_tpu.engine.pallas_kernels import extract_plan

    _, segs = big_setup
    for sql in BIG_QUERIES:
        plan = plan_segment(compile_query(sql), segs[0])
        assert extract_plan(plan, segs[0]) is not None, sql


@pytest.mark.parametrize("sql", BIG_QUERIES, ids=[q[:60] for q in BIG_QUERIES])
def test_big_value_sums_exact(big_setup, pallas_exec, host_exec, sql):
    """Limb-split accumulation must be EXACT (integer equality), not
    approximately right: the host engine computes in f64/int64."""
    _, segs = big_setup
    got, _ = pallas_exec.execute(compile_query(sql), segs)
    want, _ = host_exec.execute(compile_query(sql), segs)
    assert len(got.rows) == len(want.rows)
    for gr, wr in zip(got.rows, want.rows):
        for g, w in zip(gr, wr):
            if isinstance(w, float):
                assert g == pytest.approx(w, rel=1e-12), (sql, gr, wr)
            else:
                assert g == w, (sql, gr, wr)


def test_product_sum_matches_numpy_exactly(big_setup, pallas_exec):
    frame, segs = big_setup
    m = (frame["disc"] >= 1) & (frame["disc"] <= 3)
    exact = int((frame["price"][m] * frame["disc"][m]).sum())
    got, _ = pallas_exec.execute(compile_query(BIG_QUERIES[0]), segs)
    assert float(got.rows[0][0]) == float(exact)


# -- sharded fused-pallas combine (the serving path) ------------------------

@pytest.fixture(scope="module", params=[1, 2], ids=["doc1", "doc2"])
def sharded_pallas_exec(request):
    from pinot_tpu.parallel import ShardedQueryExecutor, make_combine_mesh

    mesh = make_combine_mesh(doc_shards=request.param)
    return ShardedQueryExecutor(mesh=mesh, use_pallas=True)


@pytest.mark.parametrize("sql", QUERIES + WIDE_QUERIES,
                         ids=[q[:60] for q in QUERIES + WIDE_QUERIES])
def test_sharded_pallas_matches_host(setup, sharded_pallas_exec, host_exec,
                                     sql):
    _, segs = setup
    got, stats = sharded_pallas_exec.execute(compile_query(sql), segs)
    want, _ = host_exec.execute(compile_query(sql), segs)
    assert len(got.rows) == len(want.rows)
    for gr, wr in zip(got.rows, want.rows):
        for g, w in zip(gr, wr):
            if isinstance(w, float):
                assert g == pytest.approx(w, rel=1e-5, abs=1e-6), (sql, gr, wr)
            else:
                assert g == w, (sql, gr, wr)
    assert stats.num_segments_processed == len(segs)


def test_sharded_pallas_kernel_actually_used(setup, sharded_pallas_exec):
    """The serving path must run the fused kernel, not the jnp fallback."""
    _, segs = setup
    sharded_pallas_exec.execute(compile_query(QUERIES[1]), segs)
    assert len(sharded_pallas_exec._pallas_sharded) >= 1


def test_lowering_failure_blocks_only_that_shape(setup, host_exec,
                                                 monkeypatch):
    """A Mosaic/compile failure must blocklist the failing QUERY SHAPE,
    not disable pallas process-wide (one unlowerable shape on the chip
    must not cost every other query its fused kernel)."""
    from pinot_tpu.engine import pallas_kernels as pk

    _, segs = setup
    ex = ServerQueryExecutor(use_device=True, use_pallas=True)
    bad_sql = QUERIES[0]
    good_sql = QUERIES[1]
    bad_spec = {}

    real = pk.run_segment

    def flaky(plan, staged, cache, interpret, **kw):
        if not bad_spec:
            bad_spec["spec"] = plan.spec
        if plan.spec == bad_spec["spec"]:
            raise RuntimeError("simulated Mosaic lowering failure")
        return real(plan, staged, cache, interpret, **kw)

    monkeypatch.setattr(pk, "run_segment", flaky)
    got, _ = ex.execute(compile_query(bad_sql), segs)     # falls back
    want, _ = host_exec.execute(compile_query(bad_sql), segs)
    assert got.rows == want.rows
    assert ex.use_pallas is not False                      # NOT global
    assert len(ex._pallas_blocked) == 1
    before = len(ex.pallas_kernels)
    ex.execute(compile_query(good_sql), segs)              # still fused
    assert len(ex.pallas_kernels) > before


def test_sharded_lowering_failure_blocks_only_that_shape(setup, host_exec,
                                                         monkeypatch):
    """Same per-shape containment on the SHARDED combine: the failing
    spec's compiled kernel is evicted, the shape falls back to the jnp
    combine with correct results, and other shapes keep the fused path."""
    from pinot_tpu.parallel import ShardedQueryExecutor, combine

    _, segs = setup
    ex = ShardedQueryExecutor(use_pallas=True)
    bad_sql, good_sql = QUERIES[0], QUERIES[1]

    real = combine.build_sharded_pallas_kernel

    def poisoned(spec, plan_spec, mesh):
        kernel = real(spec, plan_spec, mesh)
        state = {"first": True}

        def run(*args, **kw):
            if state["first"]:
                state["first"] = False
                raise RuntimeError("simulated Mosaic lowering failure")
            return kernel(*args, **kw)

        return run

    monkeypatch.setattr(combine, "build_sharded_pallas_kernel", poisoned)
    got, _ = ex.execute(compile_query(bad_sql), segs)      # jnp fallback
    want, _ = host_exec.execute(compile_query(bad_sql), segs)
    assert got.rows == want.rows
    assert ex.use_pallas is not False
    assert len(ex._pallas_blocked) == 1
    assert not ex._pallas_sharded                           # evicted
    monkeypatch.setattr(combine, "build_sharded_pallas_kernel", real)
    ex.execute(compile_query(good_sql), segs)               # still fused
    assert len(ex._pallas_sharded) == 1
    # the blocked shape stays on jnp even though pallas works again
    got2, _ = ex.execute(compile_query(bad_sql), segs)
    assert got2.rows == want.rows
    assert len(ex._pallas_sharded) == 1
