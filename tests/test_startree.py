"""Star-tree index: build/load round-trip, the reference's core parity
strategy — star-tree answers must equal non-star-tree answers on the same
data (ref: StarTreeClusterIntegrationTest) — and the DEVICE rung: node
slices through the group-by kernels, bit-identical to the scan paths."""

import numpy as np
import pandas as pd
import pytest

from pinot_tpu.engine import ServerQueryExecutor
from pinot_tpu.engine.aggregates import resolve_agg
from pinot_tpu.engine.startree_exec import pick_star_tree
from pinot_tpu.query import compile_query
from pinot_tpu.segment import SegmentBuilder, load_segment
from pinot_tpu.segment.startree import (
    STAR,
    DictIdRange,
    StarTree,
    StarTreeBuilder,
    StarTreeConfig,
)
from pinot_tpu.spi import DataType, FieldSpec, FieldType, Schema
from pinot_tpu.spi.table import IndexingConfig, StarTreeIndexConfig

pytestmark = pytest.mark.startree

N = 4000


def make_schema():
    return Schema("orders", [
        FieldSpec("country", DataType.STRING),
        FieldSpec("category", DataType.STRING),
        FieldSpec("channel", DataType.STRING),
        FieldSpec("revenue", DataType.DOUBLE, FieldType.METRIC),
        FieldSpec("units", DataType.LONG, FieldType.METRIC),
    ])


def make_df(n=N, seed=3):
    rng = np.random.default_rng(seed)
    return pd.DataFrame({
        "country": [f"c{i}" for i in rng.integers(0, 12, n)],
        "category": [f"k{i}" for i in rng.integers(0, 8, n)],
        "channel": [["web", "store", "app"][i] for i in rng.integers(0, 3, n)],
        "revenue": np.round(rng.gamma(2.0, 50.0, n), 2),
        "units": rng.integers(1, 20, n).astype(np.int64),
    })


@pytest.fixture(scope="module", params=[10_000, 16], ids=["fat-leaves", "deep-split"])
def seg_with_tree(request, tmp_path_factory):
    out = str(tmp_path_factory.mktemp("st"))
    df = make_df()
    cfg = IndexingConfig(star_tree_index_configs=[StarTreeIndexConfig(
        dimensions_split_order=["country", "category", "channel"],
        function_column_pairs=["COUNT__*", "SUM__revenue", "MAX__revenue",
                               "MIN__revenue", "SUM__units"],
        max_leaf_records=request.param)])
    b = SegmentBuilder(make_schema(), "orders_0", indexing_config=cfg)
    b.build({c: df[c].tolist() for c in df.columns}, out)
    seg = load_segment(f"{out}/orders_0")
    assert seg.metadata.star_tree_count == 1
    assert len(seg.star_trees) == 1
    return seg, df


PARITY_QUERIES = [
    "SELECT count(*), sum(revenue) FROM orders",
    "SELECT sum(revenue), sum(units) FROM orders WHERE country = 'c3'",
    "SELECT min(revenue), max(revenue) FROM orders WHERE category IN ('k1','k2')",
    "SELECT country, sum(revenue), count(*) FROM orders GROUP BY country "
    "ORDER BY country LIMIT 50",
    "SELECT country, category, sum(units) FROM orders WHERE channel = 'web' "
    "GROUP BY country, category ORDER BY country, category LIMIT 200",
    "SELECT category, avg(revenue) FROM orders GROUP BY category "
    "ORDER BY category LIMIT 50",
    "SELECT channel, max(revenue) FROM orders WHERE country != 'c0' "
    "GROUP BY channel ORDER BY channel LIMIT 50",
]


class TestStarTreeParity:
    @pytest.mark.parametrize("sql", PARITY_QUERIES)
    def test_star_tree_matches_scan(self, seg_with_tree, sql):
        """The reference's StarTreeClusterIntegrationTest invariant."""
        seg, _ = seg_with_tree
        ex = ServerQueryExecutor(use_device=False)
        ctx = compile_query(sql)
        aggs = [resolve_agg(f) for f in ctx.aggregations]
        assert pick_star_tree(ctx, aggs, seg) is not None, "tree must fit"

        with_tree, stats_tree = ex.execute(ctx, [seg])
        ctx2 = compile_query(sql)
        ctx2.options["useStarTree"] = "false"
        without, _ = ex.execute(ctx2, [seg])
        assert len(with_tree.rows) == len(without.rows)
        for a, b in zip(with_tree.rows, without.rows):
            for x, y in zip(a, b):
                if isinstance(y, float):
                    assert x == pytest.approx(y, rel=1e-9)
                else:
                    assert x == y

    def test_tree_scans_fewer_records(self, seg_with_tree):
        seg, _ = seg_with_tree
        ex = ServerQueryExecutor(use_device=False)
        ctx = compile_query("SELECT sum(revenue) FROM orders")
        _, stats = ex.execute(ctx, [seg])
        # filter-less total should touch far fewer pre-agg records than docs
        assert 0 < stats.num_docs_scanned < N / 2

    def test_unfit_queries_fall_through(self, seg_with_tree):
        seg, _ = seg_with_tree
        ex = ServerQueryExecutor(use_device=False)
        # revenue (a metric, not a dim) in the filter -> not fit, still correct
        t, _ = ex.execute(compile_query(
            "SELECT count(*) FROM orders WHERE revenue > 100"), [seg])
        ctx = compile_query("SELECT count(*) FROM orders WHERE revenue > 100")
        aggs = [resolve_agg(f) for f in ctx.aggregations]
        assert pick_star_tree(ctx, aggs, seg) is None
        assert t.rows[0][0] > 0


class TestStarTreeBuilder:
    def test_save_load_round_trip(self, tmp_path):
        df = make_df(500, seed=9)
        cfg = StarTreeConfig(["country", "category"],
                             [("count", "*"), ("sum", "revenue")],
                             max_leaf_records=8)
        # dictIds: factorize in sorted order like the segment dictionaries
        c_codes = pd.Categorical(df.country).codes.astype(np.int32)
        k_codes = pd.Categorical(df.category).codes.astype(np.int32)
        tree = StarTreeBuilder(cfg).build(
            {"country": c_codes, "category": k_codes},
            {"revenue": df.revenue.to_numpy()}, len(df))
        tree.save(str(tmp_path))
        loaded = StarTree.load(str(tmp_path))
        assert loaded is not None
        assert loaded.num_records == tree.num_records
        np.testing.assert_array_equal(np.asarray(loaded.dims),
                                      np.asarray(tree.dims))

        # filter-less total via traversal (star path / un-split leaves)
        idx = loaded.select_records({}, [])
        assert np.asarray(loaded.metrics["count__*"])[idx].sum() == len(df)

    def test_skip_star_creation(self):
        df = make_df(300, seed=11)
        c = pd.Categorical(df.country).codes.astype(np.int32)
        k = pd.Categorical(df.category).codes.astype(np.int32)
        cfg = StarTreeConfig(["country", "category"], [("count", "*")],
                             max_leaf_records=1,
                             skip_star_creation=["country"])
        tree = StarTreeBuilder(cfg).build({"country": c, "category": k}, {},
                                          len(df))
        # no record may have STAR at the skipped dimension
        assert not np.any(np.asarray(tree.dims)[:, 0] == STAR)
        # grouping by category still answers correctly via concrete rows
        idx = tree.select_records({}, ["category"])
        got = {}
        cats = np.asarray(tree.dims)[idx, 1]
        cnts = np.asarray(tree.metrics["count__*"])[idx]
        for cat, n in zip(cats, cnts):
            got[cat] = got.get(cat, 0) + int(n)
        want = df.groupby(k).size().to_dict()
        assert got == want

    def test_default_star_tree(self, tmp_path):
        df = make_df(400, seed=13)
        cfg = IndexingConfig(enable_default_star_tree=True)
        b = SegmentBuilder(make_schema(), "orders_d", indexing_config=cfg)
        b.build({c: df[c].tolist() for c in df.columns}, str(tmp_path))
        seg = load_segment(f"{tmp_path}/orders_d")
        assert seg.metadata.star_tree_count == 1
        tree = seg.star_trees[0]
        assert tree.has_pair("count", "*")
        assert tree.has_pair("sum", "revenue")
        assert tree.has_pair("sum", "units")


# ==========================================================================
# the device rung: node slices through the group-by kernels
# ==========================================================================

SSB_DIMS = ["d_year", "c_region", "s_region", "p_category", "p_brand1"]


def ssb_shaped_schema():
    D, M = FieldType.DIMENSION, FieldType.METRIC
    return Schema("lineorder_t", [
        FieldSpec("d_year", DataType.INT, D),
        FieldSpec("c_region", DataType.STRING, D),
        FieldSpec("s_region", DataType.STRING, D),
        FieldSpec("p_category", DataType.STRING, D),
        FieldSpec("p_brand1", DataType.STRING, D),
        FieldSpec("lo_quantity", DataType.INT, D),
        FieldSpec("lo_revenue", DataType.LONG, M),
        FieldSpec("lo_supplycost", DataType.LONG, M),
        FieldSpec("tags", DataType.LONG, single_value=False),
    ])


def ssb_shaped_frame(n, seed):
    rng = np.random.default_rng(seed)
    regions = np.array(["AFRICA", "AMERICA", "ASIA", "EUROPE"])
    cat_i = rng.integers(0, 5, n)
    brand_i = rng.integers(0, 4, n)
    return {
        "d_year": rng.integers(1992, 1999, n).astype(np.int64),
        "c_region": regions[rng.integers(0, 4, n)],
        "s_region": regions[rng.integers(0, 4, n)],
        "p_category": np.array([f"C{i}" for i in range(5)])[cat_i],
        "p_brand1": np.array([f"C{c}B{b}" for c in range(5)
                              for b in range(4)])[cat_i * 4 + brand_i],
        "lo_quantity": rng.integers(1, 50, n).astype(np.int64),
        "lo_revenue": rng.integers(100, 900_000, n).astype(np.int64),
        "lo_supplycost": rng.integers(50, 60_000, n).astype(np.int64),
        "tags": [list(rng.integers(0, 9, rng.integers(1, 4)))
                 for _ in range(n)],
    }


@pytest.fixture(scope="module")
def ssb_shaped(tmp_path_factory):
    """Two SSB-shaped segments with the full pre-agg pair set (sum/min/max
    revenue + sum supplycost + count, so avg/min/max queries are eligible
    too)."""
    out = str(tmp_path_factory.mktemp("st_dev"))
    cfg = IndexingConfig(star_tree_index_configs=[StarTreeIndexConfig(
        dimensions_split_order=list(SSB_DIMS),
        function_column_pairs=["COUNT__*", "SUM__lo_revenue",
                               "SUM__lo_supplycost", "MIN__lo_revenue",
                               "MAX__lo_revenue"],
        max_leaf_records=64)])
    segs = []
    for i in range(2):
        b = SegmentBuilder(ssb_shaped_schema(), f"lot_{i}",
                           indexing_config=cfg)
        b.build(ssb_shaped_frame(6000, seed=50 + i), out)
        segs.append(load_segment(f"{out}/lot_{i}"))
    assert all(s.metadata.star_tree_count == 1 for s in segs)
    return segs


@pytest.fixture(scope="module")
def device_exec():
    return ServerQueryExecutor()


@pytest.fixture(scope="module")
def host_exec():
    return ServerQueryExecutor(use_device=False)


def _run3(sql, segs, device_exec, host_exec):
    """(device rows+stats, device-scan rows, host rows) for one SQL."""
    got, stats = device_exec.execute(compile_query(sql), segs)
    scan_ctx = compile_query(sql)
    scan_ctx.options["useStarTree"] = "false"
    scan, _ = device_exec.execute(scan_ctx, segs)
    want, _ = host_exec.execute(compile_query(sql), segs)
    return got, stats, scan, want


def _assert_identical(name, a_rows, b_rows):
    """BIT-identical: pre-agg sums of integers in f64 are exact, so the
    star-tree rung owes the scan paths full equality, not approx."""
    assert len(a_rows) == len(b_rows), (name, len(a_rows), len(b_rows))
    for ar, br in zip(a_rows, b_rows):
        assert ar == br, (name, ar, br)


class TestStarTreeDeviceRung:
    AGGS = ["count(*)", "sum(lo_revenue)", "sum(lo_supplycost)",
            "min(lo_revenue)", "max(lo_revenue)", "avg(lo_revenue)"]

    def test_q2_shape_serves_from_device_nodes(self, ssb_shaped,
                                               device_exec, host_exec):
        sql = ("SELECT d_year, p_brand1, sum(lo_revenue) FROM lineorder_t "
               "WHERE p_category = 'C2' AND s_region = 'AMERICA' "
               "GROUP BY d_year, p_brand1 ORDER BY d_year, p_brand1 "
               "LIMIT 10000")
        got, stats, scan, want = _run3(sql, ssb_shaped, device_exec,
                                       host_exec)
        assert stats.group_by_rung == "startree_device"
        total = sum(s.num_docs for s in ssb_shaped)
        assert 0 < stats.num_docs_scanned < total / 10
        _assert_identical("q2-scan", got.rows, scan.rows)
        _assert_identical("q2-host", got.rows, want.rows)

    def test_parity_fuzz_eligible(self, ssb_shaped, device_exec, host_exec):
        """Randomized eligible queries: device star-tree rung vs full-scan
        device path vs host engine, bit-identical, rung recorded."""
        rng = np.random.default_rng(7)
        preds_pool = [
            "c_region = 'ASIA'",
            "s_region IN ('AMERICA', 'EUROPE')",
            "p_category = 'C1'",
            "p_brand1 BETWEEN 'C1B0' AND 'C3B2'",
            "d_year BETWEEN 1993 AND 1996",
            "d_year IN (1992, 1995, 1998)",
        ]
        for trial in range(20):
            gdims = list(rng.choice(SSB_DIMS, size=int(rng.integers(1, 4)),
                                    replace=False))
            aggs = list(rng.choice(self.AGGS,
                                   size=int(rng.integers(1, 4)),
                                   replace=False))
            preds = list(rng.choice(preds_pool,
                                    size=int(rng.integers(0, 3)),
                                    replace=False))
            sql = (f"SELECT {', '.join(gdims + aggs)} FROM lineorder_t "
                   + (f"WHERE {' AND '.join(preds)} " if preds else "")
                   + f"GROUP BY {', '.join(gdims)} "
                   + f"ORDER BY {', '.join(gdims)} LIMIT 100000")
            got, stats, scan, want = _run3(sql, ssb_shaped, device_exec,
                                           host_exec)
            assert stats.group_by_rung == "startree_device", (trial, sql)
            _assert_identical(f"fuzz{trial}-scan", got.rows, scan.rows)
            _assert_identical(f"fuzz{trial}-host", got.rows, want.rows)

    @pytest.mark.parametrize("sql,why", [
        ("SELECT d_year, sum(lo_revenue) FROM lineorder_t "
         "WHERE c_region = 'ASIA' OR s_region = 'ASIA' "
         "GROUP BY d_year ORDER BY d_year", "OR filter"),
        ("SELECT lo_quantity, sum(lo_revenue) FROM lineorder_t "
         "WHERE c_region = 'ASIA' GROUP BY lo_quantity "
         "ORDER BY lo_quantity LIMIT 100", "group-by off the split order"),
        ("SELECT d_year, summv(tags) FROM lineorder_t GROUP BY d_year "
         "ORDER BY d_year", "MV aggregation has no pre-agg pair"),
        ("SELECT d_year, sum(lo_quantity) FROM lineorder_t GROUP BY d_year "
         "ORDER BY d_year", "aggregation outside the pre-agg set"),
    ])
    def test_almost_eligible_falls_to_scan(self, ssb_shaped, device_exec,
                                           host_exec, sql, why):
        """Queries one rule short of eligibility must take the scan path —
        correct rung AND correct answers."""
        got, stats = device_exec.execute(compile_query(sql), ssb_shaped)
        assert stats.group_by_rung not in ("startree_device", "startree"), \
            (why, stats.group_by_rung)
        want, _ = host_exec.execute(compile_query(sql), ssb_shaped)
        _assert_identical(why, got.rows, want.rows)

    def test_scalar_aggregation_on_device_nodes(self, ssb_shaped,
                                                device_exec, host_exec):
        sql = ("SELECT count(*), sum(lo_revenue), avg(lo_revenue) "
               "FROM lineorder_t WHERE c_region = 'AMERICA'")
        got, stats, scan, want = _run3(sql, ssb_shaped, device_exec,
                                       host_exec)
        total = sum(s.num_docs for s in ssb_shaped)
        assert 0 < stats.num_docs_scanned < total / 10
        _assert_identical("scalar-scan", got.rows, scan.rows)
        _assert_identical("scalar-host", got.rows, want.rows)

    def test_empty_slice_matches_scan(self, ssb_shaped, device_exec,
                                      host_exec):
        sql = ("SELECT d_year, sum(lo_revenue) FROM lineorder_t "
               "WHERE c_region = 'AMERICA' AND c_region = 'ASIA' "
               "GROUP BY d_year ORDER BY d_year")
        got, stats, scan, want = _run3(sql, ssb_shaped, device_exec,
                                       host_exec)
        _assert_identical("empty-scan", got.rows, scan.rows)
        _assert_identical("empty-host", got.rows, want.rows)
        assert got.rows == []


class TestCapSafeRange:
    def test_range_over_cap_declines_to_slice(self, ssb_shaped, device_exec,
                                              host_exec, monkeypatch):
        """A RANGE whose dictId set would exceed _MAX_RANGE_IDS must ride a
        contiguous DictIdRange slice check — still the star-tree rung, same
        answers — instead of bailing to the full scan."""
        from pinot_tpu.engine import startree_exec

        monkeypatch.setattr(startree_exec, "_MAX_RANGE_IDS", 4)
        sql = ("SELECT d_year, p_brand1, sum(lo_revenue) FROM lineorder_t "
               "WHERE p_brand1 BETWEEN 'C0B0' AND 'C2B3' "  # 12 dictIds > 4
               "GROUP BY d_year, p_brand1 ORDER BY d_year, p_brand1 "
               "LIMIT 100000")
        got, stats, scan, want = _run3(sql, ssb_shaped, device_exec,
                                       host_exec)
        assert stats.group_by_rung == "startree_device"
        _assert_identical("cap-scan", got.rows, scan.rows)
        _assert_identical("cap-host", got.rows, want.rows)

    def test_range_at_cap_boundary_stays_set(self, ssb_shaped, device_exec,
                                             host_exec, monkeypatch):
        from pinot_tpu.engine import startree_exec
        from pinot_tpu.query.expressions import Identifier, Predicate, PredicateType

        monkeypatch.setattr(startree_exec, "_MAX_RANGE_IDS", 4)
        seg = ssb_shaped[0]
        # exactly at the cap: still a set
        p = Predicate(PredicateType.RANGE, Identifier("p_brand1"),
                      lower="C0B0", upper="C0B3",
                      lower_inclusive=True, upper_inclusive=True)
        m = startree_exec._matching_ids(seg, p)
        assert isinstance(m, set) and len(m) == 4
        # one past the cap: the contiguous slice representation
        p2 = Predicate(PredicateType.RANGE, Identifier("p_brand1"),
                       lower="C0B0", upper="C1B0",
                       lower_inclusive=True, upper_inclusive=True)
        m2 = startree_exec._matching_ids(seg, p2)
        assert isinstance(m2, DictIdRange) and len(m2) == 5

    def test_noncontiguous_over_cap_falls_to_scan(self, ssb_shaped,
                                                  device_exec, host_exec,
                                                  monkeypatch):
        from pinot_tpu.engine import startree_exec

        monkeypatch.setattr(startree_exec, "_MAX_RANGE_IDS", 4)
        # NOT_IN materializes card-1 non-contiguous ids > cap -> scan path
        sql = ("SELECT d_year, sum(lo_revenue) FROM lineorder_t "
               "WHERE p_brand1 NOT IN ('C2B1') GROUP BY d_year "
               "ORDER BY d_year")
        got, stats = device_exec.execute(compile_query(sql), ssb_shaped)
        assert stats.group_by_rung not in ("startree_device", "startree")
        want, _ = host_exec.execute(compile_query(sql), ssb_shaped)
        _assert_identical("notin", got.rows, want.rows)

    def test_select_records_range_equals_set(self, ssb_shaped):
        tree = ssb_shaped[0].star_trees[0]
        as_range = tree.select_records({"p_brand1": DictIdRange(3, 9)},
                                       ["d_year"])
        as_set = tree.select_records({"p_brand1": set(range(3, 10))},
                                     ["d_year"])
        np.testing.assert_array_equal(np.sort(as_range), np.sort(as_set))


class TestNodeArrayResidency:
    def test_nodes_in_memory_accounting_and_evictable(self, ssb_shaped):
        """Acceptance: node arrays appear in /debug/memory byte accounting
        and are evictable under budget pressure."""
        ex = ServerQueryExecutor()
        sql = ("SELECT d_year, sum(lo_revenue) FROM lineorder_t "
               "WHERE p_category = 'C1' GROUP BY d_year ORDER BY d_year")
        _, stats = ex.execute(compile_query(sql), ssb_shaped)
        assert stats.group_by_rung == "startree_device"

        snap = ex.residency.snapshot()
        staged = snap["stagedSegments"]
        assert staged, "star-tree query staged nothing"
        assert all(d["startrees"] >= 1 for d in staged.values()), staged
        assert snap["stagedBytes"] > 0
        # node bytes are part of the resident's accounting: releasing the
        # trees must shrink nbytes
        name = next(iter(staged))
        resident = ex.residency._entries[name].resident
        with_nodes = resident.nbytes()
        node_bytes = sum(int(a.nbytes) for t in resident._startree.values()
                         for a in t.values())
        assert node_bytes > 0
        assert with_nodes >= node_bytes

        # budget pressure: unpinned residents (trees included) evict
        ex.residency.set_budget_bytes(1)
        assert ex.residency.resident_count() == 0
        assert resident._startree == {}
        # and the rung recovers after eviction (restage on demand)
        ex.residency.set_budget_bytes(0)  # uncapped
        _, stats2 = ex.execute(compile_query(sql), ssb_shaped)
        assert stats2.group_by_rung == "startree_device"

    def test_spilled_query_uses_host_walker(self, ssb_shaped, host_exec):
        """Admission spill (device not allowed) must still serve star-tree
        queries — through the host walker, host-identical."""
        ex = ServerQueryExecutor(hbm_budget_bytes=1)
        sql = ("SELECT d_year, sum(lo_revenue) FROM lineorder_t "
               "WHERE p_category = 'C1' GROUP BY d_year ORDER BY d_year")
        got, stats = ex.execute(compile_query(sql), ssb_shaped)
        assert stats.group_by_rung == "startree"
        assert stats.staging.get("spills") == 1
        want, _ = host_exec.execute(compile_query(sql), ssb_shaped)
        _assert_identical("spill", got.rows, want.rows)


class TestShardedStarTree:
    def test_sharded_executor_rides_device_rung(self, ssb_shaped,
                                                host_exec):
        """The sharded combine routes star-tree-fit queries to the
        per-segment path: each segment's node slice through the device
        kernels, partials merged by GroupByResult (the CombineOperator
        analogue) — coalescing machinery untouched."""
        from pinot_tpu.parallel import ShardedQueryExecutor

        ex = ShardedQueryExecutor()
        sql = ("SELECT d_year, p_brand1, sum(lo_revenue), count(*) "
               "FROM lineorder_t WHERE s_region = 'EUROPE' "
               "GROUP BY d_year, p_brand1 ORDER BY d_year, p_brand1 "
               "LIMIT 100000")
        got, stats = ex.execute(compile_query(sql), ssb_shaped)
        assert stats.group_by_rung == "startree_device"
        assert stats.num_segments_processed == len(ssb_shaped)
        want, _ = host_exec.execute(compile_query(sql), ssb_shaped)
        _assert_identical("sharded", got.rows, want.rows)


# ==========================================================================
# PR-13: expression pre-agg pairs, multi-tree selection, lexsort build
# ==========================================================================


@pytest.fixture(scope="module")
def expr_shaped(tmp_path_factory):
    """Two segments whose tree carries DERIVED expression pairs
    (ref: StarTreeV2 derived-column function-column pairs)."""
    out = str(tmp_path_factory.mktemp("st_expr"))
    cfg = IndexingConfig(star_tree_index_configs=[StarTreeIndexConfig(
        dimensions_split_order=["d_year", "c_region", "lo_quantity"],
        function_column_pairs=["COUNT__*", "SUM__lo_revenue",
                               "SUM__lo_revenue*lo_quantity",
                               "SUM__lo_revenue-lo_supplycost"],
        max_leaf_records=64)])
    segs = []
    for i in range(2):
        b = SegmentBuilder(ssb_shaped_schema(), f"loe_{i}",
                           indexing_config=cfg)
        b.build(ssb_shaped_frame(6000, seed=90 + i), out)
        segs.append(load_segment(f"{out}/loe_{i}"))
    assert all(s.metadata.star_tree_count == 1 for s in segs)
    return segs


class TestExpressionPairs:
    """Tentpole (a): sum/avg over +/-/* expressions serve from derived
    pre-agg pairs, bit-identical to both scan paths."""

    EXPR_AGGS = ["sum(lo_revenue * lo_quantity)",
                 "sum(lo_quantity * lo_revenue)",   # commutative canon
                 "sum(lo_revenue - lo_supplycost)",
                 "avg(lo_revenue * lo_quantity)",
                 "count(*)"]

    def test_parity_fuzz_expression_pairs(self, expr_shaped, device_exec,
                                          host_exec):
        rng = np.random.default_rng(23)
        gpool = ["d_year", "c_region", "lo_quantity"]
        ppool = ["c_region = 'ASIA'", "d_year BETWEEN 1993 AND 1996",
                 "lo_quantity < 25", "d_year IN (1992, 1995)"]
        for trial in range(12):
            gdims = list(rng.choice(gpool, size=int(rng.integers(0, 3)),
                                    replace=False))
            aggs = list(rng.choice(self.EXPR_AGGS,
                                   size=int(rng.integers(1, 4)),
                                   replace=False))
            preds = list(rng.choice(ppool, size=int(rng.integers(0, 3)),
                                    replace=False))
            sql = (f"SELECT {', '.join(gdims + aggs)} FROM lineorder_t "
                   + (f"WHERE {' AND '.join(preds)} " if preds else "")
                   + (f"GROUP BY {', '.join(gdims)} "
                      f"ORDER BY {', '.join(gdims)} " if gdims else "")
                   + "LIMIT 100000")
            got, stats, scan, want = _run3(sql, expr_shaped, device_exec,
                                           host_exec)
            if gdims:
                assert stats.group_by_rung == "startree_device", (trial, sql)
            else:
                assert stats.startree_tree_index == 0, (trial, sql)
            _assert_identical(f"expr{trial}-scan", got.rows, scan.rows)
            _assert_identical(f"expr{trial}-host", got.rows, want.rows)

    def test_almost_eligible_expression_declines(self, expr_shaped,
                                                 device_exec, host_exec):
        """sum(a*b + c): a valid arithmetic shape whose derived pair is
        NOT stored — must decline with the expression reason and still
        answer correctly from the scan."""
        sql = ("SELECT d_year, sum(lo_revenue * lo_quantity + lo_supplycost) "
               "FROM lineorder_t GROUP BY d_year ORDER BY d_year")
        got, stats = device_exec.execute(compile_query(sql), expr_shaped)
        assert stats.group_by_rung not in ("startree_device", "startree")
        assert any("startree_expression_agg_no_pair" in k
                   for k in stats.decisions), stats.decisions
        want, _ = host_exec.execute(compile_query(sql), expr_shaped)
        _assert_identical("almost", got.rows, want.rows)

    def test_division_never_pairs(self, expr_shaped, device_exec):
        """sum(a/b) is outside the pre-aggregable subset (float division
        breaks the exact-integer pre-agg contract) — scan serves."""
        sql = ("SELECT sum(lo_revenue / lo_quantity) FROM lineorder_t "
               "WHERE c_region = 'ASIA'")
        _, stats = device_exec.execute(compile_query(sql), expr_shaped)
        assert stats.startree_tree_index is None
        assert any("startree_expression_agg_no_pair" in k
                   for k in stats.decisions), stats.decisions


class TestMultiTreeSelection:
    """Tentpole (b): every fitting tree scored by estimated records-read;
    cheapest wins, index breaks ties."""

    def _segment(self, tmp_path, configs, name="orders_mt"):
        df = make_df(1200, seed=21)
        cfg = IndexingConfig(star_tree_index_configs=configs)
        b = SegmentBuilder(make_schema(), name, indexing_config=cfg)
        b.build({c: df[c].tolist() for c in df.columns}, str(tmp_path))
        return load_segment(f"{tmp_path}/{name}")

    def test_cheapest_tree_wins(self, tmp_path):
        """Tree 0 skips star creation on its leading (free) dim, so a
        category-filtered scalar query costs card(country) there; tree 1
        answers it from one record slice — the pick must take tree 1."""
        seg = self._segment(tmp_path, [
            StarTreeIndexConfig(
                dimensions_split_order=["country", "category"],
                skip_star_node_creation_for_dimensions=["country"],
                function_column_pairs=["COUNT__*", "SUM__revenue"],
                max_leaf_records=4),
            StarTreeIndexConfig(
                dimensions_split_order=["category"],
                function_column_pairs=["COUNT__*", "SUM__revenue"],
                max_leaf_records=4),
        ])
        assert seg.metadata.star_tree_count == 2
        ctx = compile_query(
            "SELECT sum(revenue) FROM orders WHERE category = 'k3'")
        aggs = [resolve_agg(f) for f in ctx.aggregations]
        pick = pick_star_tree(ctx, aggs, seg)
        assert pick is not None and pick.index == 1

    def test_tie_breaks_on_lower_index(self, tmp_path):
        """Two trees scoring identically: the configured order pins the
        winner (index 0) — deterministic plans across restarts."""
        twice = [StarTreeIndexConfig(
            dimensions_split_order=["country", "category"],
            function_column_pairs=["COUNT__*", "SUM__revenue"],
            max_leaf_records=4)] * 2
        seg = self._segment(tmp_path, twice, name="orders_tie")
        assert seg.metadata.star_tree_count == 2
        ctx = compile_query(
            "SELECT sum(revenue) FROM orders WHERE country = 'c1'")
        aggs = [resolve_agg(f) for f in ctx.aggregations]
        pick = pick_star_tree(ctx, aggs, seg)
        assert pick is not None and pick.index == 0

    def test_selection_rides_ledger_and_stats(self, tmp_path):
        seg = self._segment(tmp_path, [
            StarTreeIndexConfig(
                dimensions_split_order=["country"],
                function_column_pairs=["COUNT__*"],
                max_leaf_records=4),
            StarTreeIndexConfig(
                dimensions_split_order=["category", "channel"],
                function_column_pairs=["COUNT__*", "SUM__revenue"],
                max_leaf_records=4),
        ], name="orders_led")
        ex = ServerQueryExecutor(use_device=False)
        _, stats = ex.execute(compile_query(
            "SELECT channel, sum(revenue) FROM orders "
            "GROUP BY channel ORDER BY channel"), [seg])
        assert stats.startree_tree_index == 1
        assert stats.decisions.get("startree:scan->startree:tree1") == 1

    def test_most_specific_decline_reason_across_trees(self, tmp_path):
        """Satellite: a tree failing on missing_function_pair (one config
        line from serving) must out-report a sibling failing on
        group_off_split_order — in EITHER tree order."""
        a = StarTreeIndexConfig(
            dimensions_split_order=["country"],
            function_column_pairs=["COUNT__*"], max_leaf_records=4)
        b = StarTreeIndexConfig(
            dimensions_split_order=["country", "category"],
            function_column_pairs=["COUNT__*"], max_leaf_records=4)
        for name, configs in (("mt_ab", [a, b]), ("mt_ba", [b, a])):
            seg = self._segment(tmp_path, configs, name=name)
            ctx = compile_query(
                "SELECT category, sum(revenue) FROM orders "
                "GROUP BY category ORDER BY category")
            aggs = [resolve_agg(f) for f in ctx.aggregations]
            reasons = []
            assert pick_star_tree(ctx, aggs, seg,
                                  on_decline=reasons.append) is None
            # tree [country] fails the group check; tree [country,
            # category] fits the shape but lacks SUM__revenue — the
            # more-specific reason wins regardless of order
            assert reasons == ["startree_missing_function_pair"], (name,
                                                                   reasons)


class TestLexsortBuildEquality:
    """Tentpole (c): the vectorized builder must emit byte-identical
    arrays to the recursive oracle on the existing fixtures."""

    @pytest.mark.parametrize("max_leaf,skip", [
        (10_000, []), (16, []), (1, []), (64, ["country"]),
        (8, ["category", "channel"]),
    ])
    def test_node_arrays_identical(self, max_leaf, skip):
        df = make_df(N, seed=3)
        cfg = StarTreeConfig(
            ["country", "category", "channel"],
            [("count", "*"), ("sum", "revenue"), ("min", "revenue"),
             ("max", "revenue"), ("sum", "units")],
            max_leaf_records=max_leaf, skip_star_creation=skip)
        dims = {
            "country": pd.Categorical(df.country).codes.astype(np.int32),
            "category": pd.Categorical(df.category).codes.astype(np.int32),
            "channel": pd.Categorical(df.channel).codes.astype(np.int32),
        }
        mets = {"revenue": df.revenue.to_numpy(),
                "units": df.units.to_numpy()}
        rec = StarTreeBuilder(cfg).build(dict(dims), dict(mets), len(df),
                                         engine="recursive")
        vec = StarTreeBuilder(cfg).build(dict(dims), dict(mets), len(df))
        np.testing.assert_array_equal(rec.dims, vec.dims)
        np.testing.assert_array_equal(rec.nodes, vec.nodes)
        assert set(rec.metrics) == set(vec.metrics)
        for k in rec.metrics:
            np.testing.assert_array_equal(rec.metrics[k], vec.metrics[k],
                                          err_msg=k)

    def test_derived_pair_equality_and_values(self):
        df = make_df(800, seed=31)
        cfg = StarTreeConfig(
            ["country"], [("count", "*"), ("sum", "(revenue*units)")],
            max_leaf_records=8)
        dims = {"country": pd.Categorical(df.country).codes.astype(np.int32)}
        mets = {"revenue": df.revenue.to_numpy(),
                "units": df.units.to_numpy()}
        rec = StarTreeBuilder(cfg).build(dict(dims), dict(mets), len(df),
                                         engine="recursive")
        vec = StarTreeBuilder(cfg).build(dict(dims), dict(mets), len(df))
        np.testing.assert_array_equal(rec.dims, vec.dims)
        np.testing.assert_array_equal(rec.metrics["sum__(revenue*units)"],
                                      vec.metrics["sum__(revenue*units)"])
        idx = vec.select_records({}, [])
        got = float(np.asarray(vec.metrics["sum__(revenue*units)"])[idx].sum())
        assert got == pytest.approx(float((df.revenue * df.units).sum()))


class TestPerTreeResidency:
    def test_release_one_tree_keeps_sibling(self, tmp_path):
        """Satellite: per-tree residency — evicting one tree must not drop
        its sibling, and the accounting must move by exactly the released
        tree's bytes."""
        df = make_df(1500, seed=41)
        cfg = IndexingConfig(star_tree_index_configs=[
            StarTreeIndexConfig(
                dimensions_split_order=["country", "category"],
                function_column_pairs=["COUNT__*", "SUM__revenue"],
                max_leaf_records=16),
            StarTreeIndexConfig(
                dimensions_split_order=["channel"],
                function_column_pairs=["COUNT__*", "SUM__units"],
                max_leaf_records=16),
        ])
        b = SegmentBuilder(make_schema(), "orders_rt", indexing_config=cfg)
        b.build({c: df[c].tolist() for c in df.columns}, str(tmp_path))
        seg = load_segment(f"{tmp_path}/orders_rt")
        ex = ServerQueryExecutor()
        # stage both trees through real queries
        _, s1 = ex.execute(compile_query(
            "SELECT country, sum(revenue) FROM orders "
            "GROUP BY country ORDER BY country"), [seg])
        _, s2 = ex.execute(compile_query(
            "SELECT channel, sum(units) FROM orders "
            "GROUP BY channel ORDER BY channel"), [seg])
        assert s1.startree_tree_index == 0
        assert s2.startree_tree_index == 1
        name = seg.segment_name
        resident = ex.residency._entries[name].resident
        per_tree = resident.startree_nbytes()
        assert set(per_tree) == {0, 1} and all(v > 0
                                               for v in per_tree.values())
        before = resident.nbytes()
        snap = ex.residency.snapshot()["stagedSegments"][name]
        assert snap["startrees"] == 2
        assert set(snap["startreeBytes"]) == {"0", "1"}

        assert ex.residency.release_startree(name, 0)
        assert set(resident.startree_nbytes()) == {1}  # sibling intact
        assert resident.nbytes() == before - per_tree[0]
        snap = ex.residency.snapshot()["stagedSegments"][name]
        assert snap["startrees"] == 1
        assert snap["startreeBytes"] == {"1": per_tree[1]}
        # double release is a no-op; unknown resident refuses
        assert not ex.residency.release_startree(name, 0)
        assert not ex.residency.release_startree("nope", 0)
        # the evicted tree restages on demand, same answers
        got, s3 = ex.execute(compile_query(
            "SELECT country, sum(revenue) FROM orders "
            "GROUP BY country ORDER BY country"), [seg])
        assert s3.startree_tree_index == 0
        assert set(resident.startree_nbytes()) == {0, 1}


# (The star-tree reason-registry conformance test moved to
# tests/test_reasons.py: ONE generic harness parameterized over
# tracing.reason_registry() replaced the per-module scans.)
