"""Star-tree index: build/load round-trip and the reference's core parity
strategy — star-tree answers must equal non-star-tree answers on the same
data (ref: StarTreeClusterIntegrationTest)."""

import numpy as np
import pandas as pd
import pytest

from pinot_tpu.engine import ServerQueryExecutor
from pinot_tpu.engine.aggregates import resolve_agg
from pinot_tpu.engine.startree_exec import pick_star_tree
from pinot_tpu.query import compile_query
from pinot_tpu.segment import SegmentBuilder, load_segment
from pinot_tpu.segment.startree import STAR, StarTree, StarTreeBuilder, StarTreeConfig
from pinot_tpu.spi import DataType, FieldSpec, FieldType, Schema
from pinot_tpu.spi.table import IndexingConfig, StarTreeIndexConfig

N = 4000


def make_schema():
    return Schema("orders", [
        FieldSpec("country", DataType.STRING),
        FieldSpec("category", DataType.STRING),
        FieldSpec("channel", DataType.STRING),
        FieldSpec("revenue", DataType.DOUBLE, FieldType.METRIC),
        FieldSpec("units", DataType.LONG, FieldType.METRIC),
    ])


def make_df(n=N, seed=3):
    rng = np.random.default_rng(seed)
    return pd.DataFrame({
        "country": [f"c{i}" for i in rng.integers(0, 12, n)],
        "category": [f"k{i}" for i in rng.integers(0, 8, n)],
        "channel": [["web", "store", "app"][i] for i in rng.integers(0, 3, n)],
        "revenue": np.round(rng.gamma(2.0, 50.0, n), 2),
        "units": rng.integers(1, 20, n).astype(np.int64),
    })


@pytest.fixture(scope="module", params=[10_000, 16], ids=["fat-leaves", "deep-split"])
def seg_with_tree(request, tmp_path_factory):
    out = str(tmp_path_factory.mktemp("st"))
    df = make_df()
    cfg = IndexingConfig(star_tree_index_configs=[StarTreeIndexConfig(
        dimensions_split_order=["country", "category", "channel"],
        function_column_pairs=["COUNT__*", "SUM__revenue", "MAX__revenue",
                               "MIN__revenue", "SUM__units"],
        max_leaf_records=request.param)])
    b = SegmentBuilder(make_schema(), "orders_0", indexing_config=cfg)
    b.build({c: df[c].tolist() for c in df.columns}, out)
    seg = load_segment(f"{out}/orders_0")
    assert seg.metadata.star_tree_count == 1
    assert len(seg.star_trees) == 1
    return seg, df


PARITY_QUERIES = [
    "SELECT count(*), sum(revenue) FROM orders",
    "SELECT sum(revenue), sum(units) FROM orders WHERE country = 'c3'",
    "SELECT min(revenue), max(revenue) FROM orders WHERE category IN ('k1','k2')",
    "SELECT country, sum(revenue), count(*) FROM orders GROUP BY country "
    "ORDER BY country LIMIT 50",
    "SELECT country, category, sum(units) FROM orders WHERE channel = 'web' "
    "GROUP BY country, category ORDER BY country, category LIMIT 200",
    "SELECT category, avg(revenue) FROM orders GROUP BY category "
    "ORDER BY category LIMIT 50",
    "SELECT channel, max(revenue) FROM orders WHERE country != 'c0' "
    "GROUP BY channel ORDER BY channel LIMIT 50",
]


class TestStarTreeParity:
    @pytest.mark.parametrize("sql", PARITY_QUERIES)
    def test_star_tree_matches_scan(self, seg_with_tree, sql):
        """The reference's StarTreeClusterIntegrationTest invariant."""
        seg, _ = seg_with_tree
        ex = ServerQueryExecutor(use_device=False)
        ctx = compile_query(sql)
        aggs = [resolve_agg(f) for f in ctx.aggregations]
        assert pick_star_tree(ctx, aggs, seg) is not None, "tree must fit"

        with_tree, stats_tree = ex.execute(ctx, [seg])
        ctx2 = compile_query(sql)
        ctx2.options["useStarTree"] = "false"
        without, _ = ex.execute(ctx2, [seg])
        assert len(with_tree.rows) == len(without.rows)
        for a, b in zip(with_tree.rows, without.rows):
            for x, y in zip(a, b):
                if isinstance(y, float):
                    assert x == pytest.approx(y, rel=1e-9)
                else:
                    assert x == y

    def test_tree_scans_fewer_records(self, seg_with_tree):
        seg, _ = seg_with_tree
        ex = ServerQueryExecutor(use_device=False)
        ctx = compile_query("SELECT sum(revenue) FROM orders")
        _, stats = ex.execute(ctx, [seg])
        # filter-less total should touch far fewer pre-agg records than docs
        assert 0 < stats.num_docs_scanned < N / 2

    def test_unfit_queries_fall_through(self, seg_with_tree):
        seg, _ = seg_with_tree
        ex = ServerQueryExecutor(use_device=False)
        # revenue (a metric, not a dim) in the filter -> not fit, still correct
        t, _ = ex.execute(compile_query(
            "SELECT count(*) FROM orders WHERE revenue > 100"), [seg])
        ctx = compile_query("SELECT count(*) FROM orders WHERE revenue > 100")
        aggs = [resolve_agg(f) for f in ctx.aggregations]
        assert pick_star_tree(ctx, aggs, seg) is None
        assert t.rows[0][0] > 0


class TestStarTreeBuilder:
    def test_save_load_round_trip(self, tmp_path):
        df = make_df(500, seed=9)
        cfg = StarTreeConfig(["country", "category"],
                             [("count", "*"), ("sum", "revenue")],
                             max_leaf_records=8)
        # dictIds: factorize in sorted order like the segment dictionaries
        c_codes = pd.Categorical(df.country).codes.astype(np.int32)
        k_codes = pd.Categorical(df.category).codes.astype(np.int32)
        tree = StarTreeBuilder(cfg).build(
            {"country": c_codes, "category": k_codes},
            {"revenue": df.revenue.to_numpy()}, len(df))
        tree.save(str(tmp_path))
        loaded = StarTree.load(str(tmp_path))
        assert loaded is not None
        assert loaded.num_records == tree.num_records
        np.testing.assert_array_equal(np.asarray(loaded.dims),
                                      np.asarray(tree.dims))

        # filter-less total via traversal (star path / un-split leaves)
        idx = loaded.select_records({}, [])
        assert np.asarray(loaded.metrics["count__*"])[idx].sum() == len(df)

    def test_skip_star_creation(self):
        df = make_df(300, seed=11)
        c = pd.Categorical(df.country).codes.astype(np.int32)
        k = pd.Categorical(df.category).codes.astype(np.int32)
        cfg = StarTreeConfig(["country", "category"], [("count", "*")],
                             max_leaf_records=1,
                             skip_star_creation=["country"])
        tree = StarTreeBuilder(cfg).build({"country": c, "category": k}, {},
                                          len(df))
        # no record may have STAR at the skipped dimension
        assert not np.any(np.asarray(tree.dims)[:, 0] == STAR)
        # grouping by category still answers correctly via concrete rows
        idx = tree.select_records({}, ["category"])
        got = {}
        cats = np.asarray(tree.dims)[idx, 1]
        cnts = np.asarray(tree.metrics["count__*"])[idx]
        for cat, n in zip(cats, cnts):
            got[cat] = got.get(cat, 0) + int(n)
        want = df.groupby(k).size().to_dict()
        assert got == want

    def test_default_star_tree(self, tmp_path):
        df = make_df(400, seed=13)
        cfg = IndexingConfig(enable_default_star_tree=True)
        b = SegmentBuilder(make_schema(), "orders_d", indexing_config=cfg)
        b.build({c: df[c].tolist() for c in df.columns}, str(tmp_path))
        seg = load_segment(f"{tmp_path}/orders_d")
        assert seg.metadata.star_tree_count == 1
        tree = seg.star_trees[0]
        assert tree.has_pair("count", "*")
        assert tree.has_pair("sum", "revenue")
        assert tree.has_pair("sum", "units")
