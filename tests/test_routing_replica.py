"""Replica-group instance selection + broker partition pruning.

Ref: routing/instanceselector/ReplicaGroupInstanceSelector.java,
StrictReplicaGroupInstanceSelector.java,
routing/segmentpruner/PartitionSegmentPruner.java.
"""

import numpy as np
import pytest

from pinot_tpu.broker.routing import (
    ReplicaGroupInstanceSelector,
    StrictReplicaGroupInstanceSelector,
)
from pinot_tpu.query import compile_query
from pinot_tpu.spi import DataType, FieldSpec, FieldType, Schema
from pinot_tpu.spi.table import (
    IndexingConfig,
    RoutingConfig,
    SegmentPartitionConfig,
    TableConfig,
)
from pinot_tpu.tools.cluster import EmbeddedCluster

GROUPS = [["s0", "s1"], ["s2", "s3"]]


class TestSelectors:
    def test_replica_group_picks_one_group(self):
        sel = ReplicaGroupInstanceSelector(GROUPS)
        replicas = ["s0", "s2"]  # one replica in each group
        a = sel.select("seg", replicas, request_id=0, excluded=frozenset())
        b = sel.select("seg", replicas, request_id=1, excluded=frozenset())
        assert {a, b} == {"s0", "s2"}  # rotates groups by requestId

    def test_replica_group_falls_back_across_groups(self):
        sel = ReplicaGroupInstanceSelector(GROUPS)
        # picked group 0 has no live replica -> falls to group 1
        got = sel.select("seg", ["s0", "s2"], request_id=0,
                         excluded=frozenset({"s0"}))
        assert got == "s2"

    def test_strict_no_cross_group_fallback(self):
        sel = StrictReplicaGroupInstanceSelector(GROUPS)
        got = sel.select("seg", ["s0", "s2"], request_id=0,
                         excluded=frozenset({"s0"}))
        assert got is None  # strict: group 0 picked, cannot serve
        got = sel.select("seg", ["s0", "s2"], request_id=1,
                         excluded=frozenset({"s0"}))
        assert got == "s2"  # group 1 picked, serves fine


def _schema():
    return Schema("rg", [
        FieldSpec("city", DataType.STRING),
        FieldSpec("v", DataType.LONG, FieldType.METRIC)])


@pytest.fixture()
def rg_cluster(tmp_path):
    c = EmbeddedCluster(num_servers=4, data_dir=str(tmp_path / "c"))
    cfg = TableConfig("rg", routing_config=RoutingConfig(
        instance_selector_type="replicaGroup"))
    cfg.validation_config.replication = 2
    c.create_table(cfg, _schema())
    rng = np.random.default_rng(1)
    for i in range(4):
        c.ingest_rows("rg_OFFLINE", _schema(), {
            "city": np.array(["sf", "nyc"])[rng.integers(0, 2, 500)],
            "v": rng.integers(0, 9, 500).astype(np.int64),
        }, segment_name=f"rg_{i}")
    assert c.wait_for_ev_converged("rg_OFFLINE")
    yield c
    c.shutdown()


class TestReplicaGroupRouting:
    def test_instance_partitions_persisted(self, rg_cluster):
        groups = rg_cluster.store.get_instance_partitions("rg_OFFLINE")
        assert groups is not None and len(groups) == 2
        assert sorted(sum(groups, [])) == sorted(rg_cluster.servers)

    def test_one_group_serves_each_query(self, rg_cluster):
        groups = [set(g) for g in
                  rg_cluster.store.get_instance_partitions("rg_OFFLINE")]
        rm = rg_cluster.broker.routing
        ctx = compile_query("SELECT count(*) FROM rg")
        for rid in range(6):
            routing, unavailable = rm.get_routing_table(
                "rg_OFFLINE", ctx, request_id=rid)
            assert not unavailable
            used = set(routing.keys())
            # all chosen servers live in ONE replica group
            assert any(used <= g for g in groups), (used, groups)
            # and the group covers all 4 segments
            assert sorted(sum(routing.values(), [])) == \
                [f"rg_{i}" for i in range(4)]

    def test_queries_answer_correctly(self, rg_cluster):
        rows = rg_cluster.query_rows("SELECT count(*) FROM rg")
        assert rows[0][0] == 2000


class TestBrokerPartitionPruning:
    def test_partitioned_segments_prune_at_broker(self, tmp_path):
        c = EmbeddedCluster(num_servers=1, data_dir=str(tmp_path / "c"))
        part_cfg = IndexingConfig(
            segment_partition_config=SegmentPartitionConfig(
                {"city": {"functionName": "Murmur", "numPartitions": 4}}))
        cfg = TableConfig("pp", indexing_config=part_cfg,
                          routing_config=RoutingConfig(
                              segment_pruner_types=["partition"]))
        schema = Schema("pp", [
            FieldSpec("city", DataType.STRING),
            FieldSpec("v", DataType.LONG, FieldType.METRIC)])
        c.create_table(cfg, schema)
        try:
            from pinot_tpu.utils.partition import get_partition_function

            fn = get_partition_function("Murmur", 4)
            by_part = {}
            for v in (f"city{i}" for i in range(60)):
                by_part.setdefault(fn.partition(v), []).append(v)
            parts = sorted(by_part)[:2]
            for p in parts:
                vals = by_part[p]
                c.ingest_rows("pp_OFFLINE", schema, {
                    "city": np.array(vals),
                    "v": np.ones(len(vals), dtype=np.int64),
                }, segment_name=f"pp_{p}")
            assert c.wait_for_ev_converged("pp_OFFLINE")

            probe = by_part[parts[0]][0]
            rm = c.broker.routing
            ctx = compile_query(
                f"SELECT count(*) FROM pp WHERE city = '{probe}'")
            routing, _ = rm.get_routing_table("pp_OFFLINE", ctx)
            routed = sum(routing.values(), [])
            assert routed == [f"pp_{parts[0]}"]  # other partition pruned

            rows = c.query_rows(
                f"SELECT count(*) FROM pp WHERE city = '{probe}'")
            assert rows[0][0] == 1
        finally:
            c.shutdown()
