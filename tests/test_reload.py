"""Segment reload: add newly-configured indexes in place.

Ref: SegmentPreProcessor.java + loader/* IndexHandlers + the reload
message path (PinotSegmentRestletResource.reloadAllSegments).
"""

import json

import numpy as np
import pytest

from pinot_tpu.segment import SegmentBuilder, load_segment
from pinot_tpu.segment.preprocessor import preprocess_segment
from pinot_tpu.spi import DataType, FieldSpec, FieldType, Schema
from pinot_tpu.spi.table import IndexingConfig, TableConfig
from pinot_tpu.tools.cluster import EmbeddedCluster


def _schema():
    return Schema("rl", [
        FieldSpec("city", DataType.STRING),
        FieldSpec("doc", DataType.JSON),
        FieldSpec("amt", DataType.LONG, FieldType.METRIC),
        FieldSpec("v", DataType.LONG, FieldType.METRIC),
    ])


def _build(tmp_path, indexing=None, name="rl_0"):
    rng = np.random.default_rng(2)
    n = 3000
    b = SegmentBuilder(_schema(), name,
                       indexing_config=indexing or IndexingConfig(
                           no_dictionary_columns=["amt"]))
    b.build({
        "city": np.array(["sf", "nyc", "sea"])[rng.integers(0, 3, n)],
        "doc": np.array([json.dumps({"t": f"tag{i % 7}"})
                         for i in range(n)]),
        "amt": rng.integers(0, 10_000, n).astype(np.int64),
        "v": np.ones(n, dtype=np.int64),
    }, str(tmp_path))
    return f"{tmp_path}/{name}"


class TestPreprocessor:
    def test_adds_all_missing_index_kinds(self, tmp_path):
        seg_dir = _build(tmp_path)
        seg = load_segment(seg_dir)
        assert not seg.metadata.column("city").has_inverted_index
        old_crc = seg.metadata.crc

        added = preprocess_segment(seg_dir, IndexingConfig(
            inverted_index_columns=["city"],
            bloom_filter_columns=["city"],
            text_index_columns=["city"],
            json_index_columns=["doc"],
            range_index_columns=["amt"],
            no_dictionary_columns=["amt"]))
        assert sorted(added) == ["amt:range", "city:bloom", "city:inverted",
                                 "city:text", "doc:json"]
        seg2 = load_segment(seg_dir)
        cm = seg2.metadata.column("city")
        assert cm.has_inverted_index and cm.has_bloom_filter \
            and cm.has_text_index
        assert seg2.metadata.column("doc").has_json_index
        assert seg2.metadata.column("amt").has_range_index
        assert seg2.metadata.crc != old_crc
        # the added indexes actually serve reads
        assert len(seg2.data_source("city").doc_ids_for_dict_id(0)) > 0
        assert seg2.data_source("city").bloom_filter.might_contain("sf")
        assert len(seg2.data_source("city").text_index
                   .matching_ids("sf")) == 1
        assert seg2.data_source("doc").json_index.match(
            '"$.t"=\'tag3\'').sum() > 0

    def test_idempotent(self, tmp_path):
        seg_dir = _build(tmp_path)
        cfg = IndexingConfig(inverted_index_columns=["city"])
        assert preprocess_segment(seg_dir, cfg) == ["city:inverted"]
        assert preprocess_segment(seg_dir, cfg) == []  # already built


class TestClusterReload:
    def test_update_config_then_reload(self, tmp_path):
        """Add a json index to a LIVE table: update config -> reload ->
        json_match plans via the index and answers correctly."""
        cluster = EmbeddedCluster(num_servers=1,
                                  data_dir=str(tmp_path / "c"))
        try:
            cfg = TableConfig("rl", indexing_config=IndexingConfig(
                no_dictionary_columns=["amt"]))
            cluster.create_table(cfg, _schema())
            rng = np.random.default_rng(4)
            n = 2000
            cluster.ingest_rows("rl_OFFLINE", _schema(), {
                "city": np.array(["sf", "nyc"])[rng.integers(0, 2, n)],
                "doc": np.array([json.dumps({"t": f"tag{i % 5}"})
                                 for i in range(n)]),
                "amt": rng.integers(0, 100, n).astype(np.int64),
                "v": np.ones(n, dtype=np.int64)}, segment_name="rl_0")
            assert cluster.wait_for_ev_converged("rl_OFFLINE")

            expected = n // 5
            sql = ("SELECT count(*) FROM rl "
                   "WHERE json_match(doc, '\"$.t\"=''tag2''')")
            assert cluster.query_rows(sql)[0][0] == expected  # index-less

            new_cfg = TableConfig("rl", indexing_config=IndexingConfig(
                no_dictionary_columns=["amt"],
                json_index_columns=["doc"],
                inverted_index_columns=["city"]))
            cluster.controller.update_table(new_cfg)
            cluster.controller.reload_table("rl_OFFLINE")

            # reload is synchronous over the in-process watch
            server = cluster.servers["server_0"]
            held = server.data_manager.get("rl_OFFLINE")
            acq = held.acquire_segments(None)
            try:
                seg = acq[0].segment
                assert seg.metadata.column("doc").has_json_index
                assert seg.metadata.column("city").has_inverted_index
            finally:
                held.release_segments(acq)
            assert cluster.query_rows(sql)[0][0] == expected  # via index
        finally:
            cluster.shutdown()

    def test_reload_over_rest(self, tmp_path):
        import urllib.request

        from pinot_tpu.transport.rest import ControllerApi

        cluster = EmbeddedCluster(num_servers=1,
                                  data_dir=str(tmp_path / "c"))
        api = ControllerApi(cluster.controller, port=0)
        api.start()
        try:
            cluster.create_table(TableConfig("rl"), _schema())
            cluster.ingest_rows("rl_OFFLINE", _schema(), {
                "city": np.array(["sf"] * 10),
                "doc": np.array(["{}"] * 10),
                "amt": np.arange(10).astype(np.int64),
                "v": np.ones(10, dtype=np.int64)}, segment_name="rl_0")
            assert cluster.wait_for_ev_converged("rl_OFFLINE")

            def http(method, path, body=None):
                req = urllib.request.Request(
                    f"http://localhost:{api.port}{path}",
                    data=json.dumps(body).encode() if body else None,
                    method=method,
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=20) as r:
                    return json.loads(r.read().decode())

            new_cfg = TableConfig("rl", indexing_config=IndexingConfig(
                bloom_filter_columns=["city"]))
            http("PUT", "/tables/rl_OFFLINE", new_cfg.to_dict())
            resp = http("POST", "/segments/rl_OFFLINE/reload")
            assert "reload" in resp["status"].lower()
            server = cluster.servers["server_0"]
            acq = server.data_manager.get("rl_OFFLINE").acquire_segments(None)
            try:
                assert acq[0].segment.metadata.column(
                    "city").has_bloom_filter
            finally:
                server.data_manager.get("rl_OFFLINE").release_segments(acq)
        finally:
            api.stop()
            cluster.shutdown()


def test_put_table_rejects_name_mismatch(tmp_path):
    import urllib.error
    import urllib.request

    from pinot_tpu.transport.rest import ControllerApi

    cluster = EmbeddedCluster(num_servers=1, data_dir=str(tmp_path / "c"))
    api = ControllerApi(cluster.controller, port=0)
    api.start()
    try:
        cluster.create_table(TableConfig("rl"), _schema())
        body = json.dumps(TableConfig("other").to_dict()).encode()
        req = urllib.request.Request(
            f"http://localhost:{api.port}/tables/rl_OFFLINE",
            data=body, method="PUT",
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(req, timeout=20)
        assert exc.value.code == 400
    finally:
        api.stop()
        cluster.shutdown()
