"""Socket-transport stream plugin: kafka-shaped consumption over real
sockets with partition discovery + offset resume.

Ref: pinot-kafka-2.0 KafkaPartitionLevelConsumer / KafkaStreamMetadataProvider
/ KafkaConsumerFactory; the realtime FSM + commit protocol drive it exactly
like the reference's LLRealtimeSegmentDataManager drives Kafka.
"""

import numpy as np
import pandas as pd
import pytest

from pinot_tpu.ingestion.socketstream import (
    StreamBrokerServer,
    create_topic,
    produce,
)
from pinot_tpu.ingestion.stream import StreamOffset, create_consumer_factory
from pinot_tpu.spi import DataType, FieldSpec, FieldType, Schema
from pinot_tpu.spi.table import (
    SegmentsValidationConfig,
    StreamIngestionConfig,
    TableConfig,
    TableType,
)
from pinot_tpu.tools.cluster import EmbeddedCluster


@pytest.fixture()
def broker():
    b = StreamBrokerServer(port=0).start()
    yield b
    b.stop()


def _stream_cfg(broker, topic, flush_rows=10_000):
    return StreamIngestionConfig(
        stream_type="socket", topic=topic,
        segment_flush_threshold_rows=flush_rows,
        properties={"stream.socket.broker.url": broker.url})


class TestSpiOverSockets:
    def test_partition_discovery_and_fetch(self, broker):
        create_topic(broker.url, "t1", num_partitions=3)
        produce(broker.url, "t1", [{"a": 1}, {"a": 2}], partition=1)
        factory = create_consumer_factory(_stream_cfg(broker, "t1"))
        meta = factory.create_metadata_provider()
        assert meta.partition_count() == 3
        assert meta.earliest_offset(1).value == 0
        assert meta.latest_offset(1).value == 2
        consumer = factory.create_partition_consumer(1)
        batch = consumer.fetch_messages(StreamOffset(0))
        assert [m.payload for m in batch.messages] == [{"a": 1}, {"a": 2}]
        assert batch.next_offset.value == 2

    def test_offset_resume(self, broker):
        """Fetching from a committed offset skips consumed records — the
        checkpoint/restart contract (SURVEY.md §5 checkpoint/resume)."""
        create_topic(broker.url, "t2")
        produce(broker.url, "t2", [{"i": i} for i in range(10)])
        factory = create_consumer_factory(_stream_cfg(broker, "t2"))
        consumer = factory.create_partition_consumer(0)
        first = consumer.fetch_messages(StreamOffset(0), max_messages=4)
        assert first.next_offset.value == 4
        consumer.close()
        # a NEW consumer (restart) resumes from the committed offset
        resumed = factory.create_partition_consumer(0)
        batch = resumed.fetch_messages(first.next_offset)
        assert [m.payload["i"] for m in batch.messages] == list(range(4, 10))


def _schema(name):
    return Schema(name, [
        FieldSpec("region", DataType.STRING),
        FieldSpec("qty", DataType.LONG, FieldType.METRIC),
        FieldSpec("ts", DataType.LONG, FieldType.DATE_TIME),
    ])


class TestRealtimeOverSockets:
    def test_cluster_consumes_from_socket_stream(self, broker, tmp_path):
        """Full realtime path: FSM consumption + commit over the wire
        stream (the LLC protocol driving a network consumer)."""
        create_topic(broker.url, "sales_topic", num_partitions=2)
        cluster = EmbeddedCluster(num_servers=2,
                                  data_dir=str(tmp_path / "c"))
        cfg = TableConfig(
            "ssales", TableType.REALTIME,
            validation_config=SegmentsValidationConfig(time_column_name="ts"),
            stream_config=_stream_cfg(broker, "sales_topic",
                                      flush_rows=300))
        try:
            cluster.create_table(cfg, _schema("ssales"))
            rng = np.random.default_rng(12)
            df = pd.DataFrame({
                "region": np.array(["e", "w", "n"])[rng.integers(0, 3, 900)],
                "qty": rng.integers(1, 9, 900).astype(np.int64),
                "ts": np.arange(900).astype(np.int64),
            })
            recs = df.to_dict("records")
            for p in (0, 1):
                produce(broker.url, "sales_topic", recs[p::2], partition=p)
            assert cluster.wait_for_docs("ssales", 900), \
                cluster.query("SELECT count(*) FROM ssales").to_dict()
            rows = cluster.query_rows(
                "SELECT region, sum(qty) FROM ssales "
                "GROUP BY region ORDER BY region")
            want = df.groupby("region").qty.sum().sort_index()
            assert [(r[0], r[1]) for r in rows] == \
                [(k, float(v)) for k, v in want.items()]

            # flush threshold 300 -> sealed segments carry offset checkpoints
            sealed = [m for m in
                      cluster.store.segment_metadata_list("ssales_REALTIME")
                      if m.status == "ONLINE"]
            assert sealed and all(m.end_offset is not None for m in sealed)

            # late records keep flowing (consumption continues post-commit)
            produce(broker.url, "sales_topic",
                    [{"region": "z", "qty": 5, "ts": 1000}], partition=0)
            assert cluster.wait_for_docs("ssales", 901)
        finally:
            cluster.shutdown()

    def test_partition_expansion_mid_stream(self, broker, tmp_path):
        """Topic grows 2 -> 4 partitions while the table is consuming: the
        realtime validation repair (ensureAllPartitionsConsuming,
        PinotLLCRealtimeSegmentManager.java:108-113) must create CONSUMING
        segments for the new partitions, and every record must land EXACTLY
        once — no loss, no dupes."""
        create_topic(broker.url, "exp_topic", num_partitions=2)
        cluster = EmbeddedCluster(num_servers=2,
                                  data_dir=str(tmp_path / "x"))
        cfg = TableConfig(
            "exp", TableType.REALTIME,
            validation_config=SegmentsValidationConfig(time_column_name="ts"),
            stream_config=_stream_cfg(broker, "exp_topic", flush_rows=200))
        try:
            cluster.create_table(cfg, _schema("exp"))
            rng = np.random.default_rng(5)
            df = pd.DataFrame({
                "region": np.array(["e", "w", "n"])[rng.integers(0, 3, 600)],
                "qty": rng.integers(1, 9, 600).astype(np.int64),
                "ts": np.arange(600).astype(np.int64),
            })
            recs = df.to_dict("records")
            for p in (0, 1):
                produce(broker.url, "exp_topic", recs[p::4], partition=p)
            n_first = len(recs[0::4]) + len(recs[1::4])
            assert cluster.wait_for_docs("exp", n_first)

            # EXPAND mid-stream, then produce the rest to the NEW partitions
            create_topic(broker.url, "exp_topic", num_partitions=4)
            for p in (2, 3):
                produce(broker.url, "exp_topic", recs[p::4], partition=p)

            # repair pass discovers the new partitions
            fresh = cluster.controller.run_realtime_validation()
            assert any("__2__" in s for s in fresh) \
                and any("__3__" in s for s in fresh), fresh
            assert cluster.wait_for_docs("exp", 600), \
                cluster.query("SELECT count(*) FROM exp").to_dict()

            # exactly-once: totals AND group sums match the produced frame
            rows = cluster.query_rows(
                "SELECT region, sum(qty), count(*) FROM exp "
                "GROUP BY region ORDER BY region")
            want = df.groupby("region").agg(s=("qty", "sum"),
                                            c=("qty", "size")).sort_index()
            assert [(r[0], r[1], r[2]) for r in rows] == \
                [(k, float(v.s), v.c) for k, v in want.iterrows()]

            # a second repair pass is idempotent: nothing new to create
            assert cluster.controller.run_realtime_validation() == []
        finally:
            cluster.shutdown()
