"""Streaming execution, virtual columns, automatic liveness detection.

Ref: GrpcQueryServer.submit:84 + StreamingReduceService (streaming),
segment/virtualcolumn/* ($docId/$segmentName/$hostName), Helix
ephemeral-znode liveness -> RoutingManager exclusion (failure detection).
"""

import numpy as np
import pytest

from pinot_tpu.engine import ServerQueryExecutor
from pinot_tpu.query import compile_query
from pinot_tpu.spi import DataType, FieldSpec, FieldType, Schema
from pinot_tpu.spi.table import TableConfig
from pinot_tpu.tools.cluster import EmbeddedCluster
from pinot_tpu.transport.grpc_transport import GrpcQueryServer, GrpcServerStub

N = 2000


def _schema():
    return Schema("sv", [
        FieldSpec("k", DataType.STRING),
        FieldSpec("v", DataType.LONG, FieldType.METRIC)])


@pytest.fixture()
def cluster(tmp_path):
    c = EmbeddedCluster(num_servers=2, data_dir=str(tmp_path / "c"))
    c.create_table(TableConfig("sv"), _schema())
    rng = np.random.default_rng(3)
    for i in range(4):
        c.ingest_rows("sv_OFFLINE", _schema(), {
            "k": np.array(["a", "b", "c"])[rng.integers(0, 3, N)],
            "v": rng.integers(0, 100, N).astype(np.int64)},
            segment_name=f"sv_{i}")
    assert c.wait_for_ev_converged("sv_OFFLINE")
    yield c
    c.shutdown()


class TestStreamingExecution:
    def test_server_streams_per_segment_blocks(self, cluster):
        server = cluster.servers["server_0"]
        hosted = server.hosted_segments("sv_OFFLINE")
        ctx = compile_query("SELECT k, v FROM sv LIMIT 100000")
        blocks = list(server.execute_query_streaming(ctx, "sv_OFFLINE",
                                                     hosted))
        assert len(blocks) == len(hosted)  # one block per segment
        total = sum(len(b.payload["rows"]) for b in blocks)
        assert total > 0

    def test_streaming_over_grpc_sockets(self, cluster):
        server = cluster.servers["server_0"]
        g = GrpcQueryServer(server, port=0)
        g.start()
        stub = GrpcServerStub(f"localhost:{g.port}", timeout_s=30)
        try:
            ctx = compile_query("SELECT k FROM sv LIMIT 100000")
            hosted = server.hosted_segments("sv_OFFLINE")
            blocks = list(stub.execute_query_streaming(ctx, "sv_OFFLINE",
                                                       hosted))
            assert len(blocks) == len(hosted)
            assert all(not b.exceptions for b in blocks)
        finally:
            stub.close()
            g.stop(grace=0.5)

    def test_broker_early_exit_selection(self, cluster):
        """Selection-only LIMIT stops pulling once enough rows arrived:
        fewer docs scanned than a full sweep (SelectionOnlyCombineOperator
        early exit, here over the streaming path)."""
        resp = cluster.query("SELECT k, v FROM sv LIMIT 5")
        assert not resp.has_exceptions
        assert len(resp.result_table.rows) == 5
        # early exit: far fewer than all 8000 docs scanned
        assert resp.stats.num_docs_scanned < 4 * N

    def test_streaming_matches_unary_counts(self, cluster):
        resp = cluster.query("SELECT k FROM sv WHERE v >= 50 LIMIT 100000")
        host = ServerQueryExecutor(use_device=False)
        # oracle through the per-segment executor on all segments
        all_segs = []
        for s in cluster.servers.values():
            pass
        total = cluster.query_rows(
            "SELECT count(*) FROM sv WHERE v >= 50")[0][0]
        assert len(resp.result_table.rows) == total


class TestVirtualColumns:
    def test_docid_and_segmentname(self, tmp_path):
        from pinot_tpu.segment import SegmentBuilder, load_segment

        b = SegmentBuilder(_schema(), "vc_0")
        b.build({"k": np.array(["a", "b", "c"]),
                 "v": np.array([1, 2, 3], dtype=np.int64)}, str(tmp_path))
        seg = load_segment(f"{tmp_path}/vc_0")
        ex = ServerQueryExecutor(use_device=False)
        rt, _ = ex.execute(compile_query(
            "SELECT $docId, $segmentName, k FROM sv ORDER BY $docId"), [seg])
        assert [r[0] for r in rt.rows] == [0, 1, 2]
        assert all(r[1] == "vc_0" for r in rt.rows)
        rt, _ = ex.execute(compile_query(
            "SELECT k FROM sv WHERE $docId = 1"), [seg])
        assert rt.rows == [["b"]]
        rt, _ = ex.execute(compile_query(
            "SELECT count(*) FROM sv WHERE $segmentName = 'vc_0'"), [seg])
        assert rt.rows[0][0] == 3

    def test_unknown_virtual_rejected(self, tmp_path):
        from pinot_tpu.engine.errors import QueryError
        from pinot_tpu.segment import SegmentBuilder, load_segment

        b = SegmentBuilder(_schema(), "vc_1")
        b.build({"k": np.array(["a"]), "v": np.array([1], dtype=np.int64)},
                str(tmp_path))
        seg = load_segment(f"{tmp_path}/vc_1")
        ex = ServerQueryExecutor(use_device=False)
        with pytest.raises(QueryError):
            ex.execute(compile_query("SELECT $nope FROM sv"), [seg])


class TestLivenessDetection:
    def test_stale_heartbeat_marks_dead_and_routing_excludes(self, cluster):
        t0 = 1_000_000_000_000
        for iid in cluster.servers:
            cluster.store.touch_instance(iid, now_ms=t0)
        # one server keeps beating, the other goes silent
        cluster.store.touch_instance("server_0", now_ms=t0 + 60_000)
        dead = cluster.controller.run_liveness_check(
            timeout_ms=10_000, now_ms=t0 + 61_000)
        assert dead == ["server_1"]
        assert not cluster.store.get_instance("server_1").alive
        # routing excludes the dead server; replication 1 -> partial results
        resp = cluster.query("SELECT count(*) FROM sv")
        assert resp.has_exceptions  # unavailable segments reported

        # heartbeat resumes -> revived, full results again
        cluster.store.touch_instance("server_1", now_ms=t0 + 62_000)
        assert cluster.store.get_instance("server_1").alive
        dead = cluster.controller.run_liveness_check(
            timeout_ms=10_000, now_ms=t0 + 63_000)
        assert dead == []
        resp = cluster.query("SELECT count(*) FROM sv")
        assert not resp.has_exceptions
        assert resp.result_table.rows[0][0] == 4 * N

    def test_manual_liveness_untouched(self, cluster):
        """Instances that never heartbeat keep manual liveness semantics
        (embedded tests flip the flag directly)."""
        dead = cluster.controller.run_liveness_check(timeout_ms=1)
        assert dead == []
