"""Chunk-compressed raw forward indexes (ref: ChunkCompressorFactory,
BaseChunkSVForwardIndexReader) + FieldConfig plumbing."""

import os

import numpy as np
import pytest

from pinot_tpu.engine import ServerQueryExecutor
from pinot_tpu.query import compile_query
from pinot_tpu.segment import SegmentBuilder, load_segment
from pinot_tpu.segment.compression import read_compressed, write_compressed
from pinot_tpu.spi import DataType, FieldSpec, FieldType, Schema
from pinot_tpu.spi.table import FieldConfig, TableConfig


@pytest.mark.parametrize("codec", ["ZSTANDARD", "GZIP", "SNAPPY", "LZ4",
                                   "PASS_THROUGH"])
@pytest.mark.parametrize("dtype", [np.int32, np.int64, np.float64])
def test_roundtrip(tmp_path, codec, dtype):
    rng = np.random.default_rng(3)
    vals = rng.integers(0, 1000, 200_000).astype(dtype)
    path = str(tmp_path / "col.bin")
    used = write_compressed(path, vals, codec, chunk_docs=65_536)
    assert used in ("ZSTANDARD", "ZLIB", "PASS_THROUGH")
    out = read_compressed(path)
    assert out.dtype == vals.dtype
    assert np.array_equal(out, vals)


def test_range_read_decompresses_covering_chunks_only(tmp_path):
    vals = np.arange(300_000, dtype=np.int64)
    path = str(tmp_path / "col.bin")
    write_compressed(path, vals, "ZSTANDARD", chunk_docs=10_000)
    out = read_compressed(path, doc_range=(25_000, 45_001))
    assert np.array_equal(out, vals[25_000:45_001])


def test_compression_shrinks_compressible_data(tmp_path):
    vals = np.zeros(500_000, dtype=np.int64)
    p1, p2 = str(tmp_path / "c.bin"), str(tmp_path / "p.bin")
    write_compressed(p1, vals, "ZSTANDARD")
    write_compressed(p2, vals, "PASS_THROUGH")
    assert os.path.getsize(p1) < os.path.getsize(p2) / 50


def test_empty_column(tmp_path):
    path = str(tmp_path / "e.bin")
    write_compressed(path, np.empty(0, dtype=np.float64), "ZSTANDARD")
    assert read_compressed(path).size == 0


def _build(tmp_path, codec):
    schema = Schema("t", [
        FieldSpec("k", DataType.STRING),
        FieldSpec("v", DataType.LONG, FieldType.METRIC),
    ])
    tc = TableConfig(table_name="t", field_config_list=[
        FieldConfig("v", encoding_type="RAW", compression_codec=codec)])
    rng = np.random.default_rng(11)
    frame = {"k": [f"k{i % 7}" for i in range(5000)],
             "v": rng.integers(0, 100, 5000).astype(np.int64)}
    SegmentBuilder(schema, "s0", table_config=tc).build(frame, str(tmp_path))
    return load_segment(str(tmp_path / "s0")), frame


def test_segment_roundtrip_with_compressed_raw_column(tmp_path):
    seg, frame = _build(tmp_path, "ZSTANDARD")
    cm = seg.metadata.column("v")
    assert not cm.has_dictionary
    assert cm.compression_codec == "ZSTANDARD"
    assert np.array_equal(
        np.asarray(seg.data_source("v").forward_index)[:5000], frame["v"])
    # the query path reads through the compressed index
    ex = ServerQueryExecutor()
    t, _ = ex.execute(compile_query(
        "SELECT sum(v) FROM t WHERE k = 'k3'"), [seg])
    expect = sum(v for k, v in zip(frame["k"], frame["v"]) if k == "k3")
    assert t.rows[0][0] == expect


def test_fieldconfig_json_roundtrip():
    tc = TableConfig(table_name="x", field_config_list=[
        FieldConfig("a", "RAW", index_type="TEXT",
                    compression_codec="LZ4", properties={"p": "1"})])
    tc2 = TableConfig.from_dict(tc.to_dict())
    fc = tc2.field_config_list[0]
    assert (fc.name, fc.encoding_type, fc.index_type,
            fc.compression_codec, fc.properties) == (
        "a", "RAW", "TEXT", "LZ4", {"p": "1"})


def test_star_tree_builds_on_compressed_metric(tmp_path):
    """Star-tree build must read through the compressed fwd index
    (regression: load_fwd only knew .fwd.npy)."""
    from pinot_tpu.spi.table import IndexingConfig, StarTreeIndexConfig

    schema = Schema("t", [
        FieldSpec("k", DataType.STRING),
        FieldSpec("m", DataType.LONG, FieldType.METRIC),
    ])
    tc = TableConfig(
        table_name="t",
        indexing_config=IndexingConfig(star_tree_index_configs=[
            StarTreeIndexConfig(dimensions_split_order=["k"],
                                function_column_pairs=["SUM__m"],
                                max_leaf_records=100)]),
        field_config_list=[FieldConfig("m", encoding_type="RAW",
                                       compression_codec="ZSTANDARD")])
    frame = {"k": [f"k{i % 5}" for i in range(2000)],
             "m": list(range(2000))}
    sm = SegmentBuilder(schema, "st0", table_config=tc).build(
        frame, str(tmp_path))
    assert sm.star_tree_count == 1
