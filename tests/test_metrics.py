"""Metrics + phase timing (VERDICT r3 item 8).

Ref: AbstractMetrics.java:46 (meters/gauges/timers per role),
ServerQueryExecutorV1Impl.java:122-303 (phase timers),
SingleConnectionBrokerRequestHandler.java:90-123 (broker phases).
"""

import json
import urllib.request

import numpy as np
import pytest

from pinot_tpu.spi import DataType, FieldSpec, FieldType, Schema
from pinot_tpu.spi.metrics import (
    BrokerQueryPhase,
    MetricsRegistry,
    ServerQueryPhase,
)
from pinot_tpu.spi.table import TableConfig
from pinot_tpu.tools.cluster import EmbeddedCluster


class TestRegistry:
    def test_meter_gauge_timer(self):
        r = MetricsRegistry(role="server")
        r.meter("queries_total").mark()
        r.meter("queries_total").mark(2)
        r.gauge("tables", lambda: 3)
        with r.timer("exec").time():
            pass
        assert r.meter("queries_total").count == 3
        d = r.to_dict()
        assert d["meters"]["queries_total"] == 3
        assert d["gauges"]["tables"] == 3
        assert d["timers"]["exec"]["count"] == 1

    def test_prometheus_export(self):
        r = MetricsRegistry(role="broker")
        r.meter("queries_total").mark(7)
        r.timer("REDUCE").update_ms(1.5)
        text = r.export_prometheus()
        assert "pinot_broker_queries_total 7" in text
        assert "pinot_broker_REDUCE_ms_sum 1.5" in text
        assert "# TYPE pinot_broker_queries_total counter" in text


@pytest.fixture()
def cluster(tmp_path):
    c = EmbeddedCluster(num_servers=2, data_dir=str(tmp_path / "c"))
    schema = Schema("mt", [
        FieldSpec("city", DataType.STRING),
        FieldSpec("v", DataType.LONG, FieldType.METRIC)])
    c.create_table(TableConfig("mt"), schema)
    rng = np.random.default_rng(4)
    for i in range(2):
        c.ingest_rows("mt_OFFLINE", schema, {
            "city": np.array(["sf", "nyc"])[rng.integers(0, 2, 800)],
            "v": rng.integers(0, 9, 800).astype(np.int64)},
            segment_name=f"mt_{i}")
    assert c.wait_for_ev_converged("mt_OFFLINE")
    yield c
    c.shutdown()


class TestPhaseTiming:
    def test_response_carries_phase_times(self, cluster):
        resp = cluster.query("SELECT city, sum(v) FROM mt GROUP BY city")
        d = resp.to_dict()
        phases = d["phaseTimesMs"]
        # broker phases
        for p in (BrokerQueryPhase.COMPILATION, BrokerQueryPhase.ROUTING,
                  BrokerQueryPhase.SCATTER_GATHER, BrokerQueryPhase.REDUCE):
            assert p in phases and phases[p] >= 0.0, phases
        # server phases (merged across servers via DataTable stats)
        for p in (ServerQueryPhase.SCHEDULER_WAIT,
                  ServerQueryPhase.SEGMENT_PRUNING,
                  ServerQueryPhase.QUERY_EXECUTION):
            assert p in phases, phases

    def test_role_metrics_populated(self, cluster):
        cluster.query("SELECT count(*) FROM mt")
        cluster.query("SELECT count(*) FROM nope")  # exception path
        bm = cluster.broker.metrics.to_dict()
        assert bm["meters"]["queries_total"] >= 2
        assert bm["meters"]["query_exceptions_total"] >= 1
        sm = cluster.servers["server_0"].metrics.to_dict()
        assert sm["meters"]["queries_total"] >= 1
        cm = cluster.controller.metrics.to_dict()
        assert cm["gauges"]["tables"] == 1
        assert cm["gauges"]["segments"] == 2
        assert cm["gauges"]["live_servers"] == 2


class TestMetricsEndpoints:
    def test_metrics_over_rest(self, cluster):
        from pinot_tpu.transport.rest import (
            BrokerApi,
            ControllerApi,
            ServerAdminApi,
        )

        cluster.query("SELECT count(*) FROM mt")
        apis = [ControllerApi(cluster.controller, port=0),
                BrokerApi(cluster.broker, port=0),
                ServerAdminApi(cluster.servers["server_0"], port=0)]
        for api in apis:
            api.start()
        try:
            for api, needle in zip(apis, ("pinot_controller_tables",
                                          "pinot_broker_queries_total",
                                          "pinot_server_queries_total")):
                with urllib.request.urlopen(
                        f"http://localhost:{api.port}/metrics",
                        timeout=10) as r:
                    assert r.headers["Content-Type"].startswith("text/plain")
                    body = r.read().decode()
                assert needle in body, body[:300]
        finally:
            for api in apis:
                api.stop()
