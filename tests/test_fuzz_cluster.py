"""Cluster-level query fuzz: the FULL broker front door vs the pandas oracle.

VERDICT r4 item 9 / ref: the reference fuzzes generated queries through a
running cluster against H2 (``QueryGenerator.java:65``,
``ClusterIntegrationTestUtils.java:104``). Here ≥100 seeded random queries
go through parse -> routing -> hybrid time-boundary split -> 2-server
scatter -> DataTable wire -> broker reduce, over a HYBRID table (offline
segments + realtime consumption) and an UPSERT table, with vectorized
pandas as the independent oracle.
"""

import numpy as np
import pandas as pd
import pytest

from pinot_tpu.ingestion.stream import MemoryStream
from pinot_tpu.spi import DataType, FieldSpec, FieldType, Schema
from pinot_tpu.spi.table import (
    SegmentsValidationConfig,
    StreamIngestionConfig,
    TableConfig,
    TableType,
    UpsertConfig,
    UpsertMode,
)
from pinot_tpu.tools import EmbeddedCluster

from tests.test_fuzz import DIMS, _pandas_agg, _rand_filter

N_QUERIES = 110
OFF_DOCS = 4096
RT_DOCS = 1200

AGGS = ["count(*)", "sum(qty)", "min(price)", "max(price)", "avg(qty)",
        "minmaxrange(year)", "distinctcount(color)", "sum(qty * price)"]


def _frame(n, seed, ts_base):
    rng = np.random.default_rng(seed)
    return pd.DataFrame({
        "color": np.asarray(DIMS["color"])[rng.integers(0, 4, n)],
        "shape": np.asarray(DIMS["shape"])[rng.integers(0, 3, n)],
        "year": rng.integers(2000, 2020, n),
        "qty": rng.integers(0, 100, n),
        "price": np.round(rng.uniform(1, 500, n), 2),
        "ts": np.arange(ts_base, ts_base + n, dtype=np.int64),
    })


def _schema(name):
    return Schema(name, [
        FieldSpec("color", DataType.STRING),
        FieldSpec("shape", DataType.STRING),
        FieldSpec("year", DataType.INT),
        FieldSpec("qty", DataType.LONG, FieldType.METRIC),
        FieldSpec("price", DataType.DOUBLE, FieldType.METRIC),
        FieldSpec("ts", DataType.LONG, FieldType.DATE_TIME),
    ])


@pytest.fixture(scope="module")
def fleet(tmp_path_factory):
    """2-server cluster hosting a HYBRID table (2 offline segments +
    realtime rows streaming in strictly after the offline time range, so
    the union is exact under any time boundary) and an upsert table."""
    out = str(tmp_path_factory.mktemp("fuzzc"))
    MemoryStream.create("fzc_topic", 2)
    MemoryStream.create("fzu_topic", 1)
    cluster = EmbeddedCluster(num_servers=2, data_dir=out)
    schema = _schema("fzc")

    off_cfg = TableConfig(
        "fzc", TableType.OFFLINE,
        validation_config=SegmentsValidationConfig(time_column_name="ts"))
    rt_cfg = TableConfig(
        "fzc", TableType.REALTIME,
        validation_config=SegmentsValidationConfig(time_column_name="ts"),
        stream_config=StreamIngestionConfig(
            stream_type="memory", topic="fzc_topic",
            segment_flush_threshold_rows=400))
    cluster.create_table(off_cfg, schema)
    cluster.create_table(rt_cfg, schema)

    frames = []
    for i in range(2):
        df = _frame(OFF_DOCS, seed=70 + i, ts_base=i * OFF_DOCS)
        frames.append(df)
        cluster.ingest_rows("fzc_OFFLINE", schema,
                            {c: df[c].tolist() for c in df.columns},
                            segment_name=f"fzc_off_{i}")
    assert cluster.wait_for_ev_converged("fzc_OFFLINE")
    rt = _frame(RT_DOCS, seed=90, ts_base=2 * OFF_DOCS + 1000)
    frames.append(rt)
    stream = MemoryStream.get("fzc_topic")
    # the hybrid boundary is max(offline end time) - 1 (routing.py
    # get_boundary, mirroring the reference's in-flight-push guard), so
    # offline rows PAST the boundary are served by the realtime side — in
    # production realtime overlaps the offline tail; replicate that overlap
    boundary = 2 * OFF_DOCS - 2
    overlap = pd.concat(frames[:2], ignore_index=True)
    overlap = overlap[overlap.ts > boundary]
    for i, rec in enumerate(list(overlap.to_dict("records"))
                            + rt.to_dict("records")):
        stream.produce(rec, partition=i % 2)
    assert cluster.wait_for_docs("fzc", 2 * OFF_DOCS + RT_DOCS,
                                 timeout_s=30)
    union = pd.concat(frames, ignore_index=True)

    # upsert table: repeated keys, oracle = latest row per key (primary
    # keys ride on the Schema, as in the reference)
    us = Schema("fzu", _schema("fzu").field_specs,
                primary_key_columns=["color"])
    us_cfg = TableConfig(
        "fzu", TableType.REALTIME,
        validation_config=SegmentsValidationConfig(time_column_name="ts"),
        stream_config=StreamIngestionConfig(
            stream_type="memory", topic="fzu_topic",
            segment_flush_threshold_rows=150),
        upsert_config=UpsertConfig(mode=UpsertMode.FULL))
    cluster.create_table(us_cfg, us)
    rng = np.random.default_rng(17)
    latest = {}
    ustream = MemoryStream.get("fzu_topic")
    for t in range(400):
        rec = {"color": str(rng.choice(DIMS["color"])),
               "shape": str(rng.choice(DIMS["shape"])),
               "year": int(rng.integers(2000, 2020)),
               "qty": int(rng.integers(0, 100)),
               "price": float(np.round(rng.uniform(1, 500), 2)),
               "ts": 1000 + t}
        latest[rec["color"]] = rec
        ustream.produce(rec, partition=0)
    assert cluster.wait_for_docs("fzu", len(latest), timeout_s=30)
    upsert_df = pd.DataFrame(list(latest.values()))

    yield cluster, union, upsert_df
    cluster.shutdown()
    MemoryStream.delete("fzc_topic")
    MemoryStream.delete("fzu_topic")


def _check(cluster, df, table, qi):
    rng = np.random.default_rng(4321 + qi)
    n_aggs = int(rng.integers(1, 4))
    aggs = list(rng.choice(AGGS, size=n_aggs, replace=False))
    where, mask_fn = _rand_filter(rng)
    group = []
    if rng.integers(0, 2):
        group = list(rng.choice(list(DIMS), size=int(rng.integers(1, 3)),
                                replace=False))
    cols = ", ".join(group + aggs)
    sql = f"SELECT {cols} FROM {table}{where}"
    if group:
        sql += (f" GROUP BY {', '.join(group)}"
                f" ORDER BY {', '.join(group)} LIMIT 10000")

    resp = cluster.query(sql)
    assert not resp.exceptions, (sql, resp.exceptions)
    rows = resp.result_table.rows if resp.result_table else []

    sub = df[mask_fn(df)]
    if group:
        want = {}
        for key, g in sub.groupby(group, sort=True):
            key = key if isinstance(key, tuple) else (key,)
            want[tuple(str(k) for k in key)] = [
                _pandas_agg(g, a) for a in aggs]
        got = {tuple(str(v) for v in r[:len(group)]): r[len(group):]
               for r in rows}
        assert set(got) == set(want), (sql, len(got), len(want))
        for k, vals in want.items():
            for g_v, w_v in zip(got[k], vals):
                _assert_close(g_v, w_v, sql)
    else:
        assert len(rows) == 1, sql
        for g_v, a in zip(rows[0], aggs):
            _assert_close(g_v, _pandas_agg(sub, a), sql)


def _assert_close(got, want, sql):
    if want is None:  # empty-filter scalar semantics differ per agg; the
        return        # executor-level fuzzer pins those exactly
    if isinstance(want, float):
        assert abs(got - want) <= 1e-6 * max(1.0, abs(want)), \
            (sql, got, want)
    else:
        assert got == want, (sql, got, want)


@pytest.mark.parametrize("qi", range(N_QUERIES))
def test_fuzz_hybrid_front_door(fleet, qi):
    cluster, union, _ = fleet
    _check(cluster, union, "fzc", qi)


@pytest.mark.parametrize("qi", range(20))
def test_fuzz_upsert_front_door(fleet, qi):
    cluster, _, upsert_df = fleet
    _check(cluster, upsert_df, "fzu", 100_000 + qi)
