"""graftlint: the tier-1 invariant gate + per-checker negative fixtures.

Two layers:

- ``test_package_is_clean`` runs every checker family over the whole
  ``pinot_tpu`` package with the checked-in baseline — the machine-enforced
  gate that keeps the PR-1..3 bug classes (field touched outside its
  guarding lock, acquire without a paired release, host effects in traced
  code, stat added but never wired) from coming back. PR 5 adds the
  dataflow families: kernel param protocol (``protocol``), device-sync
  taint (``sync``), and HBM accounting conservation (``conservation``).
- the fixture tests seed one violation of each invariant into a temp file
  and prove the checker catches it — including a regression fixture in the
  exact shape of the PR-2 ``stage()`` get-then-set race, an unpaired-lease
  fixture, and (for the protocol family) a scratch copy of
  ``pallas_kernels.py`` with one ``pc.take()`` reordered.

``pytest -m lint`` runs just this module (fast: stdlib ast only, no jax
work beyond the conftest import).
"""

import json
import os
import textwrap

import pytest

import pinot_tpu
from pinot_tpu.tools.lint import run_lint
from pinot_tpu.tools.lint.__main__ import main as lint_main
from pinot_tpu.tools.lint.core import DEFAULT_BASELINE

pytestmark = pytest.mark.lint

PKG = os.path.dirname(os.path.abspath(pinot_tpu.__file__))


def _lint(tmp_path, source, name="fixture.py"):
    p = tmp_path / name
    p.write_text(textwrap.dedent(source))
    new, _accepted = run_lint([str(p)])
    return new


def _by_checker(findings, checker):
    return [f for f in findings if f.checker == checker]


# --------------------------------------------------------------------------
# the gate
# --------------------------------------------------------------------------

def test_package_is_clean():
    """The whole package passes every checker family against the
    checked-in (ideally empty) baseline. A finding here means either fix
    the code or — rarely, with justification — baseline it."""
    new, accepted = run_lint([PKG], baseline=DEFAULT_BASELINE)
    assert not new, "graftlint findings:\n" + "\n".join(
        f.render() for f in new)


def test_baseline_is_empty():
    """The dataflow families ship with a truly empty baseline: every true
    positive they found at landing time was fixed, not accepted."""
    with open(DEFAULT_BASELINE, encoding="utf-8") as f:
        assert json.load(f)["entries"] == []


def test_cli_exit_codes(tmp_path):
    """CI contract: non-zero exit iff there are non-baselined findings."""
    assert lint_main([PKG]) == 0
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent("""\
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._d = {}  # guarded-by: _lock

            def peek(self):
                return self._d.get("k")
        """))
    assert lint_main([str(bad)]) == 1


# --------------------------------------------------------------------------
# lock discipline
# --------------------------------------------------------------------------

def test_lock_guard_catches_unguarded_access(tmp_path):
    new = _lint(tmp_path, """\
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._d = {}  # guarded-by: _lock

            def ok(self):
                with self._lock:
                    return self._d.get("k")

            def bad_read(self):
                return self._d.get("k")

            def bad_write(self, v):
                self._d["k"] = v
        """)
    got = {(f.symbol, "read" in f.message) for f in _by_checker(new,
                                                               "lock-guard")}
    assert ("C._d:bad_read", True) in got
    assert ("C._d:bad_write", False) in got
    assert not any("ok" in f.symbol for f in new)


def test_lock_guard_regression_stage_get_then_set(tmp_path):
    """The PR-2 ``stage()`` shape: optimistic get outside the lock, insert
    inside it. Two concurrent stagers both miss and build duplicate device
    arrays; the loser's set leaks until GC. The checker must flag the
    unguarded read."""
    new = _lint(tmp_path, """\
        import threading

        class Cache:
            def __init__(self):
                self._lock = threading.Lock()
                self._cached = {}  # guarded-by: _lock

            def stage(self, name):
                e = self._cached.get(name)
                if e is None:
                    e = object()
                    with self._lock:
                        self._cached[name] = e
                return e
        """)
    reads = [f for f in _by_checker(new, "lock-guard")
             if f.symbol == "Cache._cached:stage" and "read" in f.message]
    assert reads, [f.render() for f in new]


def test_lock_guard_writes_only_mode_and_closures(tmp_path):
    """``guarded-by-writes`` permits lock-free reads but still flags
    unguarded mutation; a closure does NOT inherit the enclosing ``with``
    (it runs later, on another thread)."""
    new = _lint(tmp_path, """\
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._d = {}  # guarded-by-writes: _lock

            def lockfree_read(self):
                return self._d.get("k")

            def bad_write(self, v):
                self._d["k"] = v

            def bad_closure(self):
                with self._lock:
                    return lambda v: self._d.update(v)
        """)
    syms = {f.symbol for f in _by_checker(new, "lock-guard")}
    assert "C._d:bad_write" in syms
    assert "C._d:bad_closure" in syms
    assert not any("lockfree_read" in s for s in syms)


def test_lock_guard_inherited_lock_and_locked_suffix(tmp_path):
    """A base-class lock guards subclass fields; ``*_locked`` methods
    assert caller-holds-the-lock and are exempt."""
    new = _lint(tmp_path, """\
        import threading

        class Base:
            def __init__(self):
                self._lock = threading.Lock()

        class Sub(Base):
            def __init__(self):
                super().__init__()
                self._d = {}  # guarded-by: _lock

            def _pick_locked(self):
                return self._d.get("k")

            def ok(self):
                with self._lock:
                    return self._pick_locked()
        """)
    assert not new, [f.render() for f in new]


def test_lock_order_catches_inversion(tmp_path):
    new = _lint(tmp_path, """\
        import threading

        class A:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def ab(self):
                with self._a:
                    with self._b:
                        pass

            def ba(self):
                with self._b:
                    with self._a:
                        pass
        """)
    inv = _by_checker(new, "lock-order")
    assert len(inv) == 1 and "A._a" in inv[0].symbol \
        and "A._b" in inv[0].symbol


def test_lock_order_follows_calls(tmp_path):
    """The inversion hides behind a call: holding A, call a method that
    takes B; holding B, call one that takes A."""
    new = _lint(tmp_path, """\
        import threading

        class M:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def take_b(self):
                with self._b:
                    pass

            def take_a(self):
                with self._a:
                    pass

            def ab(self):
                with self._a:
                    self.take_b()

            def ba(self):
                with self._b:
                    self.take_a()
        """)
    assert _by_checker(new, "lock-order")


# --------------------------------------------------------------------------
# resource pairing
# --------------------------------------------------------------------------

def test_pairing_catches_unpaired_lease(tmp_path):
    """The unpaired-lease shape: ``end_query`` exists but only on the
    fall-through path — an exception in between leaks the lease's pins
    (and under admission pressure, pinned bytes never unpin)."""
    new = _lint(tmp_path, """\
        def leaky(mgr, segments, run):
            lease = mgr.begin_query(segments, [])
            out = run(segments)
            mgr.end_query(lease)
            return out
        """)
    pf = _by_checker(new, "pairing")
    assert len(pf) == 1
    assert "finally" in pf[0].message and "begin_query" in pf[0].symbol


def test_pairing_catches_missing_and_discarded_release(tmp_path):
    new = _lint(tmp_path, """\
        def never_released(mgr, segments, run):
            lease = mgr.begin_query(segments, [])
            return run(segments, lease)

        def discarded(mgr, segments):
            mgr.begin_query(segments, [])
        """)
    msgs = [f.message for f in _by_checker(new, "pairing")]
    # `lease` escapes through run(...) -> the caller's job; only the
    # discarded acquire is a local certainty
    assert len(msgs) == 1 and "discarded" in msgs[0]


def test_pairing_catches_unreleased_lease_on_reject(tmp_path):
    """The admission-reject leak shape (PR 7): a gate that can raise
    between ``begin_query``/``admit`` and the fall-through releases means
    every rejection leaks a lease pin AND a gate slot. Both releases live
    only on the fall-through path — the checker must flag both halves."""
    new = _lint(tmp_path, """\
        def rejected_leaks(mgr, gate, segments, run):
            lease = mgr.begin_query(segments, [])
            ticket = gate.admit("t")
            out = run(segments)
            mgr.end_query(lease)
            gate.release(ticket)
            return out
        """)
    pf = _by_checker(new, "pairing")
    assert len(pf) == 2, [f.render() for f in pf]
    symbols = {f.symbol for f in pf}
    assert "rejected_leaks:begin_query" in symbols
    assert "rejected_leaks:admit" in symbols
    assert all("finally" in f.message for f in pf)


def test_pairing_accepts_admission_gate_shape(tmp_path):
    """The correct executor shape: admit -> try -> lease inside ->
    releases in finally, rejection before the lease ever opens."""
    new = _lint(tmp_path, """\
        def admitted(mgr, gate, segments, run):
            ticket = gate.admit("t")
            try:
                lease = mgr.begin_query(segments, [])
                try:
                    return run(segments)
                finally:
                    mgr.end_query(lease)
            finally:
                gate.release(ticket)
        """)
    assert not _by_checker(new, "pairing")


def test_pairing_accepts_finally_and_context_manager(tmp_path):
    new = _lint(tmp_path, """\
        def safe(mgr, segments, run):
            lease = mgr.begin_query(segments, [])
            try:
                return run(segments)
            finally:
                mgr.end_query(lease)

        def acquired(tdm, run):
            sdms = tdm.acquire_segments(None)
            try:
                return run(sdms)
            finally:
                tdm.release_segments(sdms)
        """)
    assert not _by_checker(new, "pairing")


def test_pairing_catches_unpaired_segment_acquire(tmp_path):
    """Release on the fall-through path only. (Passing the acquired list
    into another call would make it escape — the checker is conservative —
    so the work here is local.)"""
    new = _lint(tmp_path, """\
        def leaky(tdm):
            sdms = tdm.acquire_segments(None)
            total = 0
            for s in sdms:
                total += s.segment.num_docs
            tdm.release_segments(sdms)
            return total
        """)
    assert _by_checker(new, "pairing")


# --------------------------------------------------------------------------
# tracer safety
# --------------------------------------------------------------------------

def test_tracer_catches_host_effects_in_jit_reachable_code(tmp_path):
    """Roots via decorator AND call-site; the denylisted call sits one
    call-graph hop below the root."""
    new = _lint(tmp_path, """\
        import time
        import jax


        def helper(x):
            return x + time.time()


        @jax.jit
        def decorated(x):
            return helper(x)


        def kernel(x):
            return float(x) + 1.0


        def build():
            return jax.jit(kernel)
        """)
    tf = _by_checker(new, "tracer")
    msgs = " | ".join(f.message for f in tf)
    assert "time.time" in msgs                      # transitively reached
    assert any("float" in f.symbol for f in tf)     # cast on traced param


def test_tracer_catches_item_and_global_mutation(tmp_path):
    new = _lint(tmp_path, """\
        import jax

        _CACHE = {}


        def kernel(x):
            _CACHE[int(x.shape[0])] = 1
            return x.sum().item()


        out = jax.jit(kernel)
        """)
    syms = {f.symbol for f in _by_checker(new, "tracer")}
    assert "kernel:item" in syms
    assert "kernel:mutate:_CACHE" in syms


def test_tracer_ignores_untraced_functions(tmp_path):
    new = _lint(tmp_path, """\
        import time


        def host_side(x):
            return x + time.time()
        """)
    assert not _by_checker(new, "tracer")


# --------------------------------------------------------------------------
# wire / config consistency
# --------------------------------------------------------------------------

WIRE_FIXTURE = """\
    from dataclasses import dataclass, field


    @dataclass
    class QueryStats:
        num_docs: int = 0
        forgotten: int = 0

        def to_dict(self):
            return {"numDocsScanned": self.num_docs}

        def merge(self, other):
            self.num_docs += other.num_docs


    def _stats_from_dict(st):
        return QueryStats(num_docs=st.get("numDocsScanned", 0))
    """


def test_wire_catches_stat_missing_from_wire(tmp_path):
    """The 'added a stat, forgot the wire' drift: ``forgotten`` rides
    neither to_dict nor merge nor the decode side."""
    new = _lint(tmp_path, WIRE_FIXTURE)
    syms = {f.symbol for f in _by_checker(new, "wire")}
    assert "QueryStats.forgotten:to_dict" in syms
    assert "QueryStats.forgotten:merge" in syms
    assert "QueryStats.forgotten:_stats_from_dict" in syms
    assert not any("num_docs" in s for s in syms)


def test_wire_catches_launch_key_merge_disagreement(tmp_path):
    new = _lint(tmp_path, """\
        LAUNCH_MAX_KEYS = ("batchSize", "notMerged")


        class QueryStats:
            def to_dict(self):
                return {}

            def merge(self, other):
                key = "batchSize"
                return key
        """)
    syms = {f.symbol for f in _by_checker(new, "wire")}
    assert "LAUNCH_MAX_KEYS.notMerged" in syms
    assert "LAUNCH_MAX_KEYS.batchSize" not in syms


COLKIND_FIXTURE = """\
    _COL_I64 = 0
    _COL_STR = 2
    _COL_NEW = 7


    def _encode_column(out, vals):
        out.append(_COL_I64)


    def _decode_column(buf, off, n):
        kind = buf[off]
        if kind == _COL_I64:
            return [], off
        if kind == _COL_STR:
            return [], off
        if kind == _COL_NEW:
            return [], off
        raise ValueError(kind)


    def take_boxed(col):
        if col.kind == _COL_I64:
            return list(col.arr)
        if col.kind == _COL_STR:
            return col.strings()
        raise ValueError(col.kind)


    def single_kind_helper(col):
        return col.kind == _COL_STR
    """


def test_wire_colkind_partial_dispatch_flagged(tmp_path):
    """A new column kind (_COL_NEW) that encode and a columns() consumer
    don't handle is flagged; the full decode dispatch and the single-kind
    helper are clean."""
    new = _lint(tmp_path, COLKIND_FIXTURE)
    syms = {f.symbol for f in _by_checker(new, "wire")}
    assert "colkind._encode_column" in syms
    assert "colkind.take_boxed" in syms
    assert "colkind._decode_column" not in syms
    assert "colkind.single_kind_helper" not in syms


def test_wire_colkind_full_dispatch_clean(tmp_path):
    new = _lint(tmp_path, """\
        _COL_I64 = 0
        _COL_OBJ = 3
        _COL_NUMERIC = (_COL_I64,)


        def _encode_column(out, vals):
            out.append(_COL_I64 if vals else _COL_OBJ)


        def _decode_column(buf, off, n):
            return {_COL_I64: 1, _COL_OBJ: 2}[buf[off]], off


        def grouping_helper(col):
            return col.kind in _COL_NUMERIC
        """)
    assert not _by_checker(new, "wire")


def test_config_catches_undeclared_key(tmp_path):
    new = _lint(tmp_path, """\
        class CommonConstants:
            DECLARED = "pinot.server.query.declared.knob"


        def read(cfg):
            a = cfg.get("pinot.server.query.declared.knob")
            b = cfg.get("pinot.server.query.bogus.knob")
            return a, b
        """)
    cf = _by_checker(new, "config")
    assert [f.symbol for f in cf] == ["pinot.server.query.bogus.knob"]


# --------------------------------------------------------------------------
# kernel param protocol (dataflow tier)
# --------------------------------------------------------------------------

PROTO_TABLE = """\
    _FILTER_PARAMS = {"eq": 1, "range": 2, "lut": 1}


"""


def test_protocol_catches_missing_take(tmp_path):
    """The consumer takes fewer params than the table declares for an op:
    every later predicate reads the WRONG array — silently wrong results."""
    new = _lint(tmp_path, PROTO_TABLE + """\
    def _emit(spec, pc):
        op = spec[0]
        if op == "eq":
            return pc.take()
        if op == "range":
            lo = pc.take()  # table says 2: the hi bound is never taken
            return lo
        if op == "lut":
            return pc.take()
        raise AssertionError(op)
    """)
    syms = {f.symbol for f in _by_checker(new, "protocol")}
    assert "_emit:range" in syms, [f.render() for f in new]
    assert not any(s.endswith(":eq") or s.endswith(":lut") for s in syms)


def test_protocol_catches_extra_take(tmp_path):
    new = _lint(tmp_path, PROTO_TABLE + """\
    def _emit(spec, pc):
        op = spec[0]
        if op == "eq":
            return pc.take() + pc.take()  # table says 1
        if op == "range":
            lo, hi = pc.take(), pc.take()
            return lo + hi
        if op == "lut":
            return pc.take()
        raise AssertionError(op)
    """)
    syms = {f.symbol for f in _by_checker(new, "protocol")}
    assert "_emit:eq" in syms
    assert not any(s.endswith(":range") for s in syms)


def test_protocol_raise_declines_an_op(tmp_path):
    """A consumer that raises for an op declines it (the pallas extractor's
    ``_Ineligible`` contract) — no finding for ops it never claims."""
    new = _lint(tmp_path, PROTO_TABLE + """\
    def _emit(spec, pc):
        op = spec[0]
        if op == "eq":
            return pc.take()
        raise ValueError(op)  # range/lut: declined, another rung serves
    """)
    assert not _by_checker(new, "protocol"), [f.render() for f in new]


def test_protocol_catches_reordered_group_takes(tmp_path):
    """The classic silent-wrong-results drift: the pack side writes
    (strides, bases) but a consumer takes (bases, strides) — every grouped
    result mis-keys."""
    new = _lint(tmp_path, """\
        def pack(params, strides, group_bases):
            params.append(strides)
            params.append(group_bases)

        def consume(pc):
            bases = pc.take()
            strides = pc.take()
            return strides, bases
        """)
    hits = [f for f in _by_checker(new, "protocol")
            if "group-order" in f.symbol]
    assert hits and "consume" in hits[0].symbol


def test_protocol_pack_side_drift(tmp_path):
    """The pack side appends a different count than the table declares for
    the op its return tuple carries."""
    new = _lint(tmp_path, PROTO_TABLE + """\
    def _compile(pred, params):
        op = pred[0]
        if op == "eq":
            params.append(pred[1])
            params.append(pred[2])  # one too many: table says 1
            return ("eq", pred[1])
        if op == "range":
            params.append(pred[1])
            params.append(pred[2])
            return ("range", pred[1])
        raise ValueError(op)
    """)
    syms = {f.symbol for f in _by_checker(new, "protocol")}
    assert "_compile:pack:eq" in syms
    assert not any(s.endswith("pack:range") for s in syms)


def test_protocol_flags_reordered_take_in_pallas_scratch(tmp_path):
    """Acceptance fixture: a scratch copy of the REAL pallas_kernels.py
    with the strides/bases ``pc.take()`` pair swapped must produce a
    protocol finding against the real plan.py pack order; the unmodified
    pair is clean."""
    eng = os.path.join(PKG, "engine")
    with open(os.path.join(eng, "plan.py"), encoding="utf-8") as f:
        plan_src = f.read()
    with open(os.path.join(eng, "pallas_kernels.py"),
              encoding="utf-8") as f:
        pk_src = f.read()
    s_line = "strides = [int(s) for s in np.asarray(pc.take())]"
    b_line = "bases = [int(b) for b in np.asarray(pc.take())]"
    assert s_line in pk_src and b_line in pk_src, \
        "pallas_kernels group-take lines moved; update the fixture"
    swapped = (pk_src.replace(s_line, "@@SWAP@@")
               .replace(b_line, s_line)
               .replace("@@SWAP@@", b_line))
    (tmp_path / "plan.py").write_text(plan_src)
    (tmp_path / "pallas_kernels.py").write_text(swapped)
    new, _ = run_lint([str(tmp_path)])
    hits = [f for f in _by_checker(new, "protocol")
            if "group-order" in f.symbol]
    assert hits, [f.render() for f in new]

    (tmp_path / "pallas_kernels.py").write_text(pk_src)
    clean, _ = run_lint([str(tmp_path)])
    assert not _by_checker(clean, "protocol"), \
        [f.render() for f in clean]


# --------------------------------------------------------------------------
# device-sync taint (dataflow tier)
# --------------------------------------------------------------------------

def test_sync_catches_materialization_under_lock(tmp_path):
    """float() on a device value inside ``with self._lock`` blocks every
    thread queuing on the lock until the device program finishes — the
    convoy PR 3 removed the global combine lock to escape."""
    new = _lint(tmp_path, """\
        import threading

        import jax.numpy as jnp


        class Accum:
            def __init__(self):
                self._lock = threading.Lock()
                self.total = 0.0

            def add(self, x):
                dev = jnp.sum(x)
                with self._lock:
                    self.total += float(dev)

            def add_ok(self, x):
                host = float(jnp.sum(x))  # sync BEFORE taking the lock
                with self._lock:
                    self.total += host
        """)
    sf = _by_checker(new, "sync")
    assert any("Accum.add" in f.symbol and "float()" in f.symbol
               for f in sf), [f.render() for f in new]
    assert not any("add_ok" in f.symbol for f in sf)


def test_sync_catches_dispatcher_thread_materialization(tmp_path):
    """An implicit D2H on the per-mesh dispatcher thread stalls EVERY
    sharded launch in the process, not one query."""
    new = _lint(tmp_path, """\
        import threading

        import jax.numpy as jnp
        import numpy as np


        class Dispatcher:
            def start(self):
                self._t = threading.Thread(target=self._loop, daemon=True)
                self._t.start()

            def _loop(self):
                dev = jnp.zeros(4)
                return np.asarray(dev)  # blocks the dispatcher on device
        """, name="mini_launcher.py")
    sf = _by_checker(new, "sync")
    assert any("_loop" in f.symbol and "asarray" in f.symbol
               for f in sf), [f.render() for f in new]


def test_sync_catches_gauge_callback_materialization(tmp_path):
    """Gauge/telemetry callbacks registered via ``MetricsRegistry.gauge``
    (or ``Telemetry.track_gauge``) run on scrape/sampler threads: a
    device sink inside one silently stalls every /metrics pull on device
    execution. Both the lambda-closure and named-function registration
    shapes must flag; an int-only gauge stays clean."""
    new = _lint(tmp_path, """\
        import jax.numpy as jnp
        import numpy as np


        class Exporter:
            def __init__(self, registry):
                self._staged = jnp.zeros(8)
                self.depth = 3
                dev = jnp.sum(self._staged)
                # BAD: the lambda closes over a device value and
                # materializes it at scrape time
                registry.gauge("staged_total", lambda: float(dev))
                # OK: plain host int
                registry.gauge("queue_depth", lambda: float(self.depth))

            def bind(self, registry):
                # BAD: named callback sinking a device value per scrape
                registry.gauge("staged_sum", self._read_total)

            def _read_total(self):
                total = jnp.sum(self._staged)
                return np.asarray(total)
        """)
    sf = _by_checker(new, "sync")
    assert any("gauge-lambda:float()" in f.symbol for f in sf), \
        [f.render() for f in new]
    assert any("_read_total" in f.symbol and "asarray" in f.symbol
               for f in sf), [f.render() for f in new]
    assert not any("queue_depth" in f.render() for f in sf)


def test_sync_metadata_reads_never_flag(tmp_path):
    """.nbytes/.shape/.dtype on a device array are host-side metadata —
    reading them never syncs, even under a lock."""
    new = _lint(tmp_path, """\
        import threading

        import jax.numpy as jnp


        class Meter:
            def __init__(self):
                self._lock = threading.Lock()
                self.bytes = 0

            def measure(self, x):
                dev = jnp.sum(x)
                with self._lock:
                    self.bytes += int(dev.nbytes)
        """)
    assert not _by_checker(new, "sync"), [f.render() for f in new]


# --------------------------------------------------------------------------
# HBM accounting conservation (dataflow tier)
# --------------------------------------------------------------------------

CONSERVATION_PRELUDE = """\
    class Manager:
        def __init__(self):
            self._entries = {}
            self._staged_bytes = 0

        def _account(self):
            self._staged_bytes += 1

        def _release_all(self, doomed):
            for r in doomed:
                r.release()

        def get(self, name):
            e = self._entries.get(name)
            if e is not None:
                return e.resident
            return None

"""


def test_conservation_catches_unreleased_pop(tmp_path):
    new = _lint(tmp_path, CONSERVATION_PRELUDE + """\
        def evict(self, name):
            e = self._entries.pop(name, None)
            if e is not None:
                self._staged_bytes -= 1  # accounted, but never released
""")
    cf = _by_checker(new, "conservation")
    assert any("evict" in f.symbol and f.symbol.endswith("remove")
               for f in cf), [f.render() for f in new]


def test_conservation_release_on_exception_edge(tmp_path):
    """A release only on the try fall-through leaks on the handler path —
    the exception-edged CFG must see it; releasing in a ``finally``
    satisfies every path."""
    new = _lint(tmp_path, CONSERVATION_PRELUDE + """\
        def evict_leaky(self, name):
            e = self._entries.pop(name, None)
            if e is None:
                return
            try:
                self._prepare(e)
            except ValueError:
                return  # handler path: e.resident leaks until GC
            e.resident.release()

        def evict_safe(self, name):
            e = self._entries.pop(name, None)
            if e is None:
                return
            try:
                self._prepare(e)
            finally:
                e.resident.release()
""")
    cf = _by_checker(new, "conservation")
    assert any("evict_leaky" in f.symbol for f in cf), \
        [f.render() for f in new]
    assert not any("evict_safe" in f.symbol for f in cf)


def test_conservation_catches_unaccounted_insert(tmp_path):
    new = _lint(tmp_path, CONSERVATION_PRELUDE + """\
        def put(self, name, r):
            self._entries[name] = r  # stagedBytes never re-measured

        def put_ok(self, name, r):
            self._entries[name] = r
            self._account()
""")
    cf = _by_checker(new, "conservation")
    assert any("put" in f.symbol and f.symbol.endswith("insert")
               for f in cf), [f.render() for f in new]
    assert not any("put_ok" in f.symbol for f in cf)


def test_conservation_cache_parity_star_tree_nodes(tmp_path):
    """Star-tree node-array residents obey the same byte-accounting and
    release obligations as column residents: a node cache populated via
    ``setdefault`` (no plain subscript assignment anywhere) that nbytes()
    cannot see and release() never drops must be flagged on both axes."""
    new = _lint(tmp_path, """\
        class StagedNodes:
            def __init__(self):
                self._columns = {}
                self._startree = {}

            def column(self, name):
                col = object()
                self._columns[name] = col
                return col

            def startree_nodes(self, i):
                return self._startree.setdefault(i, {"dim": object()})

            def nbytes(self):
                return len(self._columns)

            def release(self):
                self._columns.clear()
        """)
    cf = _by_checker(new, "conservation")
    assert any("_startree" in f.symbol and f.symbol.endswith("nbytes")
               for f in cf), [f.render() for f in new]
    assert any("_startree" in f.symbol and f.symbol.endswith("release")
               for f in cf), [f.render() for f in new]
    assert not any("_columns" in f.symbol for f in cf)


def test_conservation_chunkacct_store_must_reach_counter(tmp_path):
    """PR 17 mutable-staging obligation: every store into a
    ``self.*chunk*`` collection must reach the class's byte counter on
    EVERY path out of the method — an early return that skips the
    recount, or a method with no recount at all, grows the device image
    invisibly to the HBM budget."""
    new = _lint(tmp_path, """\
        class StagedChunks:
            def __init__(self):
                self._chunks = {}
                self._staged_bytes = 0

            def _recount(self):
                total = 0
                for a in self._chunks.values():
                    total += a
                self._staged_bytes = total

            def install_ok(self, key, arr):
                self._chunks[key] = arr
                self._recount()

            def install_bad(self, key, arr):
                self._chunks[key] = arr

            def install_branchy(self, key, arr, cond):
                self._chunks[key] = arr
                if cond:
                    return
                self._recount()

            def nbytes(self):
                total = 0
                for a in self._chunks.values():
                    total += a
                return max(total, self._staged_bytes)

            def release(self):
                self._chunks.clear()
                self._staged_bytes = 0
        """)
    cf = _by_checker(new, "conservation")
    assert any(f.symbol == "StagedChunks.install_bad:chunkacct"
               for f in cf), [f.render() for f in new]
    assert any(f.symbol == "StagedChunks.install_branchy:chunkacct"
               for f in cf), [f.render() for f in new]
    assert not any("install_ok" in f.symbol for f in cf), \
        [f.render() for f in cf]


def test_conservation_chunkacct_no_accounting_method_at_all(tmp_path):
    """A chunk-storing resident with nbytes()/release() but NO byte
    counter anywhere cannot discharge the obligation — every store is a
    finding (the counter is what residency accounting re-measures)."""
    new = _lint(tmp_path, """\
        class NoCounter:
            def __init__(self):
                self._chunks = {}

            def put(self, key, arr):
                self._chunks[key] = arr

            def nbytes(self):
                return len(self._chunks)

            def release(self):
                self._chunks.clear()
        """)
    cf = _by_checker(new, "conservation")
    assert any(f.symbol == "NoCounter.put:chunkacct"
               and "no byte-counter" in f.message
               for f in cf), [f.render() for f in new]


def test_conservation_idxacct_pin_must_reach_accounting(tmp_path):
    """PR 18 index-rung obligation: a ``.index_slice(...)`` call pins a
    freshly-built device idx array on a staged resident, so every
    fall-through path out of the function must reach a residency
    ``.account(...)`` call (or a direct ``*bytes*`` counter write) — a
    branch that returns early leaves the budget's running view predating
    the pinned slice. Exception paths are exempt (nbytes() walks the
    slice cache; the next refresh re-measures)."""
    new = _lint(tmp_path, """\
        def serve_ok(executor, staged, key, build, name, lease):
            idx = staged.index_slice(key, build)
            executor.residency.account(name, lease)
            return idx

        def serve_bad(executor, staged, key, build, name, lease):
            idx = staged.index_slice(key, build)
            return idx

        def serve_branchy(executor, staged, key, build, name, lease, hot):
            idx = staged.index_slice(key, build)
            if hot:
                return idx
            executor.residency.account(name, lease)
            return idx

        def serve_exc_ok(executor, staged, key, build, name, lease):
            try:
                idx = staged.index_slice(key, build)
                executor.residency.account(name, lease)
            except Exception:
                return None
            return idx
        """)
    cf = _by_checker(new, "conservation")
    assert any(f.symbol == "serve_bad:idxacct"
               for f in cf), [f.render() for f in new]
    assert any(f.symbol == "serve_branchy:idxacct"
               for f in cf), [f.render() for f in new]
    assert not any("serve_ok" in f.symbol for f in cf), \
        [f.render() for f in cf]
    assert not any("serve_exc_ok" in f.symbol for f in cf), \
        [f.render() for f in cf]


def test_conservation_catches_discarded_pop(tmp_path):
    new = _lint(tmp_path, CONSERVATION_PRELUDE + """\
        def drop(self, name):
            self._entries.pop(name, None)
""")
    cf = _by_checker(new, "conservation")
    assert any("drop" in f.symbol and "discard" in f.symbol
               for f in cf), [f.render() for f in new]


HOST_TIER_PRELUDE = """\
    class TieredManager:
        def __init__(self):
            self._entries = {}
            self._host_entries = {}
            self._staged_bytes = 0
            self._host_bytes = 0

        def _release_all(self, doomed):
            for r in doomed:
                r.release()

        def _release_host(self, e):
            self._host_bytes -= e.nbytes

        def get(self, name):
            e = self._entries.get(name)
            if e is not None:
                return e.resident
            return None

        def get_host(self, name):
            e = self._host_entries.get(name)
            if e is not None:
                return e.resident
            return None

"""


def test_conservation_host_tier_demote_without_account(tmp_path):
    """The host-tier half of the byte-accounting conservation family: a
    demotion that inserts the image into the host dict WITHOUT adjusting
    host bytes lets the running total drift from reality — the insert
    rule must extend to the host tier unchanged."""
    new = _lint(tmp_path, HOST_TIER_PRELUDE + """\
        def demote_bad(self, name, image):
            e = self._entries.pop(name, None)
            if e is not None:
                self._release_all([e.resident])
                self._host_entries[name] = image  # bytes never accounted

        def demote_ok(self, name, image):
            e = self._entries.pop(name, None)
            if e is not None:
                self._release_all([e.resident])
                self._host_entries[name] = image
                self._host_bytes += image.nbytes
""")
    cf = _by_checker(new, "conservation")
    assert any("demote_bad" in f.symbol and f.symbol.endswith("insert")
               for f in cf), [f.render() for f in new]
    assert not any("demote_ok" in f.symbol for f in cf)


def test_conservation_host_tier_pop_must_account(tmp_path):
    """Host-tier removal -> accounting (the new ``hostacct`` obligation):
    the host total is a RUNNING counter, so a promotion that pops an
    image and even releases it — but never subtracts its bytes — drifts
    the host budget forever. Accounting only on the try fall-through
    leaks on the handler path (exception edges included)."""
    new = _lint(tmp_path, HOST_TIER_PRELUDE + """\
        def promote_bad(self, name):
            he = self._host_entries.pop(name, None)
            if he is None:
                return None
            self._release_all([he.resident])  # released, NOT accounted
            return he.resident

        def promote_exc_leak(self, name):
            he = self._host_entries.pop(name, None)
            if he is None:
                return None
            try:
                self._validate(he)
            except ValueError:
                self._release_all([he.resident])
                return None  # handler path skips the accounting
            self._release_host(he)
            return he.resident

        def promote_ok(self, name):
            he = self._host_entries.pop(name, None)
            if he is None:
                return None
            self._release_host(he)
            return he.resident
""")
    cf = _by_checker(new, "conservation")
    assert any("promote_bad" in f.symbol and "hostacct" in f.symbol
               for f in cf), [f.render() for f in new]
    assert any("promote_exc_leak" in f.symbol and "hostacct" in f.symbol
               for f in cf), [f.render() for f in new]
    assert not any("promote_ok" in f.symbol and "hostacct" in f.symbol
                   for f in cf), [f.render() for f in new]


def test_conservation_spanpair_open_without_close(tmp_path):
    """The spanpair obligation: a span_begin assigned to a local must
    reach a span_end naming it on every path — an open that never closes
    corrupts the query's trace tree. With-statement spans and
    returned/stored spans create no obligation."""
    new = _lint(tmp_path, """\
        def open_no_close(rec):
            sp = rec.span_begin("x")
            do_work(sp)

        def open_ok_finally(rec):
            sp = rec.span_begin("x")
            try:
                do_work(sp)
            finally:
                rec.span_end(sp)

        def open_ok_with(rec):
            with rec.span("x"):
                do_work()

        def open_ok_returned(rec):
            sp = rec.span_begin("x")
            return sp

        def open_ok_stored(rec, stats):
            sp = rec.span_begin("x")
            stats._root_span = sp

        def open_ok_closure(rec):
            sp = rec.span_begin("x")

            def done(result):
                rec.span_end(sp)
                return result

            return done

        def discarded(rec):
            rec.span_begin("x")
            do_work()
""")
    cf = _by_checker(new, "conservation")
    assert any("open_no_close" in f.symbol and "spanpair" in f.symbol
               for f in cf), [f.render() for f in new]
    assert any("discarded" in f.symbol and "spanpair-discard" in f.symbol
               for f in cf), [f.render() for f in new]
    for ok in ("open_ok_finally", "open_ok_with", "open_ok_returned",
               "open_ok_stored", "open_ok_closure"):
        assert not any(ok in f.symbol for f in cf), \
            [f.render() for f in cf]


def test_conservation_spanpair_exception_edge(tmp_path):
    """A span_end that lives only on the try fall-through leaks the span
    on the handler path — exception edges are part of the obligation."""
    new = _lint(tmp_path, """\
        def exc_leak(rec):
            sp = rec.span_begin("x")
            try:
                do_work()
            except ValueError:
                return None
            rec.span_end(sp)

        def exc_ok(rec):
            sp = rec.span_begin("x")
            try:
                do_work()
            except ValueError:
                rec.span_end(sp)
                return None
            rec.span_end(sp)

        def none_guard_ok(rec, traced):
            sp = rec.span_begin("x") if traced else None
            try:
                do_work()
            finally:
                if sp is not None:
                    rec.span_end(sp)
""")
    cf = _by_checker(new, "conservation")
    assert any("exc_leak" in f.symbol and "spanpair" in f.symbol
               for f in cf), [f.render() for f in new]
    assert not any("exc_ok" in f.symbol for f in cf), \
        [f.render() for f in cf]
    assert not any("none_guard_ok" in f.symbol for f in cf), \
        [f.render() for f in cf]


# --------------------------------------------------------------------------
# CLI: --json / --families
# --------------------------------------------------------------------------

BAD_LOCK_SRC = """\
    import threading

    class C:
        def __init__(self):
            self._lock = threading.Lock()
            self._d = {}  # guarded-by: _lock

        def peek(self):
            return self._d.get("k")
    """


def test_cli_json_output(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent(BAD_LOCK_SRC))
    rc = lint_main([str(bad), "--json"])
    out = capsys.readouterr().out
    rows = [json.loads(line) for line in out.splitlines()]
    assert rc == 1 and rows
    assert set(rows[0]) == {"key", "family", "file", "line", "message"}
    assert rows[0]["family"] == "lock-guard"


def test_cli_families_filter(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent(BAD_LOCK_SRC))
    # the finding is lock-guard: a protocol-only run must not see it
    assert lint_main([str(bad), "--families", "protocol,sync"]) == 0
    assert lint_main([str(bad), "--families", "lock-guard"]) == 1
    assert lint_main([str(bad), "--families", "nonsense"]) == 2


# --------------------------------------------------------------------------
# decline family: pallas decline-reason drift (engine/pallas_kernels.py
# strings must resolve to registered ledger codes)
# --------------------------------------------------------------------------

def test_decline_catches_unclassifiable_ineligible(tmp_path):
    """A NEW _Ineligible message with no classify_decline rule would mint
    an ad-hoc sanitized code on the ledger — flagged at lint time."""
    new = _lint(tmp_path, """\
        class _Ineligible(Exception):
            pass

        def extract(plan):
            raise _Ineligible("some brand new unlisted obstacle")
        """, name="pallas_kernels.py")
    found = _by_checker(new, "decline")
    assert len(found) == 1
    assert "classify_decline" in found[0].message


def test_decline_catches_unregistered_code(tmp_path):
    """decline('...') literals are direct ledger codes: they must appear
    in tracing.DIRECT_DECLINE_CODES (or the rules table)."""
    new = _lint(tmp_path, """\
        def bind(decline):
            decline("pallas_brand_new_unregistered_code")
        """, name="pallas_kernels.py")
    found = _by_checker(new, "decline")
    assert len(found) == 1
    assert "DIRECT_DECLINE_CODES" in found[0].message


def test_decline_known_strings_are_clean(tmp_path):
    """Registered codes and classifiable messages pass; dynamic args are
    exempt (runtime namespacing covers them)."""
    new = _lint(tmp_path, """\
        class _Ineligible(Exception):
            pass

        def extract(plan, decline, op):
            decline("pallas_too_many_groups")
            if plan:
                raise _Ineligible("lut with too many runs")
            raise _Ineligible(op)   # dynamic: exempt
        """, name="pallas_kernels.py")
    assert not _by_checker(new, "decline")


def test_decline_only_scopes_pallas_kernels_module(tmp_path):
    """Other modules calling something named decline() are out of scope."""
    new = _lint(tmp_path, """\
        def f(decline):
            decline("not_a_pallas_code_at_all")
        """, name="other_module.py")
    assert not _by_checker(new, "decline")


# --------------------------------------------------------------------------
# device family (ISSUE 15): TPU-lowering obligations on the kernel
# builders — each acceptance mutation is a scratch copy of the REAL
# module with one seeded violation, and must yield exactly one finding
# --------------------------------------------------------------------------

def _real_src(rel):
    with open(os.path.join(PKG, *rel.split("/")), encoding="utf-8") as f:
        return f.read()


def _device_scratch(tmp_path, name, src):
    p = tmp_path / name
    p.write_text(src)
    new, _ = run_lint([str(p)])
    return _by_checker(new, "device")


def test_device_clean_on_real_builders(tmp_path):
    for rel, name in (("engine/pallas_kernels.py", "pallas_kernels.py"),
                      ("parallel/combine.py", "combine.py"),
                      ("parallel/reduce_device.py", "reduce_device.py"),
                      ("engine/plan.py", "plan.py"),
                      ("engine/startree_device.py", "startree_device.py")):
        hits = _device_scratch(tmp_path, name, _real_src(rel))
        assert not hits, (rel, [f.render() for f in hits])


def test_device_reduce_bad_axis_through_helper_param(tmp_path):
    """PR-16 seeded mutations: a literal axis at the dense-rung combine
    dispatch that is NOT the declared ``MERGE_AXIS`` — resolved
    interprocedurally through the helper's ``axis`` param (one mutation
    per combine flavor: the psum helper and the all_to_all helper),
    exactly one finding each."""
    src = _real_src("parallel/reduce_device.py")
    for target in ('_axis_reduce(v, op, MERGE_AXIS, mesh)',
                   '_slice_reduce(v, op, MERGE_AXIS, mesh)'):
        bad = src.replace(target, target.replace("MERGE_AXIS", '"rows"'))
        assert bad != src, \
            f"dense-rung combine dispatch moved ({target}); update fixture"
        hits = _device_scratch(tmp_path, "reduce_device.py", bad)
        assert len(hits) == 1 \
            and "not a declared mesh axis" in hits[0].message, \
            (target, [f.render() for f in hits])


def test_device_swapped_blockspec_dim(tmp_path):
    """Seeded mutation 1: a swapped BlockSpec dim — the lane (last) dim
    is no longer provably %128."""
    src = _real_src("engine/pallas_kernels.py")
    bad = src.replace(
        "pl.BlockSpec((Mf, G), lambda s, t: (0, 0), "
        "memory_space=pltpu.VMEM),",
        "pl.BlockSpec((G, Mf), lambda s, t: (0, 0), "
        "memory_space=pltpu.VMEM),")
    assert bad != src, "out-spec line moved; update the fixture"
    hits = _device_scratch(tmp_path, "pallas_kernels.py", bad)
    assert len(hits) == 1 and "lane dim" in hits[0].message, \
        [f.render() for f in hits]


def test_device_helper_concat_swap_flags_every_call_shape(tmp_path):
    """The block() helper's (1, 1) prefix swapped to a suffix puts a
    size-1 lane dim on every helper-built block — one finding per
    distinct call-site shape."""
    src = _real_src("engine/pallas_kernels.py")
    bad = src.replace("return pl.BlockSpec((1, 1) + shape0,",
                      "return pl.BlockSpec(shape0 + (1, 1),")
    assert bad != src
    hits = _device_scratch(tmp_path, "pallas_kernels.py", bad)
    assert len(hits) == 2, [f.render() for f in hits]
    assert all("lane dim" in f.message for f in hits)


def test_device_over_cap_ivs_lut(tmp_path):
    """Seeded mutation 2: an over-cap ivs LUT — the module's run cap
    outgrowing the pallas.lut.max.runs config table."""
    src = _real_src("engine/pallas_kernels.py")
    bad = src.replace("DEFAULT_LUT_RUN_CAP = 64",
                      "DEFAULT_LUT_RUN_CAP = 1024")
    assert bad != src
    hits = _device_scratch(tmp_path, "pallas_kernels.py", bad)
    assert len(hits) == 1 and "DEFAULT_PALLAS_LUT_MAX_RUNS" \
        in hits[0].message, [f.render() for f in hits]


def test_device_i64_inside_kernel_body(tmp_path):
    """Seeded mutation 3: an i64 op outside the blessed limb-reassembly
    pattern — here, inside the kernel body itself."""
    src = _real_src("engine/pallas_kernels.py")
    bad = src.replace(
        "out_seg[0, :] += mask.astype(jnp.int32).sum(axis=0, "
        "dtype=jnp.int32)",
        "out_seg[0, :] += mask.astype(jnp.int64).sum(axis=0, "
        "dtype=jnp.int32)")
    assert bad != src
    hits = _device_scratch(tmp_path, "pallas_kernels.py", bad)
    assert len(hits) == 1 and "Pallas kernel body" in hits[0].message, \
        [f.render() for f in hits]


def test_device_i64_outside_blessed_functions(tmp_path):
    src = _real_src("engine/pallas_kernels.py")
    bad = src.replace(
        "def _segment_params(pp: PallasPlan, staged: StagedSegment):\n"
        "    return jnp.concatenate([",
        "def _segment_params(pp: PallasPlan, staged: StagedSegment):\n"
        "    _w = jnp.int64(0)\n    return jnp.concatenate([")
    assert bad != src
    hits = _device_scratch(tmp_path, "pallas_kernels.py", bad)
    assert len(hits) == 1 and "blessed" in hits[0].message, \
        [f.render() for f in hits]


def test_device_mismatched_psum_axis(tmp_path):
    """Seeded mutation 4: a psum over an axis name the mesh never
    declared."""
    src = _real_src("parallel/combine.py")
    bad = src.replace('local = jax.lax.psum(local, DOC_AXIS)',
                      'local = jax.lax.psum(local, "docs")')
    assert bad != src
    hits = _device_scratch(tmp_path, "combine.py", bad)
    assert len(hits) == 1 and "'docs'" in hits[0].message, \
        [f.render() for f in hits]


def test_device_bad_axis_through_helper_param(tmp_path):
    """Interprocedural: a bad literal handed to _cross_reduce's axes
    param is flagged at the call site."""
    src = _real_src("parallel/combine.py")
    bad = src.replace(
        'seg_local = _cross_reduce(seg_local, "sum", (DOC_AXIS,), mesh)',
        'seg_local = _cross_reduce(seg_local, "sum", ("docs",), mesh)')
    assert bad != src
    hits = _device_scratch(tmp_path, "combine.py", bad)
    assert len(hits) == 1, [f.render() for f in hits]


def test_device_value_ref_count_drift(tmp_path):
    """value_limbs planes must size the ref blocks: a value-spec loop
    counting inputs instead of planes is the i64 read-someone-else's-
    plane bug."""
    src = _real_src("engine/pallas_kernels.py")
    bad = src.replace(
        "for _ in range(n_value_refs):\n        "
        "in_specs.append(block((RT, 128)))",
        "for _ in range(n_values):\n        "
        "in_specs.append(block((RT, 128)))")
    assert bad != src
    hits = _device_scratch(tmp_path, "pallas_kernels.py", bad)
    assert len(hits) == 1 and "value_limbs" in hits[0].message, \
        [f.render() for f in hits]


def test_device_narrow_drops_pow2(tmp_path):
    src = _real_src("engine/plan.py")
    bad = src.replace("    num_groups = _next_pow2(total)",
                      "    num_groups = total")
    assert bad != src
    hits = _device_scratch(tmp_path, "plan.py", bad)
    assert len(hits) == 1 and "_next_pow2" in hits[0].message, \
        [f.render() for f in hits]


def test_device_narrow_drops_capacity(tmp_path):
    src = _real_src("engine/plan.py")
    bad = src.replace(
        "    spec = (filter_spec, agg_specs, group_specs, num_groups, "
        "capacity)",
        "    spec = (filter_spec, agg_specs, group_specs, num_groups, "
        "4096)")
    assert bad != src
    hits = _device_scratch(tmp_path, "plan.py", bad)
    assert len(hits) == 1 and "capacity" in hits[0].message, \
        [f.render() for f in hits]


def test_device_startree_idx_pad_off_spec(tmp_path):
    src = _real_src("engine/startree_device.py")
    bad = src.replace("padded = np.zeros(capacity, dtype=np.int32)",
                      "padded = np.zeros(n, dtype=np.int32)")
    assert bad != src
    hits = _device_scratch(tmp_path, "startree_device.py", bad)
    assert len(hits) == 1 and "capacity" in hits[0].message, \
        [f.render() for f in hits]


# --------------------------------------------------------------------------
# --changed mode + the wall-clock budget
# --------------------------------------------------------------------------

def _git(cwd, *args):
    import subprocess

    subprocess.run(["git", *args], cwd=cwd, check=True,
                   capture_output=True,
                   env={**os.environ,
                        "GIT_AUTHOR_NAME": "t", "GIT_AUTHOR_EMAIL": "t@t",
                        "GIT_COMMITTER_NAME": "t",
                        "GIT_COMMITTER_EMAIL": "t@t"})


def test_changed_selects_reverse_and_forward_deps(tmp_path):
    """--changed lints the changed file, its reverse importers
    (transitively), and one forward hop of context for every selected
    file — not the whole tree."""
    from pinot_tpu.tools.lint.core import select_changed

    pkg = tmp_path / "mypkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "base.py").write_text("X = 1\n")
    (pkg / "mid.py").write_text("from mypkg.base import X\nY = X\n")
    (pkg / "top.py").write_text("from mypkg import mid\nZ = mid.Y\n")
    (pkg / "island.py").write_text("W = 9\n")
    _git(tmp_path, "init", "-q")
    _git(tmp_path, "add", ".")
    _git(tmp_path, "commit", "-qm", "seed")
    (pkg / "mid.py").write_text("from mypkg.base import X\nY = X + 1\n")
    got = {os.path.basename(p)
           for p in select_changed("HEAD", str(pkg))}
    # mid changed; top imports mid (reverse, transitive); base is mid's
    # forward context (and __init__ is top's); island untouched
    assert got == {"mid.py", "top.py", "base.py", "__init__.py"}

    _git(tmp_path, "add", ".")
    _git(tmp_path, "commit", "-qm", "mid")
    assert select_changed("HEAD", str(pkg)) == []


def test_changed_cli_on_this_repo():
    """The CLI path end-to-end against the real repo: HEAD-relative
    selection runs and stays zero-finding (same gate as the package)."""
    assert lint_main(["--changed", "HEAD"]) == 0


def test_whole_package_wall_clock_budget():
    """The whole-package run must stay CI-viable as the dataflow tier
    grows — v4 added three more families (decisions totality over the
    ledger scope CFGs, the exactness proof guards, config-key
    conformance with the README table check) and v5 adds the whole-
    program thread-topology family, paid for by the shared parse/CFG
    tier (one ast.parse + one CFG per function, reused by all 14
    families): a generous multiple of the measured wall clock, but a
    hard ceiling — a quadratic blow-up in a new family fails here
    before it fails the CI budget."""
    import time

    t0 = time.perf_counter()
    run_lint([PKG], baseline=DEFAULT_BASELINE)
    elapsed = time.perf_counter() - t0
    assert elapsed < 120, f"whole-package lint took {elapsed:.1f}s"


# --------------------------------------------------------------------------
# v4: decision-path totality (seeded mutations, each exactly one finding)
# --------------------------------------------------------------------------

def _lint_family(tmp_path, source, family, name="fixture.py"):
    p = tmp_path / name
    p.write_text(textwrap.dedent(source))
    new, _accepted = run_lint([str(p)], families=[family])
    return new


def test_decisions_dropped_record_on_return_path(tmp_path):
    """A scoped rung probe with one decline exit that never reaches the
    ledger: exactly the silent-fallback shape the family exists for."""
    new = _lint_family(tmp_path, """\
        def record_decision(stats, point, chosen, declined, reason):
            pass

        def _try_star_tree(self, ctx, aggs, seg, stats):
            tree = seg.tree
            if tree is None:
                return None
            record_decision(stats, "startree", "scan", "startree", "tree1")
            return None
        """, "decisions", name="executor.py")
    assert len(new) == 1
    assert new[0].checker == "decisions"
    assert "_try_star_tree" in new[0].symbol


def test_decisions_dropped_record_on_exception_edge(tmp_path):
    """A handler that swallows the rung's failure and returns None must
    record on its own — the exception edge carries the raising
    statement's PRE-state, so the record after the try doesn't count."""
    new = _lint_family(tmp_path, """\
        def record_decision(stats, point, chosen, declined, reason):
            pass

        def _try_star_tree(self, ctx, aggs, seg, stats):
            try:
                res = seg.walk()
            except ValueError:
                return None
            record_decision(stats, "startree", "scan", "startree", "tree1")
            return res
        """, "decisions", name="executor.py")
    assert len(new) == 1
    assert "exit" in new[0].symbol


def test_decisions_discharges_are_clean(tmp_path):
    """The three legitimate unrecorded-exit shapes: the 'not a decline'
    annotation, the hook-credited pass-through (x = f(on_decline=...)
    then `if x is None: return None`), and the vacuous-hook guard."""
    new = _lint_family(tmp_path, """\
        def record_decision(stats, point, chosen, declined, reason):
            pass

        def _try_star_tree(self, ctx, aggs, seg, stats, on_decline=None):
            if seg is None:
                return None  # no segment shipped: not a decline
            pick = self._pick(seg, on_decline=on_decline)
            if pick is None:
                return None
            if on_decline is None:
                return None
            record_decision(stats, "startree", "scan", "startree", "tree1")
            return None
        """, "decisions", name="executor.py")
    assert not new, [f.render() for f in new]


def test_decisions_all_mode_checks_every_exit(tmp_path):
    """`all`-mode scope (routing pruners, the hybrid split): a non-None
    return without a record is a finding too."""
    new = _lint_family(tmp_path, """\
        def record_decision(stats, point, chosen, declined, reason):
            pass

        def _time_prune(self, ctx, segments):
            if not segments:
                return segments
            record_decision(None, "routing", "pruned", "all_servers",
                            "time_prune")
            return [s for s in segments if s.live]
        """, "decisions", name="routing.py")
    assert len(new) == 1
    assert "_time_prune" in new[0].symbol


def test_decisions_unregistered_reason_literal(tmp_path):
    """Every literal reason at a scoped recorder call must discharge
    against tracing.reason_registry()."""
    new = _lint_family(tmp_path, """\
        def record_decision(stats, point, chosen, declined, reason):
            pass

        def _try_star_tree(self, ctx):
            record_decision(None, "startree", "scan", "startree",
                            "totally_bogus_reason")
            return 1
        """, "decisions", name="executor.py")
    assert len(new) == 1
    assert "totally_bogus_reason" in new[0].symbol


# --------------------------------------------------------------------------
# v4: numeric-exactness proof guards
# --------------------------------------------------------------------------

def test_exactness_raw_wide_literal(tmp_path):
    new = _lint_family(tmp_path, """\
        def fold_cap(n):
            return n < 1 << 62
        """, "exactness")
    assert len(new) == 1
    assert "wide_literal" in new[0].symbol


def test_exactness_power_form_also_banned(tmp_path):
    new = _lint_family(tmp_path, """\
        LIMIT = 2 ** 53
        """, "exactness")
    assert len(new) == 1


def test_exactness_dtype_mismatched_guard(tmp_path):
    """Comparing a float path against an i64 bound proves nothing: no
    integer-dtype evidence anywhere in the function."""
    new = _lint_family(tmp_path, """\
        from pinot_tpu.common.bounds import I64_FOLD_BOUND

        def check(arr):
            total = arr.sum() * 2.5
            return total < I64_FOLD_BOUND
        """, "exactness")
    assert len(new) == 1
    assert "i64_evidence" in new[0].symbol


def test_exactness_guard_deletion_is_a_finding(tmp_path):
    """The known sum-reassembly sites must keep a bounds-constant guard
    even after every raw literal is gone."""
    new = _lint_family(tmp_path, """\
        def _finish_group_by(self):
            return self._rows
        """, "exactness", name="reduce.py")
    assert len(new) == 1
    assert "guard_missing" in new[0].symbol


def test_exactness_real_guard_shape_is_clean(tmp_path):
    new = _lint_family(tmp_path, """\
        from pinot_tpu.common.bounds import I64_FOLD_BOUND

        def _finish_group_by(self):
            if self._gb_i64_bound >= I64_FOLD_BOUND:
                return None
            return self._rows
        """, "exactness", name="reduce.py")
    assert not new, [f.render() for f in new]


# --------------------------------------------------------------------------
# v4: config-key conformance
# --------------------------------------------------------------------------

def test_configkeys_undeclared_inline_key(tmp_path):
    new = _lint_family(tmp_path, """\
        def setup(cfg):
            return cfg.get_bool("pinot.server.query.mystery.enabled",
                                False)
        """, "configkeys")
    assert len(new) == 1
    assert "pinot.server.query.mystery.enabled" in new[0].symbol


def test_configkeys_declared_keys_resolve_clean(tmp_path):
    new = _lint_family(tmp_path, """\
        from pinot_tpu.spi.config import CommonConstants

        def setup(cfg):
            return cfg.get_int(CommonConstants.RUNNER_THREADS_KEY, 8)
        """, "configkeys")
    assert not new, [f.render() for f in new]


def _configkeys_tree(tmp_path, config_src, reader_src, readme=None):
    pkg = tmp_path / "pkg"
    (pkg / "spi").mkdir(parents=True)
    (pkg / "spi" / "config.py").write_text(textwrap.dedent(config_src))
    (pkg / "reader.py").write_text(textwrap.dedent(reader_src))
    if readme is not None:
        (tmp_path / "README.md").write_text(textwrap.dedent(readme))
    new, _ = run_lint([str(pkg)], families=["configkeys"])
    return new


def test_configkeys_declared_but_unread_key(tmp_path):
    new = _configkeys_tree(tmp_path, """\
        class CommonConstants:
            USED_KEY = "pinot.server.query.used"
            GHOST_KEY = "pinot.server.query.ghost"
        """, """\
        from pkg.spi.config import CommonConstants

        def setup(cfg):
            return cfg.get(CommonConstants.USED_KEY, None)
        """)
    assert len(new) == 1
    assert "unread:GHOST_KEY" in new[0].symbol


def test_configkeys_stale_readme_default(tmp_path):
    new = _configkeys_tree(tmp_path, """\
        class CommonConstants:
            RUNNER_THREADS_KEY = "pinot.server.query.runner.threads"
            DEFAULT_RUNNER_THREADS = 8
        """, """\
        from pkg.spi.config import CommonConstants

        def setup(cfg):
            return cfg.get_int(CommonConstants.RUNNER_THREADS_KEY, 8)
        """, readme="""\
        # fixture

        <!-- config-keys:begin -->
        | key | default | controls |
        |---|---|---|
        | `pinot.server.query.runner.threads` | `4` | runner pool |
        <!-- config-keys:end -->
        """)
    assert len(new) == 1
    assert "readme:stale:RUNNER_THREADS_KEY" in new[0].symbol


def test_configkeys_readme_row_matching_code_is_clean(tmp_path):
    new = _configkeys_tree(tmp_path, """\
        class CommonConstants:
            RUNNER_THREADS_KEY = "pinot.server.query.runner.threads"
            DEFAULT_RUNNER_THREADS = 8
        """, """\
        from pkg.spi.config import CommonConstants

        def setup(cfg):
            return cfg.get_int(CommonConstants.RUNNER_THREADS_KEY, 8)
        """, readme="""\
        # fixture

        <!-- config-keys:begin -->
        | key | default | controls |
        |---|---|---|
        | `pinot.server.query.runner.threads` | `8` | runner pool |
        <!-- config-keys:end -->
        """)
    assert not new, [f.render() for f in new]


def test_cli_sarif_output(tmp_path, capsys):
    """--sarif: one SARIF 2.1.0 run, one rule per family, results carry
    the stable baseline key as a partial fingerprint."""
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent("""\
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._d = {}  # guarded-by: _lock

            def peek(self):
                return self._d.get("k")
        """))
    assert lint_main([str(bad), "--sarif", "--no-baseline"]) == 1
    log = json.loads(capsys.readouterr().out)
    assert log["version"] == "2.1.0"
    run = log["runs"][0]
    assert run["tool"]["driver"]["name"] == "graftlint"
    rule_ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
    assert {"decisions", "exactness", "configkeys", "threads"} <= rule_ids
    res = run["results"][0]
    assert res["ruleId"] == "lock-guard"
    assert res["locations"][0]["physicalLocation"]["region"]["startLine"]
    assert res["partialFingerprints"]["graftlintKey/v1"].startswith(
        "lock-guard:")


# --------------------------------------------------------------------------
# suppression machinery
# --------------------------------------------------------------------------

def test_inline_ignore_suppresses_with_reason(tmp_path):
    p = tmp_path / "sup.py"
    p.write_text(textwrap.dedent("""\
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._d = {}  # guarded-by: _lock

            def peek(self):
                return self._d.get("k")  # lint: ignore[lock-guard] — stats-only racy read
        """))
    new, accepted = run_lint([str(p)])
    assert not new
    assert len(accepted) == 1


def test_baseline_suppresses_by_stable_key(tmp_path):
    src = textwrap.dedent("""\
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._d = {}  # guarded-by: _lock

            def peek(self):
                return self._d.get("k")
        """)
    p = tmp_path / "base.py"
    p.write_text(src)
    new, _ = run_lint([str(p)])
    assert len(new) == 1
    bl = tmp_path / "baseline.json"
    bl.write_text('{"entries": [{"key": "%s", "reason": "test"}]}'
                  % new[0].key)
    new2, accepted2 = run_lint([str(p)], baseline=str(bl))
    assert not new2 and len(accepted2) == 1


# --------------------------------------------------------------------------
# v5: thread-topology race analysis (seeded mutations, each exactly one
# finding; the real modules stay clean under the same rules)
# --------------------------------------------------------------------------

def test_threads_unguarded_cross_role_write(tmp_path):
    """A daemon sampler thread writing a field the request path reads,
    with no lock anywhere: the core race the family exists for."""
    new = _lint_family(tmp_path, """\
        import threading

        class Sampler:
            def __init__(self):
                self.ticks = 0
                self._thread = None

            def start(self):
                self._thread = threading.Thread(
                    target=self._loop, name="telemetry-sampler-0",
                    daemon=True)
                self._thread.start()

            def _loop(self):
                self.ticks += 1

            def snapshot(self):
                return self.ticks
        """, "threads")
    assert len(new) == 1, [f.render() for f in new]
    assert "Sampler.ticks" in new[0].key
    assert "sampler" in new[0].message and "request" in new[0].message


def test_threads_role_widened_by_new_submit_site(tmp_path):
    """A worker confined to the prefetch thread is clean; adding ONE
    ``pool.submit`` call from the public surface widens its role set and
    the previously-confined field becomes a finding."""
    confined = """\
        import threading

        class Prefetcher:
            def __init__(self):
                self.staged = 0
                self._thread = None

            def start(self):
                self._thread = threading.Thread(
                    target=self._drain, name="hbm-prefetch-0", daemon=True)
                self._thread.start()

            def _drain(self):
                self.staged += 1
        """
    assert _lint_family(tmp_path, confined, "threads") == []
    new = _lint_family(tmp_path, confined + """\

            def flush(self, pool):
                pool.submit(self._drain)
        """, "threads", name="widened.py")
    assert len(new) == 1, [f.render() for f in new]
    assert "Prefetcher.staged" in new[0].key


def test_threads_post_spawn_write_to_immutable_field(tmp_path):
    """Publish-before-spawn: a config field written before the thread
    starts is proven immutable-after-publish; moving the write below
    ``start()`` breaks the proof and is a finding."""
    new = _lint_family(tmp_path, """\
        import threading

        class Beat:
            def __init__(self):
                self.interval = 1.0
                self._thread = None

            def boot(self, interval):
                self.interval = interval
                self._thread = threading.Thread(
                    target=self._tick, name="heartbeat-0", daemon=True)
                self._thread.start()

            def _tick(self):
                return self.interval
        """, "threads")
    assert new == [], [f.render() for f in new]
    new = _lint_family(tmp_path, """\
        import threading

        class Beat:
            def __init__(self):
                self.interval = 1.0
                self._thread = None

            def boot(self, interval):
                self._thread = threading.Thread(
                    target=self._tick, name="heartbeat-0", daemon=True)
                self._thread.start()
                self.interval = interval

            def _tick(self):
                return self.interval
        """, "threads", name="postspawn.py")
    assert len(new) == 1, [f.render() for f in new]
    assert "Beat.interval" in new[0].key


def test_threads_stale_race_ok_on_guarded_field(tmp_path):
    """A ``# race-ok:`` on a field that IS lock-guarded is a dead
    annotation — the waiver must be removed, not accumulated."""
    new = _lint_family(tmp_path, """\
        import threading

        class Guarded:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0  # guarded-by: _lock
                self._thread = None

            def start(self):
                self._thread = threading.Thread(
                    target=self._loop, name="telemetry-sampler-0",
                    daemon=True)
                self._thread.start()

            def _loop(self):
                with self._lock:
                    self.n = self.n + 1  # race-ok: single_writer

            def snapshot(self):
                with self._lock:
                    return self.n
        """, "threads")
    assert len(new) == 1, [f.render() for f in new]
    assert "Guarded.n:race-ok-dead" in new[0].key


def test_threads_race_ok_reason_must_be_registered(tmp_path):
    """A waiver only counts with a reason from
    ``tracing.RACE_OK_REASONS``; an ad-hoc reason is itself a finding,
    and a registered one silences the race."""
    racy = """\
        import threading

        class Loose:
            def __init__(self):
                self.flag = False
                self._thread = None

            def start(self):
                self._thread = threading.Thread(
                    target=self._loop, name="telemetry-sampler-0",
                    daemon=True)
                self._thread.start()

            def _loop(self):
                self.flag = True  # race-ok: %s

            def done(self):
                return self.flag
        """
    new = _lint_family(tmp_path, racy % "because_i_said_so", "threads")
    assert len(new) == 1, [f.render() for f in new]
    assert "Loose.flag:race-ok-reason" in new[0].key
    assert _lint_family(tmp_path, racy % "single_writer", "threads",
                        name="waived.py") == []


def test_threads_spawn_graph_rules(tmp_path):
    """Spawn sites carry obligations of their own: every thread needs a
    role-mapped name, and every target must resolve statically."""
    new = _lint_family(tmp_path, """\
        import threading

        def _work():
            pass

        def unnamed():
            threading.Thread(target=_work).start()

        def opaque(fn):
            threading.Thread(target=fn, name="heartbeat-0").start()
        """, "threads")
    keys = {f.key for f in new}
    assert any(k.endswith("spawn:unnamed:role") for k in keys), keys
    assert any(k.endswith("spawn:opaque:target") for k in keys), keys


def test_threads_real_modules_stay_clean():
    """The whole package under the threads family alone: every true
    positive found at landing was fixed or waived with a registered
    reason — none baselined."""
    new, _ = run_lint([PKG], families=["threads"])
    assert new == [], [f.render() for f in new]


def test_threads_changed_scope_sees_package_spawn_graph(tmp_path):
    """--changed correctness for whole-program families: the spawn graph
    is computed package-wide, findings are scoped afterwards. A spawn-
    site edit in file A surfaces the role violation in UNTOUCHED file B;
    scoping to A alone filters B's finding out; and a subset run without
    the whole-program root is blind to the package's spawn graph."""
    pkg = tmp_path / "mypkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "b.py").write_text(textwrap.dedent("""\
        class Store:
            def __init__(self):
                self.n = 0

            def bump(self):
                self.n += 1

            def read(self):
                return self.n
        """))
    a_seed = textwrap.dedent("""\
        import threading

        from mypkg.b import Store

        STORE = Store()

        def _loop():
            STORE.bump()
        """)
    (pkg / "a.py").write_text(a_seed)
    _git(tmp_path, "init", "-q")
    _git(tmp_path, "add", ".")
    _git(tmp_path, "commit", "-qm", "seed")

    # the edit: a.py gains a sampler-thread spawn site for _loop
    (pkg / "a.py").write_text(a_seed + textwrap.dedent("""\

        def start():
            threading.Thread(target=_loop, name="telemetry-sampler-0",
                             daemon=True).start()
        """))
    from pinot_tpu.tools.lint.core import select_changed

    sel = select_changed("HEAD", str(pkg))
    assert {os.path.basename(p) for p in sel} >= {"a.py", "b.py"}
    new, _ = run_lint(sel, families=["threads"],
                      whole_program_root=str(pkg))
    assert len(new) == 1, [f.render() for f in new]
    assert "Store.n" in new[0].key and new[0].path.endswith("b.py")

    # scope to a.py only: the b.py finding is out of scope
    new, _ = run_lint([str(pkg / "a.py")], families=["threads"],
                      whole_program_root=str(pkg))
    assert new == [], [f.render() for f in new]

    # no whole-program root: the subset never sees a.py's spawn site
    new, _ = run_lint([str(pkg / "b.py")], families=["threads"])
    assert new == [], [f.render() for f in new]

    # b.py alone IN scope still inherits the package spawn graph
    new, _ = run_lint([str(pkg / "b.py")], families=["threads"],
                      whole_program_root=str(pkg))
    assert len(new) == 1 and new[0].path.endswith("b.py")


# --------------------------------------------------------------------------
# v5: the shared parse/CFG tier every family reuses
# --------------------------------------------------------------------------

def test_module_cache_reuses_parses(tmp_path):
    """load_modules serves the SAME Module object for unchanged source
    (13+ families re-enter it per run) and invalidates on content — not
    mtime, which lies on fast rewrites."""
    from pinot_tpu.tools.lint.core import load_modules

    p = tmp_path / "m.py"
    p.write_text("X = 1\n")
    ctx1, _ = load_modules([str(p)])
    ctx2, _ = load_modules([str(p)])
    assert ctx1.modules[0] is ctx2.modules[0]
    p.write_text("X = 2\n")
    ctx3, _ = load_modules([str(p)])
    assert ctx3.modules[0] is not ctx2.modules[0]


def test_cfg_memo_returns_identical_graphs():
    """build_cfg memoizes per function node: the dataflow families share
    one CFG instead of rebuilding it per family."""
    import ast as _ast

    from pinot_tpu.tools.lint.dataflow import build_cfg

    fn = _ast.parse(
        "def f(x):\n    if x:\n        return 1\n    return 0\n").body[0]
    assert build_cfg(fn) is build_cfg(fn)
