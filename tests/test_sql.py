"""SQL parser + query context + optimizer tests
(mirrors pinot-common CalciteSqlCompilerTest coverage areas)."""

import pytest

from pinot_tpu.query import (
    FilterOp,
    Function,
    Identifier,
    Literal,
    PredicateType,
    SqlParseError,
    compile_query,
    parse_sql,
)
from pinot_tpu.query.optimizer import like_to_regex


class TestParser:
    def test_basic_selection(self):
        q = parse_sql("SELECT a, b FROM tbl LIMIT 5")
        assert q.table == "tbl"
        assert [str(e) for e, _ in q.select] == ["a", "b"]
        assert q.limit == 5 and q.offset == 0

    def test_star(self):
        q = parse_sql("select * from tbl")
        assert q.select[0][0] == Identifier("*")

    def test_default_limit_is_10(self):
        assert parse_sql("SELECT a FROM t").limit == 10

    def test_aliases(self):
        q = parse_sql("SELECT a AS x, sum(b) total FROM t GROUP BY x")
        assert q.select[0][1] == "x"
        assert q.select[1][1] == "total"

    def test_where_comparisons(self):
        q = parse_sql("SELECT a FROM t WHERE b = 3 AND c > 1.5 AND d <= 'x'")
        node = q.where
        assert node.op is FilterOp.AND
        types = [c.predicate.type for c in node.children]
        assert types == [PredicateType.EQ, PredicateType.RANGE, PredicateType.RANGE]
        rng = node.children[1].predicate
        assert rng.lower == 1.5 and not rng.lower_inclusive and rng.upper is None

    def test_swapped_comparison(self):
        q = parse_sql("SELECT a FROM t WHERE 5 < b")
        p = q.where.predicate
        assert p.type is PredicateType.RANGE and p.lower == 5

    def test_between_in_like(self):
        q = parse_sql("SELECT a FROM t WHERE a BETWEEN 1 AND 10 "
                      "AND b IN ('x','y') AND c NOT IN (1) AND d LIKE 'ab%'")
        ps = [c.predicate for c in q.where.children]
        assert ps[0].type is PredicateType.RANGE and ps[0].lower_inclusive and ps[0].upper_inclusive
        assert ps[1].type is PredicateType.IN and ps[1].values == ("x", "y")
        assert ps[2].type is PredicateType.NOT_IN
        assert ps[3].type is PredicateType.LIKE

    def test_is_null(self):
        q = parse_sql("SELECT a FROM t WHERE b IS NULL OR c IS NOT NULL")
        ps = [c.predicate for c in q.where.children]
        assert ps[0].type is PredicateType.IS_NULL
        assert ps[1].type is PredicateType.IS_NOT_NULL

    def test_not_and_grouping(self):
        q = parse_sql("SELECT a FROM t WHERE NOT (a = 1 OR b = 2) AND c = 3")
        assert q.where.op is FilterOp.AND
        assert q.where.children[0].op is FilterOp.NOT
        assert q.where.children[0].children[0].op is FilterOp.OR

    def test_parenthesized_arithmetic_in_predicate(self):
        q = parse_sql("SELECT a FROM t WHERE (a + 1) * 2 > 6")
        p = q.where.predicate
        assert p.type is PredicateType.RANGE
        assert str(p.lhs) == "times(plus(a,1),2)"

    def test_function_predicates(self):
        q = parse_sql("SELECT a FROM t WHERE regexp_like(b, '^x.*') AND text_match(c, 'foo')")
        ps = [c.predicate for c in q.where.children]
        assert ps[0].type is PredicateType.REGEXP_LIKE
        assert ps[1].type is PredicateType.TEXT_MATCH

    def test_arithmetic_canonical_functions(self):
        q = parse_sql("SELECT a + b * 2 - c FROM t")
        assert str(q.select[0][0]) == "minus(plus(a,times(b,2)),c)"

    def test_unary_minus(self):
        q = parse_sql("SELECT a FROM t WHERE b > -5")
        assert q.where.predicate.lower == -5

    def test_string_escapes(self):
        q = parse_sql("SELECT a FROM t WHERE b = 'it''s'")
        assert q.where.predicate.value == "it's"

    def test_quoted_identifiers(self):
        q = parse_sql('SELECT "select" FROM t WHERE "group" = 1')
        assert str(q.select[0][0]) == "select"

    def test_order_limit_offset(self):
        q = parse_sql("SELECT a FROM t ORDER BY a DESC, b LIMIT 7 OFFSET 3")
        assert not q.order_by[0].ascending and q.order_by[1].ascending
        assert q.limit == 7 and q.offset == 3
        q2 = parse_sql("SELECT a FROM t LIMIT 3, 7")  # MySQL style
        assert q2.limit == 7 and q2.offset == 3

    def test_options(self):
        q = parse_sql("SELECT a FROM t OPTION(timeoutMs=100, useStarTree=false)")
        assert q.options == {"timeoutMs": "100", "useStarTree": "false"}

    def test_count_distinct_rewrite(self):
        q = parse_sql("SELECT COUNT(DISTINCT a) FROM t")
        assert str(q.select[0][0]) == "distinctcount(a)"

    def test_case_when(self):
        q = parse_sql("SELECT CASE WHEN a > 1 THEN 'hi' ELSE 'lo' END FROM t")
        f = q.select[0][0]
        assert isinstance(f, Function) and f.name == "case"

    def test_distinct(self):
        assert parse_sql("SELECT DISTINCT a, b FROM t").distinct

    def test_trailing_semicolon(self):
        assert parse_sql("SELECT a FROM t;").table == "t"

    def test_errors(self):
        for bad in ["SELECT", "SELECT a", "SELECT a FROM", "SELECT a FROM t WHERE",
                    "SELECT a FROM t WHERE b ==", "SELECT a FROM t garbage here",
                    "SELECT a FROM t WHERE b = c"]:
            with pytest.raises(SqlParseError):
                parse_sql(bad)


class TestQueryContext:
    def test_aggregation_extraction(self):
        ctx = compile_query("SELECT sum(a), max(b), count(*) FROM t")
        assert [f.name for f in ctx.aggregations] == ["sum", "max", "count"]
        assert ctx.is_aggregation and not ctx.is_group_by

    def test_post_aggregation(self):
        ctx = compile_query("SELECT sum(a) / count(a) FROM t")
        assert [f.name for f in ctx.aggregations] == ["sum", "count"]

    def test_group_by_alias_and_ordinal(self):
        ctx = compile_query("SELECT team t, sum(runs) FROM x GROUP BY 1 ORDER BY 2 DESC")
        assert str(ctx.group_by[0]) == "team"
        assert str(ctx.order_by[0].expr) == "sum(runs)"

    def test_having_aggregations_collected(self):
        ctx = compile_query("SELECT team, sum(r) FROM x GROUP BY team HAVING min(r) > 2")
        assert {f.name for f in ctx.aggregations} == {"sum", "min"}

    def test_referenced_columns(self):
        ctx = compile_query(
            "SELECT sum(a) FROM t WHERE b = 1 GROUP BY c ORDER BY sum(a)")
        assert ctx.referenced_columns() == ["a", "b", "c"]

    def test_count_star_columns(self):
        ctx = compile_query("SELECT count(*) FROM t")
        assert ctx.referenced_columns() == []

    def test_selection_query(self):
        ctx = compile_query("SELECT a, b FROM t WHERE c > 1 ORDER BY a LIMIT 5")
        assert ctx.is_selection

    def test_percentile_variants(self):
        ctx = compile_query("SELECT percentile95(lat), percentiletdigest90(lat) FROM t")
        assert [f.name for f in ctx.aggregations] == ["percentile95", "percentiletdigest90"]


class TestOptimizer:
    def test_flatten_and(self):
        ctx = compile_query("SELECT a FROM t WHERE (a=1 AND b=2) AND c=3")
        assert ctx.filter.op is FilterOp.AND
        assert len(ctx.filter.children) == 3

    def test_merge_eq_to_in(self):
        ctx = compile_query("SELECT a FROM t WHERE b='x' OR b='y' OR b='z'")
        p = ctx.filter.predicate
        assert p.type is PredicateType.IN
        assert set(p.values) == {"x", "y", "z"}

    def test_merge_ranges(self):
        ctx = compile_query("SELECT a FROM t WHERE b > 1 AND b <= 10 AND b >= 2")
        p = ctx.filter.predicate
        assert p.type is PredicateType.RANGE
        assert p.lower == 2 and p.lower_inclusive
        assert p.upper == 10 and p.upper_inclusive

    def test_like_rewrite(self):
        ctx = compile_query("SELECT a FROM t WHERE b LIKE 'ab%c_'")
        p = ctx.filter.predicate
        assert p.type is PredicateType.REGEXP_LIKE
        assert p.value == "^ab.*c.$"

    def test_like_to_regex_escaping(self):
        assert like_to_regex("a.b%") == r"^a\.b.*$"

    def test_constant_folding(self):
        ctx = compile_query("SELECT a + 2 * 3 FROM t")
        assert str(ctx.select_expressions[0]) == "plus(a,6)"

    def test_folding_consistent_across_clauses(self):
        # select/order_by/having/where must fold identically (expression
        # identity keys the jit cache and result-column matching)
        ctx = compile_query("SELECT sum(a) * (1 + 1) FROM t "
                            "WHERE b > 2 + 3 ORDER BY sum(a) * (1 + 1)")
        assert ctx.select_expressions[0] == ctx.order_by[0].expr
        assert ctx.filter.predicate.lower == 5

    def test_constant_order_by_is_not_ordinal(self):
        # ORDER BY 1 + 1 is a constant sort key, not ordinal 2 (regression)
        ctx = compile_query("SELECT a, b FROM t ORDER BY 1 + 1")
        assert str(ctx.order_by[0].expr) == "2"

    def test_ordinal_only_at_top_level(self):
        # ORDER BY a + 1 is arithmetic, not ordinal 1 (regression)
        ctx = compile_query("SELECT a, b FROM t ORDER BY a + 1")
        assert str(ctx.order_by[0].expr) == "plus(a,1)"
        ctx2 = compile_query("SELECT count(*) FROM t GROUP BY mod(a, 2)")
        assert str(ctx2.group_by[0]) == "mod(a,2)"

    def test_mixed_type_range_merge_survives(self):
        # must not crash with TypeError (regression)
        ctx = compile_query("SELECT a FROM t WHERE b > 1 AND b > 'x'")
        assert len(ctx.filter.children) == 2

    def test_fractional_limit_rejected(self):
        with pytest.raises(SqlParseError):
            parse_sql("SELECT a FROM t LIMIT 1.5")


class TestExplainPlan:
    """EXPLAIN PLAN FOR (ref: ExplainPlanDataTableReducer)."""

    def test_parse_flag(self):
        from pinot_tpu.query import compile_query

        ctx = compile_query("explain plan for SELECT count(*) FROM t")
        assert ctx.explain
        assert not compile_query("SELECT count(*) FROM t").explain

    def test_tree_shape(self):
        from pinot_tpu.query import compile_query
        from pinot_tpu.query.explain import explain_rows

        rows = explain_rows(compile_query(
            "EXPLAIN PLAN FOR SELECT region, sum(qty) FROM s "
            "WHERE year > 2020 GROUP BY region"))
        ops = [r[0] for r in rows]
        assert ops[0].startswith("BROKER_REDUCE")
        assert any(o.startswith("COMBINE_GROUP_BY") for o in ops)
        assert any(o.startswith("GROUP_BY") for o in ops)
        assert any(o.startswith("FILTER_RANGE") for o in ops)
        # parent ids form a tree rooted at -1
        ids = {r[1] for r in rows}
        assert all(r[2] in ids or r[2] == -1 for r in rows)

    def test_broker_explain_endpoint(self, tmp_path):
        from pinot_tpu.segment import SegmentBuilder
        from pinot_tpu.spi import DataType, FieldSpec, FieldType, Schema
        from pinot_tpu.spi.table import TableConfig
        from pinot_tpu.tools.cluster import EmbeddedCluster

        schema = Schema("ex", [
            FieldSpec("k", DataType.STRING),
            FieldSpec("v", DataType.LONG, FieldType.METRIC)])
        cluster = EmbeddedCluster(data_dir=str(tmp_path / "c"))
        try:
            cluster.create_table(TableConfig(table_name="ex"), schema)
            SegmentBuilder(schema, "ex_0").build(
                {"k": ["a", "b"] * 50, "v": list(range(100))},
                str(tmp_path))
            cluster.upload_segment_dir("ex_OFFLINE", str(tmp_path / "ex_0"))
            cluster.wait_for_ev_converged("ex_OFFLINE")
            resp = cluster.query(
                "EXPLAIN PLAN FOR SELECT sum(v) FROM ex WHERE k = 'a'")
            assert not resp.exceptions
            cols = resp.result_table.schema.column_names
            assert cols == ["Operator", "Operator_Id", "Parent_Id"]
            assert resp.result_table.rows[0][0].startswith("BROKER_REDUCE")
        finally:
            cluster.shutdown()


def test_explain_unknown_table_errors(tmp_path):
    from pinot_tpu.tools.cluster import EmbeddedCluster

    cluster = EmbeddedCluster(data_dir=str(tmp_path / "c"))
    try:
        resp = cluster.query("EXPLAIN PLAN FOR SELECT count(*) FROM nope")
        assert resp.exceptions  # same contract as the real query
    finally:
        cluster.shutdown()
