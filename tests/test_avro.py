"""Avro container decoder + AvroRecordReader
(ref: pinot-avro AvroRecordReader over org.apache.avro)."""

import numpy as np
import pytest

from pinot_tpu.ingestion.avro import (
    AvroError,
    read_container,
    write_container,
)
from pinot_tpu.ingestion.readers import create_record_reader

SCHEMA = {
    "type": "record", "name": "Event", "namespace": "test",
    "fields": [
        {"name": "id", "type": "long"},
        {"name": "name", "type": "string"},
        {"name": "score", "type": "double"},
        {"name": "active", "type": "boolean"},
        {"name": "tags", "type": {"type": "array", "items": "string"}},
        {"name": "attrs", "type": {"type": "map", "values": "int"}},
        {"name": "maybe", "type": ["null", "string"]},
        {"name": "kind", "type": {"type": "enum", "name": "Kind",
                                  "symbols": ["A", "B", "C"]}},
        {"name": "raw", "type": "bytes"},
    ],
}

ROWS = [
    {"id": 1, "name": "alpha", "score": 1.5, "active": True,
     "tags": ["x", "y"], "attrs": {"a": 1}, "maybe": None, "kind": "A",
     "raw": b"\x00\x01"},
    {"id": -((1 << 40) + 7), "name": "βeta", "score": -2.25, "active": False,
     "tags": [], "attrs": {}, "maybe": "yes", "kind": "C", "raw": b""},
]


@pytest.mark.parametrize("codec", ["null", "deflate"])
def test_container_roundtrip(tmp_path, codec):
    path = str(tmp_path / "e.avro")
    write_container(path, SCHEMA, ROWS, codec=codec)
    schema, values = read_container(path)
    assert schema["name"] == "Event"
    assert list(values) == ROWS


def test_record_reader(tmp_path):
    path = str(tmp_path / "e.avro")
    write_container(path, SCHEMA, ROWS)
    reader = create_record_reader(path)
    rows = [dict(r) for r in reader]
    assert rows[0]["name"] == "alpha"
    assert rows[1]["maybe"] == "yes"
    # fields_to_read filters
    reader = create_record_reader(path, fields_to_read=["id", "kind"])
    rows = [dict(r) for r in reader]
    assert set(rows[0].keys()) == {"id", "kind"}


def test_ingest_avro_to_segment(tmp_path):
    """Avro -> segment -> query, end to end through the batch job path."""
    from pinot_tpu.engine import ServerQueryExecutor
    from pinot_tpu.query import compile_query
    from pinot_tpu.segment import SegmentBuilder, load_segment
    from pinot_tpu.spi import DataType, FieldSpec, FieldType, Schema

    schema_j = {"type": "record", "name": "S", "fields": [
        {"name": "k", "type": "string"},
        {"name": "v", "type": "long"}]}
    rows = [{"k": f"k{i % 3}", "v": i} for i in range(500)]
    path = str(tmp_path / "d.avro")
    write_container(path, schema_j, rows)
    reader = create_record_reader(path)
    schema = Schema("t", [FieldSpec("k", DataType.STRING),
                          FieldSpec("v", DataType.LONG, FieldType.METRIC)])
    SegmentBuilder(schema, "s0").build(list(reader), str(tmp_path))
    seg = load_segment(str(tmp_path / "s0"))
    ex = ServerQueryExecutor()
    t, _ = ex.execute(compile_query("SELECT sum(v) FROM t WHERE k = 'k1'"),
                      [seg])
    assert t.rows[0][0] == sum(r["v"] for r in rows if r["k"] == "k1")


def test_bad_magic(tmp_path):
    p = tmp_path / "x.avro"
    p.write_bytes(b"nope" + b"\x00" * 32)
    with pytest.raises(AvroError):
        read_container(str(p))


def test_nested_record_and_fixed(tmp_path):
    schema = {"type": "record", "name": "Outer", "fields": [
        {"name": "inner", "type": {"type": "record", "name": "Inner",
                                   "fields": [{"name": "x", "type": "int"}]}},
        {"name": "fx", "type": {"type": "fixed", "name": "F4", "size": 4}},
        {"name": "again", "type": "Inner"},
    ]}
    rows = [{"inner": {"x": 7}, "fx": b"abcd", "again": {"x": -1}}]
    path = str(tmp_path / "n.avro")
    write_container(path, schema, rows)
    _, values = read_container(path)
    assert list(values) == rows
