"""Config recommender rules (ref: controller recommender/RecommenderDriver)."""

import pytest

from pinot_tpu.controller.recommender import recommend
from pinot_tpu.spi import DataType, FieldSpec, FieldType, Schema


@pytest.fixture
def schema():
    return Schema("ev", [
        FieldSpec("country", DataType.STRING),
        FieldSpec("city", DataType.STRING),
        FieldSpec("url", DataType.STRING),
        FieldSpec("payload", DataType.STRING),
        FieldSpec("ts", DataType.LONG),
        FieldSpec("clicks", DataType.LONG, FieldType.METRIC),
        FieldSpec("cost", DataType.DOUBLE, FieldType.METRIC),
    ])


def test_inverted_sorted_and_bloom(schema):
    queries = (["SELECT count(*) FROM ev WHERE country = 'US'"] * 6
               + ["SELECT count(*) FROM ev WHERE city = 'SF'"] * 3
               + ["SELECT sum(clicks) FROM ev"])
    out = recommend(schema, queries)
    rec = out["recommendations"]
    assert rec["sortedColumn"] == ["country"]          # most filtered
    assert rec["invertedIndexColumns"] == ["city"]
    assert "country" in rec["bloomFilterColumns"]
    assert out["numQueriesParsed"] == 10


def test_range_text_json_regex_rules(schema):
    queries = [
        "SELECT count(*) FROM ev WHERE ts BETWEEN 1 AND 9",
        "SELECT count(*) FROM ev WHERE text_match(url, 'foo')",
        "SELECT count(*) FROM ev WHERE json_match(payload, '\"a\"=1')",
        "SELECT count(*) FROM ev WHERE regexp_like(url, '^/api')",
    ]
    rec = recommend(schema, queries)["recommendations"]
    assert rec["rangeIndexColumns"] == ["ts"]
    assert rec["textIndexColumns"] == ["url"]
    assert rec["jsonIndexColumns"] == ["payload"]
    assert rec["fstIndexColumns"] == ["url"]


def test_nodict_metrics(schema):
    rec = recommend(schema, ["SELECT sum(clicks), avg(cost) FROM ev "
                             "WHERE country = 'US'"])["recommendations"]
    assert rec["noDictionaryColumns"] == ["clicks", "cost"]


def test_partitioning_needs_qps(schema):
    q = ["SELECT count(*) FROM ev WHERE country = 'US'"] * 10
    assert "segmentPartitionConfig" not in \
        recommend(schema, q, qps=10)["recommendations"]
    rec = recommend(schema, q, qps=500)["recommendations"]
    assert rec["segmentPartitionConfig"]["columnPartitionMap"][
        "country"]["functionName"] == "Murmur"


def test_star_tree_rule(schema):
    q = ["SELECT country, city, sum(clicks), count(*) FROM ev "
         "GROUP BY country, city"] * 5 + ["SELECT count(*) FROM ev"]
    rec = recommend(schema, q)["recommendations"]
    st = rec["starTreeIndexConfigs"][0]
    assert sorted(st["dimensionsSplitOrder"]) == ["city", "country"]
    assert "SUM__clicks" in st["functionColumnPairs"]
    assert "COUNT__*" in st["functionColumnPairs"]


def test_unparseable_skipped(schema):
    out = recommend(schema, ["NOT SQL AT ALL", "SELECT count(*) FROM ev"])
    assert out["skipped"] == ["NOT SQL AT ALL"]
    assert out["numQueriesParsed"] == 1
