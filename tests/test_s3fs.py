"""S3 PinotFS: SigV4-signed REST protocol against a verifying endpoint.

Ref: pinot-plugins/pinot-file-system/pinot-s3 S3PinotFS — here the client
speaks the S3 REST API itself (ListObjectsV2/Get/Put/Delete with AWS
Signature V4); the mock endpoint recomputes every signature from the
shared secret, so a signing bug fails the suite, not production.
"""

import urllib.error

import numpy as np
import pytest

from pinot_tpu.spi import DataType, FieldSpec, FieldType, Schema
from pinot_tpu.spi.filesystem import fetch_segment
from pinot_tpu.spi.s3fs import MockS3Server, S3PinotFS, sign_request


@pytest.fixture()
def s3():
    srv = MockS3Server().start()
    fs = S3PinotFS(endpoint=srv.endpoint, access_key=srv.access_key,
                   secret_key=srv.secret_key, region=srv.region)
    yield srv, fs
    srv.stop()


class TestSigV4:
    def test_known_vector_shape(self):
        """Signature is deterministic and carries the scope/headers the
        service recomputes from."""
        import datetime

        now = datetime.datetime(2026, 7, 30, 12, 0, 0,
                                tzinfo=datetime.timezone.utc)
        h = sign_request("GET", "http://localhost:9000/bucket/key", {},
                         b"", "AK", "SK", "us-east-1", now=now)
        assert h["x-amz-date"] == "20260730T120000Z"
        assert "Credential=AK/20260730/us-east-1/s3/aws4_request" \
            in h["Authorization"]
        again = sign_request("GET", "http://localhost:9000/bucket/key", {},
                             b"", "AK", "SK", "us-east-1", now=now)
        assert h["Authorization"] == again["Authorization"]

    def test_wrong_secret_is_rejected(self, s3):
        srv, _ = s3
        bad = S3PinotFS(endpoint=srv.endpoint, access_key=srv.access_key,
                        secret_key="wrong", region=srv.region)
        with pytest.raises(urllib.error.HTTPError) as e:
            bad.list_files("s3://b/x")
        assert e.value.code == 403


class TestRoundtrip:
    def test_upload_list_download_delete(self, s3, tmp_path):
        srv, fs = s3
        src = tmp_path / "seg_src"
        (src / "sub").mkdir(parents=True)
        (src / "a.npy").write_bytes(b"alpha")
        (src / "sub" / "b.npy").write_bytes(b"beta")
        fs.copy_from_local_dir(str(src), "s3://deepstore/tables/t/seg_0")
        assert sorted(fs.list_files("s3://deepstore/tables/t/seg_0")) == [
            "tables/t/seg_0/a.npy", "tables/t/seg_0/sub/b.npy"]
        out = fs.copy_to_local_dir("s3://deepstore/tables/t/seg_0",
                                   str(tmp_path / "dl"))
        assert (tmp_path / "dl" / "seg_0" / "a.npy").read_bytes() == b"alpha"
        assert (tmp_path / "dl" / "seg_0" / "sub" / "b.npy").read_bytes() \
            == b"beta"
        fs.delete("s3://deepstore/tables/t/seg_0")
        assert fs.list_files("s3://deepstore/tables/t/seg_0") == []

    def test_pagination_and_special_keys(self, s3, tmp_path):
        """ListObjectsV2 pagination follows continuation tokens; keys with
        spaces sign correctly (no double-encoding); directory markers and
        missing prefixes behave."""
        srv, fs = s3
        srv.page_size = 3
        src = tmp_path / "many"
        src.mkdir()
        for i in range(10):
            (src / f"file {i:02d}.bin").write_bytes(bytes([i]))
        fs.copy_from_local_dir(str(src), "s3://b/pfx/many")
        keys = fs.list_files("s3://b/pfx/many")
        assert len(keys) == 10  # 4 pages of 3
        # console-style directory marker must be skipped, not an error
        srv.objects["b/pfx/many/"] = b""
        out = fs.copy_to_local_dir("s3://b/pfx/many", str(tmp_path / "dl"))
        assert (tmp_path / "dl" / "many" / "file 07.bin").read_bytes() \
            == bytes([7])
        with pytest.raises(FileNotFoundError):
            fs.copy_to_local_dir("s3://b/pfx/NOPE", str(tmp_path / "dl2"))
        assert fs.exists("s3://b/pfx/many")
        assert not fs.exists("s3://b/pfx/NOPE")

    def test_segment_through_s3_deep_store(self, s3, tmp_path, monkeypatch):
        """The server download path (fetch_segment) resolves s3:// URLs:
        build -> upload -> fetch via the registry -> load -> query."""
        from pinot_tpu.engine import ServerQueryExecutor
        from pinot_tpu.query import compile_query
        from pinot_tpu.segment import SegmentBuilder, load_segment

        srv, fs = s3
        monkeypatch.setenv("PINOT_S3_ENDPOINT", srv.endpoint)
        monkeypatch.setenv("AWS_ACCESS_KEY_ID", srv.access_key)
        monkeypatch.setenv("AWS_SECRET_ACCESS_KEY", srv.secret_key)
        monkeypatch.setenv("AWS_REGION", srv.region)

        schema = Schema("s3t", [
            FieldSpec("k", DataType.STRING),
            FieldSpec("v", DataType.LONG, FieldType.METRIC)])
        rng = np.random.default_rng(4)
        frame = {"k": ["a", "b"] * 100,
                 "v": rng.integers(0, 10, 200).tolist()}
        SegmentBuilder(schema, "s3t_0").build(frame, str(tmp_path))
        fs.copy_from_local_dir(str(tmp_path / "s3t_0"),
                               "s3://deepstore/segments/s3t_0")

        local = fetch_segment("s3://deepstore/segments/s3t_0",
                              str(tmp_path / "fetched"))
        seg = load_segment(local)
        ex = ServerQueryExecutor(use_device=False)
        rt, _ = ex.execute(
            compile_query("SELECT count(*), sum(v) FROM s3t"), [seg])
        assert rt.rows[0][0] == 200
        assert rt.rows[0][1] == float(sum(frame["v"]))
