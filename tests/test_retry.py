"""Retry policies + ServiceStartable lifecycle.

Ref: pinot-spi/.../utils/retry/ (RetryPolicies, AttemptsExceededException)
and pinot-spi/.../services/ServiceStartable.java.
"""

import pytest

from pinot_tpu.spi.retry import (
    AttemptsExceededError,
    ServiceManager,
    ServiceStartable,
    exponential_backoff,
    fixed_delay,
)


class TestRetryPolicies:
    def test_succeeds_after_transient_failures(self):
        calls = {"n": 0}

        def op():
            calls["n"] += 1
            if calls["n"] < 3:
                raise OSError("transient")
            return "ok"

        assert fixed_delay(5, delay_ms=1).attempt(op) == "ok"
        assert calls["n"] == 3

    def test_exhaustion_raises_with_cause(self):
        def op():
            raise OSError("down")

        with pytest.raises(AttemptsExceededError) as e:
            exponential_backoff(3, initial_delay_ms=1).attempt(op)
        assert e.value.attempts == 3
        assert isinstance(e.value.last, OSError)

    def test_permanent_errors_never_retry(self):
        calls = {"n": 0}

        def op():
            calls["n"] += 1
            raise ValueError("bad input")

        with pytest.raises(ValueError):
            fixed_delay(5, delay_ms=1).attempt(op)
        assert calls["n"] == 1

    def test_custom_retriable_gate(self):
        calls = {"n": 0}

        def op():
            calls["n"] += 1
            raise KeyError("nope")

        with pytest.raises(KeyError):
            fixed_delay(5, delay_ms=1).attempt(
                op, retriable=lambda e: not isinstance(e, KeyError))
        assert calls["n"] == 1

    def test_exponential_delays_scale(self):
        p = exponential_backoff(4, initial_delay_ms=100, delay_scale=2.0)
        p._randomize = False
        assert [p.delay_s(i) for i in range(3)] == [0.1, 0.2, 0.4]


class _Svc(ServiceStartable):
    def __init__(self, name, log, fail=False):
        self._name, self._log, self._fail = name, log, fail

    def start(self):
        if self._fail:
            raise RuntimeError(f"{self._name} failed to start")
        self._log.append(("start", self._name))

    def stop(self):
        self._log.append(("stop", self._name))

    @property
    def service_role(self):
        return self._name


class TestServiceManager:
    def test_start_order_and_reverse_stop(self):
        log = []
        mgr = ServiceManager()
        for n in ("CONTROLLER", "BROKER", "SERVER"):
            mgr.register(_Svc(n, log))
        mgr.start_all()
        mgr.stop_all()
        assert log == [("start", "CONTROLLER"), ("start", "BROKER"),
                       ("start", "SERVER"), ("stop", "SERVER"),
                       ("stop", "BROKER"), ("stop", "CONTROLLER")]

    def test_failed_start_unwinds_started_prefix(self):
        log = []
        mgr = ServiceManager()
        mgr.register(_Svc("CONTROLLER", log))
        mgr.register(_Svc("BROKER", log, fail=True))
        mgr.register(_Svc("SERVER", log))
        with pytest.raises(RuntimeError):
            mgr.start_all()
        assert log == [("start", "CONTROLLER"), ("stop", "CONTROLLER")]
