"""Cross-generation compatibility + scheduler concurrency.

The compatibility-verifier analogue (ref: compatibility-verifier/
compCheck.sh + pinot-compatibility-verifier yaml ops: create table,
ingest, query, roll each role, re-verify): a cluster generation writes
state + segments, shuts down, and a NEW generation (fresh processes in
the same deployment dir) must recover everything from the snapshot +
deep store and answer the same queries. Plus the scheduler-under-
concurrency coverage the round-3 verdict flagged.
"""

import concurrent.futures
import time

import numpy as np
import pytest

from pinot_tpu.segment import SegmentBuilder
from pinot_tpu.spi import DataType, FieldSpec, FieldType, Schema
from pinot_tpu.spi.table import TableConfig
from pinot_tpu.tools.cluster import EmbeddedCluster

QUERIES = [
    "SELECT count(*) FROM ct",
    "SELECT k, sum(v) FROM ct GROUP BY k ORDER BY k",
    "SELECT max(v), min(v) FROM ct WHERE k = 'a'",
]


def _schema():
    return Schema("ct", [
        FieldSpec("k", DataType.STRING),
        FieldSpec("v", DataType.LONG, FieldType.METRIC)])


class TestGenerationCompat:
    def test_restart_recovers_state_and_answers(self, tmp_path):
        data_dir = str(tmp_path / "deploy")
        schema = _schema()
        rng = np.random.default_rng(9)
        frame = {"k": ["a", "b", "c"][0:2] * 500,
                 "v": rng.integers(0, 100, 1000).tolist()}

        # ---- generation 1: create, ingest, capture answers -------------
        gen1 = EmbeddedCluster(num_servers=2, data_dir=data_dir,
                               snapshot=True)
        gen1.create_table(TableConfig(table_name="ct"), schema)
        seg_dir = str(tmp_path / "segs")
        for i in range(3):
            SegmentBuilder(schema, f"ct_{i}").build(frame, seg_dir)
            gen1.upload_segment_dir("ct_OFFLINE", f"{seg_dir}/ct_{i}")
        gen1.wait_for_ev_converged("ct_OFFLINE")
        expected = [gen1.query_rows(q) for q in QUERIES]
        assert expected[0][0][0] == 3000
        gen1.shutdown()

        # ---- generation 2: fresh processes, same deployment dir ---------
        gen2 = EmbeddedCluster(num_servers=2, data_dir=data_dir,
                               snapshot=True)
        try:
            # state recovered: table config + schema + segment metadata
            assert "ct_OFFLINE" in gen2.store.table_names()
            assert sorted(gen2.store.segment_names("ct_OFFLINE")) == \
                ["ct_0", "ct_1", "ct_2"]
            gen2.wait_for_ev_converged("ct_OFFLINE")
            for q, want in zip(QUERIES, expected):
                assert gen2.query_rows(q) == want, q
        finally:
            gen2.shutdown()

    def test_rolling_server_replacement(self, tmp_path):
        """One server at a time is replaced (the rolling-upgrade shape);
        queries keep answering throughout."""
        data_dir = str(tmp_path / "roll")
        schema = _schema()
        cluster = EmbeddedCluster(num_servers=2, data_dir=data_dir)
        try:
            cluster.create_table(TableConfig(table_name="ct"), schema)
            seg_dir = str(tmp_path / "segs")
            frame = {"k": ["a", "b"] * 300,
                     "v": list(range(600))}
            for i in range(4):
                SegmentBuilder(schema, f"ct_{i}").build(frame, seg_dir)
                cluster.upload_segment_dir("ct_OFFLINE", f"{seg_dir}/ct_{i}")
            cluster.wait_for_ev_converged("ct_OFFLINE")
            want = cluster.query_rows("SELECT count(*) FROM ct")[0][0]
            for victim in list(cluster.servers):
                cluster.stop_server(victim)
                replacement = f"{victim}_v2"
                cluster.add_server(replacement)
                cluster.controller.rebalance_table("ct_OFFLINE")
                cluster.wait_for_ev_converged("ct_OFFLINE")
                got = cluster.query_rows("SELECT count(*) FROM ct")[0][0]
                assert got == want, f"after replacing {victim}"
        finally:
            cluster.shutdown()


class TestSchedulerConcurrency:
    def test_parallel_queries_through_scheduler(self, tmp_path):
        """Round-3 verdict: 'nothing exercises the scheduler under
        concurrency' — 32 concurrent queries through the cluster's
        scheduler path must all answer correctly."""
        schema = _schema()
        cluster = EmbeddedCluster(num_servers=2,
                                  data_dir=str(tmp_path / "conc"))
        try:
            cluster.create_table(TableConfig(table_name="ct"), schema)
            frame = {"k": ["a", "b"] * 400, "v": list(range(800))}
            SegmentBuilder(schema, "ct_0").build(frame, str(tmp_path))
            cluster.upload_segment_dir("ct_OFFLINE",
                                       str(tmp_path / "ct_0"))
            cluster.wait_for_ev_converged("ct_OFFLINE")
            expect = sum(frame["v"])

            def one(i):
                rows = cluster.query_rows("SELECT sum(v) FROM ct")
                return rows[0][0]

            with concurrent.futures.ThreadPoolExecutor(16) as pool:
                results = list(pool.map(one, range(32)))
            assert all(r == expect for r in results), results
        finally:
            cluster.shutdown()

    def test_priority_scheduler_under_load(self):
        """PriorityScheduler keeps serving all tables under saturation."""
        from pinot_tpu.server.scheduler import make_scheduler

        sched = make_scheduler("priority", num_workers=4)
        done = {"t1": 0, "t2": 0}
        lock = __import__("threading").Lock()

        def work(table):
            def fn():
                time.sleep(0.002)
                with lock:
                    done[table] += 1
                return table
            return fn

        futures = []
        for i in range(100):
            table = "t1" if i % 2 else "t2"
            futures.append(sched.submit(work(table), table=table))
        for f in futures:
            f.result(timeout=30)
        sched.shutdown()
        assert done == {"t1": 50, "t2": 50}
