"""Binary columnar DataTable framing + tagged object serde round-trips
(ref: DataTableImplV3.java:43, ObjectSerDeUtils.java)."""

import math

import pytest

from pinot_tpu.common import serde
from pinot_tpu.common.datatable import MAGIC, DataTable, ResponseType
from pinot_tpu.engine.results import DataSchema, QueryStats


# -- serde ------------------------------------------------------------------

@pytest.mark.parametrize("v", [
    None, True, False, 0, 1, -1, 127, 128, -(1 << 40), 1 << 62, 1 << 80,
    0.0, -2.5, float("inf"), float("-inf"),
    "", "héllo", b"", b"\x00\xff" * 5,
    (1, 2.5, "x"), (0.0, 0), frozenset({1, 2, 3}), frozenset(),
    [1, [2, (3, frozenset({"a"}))], None],
])
def test_serde_roundtrip(v):
    assert serde.loads(serde.dumps(v)) == v


def test_serde_nan():
    out = serde.loads(serde.dumps(float("nan")))
    assert math.isnan(out)


def test_serde_trailing_rejected():
    with pytest.raises(ValueError):
        serde.loads(serde.dumps(1) + b"\x00")


# -- DataTable framing ------------------------------------------------------

def _roundtrip(dt: DataTable) -> DataTable:
    raw = dt.to_bytes()
    assert raw.startswith(MAGIC)
    return DataTable.from_bytes(raw)


def test_aggregation_states():
    stats = QueryStats(num_docs_scanned=42, total_docs=100)
    dt = DataTable.for_aggregation(
        [3, (12.5, 4), float("-inf"), b"\x01sketch", frozenset({"a", "b"})],
        stats)
    out = _roundtrip(dt)
    assert out.response_type is ResponseType.AGGREGATION
    assert out.agg_states() == [3, (12.5, 4), float("-inf"), b"\x01sketch",
                                frozenset({"a", "b"})]
    assert out.stats.num_docs_scanned == 42


def test_group_by_rung_index_wire_and_merge():
    """PR 18: the 'index' rung joins the group_by_rung lattice — it must
    survive the binary wire round-trip and merge like any other rung
    (same+same keeps it, disagreement collapses to 'mixed', None adopts)."""
    stats = QueryStats(num_docs_scanned=7, total_docs=1000,
                       group_by_rung="index")
    out = _roundtrip(DataTable.for_aggregation([7], stats))
    assert out.stats.group_by_rung == "index"
    assert out.stats.num_docs_scanned == 7

    a = QueryStats(group_by_rung="index")
    a.merge(QueryStats(group_by_rung="index"))
    assert a.group_by_rung == "index"

    b = QueryStats()
    b.merge(QueryStats(group_by_rung="index"))
    assert b.group_by_rung == "index"

    c = QueryStats(group_by_rung="index")
    c.merge(QueryStats(group_by_rung="dense"))
    assert c.group_by_rung == "mixed"

    d = QueryStats(group_by_rung="startree_device")
    d.merge(QueryStats(group_by_rung="index"))
    assert d.group_by_rung == "mixed"


def test_group_by_columnar():
    groups = {("east", 2019): [10, 1.5], ("west", 2020): [20, -2.5]}
    dt = DataTable.for_group_by(groups, {"region": "STRING", "year": "INT"},
                                QueryStats())
    out = _roundtrip(dt)
    assert out.group_by_groups() == groups
    assert out.schema_types() == {"region": "STRING", "year": "INT"}


def test_group_by_mixed_state_column():
    groups = {("a",): [(1.0, 2)], ("b",): [(3.5, 7)]}
    out = _roundtrip(DataTable.for_group_by(groups, {}, QueryStats()))
    assert out.group_by_groups() == groups


def test_selection_columnar_types():
    schema = DataSchema(["s", "i", "f", "o"],
                        ["STRING", "LONG", "DOUBLE", "STRING"])
    rows = [["x", 1, 1.5, "p"], ["yy", -9, float("inf"), None]]
    dt = DataTable.for_selection(schema, rows, QueryStats(), num_hidden=1)
    out = _roundtrip(dt)
    assert out.rows() == rows
    assert out.num_hidden == 1
    assert out.data_schema().column_names == ["s", "i", "f", "o"]


def test_selection_large_numeric_is_compact():
    schema = DataSchema(["v"], ["LONG"])
    rows = [[i] for i in range(10_000)]
    raw = DataTable.for_selection(schema, rows, QueryStats()).to_bytes()
    # i64 column: ~8 bytes/row, far below per-cell JSON
    assert len(raw) < 10_000 * 12
    assert DataTable.from_bytes(raw).rows() == rows


def test_distinct_roundtrip():
    schema = DataSchema(["name"], ["STRING"])
    rows = [["α"], ["b"]]
    out = _roundtrip(DataTable.for_distinct(schema, rows, QueryStats()))
    assert out.response_type is ResponseType.DISTINCT
    assert out.rows() == rows


def test_exception_table():
    out = _roundtrip(DataTable.for_exception("boom"))
    assert out.exceptions == ["boom"]
    assert "states" in out.payload
    assert out.agg_states() == []


def test_legacy_json_framing_still_decodes():
    dt = DataTable.for_aggregation([1, 2.5], QueryStats(total_docs=7))
    out = DataTable.from_bytes(dt.to_json_bytes())
    assert out.agg_states() == [1, 2.5]
    assert out.stats.total_docs == 7


def test_empty_group_by():
    out = _roundtrip(DataTable.for_group_by({}, {}, QueryStats()))
    assert out.group_by_groups() == {}


# -- columnar accessors (columns()/rows() parity, lazy payload) -------------

def _rand_cell(rng, kind):
    if kind == "i64":
        return rng.randint(-(1 << 62), 1 << 62)
    if kind == "f64":
        return rng.choice([
            float(rng.randint(-1000, 1000)), rng.random() * 1e9,
            float("inf"), float("-inf"), float("nan"), -0.0])
    if kind == "str":
        return rng.choice(["", "a", "héllo", "x" * rng.randint(0, 20), "α β"])
    return rng.choice([
        None, True, (1, 2.5), frozenset({1, "a"}), b"\x00\xff",
        [1, [2]], (float("nan"),), "mixed-in-obj", 7])


def test_columns_rows_parity_fuzz():
    """Wire round-trip fuzz: ``columns()`` (typed buffers) and ``rows()``
    (boxed view) agree cell-for-cell over mixed i64/f64/str/obj schemas,
    non-finite floats included — and on EMPTY tables."""
    import random

    rng = random.Random(42)
    for trial in range(30):
        kinds = [rng.choice(["i64", "f64", "str", "obj"])
                 for _ in range(rng.randint(1, 5))]
        n = rng.choice([0, 1, 2, 17, 64])
        rows = [[_rand_cell(rng, k) for k in kinds] for _ in range(n)]
        schema = DataSchema([f"c{i}" for i in range(len(kinds))],
                            ["STRING"] * len(kinds))
        out = _roundtrip(DataTable.for_selection(schema, rows, QueryStats()))
        assert out.num_rows() == n
        cols = out.columns()
        assert len(cols) == len(kinds)
        boxed = out.rows()
        for c, col in enumerate(cols):
            assert col.n == n
            colvals = col.tolist()
            for i in range(n):
                want = boxed[i][c]
                got = colvals[i]
                if isinstance(want, float) and math.isnan(want):
                    assert isinstance(got, float) and math.isnan(got)
                else:
                    assert got == want and type(got) is type(want)
            if n and kinds[c] in ("i64", "f64"):
                # typed accessor: a real numpy view, dtype preserved
                arr = col.array()
                assert arr.dtype.kind == ("i" if kinds[c] == "i64" else "f")
                assert arr.shape == (n,)


def test_f64_json_safe_computed_from_array():
    """The f64 decode computes json_safe from the ARRAY (no box-then-scan
    double pass): non-finite columns re-wrap only at payload
    materialization, finite ones pass through."""
    schema = DataSchema(["f"], ["DOUBLE"])
    fin = _roundtrip(DataTable.for_selection(
        schema, [[1.5], [2.5]], QueryStats()))
    assert fin.columns()[0].json_safe is True
    inf = _roundtrip(DataTable.for_selection(
        schema, [[1.5], [float("inf")]], QueryStats()))
    assert inf.columns()[0].json_safe is False
    assert inf.rows() == [[1.5], [float("inf")]]
    # legacy payload view wraps the non-finite cell for JSON transport
    assert inf.payload["rows"][1][0] == {"__t": "f", "v": "inf"}


def test_payload_materializes_lazily():
    """Wire-decoded tables keep the row section columnar until something
    touches ``payload``; the boxed dict appears on demand and the JSON
    framing still round-trips."""
    schema = DataSchema(["a", "b"], ["STRING", "LONG"])
    out = _roundtrip(DataTable.for_selection(
        schema, [["x", 1], ["y", 2]], QueryStats()))
    assert "rows" not in out._payload
    assert out.num_rows() == 2          # no materialization
    assert "rows" not in out._payload
    assert out.payload["rows"] == [["x", 1], ["y", 2]]
    again = DataTable.from_bytes(out.to_json_bytes())
    assert again.rows() == [["x", 1], ["y", 2]]


def test_group_columns_accessor():
    groups = {("east", 2019): [10, 1.5], ("west", 2020): [20, -2.5]}
    out = _roundtrip(DataTable.for_group_by(
        groups, {"region": "STRING", "year": "INT"}, QueryStats()))
    keys, aggs = out.group_columns()
    assert [k.tolist() for k in keys] == [["east", "west"], [2019, 2020]]
    assert [a.array().tolist() for a in aggs] == [[10, 20], [1.5, -2.5]]
    assert out.group_by_groups() == groups  # boxed view still intact


def test_take_boxed_partial_materialization():
    schema = DataSchema(["s", "i", "f"], ["STRING", "LONG", "DOUBLE"])
    rows = [[f"r{i}", i, float(i) / 2] for i in range(100)]
    out = _roundtrip(DataTable.for_selection(schema, rows, QueryStats()))
    import numpy as np

    idx = np.asarray([5, 93, 7])
    got = [c.take_boxed(idx) for c in out.columns()]
    assert got == [["r5", "r93", "r7"], [5, 93, 7], [2.5, 46.5, 3.5]]
    assert "rows" not in out._payload
