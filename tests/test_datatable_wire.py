"""Binary columnar DataTable framing + tagged object serde round-trips
(ref: DataTableImplV3.java:43, ObjectSerDeUtils.java)."""

import math

import pytest

from pinot_tpu.common import serde
from pinot_tpu.common.datatable import MAGIC, DataTable, ResponseType
from pinot_tpu.engine.results import DataSchema, QueryStats


# -- serde ------------------------------------------------------------------

@pytest.mark.parametrize("v", [
    None, True, False, 0, 1, -1, 127, 128, -(1 << 40), 1 << 62, 1 << 80,
    0.0, -2.5, float("inf"), float("-inf"),
    "", "héllo", b"", b"\x00\xff" * 5,
    (1, 2.5, "x"), (0.0, 0), frozenset({1, 2, 3}), frozenset(),
    [1, [2, (3, frozenset({"a"}))], None],
])
def test_serde_roundtrip(v):
    assert serde.loads(serde.dumps(v)) == v


def test_serde_nan():
    out = serde.loads(serde.dumps(float("nan")))
    assert math.isnan(out)


def test_serde_trailing_rejected():
    with pytest.raises(ValueError):
        serde.loads(serde.dumps(1) + b"\x00")


# -- DataTable framing ------------------------------------------------------

def _roundtrip(dt: DataTable) -> DataTable:
    raw = dt.to_bytes()
    assert raw.startswith(MAGIC)
    return DataTable.from_bytes(raw)


def test_aggregation_states():
    stats = QueryStats(num_docs_scanned=42, total_docs=100)
    dt = DataTable.for_aggregation(
        [3, (12.5, 4), float("-inf"), b"\x01sketch", frozenset({"a", "b"})],
        stats)
    out = _roundtrip(dt)
    assert out.response_type is ResponseType.AGGREGATION
    assert out.agg_states() == [3, (12.5, 4), float("-inf"), b"\x01sketch",
                                frozenset({"a", "b"})]
    assert out.stats.num_docs_scanned == 42


def test_group_by_columnar():
    groups = {("east", 2019): [10, 1.5], ("west", 2020): [20, -2.5]}
    dt = DataTable.for_group_by(groups, {"region": "STRING", "year": "INT"},
                                QueryStats())
    out = _roundtrip(dt)
    assert out.group_by_groups() == groups
    assert out.schema_types() == {"region": "STRING", "year": "INT"}


def test_group_by_mixed_state_column():
    groups = {("a",): [(1.0, 2)], ("b",): [(3.5, 7)]}
    out = _roundtrip(DataTable.for_group_by(groups, {}, QueryStats()))
    assert out.group_by_groups() == groups


def test_selection_columnar_types():
    schema = DataSchema(["s", "i", "f", "o"],
                        ["STRING", "LONG", "DOUBLE", "STRING"])
    rows = [["x", 1, 1.5, "p"], ["yy", -9, float("inf"), None]]
    dt = DataTable.for_selection(schema, rows, QueryStats(), num_hidden=1)
    out = _roundtrip(dt)
    assert out.rows() == rows
    assert out.num_hidden == 1
    assert out.data_schema().column_names == ["s", "i", "f", "o"]


def test_selection_large_numeric_is_compact():
    schema = DataSchema(["v"], ["LONG"])
    rows = [[i] for i in range(10_000)]
    raw = DataTable.for_selection(schema, rows, QueryStats()).to_bytes()
    # i64 column: ~8 bytes/row, far below per-cell JSON
    assert len(raw) < 10_000 * 12
    assert DataTable.from_bytes(raw).rows() == rows


def test_distinct_roundtrip():
    schema = DataSchema(["name"], ["STRING"])
    rows = [["α"], ["b"]]
    out = _roundtrip(DataTable.for_distinct(schema, rows, QueryStats()))
    assert out.response_type is ResponseType.DISTINCT
    assert out.rows() == rows


def test_exception_table():
    out = _roundtrip(DataTable.for_exception("boom"))
    assert out.exceptions == ["boom"]
    assert "states" in out.payload
    assert out.agg_states() == []


def test_legacy_json_framing_still_decodes():
    dt = DataTable.for_aggregation([1, 2.5], QueryStats(total_docs=7))
    out = DataTable.from_bytes(dt.to_json_bytes())
    assert out.agg_states() == [1, 2.5]
    assert out.stats.total_docs == 7


def test_empty_group_by():
    out = _roundtrip(DataTable.for_group_by({}, {}, QueryStats()))
    assert out.group_by_groups() == {}
