"""Segment lineage (replace protocol + routing visibility) and tiered
storage relocation (ref: SegmentLineage.java, TierConfig.java,
SegmentRelocator)."""

import pytest

from pinot_tpu.broker.routing import RoutingManager
from pinot_tpu.controller.lineage import (
    COMPLETED,
    IN_PROGRESS,
    SegmentLineageManager,
)
from pinot_tpu.controller.state import (
    ClusterStateStore,
    InstanceInfo,
    SegmentZKMetadata,
)
from pinot_tpu.controller.tiers import (
    SegmentRelocator,
    TierConfig,
    parse_age_ms,
    target_tier,
)
from pinot_tpu.spi.table import TableConfig, TableType


@pytest.fixture
def store():
    return ClusterStateStore()


TABLE = "t_OFFLINE"


def _seed(store, segments=("s0", "s1", "s2"), servers=("srv1", "srv2")):
    store.add_table_config(TableConfig(table_name="t"))
    for s in servers:
        store.register_instance(InstanceInfo(s, "SERVER"))
    ideal = {}
    for seg in segments:
        store.set_segment_metadata(SegmentZKMetadata(
            seg, TABLE, status="ONLINE", push_time_ms=1_000_000))
        ideal[seg] = {servers[0]: "ONLINE"}
    store.set_ideal_state(TABLE, ideal)
    for seg in segments:
        for inst, st in ideal[seg].items():
            store.report_instance_state(TABLE, seg, inst, st)
    return ideal


class TestLineage:
    def test_protocol_states(self, store):
        lm = SegmentLineageManager(store)
        eid = lm.start_replace(TABLE, ["s0", "s1"], ["m0"])
        assert lm.entries(TABLE)[0].state == IN_PROGRESS
        # while in progress: outputs hidden, inputs visible
        assert lm.hidden_segments(TABLE) == {"m0"}
        lm.end_replace(TABLE, eid)
        assert lm.entries(TABLE)[0].state == COMPLETED
        assert lm.hidden_segments(TABLE) == {"s0", "s1"}

    def test_revert_hides_outputs_forever(self, store):
        lm = SegmentLineageManager(store)
        eid = lm.start_replace(TABLE, ["s0"], ["m0"])
        lm.revert_replace(TABLE, eid)
        assert lm.hidden_segments(TABLE) == {"m0"}
        # inputs are free for a new attempt
        lm.start_replace(TABLE, ["s0"], ["m1"])

    def test_conflicting_start_rejected(self, store):
        lm = SegmentLineageManager(store)
        lm.start_replace(TABLE, ["s0"], ["m0"])
        with pytest.raises(ValueError):
            lm.start_replace(TABLE, ["s0", "s2"], ["m1"])

    def test_double_end_rejected(self, store):
        lm = SegmentLineageManager(store)
        eid = lm.start_replace(TABLE, ["s0"], ["m0"])
        lm.end_replace(TABLE, eid)
        with pytest.raises(ValueError):
            lm.end_replace(TABLE, eid)

    def test_routing_respects_lineage(self, store):
        _seed(store)
        rm = RoutingManager(store)
        routing, _ = rm.get_routing_table(TABLE)
        assert sorted(sum(routing.values(), [])) == ["s0", "s1", "s2"]
        lm = SegmentLineageManager(store)
        eid = lm.start_replace(TABLE, ["s0", "s1"], ["m0"])
        # m0 uploads mid-protocol: visible in EV but must not be routed
        store.set_segment_metadata(SegmentZKMetadata(
            "m0", TABLE, status="ONLINE"))
        store.update_ideal_state(
            TABLE, lambda i: {**i, "m0": {"srv1": "ONLINE"}})
        store.report_instance_state(TABLE, "m0", "srv1", "ONLINE")
        routing, _ = rm.get_routing_table(TABLE)
        assert sorted(sum(routing.values(), [])) == ["s0", "s1", "s2"]
        lm.end_replace(TABLE, eid)
        routing, _ = rm.get_routing_table(TABLE)
        assert sorted(sum(routing.values(), [])) == ["m0", "s2"]


class TestTiers:
    def test_parse_age(self):
        assert parse_age_ms("7d") == 7 * 86_400_000
        assert parse_age_ms("90m") == 90 * 60_000
        with pytest.raises(ValueError):
            parse_age_ms("7 fortnights")

    def test_target_tier_most_specific(self):
        tiers = [TierConfig("warm", "1d", "warm_tag"),
                 TierConfig("cold", "30d", "cold_tag")]
        assert target_tier(tiers, parse_age_ms("2d")).name == "warm"
        assert target_tier(tiers, parse_age_ms("45d")).name == "cold"
        assert target_tier(tiers, 1000) is None

    def test_relocation_moves_aged_segments(self, store):
        store.add_table_config(TableConfig(
            table_name="t",
            tier_configs=[{"name": "cold", "segmentAge": "30d",
                           "serverTag": "cold_tag",
                           "segmentSelectorType": "time"}]))
        store.register_instance(InstanceInfo("hot1", "SERVER",
                                             tags=["DefaultTenant"]))
        store.register_instance(InstanceInfo("cold1", "SERVER",
                                             tags=["cold_tag"]))
        now = 100 * 86_400_000
        store.set_segment_metadata(SegmentZKMetadata(
            "old", TABLE, status="ONLINE", push_time_ms=now - 40 * 86_400_000))
        store.set_segment_metadata(SegmentZKMetadata(
            "new", TABLE, status="ONLINE", push_time_ms=now - 86_400_000))
        store.set_ideal_state(TABLE, {"old": {"hot1": "ONLINE"},
                                      "new": {"hot1": "ONLINE"}})
        moved = SegmentRelocator(store).relocate_table(TABLE, now_ms=now)
        assert moved == ["old"]
        ideal = store.get_ideal_state(TABLE)
        assert list(ideal["old"].keys()) == ["cold1"]
        assert list(ideal["new"].keys()) == ["hot1"]
        # idempotent: second run moves nothing
        assert SegmentRelocator(store).relocate_table(TABLE, now_ms=now) == []

    def test_no_tagged_server_leaves_placement(self, store):
        store.add_table_config(TableConfig(
            table_name="t",
            tier_configs=[{"name": "cold", "segmentAge": "1d",
                           "serverTag": "nosuch_tag"}]))
        store.register_instance(InstanceInfo("hot1", "SERVER"))
        now = 10 * 86_400_000
        store.set_segment_metadata(SegmentZKMetadata(
            "s", TABLE, status="ONLINE", push_time_ms=now - 5 * 86_400_000))
        store.set_ideal_state(TABLE, {"s": {"hot1": "ONLINE"}})
        assert SegmentRelocator(store).relocate_table(TABLE, now_ms=now) == []

    def test_tierconfig_json_roundtrip(self):
        tc = TableConfig(table_name="x", tier_configs=[
            {"name": "cold", "segmentAge": "30d", "serverTag": "cold_tag"}])
        tc2 = TableConfig.from_dict(tc.to_dict())
        assert tc2.tier_configs[0]["serverTag"] == "cold_tag"


class TestLineageCleanup:
    def test_stale_in_progress_auto_reverts(self, store):
        lm = SegmentLineageManager(store)
        eid = lm.start_replace(TABLE, ["s0"], ["m0"])
        ts = lm.entries(TABLE)[0].timestamp_ms
        # young: untouched
        assert lm.cleanup(TABLE, now_ms=ts + 1000) == []
        # stale: auto-revert frees the inputs, keeps outputs hidden
        touched = lm.cleanup(TABLE, now_ms=ts + 25 * 3_600_000)
        assert touched == [eid]
        assert lm.entries(TABLE)[0].state == "REVERTED"
        assert lm.hidden_segments(TABLE) == {"m0"}
        lm.start_replace(TABLE, ["s0"], ["m1"])  # retry now possible

    def test_terminal_entries_dropped_once_realized(self, store):
        lm = SegmentLineageManager(store)
        eid = lm.start_replace(TABLE, ["gone0"], ["m0"])
        lm.end_replace(TABLE, eid)
        ts = lm.entries(TABLE)[0].timestamp_ms
        # inputs no longer exist in the segment list -> entry drops
        touched = lm.cleanup(TABLE, now_ms=ts + 25 * 3_600_000)
        assert touched == [eid]
        assert lm.entries(TABLE) == []
