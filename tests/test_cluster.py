"""Embedded-cluster integration: the full controller/broker/server path with
a pandas oracle (the reference's H2-parity strategy, SURVEY.md §4:
ClusterIntegrationTestUtils.testQuery)."""

import numpy as np
import pandas as pd
import pytest

from pinot_tpu.controller.state import ONLINE
from pinot_tpu.ingestion import MemoryStream
from pinot_tpu.spi import DataType, FieldSpec, FieldType, Schema
from pinot_tpu.spi.table import (
    SegmentsValidationConfig,
    StreamIngestionConfig,
    TableConfig,
    TableType,
)
from pinot_tpu.tools import EmbeddedCluster

RNG = np.random.default_rng(21)
N = 3000


def make_schema(name="sales"):
    return Schema(name, [
        FieldSpec("region", DataType.STRING),
        FieldSpec("kind", DataType.STRING),
        FieldSpec("qty", DataType.LONG, FieldType.METRIC),
        FieldSpec("price", DataType.DOUBLE, FieldType.METRIC),
        FieldSpec("ts", DataType.LONG, FieldType.DATE_TIME),
    ])


def make_df(n=N, seed=21, ts0=1_600_000_000_000):
    rng = np.random.default_rng(seed)
    regions = ["east", "west", "north", "south"]
    kinds = ["a", "b", "c"]
    return pd.DataFrame({
        "region": [regions[i] for i in rng.integers(0, 4, n)],
        "kind": [kinds[i] for i in rng.integers(0, 3, n)],
        "qty": rng.integers(1, 50, n).astype(np.int64),
        "price": np.round(rng.normal(100, 25, n), 2),
        "ts": (ts0 + rng.integers(0, 10_000_000, n)).astype(np.int64),
    })


@pytest.fixture(scope="module")
def offline_cluster(tmp_path_factory):
    data_dir = str(tmp_path_factory.mktemp("cluster"))
    cluster = EmbeddedCluster(num_servers=3, data_dir=data_dir)
    schema = make_schema()
    cfg = TableConfig("sales", TableType.OFFLINE,
                      validation_config=SegmentsValidationConfig(
                          time_column_name="ts", replication=2))
    cluster.create_table(cfg, schema)
    df = make_df()
    # 4 segments, uneven sizes
    bounds = [0, 700, 1500, 2100, N]
    for i in range(4):
        part = df.iloc[bounds[i]:bounds[i + 1]]
        cluster.ingest_rows("sales_OFFLINE", schema,
                            {c: part[c].tolist() for c in df.columns},
                            segment_name=f"sales_{i}")
    assert cluster.wait_for_ev_converged("sales_OFFLINE")
    yield cluster, df
    cluster.shutdown()


class TestOfflineCluster:
    def test_segments_spread_and_replicated(self, offline_cluster):
        cluster, _ = offline_cluster
        ideal = cluster.store.get_ideal_state("sales_OFFLINE")
        assert len(ideal) == 4
        for seg, m in ideal.items():
            assert len(m) == 2
        hosted = {sid: len(s.hosted_segments("sales_OFFLINE"))
                  for sid, s in cluster.servers.items()}
        assert sum(hosted.values()) == 8  # 4 segments x 2 replicas

    def test_aggregation_parity(self, offline_cluster):
        cluster, df = offline_cluster
        rows = cluster.query_rows(
            "SELECT count(*), sum(qty), avg(price) FROM sales WHERE region = 'east'")
        want = df[df.region == "east"]
        assert rows[0][0] == len(want)
        assert rows[0][1] == pytest.approx(float(want.qty.sum()))
        assert rows[0][2] == pytest.approx(float(want.price.mean()))

    def test_group_by_parity(self, offline_cluster):
        cluster, df = offline_cluster
        rows = cluster.query_rows(
            "SELECT region, kind, sum(qty) FROM sales "
            "GROUP BY region, kind ORDER BY region, kind LIMIT 50")
        want = df.groupby(["region", "kind"]).qty.sum().sort_index()
        assert [(r[0], r[1], r[2]) for r in rows] == \
            [(k[0], k[1], float(v)) for k, v in want.items()]

    def test_selection_order_by_parity(self, offline_cluster):
        cluster, df = offline_cluster
        rows = cluster.query_rows(
            "SELECT region, qty FROM sales ORDER BY qty DESC, region LIMIT 10")
        want = df.sort_values(["qty", "region"],
                              ascending=[False, True]).head(10)
        assert [(r[0], r[1]) for r in rows] == \
            [(r.region, r.qty) for r in want.itertuples()]

    def test_selection_order_by_hidden_column(self, offline_cluster):
        cluster, df = offline_cluster
        # order-by column not in the select list -> hidden-column merge
        rows = cluster.query_rows(
            "SELECT region FROM sales ORDER BY ts LIMIT 5")
        want = df.sort_values("ts", kind="stable").head(5)
        assert [r[0] for r in rows] == list(want.region)
        assert all(len(r) == 1 for r in rows)

    def test_distinct_parity(self, offline_cluster):
        cluster, df = offline_cluster
        rows = cluster.query_rows(
            "SELECT DISTINCT region, kind FROM sales ORDER BY region, kind LIMIT 50")
        want = sorted(set(zip(df.region, df.kind)))
        assert [(r[0], r[1]) for r in rows] == want

    def test_time_pruning_correct(self, offline_cluster):
        cluster, df = offline_cluster
        ts_cut = int(df.ts.quantile(0.2))
        resp = cluster.query(
            f"SELECT count(*) FROM sales WHERE ts <= {ts_cut}")
        want = (df.ts <= ts_cut).sum()
        assert resp.result_table.rows[0][0] == want

    def test_unknown_table_errors(self, offline_cluster):
        cluster, _ = offline_cluster
        resp = cluster.query("SELECT count(*) FROM nope")
        assert resp.has_exceptions
        assert resp.exceptions[0]["errorCode"] == 190

    def test_server_loss_partial_failure(self, offline_cluster):
        cluster, df = offline_cluster
        # unregister one server's transport: queries still answer via the
        # second replica (ref: partial-server-loss tolerance)
        victim = sorted(cluster.servers)[0]
        cluster.broker._servers.pop(victim)
        try:
            rows = cluster.query_rows("SELECT count(*) FROM sales")
            assert rows[0][0] == N
        finally:
            cluster.broker.register_server(victim, cluster.servers[victim])


class TestRealtimeCluster:
    def test_realtime_ingest_and_query(self, tmp_path):
        MemoryStream.create("rt_sales", 2)
        cluster = EmbeddedCluster(num_servers=2, data_dir=str(tmp_path))
        schema = make_schema("rtsales")
        cfg = TableConfig(
            "rtsales", TableType.REALTIME,
            validation_config=SegmentsValidationConfig(time_column_name="ts"),
            stream_config=StreamIngestionConfig(
                stream_type="memory", topic="rt_sales",
                segment_flush_threshold_rows=400))
        cluster.create_table(cfg, schema)
        df = make_df(1000, seed=33)
        stream = MemoryStream.get("rt_sales")
        for i, r in enumerate(df.to_dict("records")):
            stream.produce(r, partition=i % 2)

        assert cluster.wait_for_docs("rtsales", 1000), \
            cluster.query("SELECT count(*) FROM rtsales").to_dict()
        rows = cluster.query_rows(
            "SELECT region, sum(qty) FROM rtsales GROUP BY region ORDER BY region LIMIT 50")
        want = df.groupby("region").qty.sum().sort_index()
        assert [(r[0], r[1]) for r in rows] == \
            [(k, float(v)) for k, v in want.items()]

        # some segments sealed (flush threshold 400 over 2 partitions)
        online = [m for m in
                  cluster.store.segment_metadata_list("rtsales_REALTIME")
                  if m.status == ONLINE]
        assert len(online) >= 2
        cluster.shutdown()
        MemoryStream.delete("rt_sales")

    def test_hybrid_time_boundary(self, tmp_path):
        """Offline + realtime table: query must not double count around the
        time boundary (ref: HybridClusterIntegrationTest)."""
        MemoryStream.create("hy_topic", 1)
        cluster = EmbeddedCluster(num_servers=2, data_dir=str(tmp_path))
        schema = make_schema("hybrid")
        off_cfg = TableConfig("hybrid", TableType.OFFLINE,
                              validation_config=SegmentsValidationConfig(
                                  time_column_name="ts"))
        rt_cfg = TableConfig(
            "hybrid", TableType.REALTIME,
            validation_config=SegmentsValidationConfig(time_column_name="ts"),
            stream_config=StreamIngestionConfig(
                stream_type="memory", topic="hy_topic",
                segment_flush_threshold_rows=10_000))
        cluster.create_table(off_cfg, schema)
        cluster.controller.add_table(rt_cfg)

        ts0 = 1_600_000_000_000
        df = make_df(2000, seed=44, ts0=ts0)
        df = df.sort_values("ts").reset_index(drop=True)
        offline_part = df.iloc[:1200]   # older data -> offline segment
        overlap_and_new = df.iloc[1000:]  # overlaps offline + extends past it

        cluster.ingest_rows("hybrid_OFFLINE", schema,
                            {c: offline_part[c].tolist() for c in df.columns},
                            segment_name="hybrid_off_0")
        stream = MemoryStream.get("hy_topic")
        for r in overlap_and_new.to_dict("records"):
            stream.produce(r, partition=0)
        assert cluster.wait_for_ev_converged("hybrid_OFFLINE")

        boundary = cluster.broker.routing.time_boundary.get_boundary(
            "hybrid_OFFLINE")
        assert boundary == int(offline_part.ts.max()) - 1

        # expected: offline rows with ts <= boundary + realtime rows after
        exp = (offline_part.ts <= boundary).sum() + \
              (overlap_and_new.ts > boundary).sum()
        deadline_rows = None
        import time as _t
        for _ in range(200):
            rows = cluster.query_rows("SELECT count(*) FROM hybrid")
            deadline_rows = rows[0][0]
            if deadline_rows == exp:
                break
            _t.sleep(0.05)
        assert deadline_rows == exp
        cluster.shutdown()
        MemoryStream.delete("hy_topic")


def test_in_subquery_semijoin(tmp_path):
    """inSubquery(col, 'SELECT idset(...)') = 1: the broker pre-executes
    the inner query and rewrites to an inIdSet membership transform
    (ref: the IN_SUBQUERY IdSet rewrite, ServerQueryExecutorV1Impl:404)."""
    import numpy as np

    from pinot_tpu.segment import SegmentBuilder
    from pinot_tpu.spi import DataType, FieldSpec, FieldType, Schema
    from pinot_tpu.spi.table import TableConfig
    from pinot_tpu.tools.cluster import EmbeddedCluster

    cluster = EmbeddedCluster(data_dir=str(tmp_path / "c"))
    try:
        users = Schema("users2", [
            FieldSpec("uid", DataType.LONG),
            FieldSpec("vip", DataType.STRING)])
        events = Schema("events2", [
            FieldSpec("uid", DataType.LONG),
            FieldSpec("amount", DataType.LONG, FieldType.METRIC)])
        cluster.create_table(TableConfig(table_name="users2"), users)
        cluster.create_table(TableConfig(table_name="events2"), events)
        rng = np.random.default_rng(7)
        u = {"uid": list(range(100)),
             "vip": ["y" if i % 10 == 0 else "n" for i in range(100)]}
        e = {"uid": rng.integers(0, 100, 2000).tolist(),
             "amount": rng.integers(1, 50, 2000).tolist()}
        SegmentBuilder(users, "u0").build(u, str(tmp_path))
        SegmentBuilder(events, "e0").build(e, str(tmp_path))
        cluster.upload_segment_dir("users2_OFFLINE", str(tmp_path / "u0"))
        cluster.upload_segment_dir("events2_OFFLINE", str(tmp_path / "e0"))
        cluster.wait_for_ev_converged("users2_OFFLINE")
        cluster.wait_for_ev_converged("events2_OFFLINE")

        resp = cluster.query(
            "SELECT sum(amount) FROM events2 WHERE "
            "inSubquery(uid, 'SELECT idset(uid) FROM users2 "
            "WHERE vip = ''y''') = 1")
        assert not resp.exceptions, resp.exceptions
        vips = {i for i in range(100) if i % 10 == 0}
        expect = sum(a for uid, a in zip(e["uid"], e["amount"])
                     if uid in vips)
        assert resp.result_table.rows[0][0] == expect
    finally:
        cluster.shutdown()
