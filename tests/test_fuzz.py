"""Random query fuzzer: device executor vs host engine vs pandas.

Re-design of the reference's random query generator
(``pinot-integration-tests/.../QueryGenerator.java:65`` — fuzzes
selection/aggregation/group-by queries against Pinot and the H2 oracle):
seeded random SQL over a synthetic table, executed through the sharded
device executor AND the host (numpy) engine, with pandas as the
independent oracle for the aggregation algebra.
"""

import numpy as np
import pandas as pd
import pytest

from pinot_tpu.engine import ServerQueryExecutor
from pinot_tpu.parallel import ShardedQueryExecutor
from pinot_tpu.query import compile_query
from pinot_tpu.segment import SegmentBuilder, load_segment
from pinot_tpu.spi import DataType, FieldSpec, FieldType, Schema

N_QUERIES = 40
N_SEGMENTS = 3
DOCS = 4096

DIMS = {"color": ["red", "green", "blue", "gold"],
        "shape": ["circle", "square", "tri"]}
# high-cardinality dictionary INT column: composed with the DIMS columns it
# pushes the group key space past SPARSE_MIN_GROUPS, so fuzzed group-bys
# exercise the hash-aggregation rung (and, with a selective item filter,
# the dictId-narrowing path)
ITEM_SPAN = 30_000
GROUP_POOL = list(DIMS) + ["item"]
INT_COLS = ["year", "qty"]
FLOAT_COLS = ["price"]
AGGS = ["count(*)", "sum(qty)", "min(price)", "max(price)", "avg(qty)",
        "minmaxrange(year)", "distinctcount(color)", "sum(qty * price)",
        "sum(fromEpochSeconds(qty))", "sum(timeConvert(qty, 'SECONDS', "
        "'MILLISECONDS'))"]


def _frame(n, seed):
    rng = np.random.default_rng(seed)
    return pd.DataFrame({
        "color": np.asarray(DIMS["color"])[rng.integers(0, 4, n)],
        "shape": np.asarray(DIMS["shape"])[rng.integers(0, 3, n)],
        "item": rng.integers(0, ITEM_SPAN, n),
        "year": rng.integers(2000, 2020, n),
        "qty": rng.integers(0, 100, n),
        "price": np.round(rng.uniform(1, 500, n), 2),
    })


@pytest.fixture(scope="module")
def table(tmp_path_factory):
    out = str(tmp_path_factory.mktemp("fuzz"))
    schema = Schema("fz", [
        FieldSpec("color", DataType.STRING),
        FieldSpec("shape", DataType.STRING),
        FieldSpec("item", DataType.INT),
        FieldSpec("year", DataType.INT),
        FieldSpec("qty", DataType.LONG, FieldType.METRIC),
        FieldSpec("price", DataType.DOUBLE, FieldType.METRIC),
    ])
    frames, segs = [], []
    for i in range(N_SEGMENTS):
        df = _frame(DOCS, seed=50 + i)
        frames.append(df)
        SegmentBuilder(schema, f"fz_{i}").build(
            {c: df[c].tolist() for c in df.columns}, out)
        segs.append(load_segment(f"{out}/fz_{i}"))
    return segs, pd.concat(frames, ignore_index=True)


def _rand_predicate(rng, with_item=False):
    # 'item' predicates are opt-in: test_fuzz_cluster reuses this generator
    # against tables that don't carry the high-card column
    kind = rng.integers(0, 7 if with_item else 6)
    if kind == 6:
        # selective dictionary range on the high-card column: when 'item'
        # is also a group key this drives the plan-time dictId narrowing
        lo = int(rng.integers(0, ITEM_SPAN - 4000))
        hi = lo + int(rng.integers(200, 4000))
        return (f"item BETWEEN {lo} AND {hi}",
                lambda df: (df.item >= lo) & (df.item <= hi))
    if kind == 0:
        c = rng.choice(list(DIMS))
        v = rng.choice(DIMS[c])
        return f"{c} = '{v}'", lambda df: df[c] == v
    if kind == 1:
        c = rng.choice(list(DIMS))
        v = rng.choice(DIMS[c])
        return f"{c} != '{v}'", lambda df: df[c] != v
    if kind == 2:
        c = rng.choice(list(DIMS))
        vs = list(rng.choice(DIMS[c], size=2, replace=False))
        quoted = ", ".join(f"'{v}'" for v in vs)
        return f"{c} IN ({quoted})", lambda df: df[c].isin(vs)
    if kind == 3:
        lo, hi = sorted(rng.integers(2000, 2020, 2).tolist())
        return (f"year BETWEEN {lo} AND {hi}",
                lambda df: (df.year >= lo) & (df.year <= hi))
    if kind == 4:
        v = int(rng.integers(0, 100))
        return f"qty > {v}", lambda df: df.qty > v
    v = float(np.round(rng.uniform(1, 500), 2))
    return f"price <= {v}", lambda df: df.price <= v


def _rand_filter(rng, with_item=False):
    n = int(rng.integers(0, 3))
    if n == 0:
        return "", lambda df: pd.Series(True, index=df.index)
    parts, fns = [], []
    for _ in range(n):
        sql, fn = _rand_predicate(rng, with_item)
        parts.append(sql)
        fns.append(fn)
    op = " AND " if rng.integers(0, 2) else " OR "
    sql = " WHERE " + op.join(parts)
    if op == " AND ":
        return sql, lambda df: np.logical_and.reduce([f(df) for f in fns])
    return sql, lambda df: np.logical_or.reduce([f(df) for f in fns])


def _pandas_agg(df, agg):
    if not len(df):
        return {"count(*)": 0}.get(agg)  # empty-group semantics vary; skip
    if agg == "count(*)":
        return len(df)
    if agg == "sum(qty)":
        return float(df.qty.sum())
    if agg == "min(price)":
        return float(df.price.min())
    if agg == "max(price)":
        return float(df.price.max())
    if agg == "avg(qty)":
        return float(df.qty.mean())
    if agg == "minmaxrange(year)":
        return float(df.year.max() - df.year.min())
    if agg == "distinctcount(color)":
        return df.color.nunique()
    if agg == "sum(qty * price)":
        return float((df.qty * df.price).sum())
    if agg == "sum(fromEpochSeconds(qty))":
        return float((df.qty * 1000).sum())
    if agg.startswith("sum(timeConvert"):
        return float((df.qty * 1000).sum())
    raise AssertionError(agg)


def _close(a, b):
    if b is None:
        return True  # empty-group: engine semantics checked by parity below
    if isinstance(b, float):
        return abs(a - b) <= 1e-6 * max(1.0, abs(b))
    return a == b


@pytest.mark.parametrize("qi", range(N_QUERIES))
def test_fuzz_query(table, qi):
    segs, df = table
    rng = np.random.default_rng(1234 + qi)
    n_aggs = int(rng.integers(1, 4))
    aggs = list(rng.choice(AGGS, size=n_aggs, replace=False))
    where, mask_fn = _rand_filter(rng, with_item=True)
    group = []
    gexpr = None  # (sql text, pandas series fn) expression group key
    if rng.integers(0, 2):
        # the pool includes the high-card 'item' column: composed with a
        # DIMS column the key space crosses SPARSE_MIN_GROUPS and the query
        # rides the hash rung (or the narrowed dense rung under a
        # conjunctive item filter)
        group = list(rng.choice(GROUP_POOL, size=int(rng.integers(1, 3)),
                                replace=False))
        if rng.integers(0, 3) == 0:
            # bounded integral EXPRESSION key (the device 'gexpr' strategy)
            gexpr = ("year - 2000", lambda df: df.year - 2000)
    cols = ", ".join(([gexpr[0]] if gexpr else []) + group + aggs)
    sql = f"SELECT {cols} FROM fz{where}"
    if group:
        keys = ([gexpr[0]] if gexpr else []) + group
        sql += f" GROUP BY {', '.join(keys)}"
        # LIMIT must exceed any possible group count: the high-card 'item'
        # key alone yields ~11k groups and the oracle never truncates
        sql += f" ORDER BY {', '.join(keys)} LIMIT 60000"

    device = ShardedQueryExecutor()
    host = ServerQueryExecutor(use_device=False)
    dev_rt, _ = device.execute(compile_query(sql), segs)
    host_rt, _ = host.execute(compile_query(sql), segs)

    # 1) device/host parity (exact algebra match)
    assert len(dev_rt.rows) == len(host_rt.rows), sql
    for dr, hr in zip(dev_rt.rows, host_rt.rows):
        for d, h in zip(dr, hr):
            if isinstance(h, float):
                assert abs(d - h) <= 1e-4 * max(1.0, abs(h)), (sql, d, h)
            else:
                assert d == h, (sql, d, h)

    # 2) pandas oracle
    fdf = df[mask_fn(df)]
    if not group:
        assert len(dev_rt.rows) == 1, sql
        for val, agg in zip(dev_rt.rows[0], aggs):
            expect = _pandas_agg(fdf, agg)
            assert _close(val, expect), (sql, agg, val, expect)
    else:
        gdf = fdf
        gb_cols = list(group)
        if gexpr is not None:
            gdf = fdf.assign(__gx=gexpr[1](fdf))
            gb_cols = ["__gx"] + gb_cols
        nk = len(gb_cols)
        expect_groups = {k if isinstance(k, tuple) else (k,): g
                         for k, g in gdf.groupby(gb_cols)}
        got_keys = {tuple(r[:nk]) for r in dev_rt.rows}
        assert got_keys == set(expect_groups.keys()), sql
        for row in dev_rt.rows:
            key = tuple(row[:nk])
            g = expect_groups[key]
            for val, agg in zip(row[nk:], aggs):
                expect = _pandas_agg(g, agg)
                assert _close(val, expect), (sql, key, agg, val, expect)
