"""Query lifecycle tracing: span trees, decision ledger, slow-query log.

The contracts the subsystem guarantees (common/tracing.py + the
instrumented execution layers):

- a traced query returns ONE hierarchical span tree covering the full
  lifecycle (admission -> lease -> launch -> kernel -> combine), with
  explicit queue-vs-work attribution wherever a queue exists;
- span trees ride the DataTable wire and re-parent under the broker root
  at reduce, instance-tagged BEFORE re-parenting; the legacy flat
  ``traceInfo["entries"]`` view is preserved;
- exception edges close every open span — a dying query never leaves a
  dangling tree;
- the untraced path allocates NO span objects;
- every decline of a faster rung lands in ``QueryStats.decisions`` with
  a stable, non-``unknown`` reason code (the Q1.x expression-agg and
  Q3.x off-split-order shapes pinned here);
- the query registry backs ``/debug/queries`` and the slow-query log
  retains full span trees for over-threshold queries even when
  trace/sampling missed them.
"""

import json
import threading

import numpy as np
import pytest

from pinot_tpu.common.datatable import DataTable
from pinot_tpu.common.tracing import (
    DecisionLedger,
    SpanRecorder,
    build_broker_root,
    classify_decline,
    parse_decision_key,
)
from pinot_tpu.engine import QueryStats, ServerQueryExecutor
from pinot_tpu.parallel import ShardedQueryExecutor
from pinot_tpu.query import compile_query
from pinot_tpu.segment import SegmentBuilder, load_segment
from pinot_tpu.spi import DataType, FieldSpec, FieldType, Schema
from pinot_tpu.spi.config import CommonConstants, PinotConfiguration

pytestmark = pytest.mark.trace

RNG = np.random.default_rng(11)
N = 1024
NUM_SEGMENTS = 3

GROUP_SQL = ("SELECT region, sum(qty), count(*) FROM sales "
             "GROUP BY region ORDER BY region")
TRACED_SQL = GROUP_SQL + " OPTION(trace=true)"


def _schema():
    return Schema("sales", [
        FieldSpec("region", DataType.STRING),
        FieldSpec("qty", DataType.LONG, FieldType.METRIC),
    ])


@pytest.fixture(scope="module")
def segs(tmp_path_factory):
    out = tmp_path_factory.mktemp("trace_segs")
    regions = ["east", "west", "north", "south"]
    built = []
    for i in range(NUM_SEGMENTS):
        b = SegmentBuilder(_schema(), f"sales_{i}")
        b.build({
            "region": [regions[j] for j in RNG.integers(0, 4, N)],
            "qty": RNG.integers(1, 50, N).tolist(),
        }, str(out))
        built.append(load_segment(str(out / f"sales_{i}")))
    return built


@pytest.fixture(scope="module")
def st_segs(tmp_path_factory):
    """Segments carrying a star-tree over (region, kind) — the decline
    shapes (expression agg, off-split-order group) need trees to
    decline."""
    from pinot_tpu.spi.table import IndexingConfig, StarTreeIndexConfig

    out = tmp_path_factory.mktemp("trace_st_segs")
    cfg = IndexingConfig(star_tree_index_configs=[StarTreeIndexConfig(
        dimensions_split_order=["region", "kind"],
        function_column_pairs=["SUM__qty", "COUNT__*"],
        max_leaf_records=100)])
    schema = Schema("sales_st", [
        FieldSpec("region", DataType.STRING),
        FieldSpec("kind", DataType.STRING),
        FieldSpec("year", DataType.INT),
        FieldSpec("qty", DataType.LONG, FieldType.METRIC),
        FieldSpec("price", DataType.DOUBLE, FieldType.METRIC),
    ])
    built = []
    for i in range(2):
        b = SegmentBuilder(schema, f"sales_st_{i}", indexing_config=cfg)
        b.build({
            "region": [["east", "west"][j] for j in RNG.integers(0, 2, N)],
            "kind": [["a", "b", "c"][j] for j in RNG.integers(0, 3, N)],
            "year": (2015 + RNG.integers(0, 5, N)).tolist(),
            "qty": RNG.integers(1, 50, N).tolist(),
            "price": np.round(RNG.normal(100.0, 10.0, N), 2).tolist(),
        }, str(out))
        built.append(load_segment(str(out / f"sales_st_{i}")))
    return built


def _names(children):
    return [c["name"] for c in children]


def _find(children, name):
    for c in children:
        if c["name"] == name:
            return c
    return None


# --------------------------------------------------------------------------
# span-tree shape
# --------------------------------------------------------------------------

class TestSpanTreeShape:
    def test_per_segment_group_by_nesting(self, segs):
        """admission -> lease -> per-segment (stage, kernel) nesting under
        one ServerQuery root."""
        ex = ServerQueryExecutor()
        rt, stats = ex.execute(compile_query(TRACED_SQL), segs)
        assert len(stats.spans) == 1
        root = stats.spans[0]
        assert root["name"] == "ServerQuery"
        kids = _names(root["children"])
        assert kids[0] == "Admission"
        assert "Lease" in kids
        seg_spans = [c for c in root["children"]
                     if c["name"] == "SegmentGroupBy"]
        assert len(seg_spans) == NUM_SEGMENTS
        for sp in seg_spans:
            assert sp["path"] in ("device", "host")
            inner = _names(sp.get("children", []))
            assert "Kernel" in inner
        # explicit queue-vs-work split at the admission level
        adm = _find(root["children"], "Admission")
        assert "queueMs" in adm and "workMs" in adm
        # children account for (nearly) the root's wall time
        covered = sum(c["ms"] for c in root["children"])
        assert covered <= root["ms"] * 1.05
        # legacy flat view is emitted FROM the tree
        ops = {e["operator"] for e in stats.trace}
        assert {"ServerQuery", "SegmentGroupBy", "Kernel"} <= ops

    def test_sharded_combine_queue_attribution(self, segs):
        """The launch-dispatcher level carries the queue-vs-work split
        (queueMs = dispatcher queue wait, workMs = launch + D2H)."""
        ex = ShardedQueryExecutor()
        rt, stats = ex.execute(compile_query(TRACED_SQL), segs)
        root = stats.spans[0]
        sc = _find(root["children"], "ShardedCombine")
        assert sc is not None, _names(root["children"])
        assert "queueMs" in sc and "workMs" in sc
        assert sc["kernel"] in ("jnp", "pallas")
        assert sc["segments"] == NUM_SEGMENTS
        # nesting order: Admission -> Lease -> ShardedCombine
        kids = _names(root["children"])
        assert kids.index("Admission") < kids.index("Lease") \
            < kids.index("ShardedCombine")

    def test_off_path_zero_allocation(self, segs):
        """An untraced query allocates no recorder, no spans, no flat
        entries — the off path pays one getattr per site."""
        ex = ServerQueryExecutor()
        rt, stats = ex.execute(compile_query(GROUP_SQL), segs)
        assert getattr(stats, "_recorder", None) is None
        assert stats.spans == []
        assert stats.trace == []

    def test_sample_rate_records_without_option(self, segs):
        """pinot.server.query.trace.sample=1.0: every query records and
        SHIPS its tree exactly as if trace=true had been set."""
        cfg = PinotConfiguration(
            {CommonConstants.TRACE_SAMPLE_KEY: "1.0"}, use_env=False)
        ex = ServerQueryExecutor(config=cfg)
        rt, stats = ex.execute(compile_query(GROUP_SQL), segs)
        assert stats.spans and stats.spans[0]["name"] == "ServerQuery"


# --------------------------------------------------------------------------
# wire + reduce re-parenting
# --------------------------------------------------------------------------

class TestWire:
    def _stats_with_tree(self):
        st = QueryStats(num_docs_scanned=7)
        st.spans.append({"name": "ServerQuery", "ms": 5.0, "children": [
            {"name": "Kernel", "ms": 4.0, "kernel": "jnp"}]})
        st.decisions["pallas:pallas_kernel->jnp_kernel:pallas_distinct_agg"] = 2
        st.trace.append({"operator": "Kernel", "ms": 4.0})
        return st

    def test_binary_wire_round_trip(self):
        dt = DataTable.for_aggregation([1.0], self._stats_with_tree())
        back = DataTable.from_bytes(dt.to_bytes())
        assert back.stats.spans == dt.stats.spans
        assert back.stats.decisions == dt.stats.decisions
        assert back.stats.trace == dt.stats.trace

    def test_legacy_json_wire_round_trip(self):
        dt = DataTable.for_aggregation([1.0], self._stats_with_tree())
        back = DataTable.from_bytes(dt.to_json_bytes())
        assert back.stats.spans == dt.stats.spans
        assert back.stats.decisions == dt.stats.decisions

    def test_reduce_merges_and_broker_root_reparents(self):
        """_tag_trace attributes per instance BEFORE reduce; the broker
        root adopts every server tree under ScatterGather."""
        from pinot_tpu.broker.broker import _tag_trace
        from pinot_tpu.broker.reduce import BrokerReduceService

        dts = []
        for i in range(2):
            dt = DataTable.for_aggregation([float(i)],
                                           self._stats_with_tree())
            _tag_trace(dt, f"server_{i}")
            dts.append(dt)
        ctx = compile_query("SELECT sum(qty) FROM sales")
        table, stats, errors = BrokerReduceService().reduce(ctx, dts)
        assert len(stats.spans) == 2
        assert {s["instance"] for s in stats.spans} \
            == {"server_0", "server_1"}
        # decisions summed across servers
        assert stats.decisions[
            "pallas:pallas_kernel->jnp_kernel:pallas_distinct_agg"] == 4
        root = build_broker_root(
            {"COMPILATION": 1.0, "SCATTER_GATHER": 12.0, "REDUCE": 0.5},
            stats.spans, 14.0, admission_wait_ms=0.2)
        assert root["name"] == "BrokerQuery"
        sg = _find(root["children"], "ScatterGather")
        assert _names(sg["children"]) == ["ServerQuery", "ServerQuery"]
        adm = _find(root["children"], "Admission")
        assert adm["queueMs"] == 0.2

    def test_cluster_trace_end_to_end(self, segs, tmp_path):
        """Full wire path: broker root whose children account >= 90% of
        measured wall time, server trees instance-tagged, scheduler-queue
        attribution present, legacy entries preserved."""
        from pinot_tpu.spi.table import TableConfig
        from pinot_tpu.tools.cluster import EmbeddedCluster

        c = EmbeddedCluster(num_servers=2, data_dir=str(tmp_path))
        try:
            c.create_table(TableConfig("sales"), _schema())
            regions = ["east", "west"]
            for i in range(2):
                c.ingest_rows("sales_OFFLINE", _schema(), {
                    "region": [regions[j]
                               for j in RNG.integers(0, 2, 512)],
                    "qty": RNG.integers(1, 50, 512).tolist(),
                }, segment_name=f"sales_{i}")
            assert c.wait_for_ev_converged("sales_OFFLINE")
            best = 0.0
            for _ in range(5):
                resp = c.query(TRACED_SQL)
                assert not resp.exceptions, resp.exceptions
                ti = resp.to_dict()["traceInfo"]
                root = ti["spans"][0]
                assert root["name"] == "BrokerQuery"
                covered = sum(ch["ms"] for ch in root["children"])
                best = max(best, covered / root["ms"])
                if best >= 0.9:
                    break
            assert best >= 0.9, f"broker-root children cover {best:.2%}"
            sg = _find(root["children"], "ScatterGather")
            server_roots = [s for s in sg["children"]
                            if s["name"] == "ServerQuery"]
            assert server_roots
            assert all("instance" in s for s in server_roots)
            # scheduler-level queue attribution inside each server tree
            for s in server_roots:
                q = _find(s["children"], "SchedulerQueue")
                assert q is not None and "queueMs" in q
            # legacy flat entries preserved, instance-tagged
            entries = ti["entries"]
            assert entries and all("operator" in e and "ms" in e
                                   for e in entries)
            assert all("instance" in e for e in entries)
            # scheduler wait totals surfaced for ops
            snap = list(c.servers.values())[0].scheduler.stats_snapshot()
            assert "queueWaitMsTotal" in snap
            # untraced responses stay untraced
            resp2 = c.query(GROUP_SQL)
            assert "traceInfo" not in resp2.to_dict()
        finally:
            c.shutdown()


# --------------------------------------------------------------------------
# exception edges + slow-query log + registry
# --------------------------------------------------------------------------

class TestRegistry:
    def test_exception_edge_closes_spans(self, segs, monkeypatch):
        """A query dying mid-execution still produces a CLOSED tree (the
        registry's completed entry carries the error; the slow log keeps
        the tree)."""
        from pinot_tpu.engine import executor as executor_mod

        cfg = PinotConfiguration(
            {CommonConstants.SLOW_THRESHOLD_MS_KEY: "0.0001"},
            use_env=False)
        ex = ServerQueryExecutor(use_device=False, config=cfg)

        def boom(*a, **k):
            raise RuntimeError("kernel exploded")

        monkeypatch.setattr(executor_mod.host_engine,
                            "host_group_by_segment", boom)
        with pytest.raises(RuntimeError):
            ex.execute(compile_query(GROUP_SQL), segs)
        snap = ex.queries.snapshot()
        assert snap["running"] == []
        done = snap["completed"][-1]
        assert "kernel exploded" in done["error"]
        slow = snap["slow"][-1]
        root = slow["spans"][0]
        assert root["name"] == "ServerQuery"
        assert root["ms"] >= 0  # closed: wall time measured

    def test_slow_log_retains_tree_when_untraced(self, segs):
        """The slow log keeps the FULL span tree for over-threshold
        queries even though the response ships untraced."""
        cfg = PinotConfiguration(
            {CommonConstants.SLOW_THRESHOLD_MS_KEY: "0.0001"},
            use_env=False)
        ex = ServerQueryExecutor(config=cfg)
        rt, stats = ex.execute(compile_query(GROUP_SQL), segs)
        # response payload: untraced (no spans shipped)
        assert stats.spans == []
        assert stats.trace == []
        slow = ex.queries.snapshot()["slow"][-1]
        assert slow["spans"][0]["name"] == "ServerQuery"
        assert _names(slow["spans"][0]["children"])

    def test_registry_ring_and_request_id(self, segs):
        ex = ServerQueryExecutor()
        sql = GROUP_SQL + " OPTION(requestId=dash42)"
        ex.execute(compile_query(sql), segs)
        done = ex.queries.snapshot()["completed"][-1]
        assert done["requestId"] == "dash42"
        assert done["table"] == "sales"
        assert done["elapsedMs"] > 0


# --------------------------------------------------------------------------
# decision ledger
# --------------------------------------------------------------------------

class TestDecisionLedger:
    def test_q1_shape_expression_agg_decline_is_stable(self, st_segs):
        """The Q1.x shape: an expression aggregation has no pre-agg pair,
        so the star-tree declines with a stable reason — twice."""
        ex = ServerQueryExecutor()
        ctx = compile_query("SELECT region, sum(qty * price) FROM sales_st "
                            "GROUP BY region ORDER BY region")
        keys = []
        for _ in range(2):
            rt, stats = ex.execute(ctx, st_segs)
            keys.append({k for k in stats.decisions
                         if k.startswith("startree:")})
        assert keys[0] == keys[1]
        assert any("startree_expression_agg_no_pair" in k
                   for k in keys[0]), keys

    def test_q3_shape_off_split_order_decline(self, st_segs):
        """The Q3.x shape: a group column off the split order declines
        the tree with the off-split-order reason."""
        ex = ServerQueryExecutor()
        rt, stats = ex.execute(
            compile_query("SELECT year, sum(qty) FROM sales_st "
                          "GROUP BY year ORDER BY year"), st_segs)
        assert any("startree_group_off_split_order" in k
                   for k in stats.decisions), stats.decisions

    def test_pallas_declines_are_classified(self, segs):
        """Every pallas decline carries a non-unknown reason code (the
        bench loud-fails otherwise)."""
        ex = ServerQueryExecutor(use_pallas=True)
        rt, stats = ex.execute(
            compile_query("SELECT distinctcount(region) FROM sales"), segs)
        pallas = {k: v for k, v in stats.decisions.items()
                  if parse_decision_key(k)[0] == "pallas"}
        assert pallas, stats.decisions
        assert all(parse_decision_key(k)[3] != "unknown" for k in pallas)
        assert any("pallas_distinct_agg" in k for k in pallas), pallas

    def test_residency_spill_decision(self, segs):
        """An over-budget unsliceable working set records WHY it fell to
        the host engine."""
        ex = ServerQueryExecutor(hbm_budget_bytes=1)
        rt, stats = ex.execute(compile_query(GROUP_SQL), segs)
        spill = [k for k in stats.decisions
                 if parse_decision_key(k)[0] == "residency"]
        assert spill, stats.decisions
        assert parse_decision_key(spill[0])[3] \
            == "single_segment_over_budget"

    def test_decisions_merge_and_response_surface(self, segs):
        """Decisions sum at merge and surface on the broker response."""
        a = QueryStats()
        b = QueryStats()
        a.decisions["plan:device_kernel->host_engine:mutable_segment"] = 1
        b.decisions["plan:device_kernel->host_engine:mutable_segment"] = 2
        a.merge(b)
        assert a.decisions[
            "plan:device_kernel->host_engine:mutable_segment"] == 3
        from pinot_tpu.common.response import BrokerResponse

        resp = BrokerResponse(stats=a)
        assert resp.to_dict()["decisions"] == a.decisions

    def test_classifier_never_unknown_for_real_messages(self):
        for msg in (
                "mutable segment -> host path",
                "group key space 4194304+ exceeds device limit",
                "aggregation percentile not device-supported grouped",
                "transform regexpextract -> host path",
                "lut with too many runs",
                "int expr bound exceeds i32",
                "some brand new decline nobody classified yet"):
            assert classify_decline(msg) != "unknown", msg
        # digits are stripped so runtime values never fork the code
        assert classify_decline("group key space 123+ exceeds device limit") \
            == classify_decline("group key space 999+ exceeds device limit")

    def test_ledger_histogram_and_metrics(self):
        from pinot_tpu.spi.metrics import MetricsRegistry

        led = DecisionLedger()
        reg = MetricsRegistry(role="server")
        led.bind_metrics(reg)
        led.record("pallas", "jnp_kernel", "pallas_kernel",
                   "pallas_distinct_agg")
        led.record("pallas", "jnp_kernel", "pallas_kernel",
                   "pallas_distinct_agg")
        snap = led.snapshot()
        assert snap[
            "pallas:pallas_kernel->jnp_kernel:pallas_distinct_agg"] == 2
        assert led.reason_histogram()["pallas_distinct_agg"] == 2
        text = reg.export_prometheus()
        # ONE labeled family, not N name-mangled counters: every decline
        # cell is a (point, reason) label pair under one TYPE header
        assert "# TYPE pinot_server_decision_declined_total counter" in text
        assert ('pinot_server_decision_declined_total{point="pallas",'
                'reason="pallas_distinct_agg"} 2') in text
        # delta: the bench's per-suite view
        mark = led.snapshot()
        led.record("plan", "host_engine", "device_kernel",
                   "mutable_segment")
        delta = led.delta(mark)
        assert list(delta.values()) == [1]


# --------------------------------------------------------------------------
# recorder unit behavior
# --------------------------------------------------------------------------

class TestRecorder:
    def test_context_manager_closes_on_raise(self):
        rec = SpanRecorder()
        with pytest.raises(ValueError):
            with rec.span("outer"):
                with rec.span("inner"):
                    raise ValueError("boom")
        assert rec.open_depth == 0
        assert rec.spans[0]["name"] == "outer"
        assert rec.spans[0]["children"][0]["name"] == "inner"

    def test_abandoned_child_swept_by_parent_close(self):
        rec = SpanRecorder()
        outer = rec.span_begin("outer")
        rec.span_begin("abandoned")
        rec.span_end(outer)
        assert rec.open_depth == 0
        assert _names(rec.spans[0]["children"]) == ["abandoned"]

    def test_double_close_is_noop(self):
        rec = SpanRecorder()
        sp = rec.span_begin("x")
        rec.span_end(sp)
        assert rec.span_end(sp) is None
        assert len(rec.spans) == 1


# --------------------------------------------------------------------------
# trace-while-querying hammer
# --------------------------------------------------------------------------

def test_trace_hammer(segs):
    """4 threads, traced + untraced queries interleaved on one sharded
    executor: results stay bit-identical, every traced tree is closed and
    rooted, untraced stats stay span-free."""
    ex = ShardedQueryExecutor()
    oracle, _ = ex.execute(compile_query(GROUP_SQL), segs)
    errors = []

    def pump(i):
        try:
            for j in range(6):
                traced = (i + j) % 2 == 0
                ctx = compile_query(TRACED_SQL if traced else GROUP_SQL)
                rt, stats = ex.execute(ctx, segs)
                assert rt.rows == oracle.rows
                if traced:
                    assert stats.spans[0]["name"] == "ServerQuery"
                    rec = getattr(stats, "_recorder", None)
                    assert rec is None or rec.open_depth == 0
                else:
                    assert stats.spans == []
        except BaseException as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=pump, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60)
    assert not errors, errors


def test_spans_json_serializable(segs):
    ex = ShardedQueryExecutor()
    rt, stats = ex.execute(compile_query(TRACED_SQL), segs)
    json.dumps(stats.spans)
    json.dumps(stats.decisions)
