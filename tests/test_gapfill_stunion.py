"""Gapfill reduce + ST_UNION + distinctcount-MV parity.

Refs: pinot-core/.../query/reduce/GapfillProcessor.java (dispatched from
BrokerReduceService.java:44), StUnionAggregationFunction.java,
DistinctCountMVAggregationFunction / DistinctCountHLLMVAggregationFunction.
"""

import numpy as np
import pytest

from pinot_tpu.engine import ServerQueryExecutor
from pinot_tpu.query import compile_query
from pinot_tpu.segment import SegmentBuilder, load_segment
from pinot_tpu.spi import DataType, FieldSpec, FieldType, Schema
from pinot_tpu.spi.table import TableConfig
from pinot_tpu.tools.cluster import EmbeddedCluster


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    out = str(tmp_path_factory.mktemp("gapfill"))
    schema = Schema("events", [
        FieldSpec("bucket", DataType.INT),
        FieldSpec("host", DataType.STRING),
        FieldSpec("loc", DataType.STRING),
        FieldSpec("tags", DataType.STRING, single_value=False),
        FieldSpec("v", DataType.LONG, FieldType.METRIC),
    ])
    # buckets 0,10,30,40 present; 20 and 50 missing for host a; host b has
    # only 10 and 20
    frame = {
        "bucket": [0, 10, 30, 40, 10, 20, 0, 30],
        "host": ["a", "a", "a", "a", "b", "b", "a", "a"],
        "loc": ["POINT (1 2)", "POINT (3 4)", "POINT (1 2)", "POINT (5 6)",
                "POINT (7 8)", "POINT (7 8)", "POINT (9 9)", "POINT (3 4)"],
        "tags": [["x", "y"], ["y"], ["z"], ["x"], ["x", "z"], ["y"],
                 ["x"], ["q", "x"]],
        "v": [1, 2, 3, 4, 5, 6, 7, 8],
    }
    cl = EmbeddedCluster(data_dir=out)
    cl.create_table(TableConfig(table_name="events"), schema)
    seg_dir = str(tmp_path_factory.mktemp("gapfill_seg"))
    SegmentBuilder(schema, "events_0").build(frame, seg_dir)
    cl.upload_segment_dir("events_OFFLINE", f"{seg_dir}/events_0")
    assert cl.wait_for_ev_converged("events_OFFLINE")
    yield cl, frame
    cl.shutdown()


class TestGapfill:
    def test_default_fill(self, cluster):
        cl, _ = cluster
        resp = cl.query(
            "SELECT gapfill(bucket, 0, 60, 10), sum(v) FROM events "
            "WHERE host = 'a' GROUP BY gapfill(bucket, 0, 60, 10) "
            "ORDER BY gapfill(bucket, 0, 60, 10) LIMIT 100")
        assert not resp.exceptions, resp.exceptions
        rows = resp.result_table.rows
        assert [r[0] for r in rows] == [0, 10, 20, 30, 40, 50]
        # present buckets keep sums; absent buckets fill 0
        assert [r[1] for r in rows] == [8.0, 2.0, 0, 11.0, 4.0, 0]

    def test_previous_fill_with_dims(self, cluster):
        cl, _ = cluster
        resp = cl.query(
            "SELECT host, gapfill(bucket, 0, 40, 10, 'FILL_PREVIOUS_VALUE'),"
            " sum(v) FROM events GROUP BY host, "
            "gapfill(bucket, 0, 40, 10, 'FILL_PREVIOUS_VALUE') "
            "ORDER BY host, gapfill(bucket, 0, 40, 10, "
            "'FILL_PREVIOUS_VALUE') LIMIT 100")
        assert not resp.exceptions, resp.exceptions
        by_host = {}
        for host, bucket, v in resp.result_table.rows:
            by_host.setdefault(host, []).append((bucket, v))
        # host b: bucket 0 absent with NO previous -> default 0; bucket 30
        # absent -> carries bucket 20's value
        assert sorted(by_host["b"]) == [(0, 0), (10, 5.0), (20, 6.0),
                                        (30, 6.0)]
        assert sorted(by_host["a"]) == [(0, 8.0), (10, 2.0), (20, 2.0),
                                        (30, 11.0)]

    def test_gapfill_requires_group_by(self, cluster):
        cl, _ = cluster
        resp = cl.query("SELECT gapfill(bucket, 0, 60, 10) FROM events "
                        "LIMIT 5")
        assert resp.exceptions

    def test_misaligned_bucket_is_loud(self, cluster):
        """A bucket off the start+k*step grid must error, not be silently
        shadowed by a fabricated zero row."""
        cl, _ = cluster
        resp = cl.query(
            "SELECT gapfill(bucket, 5, 60, 10), sum(v) FROM events "
            "WHERE host = 'a' GROUP BY gapfill(bucket, 5, 60, 10) LIMIT 100")
        assert resp.exceptions and "aligned" in resp.exceptions[0]["message"]

    def test_reduce_trim_cannot_shadow_present_buckets(self, cluster):
        """The default group-by LIMIT 10 (or any reduce-side trim) must NOT
        make present buckets look absent — gapfill lifts the limit for the
        reduce and trims AFTER filling. With no explicit LIMIT, the 6-bucket
        window returns all present sums, never fabricated zeros over data."""
        cl, _ = cluster
        resp = cl.query(
            "SELECT gapfill(bucket, 0, 60, 10), sum(v) FROM events "
            "WHERE host = 'a' GROUP BY gapfill(bucket, 0, 60, 10) "
            "ORDER BY sum(v) DESC")
        assert not resp.exceptions, resp.exceptions
        rows = resp.result_table.rows
        # ORDER BY sum DESC over FILLED rows: real sums first, zeros last
        assert [(r[0], r[1]) for r in rows] == [
            (30, 11.0), (0, 8.0), (40, 4.0), (10, 2.0), (20, 0), (50, 0)]

    def test_order_by_desc_applies_to_filled_rows(self, cluster):
        cl, _ = cluster
        resp = cl.query(
            "SELECT gapfill(bucket, 0, 60, 10), sum(v) FROM events "
            "WHERE host = 'a' GROUP BY gapfill(bucket, 0, 60, 10) "
            "ORDER BY gapfill(bucket, 0, 60, 10) DESC LIMIT 3")
        assert not resp.exceptions, resp.exceptions
        # top-3 of the DESCENDING filled series: 50 (fabricated), 40, 30
        assert [r[0] for r in resp.result_table.rows] == [50, 40, 30]
        assert resp.result_table.rows[0][1] == 0


class TestStUnion:
    def test_scalar_union(self, cluster):
        cl, frame = cluster
        resp = cl.query("SELECT stunion(loc) FROM events WHERE host = 'a'")
        assert not resp.exceptions, resp.exceptions
        wkt = resp.result_table.rows[0][0]
        assert wkt.startswith("MULTIPOINT")
        # distinct points of host a, sorted
        assert wkt == ("MULTIPOINT (1 2, 3 4, 5 6, 9 9)")

    def test_grouped_union(self, cluster):
        cl, _ = cluster
        resp = cl.query("SELECT host, st_union(loc) FROM events "
                        "GROUP BY host ORDER BY host")
        assert not resp.exceptions, resp.exceptions
        rows = resp.result_table.rows
        assert rows[0][0] == "a"
        assert rows[1] == ["b", "MULTIPOINT (7 8)"]


class TestDistinctCountMV:
    def test_distinctcountmv(self, cluster):
        cl, frame = cluster
        resp = cl.query("SELECT distinctcountmv(tags) FROM events")
        assert not resp.exceptions, resp.exceptions
        want = len({t for tags in frame["tags"] for t in tags})
        assert resp.result_table.rows[0][0] == want

    def test_distinctcountmv_grouped(self, cluster):
        cl, frame = cluster
        resp = cl.query("SELECT host, distinctcountmv(tags) FROM events "
                        "GROUP BY host ORDER BY host")
        assert not resp.exceptions, resp.exceptions
        want = {}
        for h, tags in zip(frame["host"], frame["tags"]):
            want.setdefault(h, set()).update(tags)
        assert resp.result_table.rows == [
            ["a", len(want["a"])], ["b", len(want["b"])]]

    def test_distinctcounthllmv(self, cluster):
        cl, frame = cluster
        resp = cl.query("SELECT distinctcounthllmv(tags) FROM events")
        assert not resp.exceptions, resp.exceptions
        want = len({t for tags in frame["tags"] for t in tags})
        # HLL is exact at this tiny cardinality
        assert resp.result_table.rows[0][0] == want
