"""SPI layer tests: schema/table-config serde, layered config, partitioning."""

import json

import numpy as np
import pytest

from pinot_tpu.spi import (
    DataType,
    FieldSpec,
    FieldType,
    IndexingConfig,
    PinotConfiguration,
    Schema,
    StarTreeIndexConfig,
    TableConfig,
    TableType,
    UpsertConfig,
    UpsertMode,
)
from pinot_tpu.spi.table import raw_table_name, table_name_with_type
from pinot_tpu.utils.partition import get_partition_function


def make_schema():
    return Schema("baseballStats", [
        FieldSpec("playerID", DataType.STRING),
        FieldSpec("teamID", DataType.STRING),
        FieldSpec("yearID", DataType.INT),
        FieldSpec("league", DataType.STRING),
        FieldSpec("homeRuns", DataType.INT, FieldType.METRIC),
        FieldSpec("runs", DataType.LONG, FieldType.METRIC),
        FieldSpec("avgScore", DataType.DOUBLE, FieldType.METRIC),
    ])


class TestSchema:
    def test_roundtrip(self):
        s = make_schema()
        s2 = Schema.from_json(s.to_json())
        assert s2 == s
        assert s2.column_names == s.column_names
        assert s2.field_spec("homeRuns").field_type is FieldType.METRIC

    def test_dimension_metric_split(self):
        s = make_schema()
        assert "playerID" in s.dimension_names
        assert "homeRuns" in s.metric_names
        assert s.time_column is None

    def test_default_null_values(self):
        s = make_schema()
        assert s.field_spec("homeRuns").default_null_value == 0
        assert s.field_spec("yearID").default_null_value == np.iinfo(np.int32).min
        assert s.field_spec("playerID").default_null_value == "null"

    def test_duplicate_column_rejected(self):
        with pytest.raises(ValueError):
            Schema("x", [FieldSpec("a", DataType.INT), FieldSpec("a", DataType.INT)])

    def test_primary_keys(self):
        s = Schema("t", [FieldSpec("k", DataType.STRING),
                         FieldSpec("v", DataType.INT, FieldType.METRIC)],
                   primary_key_columns=["k"])
        assert Schema.from_json(s.to_json()).primary_key_columns == ["k"]
        with pytest.raises(ValueError):
            Schema("t", [FieldSpec("k", DataType.STRING)], primary_key_columns=["nope"])

    def test_reference_style_time_field_spec(self):
        # The reference's legacy timeFieldSpec JSON shape loads as TIME
        d = {
            "schemaName": "airlineStats",
            "dimensionFieldSpecs": [{"name": "Carrier", "dataType": "STRING"}],
            "timeFieldSpec": {
                "incomingGranularitySpec": {
                    "name": "DaysSinceEpoch", "dataType": "INT", "timeType": "DAYS"}
            },
        }
        s = Schema.from_dict(d)
        assert s.time_column == "DaysSinceEpoch"
        assert s.field_spec("DaysSinceEpoch").field_type is FieldType.TIME
        # round-trip must preserve TIME (not silently become DATE_TIME)
        s2 = Schema.from_json(s.to_json())
        assert s2.field_spec("DaysSinceEpoch").field_type is FieldType.TIME
        assert s2 == s

    def test_max_length_roundtrip(self):
        fs = FieldSpec("x", DataType.STRING, max_length=64)
        assert FieldSpec.from_dict(fs.to_dict()).max_length == 64

    def test_float_dimension_null_is_negative_infinity(self):
        # ref: FieldSpec.java DEFAULT_DIMENSION_NULL_VALUE_OF_FLOAT/DOUBLE
        assert FieldSpec("f", DataType.FLOAT).default_null_value == float("-inf")
        assert FieldSpec("d", DataType.DOUBLE).default_null_value == float("-inf")

    def test_data_type_coercion(self):
        assert DataType.INT.convert("42") == 42
        assert DataType.DOUBLE.convert("1.5") == 1.5
        assert DataType.BOOLEAN.convert("true") == 1
        assert DataType.STRING.convert(7) == "7"
        assert DataType.BYTES.convert("deadbeef") == b"\xde\xad\xbe\xef"


class TestTableConfig:
    def test_roundtrip(self):
        tc = TableConfig(
            table_name="baseballStats",
            table_type=TableType.OFFLINE,
            indexing_config=IndexingConfig(
                inverted_index_columns=["teamID"],
                star_tree_index_configs=[StarTreeIndexConfig(
                    dimensions_split_order=["league", "teamID"],
                    function_column_pairs=["SUM__homeRuns"])],
            ),
            upsert_config=UpsertConfig(mode=UpsertMode.FULL),
        )
        tc2 = TableConfig.from_json(tc.to_json())
        assert tc2.table_name == "baseballStats"
        assert tc2.table_name_with_type == "baseballStats_OFFLINE"
        assert tc2.indexing_config.inverted_index_columns == ["teamID"]
        st = tc2.indexing_config.star_tree_index_configs[0]
        assert st.function_column_pairs == ["SUM__homeRuns"]
        assert tc2.upsert_config.mode is UpsertMode.FULL

    def test_reference_realtime_stream_configs(self):
        # reference layout: flat streamConfigs map nested in tableIndexConfig
        d = {
            "tableName": "airlineStats",
            "tableType": "REALTIME",
            "tableIndexConfig": {
                "streamConfigs": {
                    "streamType": "kafka",
                    "stream.kafka.topic.name": "flights-realtime",
                    "realtime.segment.flush.threshold.size": "50000",
                    "realtime.segment.flush.threshold.time": "3600000",
                },
            },
        }
        tc = TableConfig.from_dict(d)
        assert tc.stream_config is not None
        assert tc.stream_config.stream_type == "kafka"
        assert tc.stream_config.topic == "flights-realtime"
        assert tc.stream_config.segment_flush_threshold_rows == 50000
        assert tc.stream_config.segment_flush_threshold_millis == 3600000

    def test_table_name_helpers(self):
        assert table_name_with_type("t", TableType.REALTIME) == "t_REALTIME"
        assert raw_table_name("t_OFFLINE") == "t"
        assert raw_table_name("plain") == "plain"


class TestPinotConfiguration:
    def test_layering_and_relaxed_keys(self, monkeypatch):
        monkeypatch.setenv("PINOT_SERVER_QUERY_PORT", "9999")
        cfg = PinotConfiguration({"pinot.broker.timeoutMs": 5000})
        assert cfg.get_int("pinot.server.query.port") == 9999
        assert cfg.get_int("PINOT.BROKER.TIMEOUTMS") == 5000
        cfg.set("pinot.broker.timeoutMs", 1)  # explicit override wins
        assert cfg.get_int("pinot.broker.timeout-ms") == 1

    def test_env_beats_properties_file(self, monkeypatch, tmp_path):
        monkeypatch.setenv("PINOT_SERVER_PORT", "9")
        p = tmp_path / "conf.properties"
        p.write_text("pinot.server.port=1\n")
        cfg = PinotConfiguration()
        cfg.load_properties_file(str(p))  # loaded after env, but env wins
        assert cfg.get_int("pinot.server.port") == 9

    def test_typed_getters_and_subset(self):
        cfg = PinotConfiguration({"a.b.flag": "true", "a.b.n": "7", "c": "x"},
                                 use_env=False)
        assert cfg.get_bool("a.b.flag") is True
        sub = cfg.subset("a.b")
        assert sub.get_int("n") == 7
        assert "c" not in sub

    def test_subset_respects_segment_boundary(self):
        cfg = PinotConfiguration({"server.port": 1, "serverx.port": 2}, use_env=False)
        sub = cfg.subset("server")
        assert sub.get_int("port") == 1
        assert "xport" not in sub and "x.port" not in sub

    def test_properties_file(self, tmp_path):
        p = tmp_path / "server.properties"
        p.write_text("# comment\npinot.server.port=1234\n")
        cfg = PinotConfiguration(use_env=False)
        cfg.load_properties_file(str(p))
        assert cfg.get_int("pinot.server.port") == 1234


class TestPartitionFunctions:
    def test_modulo(self):
        f = get_partition_function("Modulo", 4)
        assert f.partition(10) == 2

    def test_murmur_stability(self):
        # Kafka murmur2 known values: partition must be stable across runs
        f = get_partition_function("Murmur", 8)
        vals = [f.partition(x) for x in ["a", "b", "hello", "12345"]]
        assert vals == [f.partition(x) for x in ["a", "b", "hello", "12345"]]
        assert all(0 <= v < 8 for v in vals)

    def test_hashcode_matches_java(self):
        # "abc".hashCode() == 96354 in Java
        f = get_partition_function("HashCode", 100000)
        assert f.partition("abc") == 96354

    def test_unknown_function(self):
        with pytest.raises(ValueError):
            get_partition_function("nope", 2)


def test_every_reference_example_config_loads():
    """EVERY schema + table config bundled with the reference's quickstarts
    must parse (the drop-in-compatibility contract; includes realtime
    configs with '12h'-style flush durations and dateTimeFieldSpecs)."""
    import glob
    import os

    base = "/root/reference/pinot-tools/src/main/resources/examples"
    if not os.path.isdir(base):
        import pytest
        pytest.skip("reference checkout not present")
    schemas = glob.glob(f"{base}/*/*/*_schema.json")
    tables = glob.glob(f"{base}/*/*/*table_config.json")
    assert len(schemas) >= 10 and len(tables) >= 10
    for f in schemas:
        s = Schema.from_file(f)
        assert s.schema_name
    for f in tables:
        t = TableConfig.from_file(f)
        assert t.table_name_with_type
        if t.stream_config is not None:
            assert t.stream_config.segment_flush_threshold_millis > 0
