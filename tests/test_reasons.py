"""Unified reason-registry conformance (tracing.reason_registry()).

PR 12/13/14 each grew a hand-rolled frozenset of ledger reason codes plus
its own near-duplicate source-scanning test (routing, gather, star-tree,
reduce; the pallas registry leaned on the graftlint ``decline`` family).
Those four tests collapse into ONE harness parameterized by
``(module, registry)``: every namespace declares how its record-site
literals are found (regex patterns and/or a quoted-literal prefix), and
the generic scan proves every literal that can reach the ledger is a
registered, stable code. New namespaces — the kernel preflight's
``pallas_preflight_<rule>`` codes — register once and inherit the
conformance gate for free.
"""

import re

import pytest

from pinot_tpu.common import tracing

pytestmark = pytest.mark.trace


@pytest.mark.parametrize("name", sorted(tracing.reason_registry()))
def test_namespace_record_sites_conform(name):
    """Every reason literal at a namespace's record sites is registered
    (dynamic patterns like ``tree<i>`` excepted); the scan itself must
    find sites (an empty scan means the patterns drifted, not that the
    module conformed); ``exact`` namespaces must use every code."""
    ns = tracing.reason_registry(name)
    found, unregistered = ns.conformance()
    assert len(found) >= ns.min_sites, \
        f"{name}: scan found only {sorted(found)} — patterns drifted?"
    assert not unregistered, f"{name}: unregistered codes {unregistered}"
    if ns.exact:
        missing = ns.codes - found
        assert not missing, \
            f"{name}: registered but never recorded: {missing}"


def test_registry_covers_the_five_legacy_sets_plus_preflight():
    names = set(tracing.reason_registry())
    assert {"pallas", "routing", "gather", "startree", "reduce",
            "pallas_preflight"} <= names
    codes = tracing.registered_reason_codes()
    assert tracing.ROUTING_DECISION_REASONS <= codes
    assert tracing.GATHER_DECISION_REASONS <= codes
    assert tracing.STARTREE_DECISION_REASONS <= codes
    assert tracing.REDUCE_DECISION_REASONS <= codes
    assert tracing.DIRECT_DECLINE_CODES <= codes
    assert tracing.PALLAS_PREFLIGHT_REASONS <= codes


def test_registry_covers_the_realtime_tier_sets():
    """PR 17: the mutable serve declines, hybrid route outcomes, and
    seal-swap records register as first-class namespaces and inherit
    the generic conformance scan above."""
    names = set(tracing.reason_registry())
    assert {"mutable", "hybrid", "seal"} <= names
    codes = tracing.registered_reason_codes()
    assert tracing.MUTABLE_DECLINE_REASONS <= codes
    assert tracing.HYBRID_ROUTE_REASONS <= codes
    assert tracing.SEAL_SWAP_REASONS <= codes
    # Prefix discipline: every code carries its decision-point prefix so
    # ledger histograms stay partitioned by namespace.
    assert all(c.startswith("mutable_")
               for c in tracing.MUTABLE_DECLINE_REASONS)
    assert all(c.startswith("hybrid_")
               for c in tracing.HYBRID_ROUTE_REASONS)
    assert all(c.startswith("seal_")
               for c in tracing.SEAL_SWAP_REASONS)


def test_namespaces_do_not_collide():
    """A reason code means ONE thing: no code registered under two
    namespaces (prefix discipline keeps histograms per decision point).
    The pallas/pallas_preflight split is the one sanctioned overlap
    surface — preflight codes carry their own prefix."""
    seen = {}
    for name, ns in tracing.reason_registry().items():
        for code in ns.codes:
            assert code not in seen, \
                f"{code} in both {seen[code]} and {name}"
            seen[code] = name


def test_startree_rank_and_tree_pattern():
    """The residual bits of the old per-module tests the generic scan
    does not cover: the star-tree rank table is a registry subset and
    the executor's chosen-tree record matches the dynamic pattern."""
    import pinot_tpu.engine.executor as executor_mod
    import pinot_tpu.engine.startree_exec as exec_mod

    assert set(exec_mod._REASON_RANK) <= tracing.STARTREE_DECISION_REASONS
    esrc = open(executor_mod.__file__.rstrip("c")).read()
    assert 'f"tree{tree_index}"' in esrc
    assert tracing.STARTREE_TREE_REASON.match("tree0")
    assert tracing.STARTREE_TREE_REASON.match("tree12")
    assert not tracing.STARTREE_TREE_REASON.match("tree")
    assert not tracing.STARTREE_TREE_REASON.match("tree0x")


def test_routing_scan_still_sees_the_prune_sites():
    """The routing namespace's patterns must keep matching the two
    prune-fired records (the old test pinned these two by name)."""
    ns = tracing.reason_registry("routing")
    found = ns.scan_source()
    assert "partition_prune" in found and "time_prune" in found


def test_preflight_namespace_is_exact_and_prefixed():
    ns = tracing.reason_registry("pallas_preflight")
    assert ns.exact
    assert all(re.fullmatch(r"pallas_preflight_[a-z0-9_]+", c)
               for c in ns.codes)


def test_registry_covers_the_race_waiver_set():
    """PR 20: the threads lint family's ``# race-ok:`` waiver vocabulary
    is a first-class namespace. It scans the whole package (waivers live
    on field declarations wherever shared state lives) and is exact — a
    registered code no annotation uses is itself a conformance failure,
    so the vocabulary cannot rot in either direction."""
    ns = tracing.reason_registry("race_ok")
    assert ns.module == "pinot_tpu" and ns.exact
    assert tracing.RACE_OK_REASONS <= tracing.registered_reason_codes()
    found, unregistered = ns.conformance()
    assert found == tracing.RACE_OK_REASONS and not unregistered
