"""Server-side segment pruning: min/max, partition, bloom.

Ref: ColumnValueSegmentPruner.java + SegmentPrunerService.java (pruning
before plan/stage at ServerQueryExecutorV1Impl:277).
"""

import numpy as np
import pytest

from pinot_tpu.engine import ServerQueryExecutor
from pinot_tpu.engine.pruner import prune_segments
from pinot_tpu.parallel import ShardedQueryExecutor
from pinot_tpu.query import compile_query
from pinot_tpu.segment import SegmentBuilder, load_segment
from pinot_tpu.spi import DataType, FieldSpec, FieldType, Schema
from pinot_tpu.spi.table import IndexingConfig, SegmentPartitionConfig
from pinot_tpu.utils.bloom import BloomFilter

N = 5000


def _schema():
    return Schema("pr_sales", [
        FieldSpec("region", DataType.STRING),
        FieldSpec("day", DataType.INT),
        FieldSpec("qty", DataType.LONG, FieldType.METRIC),
    ])


@pytest.fixture(scope="module")
def segs(tmp_path_factory):
    """4 segments with disjoint day ranges + bloom on region."""
    out = tmp_path_factory.mktemp("pr_segs")
    cfg = IndexingConfig(bloom_filter_columns=["region"])
    rng = np.random.default_rng(5)
    segs = []
    for i in range(4):
        regions = [f"r{i}a", f"r{i}b"]  # disjoint per segment
        b = SegmentBuilder(_schema(), f"pr_{i}", indexing_config=cfg)
        b.build({
            "region": np.array(regions)[rng.integers(0, 2, N)],
            "day": rng.integers(i * 100, i * 100 + 50, N).astype(np.int64),
            "qty": rng.integers(1, 10, N).astype(np.int64),
        }, str(out))
        segs.append(load_segment(str(out / f"pr_{i}")))
    return segs


class TestBloomFilter:
    def test_membership(self):
        vals = [f"v{i}" for i in range(500)]
        bf = BloomFilter.from_values(vals)
        assert all(bf.might_contain(v) for v in vals)
        misses = sum(bf.might_contain(f"x{i}") for i in range(1000))
        assert misses < 150  # fpp ~5%

    def test_serde_roundtrip(self):
        bf = BloomFilter.from_values([1, 2, 3, 99])
        back = BloomFilter.from_array(bf.to_array())
        assert back.might_contain(99) and back.num_hashes == bf.num_hashes

    def test_segment_exposes_bloom(self, segs):
        ds = segs[0].data_source("region")
        assert ds.metadata.has_bloom_filter
        assert ds.bloom_filter.might_contain("r0a")
        assert not ds.bloom_filter.might_contain("definitely-absent-xyz") \
            or True  # probabilistic: only the positive direction is certain


class TestPruner:
    def test_minmax_range_prunes(self, segs):
        ctx = compile_query("SELECT count(*) FROM pr_sales "
                            "WHERE day BETWEEN 210 AND 240")
        kept = prune_segments(ctx, segs)
        assert [s.segment_name for s in kept] == ["pr_2"]

    def test_eq_out_of_bounds_prunes(self, segs):
        ctx = compile_query("SELECT count(*) FROM pr_sales WHERE day = 120")
        kept = prune_segments(ctx, segs)
        assert [s.segment_name for s in kept] == ["pr_1"]

    def test_bloom_prunes_absent_string(self, segs):
        ctx = compile_query("SELECT count(*) FROM pr_sales "
                            "WHERE region = 'r2a'")
        kept = prune_segments(ctx, segs)
        # min/max keeps lexicographic overlap ('r0a' < 'r2a' < 'r3b') for
        # segments 0-3; bloom knocks out the non-owners (modulo fp)
        names = {s.segment_name for s in kept}
        assert "pr_2" in names and len(names) <= 2

    def test_and_or_composition(self, segs):
        ctx = compile_query("SELECT count(*) FROM pr_sales "
                            "WHERE day < 40 AND qty > 0")
        assert [s.segment_name for s in prune_segments(ctx, segs)] == ["pr_0"]
        ctx = compile_query("SELECT count(*) FROM pr_sales "
                            "WHERE day < 40 OR day > 330")
        assert [s.segment_name for s in prune_segments(ctx, segs)] == \
            ["pr_0", "pr_3"]

    def test_not_is_conservative(self, segs):
        ctx = compile_query("SELECT count(*) FROM pr_sales "
                            "WHERE NOT (day < 40)")
        assert len(prune_segments(ctx, segs)) == 4

    def test_executor_stats_and_results(self, segs):
        ex = ServerQueryExecutor(use_device=False)
        rt, stats = ex.execute(compile_query(
            "SELECT count(*), sum(qty) FROM pr_sales "
            "WHERE day BETWEEN 100 AND 149"), segs)
        assert stats.num_segments_pruned == 3
        assert stats.num_segments_processed == 1
        assert rt.rows[0][0] == N  # all docs of pr_1

    def test_all_pruned_returns_identity(self, segs):
        ex = ServerQueryExecutor(use_device=False)
        rt, stats = ex.execute(compile_query(
            "SELECT count(*), min(qty) FROM pr_sales WHERE day = 99999"),
            segs)
        assert rt.rows[0][0] == 0

    def test_sharded_executor_prunes_too(self, segs):
        ex = ShardedQueryExecutor()
        rt, stats = ex.execute(compile_query(
            "SELECT count(*) FROM pr_sales WHERE day BETWEEN 0 AND 49"),
            segs)
        assert stats.num_segments_pruned == 3
        assert rt.rows[0][0] == N


class TestPartitionPruning:
    def test_partition_metadata_prunes(self, tmp_path):
        """Segments built with a partition function + single partition:
        EQ literals hashing elsewhere prune (ref: the partition branch)."""
        cfg = IndexingConfig(segment_partition_config=SegmentPartitionConfig(
            {"region": {"functionName": "Modulo", "numPartitions": 4}}))
        schema = _schema()
        from pinot_tpu.utils.partition import get_partition_function

        fn = get_partition_function("Modulo", 4)
        segs = []
        for p in range(2):
            # region values chosen so each segment holds ONE partition
            vals = [str(v) for v in range(40) if fn.partition(str(v)) == p]
            b = SegmentBuilder(schema, f"pp_{p}", indexing_config=cfg)
            n = len(vals)
            b.build({"region": np.array(vals),
                     "day": np.arange(n).astype(np.int64),
                     "qty": np.ones(n, dtype=np.int64)}, str(tmp_path))
            segs.append(load_segment(str(tmp_path / f"pp_{p}")))
        probe = "8"  # Modulo(8, 4) == 0
        assert fn.partition(probe) == 0
        ctx = compile_query(
            f"SELECT count(*) FROM pr_sales WHERE region = '{probe}'")
        kept = prune_segments(ctx, segs)
        assert [s.segment_name for s in kept] == ["pp_0"]


def test_float_bloom_does_not_false_prune(tmp_path):
    """Regression: f32-stored FLOAT values vs f64 query literals must hash
    consistently or bloom pruning silently empties correct queries."""
    schema = Schema("fb", [FieldSpec("f", DataType.FLOAT),
                           FieldSpec("v", DataType.LONG, FieldType.METRIC)])
    cfg = IndexingConfig(bloom_filter_columns=["f"],
                         no_dictionary_columns=["f"])
    b = SegmentBuilder(schema, "fb_0", indexing_config=cfg)
    b.build({"f": np.array([0.1, 0.25, 7.5], dtype=np.float32),
             "v": np.array([1, 2, 3], dtype=np.int64)}, str(tmp_path))
    seg = load_segment(str(tmp_path / "fb_0"))
    ctx = compile_query("SELECT count(*) FROM fb WHERE f = 0.1")
    assert prune_segments(ctx, [seg]) == [seg]


def test_total_docs_includes_pruned(segs):
    ex = ServerQueryExecutor(use_device=False)
    _, stats = ex.execute(compile_query(
        "SELECT count(*) FROM pr_sales WHERE day BETWEEN 100 AND 149"), segs)
    assert stats.total_docs == 4 * N  # pruned segments still counted
