"""Native host runtime: C++ bit-pack/mmap/CRC/varint via ctypes, and their
numpy fallbacks (ref: the reference's [NATIVE-EQ] layer — PinotDataBuffer,
PinotDataBitSet, RoaringBitmap storage)."""

import os
import zlib

import numpy as np
import pytest

from pinot_tpu import native


@pytest.fixture(scope="module")
def lib_loaded():
    return native.available()


class TestBitPack:
    @pytest.mark.parametrize("bits", [1, 3, 7, 8, 13, 16, 21, 31])
    def test_round_trip(self, bits):
        rng = np.random.default_rng(bits)
        vals = rng.integers(0, 1 << bits, 10_000).astype(np.int32)
        packed = native.bitpack(vals, bits)
        assert len(packed) == (10_000 * bits + 63) // 64 * 8
        out = native.bitunpack(packed, 10_000, bits)
        assert np.array_equal(out, vals)

    def test_numpy_fallback_matches_native(self, lib_loaded):
        if not lib_loaded:
            pytest.skip("no native lib; nothing to compare")
        rng = np.random.default_rng(9)
        vals = rng.integers(0, 1 << 11, 5000).astype(np.int32)
        # force the numpy paths
        lib = native._lib
        try:
            native._lib = None
            py_packed = native.bitpack(vals, 11)
            py_out = native.bitunpack(py_packed, 5000, 11)
        finally:
            native._lib = lib
        assert py_packed == native.bitpack(vals, 11)
        assert np.array_equal(py_out, native.bitunpack(py_packed, 5000, 11))

    def test_bits_needed(self):
        assert native.bits_needed(1) == 1
        assert native.bits_needed(2) == 1
        assert native.bits_needed(3) == 2
        assert native.bits_needed(256) == 8
        assert native.bits_needed(257) == 9


class TestVarint:
    def test_round_trip(self):
        rng = np.random.default_rng(4)
        ids = np.unique(rng.integers(0, 10_000_000, 20_000)).astype(np.int32)
        enc = native.varint_encode(ids)
        assert len(enc) < ids.nbytes  # compression on sorted ids
        out = native.varint_decode(enc, len(ids))
        assert np.array_equal(out, ids)

    def test_empty(self):
        assert native.varint_encode(np.empty(0, dtype=np.int32)) == b""


class TestMmapAndCrc:
    def test_crc_matches_zlib(self, tmp_path):
        p = str(tmp_path / "f.bin")
        data = os.urandom(1 << 18)
        with open(p, "wb") as f:
            f.write(data)
        assert native.crc32_file(p) == (zlib.crc32(data) & 0xFFFFFFFF)

    def test_mmap_view_and_refcount(self, tmp_path):
        p = str(tmp_path / "m.bin")
        arr = np.arange(1000, dtype=np.int64)
        with open(p, "wb") as f:
            f.write(arr.tobytes())
        buf = native.MmapBuffer(p)
        view = buf.as_array(np.int64)
        assert np.array_equal(view, arr)
        assert buf.acquire()
        buf.release()  # still held once
        view2 = buf.as_array(np.int64, count=10, offset=80)
        assert view2[0] == 10
        buf.release()


class TestPackedSegmentFormat:
    def test_packed_fwd_and_posting_lists_round_trip(self, tmp_path):
        from pinot_tpu.engine import ServerQueryExecutor
        from pinot_tpu.query import compile_query
        from pinot_tpu.segment import SegmentBuilder, load_segment
        from pinot_tpu.spi import DataType, FieldSpec, FieldType, Schema
        from pinot_tpu.spi.table import IndexingConfig

        rng = np.random.default_rng(23)
        n = 5000
        rows = {
            "k": [f"k{int(i)}" for i in rng.integers(0, 300, n)],
            "v": [int(v) for v in rng.integers(0, 1000, n)],
        }
        schema = Schema("t", [FieldSpec("k", DataType.STRING),
                              FieldSpec("v", DataType.LONG, FieldType.METRIC)])
        cfg = IndexingConfig(inverted_index_columns=["k"])
        md = SegmentBuilder(schema, "t_0", indexing_config=cfg).build(
            rows, str(tmp_path))
        assert md.columns["k"].stored_dtype.startswith("packed:")
        files = os.listdir(str(tmp_path / "t_0" / "columns"))
        assert "k.fwdpk.bin" in files
        assert "k.inv.bin" in files and "k.invbo.npy" in files

        seg = load_segment(str(tmp_path / "t_0"))
        # inverted posting-list path answers EQ identically to the scan
        docs = seg.data_source("k").doc_ids_for_dict_id(0)
        k0 = seg.data_source("k").dictionary.get_value(0)
        expected = [i for i, kv in enumerate(rows["k"]) if kv == k0]
        assert docs.tolist() == expected

        ex = ServerQueryExecutor(use_device=False)
        t, _ = ex.execute(compile_query(
            f"SELECT count(*), sum(v) FROM t WHERE k = '{k0}'"), [seg])
        assert t.rows[0][0] == len(expected)
        assert t.rows[0][1] == float(sum(rows["v"][i] for i in expected))


def test_microbench_smoke():
    """Every microbenchmark runs and reports a positive rate (shrunk: the
    suite only validates the harness, not the numbers)."""
    import pinot_tpu.tools.microbench as mb

    old = mb.N_ROWS
    mb.N_ROWS = 1 << 14
    try:
        for name, fn in mb.BENCHMARKS.items():
            out = fn()
            rates = [v for k, v in out.items()
                     if isinstance(v, (int, float)) and k != "bytes_per_row"]
            assert rates and all(r > 0 for r in rates), (name, out)
    finally:
        mb.N_ROWS = old
