"""Launch-coalescing tests: the micro-batched dispatcher must (a) return
bit-identical results vs the serial path under concurrent mixed-shape load,
(b) actually coalesce (batch size > 1) when requests pile up, and (c) never
deadlock on the multi-device mesh — the original reason the old global
combine lock existed. Plus the satellites that ride along: the
literal-normalized launch cache, the worker/runner pool config keys, the
batch-column borrow path, and the QueryStats.launch wire."""

import threading
import time

import numpy as np
import pandas as pd
import pytest

from pinot_tpu.common.datatable import DataTable
from pinot_tpu.engine import ServerQueryExecutor
from pinot_tpu.engine.results import QueryStats
from pinot_tpu.parallel import ShardedQueryExecutor
from pinot_tpu.parallel.launcher import LaunchKernel, LaunchScheduler
from pinot_tpu.query import compile_query
from pinot_tpu.segment import SegmentBuilder, load_segment
from pinot_tpu.spi import DataType, FieldSpec, FieldType, IndexingConfig, Schema
from pinot_tpu.spi.config import CommonConstants, PinotConfiguration

RNG = np.random.default_rng(23)
NUM_SEGMENTS = 4
DOCS = 1024  # EQUAL sizes: the borrow path requires capacity parity


def make_schema():
    return Schema("sales", [
        FieldSpec("region", DataType.STRING),
        FieldSpec("kind", DataType.STRING),
        FieldSpec("year", DataType.INT),
        FieldSpec("qty", DataType.LONG, FieldType.METRIC),
        FieldSpec("price", DataType.DOUBLE, FieldType.METRIC),
        FieldSpec("raw_amt", DataType.LONG, FieldType.METRIC),
    ])


@pytest.fixture(scope="module")
def setup(tmp_path_factory):
    out = tmp_path_factory.mktemp("launcher_segs")
    regions = ["east", "west", "north", "south"]
    kinds = ["a", "b", "c"]
    segs, frames = [], []
    for i in range(NUM_SEGMENTS):
        # every segment carries the FULL region/kind value sets (leading
        # rows), so each per-segment dictionary equals the unified one —
        # the identity-remap precondition the borrow path verifies
        r = [regions[j % 4] for j in range(4)] + \
            [regions[j] for j in RNG.integers(0, 4, DOCS - 4)]
        k = [kinds[j % 3] for j in range(3)] + \
            [kinds[j] for j in RNG.integers(0, 3, DOCS - 3)]
        frame = {
            "region": r,
            "kind": k,
            "year": RNG.integers(2015, 2024, DOCS).astype(np.int64),
            # full 1..49 coverage per segment: qty's per-segment dictionary
            # must equal the unified one for the dictvals-sharing check
            "qty": np.r_[np.arange(1, 50),
                         RNG.integers(1, 50, DOCS - 49)].astype(np.int64),
            "price": np.round(RNG.normal(100, 25, DOCS), 2),
            "raw_amt": RNG.integers(0, 10_000, DOCS).astype(np.int64),
        }
        frames.append(pd.DataFrame(frame))
        b = SegmentBuilder(
            make_schema(), f"sales_{i}",
            indexing_config=IndexingConfig(no_dictionary_columns=["raw_amt"]))
        b.build({c: list(frame[c]) for c in frame}, str(out))
        segs.append(load_segment(str(out / f"sales_{i}")))
    return pd.concat(frames, ignore_index=True), segs


# --------------------------------------------------------------------------
# scheduler unit tests (fake kernels; deterministic coalescing via a
# blocker request that pins the dispatcher while the batch piles up)
# --------------------------------------------------------------------------

def _blocker():
    """(kernel, release) whose single launch parks the dispatcher."""
    gate = threading.Event()

    def call(params, num_docs):
        gate.wait(20)
        return params

    return LaunchKernel(("blocker",), call, max_batch=1), gate


def test_dedup_identical_params():
    sched = LaunchScheduler(name="t-dedup")
    blocker, gate = _blocker()
    calls = []

    def counted(params, num_docs):
        calls.append(params)
        return ("out", params)

    kern = LaunchKernel(("k1",), counted, max_batch=8)
    kern.batchable = False  # isolate the dedup path from vmap
    b = sched.submit(blocker, 0, 0)
    params = ("p",)
    reqs = [sched.submit(kern, params, 7) for _ in range(3)]
    gate.set()
    assert b.result(30) == 0
    outs = [r.result(30) for r in reqs]
    assert outs == [("out", params)] * 3
    assert len(calls) == 1, "identical params must share one launch"
    assert all(r.batch_size == 3 for r in reqs)
    assert all(r.launches_saved == 2 for r in reqs)
    snap = sched.stats_snapshot()
    assert snap["dedupedRequests"] >= 2
    assert snap["coalescedLaunches"] >= 1


def test_vmapped_batch_distinct_params():
    import jax.numpy as jnp

    sched = LaunchScheduler(name="t-batch")
    blocker, gate = _blocker()
    launches = []

    def call(params, num_docs):
        launches.append(1)
        return params * num_docs

    kern = LaunchKernel(("k2",), call, max_batch=8)
    b = sched.submit(blocker, 0, 0)
    nd = jnp.int32(3)
    reqs = [sched.submit(kern, jnp.float32(v), nd) for v in (1.0, 2.0, 5.0)]
    gate.set()
    b.result(30)
    outs = [float(np.asarray(r.result(30))) for r in reqs]
    assert outs == [3.0, 6.0, 15.0]
    # one vmapped trace serves the whole chunk (the solo fn body runs once
    # under the batching trace, not once per request)
    assert len(launches) == 1
    assert all(r.batch_size == 3 for r in reqs)
    assert sched.stats_snapshot()["launchesSaved"] >= 2


def test_unbatchable_kernel_falls_back_serial():
    sched = LaunchScheduler(name="t-serial")
    blocker, gate = _blocker()

    def call(params, num_docs):
        # .item() works on concrete values, explodes under a vmap trace —
        # the shape of backend batching-rule failures
        return params.item() * 2

    import jax.numpy as jnp

    kern = LaunchKernel(("k3",), call, max_batch=8)
    b = sched.submit(blocker, 0, 0)
    reqs = [sched.submit(kern, jnp.float32(v), 0) for v in (1.0, 4.0)]
    gate.set()
    b.result(30)
    assert [r.result(30) for r in reqs] == [2.0, 8.0]
    assert kern.batchable is False, "failed vmap must disable batching"
    # a later round stays serial and still serves
    r2 = sched.submit(kern, jnp.float32(3.0), 0)
    assert r2.result(30) == 6.0


def test_launch_errors_reach_every_rider():
    sched = LaunchScheduler(name="t-err")
    blocker, gate = _blocker()

    def boom(params, num_docs):
        raise RuntimeError("kernel exploded")

    kern = LaunchKernel(("k4",), boom, max_batch=4)
    kern.batchable = False
    b = sched.submit(blocker, 0, 0)
    params = ("same",)
    reqs = [sched.submit(kern, params, 0) for _ in range(2)]
    gate.set()
    b.result(30)
    for r in reqs:
        with pytest.raises(RuntimeError, match="kernel exploded"):
            r.result(30)
    assert sched.stats_snapshot()["failures"] >= 1


def test_dispatcher_crash_completes_waiters_and_recovers(monkeypatch):
    """A failure escaping _launch_group entirely (an import error, a bug in
    the grouping) must still complete every waiter's future — the 8-thread
    hang shape — and the dispatcher must keep serving afterwards."""
    sched = LaunchScheduler(name="t-crash")
    orig = LaunchScheduler._launch_group
    crashed = []

    def flaky(self, reqs):
        if not crashed:
            crashed.append(True)
            raise RuntimeError("synthetic dispatcher bug")
        return orig(self, reqs)

    monkeypatch.setattr(LaunchScheduler, "_launch_group", flaky)
    kern = LaunchKernel(("k5",), lambda params, num_docs: params,
                        max_batch=1)
    req = sched.submit(kern, ("p1",), 0)
    with pytest.raises(RuntimeError, match="synthetic dispatcher bug"):
        req.result(30)
    # the dispatcher thread survived (or was revived): next launch works
    assert sched.submit(kern, ("p2",), 0).result(30) == ("p2",)


# --------------------------------------------------------------------------
# the hammer: mixed same-shape / different-shape queries from >= 8 threads
# --------------------------------------------------------------------------

HAMMER_QUERIES = [
    # same shape, different literals: share one compiled kernel (the
    # literal-normalized launch tier) and stack into vmapped launches
    "SELECT region, sum(qty), count(*) FROM sales WHERE year >= 2016 "
    "GROUP BY region ORDER BY region",
    "SELECT region, sum(qty), count(*) FROM sales WHERE year >= 2018 "
    "GROUP BY region ORDER BY region",
    "SELECT region, sum(qty), count(*) FROM sales WHERE year >= 2020 "
    "GROUP BY region ORDER BY region",
    # different shapes: pipeline through the queue
    "SELECT count(*), sum(price) FROM sales WHERE kind = 'a'",
    "SELECT year, min(price), max(price) FROM sales GROUP BY year "
    "ORDER BY year",
    "SELECT kind, avg(qty), sum(raw_amt) FROM sales GROUP BY kind "
    "ORDER BY kind",
]

THREADS = 8
ITERS = 6


def test_concurrency_hammer(setup):
    _, segs = setup
    dev = ShardedQueryExecutor()  # the suite-wide virtual 8-device mesh
    ctxs = [compile_query(q) for q in HAMMER_QUERIES]
    # serial reference pass (also warms every compile)
    serial = []
    for ctx in ctxs:
        rt, _ = dev.execute(ctx, segs)
        serial.append(rt.rows)
    mark = dev.launcher.stats_snapshot()

    errors = []
    coalesced_seen = []
    start = threading.Barrier(THREADS)

    def pump(tid: int) -> None:
        try:
            start.wait(30)
            for it in range(ITERS):
                qi = (tid + it) % len(ctxs)
                stats = QueryStats()
                rt, stats = dev.execute(ctxs[qi], segs)
                # (a) bit-identical vs the serial path
                assert rt.rows == serial[qi], \
                    f"thread {tid} iter {it} q{qi} diverged"
                if stats.launch.get("batchSize", 0) > 1:
                    coalesced_seen.append(stats.launch)
        except BaseException as e:  # noqa: BLE001 — surfaced below
            errors.append(e)

    threads = [threading.Thread(target=pump, args=(t,), daemon=True)
               for t in range(THREADS)]
    for t in threads:
        t.start()
    deadline = time.monotonic() + 120
    for t in threads:
        t.join(max(0.0, deadline - time.monotonic()))
    # (c) no deadlock on the multi-device mesh
    assert not any(t.is_alive() for t in threads), \
        "hammer threads hung: combine launches deadlocked"
    assert not errors, errors[:3]
    # (b) at least one coalesced launch with batch size > 1
    delta = dev.launcher.stats_snapshot()
    assert delta["coalescedLaunches"] > mark["coalescedLaunches"], \
        f"no coalescing under {THREADS}-thread load: {delta}"
    assert delta["maxBatchSize"] >= 2
    assert coalesced_seen, "no query reported riding a coalesced batch"
    assert delta["launchesSaved"] > mark["launchesSaved"]


def test_uncontended_single_query_stats(setup):
    """The uncontended path must not report phantom coalescing (and must
    still flow through the dispatcher: launches == requests)."""
    _, segs = setup
    dev = ShardedQueryExecutor()
    rt, stats = dev.execute(compile_query(HAMMER_QUERIES[3]), segs)
    assert stats.launch["launches"] == 1
    assert stats.launch["batchSize"] == 1
    assert stats.launch["coalesced"] == 0


def test_vmapped_real_combine_bit_identical(setup):
    """The vmapped form of the ACTUAL sharded combine (shard_map + psum +
    all_gather on the 8-device mesh) must produce bit-identical packed
    outputs to solo launches — the property the hammer's exactness rides
    on even when scheduling happens to dedup instead of batch."""
    _, segs = setup
    from pinot_tpu.parallel.combine import SEG_AXIS, pad_segments

    dev = ShardedQueryExecutor()
    sqls = [f"SELECT region, sum(qty), count(*) FROM sales "
            f"WHERE year >= {y} GROUP BY region ORDER BY region"
            for y in (2016, 2019)]
    for sql in sqls:  # populate both cache tiers
        dev.execute(compile_query(sql), segs)
    with dev._cache_lock:
        entries = list(dev._param_cache.values())
    assert len(entries) == 2
    (_, lkey0, params0), (_, lkey1, params1) = entries
    assert lkey0 == lkey1, "same-shape literals must share the launch key"
    kernel = dev._launch_cache[lkey0]
    batch = dev.batch_for(segs)
    S = pad_segments(batch.num_segments, dev.mesh.shape[SEG_AXIS])
    num_docs = dev._device_num_docs(batch, S)
    solo = [np.asarray(kernel.run_one(p, num_docs))
            for p in (params0, params1)]
    rows = kernel.run_many([params0, params1], num_docs)
    assert np.array_equal(np.asarray(rows[0]), solo[0])
    assert np.array_equal(np.asarray(rows[1]), solo[1])


# --------------------------------------------------------------------------
# literal-normalized launch tier (the query-cache churn satellite)
# --------------------------------------------------------------------------

def test_unique_literals_share_compiled_launch_entry(setup):
    _, segs = setup
    host = ServerQueryExecutor(use_device=False)
    dev = ShardedQueryExecutor()
    sqls = [f"SELECT region, sum(qty) FROM sales WHERE year >= {y} "
            "GROUP BY region ORDER BY region" for y in (2016, 2017, 2019,
                                                        2021)]
    rt0, _ = dev.execute(compile_query(sqls[0]), segs)
    n_launch = len(dev._launch_cache)
    n_kernels = len(dev.sharded_kernels)
    for sql in sqls[1:]:
        got, _ = dev.execute(compile_query(sql), segs)
        want, _ = host.execute(compile_query(sql), segs)
        assert [r[0] for r in got.rows] == [r[0] for r in want.rows]
        for gr, wr in zip(got.rows, want.rows):
            assert gr[1] == pytest.approx(wr[1], rel=1e-5)
    # unique literals HIT the launch tier (one compiled closure), while the
    # exact-literal param tier holds one entry per literal set
    assert len(dev._launch_cache) == n_launch
    assert len(dev.sharded_kernels) == n_kernels
    assert len(dev._param_cache) >= len(sqls)
    # exact repeat: the param tier serves the same device params object,
    # which is what makes dispatcher-level dedup possible
    with dev._cache_lock:
        before = {k: id(v[2]) for k, v in dev._param_cache.items()}
    dev.execute(compile_query(sqls[0]), segs)
    with dev._cache_lock:
        after = {k: id(v[2]) for k, v in dev._param_cache.items()}
    assert before == after


# --------------------------------------------------------------------------
# pool sizing knobs (runner/worker threads satellite)
# --------------------------------------------------------------------------

def test_worker_threads_config_key():
    import os

    cfg = PinotConfiguration({CommonConstants.WORKER_THREADS_KEY: 3})
    ex = ServerQueryExecutor(use_device=False, config=cfg)
    assert ex.worker_threads == 3
    assert ex._worker_pool().num_workers == 3
    # default preserves the old hardcoded fan-out bound
    ex2 = ServerQueryExecutor(use_device=False)
    assert ex2.worker_threads == min(os.cpu_count() or 1, 8)
    # the relaxed key spelling resolves too (PinotConfiguration contract)
    cfg3 = PinotConfiguration({"pinot.server.query.workerThreads": 2})
    ex3 = ServerQueryExecutor(use_device=False, config=cfg3)
    assert ex3.worker_threads == 2


def test_worker_pool_runs_fanout_and_reuses(setup):
    _, segs = setup
    cfg = PinotConfiguration({CommonConstants.WORKER_THREADS_KEY: 4})
    ex = ServerQueryExecutor(use_device=False, config=cfg)
    ctx = compile_query("SELECT region, sum(qty) FROM sales "
                        "GROUP BY region ORDER BY region")
    rt1, _ = ex.execute(ctx, segs)
    pool = ex._segment_pool
    assert pool is not None, "fan-out should have built the persistent pool"
    rt2, _ = ex.execute(compile_query(
        "SELECT region, sum(qty) FROM sales GROUP BY region "
        "ORDER BY region"), segs)
    assert ex._segment_pool is pool, "pool must persist across queries"
    assert rt1.rows == rt2.rows


def test_runner_threads_config_key():
    from pinot_tpu.server.scheduler import make_scheduler

    cfg = PinotConfiguration({CommonConstants.RUNNER_THREADS_KEY: 2})
    sched = make_scheduler("fcfs", config=cfg)
    try:
        assert len(sched._pool._threads) == 2
    finally:
        sched.shutdown(timeout_s=1)


def test_launch_max_batch_config_key():
    cfg = PinotConfiguration({CommonConstants.LAUNCH_MAX_BATCH_KEY: 1})
    dev = ShardedQueryExecutor(config=cfg)
    assert dev._launch_max_batch == 1


# --------------------------------------------------------------------------
# adaptive micro-batch window (the straggler hold)
# --------------------------------------------------------------------------

def test_window_gathers_stragglers_into_one_batch():
    """With a hot arrival EWMA the dispatcher holds the window open, so a
    straggler submitted ~20 ms behind the first request still rides the
    SAME vmapped launch — no blocker pinning needed."""
    import jax.numpy as jnp

    sched = LaunchScheduler(name="t-window")
    # hot_ms=inf: any PRIMED ewma counts as hot, so the hold is
    # deterministic; prime with a tight synthetic arrival train
    sched.set_window(max_ms=250.0, hot_ms=float("inf"))
    with sched._cond:
        t = time.perf_counter()
        for i in range(5):
            sched._note_arrival_locked(t + i * 0.0005)
    launches = []

    def call(params, num_docs):
        launches.append(1)
        return params * num_docs

    kern = LaunchKernel(("kw",), call, max_batch=8)
    r1 = sched.submit(kern, jnp.float32(2.0), jnp.int32(3))
    time.sleep(0.02)  # arrives mid-window: must join r1's drain
    r2 = sched.submit(kern, jnp.float32(5.0), jnp.int32(3))
    assert float(np.asarray(r1.result(30))) == 6.0
    assert float(np.asarray(r2.result(30))) == 15.0
    assert r1.batch_size == 2 and r2.batch_size == 2, \
        "the straggler rode the held window into one batch"
    assert len(launches) == 1
    snap = sched.stats_snapshot()
    assert snap["windowWaits"] >= 1
    assert snap["windowGathered"] >= 1
    assert sched.snapshot()["windowMaxMs"] == 250.0


def test_window_idle_traffic_pays_no_hold():
    """Cold EWMA (hot_ms=0 means nothing ever counts hot): a lone request
    must dispatch immediately — no added latency at low QPS."""
    sched = LaunchScheduler(name="t-window-idle")
    sched.set_window(max_ms=500.0, hot_ms=0.0)

    def call(params, num_docs):
        return params

    kern = LaunchKernel(("ki",), call, max_batch=8)
    t0 = time.perf_counter()
    r = sched.submit(kern, ("p",), 0)
    assert r.result(30) == ("p",)
    assert (time.perf_counter() - t0) < 0.4, \
        "idle dispatch must not wait out the window"
    assert sched.stats_snapshot()["windowWaits"] == 0


def test_window_arrival_ewma_tracks_and_resets():
    sched = LaunchScheduler(name="t-ewma")
    sched.set_window(max_ms=1.0, hot_ms=2.0)
    with sched._cond:
        t = 100.0
        sched._note_arrival_locked(t)
        for _ in range(10):  # 1 ms apart: hot
            t += 0.001
            sched._note_arrival_locked(t)
        hot = sched._arrival_ewma_ms
        assert hot is not None and hot < 2.0
        t += 10.0  # a 10 s gap must RESET, not decay over many arrivals
        sched._note_arrival_locked(t)
        assert sched._arrival_ewma_ms > 2.0
    assert sched._window_hold_s(1) == 0.0


def test_window_config_keys():
    cfg = PinotConfiguration({
        CommonConstants.LAUNCH_WINDOW_MS_KEY: 3.5,
        CommonConstants.LAUNCH_WINDOW_HOT_MS_KEY: 9.0})
    dev = ShardedQueryExecutor(config=cfg)
    assert dev.launcher.window_max_ms == 3.5
    assert dev.launcher.window_hot_ms == 9.0
    # restore the shared per-mesh dispatcher for other tests
    dev.launcher.set_window(
        max_ms=CommonConstants.DEFAULT_LAUNCH_WINDOW_MS,
        hot_ms=CommonConstants.DEFAULT_LAUNCH_WINDOW_HOT_MS)


# --------------------------------------------------------------------------
# cross-query column dedup (batch -> per-segment borrow satellite)
# --------------------------------------------------------------------------

def test_per_segment_path_borrows_batch_columns(setup):
    _, segs = setup
    dev = ShardedQueryExecutor()
    host = ServerQueryExecutor(use_device=False)
    sql = ("SELECT region, sum(raw_amt) FROM sales "
           "GROUP BY region ORDER BY region")
    # sharded combine stages the batch's device copies of region/raw_amt
    dev.execute(compile_query(sql), segs)
    assert dev.residency.stats_snapshot()["borrows"] == 0
    # single-segment queries take the per-segment path; its staging must
    # borrow the resident batch copies instead of a second H2D pass
    got, _ = dev.execute(compile_query(sql), [segs[0]])
    want, _ = host.execute(compile_query(sql), [segs[0]])
    assert [r[0] for r in got.rows] == [r[0] for r in want.rows]
    for gr, wr in zip(got.rows, want.rows):
        assert gr[1] == pytest.approx(wr[1], rel=1e-6)
    snap = dev.residency.stats_snapshot()
    assert snap["borrows"] >= 1, "per-segment staging re-staged columns " \
        "a resident batch already holds on device"
    # numeric dict columns share the unified dictvals BUFFER outright
    staged = dev.residency.stage(segs[0])
    qty_batch = dev._staged_column(dev.batch_for(segs), "qty",
                                   dev.mesh.shape["seg"])
    assert staged.column("qty").dictvals is qty_batch["dictvals"]


def test_borrow_skips_incompatible_remaps(tmp_path):
    """Segments whose dictionaries DIFFER from the unified one must stage
    their own arrays — a borrowed row would carry foreign dictIds."""
    out = tmp_path / "skew"
    segs = []
    for i, vals in enumerate((["aa", "bb"], ["bb", "cc"])):
        b = SegmentBuilder(Schema("skew", [
            FieldSpec("d", DataType.STRING),
            FieldSpec("m", DataType.LONG, FieldType.METRIC)]), f"skew_{i}")
        b.build({"d": [vals[j % 2] for j in range(64)],
                 "m": list(range(64))}, str(out))
        segs.append(load_segment(str(out / f"skew_{i}")))
    dev = ShardedQueryExecutor()
    host = ServerQueryExecutor(use_device=False)
    sql = "SELECT d, sum(m) FROM skew GROUP BY d ORDER BY d"
    dev.execute(compile_query(sql), segs)
    borrows0 = dev.residency.stats_snapshot()["borrows"]
    got, _ = dev.execute(compile_query(sql), [segs[1]])
    want, _ = host.execute(compile_query(sql), [segs[1]])
    assert got.rows == want.rows
    # segment 1 stages TWO columns: 'm' (identical value sets -> identity
    # remap) may borrow, but 'd' ('bb' is unified id 1, its own id 0) must
    # NOT — a borrowed row would group under the wrong keys
    assert dev.residency.stats_snapshot()["borrows"] - borrows0 <= 1


# --------------------------------------------------------------------------
# QueryStats.launch on the wire + merge semantics
# --------------------------------------------------------------------------

def test_launch_stats_merge_and_wire():
    a = QueryStats()
    a.launch = {"launches": 1, "coalesced": 1, "batchSize": 3,
                "launchesSaved": 2, "queueWaitMs": 1.5}
    b = QueryStats()
    b.launch = {"launches": 1, "coalesced": 0, "batchSize": 1,
                "launchesSaved": 0, "queueWaitMs": 4.0}
    a.merge(b)
    assert a.launch["launches"] == 2          # counters sum
    assert a.launch["coalesced"] == 1
    assert a.launch["launchesSaved"] == 2
    assert a.launch["batchSize"] == 3         # max keys
    assert a.launch["queueWaitMs"] == 4.0

    dt = DataTable.for_aggregation([1.0], a)
    for raw in (dt.to_bytes(), dt.to_json_bytes()):
        back = DataTable.from_bytes(raw)
        assert back.stats.launch == a.launch
    # absent stays absent (no phantom key on host-path replies)
    empty = DataTable.for_aggregation([1.0], QueryStats())
    assert DataTable.from_bytes(empty.to_bytes()).stats.launch == {}


def test_debug_launches_endpoint(setup):
    _, segs = setup
    from pinot_tpu.controller.state import ClusterStateStore
    from pinot_tpu.server.server import ServerInstance

    store = ClusterStateStore()
    inst = ServerInstance("Server_launch_0", store,
                         executor=ShardedQueryExecutor())
    try:
        d = inst.launch_debug()
        assert d["enabled"] is True
        assert "launches" in d and "queued" in d
        host_inst = ServerInstance("Server_launch_1", store)
        assert host_inst.launch_debug() == {"enabled": False}
    finally:
        pass  # instances were never started; nothing to drain
