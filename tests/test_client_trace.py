"""Python client library + request-scoped tracing.

Ref: pinot-java-client Connection/ResultSetGroup (client),
TraceContext.java:46 + response traceInfo (tracing).
"""

import numpy as np
import pytest

from pinot_tpu.client import PinotClientError, connect
from pinot_tpu.spi import DataType, FieldSpec, FieldType, Schema
from pinot_tpu.spi.table import TableConfig
from pinot_tpu.tools.cluster import EmbeddedCluster
from pinot_tpu.transport.rest import BrokerApi

N = 3000


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    c = EmbeddedCluster(num_servers=2, data_dir=str(
        tmp_path_factory.mktemp("cl")))
    schema = Schema("ct", [
        FieldSpec("city", DataType.STRING),
        FieldSpec("v", DataType.LONG, FieldType.METRIC)])
    c.create_table(TableConfig("ct"), schema)
    rng = np.random.default_rng(5)
    for i in range(2):
        c.ingest_rows("ct_OFFLINE", schema, {
            "city": np.array(["sf", "nyc"])[rng.integers(0, 2, N)],
            "v": rng.integers(0, 50, N).astype(np.int64)},
            segment_name=f"ct_{i}")
    assert c.wait_for_ev_converged("ct_OFFLINE")
    api = BrokerApi(c.broker, port=0)
    api.start()
    yield c, f"localhost:{api.port}"
    api.stop()
    c.shutdown()


class TestClient:
    def test_connect_and_query(self, cluster):
        _, broker = cluster
        conn = connect([broker])
        results = conn.execute("SELECT count(*), sum(v) FROM ct")
        rs = results.get_result_set()
        assert rs.get_long(0, 0) == 2 * N
        assert rs.column_names == ["count(*)", "sum(v)"]
        assert results.stats["numServersQueried"] >= 1

    def test_group_by_iteration(self, cluster):
        _, broker = cluster
        conn = connect([broker])
        rs = conn.execute(
            "SELECT city, count(*) FROM ct GROUP BY city ORDER BY city"
        ).result_set
        cities = [row[0] for row in rs]
        assert cities == ["nyc", "sf"]
        assert rs.get_string(1, 0) == "sf"

    def test_exceptions_raise(self, cluster):
        _, broker = cluster
        conn = connect([broker])
        with pytest.raises(PinotClientError):
            conn.execute("SELECT count(*) FROM nope")
        lax = connect([broker], fail_on_exceptions=False)
        group = lax.execute("SELECT count(*) FROM nope")
        assert group.exceptions

    def test_unreachable_broker(self):
        conn = connect(["localhost:1"], timeout_s=2.0)
        with pytest.raises(PinotClientError, match="unreachable"):
            conn.execute("SELECT 1 FROM t")


class TestTracing:
    def test_trace_option_attaches_entries(self, cluster):
        c, broker = cluster
        conn = connect([broker])
        results = conn.execute(
            "SELECT city, sum(v) FROM ct GROUP BY city "
            "OPTION(trace=true)")
        trace = results.raw.get("traceInfo", {}).get("entries", [])
        assert trace, results.raw
        assert all("operator" in e and "ms" in e for e in trace)
        ops = {e["operator"] for e in trace}
        assert ops & {"ShardedCombine", "SegmentGroupBy"}

    def test_no_trace_by_default(self, cluster):
        _, broker = cluster
        conn = connect([broker])
        results = conn.execute("SELECT count(*) FROM ct")
        assert "traceInfo" not in results.raw


def test_trace_entries_carry_instance(cluster):
    c, broker = cluster
    conn = connect([broker])
    results = conn.execute(
        "SELECT count(*) FROM ct OPTION(trace=true)")
    entries = results.raw["traceInfo"]["entries"]
    assert all("instance" in e for e in entries), entries
    assert {e["instance"] for e in entries} <= {"server_0", "server_1"}


class TestDynamicBrokerSelection:
    """Dynamic broker discovery + transport failover
    (ref: DynamicBrokerSelector + round-robin with failover)."""

    def test_discovery_from_controller(self, cluster):
        from pinot_tpu.client import connect_with_controller
        from pinot_tpu.transport.rest import ControllerApi

        c, _broker = cluster
        api = ControllerApi(c.controller)
        api.start()
        try:
            conn = connect_with_controller(f"localhost:{api.port}")
            rs = conn.execute("SELECT count(*) FROM ct").get_result_set()
            assert rs.get_long(0, 0) == 2 * N
        finally:
            api.stop()

    def test_failover_to_live_broker(self, cluster):
        """First broker in the list is dead: the client must fail over and
        answer from the live one instead of erroring."""
        _, broker = cluster
        conn = connect(["localhost:1", broker], retries=4, backoff_s=0.01)
        rs = conn.execute("SELECT count(*) FROM ct").get_result_set()
        assert rs.get_long(0, 0) == 2 * N
