"""Text index: tokenized TEXT_MATCH over dictId postings.

Ref: LuceneTextIndexCreator / TextIndexReader / TextMatchFilterOperator
(Lucene QueryParser dialect subset: terms, phrases, prefix*, AND/OR).
"""

import numpy as np
import pytest

from pinot_tpu.engine import ServerQueryExecutor
from pinot_tpu.query import compile_query
from pinot_tpu.segment import SegmentBuilder, load_segment
from pinot_tpu.segment.textindex import (
    match_text_value,
    parse_text_query,
    tokenize,
)
from pinot_tpu.spi import DataType, FieldSpec, FieldType, Schema
from pinot_tpu.spi.table import IndexingConfig

DOCS = [
    "Java database for realtime analytics",
    "TPU accelerated query engine",
    "distributed realtime OLAP datastore",
    "the quick brown fox",
    "quick analytics on TPU hardware",
    "batch ingestion pipeline",
    "streaming ingestion for analytics",
    "query planning and execution",
]


class TestAnalyzer:
    def test_tokenize(self):
        assert tokenize("The Quick-Brown FOX!") == \
            ["the", "quick", "brown", "fox"]

    def test_query_parse(self):
        assert parse_text_query("quick") == ("term", "quick")
        assert parse_text_query("quick fox") == \
            ("or", [("term", "quick"), ("term", "fox")])  # Lucene default OR
        assert parse_text_query("quick AND fox") == \
            ("and", [("term", "quick"), ("term", "fox")])
        assert parse_text_query('"realtime analytics"')[0] == "phrase"
        assert parse_text_query("ana*") == ("prefix", "ana")

    def test_match_oracle(self):
        assert match_text_value("quick brown fox", parse_text_query(
            '"quick brown"'))
        assert not match_text_value("brown quick fox", parse_text_query(
            '"quick brown"'))  # adjacency matters


@pytest.fixture(scope="module", params=["indexed", "unindexed"])
def seg(request, tmp_path_factory):
    out = str(tmp_path_factory.mktemp(f"tx_{request.param}"))
    n = len(DOCS) * 50
    docs = (DOCS * 50)[:n]
    cfg = IndexingConfig(
        text_index_columns=["body"] if request.param == "indexed" else [])
    schema = Schema("txt", [
        FieldSpec("body", DataType.STRING),
        FieldSpec("v", DataType.LONG, FieldType.METRIC)])
    b = SegmentBuilder(schema, "txt_0", indexing_config=cfg)
    b.build({"body": np.array(docs), "v": np.arange(n).astype(np.int64)},
            out)
    return load_segment(f"{out}/txt_0"), docs


QUERIES = [
    "analytics",
    "quick AND analytics",
    "realtime analytics",          # OR
    '"realtime analytics"',        # phrase (adjacent)
    "ingest*",
    '(quick OR streaming) AND analytics',
    "tpu AND quer*",
]


class TestTextMatchQueries:
    @pytest.mark.parametrize("q", QUERIES)
    def test_counts_match_oracle(self, seg, q):
        segment, docs = seg
        ast = parse_text_query(q)
        expected = sum(1 for d in docs if match_text_value(d, ast))
        sql_q = q.replace("'", "''")
        for use_device in (True, False):
            ex = ServerQueryExecutor(use_device=use_device)
            rt, _ = ex.execute(compile_query(
                f"SELECT count(*) FROM txt "
                f"WHERE text_match(body, '{sql_q}')"), [segment])
            assert rt.rows[0][0] == expected, (q, use_device)
        assert expected > 0, q  # every query exercises real matches

    def test_index_flag_and_reader(self, seg):
        segment, _ = seg
        cm = segment.metadata.column("body")
        ds = segment.data_source("body")
        if cm.has_text_index:
            ids = ds.text_index.matching_ids("analytics")
            assert len(ids) > 0

    def test_bad_query_is_query_error(self, seg):
        from pinot_tpu.engine.errors import QueryError

        segment, _ = seg
        ex = ServerQueryExecutor(use_device=False)
        with pytest.raises(QueryError):
            ex.execute(compile_query(
                "SELECT count(*) FROM txt WHERE text_match(body, '((')"),
                [segment])


def test_unanalyzable_query_rejected_consistently(seg):
    """'*' has no searchable terms: QueryError on BOTH paths (regression:
    the decay path matched every row, the indexed path crashed)."""
    from pinot_tpu.engine.errors import QueryError

    segment, _ = seg
    ex = ServerQueryExecutor(use_device=False)
    with pytest.raises(QueryError):
        ex.execute(compile_query(
            "SELECT count(*) FROM txt WHERE text_match(body, '*')"),
            [segment])
