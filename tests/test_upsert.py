"""Upsert engine: key->location semantics, valid-doc masking, and the
full-cluster upsert flow (ref: PartitionUpsertMetadataManager /
UpsertTableIntegrationTest)."""

import numpy as np
import pandas as pd
import pytest

from pinot_tpu.engine import ServerQueryExecutor
from pinot_tpu.ingestion import MemoryStream
from pinot_tpu.query import compile_query
from pinot_tpu.segment import MutableSegment, SegmentBuilder, load_segment
from pinot_tpu.segment.upsert import (
    PartitionUpsertMetadataManager,
    attach_valid_docs,
)
from pinot_tpu.spi import DataType, FieldSpec, FieldType, Schema
from pinot_tpu.spi.table import (
    SegmentsValidationConfig,
    StreamIngestionConfig,
    TableConfig,
    TableType,
    UpsertConfig,
    UpsertMode,
)
from pinot_tpu.tools import EmbeddedCluster


def make_schema():
    return Schema("users", [
        FieldSpec("uid", DataType.STRING),
        FieldSpec("status", DataType.STRING),
        FieldSpec("score", DataType.LONG, FieldType.METRIC),
        FieldSpec("ts", DataType.LONG, FieldType.DATE_TIME),
    ], primary_key_columns=["uid"])


def build_seg(tmp_path, name, rows):
    cols = {k: [r[k] for r in rows] for k in rows[0]}
    SegmentBuilder(make_schema(), name).build(cols, str(tmp_path))
    return load_segment(f"{tmp_path}/{name}")


class TestPartitionUpsertManager:
    def test_newer_segment_invalidates_older(self, tmp_path):
        pm = PartitionUpsertMetadataManager(["uid"], "ts")
        s1 = build_seg(tmp_path, "s1", [
            {"uid": "a", "status": "new", "score": 1, "ts": 100},
            {"uid": "b", "status": "new", "score": 2, "ts": 100},
        ])
        v1 = pm.add_segment(s1)
        s2 = build_seg(tmp_path, "s2", [
            {"uid": "a", "status": "upd", "score": 10, "ts": 200},
        ])
        v2 = pm.add_segment(s2)
        assert list(v1) == [False, True]   # 'a' superseded
        assert list(v2) == [True]
        assert pm.num_keys == 2

    def test_older_arrival_is_dropped(self, tmp_path):
        pm = PartitionUpsertMetadataManager(["uid"], "ts")
        s1 = build_seg(tmp_path, "s1", [
            {"uid": "a", "status": "new", "score": 1, "ts": 300}])
        v1 = pm.add_segment(s1)
        s2 = build_seg(tmp_path, "s2", [
            {"uid": "a", "status": "old", "score": 0, "ts": 100}])
        v2 = pm.add_segment(s2)
        assert list(v1) == [True]
        assert list(v2) == [False]  # late, older record never visible

    def test_query_sees_latest_only(self, tmp_path):
        pm = PartitionUpsertMetadataManager(["uid"], "ts")
        s1 = build_seg(tmp_path, "s1", [
            {"uid": "a", "status": "new", "score": 1, "ts": 100},
            {"uid": "b", "status": "new", "score": 2, "ts": 100},
        ])
        s2 = build_seg(tmp_path, "s2", [
            {"uid": "a", "status": "upd", "score": 10, "ts": 200},
        ])
        attach_valid_docs(s1, pm.add_segment(s1))
        attach_valid_docs(s2, pm.add_segment(s2))
        ex = ServerQueryExecutor()
        t, _ = ex.execute(compile_query(
            "SELECT count(*), sum(score) FROM users"), [s1, s2])
        assert t.rows[0] == [2, 12.0]  # a=10 (latest), b=2
        t2, _ = ex.execute(compile_query(
            "SELECT status, count(*) FROM users GROUP BY status ORDER BY status"),
            [s1, s2])
        assert [(r[0], r[1]) for r in t2.rows] == [("new", 1), ("upd", 1)]

    def test_remove_segment_clears_keys(self, tmp_path):
        pm = PartitionUpsertMetadataManager(["uid"], "ts")
        s1 = build_seg(tmp_path, "s1", [
            {"uid": "a", "status": "x", "score": 1, "ts": 100}])
        pm.add_segment(s1)
        pm.remove_segment("s1")
        assert pm.num_keys == 0


class TestUpsertCluster:
    def test_realtime_upsert_e2e(self, tmp_path):
        """Stream the same keys repeatedly: queries must see exactly one row
        per key with the latest value, across consuming + sealed segments."""
        MemoryStream.create("upsert_topic", 1)
        cluster = EmbeddedCluster(num_servers=1, data_dir=str(tmp_path))
        schema = make_schema()
        cfg = TableConfig(
            "users", TableType.REALTIME,
            validation_config=SegmentsValidationConfig(time_column_name="ts"),
            stream_config=StreamIngestionConfig(
                stream_type="memory", topic="upsert_topic",
                segment_flush_threshold_rows=60),
            upsert_config=UpsertConfig(mode=UpsertMode.FULL))
        cluster.create_table(cfg, schema)

        stream = MemoryStream.get("upsert_topic")
        rng = np.random.default_rng(3)
        latest = {}
        ts = 1000
        for _ in range(150):
            uid = f"u{int(rng.integers(0, 20))}"
            score = int(rng.integers(0, 100))
            ts += 1
            latest[uid] = (score, ts)
            stream.produce({"uid": uid, "status": "s", "score": score,
                            "ts": ts}, partition=0)

        assert cluster.wait_for_docs("users", len(latest), timeout_s=20)
        import time
        deadline = time.time() + 10
        while time.time() < deadline:
            rows = cluster.query_rows("SELECT count(*), sum(score) FROM users")
            if rows[0][0] == len(latest) and \
                    rows[0][1] == float(sum(s for s, _ in latest.values())):
                break
            time.sleep(0.1)
        assert rows[0][0] == len(latest), (rows, len(latest))
        assert rows[0][1] == float(sum(s for s, _ in latest.values()))

        # per-key check through the broker
        rows = cluster.query_rows(
            "SELECT uid, max(score) FROM users GROUP BY uid ORDER BY uid LIMIT 100")
        got = {r[0]: r[1] for r in rows}
        assert got == {k: float(s) for k, (s, _) in latest.items()}
        cluster.shutdown()
        MemoryStream.delete("upsert_topic")


class TestUpsertDevicePath:
    def test_device_serves_upsert_with_parity(self, tmp_path):
        """Sealed upsert segments ride the device kernels with the
        valid-doc snapshot ANDed into the filter (plan.py 'validdocs')."""
        rng = np.random.default_rng(13)
        n = 3000
        rows = [{"uid": f"u{i % 900}", "status": ["a", "b"][i % 2],
                 "score": int(rng.integers(0, 100)), "ts": i}
                for i in range(n)]
        seg = build_seg(tmp_path, "up_0", rows)
        pm = PartitionUpsertMetadataManager(["uid"], "ts")
        attach_valid_docs(seg, pm.add_segment(seg))
        assert seg.valid_doc_ids is not None

        dev = ServerQueryExecutor(use_device=True)
        host = ServerQueryExecutor(use_device=False)
        for sql in ("SELECT count(*) FROM users",
                    "SELECT sum(score) FROM users WHERE status = 'a'",
                    "SELECT status, count(*), max(score) FROM users "
                    "GROUP BY status ORDER BY status"):
            traced = compile_query(sql + " OPTION(trace=true)")
            drt, dstats = dev.execute(traced, [seg])
            hrt, _ = host.execute(compile_query(sql), [seg])
            assert drt.rows == hrt.rows, sql
            # the DEVICE kernels must have served (a silent PlanError
            # fallback to host would make this parity vacuous)
            paths = {t.get("path") for t in dstats.trace}
            assert "device" in paths, (sql, dstats.trace)
        # only the live doc per key is visible
        t, _ = dev.execute(compile_query("SELECT count(*) FROM users"),
                           [seg])
        assert t.rows[0][0] == 900

    def test_snapshot_tracks_new_invalidation(self, tmp_path):
        """A doc invalidated between two queries disappears from the
        second (plans snapshot the bitmap per execution)."""
        rows = [{"uid": f"u{i}", "status": "a", "score": i, "ts": i}
                for i in range(100)]
        seg = build_seg(tmp_path, "up_1", rows)
        pm = PartitionUpsertMetadataManager(["uid"], "ts")
        attach_valid_docs(seg, pm.add_segment(seg))
        dev = ServerQueryExecutor(use_device=True)
        q = compile_query("SELECT count(*) FROM users")
        assert dev.execute(q, [seg])[0].rows[0][0] == 100
        # a newer segment claims u5: the old doc goes invalid in place
        seg2 = build_seg(tmp_path, "up_2",
                         [{"uid": "u5", "status": "a", "score": 1,
                           "ts": 1000}])
        attach_valid_docs(seg2, pm.add_segment(seg2))
        assert dev.execute(q, [seg])[0].rows[0][0] == 99


class TestMutableUpsertDevicePath:
    """PR 17: CONSUMING segments ride the device kernels too — the
    watermark snapshot captures the upsert bitmap at the same instant as
    the doc count, and the kernel's validdocs placeholder is filled from
    that snapshot (mutable_staging._valid_locked)."""

    pytestmark = pytest.mark.realtime_tier

    def _consuming(self, n_rows, n_keys, seed=7):
        from pinot_tpu.server.data_manager import _LiveValidDocs

        seg = MutableSegment(make_schema(), "mut_up_0", capacity=65536)
        pm = PartitionUpsertMetadataManager(["uid"], "ts")
        attach_valid_docs(seg, _LiveValidDocs(pm, seg.segment_name))
        rng = np.random.default_rng(seed)
        latest = {}
        for i in range(n_rows):
            row = {"uid": f"u{int(rng.integers(0, n_keys))}",
                   "status": ["a", "b"][int(rng.integers(0, 2))],
                   "score": int(rng.integers(0, 100)), "ts": i}
            seg.index(row)
            pm.add_record(seg.segment_name, seg.num_docs - 1,
                          pm.key_of_row(row), row["ts"])
            latest[row["uid"]] = row
        return seg, pm, latest

    def test_consuming_upsert_device_host_parity(self):
        """Writes quiesced: device and host must agree bit-for-bit on a
        consuming upsert segment, and the device rung must actually have
        served (a silent host fallback would make parity vacuous)."""
        seg, _, latest = self._consuming(2000, 300)
        dev = ServerQueryExecutor(use_device=True)
        host = ServerQueryExecutor(use_device=False)
        for sql in ("SELECT status, count(*), sum(score), max(score) "
                    "FROM users GROUP BY status",
                    "SELECT uid, max(ts) FROM users "
                    "WHERE status = 'a' GROUP BY uid LIMIT 500"):
            drt, dstats = dev.execute(compile_query(sql), [seg])
            hrt, _ = host.execute(compile_query(sql), [seg])
            assert sorted(map(repr, drt.rows)) == \
                sorted(map(repr, hrt.rows)), sql
            assert dstats.group_by_rung == "mutable_device", \
                (sql, dstats.group_by_rung)
        # exactly one live doc per key survives the mask
        t, _ = dev.execute(compile_query("SELECT count(*) FROM users"),
                           [seg])
        assert t.rows[0][0] == len(latest)

    def test_invalidation_between_queries_same_watermark(self):
        """A key re-ingested between two queries flips its old doc's bit:
        the version-keyed device mask cache must NOT serve the stale
        bitmap (same watermark, different validdocs)."""
        from pinot_tpu.server.data_manager import _LiveValidDocs

        seg = MutableSegment(make_schema(), "mut_up_1", capacity=65536)
        pm = PartitionUpsertMetadataManager(["uid"], "ts")
        attach_valid_docs(seg, _LiveValidDocs(pm, seg.segment_name))
        for i in range(50):  # 50 unique keys, no dups yet
            row = {"uid": f"u{i}", "status": "a", "score": i, "ts": i}
            seg.index(row)
            pm.add_record(seg.segment_name, i, pm.key_of_row(row), i)
        dev = ServerQueryExecutor(use_device=True)
        q = compile_query("SELECT count(*), sum(score) FROM users")
        t0, _ = dev.execute(q, [seg])
        assert t0.rows[0][0] == 50
        # newer record for u5: old doc invalidated, count stays 50
        row = {"uid": "u5", "status": "a", "score": 1, "ts": 10_000}
        seg.index(row)
        pm.add_record(seg.segment_name, seg.num_docs - 1,
                      pm.key_of_row(row), row["ts"])
        t1, _ = dev.execute(q, [seg])
        host = ServerQueryExecutor(use_device=False)
        t1h, _ = host.execute(q, [seg])
        assert t1.rows == t1h.rows
        assert t1.rows[0][0] == 50


def test_plan_cache_respects_late_bitmap_attach(tmp_path):
    """A valid-doc bitmap attached AFTER a query cached the plan must
    invalidate it (the no-validdocs plan would count invalidated docs)."""
    rows = [{"uid": f"u{i % 50}", "status": "a", "score": i, "ts": i}
            for i in range(200)]
    seg = build_seg(tmp_path, "pc_0", rows)
    ex = ServerQueryExecutor(use_device=True)
    q = compile_query("SELECT count(*) FROM users")
    assert ex.execute(q, [seg])[0].rows[0][0] == 200  # plan cached, no bitmap
    pm = PartitionUpsertMetadataManager(["uid"], "ts")
    attach_valid_docs(seg, pm.add_segment(seg))
    assert ex.execute(q, [seg])[0].rows[0][0] == 50  # fresh plan sees it
